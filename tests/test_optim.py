"""Optimizer tests: convergence on classic problems, batched via vmap."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from spark_timeseries_tpu.utils import optim


class TestLBFGS:
    def test_quadratic(self):
        A = jnp.asarray(np.diag([1.0, 10.0, 100.0]))
        b = jnp.asarray([1.0, -2.0, 3.0])
        res = optim.minimize_lbfgs(lambda x: 0.5 * x @ A @ x - b @ x, jnp.zeros(3))
        np.testing.assert_allclose(np.asarray(res.x), np.linalg.solve(np.asarray(A), b), atol=1e-5)
        assert bool(res.converged)

    def test_rosenbrock(self):
        def rosen(x):
            return jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2 + (1.0 - x[:-1]) ** 2)

        res = optim.minimize_lbfgs(rosen, jnp.zeros(4), max_iters=200)
        np.testing.assert_allclose(np.asarray(res.x), np.ones(4), atol=1e-4)

    def test_vs_scipy(self):
        from scipy.optimize import minimize as sp_minimize

        def f_np(x):
            return float(np.sum((x - np.array([3.0, -1.0])) ** 4) + np.sum(x**2))

        def f_jnp(x):
            return jnp.sum((x - jnp.asarray([3.0, -1.0])) ** 4) + jnp.sum(x**2)

        sp = sp_minimize(f_np, np.zeros(2), method="L-BFGS-B")
        res = optim.minimize_lbfgs(f_jnp, jnp.zeros(2), max_iters=100, tol=1e-8)
        np.testing.assert_allclose(np.asarray(res.x), sp.x, atol=1e-3)

    def test_batched_independent_problems(self):
        # each row solves min (x - target_i)^2 with its own target
        targets = jnp.asarray(np.arange(6.0).reshape(6, 1))
        res = optim.batched_minimize(
            lambda x, t: jnp.sum((x - t) ** 2),
            jnp.zeros((6, 1)),
            targets,
        )
        np.testing.assert_allclose(np.asarray(res.x), np.asarray(targets), atol=1e-6)
        assert bool(jnp.all(res.converged))

    def test_nonfinite_guard(self):
        # objective returns NaN away from a basin: solver must not blow up
        def f(x):
            v = jnp.sum(x**2)
            return jnp.where(v < 100.0, v + jnp.sum(jnp.log(x + 10.0)), jnp.nan)

        res = optim.minimize_lbfgs(f, jnp.asarray([5.0]), max_iters=60)
        assert bool(jnp.isfinite(res.f))

    def test_interval_transforms(self):
        u = jnp.linspace(-5, 5, 11)
        x = optim.sigmoid_to_interval(u, 0.1, 0.9)
        assert float(x.min()) > 0.1 and float(x.max()) < 0.9
        back = optim.interval_to_sigmoid(x, 0.1, 0.9)
        np.testing.assert_allclose(np.asarray(back), np.asarray(u), atol=1e-5)

    def test_returned_f_is_best_seen(self):
        # ADVICE r3: the noise-floor-relaxed accept may adopt a step that
        # RAISES f slightly; the returned (x, f) must be the best visited
        # point, so f(returned) <= f(x0) and f == fun(x) exactly
        rng = np.random.default_rng(31)
        targets = jnp.asarray(rng.normal(size=(8, 3)).astype(np.float32))

        def fun_b(X):
            return jnp.sum((X - targets) ** 2, axis=-1)

        x0 = jnp.asarray(rng.normal(size=(8, 3)).astype(np.float32) * 3)
        res = optim.minimize_lbfgs_batched(fun_b, x0, max_iters=50)
        f0 = fun_b(x0)
        assert bool(jnp.all(res.f <= f0 + 1e-6))
        np.testing.assert_allclose(
            np.asarray(fun_b(res.x)), np.asarray(res.f), rtol=1e-6, atol=1e-6
        )
        # per-series variant holds the same contract
        one = optim.minimize_lbfgs(
            lambda x: jnp.sum((x - targets[0]) ** 2), x0[0], max_iters=50
        )
        assert float(one.f) <= float(fun_b(x0)[0]) + 1e-6
        np.testing.assert_allclose(
            float(jnp.sum((one.x - targets[0]) ** 2)), float(one.f), rtol=1e-6
        )
