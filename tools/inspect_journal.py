#!/usr/bin/env python
"""Pretty-print a chunk-journal manifest for post-mortems.

A journaled panel fit (``reliability.fit_chunked(..., checkpoint_dir=...)``)
leaves behind npz result shards plus an atomically updated
``manifest.json``.  When a job dies — SIGKILL, TPU preemption, deadline
blowout — this tool answers the on-call questions from the manifest alone:
which chunks committed, which TIMED OUT, what is still pending, what the
per-row FitStatus totals look like, and how much HBM the run peaked at.

    python tools/inspect_journal.py CHECKPOINT_DIR [--json]
    python tools/inspect_journal.py CHECKPOINT_DIR --delta NEW_PANEL

``--delta NEW_PANEL`` (ISSUE 15) dry-runs the delta planner: the new
panel (npz shard directory or ``.npy`` file) is diffed against this
journal's per-chunk content fingerprints, and the report shows which
chunks a ``fit_chunked(delta_from=...)`` walk would adopt byte-for-byte,
warm-start from journaled params, or refit in full.

Accepts the journal directory (reads ``manifest.json``; pass a
``manifest.proc_*.json`` path directly for a non-zero process's namespace)
and exits 2 on a torn (unparseable) manifest — the same condition a resume
rejects — printing what little can be salvaged.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _fmt_bytes(n) -> str:
    if not n:
        return "—"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024:
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0
    return f"{n:.1f} PiB"


def _fmt_when(ts) -> str:
    if not ts:
        return "—"
    return time.strftime("%Y-%m-%d %H:%M:%SZ", time.gmtime(ts))


def load_manifest(path: str) -> dict:
    if os.path.isdir(path):
        path = os.path.join(path, "manifest.json")
    if not os.path.exists(path):
        sys.exit(f"no manifest at {path}")
    try:
        with open(path, "rb") as f:
            return json.loads(f.read().decode())
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        print(f"TORN MANIFEST: {path} does not parse ({e}).", file=sys.stderr)
        print("A mid-commit crash tore the write; a resume under this "
              "journal is rejected (TornManifestError). The npz shards on "
              "disk are still intact — recover by removing/renaming the "
              "manifest only if you accept recomputing every chunk.",
              file=sys.stderr)
        sys.exit(2)


def summarize(m: dict) -> dict:
    chunks = sorted(m.get("chunks", []), key=lambda e: e["lo"])
    n_rows = int(m.get("n_rows", 0))
    committed = [e for e in chunks if e["status"] == "committed"]
    timeout = [e for e in chunks if e["status"] == "TIMEOUT"]
    covered = sum(e["hi"] - e["lo"] for e in committed)
    status_totals: dict = {}
    for e in committed:
        for k, v in (e.get("status_counts") or {}).items():
            status_totals[k] = status_totals.get(k, 0) + v
    peaks = [e.get("peak_hbm_bytes") for e in chunks if e.get("peak_hbm_bytes")]
    # which probe produced the readings: "device" is real HBM; "host_rss"
    # is the process peak-RSS fallback (must not be presented as HBM)
    peak_sources = sorted({e.get("peak_hbm_source") or "device"
                           for e in chunks if e.get("peak_hbm_bytes")})
    return {
        "run_id": m.get("run_id"),
        "created_at": m.get("created_at"),
        "git_commit": m.get("git_commit"),
        "config_hash": m.get("config_hash"),
        "panel_fingerprint": m.get("panel_fingerprint"),
        "n_rows": n_rows,
        "resumes": len(m.get("resumes", [])),
        "chunks_committed": len(committed),
        "chunks_timeout": len(timeout),
        "rows_committed": covered,
        "rows_pending": max(0, n_rows - covered
                            - sum(e["hi"] - e["lo"] for e in timeout)),
        "rows_timeout": sum(e["hi"] - e["lo"] for e in timeout),
        "status_totals": status_totals,
        "peak_hbm_bytes": max(peaks) if peaks else None,
        "peak_mem_sources": peak_sources,
        "chunks": chunks,
        "telemetry": m.get("telemetry"),
    }


def delta_report(journal_dir: str, new_panel: str, as_json: bool = False):
    """Classify a new panel against a committed journal (ISSUE 15): the
    dry-run of ``fit_chunked(delta_from=journal_dir)`` — prints which
    chunks a delta walk would adopt byte-for-byte, warm-start, or refit,
    and the dirty fraction the refit would pay for."""
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from spark_timeseries_tpu.reliability import delta as delta_mod
    from spark_timeseries_tpu.reliability import source as source_mod

    if os.path.isdir(new_panel):
        panel = source_mod.NpzShardSource(new_panel)
    elif new_panel.endswith(".npy"):
        panel = np.load(new_panel, allow_pickle=False)
    else:
        sys.exit(f"--delta expects an npz shard directory or a .npy "
                 f"panel file, got {new_panel}")
    try:
        plan = delta_mod.plan_delta(journal_dir, panel)
    except delta_mod.DeltaError as e:
        sys.exit(f"not delta-eligible: {e}")
    c = plan.counts
    total = max(1, len(plan.chunks))
    dirty_frac = 1.0 - c["adopted"] / total
    if as_json:
        print(json.dumps({
            "journal": os.path.abspath(journal_dir),
            "new_panel": os.path.abspath(new_panel),
            "grown": plan.grown,
            "counts": c,
            "chunks": [[ch.lo, ch.hi, ch.cls] for ch in plan.chunks],
            "dirty_fraction": round(dirty_frac, 4),
        }, indent=1, sort_keys=True))
        return
    print(f"delta plan: journal {journal_dir} vs panel {new_panel}")
    print(f"  history {'GREW' if plan.grown else 'same length'} "
          f"(fingerprints cover {plan.data_cols} data columns)")
    print(f"  {c['adopted']} adopted (zero compute), {c['warm']} warm "
          f"(journaled-param warm start), {c['dirty']} dirty + "
          f"{c['new']} new (full refit)")
    print(f"  dirty fraction {dirty_frac:.2%} — a delta walk computes "
          f"{c['warm'] + c['dirty'] + c['new']} of {total} chunks")
    for ch in plan.chunks:
        print(f"  [{ch.lo:>9}, {ch.hi:>9})  {ch.cls}")


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="journal directory or manifest path")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary instead of the table")
    ap.add_argument("--delta", default=None, metavar="NEW_PANEL",
                    help="classify a NEW panel (npz shard directory or "
                         ".npy file) against this journal's per-chunk "
                         "fingerprints: which chunks a delta walk would "
                         "adopt / warm-start / refit (ISSUE 15)")
    args = ap.parse_args()
    if args.delta is not None:
        return delta_report(args.path, args.delta, as_json=args.json)
    m = load_manifest(args.path)
    s = summarize(m)
    if args.json:
        print(json.dumps(s, indent=1, sort_keys=True))
        return

    print(f"journal {args.path}")
    print(f"  run {s['run_id']}  created {_fmt_when(s['created_at'])}  "
          f"commit {(s['git_commit'] or '?')[:12]}  resumes {s['resumes']}")
    print(f"  config {s['config_hash']}  panel {s['panel_fingerprint']}  "
          f"rows {s['n_rows']}")
    print(f"  chunks: {s['chunks_committed']} committed, "
          f"{s['chunks_timeout']} TIMEOUT; rows: {s['rows_committed']} done, "
          f"{s['rows_timeout']} timed out, {s['rows_pending']} pending")
    if s["status_totals"]:
        totals = ", ".join(f"{k}={v}" for k, v in s["status_totals"].items()
                           if v)
        print(f"  fit status totals: {totals or 'none recorded'}")
    src = ",".join(s.get("peak_mem_sources") or [])
    print(f"  peak memory (max over chunks): "
          f"{_fmt_bytes(s['peak_hbm_bytes'])}"
          + (f" [{src}]" if src else "")  # no readings -> no source claim
          + ("  (host_rss = process peak RSS fallback, NOT device HBM)"
             if "host_rss" in src else ""))
    if s["chunks"]:
        print(f"  {'rows':>21}  {'status':<9} {'wall_s':>8} {'peak_mem':>10}"
              f"  {'run':<12} counts")
        for e in s["chunks"]:
            counts = e.get("status_counts") or {}
            counts_s = ",".join(f"{k}:{v}" for k, v in counts.items() if v)
            wall = e.get("wall_s")
            print(f"  [{e['lo']:>9}, {e['hi']:>9})  {e['status']:<9} "
                  f"{wall if wall is not None else '—':>8} "
                  f"{_fmt_bytes(e.get('peak_hbm_bytes')):>10}  "
                  f"{(e.get('run_id') or '?'):<12} {counts_s}")
    else:
        print("  (no chunks recorded yet)")
    t = s.get("telemetry")
    if t:
        pm = t.get("peak_memory") or {}
        print(f"  telemetry (obs run {t.get('run_id')}): "
              f"peak mem {_fmt_bytes(pm.get('bytes'))} "
              f"[{pm.get('source', '?')}]")
        phases = {}
        for c in t.get("chunks") or []:
            p = phases.setdefault(c.get("phase"), [0, 0.0])
            p[0] += 1
            p[1] += c.get("wall_s") or 0.0
        for phase, (n, wall) in sorted(phases.items()):
            print(f"    chunks {phase:<16} n={n:<4} wall {wall:.3f}s")
        counters = {k: v for k, v in (t.get("counters") or {}).items() if v}
        if counters:
            print("    counters: " + ", ".join(
                f"{k}={v}" for k, v in sorted(counters.items())))
        hist = (t.get("histograms") or {}).get("journal.commit_s") or {}
        if hist.get("count"):
            print(f"    journal commit: n={hist['count']} "
                  f"mean={hist.get('mean', 0):.5f}s "
                  f"max={hist.get('max', 0):.5f}s")


if __name__ == "__main__":
    main()
