#!/usr/bin/env python
"""Budget advisor: turn a finished journal into knobs for the NEXT run.

A journaled chunk walk (``reliability.fit_chunked(..., checkpoint_dir=``)
records per chunk what an operator would otherwise have to guess for the
next run of the same config hash: how long a chunk really takes (wall_s,
split into trace+compile vs steady-state execute by the telemetry block),
how far OOM backoff had to shrink the chunks (``chunk_rows_after``), which
chunks blew their deadline, and how long each journal commit took.  This
tool reads one manifest and prints suggested

- ``chunk_rows``      — the largest size the run actually sustained (post
                        OOM backoff), so the next run skips the halving
                        dance and its wasted dispatches;
- ``chunk_budget_s``  — headroom over the slowest observed chunk,
                        including the cold compile chunk, so the watchdog
                        catches real hangs without killing honest work;
- ``job_budget_s``    — the same headroom over the whole walk;
- ``pipeline_depth``  — enough in-flight commits to keep the device busy:
                        commit latency divided by steady-state execute
                        wall, +1 (clamped to [1, 8] — past that the queue
                        only buys crash-loss, not overlap);
- ``prefetch_depth``  — enough staged input slices to never block on the
                        copy: mean slice-staging wall divided by execute
                        wall, clamped to [1, 4] (each extra slot pins one
                        more chunk of HBM, so depth stays at the measured
                        need — 1, the classic double buffer, when staging
                        already hides);
- ``align_mode``      — the walk's recorded static alignment plan, so the
                        next run passes the hint and skips even the one
                        per-walk NaN-probe host sync.
- ``host_resident``   — whether the NEXT run of this panel should walk it
                        from host RAM / shard dir (``fit_chunked(fit_fn,
                        as_source(...))``): recommended when the recorded
                        panel bytes crowd the device memory budget
                        (``memory_stats()['bytes_limit']`` when the local
                        backend reports one), with the staging-pool
                        telemetry (pool reuse, H2D wall, donated-buffer
                        peak) echoed so the operator can see what the
                        staging actually cost;
- ``staging_pool_buffers`` — pooled host staging buffers the walk needs
                        (prefetch_depth + 1: one per staged slice plus
                        the one being filled);
- ``shards``          — how many mesh lanes the next run should walk
                        (``fit_chunked(shard=True)`` / ``mesh=``): for a
                        merged sharded manifest, the lanes that actually
                        committed work (an idle lane is a wasted chip);
                        for a single-device manifest, the chunk count —
                        every chunk can be its own lane, and the mesh
                        clamps to its device count at runtime.  Per-shard
                        ``chunk_rows`` is resized so every lane walks at
                        least two chunks (a one-chunk lane has nothing to
                        overlap its commit/staging under), with the
                        per-shard wall balance printed so a straggler
                        lane is visible.

- ``lane_retries`` / ``rebalance_threshold`` — the elastic-lane knobs
                        (ISSUE 11), read from the merged manifest's
                        ``rebalance`` block and the per-lane wall
                        imbalance: transient quarantine causes (allocator
                        storms, deadline blips) earn a lane one more
                        retry, a straggler-paced job gets a lower steal
                        threshold, and the steal counts and quarantine
                        causes are printed as the evidence.

Pointed at an **auto-fit search root** (ISSUE 9: ``auto_manifest.json`` +
per-order/per-group ``grid_*`` journals) the advisor switches to
grid-level advice — ``orders_per_pass`` (prune candidates that never won
a row), the fusion width ``fuse`` (ISSUE 10: how many same-d orders
should share one fused walk, capped by HBM headroom, with the per-order
wall balance and compile-cache hit rate as evidence), and the per-order
``chunk_rows`` (>= 2 chunks per order so each order's compiled program
is reused), from the recorded stage-1 vs stage-2 wall balance and
selection histogram (see :func:`advise_auto`).

Pointed at a **serving root** (ISSUE 12: a ``serving.FitServer``
checkpoint root — ``server.json`` plus one journal per micro-batch under
``batches/<id>/journal``; auto-detected, or force with ``--serving``) the
advisor aggregates the per-batch advice into serving knobs — the
sustained ``cell_rows``, worst-batch ``pipeline_depth``/
``prefetch_depth``/``chunk_budget_s``, the ``max_batch_rows`` coalescing
cap — and reads the server's own shed/reject counters as the overload
evidence (see :func:`advise_serving`).  The same :func:`advise` inference
runs ONLINE inside the server between batches (``FitServer(autotune=
True)``); this mode is the post-mortem view of what it learned.

    python tools/advise_budget.py CHECKPOINT_DIR [--json] [--serving]

Suggestions only apply to a run with the SAME config hash and panel (both
printed): a different model/order/chunk layout re-derives everything.
Exits 2 on a torn manifest (same condition a resume rejects).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

from inspect_journal import load_manifest  # same directory


def _percentile(xs, q):
    xs = sorted(xs)
    if not xs:
        return None
    i = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[i]


def advise(m: dict) -> dict:
    chunks = sorted(m.get("chunks", []), key=lambda e: e["lo"])
    committed = [e for e in chunks if e["status"] == "committed"]
    timeouts = [e for e in chunks if e["status"] == "TIMEOUT"]
    if not committed:
        return {"error": "no committed chunks to learn from",
                "config_hash": m.get("config_hash")}

    # adopted delta chunks (ISSUE 15) carry a synthetic wall_s of 0.0 —
    # they were spliced, not computed — and must not teach the timing
    # model that chunks are free (a 90%-adopted manifest would otherwise
    # suggest budgets that TIMEOUT the next full refit's compile chunk)
    computed = [e for e in committed
                if (e.get("delta") or {}).get("class") != "adopted"]
    walls = [e["wall_s"] for e in computed if e.get("wall_s") is not None]
    sizes = [e["hi"] - e["lo"] for e in committed]
    after = [e.get("chunk_rows_after") for e in computed
             if e.get("chunk_rows_after")]
    requested = int(m.get("chunk_rows") or max(sizes))

    # -- chunk_rows: the size the run proved it can hold ---------------------
    sustained = min(after) if after else max(sizes)
    oom_shrunk = sustained < requested
    chunk_rows = sustained

    # -- compile vs execute split (telemetry block when present) -------------
    tele = m.get("telemetry") or {}
    exec_walls, compile_walls = [], []
    for c in tele.get("chunks") or []:
        w = c.get("wall_s")
        if w is None:
            continue
        (compile_walls if c.get("phase") == "compile+execute"
         else exec_walls).append(w)
    # fall back to manifest wall_s when the run had no telemetry: treat the
    # first chunk as the compile chunk (that is where JAX pays trace+compile)
    if not exec_walls and walls:
        compile_walls = walls[:1]
        exec_walls = walls[1:] or walls[:1]

    # -- chunk_budget_s: 2x the slowest honest chunk (compile included) ------
    chunk_budget_s = None
    if walls or compile_walls:
        slowest = max(walls + compile_walls)
        chunk_budget_s = math.ceil(2.0 * slowest)
        # a run that actually timed out at a tighter budget than the new
        # suggestion is evidence the old budget was too tight, not that the
        # chunks hang — note it rather than silently raising the bound
    job_budget_s = None
    if walls:
        n_chunks_next = max(1, -(-int(m.get("n_rows", sum(sizes)))
                                 // max(1, chunk_rows)))
        per_chunk = _percentile(exec_walls, 0.9) or max(walls)
        cold = max(compile_walls) if compile_walls else per_chunk
        job_budget_s = math.ceil(1.5 * (cold + per_chunk * n_chunks_next))

    # -- pipeline_depth: hide commit latency under execute wall --------------
    commit = ((tele.get("histograms") or {}).get("journal.commit_s") or {})
    pipeline_depth = 2  # the driver default: one commit hides under one fit
    commit_mean = commit.get("mean")
    exec_mean = (sum(exec_walls) / len(exec_walls)) if exec_walls else None
    if commit_mean and exec_mean and exec_mean > 0:
        pipeline_depth = max(1, min(8, math.ceil(commit_mean / exec_mean) + 1))

    # -- prefetch_depth: hide input staging under execute wall ---------------
    # the manifest's telemetry block records the walk's input-staging
    # accounting (reliability.prefetcher) and the static align-mode plan;
    # a run without them (prefetch disabled, pre-ISSUE-5 journal) keeps the
    # driver default and suggests no hint
    staging = tele.get("input_staging") or {}
    align_mode = tele.get("align_mode")
    prefetch_depth = 1  # the driver default: the classic double buffer
    staged = staging.get("chunks_staged") or 0
    staging_mean = ((staging.get("staging_wall_s") or 0.0) / staged
                    if staged else None)
    if staging_mean and exec_mean and exec_mean > 0:
        prefetch_depth = max(1, min(4, math.ceil(staging_mean / exec_mean)))

    # -- host residency: should the panel live off-device? (ISSUE 7) ---------
    # the manifest records what the walk read (`extra.source`: kind and
    # panel bytes) and — for host-resident walks — the staging-pool
    # accounting; the local device's allocator budget decides whether the
    # NEXT run of this panel still fits in HBM next to its workspace
    source_extra = (m.get("extra") or {}).get("source") or {}
    pool = staging.get("staging_pool") or {}
    # panel bytes: from the source block (host/npz walks) or the panel
    # geometry every journaled walk records — so the advice fires for
    # IN-HBM manifests, where "go host-resident next time" is actionable
    panel_bytes = (source_extra.get("panel_bytes")
                   or ((m.get("extra") or {}).get("panel") or {}).get(
                       "bytes"))
    budget_bytes = _device_budget_bytes()
    host_resident = None
    host_resident_reason = None
    if panel_bytes and budget_bytes:
        # the walk needs the panel AND chunk workspace resident; past
        # ~60% of the budget the in-HBM walk is one allocation away from
        # the OOM-backoff ladder — stage from host instead
        host_resident = panel_bytes > 0.6 * budget_bytes
        host_resident_reason = (
            f"panel {panel_bytes / 1e9:.2f} GB vs device budget "
            f"{budget_bytes / 1e9:.2f} GB")
    elif source_extra.get("kind") in ("host", "npz_dir"):
        host_resident = True  # it already ran host-resident and finished
        host_resident_reason = f"ran host-resident ({source_extra['kind']})"
    pool_ops = (pool.get("pool_hits") or 0) + (pool.get("pool_misses") or 0)
    pool_obs = None
    if pool:
        pool_obs = {
            "pool_hit_rate": (round((pool.get("pool_hits") or 0) / pool_ops,
                                    4) if pool_ops else None),
            "h2d_wall_s": pool.get("h2d_wall_s"),
            "h2d_bytes": pool.get("h2d_bytes"),
            "peak_live_device_bytes": pool.get("peak_live_device_bytes"),
            "peak_host_bytes": pool.get("peak_host_bytes"),
        }

    # -- shards: lanes for the next run's mesh walk (ISSUE 6) ----------------
    # a merged sharded manifest records which lanes actually carried work
    # and how their walls balanced; a single-device manifest still says how
    # many lanes the chunk grid COULD feed (the mesh clamps to its devices)
    n_rows = int(m.get("n_rows", sum(sizes)))
    shards_block = m.get("shards") or []
    shard_obs = None
    if shards_block:
        worked = [s for s in shards_block
                  if (s.get("chunks_committed") or s.get("chunks_timeout"))]
        lane_walls = {}
        for e in chunks:
            sid = e.get("shard_id")
            if sid is not None and e.get("wall_s") is not None:
                lane_walls[sid] = lane_walls.get(sid, 0.0) + e["wall_s"]
        balance = None
        if lane_walls:
            mean_w = sum(lane_walls.values()) / len(lane_walls)
            balance = (round(max(lane_walls.values()) / mean_w, 4)
                       if mean_w > 0 else None)
        shard_obs = {
            "n_shards": len(shards_block),
            "lanes_with_work": len(worked),
            "shard_wall_balance": balance,  # max lane wall / mean lane wall
            "lane_walls_s": {str(k): round(v, 4)
                             for k, v in sorted(lane_walls.items())},
        }
        shards_suggest = max(1, len(worked))
    else:
        balance = None
        # unsharded run: each chunk can become a lane (the coarsest useful
        # split); the runtime mesh clamps this to its device count
        shards_suggest = max(1, -(-n_rows // max(1, chunk_rows)))
    # per-shard chunk_rows: every lane should walk >= 2 chunks so its
    # commit/staging has a next chunk to hide under — never grow past the
    # OOM-sustained size
    rows_per_shard = -(-n_rows // shards_suggest)
    chunk_rows_sharded = max(1, min(chunk_rows, -(-rows_per_shard // 2))) \
        if shards_suggest > 1 else chunk_rows

    # -- elastic lanes: lane_retries + rebalance_threshold (ISSUE 11) --------
    # the merged manifest's `rebalance` block records what the supervisor
    # actually did — quarantine causes, steals, spans reassigned — and the
    # per-lane wall imbalance says whether the threshold let a straggler
    # pace the job.  Transient-looking causes (allocator storms, deadline
    # blips) earn the lane one more retry; deterministic failures make
    # extra retries wasted wall.
    rb = m.get("rebalance") or {}
    quarantined = rb.get("quarantined") or []
    transient_markers = ("RESOURCE_EXHAUSTED", "Out of memory",
                         "DeadlineExceeded", "OOMBackoffExceeded")
    transient = [q for q in quarantined
                 if any(t in (q.get("cause") or "") for t in transient_markers)]
    lane_retries = 1  # the driver default
    if quarantined:
        lane_retries = 2 if transient else 1
    steals = rb.get("steals") or 0
    rebalance_threshold = 4.0  # the driver default
    if balance is not None:
        if balance > 2.0:
            # a straggler paced the job and stealing never (or barely)
            # engaged: hand work off sooner next run
            rebalance_threshold = 1.5 if steals else 2.0
        elif steals and balance <= 1.2:
            # stealing engaged and the walls came out level: keep it
            rebalance_threshold = 4.0
    rebalance_obs = None
    if rb or quarantined:
        rebalance_obs = {
            "steals": steals,
            "reassigned_chunks": rb.get("reassigned_chunks"),
            "lane_retries_used": rb.get("lane_retries_used"),
            "quarantine_causes": [
                {"shard_id": q.get("shard_id"),
                 "retries": q.get("retries"),
                 "cause": (q.get("cause") or "")[:120]}
                for q in quarantined],
        }

    # -- forecast walks: horizon-aware chunk sizing (ISSUE 14) ---------------
    # a forecast manifest (`extra.forecast`) records the walk's horizon,
    # augmented width, and Monte-Carlo sampling config; the per-row
    # working set then scales with horizon (packed output + S simulated
    # paths), so the proven chunk size carries as a rows x working-set
    # budget — the next run at horizon h' solves rows from the same
    # budget instead of replaying the OOM ladder
    forecast_extra = (m.get("extra") or {}).get("forecast") or {}
    forecast_obs = None
    forecast_suggest = None
    if forecast_extra:
        fh = int(forecast_extra.get("horizon") or 1)
        f_nt = int(forecast_extra.get("n_time") or 0)
        f_k = int(forecast_extra.get("k") or 0)
        f_iv = bool(forecast_extra.get("intervals"))
        f_ns = int(forecast_extra.get("n_samples") or 0) if f_iv else 0
        row_floats = (f_nt + f_k + 2) + fh * (3 if f_iv else 1) + f_ns * fh
        budget_floats = sustained * row_floats  # proven per-chunk set
        forecast_obs = {
            "model": forecast_extra.get("model"),
            "horizon": fh,
            "intervals": f_iv,
            "n_samples": f_ns or None,
            "row_working_set_floats": row_floats,
        }
        forecast_suggest = {
            "horizon": fh,
            # rows for a DIFFERENT horizon h': budget // working_set(h')
            "chunk_rows_working_set_floats": budget_floats,
            "chunk_rows_at_2x_horizon": max(1, budget_floats // (
                (f_nt + f_k + 2) + 2 * fh * (3 if f_iv else 1)
                + f_ns * 2 * fh)),
        }

    # -- delta walks: what fraction of the panel actually changed ------------
    # a delta manifest (`extra.delta`, ISSUE 15) records the planner's
    # adopted/warm/dirty/new classification; the dirty fraction is THE
    # number that says whether the tick-feed pipeline is paying
    # incremental cost or silently degenerating to full refits.  A
    # non-delta manifest whose chunks carry content fingerprints is
    # delta-ELIGIBLE: the next run of a grown/revised version of this
    # panel should pass delta_from= instead of refitting everything.
    delta_block = (m.get("extra") or {}).get("delta") or {}
    delta_obs = None
    delta_from_suggest = None
    if delta_block:
        dc = delta_block.get("counts") or {}
        total = max(1, sum(dc.values()))
        delta_obs = {
            "from": delta_block.get("from"),
            "counts": dc,
            "warmstart": delta_block.get("warmstart"),
            "dirty_fraction": round(
                1.0 - (dc.get("adopted") or 0) / total, 4),
        }
    elif any(e.get("chunk_fingerprint") for e in committed):
        delta_from_suggest = (
            "chunk fingerprints present: an appended/revised rerun of "
            "this panel can pass delta_from= at this journal and adopt "
            "every unchanged chunk")

    return {
        "config_hash": m.get("config_hash"),
        "panel_fingerprint": m.get("panel_fingerprint"),
        "observed": {
            "chunks_committed": len(committed),
            "chunks_timeout": len(timeouts),
            "chunk_rows_requested": requested,
            "chunk_rows_sustained": sustained,
            "oom_backoff_engaged": oom_shrunk,
            "chunk_wall_s_max": max(walls) if walls else None,
            "chunk_wall_s_p90": _percentile(walls, 0.9) if walls else None,
            "execute_wall_s_mean": (round(exec_mean, 4)
                                    if exec_mean is not None else None),
            "compile_wall_s_max": (max(compile_walls)
                                   if compile_walls else None),
            "commit_s_mean": commit_mean,
            "commit_s_max": commit.get("max"),
            "staging_wall_s_mean": (round(staging_mean, 4)
                                    if staging_mean is not None else None),
            "input_overlap_efficiency":
                staging.get("input_overlap_efficiency"),
            "align_mode": align_mode,
            "source_kind": source_extra.get("kind"),
            "panel_bytes": panel_bytes,
            "device_budget_bytes": budget_bytes,
            "staging_pool": pool_obs,
            "shards": shard_obs,
            "rebalance": rebalance_obs,
            "forecast": forecast_obs,
            "delta": delta_obs,
        },
        "suggest": {
            "chunk_rows": chunk_rows,
            "chunk_budget_s": chunk_budget_s,
            "job_budget_s": job_budget_s,
            "pipeline_depth": pipeline_depth,
            "prefetch_depth": prefetch_depth,
            "staging_pool_buffers": prefetch_depth + 1,
            "host_resident": host_resident,
            "host_resident_reason": host_resident_reason,
            "align_mode": align_mode,
            "shards": shards_suggest,
            "chunk_rows_per_shard": chunk_rows_sharded,
            "lane_retries": lane_retries,
            "rebalance_threshold": rebalance_threshold,
            "forecast": forecast_suggest,
            "delta_from": delta_from_suggest,
        },
    }


def advise_profiles(root: str):
    """Warm-routing advice from a serving root's tenant profiles
    (ISSUE 19: ``serving.TenantProfileStore`` — one npz per tenant under
    ``<root>/profiles/``).

    Reads are unfenced by design (the store's read side is the standby/
    tooling surface), so this advisor can run against a LIVE fleet root.
    Per tenant it turns the profile's evidence into the next search's
    knobs:

    - ``stepwise_seed_orders`` / ``stepwise_max_order`` — a drifted
      re-search seeds from the profile's distinct winning orders; the
      expansion cap goes one step past their largest ``p``/``q`` so the
      first stepwise pass still has somewhere to move;
    - ``cell_rows`` — a tenant whose winner map has held for two or more
      passes (``stability >= 2``) takes the warm path on its next
      submit: stage 1 is skipped and every row refits its known winning
      order in per-basin warm walks, so the panel can walk as one cell —
      chunking for search-budget control buys nothing there.

    Returns ``None`` when the root has no ``profiles/`` namespace (the
    server never saw an auto-fit submit), an ``error`` dict when the
    package is unimportable, else the per-tenant advice table.
    """
    pdir = os.path.join(root, "profiles")
    if not os.path.isdir(pdir):
        return None
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import numpy as np

        from spark_timeseries_tpu.serving.profiles import TenantProfileStore
    except Exception as e:  # noqa: BLE001 - tooling must degrade loudly
        return {"error": f"cannot import serving.profiles ({e})"}
    store = TenantProfileStore(pdir)
    per_tenant = []
    for tenant in store.tenants():
        prof = store.load(tenant)
        if prof is None:
            continue
        idx = np.asarray(prof["order_index"], np.int64)
        orders = np.asarray(prof["orders"], np.int64).reshape(-1, 3)
        winners = sorted({tuple(int(v) for v in orders[g])
                          for g in idx[idx >= 0]})
        span = max((max(o[0], o[2]) for o in winners), default=0)
        stability = int(prof.get("stability", 0))
        rows = int(prof.get("rows", idx.shape[0]))
        per_tenant.append({
            "tenant": prof["tenant"],
            "rows": rows,
            "passes": int(prof.get("passes", 0)),
            "stability": stability,
            "last_route": prof.get("route"),
            "winners": [list(o) for o in winners],
            "suggest": {
                "stepwise_seed_orders": len(winners),
                "stepwise_max_order": span + 1,
                "cell_rows": rows if stability >= 2 else None,
            },
        })
    return {
        "profiled": len(per_tenant),
        "stable": sum(1 for t in per_tenant if t["stability"] >= 2),
        "per_tenant": per_tenant,
    }


def _render_profiles(p: dict) -> None:
    print(f"  tenant profiles: {p['profiled']} profiled, {p['stable']} "
          "stable (warm-path candidates on their next submit)")
    for t in p["per_tenant"]:
        s = t["suggest"]
        winners = ", ".join("(%d,%d,%d)" % tuple(o) for o in t["winners"])
        print(f"    {t['tenant']}: rows {t['rows']}, passes {t['passes']}, "
              f"stability {t['stability']}, last route {t['last_route']}; "
              f"winners {winners or '-'}")
        line = (f"      suggest: stepwise seeds = "
                f"{s['stepwise_seed_orders']} order(s), stepwise_max_order"
                f" = {s['stepwise_max_order']}")
        if s["cell_rows"]:
            line += (f", cell_rows = {s['cell_rows']} (stable tenant: the"
                     " warm refit walks the panel as one cell)")
        print(line)


def advise_serving(root: str) -> dict:
    """Serving-mode advice (ISSUE 12): a :class:`serving.FitServer`
    checkpoint root — ``server.json`` + one journal per micro-batch under
    ``batches/<id>/journal`` — instead of one walk's manifest.

    Runs the per-manifest :func:`advise` over every batch journal and
    aggregates: the **cell** size batches actually sustained (the
    server's ``cell_rows`` knob — also what its own online adaptation
    applies between batches), ``pipeline_depth``/``prefetch_depth`` at
    the across-batch max (sized for the worst batch), a
    ``chunk_budget_s`` over the slowest observed chunk, plus
    serving-level knobs from the server's own record: shed/reject counts
    argue for more queue or more capacity, and the observed batch-size
    distribution argues the ``max_batch_rows``/``batch_window_s``
    coalescing trade.
    """
    sj_path = os.path.join(root, "server.json")
    try:
        with open(sj_path) as f:
            server = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return {"error": f"not a serving root ({e})"}
    per_batch = []
    batches_dir = os.path.join(root, "batches")
    n_manifests = 0
    if os.path.isdir(batches_dir):
        for bid in sorted(os.listdir(batches_dir)):
            mp = os.path.join(batches_dir, bid, "journal", "manifest.json")
            if not os.path.exists(mp):
                continue
            n_manifests += 1
            try:
                a = advise(load_manifest(mp))
            except SystemExit:
                continue
            if "error" not in a:
                per_batch.append(a)
    counters = server.get("counters") or {}
    knobs = server.get("knobs") or {}
    # tenant profiles (ISSUE 19) ride along whenever the root has a
    # profiles/ namespace — auto-fit submits bypass the micro-batcher,
    # so a warm serving root can have profile evidence with ZERO batch
    # journals and the advice must not vanish behind the batch gate
    profiles = advise_profiles(root)
    if not per_batch:
        out = {"error": "no committed batch journals to learn from",
               "serving": {"server_state": server.get("state"),
                           "counters": counters}}
        if profiles is not None:
            out["profiles"] = profiles
        return out

    def _vals(path):
        out = []
        for a in per_batch:
            v = a
            for k in path:
                v = (v or {}).get(k)
            if v is not None:
                out.append(v)
        return out

    cells = _vals(("suggest", "chunk_rows"))
    batch_rows = _vals(("observed", "chunks_committed"))
    chunk_walls = _vals(("observed", "chunk_wall_s_max"))
    rows_per_batch = []
    for a in per_batch:
        o = a["observed"]
        rows_per_batch.append(o["chunk_rows_sustained"]
                              * max(1, o["chunks_committed"]))
    shed = counters.get("shed", 0)
    rejected = counters.get("rejected", 0)
    admitted = max(1, counters.get("admitted", 0))
    pressure = (shed + rejected) / (admitted + shed + rejected)
    q = server.get("queue") or {}
    suggest = {
        "cell_rows": int(_percentile(sorted(cells), 0.5)) if cells else
        knobs.get("cell_rows"),
        "pipeline_depth": max(_vals(("suggest", "pipeline_depth")) or [2]),
        "prefetch_depth": max(_vals(("suggest", "prefetch_depth")) or [1]),
        "chunk_budget_s": (max(_vals(("suggest", "chunk_budget_s")) or [0])
                           or None),
        # coalescing: if batches run well under the cap, a longer window
        # would pack more; if they saturate it, the cap is the lever
        "max_batch_rows": max(server.get("max_batch_rows") or 0,
                              int(1.5 * max(rows_per_batch))
                              if rows_per_batch else 0) or None,
        # backpressure: sustained shedding means the queue is the
        # bottleneck surface — either raise it (more RAM) or add capacity
        "raise_queue_or_capacity": pressure > 0.05,
    }
    out = {
        "serving": {
            "server_state": server.get("state"),
            "batches_advised": len(per_batch),
            "batch_manifests": n_manifests,
            "counters": counters,
            "queue": q,
            "knobs_in_effect": knobs,
            "shed_plus_reject_rate": round(pressure, 4),
            "rows_per_batch_p90": (int(_percentile(sorted(rows_per_batch),
                                                   0.9))
                                   if rows_per_batch else None),
            "chunk_wall_s_max": (round(max(chunk_walls), 4)
                                 if chunk_walls else None),
            "batches_with_commits": len(batch_rows),
        },
        "suggest": suggest,
    }
    if profiles is not None:
        out["profiles"] = profiles
    return out


def _render_serving(root: str, a: dict) -> None:
    s, o = a["suggest"], a["serving"]
    print(f"serving root {root}")
    c = o["counters"]
    print(f"  server: state {o['server_state']}, "
          f"{o['batches_advised']} batch journals advised "
          f"(of {o['batch_manifests']})")
    print(f"  traffic: {c.get('admitted', 0)} admitted / "
          f"{c.get('completed', 0)} completed / {c.get('shed', 0)} shed / "
          f"{c.get('rejected', 0)} rejected "
          f"(shed+reject rate {o['shed_plus_reject_rate']})")
    if c.get("batch_failures"):
        print(f"  degradation: {c['batch_failures']} batch failures, "
              f"{c.get('solo_retries', 0)} solo retries, "
              f"{c.get('timeout_requests', 0)} requests with TIMEOUT rows")
    if o["rows_per_batch_p90"] is not None:
        print(f"  batches: p90 {o['rows_per_batch_p90']} rows"
              + (f"; slowest chunk {o['chunk_wall_s_max']}s"
                 if o["chunk_wall_s_max"] is not None else ""))
    print("  suggest for this server's next life:")
    print(f"    cell_rows      = {s['cell_rows']}")
    print(f"    pipeline_depth = {s['pipeline_depth']}")
    print(f"    prefetch_depth = {s['prefetch_depth']}")
    if s["chunk_budget_s"]:
        print(f"    chunk_budget_s = {s['chunk_budget_s']}")
    if s["max_batch_rows"]:
        print(f"    max_batch_rows = {s['max_batch_rows']}")
    if s["raise_queue_or_capacity"]:
        print("    overload: sustained shedding — raise max_queue_rows "
              "(more RAM) or add serving capacity")
    if a.get("profiles") and "error" not in a["profiles"]:
        _render_profiles(a["profiles"])


def advise_auto(root: str) -> dict:
    """Auto-fit search advice (ISSUE 9): read the grid-level
    ``auto_manifest.json`` plus one per-order journal and suggest

    - ``orders_per_pass`` — how many candidate orders the NEXT search of
      this panel should sweep before pruning: the orders that actually won
      rows (+1 exploration slot, never below 2) — a candidate that never
      wins spends a full stage-1 sweep with zero stage-2 payoff, and the
      recorded selection histogram is the evidence;
    - ``chunk_rows_grid`` — the per-order walk's chunk size: the sustained
      (post-OOM-backoff) size from the per-order journals, resized so
      every order walks >= 2 chunks (program reuse across chunks is the
      point of the per-order compile cache, and a one-chunk walk has
      nothing to overlap its commits under);

    alongside the observed stage-1 vs stage-2 wall balance (the
    ``stage2="winners"`` economy is worth switching to when stage-2 spend
    is a small share of a full search, and worth widening the grid under
    when it already dominates).
    """
    path = os.path.join(root, "auto_manifest.json") if os.path.isdir(root) \
        else root
    try:
        with open(path, "rb") as f:
            m = json.loads(f.read().decode())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        sys.exit(f"advise_budget: auto manifest {path} unreadable ({e})")
    a = m.get("auto_fit") or {}
    orders = a.get("orders") or []
    counts = a.get("selection_counts") or {}
    winners = [o for o in orders if (o.get("selected_rows") or 0) > 0]
    g_total = max(len(orders), 1)
    orders_per_pass = max(2, min(g_total, len(winners) + 1))
    n_rows = int(a.get("n_rows") or 0)

    # per-order chunk advice: reuse the ordinary advisor on the first
    # per-order journal that has committed chunks (all orders share the
    # panel and the chunk layout, so one manifest speaks for the grid)
    chunk_rows_grid = None
    per_order = None
    base = root if os.path.isdir(root) else os.path.dirname(path)
    for d in sorted(m.get("grid_dirs") or []):
        sub = os.path.join(base, d, "manifest.json")
        if not os.path.exists(sub):
            continue
        per_order = advise(load_manifest(sub))
        if "error" not in per_order:
            sustained = per_order["suggest"]["chunk_rows"]
            # >= 2 chunks per order so the compiled program is REUSED
            # within the walk and commits/staging have a next chunk
            chunk_rows_grid = max(1, min(sustained,
                                         -(-n_rows // 2) if n_rows else
                                         sustained))
            break

    stage1_wall = a.get("stage1_wall_s")
    stage2_wall = a.get("stage2_wall_s")
    per_order_wall = (round(stage1_wall / g_total, 4)
                      if isinstance(stage1_wall, (int, float)) and g_total
                      else None)
    cc = a.get("compile_cache") or {}

    # -- fusion width K (ISSUE 10): how many same-d orders should share
    # one walk next time.  The ceiling is the largest same-d cohort on
    # the grid (fusion never crosses d); HBM headroom caps it — the
    # fused program holds the chunk panel plus K orders' optimizer state
    # and up to K differenced variants, so past ~half the device budget
    # the group would meet the OOM-backoff ladder instead of amortizing
    # the walk.  Per-order wall balance and the compile-cache hit rate
    # are echoed as the evidence: balanced walls mean no straggler order
    # gates the fused lockstep, and a LOW hit rate means the per-order
    # walks were paying compiles fusion would amortize.
    by_d: dict = {}
    for o in orders:
        od = o.get("order") or [0, 0, 0]
        by_d[od[1]] = by_d.get(od[1], 0) + 1
    max_same_d = max(by_d.values()) if by_d else 1
    walls = [o.get("wall_s") for o in orders
             if isinstance(o.get("wall_s"), (int, float))]
    wall_balance = None
    if walls and sum(walls) > 0:
        wall_balance = round(max(walls) / (sum(walls) / len(walls)), 4)
    budget_bytes = _device_budget_bytes()
    fuse_mem_cap = None
    po_obs = (per_order or {}).get("observed") or {}
    panel_bytes = po_obs.get("panel_bytes")
    if budget_bytes and panel_bytes and n_rows and chunk_rows_grid:
        chunk_bytes = panel_bytes * chunk_rows_grid / n_rows
        if chunk_bytes > 0:
            fuse_mem_cap = max(1, int(0.5 * budget_bytes / chunk_bytes) - 2)
    fuse_suggest = max_same_d
    if fuse_mem_cap is not None:
        fuse_suggest = max(1, min(fuse_suggest, fuse_mem_cap))
    fuse_reason = (f"largest same-d cohort {max_same_d}"
                   + (f", HBM headroom caps at {fuse_mem_cap}"
                      if fuse_mem_cap is not None
                      and fuse_mem_cap < max_same_d else "")
                   + (f"; per-order wall balance {wall_balance}"
                      if wall_balance is not None else "")
                   + (f"; compile-cache hit rate {cc.get('hit_rate')}"
                      if cc.get("hit_rate") is not None else ""))

    return {
        "auto_fit": True,
        "observed": {
            "criterion": a.get("criterion"),
            "stage2_mode": a.get("stage2"),
            "n_rows": n_rows,
            "orders_tried": len(orders),
            "orders_with_wins": len(winners),
            "selection_counts": counts,
            "stage1_wall_s": stage1_wall,
            "stage2_wall_s": stage2_wall,
            "stage2_spend_share": a.get("stage2_spend_share"),
            "stage1_wall_s_per_order": per_order_wall,
            "compile_cache_hit_rate": cc.get("hit_rate"),
            "fuse_used": a.get("fuse"),
            "fusion_groups": len(a.get("fusion_groups") or []) or None,
            "diff_cache_hits": a.get("diff_cache_hits"),
            "max_same_d_orders": max_same_d,
            "order_wall_balance": wall_balance,
        },
        "suggest": {
            "orders_per_pass": orders_per_pass,
            "orders_kept": [o.get("label") or str(tuple(o.get("order")))
                            for o in winners],
            "chunk_rows_grid": chunk_rows_grid,
            "fuse": fuse_suggest,
            "fuse_reason": fuse_reason,
            "per_order": (per_order or {}).get("suggest"),
        },
    }


def _render_auto(root: str, a: dict) -> None:
    o, s = a["observed"], a["suggest"]
    print(f"auto-fit search {root}")
    print(f"  criterion {o['criterion']}  stage2 {o['stage2_mode']}  "
          f"{o['n_rows']} rows x {o['orders_tried']} candidate orders")
    print(f"  observed: {o['orders_with_wins']} orders won rows; "
          f"stage-1 wall {o['stage1_wall_s']}s "
          f"({o['stage1_wall_s_per_order']}s/order), "
          f"stage-2 wall {o['stage2_wall_s']}s "
          f"(spend share {o['stage2_spend_share']})")
    if o["compile_cache_hit_rate"] is not None:
        print(f"  compile cache: program hit rate "
              f"{o['compile_cache_hit_rate']}")
    print("  selection:", ", ".join(f"{k}={v}"
                                    for k, v in o["selection_counts"].items()))
    if o.get("diff_cache_hits") is not None:
        print(f"  fusion: fuse={o.get('fuse_used')!r} over "
              f"{o.get('fusion_groups')} group(s); shared-prep cache "
              f"saved {o['diff_cache_hits']} differencing(s)")
    print("  suggest for the next search of this panel/grid:")
    print(f"    orders_per_pass = {s['orders_per_pass']}  "
          f"(winners {s['orders_kept']} + 1 exploration slot)")
    print(f"    fuse            = {s['fuse']}  ({s['fuse_reason']})")
    if s["chunk_rows_grid"] is not None:
        print(f"    chunk_rows (per-order grid walk) = "
              f"{s['chunk_rows_grid']}  (>= 2 chunks/order so each "
              "order's compiled program is reused)")
    if s["per_order"]:
        p = s["per_order"]
        print(f"    per-order walk knobs: chunk_budget_s = "
              f"{p.get('chunk_budget_s')}, pipeline_depth = "
              f"{p.get('pipeline_depth')}, prefetch_depth = "
              f"{p.get('prefetch_depth')}")


def advise_chaos(root: str) -> dict:
    """Advice from a chaos/soak manifest (ISSUE 17): read the scenario
    record ``reliability.chaos.write_chaos_manifest`` left at the fleet
    root and turn its evidence — the read-probe timeline, hedge win
    rate, endpoint-health counters, lease transitions — into the
    client-tuning knobs for the next run:

    - ``failure_threshold`` — how many consecutive failures should open
      the client's circuit: when the fleet went dark longer than a few
      probe periods but no circuit ever opened, the breaker was too
      patient (lower by one, floor 2); when circuits opened but the
      longest outage stayed under one probe period, it was too jumpy;
    - ``cooldown_base_s`` — the deterministic probe-backoff base:
      roughly half the observed takeover gap (lease transition to
      first healthy probe), so a cooled endpoint is re-probed about
      when the fleet has actually recovered;
    - ``hedge_after_s`` — hedged polls that never win are pure load
      (double it); a majority win rate means the primary poll is the
      slow path (halve it);
    - ``max_unavailable_s`` — the next soak's availability floor:
      the longest observed outage with 4x headroom, so the gate trips
      on regression, not on noise.
    """
    path = os.path.join(root, "chaos_manifest.json") if os.path.isdir(root) \
        else root
    try:
        with open(path, "rb") as f:
            m = json.loads(f.read().decode())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        sys.exit(f"advise_budget: chaos manifest {path} unreadable ({e})")
    probes = m.get("probes") or []
    ok = sum(1 for _, p_ok in probes if p_ok)
    windows = m.get("unavailability_windows") or []
    longest = max((b - a for a, b in windows), default=0.0)
    total_dark = sum(b - a for a, b in windows)
    period = float(m.get("probe_period_s") or 0.1)
    hedge = m.get("hedge") or {}
    launched = int(hedge.get("launched") or 0)
    won = int(hedge.get("won") or 0)
    win_rate = round(won / launched, 3) if launched else None
    client = m.get("client") or {}
    cur_threshold = int(client.get("failure_threshold") or 3)
    cur_hedge = client.get("hedge_after_s")
    eh = (m.get("endpoint_health") or {}).get("endpoints") or {}
    openings = sum(int(r.get("openings") or 0) for r in eh.values())
    failures = sum(int(r.get("failures") or 0) for r in eh.values())
    lease = m.get("lease_history") or []

    # breaker: dark fleet + a breaker that never opened = too patient;
    # opened breakers with sub-probe-period outages = too jumpy
    threshold = cur_threshold
    if longest > 3 * period and openings == 0:
        threshold = max(2, cur_threshold - 1)
    elif openings > 0 and longest < period:
        threshold = cur_threshold + 1

    # cooldown: half the takeover gap (lease flip to recovery), so the
    # first deterministic re-probe lands about when the fleet is back
    takeover_gap = None
    if len(lease) >= 2:
        takeover_gap = round(lease[-1]["t_s"] - lease[0]["t_s"], 3)
    cooldown = None
    if longest > 0:
        cooldown = round(min(max(longest / 2.0, 0.1), 2.0), 3)
    elif takeover_gap:
        cooldown = round(min(max(takeover_gap / 2.0, 0.1), 2.0), 3)

    hedge_after = cur_hedge
    if launched and cur_hedge is not None:
        if won == 0:
            hedge_after = round(float(cur_hedge) * 2.0, 3)
        elif win_rate is not None and win_rate > 0.5:
            hedge_after = round(float(cur_hedge) / 2.0, 3)

    return {
        "chaos": True,
        "observed": {
            "seed": m.get("seed"),
            "events_fired": len(m.get("fired") or []),
            "requests_expected": len((m.get("requests") or {})
                                     .get("expected") or []),
            "requests_answered": (m.get("requests") or {}).get("answered"),
            "violations": len(m.get("violations") or []),
            "probes": len(probes),
            "probe_ok_rate": round(ok / len(probes), 3) if probes else None,
            "longest_unavailable_s": round(longest, 3),
            "total_unavailable_s": round(total_dark, 3),
            "availability_bound_s": m.get("max_unavailable_s"),
            "hedges_launched": launched,
            "hedges_won": won,
            "hedge_win_rate": win_rate,
            "circuit_openings": openings,
            "endpoint_failures": failures,
            "lease_transitions": len(lease),
            "takeover_gap_s": takeover_gap,
            "write_refused_as": m.get("write_refused_as"),
        },
        "suggest": {
            "failure_threshold": threshold,
            "cooldown_base_s": cooldown,
            "hedge_after_s": hedge_after,
            "max_unavailable_s": (round(max(longest * 4.0, 1.0), 3)
                                  if probes else None),
        },
    }


def _render_chaos(root: str, a: dict) -> None:
    o, s = a["observed"], a["suggest"]
    print(f"chaos soak {root}")
    print(f"  scenario: seed {o['seed']}, {o['events_fired']} fault "
          f"event(s) fired, {o['lease_transitions']} lease "
          f"transition(s)"
          + (f" (takeover gap {o['takeover_gap_s']}s)"
             if o["takeover_gap_s"] is not None else ""))
    print(f"  requests: {o['requests_answered']}/"
          f"{o['requests_expected']} answered, "
          f"{o['violations']} invariant violation(s)")
    print(f"  availability: {o['probes']} read probes, ok rate "
          f"{o['probe_ok_rate']}; unavailable longest "
          f"{o['longest_unavailable_s']}s / total "
          f"{o['total_unavailable_s']}s (bound "
          f"{o['availability_bound_s']}s)")
    print(f"  client: hedges launched {o['hedges_launched']} won "
          f"{o['hedges_won']} (win rate {o['hedge_win_rate']}); "
          f"circuit openings {o['circuit_openings']}, endpoint "
          f"failures {o['endpoint_failures']}"
          + (f"; writes refused as {o['write_refused_as']}"
             if o.get("write_refused_as") else ""))
    print("  suggest for the next soak / client config:")
    print(f"    failure_threshold = {s['failure_threshold']}")
    if s["cooldown_base_s"] is not None:
        print(f"    cooldown_base_s   = {s['cooldown_base_s']}  "
              "(first re-probe lands about when the fleet recovers)")
    if s["hedge_after_s"] is not None:
        print(f"    hedge_after_s     = {s['hedge_after_s']}")
    if s["max_unavailable_s"] is not None:
        print(f"    max_unavailable_s = {s['max_unavailable_s']}  "
              "(longest observed outage x4 headroom)")


def advise_tickloop(root: str) -> dict:
    """Tick-loop advice (ISSUE 20): a ``serving.TickLoop`` root —
    ``tickloop.json`` plus one ``cycle_%05d`` dir per completed tick
    batch — instead of one walk's manifest.

    Per published cycle the loop records its stage walls (append / fit /
    publish) and the fit's delta classification; the advisor aggregates:

    - **cycle cadence** — the sustained tick-to-publish wall with 2x
      headroom is the shortest tick interval the loop keeps up with;
      feed ticks faster than that and cycles queue behind the fit;
    - **delta_from chaining** — whether the warm chain is actually
      paying: the across-cycle dirty fraction (warm+dirty+new over all
      chunks) near 1.0 with ``delta=False`` says turn chaining ON; a
      low dirty fraction confirms the appended-ticks fast path held;
    - the per-walk knobs (``chunk_rows``, budgets, depths) from the
      newest published cycle's fit journal via :func:`advise` — every
      cycle refits the same grown panel under the same config hash.
    """
    mp = os.path.join(root, "tickloop.json") if os.path.isdir(root) \
        else root
    try:
        with open(mp, "rb") as f:
            m = json.loads(f.read().decode())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        sys.exit(f"advise_budget: tickloop manifest {mp} unreadable ({e})")
    base = root if os.path.isdir(root) else os.path.dirname(mp)
    cycles = []
    for name in sorted(os.listdir(base)):
        cm_path = os.path.join(base, name, "tick_manifest.json")
        if not (name.startswith("cycle_") and os.path.exists(cm_path)):
            continue
        try:
            with open(cm_path, "rb") as f:
                cycles.append((name, json.loads(f.read().decode())))
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            continue
    published = [(n, c) for n, c in cycles
                 if c.get("stage") == "published"]
    if not published:
        return {"error": "no published cycles to learn from",
                "tickloop": {"cycles_seen": len(cycles)}}
    cycle_walls, counts = [], {}
    ticks_total = 0
    for _, c in published:
        w = c.get("walls") or {}
        cycle_walls.append(sum(v for v in w.values()
                               if isinstance(v, (int, float))))
        for key, v in (c.get("delta_counts") or {}).items():
            counts[key] = counts.get(key, 0) + int(v)
        ticks_total += int(c.get("n_ticks") or 0)
    total_chunks = max(1, sum(counts.values()))
    # appended ticks dirty every row's TAIL, so a healthy tick loop runs
    # all-warm (warm-started from the previous cycle's params) — the
    # churn signal is dirty+new (content revisions under the prefix),
    # not the absence of bitwise adoption
    dirty_fraction = round(((counts.get("dirty") or 0)
                            + (counts.get("new") or 0)) / total_chunks, 4)
    delta_on = bool((m.get("config") or {}).get("delta", True))
    # cadence: the slowest published cycle with 2x headroom is the
    # shortest tick interval this loop provably keeps up with
    min_tick_interval_s = round(2.0 * max(cycle_walls), 4)
    per_walk = None
    fit_mp = os.path.join(base, published[-1][0], "fit", "manifest.json")
    if os.path.exists(fit_mp):
        a = advise(load_manifest(fit_mp))
        if "error" not in a:
            per_walk = a["suggest"]
    chain = None
    if not delta_on:
        chain = ("delta chaining is OFF: every cycle refits the grown "
                 "panel cold — pass delta_from chaining (delta=True) so "
                 "appended ticks only recompute the warm tail")
    elif dirty_fraction > 0.5 and len(published) > 1:
        chain = ("delta chaining sees mostly dirty/new chunks: the panel "
                 "is churning (revised rows), not appending — consider "
                 "delta_warmstart=False (exact mode) or larger tick "
                 "batches")
    return {
        "tickloop": {
            "cycles_published": len(published),
            "cycles_seen": len(cycles),
            "n_rows": m.get("n_rows"),
            "ticks_ingested": ticks_total,
            "layout": m.get("layout"),
            "delta_enabled": delta_on,
            "cycle_wall_s_max": round(max(cycle_walls), 4),
            "cycle_wall_s_mean": round(sum(cycle_walls)
                                       / len(cycle_walls), 4),
            "delta_counts": counts,
            "dirty_fraction": dirty_fraction,
        },
        "suggest": {
            "min_tick_interval_s": min_tick_interval_s,
            "delta_from_chaining": chain,
            "per_walk": per_walk,
        },
    }


def _render_tickloop(root: str, a: dict) -> None:
    o, s = a["tickloop"], a["suggest"]
    print(f"tick loop {root}")
    print(f"  loop: {o['cycles_published']}/{o['cycles_seen']} cycles "
          f"published, {o['ticks_ingested']} ticks ingested over "
          f"{o['n_rows']} rows ({o['layout']} shards, "
          f"delta={'on' if o['delta_enabled'] else 'off'})")
    dc = o["delta_counts"]
    print(f"  refits: dirty fraction {o['dirty_fraction']} "
          f"({dc.get('adopted', 0)} adopted / {dc.get('warm', 0)} warm / "
          f"{dc.get('dirty', 0)} dirty / {dc.get('new', 0)} new chunks "
          "across published cycles)")
    print(f"  cycle wall: mean {o['cycle_wall_s_mean']}s, "
          f"max {o['cycle_wall_s_max']}s")
    print("  suggest for this loop's next life:")
    print(f"    min_tick_interval_s = {s['min_tick_interval_s']}  "
          "(slowest tick-to-publish cycle x2 headroom)")
    if s["delta_from_chaining"]:
        print(f"    delta_from chaining: {s['delta_from_chaining']}")
    else:
        print("    delta_from chaining: holding (appended ticks ride the "
              "warm tail; leave delta=True)")
    if s["per_walk"]:
        p = s["per_walk"]
        print(f"    per-cycle fit knobs: chunk_rows = {p.get('chunk_rows')}"
              f", chunk_budget_s = {p.get('chunk_budget_s')}, "
              f"pipeline_depth = {p.get('pipeline_depth')}")


def advise_backtest(root: str) -> dict:
    """Backtest-campaign advice (ISSUE 20): a rolling-origin campaign
    root (``backtest_manifest.json``) — the window-class wall split says
    whether the NEXT campaign of this config should run ``delta=True``.

    A campaign whose prior-compatible windows were adopted spent wall
    only on the genuinely new origins; a fresh campaign re-paid every
    window.  The advisor reads the per-window ``window_class`` tags and
    walls and prints the delta economy: adopted windows' recorded walls
    are what ``delta=True`` saves on an unchanged-prefix rerun.
    """
    mp = os.path.join(root, "backtest_manifest.json") \
        if os.path.isdir(root) else root
    try:
        with open(mp, "rb") as f:
            m = json.loads(f.read().decode())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        sys.exit(f"advise_budget: backtest manifest {mp} unreadable ({e})")
    windows = [w for w in m.get("windows") or []
               if w.get("status") == "committed"]
    if not windows:
        return {"error": "no committed windows to learn from",
                "backtest": {"campaign_hash": m.get("campaign_hash")}}
    by_class = {}
    for w in windows:
        cls = w.get("window_class") or (
            "warm" if w.get("warm_start") else "cold")
        ent = by_class.setdefault(cls, {"n": 0, "wall_s": 0.0})
        ent["n"] += 1
        ent["wall_s"] += float(w.get("wall_s") or 0.0)
    computed_wall = sum(v["wall_s"] for k, v in by_class.items()
                        if k != "adopted")
    adopted = by_class.get("adopted", {"n": 0, "wall_s": 0.0})
    d = m.get("delta") or {}
    return {
        "backtest": {
            "campaign_hash": m.get("campaign_hash"),
            "windows_committed": len(windows),
            "horizon": m.get("horizon"),
            "window_classes": {k: {"n": v["n"],
                                   "wall_s": round(v["wall_s"], 4)}
                               for k, v in sorted(by_class.items())},
            "computed_wall_s": round(computed_wall, 4),
            "delta": {"adopted": d.get("adopted"),
                      "recomputed": d.get("recomputed"),
                      "prior_n_time": d.get("prior_n_time")} if d else None,
        },
        "suggest": {
            # the campaign-level delta knob: an unchanged-prefix rerun
            # (appended ticks, extra origins) re-pays computed_wall_s
            # unless it adopts — this manifest is the prior to adopt from
            "delta": True,
            "adopted_windows": adopted["n"],
            "delta_reason": (
                f"{adopted['n']} window(s) adopted free (their recorded "
                f"walls total ~{round(adopted['wall_s'], 2)}s a fresh "
                "campaign would re-pay)"
                if adopted["n"] else
                f"no adoptions yet: a delta=True rerun on a grown panel "
                f"adopts every unchanged window and skips up to "
                f"~{round(computed_wall, 2)}s of window wall"),
        },
    }


def _render_backtest(root: str, a: dict) -> None:
    o, s = a["backtest"], a["suggest"]
    print(f"backtest campaign {root}")
    print(f"  campaign {o['campaign_hash']}  horizon {o['horizon']}  "
          f"{o['windows_committed']} committed window(s)")
    for cls, v in o["window_classes"].items():
        print(f"    {cls}: {v['n']} window(s), wall {v['wall_s']}s")
    if o["delta"]:
        d = o["delta"]
        print(f"  delta campaign: {d['adopted']} adopted / "
              f"{d['recomputed']} recomputed from a prior at "
              f"n_time {d['prior_n_time']}")
    print("  suggest for the next campaign of this config:")
    print(f"    delta = True  ({s['delta_reason']})")


def _device_budget_bytes():
    """The local device allocator's budget (``memory_stats()['bytes_limit']``)
    when the backend reports one; None on CPU-only hosts (the advice then
    leans on what the recorded run proved instead of a budget guess)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        return int(limit) if limit else None
    except Exception:  # noqa: BLE001 - advisory tool, never fail on probe
        return None


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="journal directory or manifest path")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable advice instead of the table")
    ap.add_argument("--serving", action="store_true",
                    help="treat PATH as a serving.FitServer checkpoint "
                         "root (server.json + per-batch journals); "
                         "auto-detected when server.json is present")
    args = ap.parse_args()
    # a chaos/soak root (ISSUE 17) is identified by its scenario record
    if ((os.path.isdir(args.path)
         and os.path.exists(os.path.join(args.path, "chaos_manifest.json")))
            or args.path.endswith("chaos_manifest.json")):
        a = advise_chaos(args.path)
        if args.json:
            print(json.dumps(a, indent=1, sort_keys=True))
        else:
            _render_chaos(args.path, a)
        return
    # a serving root (ISSUE 12) is a server.json plus one journal per
    # micro-batch under batches/<id>/journal
    if args.serving or (
            os.path.isdir(args.path)
            and os.path.exists(os.path.join(args.path, "server.json"))):
        a = advise_serving(args.path)
        if args.json:
            print(json.dumps(a, indent=1, sort_keys=True))
            return
        if "error" in a:
            prof = a.get("profiles")
            if not prof or "error" in prof:
                sys.exit(f"advise_budget: {a['error']}")
            # a warm root whose traffic was all auto-fit submits: no
            # batch journals, but the profile evidence still advises
            print(f"serving root {args.path}  ({a['error']})")
            _render_profiles(prof)
            return
        _render_serving(args.path, a)
        return
    # a tick-loop root (ISSUE 20) is identified by its loop manifest
    if ((os.path.isdir(args.path)
         and os.path.exists(os.path.join(args.path, "tickloop.json")))
            or args.path.endswith("tickloop.json")):
        a = advise_tickloop(args.path)
        if args.json:
            print(json.dumps(a, indent=1, sort_keys=True))
        elif "error" in a:
            sys.exit(f"advise_budget: {a['error']}")
        else:
            _render_tickloop(args.path, a)
        return
    # a backtest campaign root (ISSUE 14/20): per-window fit journals
    # under a campaign-level backtest_manifest.json
    if ((os.path.isdir(args.path)
         and os.path.exists(os.path.join(args.path,
                                         "backtest_manifest.json")))
            or args.path.endswith("backtest_manifest.json")):
        a = advise_backtest(args.path)
        if args.json:
            print(json.dumps(a, indent=1, sort_keys=True))
        elif "error" in a:
            sys.exit(f"advise_budget: {a['error']}")
        else:
            _render_backtest(args.path, a)
        return
    # an auto-fit search root (ISSUE 9) has no root manifest.json — the
    # grid-level auto_manifest.json plus per-order journals stand in
    if os.path.isdir(args.path) and \
            os.path.exists(os.path.join(args.path, "auto_manifest.json")) \
            and not os.path.exists(os.path.join(args.path, "manifest.json")):
        a = advise_auto(args.path)
        if args.json:
            print(json.dumps(a, indent=1, sort_keys=True))
        else:
            _render_auto(args.path, a)
        return
    m = load_manifest(args.path)
    a = advise(m)
    if args.json:
        print(json.dumps(a, indent=1, sort_keys=True))
        return
    if "error" in a:
        sys.exit(f"advise_budget: {a['error']} (config {a['config_hash']})")
    o, s = a["observed"], a["suggest"]
    print(f"journal {args.path}")
    print(f"  config {a['config_hash']}  panel {a['panel_fingerprint']}")
    print(f"  observed: {o['chunks_committed']} committed / "
          f"{o['chunks_timeout']} TIMEOUT chunks; "
          f"chunk_rows {o['chunk_rows_requested']} requested -> "
          f"{o['chunk_rows_sustained']} sustained"
          + ("  (OOM backoff engaged)" if o["oom_backoff_engaged"] else ""))
    if o["chunk_wall_s_max"] is not None:
        print(f"  walls: chunk max {o['chunk_wall_s_max']}s "
              f"p90 {o['chunk_wall_s_p90']}s"
              + (f"; execute mean {o['execute_wall_s_mean']}s"
                 if o["execute_wall_s_mean"] is not None else "")
              + (f"; compile max {o['compile_wall_s_max']}s"
                 if o["compile_wall_s_max"] is not None else ""))
    if o["commit_s_mean"] is not None:
        print(f"  journal commit: mean {o['commit_s_mean']}s "
              f"max {o['commit_s_max']}s")
    if o["staging_wall_s_mean"] is not None:
        print(f"  input staging: mean {o['staging_wall_s_mean']}s/slice"
              + (f", overlap {o['input_overlap_efficiency']}"
                 if o["input_overlap_efficiency"] is not None else ""))
    if o["source_kind"] is not None:
        sz = (f", panel {o['panel_bytes'] / 1e9:.3f} GB"
              if o["panel_bytes"] else "")
        print(f"  chunk source: {o['source_kind']}{sz}")
    if o["staging_pool"] is not None:
        sp = o["staging_pool"]
        print("  staging pool: "
              + (f"hit rate {sp['pool_hit_rate']}"
                 if sp["pool_hit_rate"] is not None else "no reuse data")
              + (f", H2D wall {sp['h2d_wall_s']}s" if sp["h2d_wall_s"]
                 is not None else "")
              + (f", peak live device bytes {sp['peak_live_device_bytes']}"
                 if sp["peak_live_device_bytes"] is not None else ""))
    if o["shards"] is not None:
        so = o["shards"]
        print(f"  sharded lanes: {so['lanes_with_work']}/{so['n_shards']} "
              "carried work"
              + (f"; wall balance max/mean {so['shard_wall_balance']}"
                 if so["shard_wall_balance"] is not None else ""))
    if o.get("delta") is not None:
        do = o["delta"]
        dc = do["counts"]
        print(f"  delta walk: dirty fraction {do['dirty_fraction']} "
              f"({dc.get('adopted', 0)} adopted / {dc.get('warm', 0)} warm"
              f" / {dc.get('dirty', 0)} dirty / {dc.get('new', 0)} new; "
              f"warmstart={do['warmstart']}) from {do['from']}")
    if o.get("rebalance") is not None:
        ro = o["rebalance"]
        print(f"  elastic: {ro['steals']} steals, "
              f"{ro['reassigned_chunks']} chunks reassigned, "
              f"{ro['lane_retries_used']} lane retries used")
        for q in ro["quarantine_causes"]:
            print(f"    quarantined shard {q['shard_id']} after "
                  f"{q['retries']} retries: {q['cause']}")
    print("  suggest for the next run of this config hash:")
    print(f"    chunk_rows     = {s['chunk_rows']}")
    print(f"    chunk_budget_s = {s['chunk_budget_s']}")
    print(f"    job_budget_s   = {s['job_budget_s']}")
    print(f"    pipeline_depth = {s['pipeline_depth']}")
    print(f"    prefetch_depth = {s['prefetch_depth']}")
    if s["host_resident"] is not None:
        print(f"    host_resident  = {s['host_resident']}  "
              f"({s['host_resident_reason']}; staging_pool_buffers = "
              f"{s['staging_pool_buffers']})")
    if s["align_mode"] is not None:
        print(f"    align_mode     = {s['align_mode']!r}")
    if s.get("forecast") is not None:
        fo, fs = o["forecast"], s["forecast"]
        print(f"    horizon-aware chunk_rows: this forecast walk proved "
              f"rows x working-set <= {fs['chunk_rows_working_set_floats']}"
              f" floats at horizon {fs['horizon']}"
              + (f" ({fo['n_samples']} interval samples/row)"
                 if fo["intervals"] else "")
              + f"; at 2x the horizon use chunk_rows <= "
                f"{fs['chunk_rows_at_2x_horizon']}")
    if s.get("delta_from") is not None:
        print(f"    delta_from     = {args.path}  ({s['delta_from']})")
    print(f"    shards         = {s['shards']}  (shard=True/mesh=; clamped "
          "to the mesh's series devices at runtime)")
    if s["shards"] > 1:
        print(f"    chunk_rows (per-shard walk) = {s['chunk_rows_per_shard']}"
              "  (>= 2 chunks per lane so commits/staging overlap)")
        print(f"    lane_retries   = {s['lane_retries']}  (failed-lane "
              "retries before quarantine)")
        print(f"    rebalance_threshold = {s['rebalance_threshold']}  "
              "(steal from a lane once its projected remaining wall "
              "exceeds this many mean chunk walls)")


if __name__ == "__main__":
    main()
