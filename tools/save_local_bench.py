#!/usr/bin/env python
"""Capture a local bench run as ``BENCH_LOCAL.json``.

Pipe a full ``bench.py`` run through this to record its output in the same
``{"tail": ...}`` shape as the driver's ``BENCH_r*.json`` artifacts, so
``tools/gen_readme_perf.py`` can regenerate the README table from
current-code numbers between driver rounds (provenance is labeled in the
generated table):

    python bench.py 2>&1 | python tools/save_local_bench.py
"""

from __future__ import annotations

import json
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def main():
    text = sys.stdin.read()
    sys.stdout.write(text)  # pass through for the terminal
    # record the actual platform so the README provenance cannot claim TPU
    # numbers for a CPU run
    on_tpu = bool(re.search(r"platform=(tpu|axon)", text))
    out = ROOT / "BENCH_LOCAL.json"
    out.write_text(json.dumps({
        "provenance": "local builder run (not a driver artifact)",
        "platform": "tpu" if on_tpu else "cpu-or-unknown",
        "cmd": "python bench.py",
        "tail": text[-8192:],
    }, indent=2) + "\n")
    print(f"[save_local_bench] wrote {out.name} (platform="
          f"{'tpu' if on_tpu else 'cpu-or-unknown'})", file=sys.stderr)


if __name__ == "__main__":
    main()
