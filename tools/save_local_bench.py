#!/usr/bin/env python
"""Capture a local bench run as ``BENCH_LOCAL.json``.

Pipe a full ``bench.py`` run through this to record its output in the same
``{"tail": ...}`` shape as the driver's ``BENCH_r*.json`` artifacts, so
``tools/gen_readme_perf.py`` can regenerate the README table from
current-code numbers between driver rounds (provenance is labeled in the
generated table):

    python bench.py 2>&1 | python tools/save_local_bench.py

The artifact records its own run metadata — timestamp, git commit, and the
newest driver round present at run time — because file mtimes are not a
staleness signal (a fresh checkout gives every file one mtime; ADVICE r5):
``gen_readme_perf.py`` compares the RECORDED metadata, never ``st_mtime``.
"""

from __future__ import annotations

import json
import pathlib
import re
import subprocess
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent


def git_head() -> str | None:
    """Current commit hash, or None outside a usable git checkout."""
    try:
        out = subprocess.run(
            ["git", "-C", str(ROOT), "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except (OSError, subprocess.SubprocessError):
        return None


def newest_driver_round() -> int:
    """Round number of the newest ``BENCH_r*.json`` present (0 if none)."""
    rounds = [
        int(m.group(1))
        for p in ROOT.glob("BENCH_r*.json")
        if (m := re.match(r"BENCH_r(\d+)\.json$", p.name))
    ]
    return max(rounds, default=0)


def main():
    text = sys.stdin.read()
    sys.stdout.write(text)  # pass through for the terminal
    # record the actual platform so the README provenance cannot claim TPU
    # numbers for a CPU run
    on_tpu = bool(re.search(r"platform=(tpu|axon)", text))
    out = ROOT / "BENCH_LOCAL.json"
    now = time.time()
    out.write_text(json.dumps({
        "provenance": "local builder run (not a driver artifact)",
        "platform": "tpu" if on_tpu else "cpu-or-unknown",
        "cmd": "python bench.py",
        "run_at": now,
        "run_at_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(now)),
        "git_commit": git_head(),
        "newest_driver_round": newest_driver_round(),
        "tail": text[-8192:],
    }, indent=2) + "\n")
    print(f"[save_local_bench] wrote {out.name} (platform="
          f"{'tpu' if on_tpu else 'cpu-or-unknown'})", file=sys.stderr)


if __name__ == "__main__":
    main()
