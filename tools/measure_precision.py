"""Quantify f32-vs-f64 fit drift on the benchmark workload (SURVEY.md §7).

The reference's Commons-Math numerics are f64; TPU f64 is emulated and slow,
so the production fit path runs f32.  This script measures what that costs:
it fits the same synthetic panels at f32 (the production path, fused Pallas
kernels on TPU) and at f64 (the oracle: scan backend under
``jax_enable_x64``), then reports parameter-error quantiles against BOTH the
f64 estimate and the GENERATING truth.  The interesting comparison is drift
vs estimation error: f32 rounding only matters if it is not dwarfed by the
statistical error of the estimator itself.

The f64 oracle runs in a SUBPROCESS: ``jax_enable_x64`` is a process-global
switch, and x64 tracing of the f32 Pallas kernels trips a jax
dtype-promotion recursion — two processes keep each world clean.

Writes a markdown table to stdout; paste into PRECISION.md.

Run: ``python tools/measure_precision.py [--batch 1024] [--t 1000]``
"""

import argparse
import os
import subprocess
import sys
import tempfile

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


ARGARCH_TRUTH = np.array([0.5, 0.5, 0.05, 0.12, 0.8])  # c, phi, omega, a, b
MODELS = ("arima", "garch", "hw", "hwm", "argarch")


def _gen(batch, t):
    from bench import gen_arima_panel, gen_garch_returns, gen_seasonal_panel

    # AR(1) over GARCH(1,1) innovations at the generating truth (the ARGARCH
    # data-generating process, numpy so both precisions share one panel)
    r = gen_garch_returns(batch, t, seed=3, omega=ARGARCH_TRUTH[2],
                          alpha=ARGARCH_TRUTH[3], beta=ARGARCH_TRUTH[4])
    c, phi = ARGARCH_TRUTH[:2]
    y = np.empty_like(r)
    y[:, 0] = c / (1.0 - phi) + r[:, 0]
    for i in range(1, t):
        y[:, i] = c + phi * y[:, i - 1] + r[:, i]

    return {
        "arima": gen_arima_panel(batch, t, seed=0).astype(np.float32),
        "garch": gen_garch_returns(batch, t, seed=1),
        "hw": gen_seasonal_panel(batch, min(t, 960), 24, seed=2),
        # multiplicative HW needs a positive panel (level >> seasonal swing),
        # same construction the bench parity gate uses
        "hwm": gen_seasonal_panel(batch, min(t, 960), 24, seed=4) + 25.0,
        "argarch": y.astype(np.float32),
    }


def _fit_all(data, backend_hint, x64):
    import jax

    if x64:
        jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from spark_timeseries_tpu.models import arima, garch
    from spark_timeseries_tpu.models import holtwinters as hw

    dtype = jnp.float64 if x64 else jnp.float32
    backend = "scan" if x64 else backend_hint
    out = {}
    r = arima.fit(jnp.asarray(data["arima"], dtype), (1, 1, 1), backend=backend)
    out["arima"] = (np.asarray(r.params), np.asarray(r.converged))
    r = garch.fit(jnp.asarray(data["garch"], dtype), backend=backend)
    out["garch"] = (np.asarray(r.params), np.asarray(r.converged))
    r = hw.fit(jnp.asarray(data["hw"], dtype), 24, "additive", backend=backend)
    out["hw"] = (np.asarray(r.params), np.asarray(r.converged))
    r = hw.fit(jnp.asarray(data["hwm"], dtype), 24, "multiplicative",
               backend=backend)
    out["hwm"] = (np.asarray(r.params), np.asarray(r.converged))
    r = garch.fit_argarch(jnp.asarray(data["argarch"], dtype), backend=backend)
    out["argarch"] = (np.asarray(r.params), np.asarray(r.converged))
    return out


def _worker(args):
    # the oracle must run on CPU: TPU has no f64 LU path for the batched
    # OLS solves, and f64 is emulated there anyway
    import jax

    jax.config.update("jax_platforms", "cpu")
    data = dict(np.load(args.data))
    out = _fit_all(data, "scan", x64=True)
    np.savez(args.out, **{f"{k}_{i}": v for k, (p, c) in out.items()
                          for i, v in (("p", p), ("c", c))})


def _q(a):
    a = a[np.isfinite(a)]
    if not a.size:
        return ("n/a",) * 3
    return tuple(f"{v:.2e}" for v in np.percentile(a, [50, 95, 99]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--t", type=int, default=1000)
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--data", help=argparse.SUPPRESS)
    ap.add_argument("--out", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args._worker:
        return _worker(args)

    data = _gen(args.batch, args.t)

    with tempfile.TemporaryDirectory() as td:
        dpath = os.path.join(td, "data.npz")
        opath = os.path.join(td, "f64.npz")
        np.savez(dpath, **data)
        # f64 oracle first, in its own x64 process
        subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--_worker",
             "--data", dpath, "--out", opath],
            check=True, cwd=_ROOT,
        )
        z = np.load(opath)
        f64 = {k: (z[f"{k}_p"], z[f"{k}_c"]) for k in MODELS}

    import jax

    platform = jax.devices()[0].platform
    f32 = _fit_all(data, "auto", x64=False)

    truth = {
        "arima": np.array([0.0, 0.6, 0.3]),
        "garch": np.array([0.05, 0.12, 0.8]),
        "hw": None,  # no single generating truth for (alpha, beta, gamma)
        "hwm": None,
        "argarch": ARGARCH_TRUTH,
    }
    names = {
        "arima": "ARIMA(1,1,1)",
        "garch": "GARCH(1,1)",
        "hw": "HoltWinters additive (vs f64 only)",
        "hwm": "HoltWinters multiplicative (vs f64 only)",
        "argarch": "AR(1)+GARCH(1,1)",
    }
    print(f"platform: {platform}; batch {args.batch} x {args.t}; "
          "f32 = production path (pallas on TPU), f64 = scan oracle under x64")
    print()
    print("| model | drift p50 | drift p95 | drift p99 | est-err p50 | "
          "est-err p95 | conv f32/f64 |")
    print("|---|---|---|---|---|---|---|")
    for k in MODELS:
        p32, c32 = f32[k]
        p64, c64 = f64[k]
        both = c32 & c64
        drift = np.abs(p32.astype(np.float64) - p64)[both].max(axis=1)
        d50, d95, d99 = _q(drift)
        if truth[k] is not None:
            est = np.abs(p64 - truth[k][None, :])[both].max(axis=1)
            e50, e95, _ = _q(est)
        else:
            e50 = e95 = "n/a"
        print(f"| {names[k]} | {d50} | {d95} | {d99} | {e50} | {e95} | "
              f"{c32.mean():.3f}/{c64.mean():.3f} |")


if __name__ == "__main__":
    main()
