"""Quantify f32-vs-f64 fit drift on the benchmark workload (SURVEY.md §7).

The reference's Commons-Math numerics are f64; TPU f64 is emulated and slow,
so the production fit path runs f32.  This script measures what that costs:
it fits the same synthetic panels at f32 (scan and, on TPU, pallas backends)
and at f64 (scan, the oracle — tests run the suite under ``jax_enable_x64``),
then reports parameter-error quantiles against BOTH the f64 estimate and the
GENERATING truth.  The interesting comparison is drift vs estimation error:
f32 rounding only matters if it is not dwarfed by the statistical error of
the estimator itself.

Writes a markdown table to stdout; paste into PRECISION.md.

Run: ``python tools/measure_precision.py [--batch 4096] [--t 1000]``
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _q(a):
    a = a[np.isfinite(a)]
    if not a.size:
        return "n/a", "n/a", "n/a"
    return tuple(f"{v:.2e}" for v in np.percentile(a, [50, 95, 99]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--t", type=int, default=1000)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)  # make f64 REAL f64 everywhere

    import jax.numpy as jnp

    from spark_timeseries_tpu.models import arima, garch
    from spark_timeseries_tpu.models import holtwinters as hw
    from spark_timeseries_tpu.ops import pallas_kernels as pk

    from bench import gen_arima_panel, gen_garch_returns, gen_seasonal_panel

    b, t = args.batch, args.t
    platform = jax.devices()[0].platform
    on_tpu = platform in ("tpu", "axon")
    rows = []

    def report(name, true_vec, f32_params, f64_params, conv32, conv64):
        p32 = np.asarray(f32_params, np.float64)
        p64 = np.asarray(f64_params, np.float64)
        both = np.asarray(conv32) & np.asarray(conv64)
        drift = np.abs(p32 - p64)[both].max(axis=1)
        est_err = np.abs(p64 - true_vec[None, :])[both].max(axis=1)
        d50, d95, d99 = _q(drift)
        e50, e95, e99 = _q(est_err)
        rows.append(
            f"| {name} | {d50} | {d95} | {d99} | {e50} | {e95} | "
            f"{float(np.mean(conv32)):.3f}/{float(np.mean(conv64)):.3f} |"
        )

    # --- ARIMA(1,1,1), the headline workload --------------------------------
    y32 = jnp.asarray(gen_arima_panel(b, t, seed=0), jnp.float32)
    y64 = jnp.asarray(np.asarray(y32), jnp.float64)
    backend32 = "pallas" if pk.supported(jnp.float32, t - 1) else "scan"
    r32 = arima.fit(y32, (1, 1, 1), backend=backend32)
    r64 = arima.fit(y64, (1, 1, 1), backend="scan")
    report(f"ARIMA(1,1,1) [{backend32}]", np.array([0.0, 0.6, 0.3]),
           r32.params, r64.params, r32.converged, r64.converged)

    # --- GARCH(1,1) ---------------------------------------------------------
    r_ret = gen_garch_returns(b, t, seed=1)
    g32 = garch.fit(jnp.asarray(r_ret, jnp.float32))
    g64 = garch.fit(jnp.asarray(r_ret, jnp.float64), backend="scan")
    report("GARCH(1,1)", np.array([0.05, 0.12, 0.8]),
           g32.params, g64.params, g32.converged, g64.converged)

    # --- Holt-Winters additive ---------------------------------------------
    ys = gen_seasonal_panel(b, min(t, 960), 24, seed=2)
    h32 = hw.fit(jnp.asarray(ys, jnp.float32), 24, "additive")
    h64 = hw.fit(jnp.asarray(ys, jnp.float64), 24, "additive", backend="scan")
    # no single generating truth for (alpha, beta, gamma); use the f64 fit
    report("HoltWinters add. (vs f64 only)", np.full(3, np.nan),
           h32.params, h64.params, h32.converged, h64.converged)

    print(f"platform: {platform} (f32 backend auto = "
          f"{'pallas' if on_tpu else 'scan'}); batch {b} x {t}")
    print()
    print("| model | drift p50 | drift p95 | drift p99 | est-err p50 | "
          "est-err p95 | conv f32/f64 |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        print(r)


if __name__ == "__main__":
    main()
