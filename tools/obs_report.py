#!/usr/bin/env python
"""Render (or validate) a telemetry JSONL event log from the obs plane.

A fit run with ``obs.enable("run.jsonl")`` streams every span and event to
a JSONL file (schema: ``spark_timeseries_tpu.obs.recorder``).  This tool
answers the operator questions from that file alone — where did the wall
clock go (compile vs execute, chunk by chunk), which ladder rungs fired,
how long did journal commits take, what did memory peak at:

    python tools/obs_report.py RUN.jsonl              # timeline + metrics
    python tools/obs_report.py RUN.jsonl --json       # machine-readable
    python tools/obs_report.py RUN.jsonl --check \\
        [--manifest CKPT_DIR]                         # CI schema gate

``--check`` validates every line against the event schema (and, with
``--manifest``, the journal manifest's embedded ``telemetry`` block:
per-chunk span times present, counters present, peak memory non-null) and
exits 0/1 — the ci.sh telemetry smoke runs exactly this.

Sharded walks (ISSUE 6): a merged job manifest carries a ``shards`` block
and shard-tagged chunk entries/telemetry rows; ``--check --manifest``
validates that block (contiguous spans, in-range shard ids, shard-rooted
npz paths), and the rendered timeline splits into ONE LANE PER SHARD so
the eight concurrent walks read as eight rows, not one interleaved blur.

Auto-fit searches (ISSUE 9): every per-order walk tags its spans/events
with a ``grid`` coordinate, and the timeline splits into ONE LANE PER
ORDER; ``--check --manifest`` pointed at the search root validates the
``auto_manifest.json`` block (orders, stage-2 spend, selection counts)
and recurses into every per-order journal, and a per-order manifest's
``extra.auto_fit`` block is checked for grid coherence.

Fleets (ISSUE 18): every process in a serving fleet — N replicas plus
the storming client — streams to its own ``obs_<name>.jsonl`` at the
fleet root.  ``--fleet ROOT`` merges them into one view: per-process
lanes, elections / step-downs / degradation transitions as
annotations, chaos-manifest injections joined to their observed
consequences (injection -> victim silent -> survivor elected ->
takeover latency).  ``--trace REQUEST_ID`` renders one request's
cross-process causal timeline from its deterministic trace ids (and,
with ``--check``, GATES its reconstruction: a submit origin, a server
admission, exactly one ``client.result`` terminal, more than one
process).  ``--slo`` summarizes availability, client-observed latency
percentiles, and failover recovery.  Merged ordering trusts same-host
wall clocks; the client's ``*.clock.json`` sidecars carry per-endpoint
monotonic-clock offsets for the cross-host story.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

KINDS = ("meta", "span", "event", "metrics")
CHUNK_PHASES = ("compile+execute", "execute", "resumed", "timeout")
MEM_SOURCES = ("device", "host_rss")


def load_events(path: str):
    """Parse the JSONL stream; returns (events, errors)."""
    events, errors = [], []
    try:
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError as e:
                    errors.append(f"line {i}: does not parse ({e})")
                    continue
                if not isinstance(ev, dict):
                    errors.append(f"line {i}: not an object")
                    continue
                events.append((i, ev))
    except OSError as e:
        errors.append(f"cannot read {path}: {e}")
    return events, errors


_TRACE_HEX = set("0123456789abcdef")


def _trace_field_ok(v) -> bool:
    return (isinstance(v, str) and len(v) == 16
            and all(c in _TRACE_HEX for c in v))


def validate_trace_stamp(i: int, ev: dict, errors: list) -> None:
    """Schema v2 (ISSUE 18): a span/event line MAY carry a top-level
    ``trace`` object — absent is fine (tracing off, schema-v1 streams),
    present-but-malformed fails the gate."""
    if "trace" not in ev:
        return
    tr = ev["trace"]
    if not isinstance(tr, dict):
        errors.append(f"line {i}: trace is not an object: {tr!r}")
        return
    for f in ("trace_id", "span_id"):
        if not _trace_field_ok(tr.get(f)):
            errors.append(f"line {i}: trace.{f} is not 16 lowercase hex "
                          f"chars: {tr.get(f)!r}")
    if "parent_id" in tr and not _trace_field_ok(tr["parent_id"]):
        errors.append(f"line {i}: trace.parent_id invalid: "
                      f"{tr['parent_id']!r}")
    extra = set(tr) - {"trace_id", "span_id", "parent_id"}
    if extra:
        errors.append(f"line {i}: trace carries unknown keys "
                      f"{sorted(extra)}")


def validate_events(events, errors) -> list:
    """Schema check (see obs.recorder docstring); appends to ``errors``."""
    if not events and not errors:
        errors.append("no events in stream")
        return errors
    if events and events[0][1].get("kind") != "meta":
        errors.append("first event is not kind=meta")
    for i, ev in events:
        kind = ev.get("kind")
        if kind not in KINDS:
            errors.append(f"line {i}: unknown kind {kind!r}")
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            errors.append(f"line {i}: missing/non-numeric ts")
        if kind in ("span", "event"):
            validate_trace_stamp(i, ev, errors)
        if kind == "meta":
            if not ev.get("run_id") or not isinstance(ev.get("schema"), int):
                errors.append(f"line {i}: meta missing run_id/schema")
        elif kind == "span":
            if not isinstance(ev.get("name"), str):
                errors.append(f"line {i}: span missing name")
            for f in ("wall_s", "process_s"):
                v = ev.get(f)
                if not isinstance(v, (int, float)) or v < 0:
                    errors.append(f"line {i}: span {f} invalid: {v!r}")
            if not isinstance(ev.get("depth"), int) or ev["depth"] < 0:
                errors.append(f"line {i}: span depth invalid")
        elif kind == "event":
            if not isinstance(ev.get("name"), str):
                errors.append(f"line {i}: event missing name")
        elif kind == "metrics":
            if not isinstance(ev.get("counters"), dict):
                errors.append(f"line {i}: metrics missing counters dict")
    return errors


DEGRADATION_EVENT_FIELDS = {
    # ISSUE 17 degradation-ladder telemetry: event name -> required
    # fields.  A renamed or stripped field here silently breaks the
    # chaos post-mortem story, so the shapes are pinned.
    "fleet.step_down": ("owner", "reason"),
    "fleet.elected": ("owner", "token"),
    "fleet.fenced": ("owner", "token"),
    "fleet.standby_read": ("owner",),
    "fleet.torn_result": ("owner", "file"),
    "client.endpoint_circuit_open": ("endpoint",),
    "client.endpoint_recovered": ("endpoint",),
    "client.primary_learned": ("endpoint",),
    "client.hedge": ("req_id",),
    "transport.auth_failed": ("conn",),
    "server.storage_refusal": ("req_id",),
    "server.torn_result": ("path",),
}

FLEET_STATE_CODES = (0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 6.0)


def validate_degradation(events) -> list:
    """Validate the degradation-ladder telemetry (ISSUE 17): the stream's
    final metrics snapshot must publish the ``fleet.state`` gauge with a
    value from the ladder's code table (full=0 … stopped=6), and every
    degradation event present must carry its pinned fields — the chaos
    soak and its ``advise_budget`` post-mortem read exactly these."""
    errors = []
    last_metrics = None
    for i, ev in events:
        if ev.get("kind") == "metrics":
            last_metrics = (i, ev)
        if ev.get("kind") != "event":
            continue
        need = DEGRADATION_EVENT_FIELDS.get(ev.get("name"))
        if not need:
            continue
        attrs = ev.get("attrs") or {}
        for f in need:
            if attrs.get(f) in (None, ""):
                errors.append(f"line {i}: degradation event "
                              f"{ev['name']} missing field {f!r}")
    if last_metrics is None:
        errors.append("degradation check: no metrics snapshot in stream")
        return errors
    i, m = last_metrics
    gauges = m.get("gauges") or {}
    state = gauges.get("fleet.state")
    if state is None:
        errors.append(f"line {i}: final metrics snapshot has no "
                      "fleet.state gauge (the degradation ladder is "
                      "not being published)")
    elif float(state) not in FLEET_STATE_CODES:
        errors.append(f"line {i}: fleet.state gauge {state!r} is not a "
                      f"ladder code {FLEET_STATE_CODES}")
    return errors


def validate_manifest_telemetry(ckpt_dir: str) -> list:
    """Validate the journal manifest's embedded ``telemetry`` block.

    An auto-fit search root (ISSUE 9: ``auto_manifest.json`` + per-order
    ``grid_*`` journals, no root ``manifest.json``) dispatches to
    :func:`validate_auto_manifest` instead, which checks the grid-level
    block and recurses into every per-order journal.
    """
    errors = []
    path = ckpt_dir
    if os.path.isdir(path):
        if (os.path.exists(os.path.join(path, "auto_manifest.json"))
                and not os.path.exists(os.path.join(path, "manifest.json"))):
            return validate_auto_manifest(path)
        if (os.path.exists(os.path.join(path, "backtest_manifest.json"))
                and not os.path.exists(os.path.join(path, "manifest.json"))):
            # a backtest campaign root (ISSUE 14): campaign manifest +
            # per-window fit journals, no root manifest.json
            return validate_backtest_manifest(path)
        if (os.path.exists(os.path.join(path, "tickloop.json"))
                and not os.path.exists(os.path.join(path, "manifest.json"))):
            # a tick-loop root (ISSUE 20): loop manifest + per-cycle
            # dirs, each holding its own fit/forecast journals + sink
            return validate_tickloop_root(path)
        path = os.path.join(path, "manifest.json")
    try:
        with open(path, "rb") as f:
            m = json.loads(f.read().decode())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        return [f"manifest {path}: unreadable ({e})"]
    t = m.get("telemetry")
    if not isinstance(t, dict):
        return [f"manifest {path}: no telemetry block"]
    chunks = t.get("chunks")
    if not isinstance(chunks, list) or not chunks:
        errors.append("telemetry.chunks missing/empty")
    else:
        for c in chunks:
            phase = c.get("phase")
            if phase not in CHUNK_PHASES:
                errors.append(f"chunk {c.get('lo')}: bad phase {phase!r}")
            if phase in ("compile+execute", "execute") and not isinstance(
                    c.get("wall_s"), (int, float)):
                errors.append(f"chunk {c.get('lo')}: missing wall_s")
    if not isinstance(t.get("counters"), dict):
        errors.append("telemetry.counters missing")
    pm = t.get("peak_memory") or {}
    if not isinstance(pm.get("bytes"), int) or pm["bytes"] <= 0:
        errors.append(f"telemetry.peak_memory.bytes invalid: "
                      f"{pm.get('bytes')!r}")
    if pm.get("source") not in MEM_SOURCES:
        errors.append(f"telemetry.peak_memory.source invalid: "
                      f"{pm.get('source')!r}")
    # input-staging block (ISSUE 5): optional — serial/unprefetched walks
    # journal none — but when present it must be well-formed, since
    # tools/advise_budget.py derives prefetch_depth from it.  A
    # host-resident walk (ISSUE 7) adds a staging_pool sub-block (and may
    # journal ONLY that when the walk ran serially): pool reuse counts,
    # H2D wall, and the donated-buffer peak must be present and sane —
    # the oversubscribed CI smoke gates on exactly this.
    st = t.get("input_staging")
    if st is not None:
        if not isinstance(st, dict):
            errors.append(f"telemetry.input_staging not a dict: {st!r}")
        else:
            for k in ("chunks_staged", "staged_hits", "staged_misses"):
                if k in st and not isinstance(st.get(k), int):
                    errors.append(f"telemetry.input_staging.{k} invalid: "
                                  f"{st.get(k)!r}")
            for k in ("staging_wall_s", "hidden_staging_s"):
                if k in st and not isinstance(st.get(k), (int, float)):
                    errors.append(f"telemetry.input_staging.{k} invalid: "
                                  f"{st.get(k)!r}")
            if not any(k in st for k in ("chunks_staged", "staging_pool")):
                errors.append("telemetry.input_staging carries neither "
                              "prefetch nor staging_pool accounting")
            pool = st.get("staging_pool")
            if pool is not None:
                if not isinstance(pool, dict):
                    errors.append("telemetry.input_staging.staging_pool "
                                  f"not a dict: {pool!r}")
                else:
                    for k in ("pool_hits", "pool_misses", "h2d_copies",
                              "h2d_bytes", "peak_live_device_bytes",
                              "peak_host_bytes"):
                        if not isinstance(pool.get(k), int) or pool[k] < 0:
                            errors.append(
                                f"telemetry.input_staging.staging_pool.{k} "
                                f"invalid: {pool.get(k)!r}")
                    if not isinstance(pool.get("h2d_wall_s"), (int, float)):
                        errors.append(
                            "telemetry.input_staging.staging_pool."
                            f"h2d_wall_s invalid: {pool.get('h2d_wall_s')!r}")
    errors += validate_manifest_shards(m, path)
    errors += validate_manifest_auto_extra(m, path)
    errors += validate_manifest_delta(m, path)
    errors += validate_manifest_sink(m, path)
    return errors


DELTA_CLASSES = ("adopted", "warm", "dirty", "new")


def validate_manifest_delta(m: dict, path: str) -> list:
    """Validate a delta walk's ``extra.delta`` provenance block
    (ISSUE 15).  Manifests without the block (ordinary walks) pass
    untouched; a walk that claims a delta plan must carry a coherent
    one: the classified chunk grid covers the panel exactly, the class
    counts tally, and every adopted chunk entry names the manifest its
    bytes were spliced from."""
    d = (m.get("extra") or {}).get("delta")
    if d is None:
        return []
    errors = []
    counts = d.get("counts")
    if not isinstance(counts, dict) or \
            set(counts) != set(DELTA_CLASSES):
        errors.append(f"extra.delta.counts malformed: {counts!r}")
        counts = {}
    grid = d.get("chunks")
    if not isinstance(grid, list) or not grid:
        errors.append("extra.delta.chunks missing/empty")
        grid = []
    tallies = {k: 0 for k in DELTA_CLASSES}
    pos = 0
    for ent in grid:
        if (not isinstance(ent, (list, tuple)) or len(ent) != 3
                or ent[2] not in DELTA_CLASSES):
            errors.append(f"extra.delta.chunks entry malformed: {ent!r}")
            continue
        lo, hi, cls = int(ent[0]), int(ent[1]), ent[2]
        if lo != pos or hi <= lo:
            errors.append(f"extra.delta.chunks not contiguous at "
                          f"[{lo}, {hi}) (expected lo={pos})")
        tallies[cls] += 1
        pos = max(pos, hi)
    if grid and pos != int(m.get("n_rows", -1)):
        errors.append(f"extra.delta.chunks cover [0, {pos}) but the "
                      f"panel has {m.get('n_rows')} rows")
    for k in DELTA_CLASSES:
        if counts and counts.get(k) != tallies[k]:
            errors.append(f"extra.delta.counts[{k!r}] = {counts.get(k)} "
                          f"but the classified grid holds {tallies[k]}")
    if not isinstance(d.get("source_manifest"), str):
        errors.append("extra.delta.source_manifest missing")
    adopted_entries = [e for e in m.get("chunks", [])
                       if isinstance(e.get("delta"), dict)
                       and e["delta"].get("class") == "adopted"]
    for e in adopted_entries:
        if not isinstance(e["delta"].get("source_manifest"), str):
            errors.append(f"adopted chunk [{e.get('lo')}, {e.get('hi')}) "
                          "does not name its source manifest")
        if e.get("status") != "committed":
            errors.append(f"adopted chunk [{e.get('lo')}, {e.get('hi')}) "
                          f"has status {e.get('status')!r} — adoption IS "
                          "a commit")
    if counts and len(adopted_entries) > counts.get("adopted", 0):
        errors.append(
            f"{len(adopted_entries)} adopted chunk entries exceed the "
            f"plan's adopted count {counts.get('adopted')}")
    return errors


def validate_sink_dir(sink_dir: str, *, expect_rows=None) -> list:
    """Validate a write-back sink directory (ISSUE 20): the durable
    ``sink_manifest.json`` parses, its recorded shards tile
    ``[0, n_rows)`` exactly, every shard file exists on disk, no
    unrecorded ``out_*.npz`` stray survived finalize, and the
    accounting block carries the footprint counters the CI smoke and
    the budget advisor read."""
    errors = []
    mp = os.path.join(sink_dir, "sink_manifest.json")
    try:
        with open(mp, "rb") as f:
            m = json.loads(f.read().decode())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        return [f"sink manifest {mp}: unreadable ({e})"]
    if m.get("kind") != "sink":
        errors.append(f"sink manifest: kind {m.get('kind')!r} != 'sink'")
    n_rows = m.get("n_rows")
    if not isinstance(n_rows, int) or n_rows < 1:
        errors.append(f"sink manifest: bad n_rows {n_rows!r}")
        n_rows = None
    if expect_rows is not None and n_rows is not None and \
            n_rows != int(expect_rows):
        errors.append(f"sink manifest: n_rows {n_rows} != walk rows "
                      f"{expect_rows}")
    shards = m.get("shards")
    if not isinstance(shards, list) or not shards:
        return errors + ["sink manifest: shards missing/empty"]
    pos = 0
    names = set()
    for s in shards:
        lo, hi, name = s.get("lo"), s.get("hi"), s.get("name")
        if not isinstance(lo, int) or not isinstance(hi, int) or \
                not isinstance(name, str) or hi <= lo:
            errors.append(f"sink shard entry malformed: {s!r}")
            continue
        if lo != pos:
            errors.append(f"sink shards not contiguous at [{lo}, {hi}) "
                          f"(expected lo={pos})")
        pos = max(pos, hi)
        names.add(name)
        if not os.path.exists(os.path.join(sink_dir, name)):
            errors.append(f"sink shard {name} missing on disk")
    if n_rows is not None and pos != n_rows:
        errors.append(f"sink shards cover [0, {pos}) but n_rows is "
                      f"{n_rows}")
    try:
        on_disk = sorted(os.listdir(sink_dir))
    except OSError as e:
        return errors + [f"sink dir unreadable: {e}"]
    for fn in on_disk:
        if fn.startswith("out_") and fn.endswith(".npz") \
                and fn not in names:
            errors.append(f"sink dir holds unrecorded shard {fn} "
                          "(finalize must sweep strays)")
    acct = m.get("accounting")
    if not isinstance(acct, dict):
        errors.append("sink manifest: accounting block missing")
    else:
        for k in ("writes", "spans", "bytes_written",
                  "peak_in_flight_bytes"):
            if not isinstance(acct.get(k), int) or acct[k] < 0:
                errors.append(f"sink accounting.{k} invalid: "
                              f"{acct.get(k)!r}")
        if not isinstance(acct.get("status_counts"), dict):
            errors.append("sink accounting.status_counts missing")
    return errors


def validate_manifest_sink(m: dict, path: str) -> list:
    """Validate a journaled walk's ``extra.sink`` block (ISSUE 20) and
    the write-back sink directory it points at.  Manifests without the
    block (no sink) pass untouched."""
    s = (m.get("extra") or {}).get("sink")
    if s is None:
        return []
    if not isinstance(s, dict):
        return [f"manifest {path}: extra.sink is not an object: {s!r}"]
    errors = []
    d = s.get("directory")
    if not isinstance(d, str) or not d:
        errors.append(f"extra.sink.directory invalid: {d!r}")
        return errors
    if not isinstance(s.get("depth"), int) or s["depth"] < 1:
        errors.append(f"extra.sink.depth invalid: {s.get('depth')!r}")
    if not os.path.isdir(d):
        errors.append(f"extra.sink.directory {d} is not a directory")
        return errors
    errors += [f"sink {d}: {e}"
               for e in validate_sink_dir(d, expect_rows=m.get("n_rows"))]
    return errors


TICKLOOP_STAGES = ("ticked", "appended", "fitted", "published")


def validate_tickloop_root(root: str) -> list:
    """Validate a tick-loop root (ISSUE 20): the ``tickloop.json`` loop
    manifest, every ``cycle_%05d`` dir's ``tick_manifest.json`` (stage
    progression, tick-count chain), and — for published cycles — the
    cycle's fit/forecast journals and write-back sink directory.  Only
    the LAST cycle may be mid-flight (anything but ``published``)."""
    import re as _re

    errors = []
    mp = os.path.join(root, "tickloop.json")
    try:
        with open(mp, "rb") as f:
            m = json.loads(f.read().decode())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        return [f"tickloop manifest {mp}: unreadable ({e})"]
    if m.get("kind") != "tickloop":
        errors.append(f"tickloop manifest: kind {m.get('kind')!r} != "
                      "'tickloop'")
    n_rows, n_time0 = m.get("n_rows"), m.get("n_time0")
    for k, v in (("n_rows", n_rows), ("n_time0", n_time0)):
        if not isinstance(v, int) or v < 1:
            errors.append(f"tickloop manifest: bad {k} {v!r}")
    if not isinstance(m.get("config"), dict):
        errors.append("tickloop manifest: config block missing")
    cycles = sorted(
        (int(mm.group(1)), name)
        for name in os.listdir(root)
        for mm in [_re.match(r"^cycle_(\d{5})$", name)] if mm)
    expect_t = n_time0 if isinstance(n_time0, int) else None
    for pos, (i, name) in enumerate(cycles):
        if i != pos:
            errors.append(f"cycle dirs not consecutive: {name} at "
                          f"position {pos}")
        cdir = os.path.join(root, name)
        cm_path = os.path.join(cdir, "tick_manifest.json")
        try:
            with open(cm_path, "rb") as f:
                cm = json.loads(f.read().decode())
        except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
            errors.append(f"{name}: tick_manifest.json unreadable ({e})")
            continue
        stage = cm.get("stage")
        if stage not in TICKLOOP_STAGES:
            errors.append(f"{name}: bad stage {stage!r}")
        elif stage != "published" and pos != len(cycles) - 1:
            errors.append(f"{name}: stage {stage!r} but later cycles "
                          "exist — only the last cycle may be mid-flight")
        if cm.get("cycle") != i:
            errors.append(f"{name}: cycle field {cm.get('cycle')!r} != "
                          f"{i}")
        n_ticks = cm.get("n_ticks")
        if not isinstance(n_ticks, int) or n_ticks < 1:
            errors.append(f"{name}: bad n_ticks {n_ticks!r}")
            n_ticks = None
        if expect_t is not None:
            if cm.get("t_before") != expect_t:
                errors.append(f"{name}: t_before {cm.get('t_before')!r} "
                              f"breaks the chain (expected {expect_t})")
            expect_t = (expect_t + n_ticks if n_ticks is not None
                        else None)
        if not isinstance(cm.get("ticks_digest"), str):
            errors.append(f"{name}: ticks_digest missing")
        if not os.path.exists(os.path.join(cdir, "ticks.npz")):
            errors.append(f"{name}: ticks.npz missing (the durable tick "
                          "record is the resume seed)")
        if not isinstance(cm.get("walls"), dict):
            errors.append(f"{name}: walls block missing")
        if stage != "published":
            continue
        pub = cm.get("published")
        if not isinstance(pub, dict):
            errors.append(f"{name}: published block missing")
        elif not isinstance(pub.get("status_counts"), dict):
            errors.append(f"{name}: published.status_counts missing")
        errors += [f"{name}/published: {e}" for e in
                   validate_sink_dir(os.path.join(cdir, "published"),
                                     expect_rows=n_rows)]
        for sub in ("fit", "forecast"):
            smp = os.path.join(cdir, sub, "manifest.json")
            if not os.path.exists(smp):
                errors.append(f"{name}: {sub}/manifest.json missing")
                continue
            try:
                with open(smp, "rb") as f:
                    sm = json.loads(f.read().decode())
            except (OSError, json.JSONDecodeError,
                    UnicodeDecodeError) as e:
                errors.append(f"{name}: {sub} manifest unreadable ({e})")
                continue
            if isinstance(sm.get("telemetry"), dict):
                errors += [f"{name}/{sub}: {e}" for e in
                           validate_manifest_telemetry(
                               os.path.join(cdir, sub))]
    return errors


def validate_manifest_auto_extra(m: dict, path: str) -> list:
    """Validate a per-order journal manifest's ``extra.auto_fit`` block
    (ISSUE 9).  Manifests without the block (non-auto walks) pass
    untouched; a walk that claims a grid position must carry a coherent
    one — the budget advisor and the search resume both read it.
    """
    a = (m.get("extra") or {}).get("auto_fit")
    if a is None:
        return []
    errors = []
    if not isinstance(a, dict):
        return [f"manifest {path}: extra.auto_fit is not an object: {a!r}"]
    gi, gn = a.get("grid_index"), a.get("grid_total")
    if not isinstance(gi, int) or not isinstance(gn, int) or not (
            0 <= gi < gn):
        errors.append(f"extra.auto_fit grid position invalid: index "
                      f"{gi!r} of {gn!r}")

    def _order_ok(od):
        return (isinstance(od, list) and len(od) == 3
                and all(isinstance(v, int) and v >= 0 for v in od))

    fused = a.get("fused_orders")
    if fused is not None:
        # a fused group walk (ISSUE 10): the chunks carry K same-d orders
        if not (isinstance(fused, list) and fused
                and all(isinstance(v, int) for v in fused)):
            errors.append(f"extra.auto_fit.fused_orders invalid: {fused!r}")
        else:
            if isinstance(gn, int) and not all(0 <= v < gn for v in fused):
                errors.append(f"extra.auto_fit.fused_orders {fused} out of "
                              f"range for grid_total {gn}")
            if isinstance(gi, int) and fused[0] != gi:
                errors.append(f"extra.auto_fit.fused_orders must lead with "
                              f"grid_index {gi}, got {fused}")
        ods = a.get("orders")
        if not (isinstance(ods, list) and ods
                and all(_order_ok(od) for od in ods)):
            errors.append(f"extra.auto_fit.orders invalid for fused walk: "
                          f"{ods!r}")
        elif len({od[1] for od in ods}) != 1:
            errors.append(f"extra.auto_fit.orders mix d values in one "
                          f"fused group: {ods!r}")
        elif isinstance(fused, list) and len(ods) != len(fused):
            errors.append(f"extra.auto_fit.orders count {len(ods)} != "
                          f"fused_orders count {len(fused)}")
    else:
        order = a.get("order")
        if not _order_ok(order):
            errors.append(f"extra.auto_fit.order invalid: {order!r}")
        seasonal = a.get("seasonal")
        if seasonal is not None and not (
                isinstance(seasonal, list) and len(seasonal) == 4
                and all(isinstance(v, int) for v in seasonal)):
            errors.append(f"extra.auto_fit.seasonal invalid: {seasonal!r}")
    if a.get("stage") not in ("full", "stage1", "winners", "stepwise"):
        errors.append(f"extra.auto_fit.stage invalid: {a.get('stage')!r}")
    if a.get("stage") == "stepwise" and not (
            isinstance(a.get("stepwise_pass"), int)
            and a["stepwise_pass"] >= 0):
        errors.append(f"extra.auto_fit.stepwise_pass invalid for a "
                      f"stepwise walk: {a.get('stepwise_pass')!r}")
    grid = (m.get("extra") or {}).get("grid") or {}
    if isinstance(gi, int) and grid.get("index") != gi:
        errors.append(f"extra.grid.index {grid.get('index')!r} disagrees "
                      f"with extra.auto_fit.grid_index {gi}")
    if fused is not None and grid.get("fused") != fused:
        errors.append(f"extra.grid.fused {grid.get('fused')!r} disagrees "
                      f"with extra.auto_fit.fused_orders {fused!r}")
    return errors


def validate_auto_manifest(root: str) -> list:
    """Validate an auto-fit search root (``auto_manifest.json``): the
    grid-level telemetry block — orders tried, per-order stage-2 spend,
    selection counts — plus every per-order journal found on disk."""
    path = root
    if os.path.isdir(path):
        path = os.path.join(path, "auto_manifest.json")
    try:
        with open(path, "rb") as f:
            m = json.loads(f.read().decode())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        return [f"auto manifest {path}: unreadable ({e})"]
    a = m.get("auto_fit")
    if not isinstance(a, dict):
        return [f"auto manifest {path}: no auto_fit block"]
    errors = []
    orders = a.get("orders")
    if not isinstance(orders, list) or not orders:
        errors.append("auto_fit.orders missing/empty")
        orders = []
    for i, o in enumerate(orders):
        if not isinstance(o, dict):
            errors.append(f"auto_fit.orders[{i}] is not an object: {o!r}")
            continue
        if o.get("grid_index") != i:
            errors.append(f"auto_fit.orders[{i}].grid_index is "
                          f"{o.get('grid_index')!r}")
        od = o.get("order")
        if not (isinstance(od, list) and len(od) == 3
                and all(isinstance(v, int) and v >= 0 for v in od)):
            errors.append(f"auto_fit.orders[{i}].order invalid: {od!r}")
        if not isinstance(o.get("selected_rows"), int) or \
                o["selected_rows"] < 0:
            errors.append(f"auto_fit.orders[{i}].selected_rows invalid: "
                          f"{o.get('selected_rows')!r}")
        if not isinstance(o.get("wall_s"), (int, float)):
            errors.append(f"auto_fit.orders[{i}].wall_s invalid: "
                          f"{o.get('wall_s')!r}")
    sc = a.get("selection_counts")
    if not isinstance(sc, dict) or not sc or not all(
            isinstance(v, int) and v >= 0 for v in sc.values()):
        errors.append(f"auto_fit.selection_counts missing/invalid: {sc!r}")
    elif isinstance(a.get("n_rows"), int) and \
            sum(sc.values()) != a["n_rows"]:
        errors.append(f"auto_fit.selection_counts sum "
                      f"{sum(sc.values())} != n_rows {a['n_rows']}")
    for key in ("stage1_wall_s", "stage2_wall_s", "stage2_spend_share"):
        if not isinstance(a.get(key), (int, float)):
            errors.append(f"auto_fit.{key} invalid: {a.get(key)!r}")
    if a.get("criterion") not in ("aicc", "aic", "bic"):
        errors.append(f"auto_fit.criterion invalid: {a.get('criterion')!r}")
    # fusion accounting (ISSUE 10): when present, the groups must
    # partition the grid exactly once — the resume path and the budget
    # advisor both read the group membership
    fg = a.get("fusion_groups")
    if fg is not None:
        if not (isinstance(fg, list) and fg
                and all(isinstance(e, dict) and isinstance(e.get("dir"), str)
                        and isinstance(e.get("orders"), list)
                        for e in fg)):
            errors.append(f"auto_fit.fusion_groups invalid: {fg!r}")
        else:
            seen = [g for e in fg for g in e["orders"]]
            if sorted(seen) != list(range(len(orders))):
                errors.append(
                    f"auto_fit.fusion_groups {seen} do not partition the "
                    f"{len(orders)}-order grid exactly once")
        if not (isinstance(a.get("diff_cache_hits"), int)
                and a["diff_cache_hits"] >= 0):
            errors.append(f"auto_fit.diff_cache_hits invalid: "
                          f"{a.get('diff_cache_hits')!r}")
    # stepwise accounting (ISSUE 19): the pass manifests must partition
    # the trial list in walk order — a SIGKILL'd search resumes by
    # replaying the pass sequence against these journals, and the budget
    # advisor reads the seed/convergence evidence
    sw = a.get("stepwise")
    if sw is not None:
        if not isinstance(sw, dict):
            errors.append(f"auto_fit.stepwise invalid: {sw!r}")
        else:
            passes = sw.get("passes")
            if not (isinstance(passes, list) and passes
                    and all(isinstance(p, dict) for p in passes)):
                errors.append(f"auto_fit.stepwise.passes missing/invalid: "
                              f"{passes!r}")
            else:
                covered = []
                for i, p in enumerate(passes):
                    if p.get("pass") != i:
                        errors.append(f"auto_fit.stepwise.passes[{i}].pass "
                                      f"is {p.get('pass')!r}")
                    if p.get("dir") != f"stepwise_{i:02d}":
                        errors.append(f"auto_fit.stepwise.passes[{i}].dir "
                                      f"is {p.get('dir')!r}, expected "
                                      f"'stepwise_{i:02d}'")
                    po = p.get("orders")
                    if not (isinstance(po, list) and po
                            and all(isinstance(v, int) for v in po)):
                        errors.append(f"auto_fit.stepwise.passes[{i}]"
                                      f".orders invalid: {po!r}")
                    else:
                        covered += po
                    if not isinstance(p.get("new_rows_won"), int) or \
                            p["new_rows_won"] < 0:
                        errors.append(f"auto_fit.stepwise.passes[{i}]"
                                      ".new_rows_won invalid: "
                                      f"{p.get('new_rows_won')!r}")
                    if not isinstance(p.get("wall_s"), (int, float)):
                        errors.append(f"auto_fit.stepwise.passes[{i}]"
                                      f".wall_s invalid: {p.get('wall_s')!r}")
                if covered and covered != list(range(len(orders))):
                    errors.append(
                        "auto_fit.stepwise passes do not partition the "
                        f"{len(orders)}-order trial list in walk order: "
                        f"{covered}")
            if not isinstance(sw.get("converged"), bool):
                errors.append(f"auto_fit.stepwise.converged invalid: "
                              f"{sw.get('converged')!r}")
            if sw.get("orders_tried") != len(orders):
                errors.append(f"auto_fit.stepwise.orders_tried "
                              f"{sw.get('orders_tried')!r} != "
                              f"{len(orders)} recorded orders")
            if not (isinstance(sw.get("seed"), list) and sw.get("seed")):
                errors.append(f"auto_fit.stepwise.seed missing/empty: "
                              f"{sw.get('seed')!r}")
        # every trial must say which pass walked it — the per-order
        # journal dirs live under stepwise_%02d/ namespaces keyed on it
        for i, o in enumerate(orders):
            if isinstance(o, dict) and not isinstance(
                    o.get("stepwise_pass"), int):
                errors.append(f"auto_fit.orders[{i}].stepwise_pass missing "
                              "for a stepwise search")
    # recurse into every per-order journal the search left on disk: each
    # is an ordinary chunk-walk manifest and must pass the same gate
    if os.path.isdir(root):
        for d in sorted(m.get("grid_dirs") or []):
            sub = os.path.join(root, d)
            if os.path.exists(os.path.join(sub, "manifest.json")):
                errors += [f"{d}: {e}"
                           for e in validate_manifest_telemetry(sub)]
    return errors


def validate_backtest_manifest(root: str) -> list:
    """Validate a rolling-origin backtest campaign root (ISSUE 14).

    Checks the campaign-level ``backtest_manifest.json`` (identity
    fields, ascending origins, per-window entries with metric vectors of
    horizon length), verifies each committed window's metrics npz exists
    and matches its recorded content digest, and recurses into every
    window's fit-walk journal when it carries a telemetry block.
    """
    import hashlib

    import numpy as np

    errors = []
    mp = os.path.join(root, "backtest_manifest.json")
    try:
        with open(mp, "rb") as f:
            m = json.loads(f.read().decode())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        return [f"backtest manifest {mp}: unreadable ({e})"]
    if m.get("kind") != "backtest":
        errors.append(f"backtest manifest: kind {m.get('kind')!r} != "
                      "'backtest'")
    for key in ("campaign_hash", "panel_fingerprint", "model"):
        if not isinstance(m.get(key), str) or not m.get(key):
            errors.append(f"backtest manifest: missing {key}")
    horizon = m.get("horizon")
    if not isinstance(horizon, int) or horizon < 1:
        errors.append(f"backtest manifest: bad horizon {horizon!r}")
        horizon = None
    origins = m.get("origins")
    if (not isinstance(origins, list) or not origins
            or origins != sorted(origins)):
        errors.append(f"backtest manifest: origins not an ascending "
                      f"list: {origins!r}")
        origins = None
    windows = m.get("windows")
    if not isinstance(windows, list):
        return errors + ["backtest manifest: windows missing"]
    seen = set()
    for w in windows:
        i = w.get("index")
        if not isinstance(i, int) or (origins is not None
                                      and not 0 <= i < len(origins)):
            errors.append(f"backtest window {i!r}: bad index")
            continue
        if i in seen:
            errors.append(f"backtest window {i}: duplicate entry")
        seen.add(i)
        if origins is not None and w.get("origin") != origins[i]:
            errors.append(f"backtest window {i}: origin {w.get('origin')} "
                          f"!= manifest origins[{i}] {origins[i]}")
        if w.get("status") not in ("committed", "timeout"):
            errors.append(f"backtest window {i}: bad status "
                          f"{w.get('status')!r}")
            continue
        wc = w.get("window_class")
        if wc is not None and wc not in ("adopted", "warm", "cold"):
            errors.append(f"backtest window {i}: bad window_class {wc!r}")
        if w.get("status") != "committed":
            continue
        for key in ("mae", "rmse", "mape"):
            v = w.get(key)
            if (not isinstance(v, list)
                    or (horizon is not None and len(v) != horizon)):
                errors.append(f"backtest window {i}: {key} is not a "
                              f"length-{horizon} vector")
        mf = w.get("metrics_file")
        if mf:
            npz_path = os.path.join(root, mf)
            import zipfile

            try:
                with np.load(npz_path, allow_pickle=False) as z:
                    arrays = {key: np.array(z[key]) for key in z.files}
            except (OSError, ValueError, KeyError,
                    zipfile.BadZipFile) as e:
                errors.append(f"backtest window {i}: metrics shard "
                              f"{mf} unreadable ({e})")
                continue
            h = hashlib.sha256()
            for name in sorted(arrays):
                a = np.ascontiguousarray(arrays[name])
                h.update(f"{name}:{a.shape}:{a.dtype}".encode())
                h.update(a.tobytes())
            if h.hexdigest()[:16] != w.get("digest"):
                errors.append(f"backtest window {i}: metrics shard "
                              f"digest mismatch (torn write?)")
        fd = w.get("fit_dir")
        if fd:
            wmp = os.path.join(root, fd, "manifest.json")
            if not os.path.exists(wmp):
                errors.append(f"backtest window {i}: fit journal "
                              f"{fd}/manifest.json missing")
            else:
                try:
                    with open(wmp, "rb") as f:
                        wm = json.loads(f.read().decode())
                except (OSError, json.JSONDecodeError,
                        UnicodeDecodeError) as e:
                    errors.append(f"backtest window {i}: fit manifest "
                                  f"unreadable ({e})")
                    continue
                if isinstance(wm.get("telemetry"), dict):
                    errors += [f"window {i}: {e2}" for e2 in
                               validate_manifest_telemetry(
                                   os.path.join(root, fd))]
    d = m.get("delta")
    if d is not None:
        # a delta-warm campaign (ISSUE 20): the manifest records what
        # window-level adoption kept from the prior campaign
        if not isinstance(d, dict):
            errors.append(f"backtest manifest: delta block is not an "
                          f"object: {d!r}")
        else:
            if not isinstance(d.get("prior_campaign_hash"), str):
                errors.append("backtest delta: prior_campaign_hash "
                              "missing")
            pt = d.get("prior_n_time")
            if not isinstance(pt, int) or pt < 1:
                errors.append(f"backtest delta: bad prior_n_time {pt!r}")
            for key in ("adopted", "recomputed"):
                v = d.get(key)
                if not isinstance(v, int) or v < 0:
                    errors.append(f"backtest delta: bad {key} {v!r}")
    return errors


def validate_manifest_shards(m: dict, path: str) -> list:
    """Validate a merged sharded-job manifest's ``shards`` block (ISSUE 6).

    Unsharded manifests (no block) pass untouched.  A merged manifest must
    carry contiguous per-shard spans covering the panel, per-shard
    accounting, chunk entries tagged with an in-range ``shard_id`` whose
    row range sits inside their shard's span and whose npz path is rooted
    in that shard's namespace, and (when telemetry rode along) shard tags
    on the merged timeline rows.

    Elastic walks (ISSUE 11): a chunk REASSIGNED by quarantine or a steal
    legitimately sits outside its committing namespace's nominal span —
    allowed iff the entry carries its ``owner`` lane tag; the per-shard
    ``owner``/``chunks_reassigned_in`` fields and the top-level
    ``rebalance`` block (quarantine causes, steal counts, reassigned
    total — which must agree with the per-shard counts) are validated
    when present.
    """
    shards = m.get("shards")
    if shards is None and not m.get("merged_from_shards"):
        return []
    errors = []
    errors += _validate_rebalance(m)
    if not isinstance(shards, list) or not shards:
        return errors + [f"manifest {path}: merged_from_shards set but "
                         "shards block missing/empty"]
    if m.get("merged_from_shards") != len(shards):
        errors.append(f"shards block has {len(shards)} entries but "
                      f"merged_from_shards={m.get('merged_from_shards')}")
    prev_hi = 0
    for i, s in enumerate(shards):
        if not isinstance(s, dict):
            errors.append(f"shards[{i}] is not an object: {s!r}")
            continue
        if s.get("shard_id") != i:
            errors.append(f"shards[{i}].shard_id is {s.get('shard_id')!r}")
        lo, hi = s.get("lo"), s.get("hi")
        if not isinstance(lo, int) or not isinstance(hi, int) or lo >= hi:
            errors.append(f"shards[{i}] span invalid: [{lo!r}, {hi!r})")
            continue
        if lo != prev_hi:
            errors.append(f"shards[{i}] span not contiguous: lo {lo} "
                          f"after hi {prev_hi}")
        prev_hi = hi
        for k in ("chunks_committed", "chunks_timeout"):
            if not isinstance(s.get(k), int) or s[k] < 0:
                errors.append(f"shards[{i}].{k} invalid: {s.get(k)!r}")
        if not isinstance(s.get("dir"), str):
            errors.append(f"shards[{i}].dir invalid: {s.get('dir')!r}")
        # elastic merges (ISSUE 11) stamp each namespace with its owner
        # lane and how many committed chunks were reassigned in
        if "owner" in s and s["owner"] != s.get("shard_id"):
            errors.append(f"shards[{i}].owner {s['owner']!r} != shard_id "
                          f"{s.get('shard_id')!r}")
        if "chunks_reassigned_in" in s and (
                not isinstance(s["chunks_reassigned_in"], int)
                or s["chunks_reassigned_in"] < 0):
            errors.append(f"shards[{i}].chunks_reassigned_in invalid: "
                          f"{s['chunks_reassigned_in']!r}")
    n_rows = m.get("n_rows")
    if isinstance(n_rows, int) and prev_hi and prev_hi != n_rows:
        errors.append(f"shard spans cover [0, {prev_hi}) but n_rows is "
                      f"{n_rows}")
    # spans only from well-formed entries: a malformed shard was already
    # reported above, and chunks pointing at it get the not-in-block error
    spans = {s.get("shard_id"): (s["lo"], s["hi"]) for s in shards
             if isinstance(s, dict)
             and isinstance(s.get("lo"), int) and isinstance(s.get("hi"), int)}
    for c in m.get("chunks", []):
        sid = c.get("shard_id")
        if sid is None:
            # a later single-device walk ADOPTING the merged manifest
            # commits retried chunks at the root, untagged and root-rooted
            # — the documented one-directional adoption contract, not a
            # merge bug
            continue
        span = spans.get(sid)
        if span is None:
            errors.append(f"chunk {c.get('lo')}: shard_id {sid!r} not in "
                          "the shards block")
            continue
        if not (span[0] <= c.get("lo", -1) and c.get("hi", 1 << 60) <= span[1]):
            # a chunk outside its committing namespace's nominal span is
            # only legitimate when elastically REASSIGNED — the owner tag
            # says which lane computed it (ISSUE 11)
            if not isinstance(c.get("owner"), int):
                errors.append(f"chunk [{c.get('lo')}, {c.get('hi')}) "
                              f"outside its shard {sid} span {span} and "
                              "not owner-tagged (no elastic reassignment "
                              "can explain it)")
            elif c["owner"] != sid:
                errors.append(f"chunk {c.get('lo')}: owner {c['owner']} "
                              f"disagrees with committing namespace {sid}")
        elif isinstance(c.get("owner"), int) and c["owner"] != sid:
            errors.append(f"chunk {c.get('lo')}: owner {c['owner']} "
                          f"disagrees with committing namespace {sid}")
        d = next((s.get("dir") for s in shards
                  if isinstance(s, dict) and s.get("shard_id") == sid), None)
        if "shard" in c and isinstance(d, str) and \
                not str(c["shard"]).startswith(d + "/"):
            errors.append(f"chunk {c.get('lo')}: npz path {c['shard']!r} "
                          f"not rooted in shard namespace {d!r}")
    for row in ((m.get("telemetry") or {}).get("chunks") or []):
        sid = row.get("shard")
        if sid is not None and sid not in spans:
            errors.append(f"telemetry chunk {row.get('lo')}: shard tag "
                          f"{sid!r} not in the shards block")
    # the rebalance block's reassigned total must agree with what the
    # chunk entries actually show — a drifting count means the merge's
    # reconciliation and the supervisor's record no longer describe the
    # same job
    rb = m.get("rebalance")
    if isinstance(rb, dict) and isinstance(rb.get("reassigned_chunks"), int):
        observed = sum(
            1 for c in m.get("chunks", [])
            if c.get("status") == "committed"
            and c.get("shard_id") in spans
            and not (spans[c["shard_id"]][0] <= c.get("lo", -1)
                     and c.get("hi", 1 << 60) <= spans[c["shard_id"]][1]))
        if observed != rb["reassigned_chunks"]:
            errors.append(f"rebalance.reassigned_chunks "
                          f"{rb['reassigned_chunks']} != {observed} "
                          "owner-tagged chunks outside their namespace "
                          "span")
    return errors


def _validate_rebalance(m: dict) -> list:
    """Validate a merged manifest's elastic ``rebalance`` block (ISSUE 11);
    absent (static/pre-elastic walks, multi-host jobs) passes untouched."""
    rb = m.get("rebalance")
    if rb is None:
        return []
    if not isinstance(rb, dict):
        return [f"rebalance block is not an object: {rb!r}"]
    errors = []
    for k in ("steals", "lane_retries_used", "reassigned_chunks",
              "reassigned_spans"):
        if not isinstance(rb.get(k), int) or rb[k] < 0:
            errors.append(f"rebalance.{k} invalid: {rb.get(k)!r}")
    q = rb.get("quarantined")
    if not isinstance(q, list):
        errors.append(f"rebalance.quarantined invalid: {q!r}")
        return errors
    n_shards = m.get("merged_from_shards")
    for i, rec in enumerate(q):
        if not isinstance(rec, dict):
            errors.append(f"rebalance.quarantined[{i}] not an object: "
                          f"{rec!r}")
            continue
        sid = rec.get("shard_id")
        if not isinstance(sid, int) or (
                isinstance(n_shards, int) and not 0 <= sid < n_shards):
            errors.append(f"rebalance.quarantined[{i}].shard_id invalid: "
                          f"{sid!r}")
        if not isinstance(rec.get("cause"), str) or not rec["cause"]:
            errors.append(f"rebalance.quarantined[{i}].cause missing")
        if not isinstance(rec.get("retries"), int) or rec["retries"] < 0:
            errors.append(f"rebalance.quarantined[{i}].retries invalid: "
                          f"{rec.get('retries')!r}")
    return errors


def validate_prom_sink(prom_path: str, events) -> list:
    """Validate a Prometheus-textfile sink output (ISSUE 12 satellite).

    Delegates to ``obs.promsink.validate_textfile`` — exposition-format
    syntax plus, when the event stream carries a final ``metrics``
    snapshot, the registry cross-check: every counter/gauge/histogram in
    the snapshot must appear in the textfile under its mapped name (a
    rename/drop fails here instead of silently emptying a dashboard).
    The serving gauges the sink adds on top of the registry are allowed —
    the contract is "nothing vanishes", not "nothing extra".
    """
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        from spark_timeseries_tpu.obs import promsink
    except Exception as e:  # noqa: BLE001 - tooling must degrade loudly
        return [f"cannot import obs.promsink to validate {prom_path}: {e}"]
    snapshot = None
    for _, ev in events:
        if ev.get("kind") == "metrics":
            snapshot = {k: ev.get(k) for k in ("counters", "gauges",
                                               "histograms")}
    return [f"prom {prom_path}: {e}"
            for e in promsink.validate_textfile(prom_path,
                                                snapshot=snapshot)]


# ---------------------------------------------------------------------------
# fleet view (ISSUE 18): N replica streams + the client stream, one story
# ---------------------------------------------------------------------------

FLEET_ANNOTATIONS = (
    "fleet.elected", "fleet.step_down", "fleet.fenced",
    "fleet.standby_read", "fleet.torn_result", "server.storage_refusal",
    "client.endpoint_circuit_open", "client.endpoint_half_open",
    "client.endpoint_probe_failed", "client.endpoint_recovered",
    "client.endpoint_redirected", "client.primary_learned",
    # warm routing (ISSUE 19): which leg each auto-fit submit took —
    # across a failover these show whether the survivor stayed warm —
    # and any fenced/failed profile write that forced a cold next pass
    "server.route", "server.profile_refused",
)


def _import_pkg():
    """Make the package importable from the repo checkout (the
    validate_prom_sink pattern)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def load_fleet(root: str):
    """Load every per-process stream at a fleet root.

    The convention (tests/_chaos_worker.py, tests/_fleet_worker.py):
    each process streams to ``obs_<name>.jsonl`` — replicas under their
    owner name, the storming client as ``obs_client.jsonl`` — next to
    the optional ``chaos_manifest.json`` and the client's
    ``*.clock.json`` offset sidecars.

    Returns ``(streams, merged, clocks, manifest, errors)``:
    ``streams`` maps stream name to the ``(line_no, event)`` list from
    :func:`load_events`; ``merged`` is every line across streams,
    tagged with its ``stream`` name and sorted by ``ts`` (wall clock —
    a same-host ordering; the clock sidecars carry the per-endpoint
    monotonic offsets a cross-host merge would need).
    """
    import glob

    streams, errors = {}, []
    for p in sorted(glob.glob(os.path.join(root, "obs_*.jsonl"))):
        name = os.path.basename(p)[len("obs_"):-len(".jsonl")]
        evs, errs = load_events(p)
        streams[name] = evs
        errors += [f"[{name}] {e}" for e in errs]
    if not streams:
        errors.append(f"fleet root {root}: no obs_*.jsonl streams")
    merged = []
    for name, evs in streams.items():
        for _, ev in evs:
            merged.append({**ev, "stream": name})
    merged.sort(key=lambda ev: (ev["ts"] if isinstance(
        ev.get("ts"), (int, float)) else 0.0))
    clocks = {}
    for p in sorted(glob.glob(os.path.join(root, "*.clock.json"))):
        try:
            with open(p, encoding="utf-8") as f:
                clocks[os.path.basename(p)] = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            errors.append(f"clock sidecar {p}: unreadable ({e})")
    manifest = None
    mp = os.path.join(root, "chaos_manifest.json")
    if os.path.exists(mp):
        try:
            with open(mp, encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            errors.append(f"chaos manifest {mp}: unreadable ({e})")
    return streams, merged, clocks, manifest, errors


def _derive_trace(request_id: str):
    """Re-derive ``(trace_id, tracing_module)`` for a request id via
    ``obs.tracing`` — the package is the single source of truth for the
    deterministic derivation, so the tool cannot drift from it."""
    _import_pkg()
    from spark_timeseries_tpu.obs import tracing

    return tracing.derive_trace_id(str(request_id)), tracing


def check_trace(merged, request_id: str) -> list:
    """The ci reconstruction gate (ISSUE 18): one stormed request's
    causal timeline must exist, cross processes, and terminate exactly
    once — a submit origin on the client stream, a server admission on
    a replica stream, and exactly one ``client.result`` terminal (a
    SIGKILLed primary shows a SECOND admission on the survivor, never a
    second terminal)."""
    try:
        tid, _ = _derive_trace(request_id)
    except Exception as e:  # noqa: BLE001 - tooling degrades loudly
        return [f"cannot import obs.tracing to derive trace ids: {e}"]
    mine = [ev for ev in merged
            if (ev.get("trace") or {}).get("trace_id") == tid]
    if not mine:
        return [f"trace {request_id}: no lines carry trace_id {tid}"]
    errors = []
    names = [ev.get("name") for ev in mine]
    streams = sorted({ev["stream"] for ev in mine})
    if "client.submit" not in names:
        errors.append(f"trace {request_id}: no client.submit origin")
    if "server.admit" not in names:
        errors.append(f"trace {request_id}: no server.admit — the "
                      "request never shows up on a replica's timeline")
    n_results = names.count("client.result")
    if n_results != 1:
        errors.append(f"trace {request_id}: {n_results} client.result "
                      "terminals (the contract is exactly one)")
    if len(streams) < 2:
        errors.append(f"trace {request_id}: confined to {streams} — a "
                      "fleet trace must cross processes")
    return errors


def render_trace(merged, request_id: str) -> list:
    """Render one request's causal story across every stream (the
    request-level timeline, then each joined batch trace); returns the
    :func:`check_trace` errors so the render and the gate agree."""
    try:
        tid, tracing = _derive_trace(request_id)
    except Exception as e:  # noqa: BLE001 - tooling degrades loudly
        print(f"cannot derive trace ids: {e}", file=sys.stderr)
        return [str(e)]
    mine = [ev for ev in merged
            if (ev.get("trace") or {}).get("trace_id") == tid]
    print(f"trace {request_id}  trace_id={tid}  ({len(mine)} lines, "
          f"streams {sorted({ev['stream'] for ev in mine})})")

    def _line(ev, t0, pad="  "):
        attrs = ev.get("attrs") or {}
        attrs_s = " ".join(f"{k}={v}" for k, v in attrs.items())
        tail = (f"wall {ev.get('wall_s', 0.0):.4f}s"
                if ev.get("kind") == "span" else "*")
        ts = ev.get("ts") if isinstance(ev.get("ts"), (int, float)) else t0
        print(f"{pad}{ts - t0:9.3f}  [{ev['stream']:<8}] "
              f"{ev.get('name', ''):<24} {tail:<16} {attrs_s}")

    if mine:
        t0 = min(ev["ts"] for ev in mine
                 if isinstance(ev.get("ts"), (int, float)))
        for ev in mine:
            _line(ev, t0)
        # the batch level: the fit work itself runs under the BATCH's
        # content-derived trace; server.batch_member joins the two
        bids = sorted({(ev.get("attrs") or {}).get("batch_id")
                       for ev in mine
                       if ev.get("name") == "server.batch_member"
                       and (ev.get("attrs") or {}).get("batch_id")})
        for bid in bids:
            btid = tracing.derive_trace_id(str(bid))
            bmine = [ev for ev in merged
                     if (ev.get("trace") or {}).get("trace_id") == btid]
            print(f"  batch {bid}  trace_id={btid}  "
                  f"({len(bmine)} lines):")
            for ev in bmine:
                _line(ev, t0, pad="    ")
    return check_trace(merged, request_id)


def _join_chaos(manifest: dict, merged):
    """Join the manifest's injections to their observed consequences
    via ``reliability.chaos.join_injections`` (package import — single
    source of truth for the ordinal-join semantics); None when the
    package is unimportable."""
    _import_pkg()
    try:
        from spark_timeseries_tpu.reliability import chaos
    except Exception as e:  # noqa: BLE001 - tooling degrades loudly
        print(f"cannot import reliability.chaos for the injection join: "
              f"{e}", file=sys.stderr)
        return None
    return chaos.join_injections(manifest.get("fired") or [], merged)


def compute_slo(merged, manifest=None) -> dict:
    """Fleet SLO summary from the merged timeline: availability (the
    share of submitted requests that reached their exactly-once
    terminal), client-observed latency percentiles (first
    ``client.submit`` to first ``client.result`` per request id), and
    failover recovery (takeover latencies from the injection join when
    a chaos manifest rode along)."""
    submits, results = {}, {}
    for ev in merged:
        if ev.get("kind") != "event":
            continue
        rid = (ev.get("attrs") or {}).get("req_id")
        ts = ev.get("ts")
        if rid is None or not isinstance(ts, (int, float)):
            continue
        if ev.get("name") == "client.submit":
            submits.setdefault(rid, ts)
        elif ev.get("name") == "client.result":
            results.setdefault(rid, ts)
    lat = sorted(results[r] - submits[r] for r in results if r in submits)

    def pct(p):
        if not lat:
            return None
        k = max(0, min(len(lat) - 1,
                       int(round(p / 100.0 * (len(lat) - 1)))))
        return round(lat[k], 6)

    takeovers = []
    if manifest:
        joins = _join_chaos(manifest, merged) or []
        takeovers = [j["takeover_latency_s"] for j in joins
                     if j.get("takeover_latency_s") is not None]
    done = sum(1 for r in results if r in submits)
    return {
        "requests_submitted": len(submits),
        "requests_completed": done,
        "availability": round(done / len(submits), 4) if submits else None,
        "latency_p50_s": pct(50),
        "latency_p99_s": pct(99),
        "elections": sum(1 for ev in merged
                         if ev.get("name") == "fleet.elected"),
        "takeover_latencies_s": takeovers,
    }


def render_fleet(streams, merged, clocks, manifest) -> None:
    """The merged fleet view: one lane per process, then the fleet
    annotations (elections, step-downs, circuit transitions), the
    injection-consequence join, and the clock-offset sidecars."""
    stamps = [ev["ts"] for ev in merged
              if isinstance(ev.get("ts"), (int, float))]
    t0 = min(stamps) if stamps else 0.0
    print(f"fleet telemetry: {len(streams)} streams, "
          f"{len(merged)} lines")
    for name in sorted(streams):
        mine = [ev for ev in merged if ev["stream"] == name
                and ev.get("kind") in ("span", "event")]
        n_spans = sum(1 for ev in mine if ev["kind"] == "span")
        print(f"\n  lane {name}  ({n_spans} spans, "
              f"{len(mine) - n_spans} events):")
        for ev in mine:
            attrs = ev.get("attrs") or {}
            attrs_s = " ".join(f"{k}={v}" for k, v in attrs.items())
            mark = " " if ev["kind"] == "span" else "*"
            tr = ev.get("trace") or {}
            tid = f"  [{tr['trace_id']}]" if tr.get("trace_id") else ""
            ts = ev.get("ts") if isinstance(ev.get("ts"),
                                            (int, float)) else t0
            print(f"    {ts - t0:9.3f}  {mark} {ev.get('name', ''):<26} "
                  f"{attrs_s}{tid}")
    ann = [ev for ev in merged if ev.get("kind") == "event"
           and ev.get("name") in FLEET_ANNOTATIONS]
    if ann:
        print(f"\n  fleet annotations ({len(ann)}):")
        for ev in ann:
            attrs = ev.get("attrs") or {}
            attrs_s = " ".join(f"{k}={v}" for k, v in attrs.items())
            ts = ev.get("ts") if isinstance(ev.get("ts"),
                                            (int, float)) else t0
            print(f"    {ts - t0:9.3f}  [{ev['stream']}] "
                  f"{ev['name']} {attrs_s}")
    if manifest:
        joins = _join_chaos(manifest, merged)
        if joins:
            print("\n  chaos injections -> consequences:")
            for j in joins:
                inj = j.get("injection") or {}
                line = (f"    t={inj.get('fired_at_s')}s "
                        f"{inj.get('kind')} {inj.get('target')}")
                if j.get("observed"):
                    line += (f" -> victim {j.get('victim')} fell silent; "
                             f"{j.get('survivor')} elected with token "
                             f"{j.get('elected_token')} (takeover "
                             f"{j.get('takeover_latency_s')}s)")
                else:
                    line += " -> no ownership change observed"
                print(line)
    if clocks:
        print("\n  clock-offset sidecars (endpoint monotonic vs client):")
        for name, rec in sorted(clocks.items()):
            for ep, est in sorted((rec.get("endpoints") or {}).items()):
                print(f"    {name}: {ep} offset "
                      f"{est.get('offset_s')}s (rtt {est.get('rtt_s')}s)")


def summarize(events) -> dict:
    """Timeline + final metrics snapshot of the LATEST run in the stream.

    ``obs.enable(path)`` appends (a crashed run's events survive a rerun
    with the same path), so one file can hold several runs, each starting
    at its own ``meta`` line — report the last one rather than splicing
    runs into a garbled timeline.
    """
    meta_idx = [i for i, (_, ev) in enumerate(events)
                if ev.get("kind") == "meta"]
    run = [ev for _, ev in events[meta_idx[-1]:]] if meta_idx \
        else [ev for _, ev in events]
    meta = run[0] if run and run[0].get("kind") == "meta" else {}
    spans = [ev for ev in run if ev.get("kind") == "span"]
    points = [ev for ev in run if ev.get("kind") == "event"]
    metrics = [ev for ev in run if ev.get("kind") == "metrics"]
    return {
        "run_id": meta.get("run_id"),
        "schema": meta.get("schema"),
        "n_runs_in_stream": max(len(meta_idx), 1),
        "n_spans": len(spans),
        "n_events": len(points),
        "spans": spans,
        "events": points,
        "metrics": metrics[-1] if metrics else None,
    }


def _render(s: dict) -> None:
    extra = (f"  (latest of {s['n_runs_in_stream']} runs in stream)"
             if s.get("n_runs_in_stream", 1) > 1 else "")
    print(f"telemetry run {s['run_id']}  schema {s['schema']}  "
          f"{s['n_spans']} spans, {s['n_events']} events{extra}")
    rows = sorted(s["spans"] + s["events"],
                  key=lambda ev: ev.get("t0", ev.get("ts", 0.0)))
    if rows:
        t_start = min(ev.get("t0", ev.get("ts", 0.0)) for ev in rows)

        def _row(ev, pad="  "):
            off = ev.get("t0", ev.get("ts", 0.0)) - t_start
            indent = "  " * ev.get("depth", 0)
            attrs = ev.get("attrs") or {}
            attrs_s = " ".join(f"{k}={v}" for k, v in attrs.items())
            if ev["kind"] == "span":
                print(f"{pad}{off:9.3f}  {indent}{ev['name']:<24} "
                      f"wall {ev['wall_s']:9.4f}s  cpu {ev['process_s']:8.4f}s"
                      f"  {attrs_s}")
            else:
                print(f"{pad}{off:9.3f}  {indent}* {ev['name']:<22} {attrs_s}")

        # host-resident walks (ISSUE 7) stage every chunk through the
        # staging pool; those spans (stage.h2d under stage.overlap) get
        # their own lane so the input pipeline reads as one row — the
        # H2D wall is then visually comparable against the compute lane.
        # Scoped to runs that actually staged H2D: an in-HBM prefetched
        # walk also emits stage.overlap spans (device slices, no pool),
        # and those must stay in their chronological timeline
        staging = []
        if any(ev.get("name") == "stage.h2d" for ev in rows):
            staging = [ev for ev in rows
                       if str(ev.get("name", "")).startswith("stage.")]
            staging_ids = {id(ev) for ev in staging}
            rows = [ev for ev in rows if id(ev) not in staging_ids]
        # backtest campaigns (ISSUE 14) wrap each expanding window in a
        # backtest.window span: split the stream into ONE LANE PER
        # WINDOW (rows falling inside the window's wall interval) so the
        # refit-and-score sweep reads as W parallel-structured rows,
        # with campaign-level rows kept in their own section
        wins = [ev for ev in rows if ev["kind"] == "span"
                and ev.get("name") == "backtest.window"]
        if wins:
            wins.sort(key=lambda ev: (ev.get("attrs") or {})
                      .get("window", 0))
            taken = {id(ev) for ev in wins}
            print(f"\ntimeline (s from start; {len(wins)} backtest "
                  "window lanes):")
            for wspan in wins:
                attrs = wspan.get("attrs") or {}
                w0 = wspan.get("t0", 0.0)
                w1 = w0 + wspan.get("wall_s", 0.0)
                mine = [ev for ev in rows if id(ev) not in taken
                        and w0 <= ev.get("t0", ev.get("ts", 0.0)) <= w1]
                taken.update(id(ev) for ev in mine)
                print(f"  window {attrs.get('window')} "
                      f"origin={attrs.get('origin')}  "
                      f"({len(mine)} rows, wall "
                      f"{wspan.get('wall_s', 0.0):.4f}s):")
                for ev in mine:
                    _row(ev, pad="    ")
            drv = [ev for ev in rows if id(ev) not in taken]
            if drv:
                print("  campaign driver:")
                for ev in drv:
                    _row(ev, pad="    ")
            rows = []
        # sharded walks (ISSUE 6) tag every lane's spans/events with its
        # shard id: split the merged stream into ONE LANE PER SHARD so the
        # concurrent walks read as parallel rows, with the driver-level
        # rows (merge, panel spans) kept in their own section
        lanes = sorted({(ev.get("attrs") or {}).get("shard") for ev in rows
                        if (ev.get("attrs") or {}).get("shard") is not None})
        if lanes:
            drv = [ev for ev in rows
                   if (ev.get("attrs") or {}).get("shard") is None]
            # elastic lane events (ISSUE 11) are shard-tagged, so each
            # quarantine/steal/retry already renders INSIDE its lane's row
            # below; the header totals make a degraded run visible at a
            # glance
            elastic_names = ("lane.quarantine", "lane.steal", "lane.retry")
            reb = [ev for ev in rows if ev["kind"] == "event"
                   and ev.get("name") in elastic_names]
            header = f"\ntimeline (s from start; {len(lanes)} sharded lanes"
            if reb:
                counts = {n: sum(1 for ev in reb if ev["name"] == n)
                          for n in elastic_names}
                header += (f"; elastic: {counts['lane.quarantine']} "
                           f"quarantined, {counts['lane.steal']} steals, "
                           f"{counts['lane.retry']} retries")
            print(header + "):")
            for sid in lanes:
                mine = [ev for ev in rows
                        if (ev.get("attrs") or {}).get("shard") == sid]
                wall = sum(ev.get("wall_s", 0.0) for ev in mine
                           if ev["kind"] == "span")
                print(f"  lane shard={sid}  ({len(mine)} rows, "
                      f"span wall {wall:.4f}s):")
                for ev in mine:
                    _row(ev, pad="    ")
            if drv:
                print("  driver:")
                for ev in drv:
                    _row(ev, pad="    ")
        else:
            # auto-fit order search (ISSUE 9): every per-order walk tags
            # its spans/events with its grid index — split the stream into
            # ONE LANE PER ORDER so the G candidate walks read as G rows
            # (the sharded-lane treatment, keyed on the grid), with the
            # search-level rows (selection, panel spans) kept separate
            grids = sorted({(ev.get("attrs") or {}).get("grid")
                            for ev in rows
                            if (ev.get("attrs") or {}).get("grid")
                            is not None})
            if grids:
                drv = [ev for ev in rows
                       if (ev.get("attrs") or {}).get("grid") is None]
                print(f"\ntimeline (s from start; {len(grids)} order-grid "
                      "lanes):")
                for gid in grids:
                    mine = [ev for ev in rows
                            if (ev.get("attrs") or {}).get("grid") == gid]
                    wall = sum(ev.get("wall_s", 0.0) for ev in mine
                               if ev["kind"] == "span")
                    label = next(
                        ((ev.get("attrs") or {}).get("order")
                         for ev in mine
                         if (ev.get("attrs") or {}).get("order")), None)
                    print(f"  lane grid={gid}"
                          + (f" order={label}" if label else "")
                          + f"  ({len(mine)} rows, span wall {wall:.4f}s):")
                    for ev in mine:
                        _row(ev, pad="    ")
                if drv:
                    print("  search driver:")
                    for ev in drv:
                        _row(ev, pad="    ")
            elif rows:  # empty when the campaign lanes consumed them
                print("\ntimeline (s from start):")
                for ev in rows:
                    _row(ev)
        if staging:
            h2d = [ev for ev in staging if ev.get("name") == "stage.h2d"
                   and ev["kind"] == "span"]
            wall = sum(ev.get("wall_s", 0.0) for ev in staging
                       if ev["kind"] == "span")
            mb = sum((ev.get("attrs") or {}).get("bytes", 0)
                     for ev in h2d) / 1e6
            print(f"  staging pool lane  ({len(staging)} rows, "
                  f"span wall {wall:.4f}s, {mb:.2f} MB H2D):")
            for ev in staging:
                _row(ev, pad="    ")
    m = s["metrics"]
    if m:
        print("\ncounters:")
        for k, v in sorted((m.get("counters") or {}).items()):
            print(f"  {k:<40} {v}")
        gauges = m.get("gauges") or {}
        if gauges:
            print("gauges:")
            for k, v in sorted(gauges.items()):
                print(f"  {k:<40} {v}")
        hists = m.get("histograms") or {}
        if hists:
            print("histograms (count/mean/max seconds):")
            for k, h in sorted(hists.items()):
                if h.get("count"):
                    print(f"  {k:<40} n={h['count']:<6} "
                          f"mean={h.get('mean', 0):.5f} "
                          f"max={h.get('max', 0):.5f}")
    else:
        print("\n(no metrics snapshot in stream — run obs.disable() or an "
              "instrumented fit to emit one)")


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("events", nargs="?", default=None,
                    help="telemetry JSONL path (obs.enable(path)); "
                         "omitted in --fleet mode")
    ap.add_argument("--check", action="store_true",
                    help="validate the event schema and exit 0/1")
    ap.add_argument("--fleet", default=None, metavar="ROOT",
                    help="fleet mode (ISSUE 18): merge every "
                         "obs_*.jsonl stream at ROOT (+ *.clock.json "
                         "sidecars + chaos_manifest.json) into one "
                         "view; composes with --check/--trace/--slo")
    ap.add_argument("--trace", default=None, metavar="REQUEST_ID",
                    help="with --fleet: render REQUEST_ID's causal "
                         "timeline across every process; with --check, "
                         "gate its reconstruction (submit origin, "
                         "server admission, exactly one terminal, "
                         "more than one process)")
    ap.add_argument("--slo", action="store_true",
                    help="with --fleet: availability / latency "
                         "percentiles / failover-recovery summary")
    ap.add_argument("--manifest", default=None, metavar="CKPT_DIR",
                    help="with --check: also validate the journal "
                         "manifest's embedded telemetry block")
    ap.add_argument("--prom", default=None, metavar="PROM_FILE",
                    help="with --check: validate a Prometheus-textfile "
                         "sink output (obs.promsink) — exposition syntax "
                         "plus name/label agreement with the event "
                         "stream's final metrics snapshot, so a renamed "
                         "counter cannot silently vanish from dashboards")
    ap.add_argument("--degradation", action="store_true",
                    help="with --check: validate the degradation-ladder "
                         "telemetry (ISSUE 17) — the fleet.state gauge "
                         "in the final metrics snapshot and the pinned "
                         "fields of step-down/circuit/hedge/auth events")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary instead of the report")
    args = ap.parse_args()
    if args.events is None and args.fleet is None:
        ap.error("need a telemetry JSONL path (or --fleet ROOT)")
    if args.trace is not None and args.fleet is None:
        ap.error("--trace needs --fleet ROOT (the causal timeline "
                 "spans every process's stream)")

    if args.fleet is not None:
        streams, merged, clocks, manifest, errors = load_fleet(args.fleet)
        if args.check:
            for name, evs in sorted(streams.items()):
                errors += [f"[{name}] {e}"
                           for e in validate_events(evs, [])]
            if args.trace is not None:
                errors += check_trace(merged, args.trace)
            if errors:
                for e in errors:
                    print(f"obs_report: FAIL {e}", file=sys.stderr)
                sys.exit(1)
            n = sum(len(v) for v in streams.values())
            extra = ""
            if args.trace is not None:
                tid, _ = _derive_trace(args.trace)
                mine = [ev for ev in merged
                        if (ev.get("trace") or {}).get("trace_id") == tid]
                extra = (f" + trace {args.trace} reconstructed "
                         f"({len(mine)} lines across "
                         f"{len({ev['stream'] for ev in mine})} streams, "
                         "1 terminal)")
            print(f"obs_report: OK — fleet {args.fleet}: "
                  f"{len(streams)} streams, {n} events valid{extra}")
            return
        for e in errors:
            print(f"obs_report: WARNING {e}", file=sys.stderr)
        if args.json:
            out = {"streams": {n: len(v) for n, v in streams.items()},
                   "slo": compute_slo(merged, manifest)}
            if args.trace is not None:
                out["trace_errors"] = check_trace(merged, args.trace)
            print(json.dumps(out, indent=1, sort_keys=True, default=repr))
            return
        shown = False
        if args.trace is not None:
            shown = True
            for e in render_trace(merged, args.trace):
                print(f"obs_report: WARNING {e}", file=sys.stderr)
        if args.slo:
            shown = True
            print("\nfleet SLO:" if args.trace else "fleet SLO:")
            for k, v in compute_slo(merged, manifest).items():
                print(f"  {k:<24} {v}")
        if not shown:
            render_fleet(streams, merged, clocks, manifest)
        return

    events, errors = load_events(args.events)
    if args.check:
        errors = validate_events(events, errors)
        if args.manifest:
            errors += validate_manifest_telemetry(args.manifest)
        if args.prom:
            errors += validate_prom_sink(args.prom, events)
        if args.degradation:
            errors += validate_degradation(events)
        if errors:
            for e in errors:
                print(f"obs_report: FAIL {e}", file=sys.stderr)
            sys.exit(1)
        n = len(events)
        extra = f" + manifest {args.manifest}" if args.manifest else ""
        if args.prom:
            extra += f" + prom textfile {args.prom}"
        if args.degradation:
            extra += " + degradation-ladder telemetry"
        print(f"obs_report: OK — {n} events valid{extra}")
        return
    if errors:
        for e in errors:
            print(f"obs_report: WARNING {e}", file=sys.stderr)
    s = summarize(events)
    if args.json:
        print(json.dumps(s, indent=1, sort_keys=True, default=repr))
        return
    _render(s)


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:
        # downstream closed early (`obs_report … | grep -q`, ci.sh under
        # pipefail): not an error — mirror the standard CLI convention
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
