"""Profile the headline ARIMA fit: count objective evaluations per L-BFGS
iteration and measure per-pass costs on the real chip.

Diagnostic only (VERDICT round 2, next-round item 1a): quantify where the
1.25s headline latency goes so the optimizer levers (linesearch evals,
fused fwd+bwd, converged-row compaction) are applied where they pay.

Usage: python tools/profile_headline.py [--b 25088] [--t 1000] [--iters 60]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bench import gen_arima_panel
from spark_timeseries_tpu.models import arima
from spark_timeseries_tpu.models.base import align_right
from spark_timeseries_tpu.ops import pallas_kernels as pk
from spark_timeseries_tpu.utils import optim
from spark_timeseries_tpu.utils.optim import _State, _two_loop


def instrumented_lbfgs(fun_batched, x0, *, max_iters, tol, ftol=None,
                       max_linesearch=20, c1=1e-4):
    """minimize_lbfgs_batched with eval counters threaded through the loop.

    Counts every fun_batched call (linesearch) and every value-and-grad call
    so the profile shows objective passes, not just iterations.
    """
    bsz, d = x0.shape
    m = 8
    dtype = x0.dtype
    if ftol is None:
        ftol = 1e-9 if dtype == jnp.float64 else 1e-6

    def vg(x):
        f, pullback = jax.vjp(fun_batched, x)
        (g,) = pullback(jnp.ones_like(f))
        bad = ~jnp.isfinite(f) | ~jnp.all(jnp.isfinite(g), axis=-1)
        return jnp.where(bad, jnp.inf, f), jnp.where(bad[:, None], 0.0, g)

    rownorm = lambda v: jnp.linalg.norm(v, axis=-1)
    rowdot = lambda a, b: jnp.sum(a * b, axis=-1)

    f0, g0 = vg(x0)
    init = _State(
        k=jnp.zeros((), jnp.int32), x=x0, f=f0, g=g0,
        s_hist=jnp.zeros((bsz, m, d), dtype),
        y_hist=jnp.zeros((bsz, m, d), dtype),
        rho_hist=jnp.zeros((bsz, m), dtype),
        converged=(rownorm(g0) < tol) & jnp.isfinite(f0),
        failed=jnp.isinf(f0),
        tprev=jnp.ones((bsz,), dtype),
    )
    iters0 = jnp.zeros((bsz,), jnp.int32)
    nls0 = jnp.zeros((), jnp.int32)  # total linesearch evals
    two_loop_b = jax.vmap(_two_loop, in_axes=(0, 0, 0, 0, None, None))

    def linesearch(x, f, g, direction, done, t0):
        gd = rowdot(g, direction)
        eps = ftol * jnp.maximum(1.0, jnp.abs(f))

        def body(carry):
            t, ok, j = carry
            fnew = fun_batched(x + t[:, None] * direction)
            fnew = jnp.where(jnp.isfinite(fnew), fnew, jnp.inf)
            ok_new = ok | (fnew <= f + c1 * t * gd + eps)
            tq = -gd * t * t / (2.0 * (fnew - f - gd * t))
            tq = jnp.where(jnp.isfinite(tq), tq, 0.0)
            tq = jnp.clip(tq, 0.1 * t, 0.5 * t)
            return jnp.where(ok_new, t, tq), ok_new, j + 1

        def cond(carry):
            _, ok, j = carry
            return jnp.any(~ok) & (j < max_linesearch)

        t, ok, j = lax.while_loop(cond, body, (t0, done, 0))
        return t, ok, j

    ls_hist0 = jnp.zeros((max_iters,), jnp.int32)  # evals per outer iteration

    def step(carry):
        state, iters, nls, ls_hist = carry
        done = state.converged | state.failed
        direction = -two_loop_b(state.g, state.s_hist, state.y_hist,
                                state.rho_hist, state.k, m)
        descent = rowdot(state.g, direction) < 0.0
        direction = jnp.where(descent[:, None], direction, -state.g)
        has_hist = jnp.any(state.rho_hist > 0.0, axis=-1)
        t0 = jnp.where(
            has_hist & descent,
            jnp.minimum(1.0, 4.0 * state.tprev),
            1.0 / jnp.maximum(1.0, rownorm(direction)),
        ).astype(dtype)
        t, ok, n_ls = linesearch(state.x, state.f, state.g, direction, done, t0)
        x_new = state.x + t[:, None] * direction
        f_new, g_new = vg(x_new)
        s = x_new - state.x
        y = g_new - state.g
        sy = rowdot(s, y)
        slot = state.k % m
        accept = (
            ok
            & (f_new <= state.f + ftol * jnp.maximum(1.0, jnp.abs(state.f)))
            & ~done
        )
        good_pair = (sy > 1e-10) & accept
        upd = lambda hist, v: hist.at[:, slot].set(
            jnp.where(good_pair[:, None], v, hist[:, slot]))
        s_hist = upd(state.s_hist, s)
        y_hist = upd(state.y_hist, y)
        rho_hist = state.rho_hist.at[:, slot].set(
            jnp.where(good_pair, 1.0 / jnp.maximum(sy, 1e-30),
                      state.rho_hist[:, slot]))
        x_out = jnp.where(accept[:, None], x_new, state.x)
        f_out = jnp.where(accept, f_new, state.f)
        g_out = jnp.where(accept[:, None], g_new, state.g)
        conv = state.converged | (rownorm(g_out) < tol * jnp.maximum(1.0, rownorm(x_out)))
        conv = conv | (accept & (state.f - f_new <= ftol * jnp.maximum(1.0, jnp.abs(f_new))))
        new_state = _State(
            k=state.k + 1, x=x_out, f=f_out, g=g_out,
            s_hist=s_hist, y_hist=y_hist, rho_hist=rho_hist,
            converged=conv, failed=state.failed | (~ok & ~conv & ~done),
            tprev=jnp.where(accept, t, state.tprev))
        iters = jnp.where(done, iters, state.k + 1)
        ls_hist = ls_hist.at[state.k].set(n_ls)
        return new_state, iters, nls + n_ls, ls_hist

    def cond(carry):
        state, _, _, _ = carry
        return (state.k < max_iters) & jnp.any(~(state.converged | state.failed))

    final, iters, nls, ls_hist = lax.while_loop(
        cond, step, (init, iters0, nls0, ls_hist0))
    return final, iters, nls, ls_hist


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--b", type=int, default=25088)
    ap.add_argument("--t", type=int, default=1000)
    ap.add_argument("--iters", type=int, default=60)
    args = ap.parse_args()

    b, t = args.b, args.t
    order = (1, 1, 1)
    print(f"devices: {jax.devices()}", file=sys.stderr)
    y = jnp.asarray(gen_arima_panel(b, t, seed=0))
    jax.block_until_ready(y)

    # objective exactly as models.arima._fit_program builds it (pallas path)
    @jax.jit
    def prep(yb):
        ya, nv0 = jax.vmap(align_right)(yb)
        yd = jax.vmap(lambda v: arima._difference(v, 1))(ya)
        nvd = nv0 - 1
        init = jax.vmap(
            lambda v, n: arima.hannan_rissanen(v, order, True, n))(yd, nvd)
        return yd, nvd, init

    yd, nvd, init = prep(y)
    jax.block_until_ready(init)
    t0 = time.perf_counter()
    out = prep(y)
    jax.block_until_ready(out)
    t_prep = time.perf_counter() - t0
    print(f"prep (align+diff+HR init): {t_prep*1e3:.1f} ms")
    n_eff = jnp.maximum(nvd - 1, 1).astype(yd.dtype)

    def fun_batched(P):
        return pk.css_neg_loglik(P, yd, order, True, nvd) / n_eff

    # -- per-pass costs ----------------------------------------------------
    fwd = jax.jit(lambda P: jnp.sum(fun_batched(P)))
    vgj = jax.jit(lambda P: jax.vjp(fun_batched, P)[1](jnp.ones((b,), yd.dtype))[0])
    fwd(init).block_until_ready()
    vgj(init).block_until_ready()
    N = 10
    t0 = time.perf_counter()
    for _ in range(N):
        fwd(init).block_until_ready()
    t_fwd = (time.perf_counter() - t0) / N
    t0 = time.perf_counter()
    for _ in range(N):
        vgj(init).block_until_ready()
    t_vg = (time.perf_counter() - t0) / N
    print(f"fwd pass: {t_fwd*1e3:.1f} ms   value+grad: {t_vg*1e3:.1f} ms")

    # -- instrumented full fit --------------------------------------------
    run = jax.jit(lambda x0: instrumented_lbfgs(
        fun_batched, x0, max_iters=args.iters, tol=1e-4))
    out = run(init)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = run(init)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    final, iters, nls, ls_hist = out
    print("ls evals per outer iter:", list(np.asarray(ls_hist)[:int(np.asarray(final.k))]))
    iters_np = np.asarray(iters)
    conv = np.asarray(final.converged)
    outer = int(np.asarray(final.k))
    n_ls = int(np.asarray(nls))
    print(f"fit wall: {dt:.3f}s  ({b/dt:.0f} series/s raw, "
          f"{b*conv.mean()/dt:.0f} converged-only)")
    print(f"outer iterations run: {outer}  (batch moves in lockstep)")
    print(f"converged frac: {conv.mean():.4f}  failed: {np.asarray(final.failed).mean():.4f}")
    print(f"linesearch evals total: {n_ls}  (avg {n_ls/max(outer,1):.2f}/iter)")
    print(f"objective passes: {n_ls} fwd (linesearch) + {outer+1} vg")
    est = n_ls * t_fwd + (outer + 1) * t_vg
    print(f"pass-cost model: {n_ls}x{t_fwd*1e3:.1f}ms + {outer+1}x{t_vg*1e3:.1f}ms"
          f" = {est:.3f}s  (measured {dt:.3f}s; rest = optimizer algebra)")
    qs = [50, 75, 90, 95, 99, 100]
    print("per-row iters quantiles:",
          {q: int(np.percentile(iters_np, q)) for q in qs})
    print("iters hist (converged rows):",
          np.histogram(iters_np[conv], bins=[0, 10, 20, 30, 40, 50, 60, 1000])[0])


if __name__ == "__main__":
    main()
