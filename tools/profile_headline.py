"""Profile the headline ARIMA fit: count objective evaluations per L-BFGS
iteration and measure per-pass costs on the real chip.

Diagnostic only (VERDICT round 2, next-round item 1a): quantify where the
headline latency goes so the optimizer levers (linesearch evals, fused
fwd+bwd, converged-row compaction) are applied where they pay.  Uses the
PRODUCTION optimizer's ``count_evals`` instrumentation — there is no forked
copy of the algorithm to drift out of date.

Usage: python tools/profile_headline.py [--b 25088] [--t 1000] [--iters 60]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from bench import gen_arima_panel
from spark_timeseries_tpu.models import arima
from spark_timeseries_tpu.models.base import maybe_align
from spark_timeseries_tpu.ops import pallas_kernels as pk
from spark_timeseries_tpu.utils import optim


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--b", type=int, default=25088)
    ap.add_argument("--t", type=int, default=1000)
    ap.add_argument("--iters", type=int, default=60)
    args = ap.parse_args()

    b, t = args.b, args.t
    order = (1, 1, 1)
    print(f"devices: {jax.devices()}", file=sys.stderr)
    y = jnp.asarray(gen_arima_panel(b, t, seed=0))
    jax.block_until_ready(y)

    # objective exactly as models.arima._fit_program builds it (pallas path,
    # dense panel)
    @jax.jit
    def prep(yb):
        ya, nv0 = maybe_align(yb, "dense")
        yd = jax.vmap(lambda v: arima._difference(v, 1))(ya)
        nvd = nv0 - 1
        init = pk.hr_init(yd, order, True, nvd)
        return yd, nvd, init

    def _sync(x):  # the tunnel's block_until_ready is a no-op
        float(jnp.sum(jnp.ravel(x)[:4]))

    yd, nvd, init = prep(y)
    _sync(init)
    t0 = time.perf_counter()
    out = prep(y)
    _sync(out[2])
    print(f"prep (diff + fused HR init): {(time.perf_counter() - t0) * 1e3:.1f} ms"
          " (includes one dispatch round trip)")
    n_eff = jnp.maximum(nvd - 1, 1).astype(yd.dtype)

    # data rides as jit ARGUMENTS throughout: a closure would embed the
    # panel as an HLO constant, which the tunnel's remote-compile endpoint
    # rejects (HTTP 413) at bench sizes
    def objective(P, yd, nvd, n_eff):
        return pk.css_neg_loglik(P, yd, order, True, nvd) / n_eff

    # -- per-pass costs (dispatch round trip included) ---------------------
    fwd = jax.jit(lambda P, yd, nvd, ne: jnp.sum(objective(P, yd, nvd, ne)))
    vgj = jax.jit(lambda P, yd, nvd, ne: jax.vjp(
        lambda P_: objective(P_, yd, nvd, ne), P)[1](jnp.ones((b,), yd.dtype))[0])

    _sync(fwd(init, yd, nvd, n_eff))
    _sync(vgj(init, yd, nvd, n_eff))
    N = 10
    t0 = time.perf_counter()
    for _ in range(N):
        _sync(fwd(init, yd, nvd, n_eff))
    t_fwd = (time.perf_counter() - t0) / N
    t0 = time.perf_counter()
    for _ in range(N):
        _sync(vgj(init, yd, nvd, n_eff))
    t_vg = (time.perf_counter() - t0) / N
    print(f"fwd pass: {t_fwd*1e3:.1f} ms   value+grad: {t_vg*1e3:.1f} ms "
          "(each includes one ~120 ms dispatch round trip)")

    # -- instrumented full fit (the PRODUCTION optimizer) ------------------
    run = jax.jit(lambda x0, yd, nvd, ne: optim.minimize_lbfgs_batched(
        lambda P: objective(P, yd, nvd, ne), x0,
        max_iters=args.iters, tol=1e-4, count_evals=True))
    out = run(init, yd, nvd, n_eff)
    _sync(out[0].x)
    t0 = time.perf_counter()
    res, info = run(init, yd, nvd, n_eff)
    _sync(res.x)
    dt = time.perf_counter() - t0
    iters_np = np.asarray(res.iters)
    conv = np.asarray(res.converged)
    outer = int(iters_np.max())
    ls = np.asarray(info["ls_evals"])[:outer]
    n_ls = int(ls.sum())
    print(f"fit wall: {dt:.3f}s  ({b/dt:.0f} series/s raw, "
          f"{b*conv.mean()/dt:.0f} converged-only)")
    print(f"outer iterations run: {outer}  (batch moves in lockstep)")
    print(f"converged frac: {conv.mean():.4f}")
    print(f"ls evals per outer iter: {ls.tolist()}")
    print(f"linesearch evals total: {n_ls}  (avg {n_ls/max(outer,1):.2f}/iter)")
    print(f"objective passes: {n_ls} fwd (linesearch) + {outer+1} vg")
    if int(info["cap"]):
        print(f"compaction: engaged at iter {int(info['compact_at'])} "
              f"(cap {int(info['cap'])})")
    else:
        print("compaction: not enabled in this tool (no straggler_fun)")
    qs = [50, 75, 90, 95, 99, 100]
    print("per-row iters quantiles:",
          {q: int(np.percentile(iters_np, q)) for q in qs})


if __name__ == "__main__":
    main()
