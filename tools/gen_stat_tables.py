"""Generate finite-sample quantile tables for the ADF and KPSS tests.

The reference embeds MacKinnon's published interpolation tables
(``TimeSeriesStatisticalTests.scala`` — SURVEY.md §2.2).  Instead of copying
half-remembered constants, this script reproduces the tables the way
MacKinnon (1994, 2010) produced them: simulate the null distribution of the
test statistic at a grid of sample sizes, take empirical quantiles, and embed
the results as literals in ``spark_timeseries_tpu/stats/_tables.py``.

Validation: the largest-n row must land within Monte-Carlo error of the
published asymptotic values (Fuller 1976 / MacKinnon 2010 for tau;
Kwiatkowski et al. 1992 Table 1 for KPSS) — asserted below before writing.

Run: ``python tools/gen_stat_tables.py [--reps 200000] [--out PATH]``
(pure numpy, single process, ~10-20 min at the default replication count).
"""

import argparse
import sys
import time

import numpy as np

PROBS = np.array(
    [0.01, 0.025, 0.05, 0.10, 0.20, 0.30, 0.40, 0.50,
     0.60, 0.70, 0.80, 0.90, 0.95, 0.975, 0.99]
)
NS = np.array([25, 50, 100, 250, 500, 2000])
MAX_LAG = 0  # DF statistic (MacKinnon tables are likewise DF-based).  The
# consumer maps an AUGMENTED regression onto these rows through its row
# count: stats.tests.adftest passes n_eff = regression rows + 1, so lag
# augmentation shrinks the effective sample exactly as it shrinks dof.

# published asymptotic checks (prob -> tau), Fuller 1976 / MacKinnon 2010
_DF_ASY = {
    "nc": {0.01: -2.57, 0.05: -1.94, 0.10: -1.62},
    "c": {0.01: -3.43, 0.05: -2.86, 0.10: -2.57},
    "ct": {0.01: -3.96, 0.05: -3.41, 0.10: -3.13},
}
# KPSS upper-tail critical values (eta), Kwiatkowski et al. 1992 Table 1
_KPSS_ASY = {
    "c": {0.10: 0.347, 0.05: 0.463, 0.01: 0.739},
    "ct": {0.10: 0.119, 0.05: 0.146, 0.01: 0.216},
}


def df_tau_sample(n, regression, reps, rng, chunk=20000):
    """tau = gamma_hat/se from dy_t = [det] + gamma*y_{t-1} + e_t under a
    pure random walk null."""
    taus = np.empty(reps)
    done = 0
    while done < reps:
        r = min(chunk, reps - done)
        e = rng.standard_normal((r, n))
        y = np.cumsum(e, axis=1)
        dy = y[:, 1:] - y[:, :-1]
        target = dy  # [r, n-1]
        rows = target.shape[1]
        cols = [y[:, :-1]]
        if regression in ("c", "ct"):
            cols.append(np.ones((r, rows)))
        if regression == "ct":
            cols.append(np.broadcast_to(np.arange(rows, dtype=float), (r, rows)))
        X = np.stack(cols, axis=2)  # [r, rows, k]
        XtX = np.einsum("rik,rim->rkm", X, X)
        Xty = np.einsum("rik,ri->rk", X, target)
        beta = np.linalg.solve(XtX, Xty[..., None])[..., 0]
        resid = target - np.einsum("rik,rk->ri", X, beta)
        dof = rows - X.shape[2]
        sigma2 = np.einsum("ri,ri->r", resid, resid) / dof
        XtX_inv00 = np.linalg.inv(XtX)[:, 0, 0]
        taus[done : done + r] = beta[:, 0] / np.sqrt(sigma2 * XtX_inv00)
        done += r
    return taus


def kpss_eta_sample(n, regression, reps, rng, chunk=50000):
    """eta under the stationarity null (iid standard normal), using the same
    Bartlett bandwidth rule as ``stats.tests.kpsstest``."""
    lags = int(12 * (n / 100.0) ** 0.25)
    etas = np.empty(reps)
    done = 0
    t = np.arange(n, dtype=float)
    if regression == "ct":
        X = np.stack([np.ones(n), t], axis=1)
        # hat matrix residual-maker applied per replication via lstsq solve
        XtX_inv = np.linalg.inv(X.T @ X)
    while done < reps:
        r = min(chunk, reps - done)
        y = rng.standard_normal((r, n))
        if regression == "c":
            e = y - y.mean(axis=1, keepdims=True)
        else:
            beta = (y @ X) @ XtX_inv  # [r, 2]
            e = y - beta @ X.T
        s = np.cumsum(e, axis=1)
        lrv = np.einsum("ri,ri->r", e, e) / n
        for k in range(1, lags + 1):
            w = 1.0 - k / (lags + 1.0)
            lrv += 2.0 * w * np.einsum("ri,ri->r", e[:, k:], e[:, :-k]) / n
        etas[done : done + r] = np.einsum("ri,ri->r", s, s) / (n * n * lrv)
        done += r
    return etas


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=200_000)
    ap.add_argument("--out", default="spark_timeseries_tpu/stats/_tables.py")
    ap.add_argument("--seed", type=int, default=20260730)
    args = ap.parse_args()

    rng = np.random.default_rng(args.seed)
    df_tables = {}
    for reg in ("nc", "c", "ct"):
        rows = []
        for n in NS:
            t0 = time.time()
            taus = df_tau_sample(int(n), reg, args.reps, rng)
            q = np.quantile(taus, PROBS)
            rows.append(q)
            print(f"DF {reg} n={n}: 1%={q[0]:.3f} 5%={q[2]:.3f} "
                  f"10%={q[3]:.3f} ({time.time()-t0:.1f}s)", flush=True)
        df_tables[reg] = np.array(rows)  # [len(NS), len(PROBS)]

    kpss_tables = {}
    for reg in ("c", "ct"):
        rows = []
        for n in NS:
            t0 = time.time()
            etas = kpss_eta_sample(int(n), reg, args.reps, rng)
            q = np.quantile(etas, PROBS)
            rows.append(q)
            print(f"KPSS {reg} n={n}: 90%={q[11]:.3f} 95%={q[12]:.3f} "
                  f"99%={q[14]:.3f} ({time.time()-t0:.1f}s)", flush=True)
        kpss_tables[reg] = np.array(rows)

    # -- validate the largest-n row against published asymptotics ----------
    tol = 0.06  # MC error + finite-n-at-2000 drift
    for reg, checks in _DF_ASY.items():
        for p, want in checks.items():
            got = df_tables[reg][-1, np.argmin(np.abs(PROBS - p))]
            assert abs(got - want) < tol, (reg, p, got, want)
    for reg, checks in _KPSS_ASY.items():
        for p, want in checks.items():
            got = kpss_tables[reg][-1, np.argmin(np.abs(PROBS - (1 - p)))]
            assert abs(got - want) < 0.07 * want, (reg, p, got, want)
    print("asymptotic validation passed")

    def fmt(a):
        if a.ndim == 1:
            return "[" + ", ".join(f"{v:.4f}" for v in a) + "]"
        return "[\n" + "\n".join("        " + fmt(r) + "," for r in a) + "\n    ]"

    with open(args.out, "w") as f:
        f.write('"""Finite-sample quantile tables for ADF and KPSS p-values.\n\n')
        f.write("AUTO-GENERATED by tools/gen_stat_tables.py — do not edit.\n")
        f.write(f"Monte-Carlo: {args.reps} replications per cell, "
                f"seed {args.seed};\nlargest-n row validated against the "
                "published asymptotic tables\n(Fuller 1976 / MacKinnon 2010; "
                'Kwiatkowski et al. 1992).\n"""\n\n')
        f.write("import numpy as np\n\n")
        f.write(f"PROBS = np.array({fmt(PROBS)})\n\n")
        f.write(f"NS = np.array({fmt(NS.astype(float))})\n\n")
        f.write("# tau quantiles [len(NS), len(PROBS)] per regression kind\n")
        f.write("DF_TAU = {\n")
        for reg, tab in df_tables.items():
            f.write(f'    "{reg}": np.array({fmt(tab)}),\n')
        f.write("}\n\n")
        f.write("# eta quantiles [len(NS), len(PROBS)] per regression kind\n")
        f.write("KPSS_ETA = {\n")
        for reg, tab in kpss_tables.items():
            f.write(f'    "{reg}": np.array({fmt(tab)}),\n')
        f.write("}\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    sys.exit(main())
