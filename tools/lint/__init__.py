"""ststpu-lint: project-specific static analysis for spark-timeseries-tpu.

Eleven PRs in, the system's correctness rests on cross-cutting contracts
no general-purpose linter knows about — bitwise reproducibility, the
journal's single-writer-per-namespace protocol, deliberate config-hash
knob exclusions, obs-off-by-default inertness, zero implicit host syncs
in the chunk walk, and lock discipline across committer / prefetcher /
lane / server threads.  Every one of them has been broken silently at
least once (the PR 8 winners regression, the PR 7 CPU zero-copy aliasing
bug, the PR 6 unguarded pool-registry iteration).  This package makes
them machine-checked: one AST checker per load-bearing contract, run as

    python -m tools.lint [--json] [--baseline LINT_BASELINE.json]
    python -m tools.lint --explain <rule>
    python -m tools.lint --self-test

Rules (see ``--explain`` for the full contract text and waiver syntax):

- ``host-sync``      implicit device->host syncs in hot-path modules
- ``config-hash``    journal config-hash coverage of every driver knob
- ``journal-writer`` file writes only from registered owner call sites
- ``lock-map``       declared per-class lock protection maps, honored
- ``obs-inert``      obs reached only through the guarded facade
- ``nondet``         wall-clock / RNG / hash-order bans in bitwise code

A genuine-but-deliberate violation carries an inline waiver comment
``# lint: <rule>(<reason>)`` on the flagged line or the line above; the
reason is mandatory and waivers that no longer cover a finding are
themselves flagged (``stale-waiver``) so they cannot rot in place.

``LINT_BASELINE.json`` (repo root) pins known findings: new findings
fail, baselined ones are tracked to zero.  The committed baseline is
EMPTY — every real violation the suite surfaced was fixed or waived.

The runtime companion (:mod:`tools.lint.runtime`) enforces the lock-map
contract dynamically: it instruments the declared classes with
owner-tracking lock proxies and asserts, on a real pipelined + sharded +
serving walk, that every declared attribute mutation happens under its
declared lock (``tests/_lockdiscipline_worker.py --smoke`` in ci.sh).
"""

from .engine import (Finding, LintModule, Waiver, collect_waivers,
                     lint_paths, lint_source, load_baseline)

__all__ = [
    "Finding",
    "LintModule",
    "Waiver",
    "collect_waivers",
    "lint_paths",
    "lint_source",
    "load_baseline",
]
