"""CLI: ``python -m tools.lint`` / the ``ststpu-lint`` console script.

Exit codes: 0 clean (no new findings), 1 new findings (or a failed
self-test), 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import checkers as checkers_mod
from .engine import (DEFAULT_BASELINE, REPO_ROOT, diff_baseline,
                     lint_paths, load_baseline, save_baseline)


def _explain(rule: str) -> int:
    rules = dict(checkers_mod.ENGINE_RULES)
    for name, mod in checkers_mod.RULES.items():
        rules[name] = (mod.__doc__ or "").strip()
    if rule == "all":
        for name in sorted(rules):
            print(f"== {name} " + "=" * max(0, 66 - len(name)))
            print(rules[name])
            print()
        return 0
    if rule not in rules:
        print(f"unknown rule {rule!r}; known: {', '.join(sorted(rules))}",
              file=sys.stderr)
        return 2
    print(rules[rule])
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ststpu-lint",
        description="Project-specific invariant linter for "
                    "spark-timeseries-tpu (see --explain all).")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: LINT_BASELINE.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--explain", metavar="RULE",
                    help="print a rule's contract text ('all' for every "
                         "rule) and exit")
    ap.add_argument("--self-test", action="store_true",
                    help="verify every checker catches its seeded "
                         "violation (ci.sh runs this before the lint so "
                         "a broken checker cannot pass vacuously)")
    ap.add_argument("--show-waived", action="store_true",
                    help="also list findings suppressed by waivers")
    args = ap.parse_args(argv)

    if args.explain:
        return _explain(args.explain)

    if args.self_test:
        from .selftest import run_self_test

        failures = run_self_test()
        if failures:
            for f in failures:
                print(f"self-test FAIL: {f}", file=sys.stderr)
            return 1
        print("self-test: all checkers catch their seeded violations; "
              "waiver + baseline machinery OK")
        return 0

    findings = lint_paths(REPO_ROOT, args.paths or None)
    if args.write_baseline:
        if args.paths:
            # a subset scan would TRUNCATE the baseline to the subset's
            # findings, and the next full run would report everything
            # else as new — refuse instead of corrupting
            print("--write-baseline requires a full scan; drop the "
                  "explicit paths", file=sys.stderr)
            return 2
        save_baseline(findings, args.baseline)
        print(f"baseline written: {args.baseline}")
        return 0
    baseline = load_baseline(args.baseline)
    new, known, prunable = diff_baseline(findings, baseline)
    waived = [f for f in findings if f.waived]

    if args.as_json:
        print(json.dumps({
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in known],
            "waived": [f.to_dict() for f in waived],
            "baseline_prunable": prunable,
            "counts": {"new": len(new), "baselined": len(known),
                       "waived": len(waived),
                       "baseline_prunable": len(prunable)},
            "ok": not new,
        }, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if known:
        print(f"-- {len(known)} baselined finding(s) still present "
              "(tracked to zero; do not add more)")
        for f in known:
            print(f"   {f.render()}")
    if prunable:
        print(f"-- {len(prunable)} baseline entr(y/ies) no longer fire — "
              "prune with --write-baseline:")
        for k in prunable:
            print(f"   {k}")
    if args.show_waived and waived:
        print(f"-- {len(waived)} waived finding(s):")
        for f in waived:
            print(f"   {f.render()}")
    if new:
        print(f"\nststpu-lint: {len(new)} NEW finding(s).  Run "
              "`python -m tools.lint --explain <rule>` for the contract "
              "and the waiver syntax.")
        return 1
    n_files = "package"
    print(f"ststpu-lint: clean ({n_files}; {len(waived)} waived, "
          f"{len(known)} baselined)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
