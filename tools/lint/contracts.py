"""Machine-readable contract registries for the invariant linter.

This module is the single place where the codebase's cross-cutting
contracts are written down as DATA: which modules are hot paths, which
are bitwise-critical, which driver knobs are deliberately excluded from
the journal config hash (and why), which call sites own file writes, and
which classes the runtime lock-discipline tracker instruments.  Every
entry carries a rationale — adding to a registry is an explicit,
reviewable act, never a silent drift.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# host-sync (rule: host-sync)
# ---------------------------------------------------------------------------

# Modules on the chunk walk's critical path: an implicit device->host
# sync here stalls the pipeline (stage N+1 / compute N / commit N-1) for
# a full dispatch round trip.  Deliberate syncs (the commit fetch, the
# staging materialization barrier) carry inline waivers naming the
# reason.
HOT_PATH_PREFIXES = (
    # the reliability/ prefix covers the whole chunk driver, including
    # the delta planner (delta.py, ISSUE 15) whose commit-path chunk
    # fingerprinting and WarmstartFit wrapper run inside the pipelined
    # walk — its one deliberate sync carries an inline waiver
    "spark_timeseries_tpu/reliability/",
    "spark_timeseries_tpu/models/",
    "spark_timeseries_tpu/utils/optim.py",
    # the forecast walk's kernels and chunk program run INSIDE the
    # pipelined walk — an implicit sync there stalls stage/compute/commit
    # exactly like a model fit would (backtest/ensemble drivers assemble
    # host-side between walks and are not hot)
    "spark_timeseries_tpu/forecasting/kernels.py",
    "spark_timeseries_tpu/forecasting/walk.py",
)

# ---------------------------------------------------------------------------
# nondeterminism (rule: nondet)
# ---------------------------------------------------------------------------

# Bitwise-critical modules: everything whose output must be reproducible
# byte-for-byte across runs, resumes, and shard layouts.  The telemetry
# plane (obs/) and the serving layer's wall-clock machinery (deadlines,
# retry_after estimates) are inherently time-dependent and exempt;
# manifest timestamps and run ids inside critical modules are identity /
# telemetry metadata and carry inline waivers.
NONDET_EXEMPT_PREFIXES = (
    "spark_timeseries_tpu/obs/",
    "spark_timeseries_tpu/serving/",
)

# ---------------------------------------------------------------------------
# config-hash coverage (rule: config-hash)
# ---------------------------------------------------------------------------

# Every keyword a fit-entry surface accepts must be either REACHABLE by
# the journal's config hash (it changes what a chunk's bytes mean) or
# EXCLUDED here with a rationale (it moves work between threads /
# devices / wall-clock budgets without changing a byte).  A new knob
# that appears in a signature without an entry FAILS the lint — it can
# no longer silently fork journal compatibility.
#
# "hashed" entries say HOW the knob reaches the journal identity; for
# ``fit_chunked`` the checker additionally verifies each hashed driver
# knob appears as a literal key of the ``extra=`` dict passed to
# ``config_hash`` in the source (or rides the **fit_kwargs catch-all /
# panel fingerprint), so this registry cannot drift from the code.
CONFIG_HASH_SURFACES = {
    "spark_timeseries_tpu/reliability/chunked.py::fit_chunked": {
        "kwargs_param": "fit_kwargs",  # hashed wholesale by config_hash
        "hashed": {
            "fit_fn": "function identity + functools.partial layers",
            "y": "panel fingerprint (content-sampled), not the config hash",
            "chunk_rows": "extra= key 'chunk_rows'",
            "min_chunk_rows": "extra= key 'min_chunk_rows'",
            "resilient": "extra= key 'resilient'",
            "policy": "extra= key 'policy'",
            "ladder": "extra= key 'ladder'",
            "align_mode": "resolved mode injected into fit_kwargs before "
                          "config_hash — a resumed run must use the same "
                          "static plan",
            "delta_warmstart": "warm mode resolves into the WarmstartFit "
                               "wrapper (a DIFFERENT fit_fn identity) "
                               "over the augmented init-column panel (a "
                               "DIFFERENT fingerprint) — both reach the "
                               "journal identity; the flag only selects "
                               "that resolution",
        },
        # keys that are extra= literals but not signature params (the
        # checker uses this to verify the extra dict exactly)
        "extra_keys": ("chunk_rows", "min_chunk_rows", "resilient",
                       "policy", "ladder"),
        "excluded": {
            "max_backoffs": "bounds how many OOM halvings are ATTEMPTED "
                            "before raising; committed boundaries land on "
                            "the same grid either way and the journal "
                            "accepts mixed boundaries on resume",
            "checkpoint_dir": "the journal's LOCATION, not its identity — "
                              "the same job may be journaled anywhere",
            "resume": "selects adoption behavior for existing state; "
                      "never changes what a fresh chunk computes",
            "chunk_budget_s": "watchdog wall-clock budget; TIMEOUT rows "
                              "are per-run status, recomputed on resume — "
                              "a resumed run may use a different budget",
            "job_budget_s": "same as chunk_budget_s, job-level",
            "pipeline": "moves commit I/O to a background thread; bytes "
                        "unchanged — a serial journal resumes under a "
                        "pipelined run and vice versa (documented "
                        "contract)",
            "pipeline_depth": "bounds in-flight commits; same contract "
                              "as pipeline",
            "prefetch_depth": "bounds staged input slices; the staged "
                              "buffer is the same yb[lo:hi] bytes",
            "mesh": "device placement; the sharded walk is "
                    "bitwise-identical to single-device and a merged "
                    "manifest is adopted by a later single-device walk",
            "shard": "same contract as mesh",
            "lane_retries": "elastic containment: how often a failing "
                            "lane retries before quarantine — recovery "
                            "policy, not chunk content",
            "lane_retry_backoff_s": "retry pacing, wall-clock only",
            "rebalance_threshold": "when idle lanes steal a straggler's "
                                   "tail; spans move between lanes on "
                                   "the same chunk grid",
            "process_index": "journal NAMESPACE selection under "
                             "jax.distributed, not job identity",
            "grid": "auto-fit grid coordinate recorded in manifest "
                    "extra= for tooling; per-order walks hash their own "
                    "fit configs",
            "delta_from": "adoption SOURCE location (ISSUE 15): clean "
                          "chunks are spliced only when the prior "
                          "config hash equals this walk's and the rows "
                          "are fingerprint-identical, so the delta "
                          "result is bitwise the full walk's on the "
                          "same grid — provenance rides manifest "
                          "extra.delta, never the hash",
            "journal_extra": "opaque manifest extra= block, documented "
                             "as non-hashed provenance",
            "sink": "write-back DESTINATION (ISSUE 20): committed chunk "
                    "params stream out to a WritableChunkSource shard "
                    "dir instead of the in-host assembly — the journal "
                    "bytes are identical either way (the sink is fed "
                    "from the same committed arrays) and a sink walk "
                    "resumes a sinkless journal; provenance rides "
                    "manifest extra.sink, never the hash",
            "_journal_commit_hook": "fault-injection instrumentation "
                                    "(tests only)",
        },
    },
    "spark_timeseries_tpu/panel.py::TimeSeriesPanel.fit": {
        "kwargs_param": "fit_kwargs",
        "hashed": {
            "model": "resolved to the model module's fit function, whose "
                     "identity the config hash covers",
            "chunk_rows": "forwarded to fit_chunked (hashed there)",
            "resilient": "forwarded to fit_chunked (hashed there)",
            "policy": "forwarded to fit_chunked (hashed there)",
            "align_mode": "forwarded to fit_chunked (hashed there)",
            "delta_warmstart": "forwarded to fit_chunked (resolved into "
                               "the warm fit_fn + augmented fingerprint "
                               "there)",
        },
        "excluded": {
            "checkpoint_dir": "see fit_chunked",
            "resume": "see fit_chunked",
            "chunk_budget_s": "see fit_chunked",
            "job_budget_s": "see fit_chunked",
            "pipeline": "see fit_chunked",
            "pipeline_depth": "see fit_chunked",
            "prefetch_depth": "see fit_chunked",
            "shard": "see fit_chunked",
            "mesh": "see fit_chunked",
            "source": "placement spelling (in-HBM / host RAM / npz "
                      "shards); panel identity is carried by the "
                      "fingerprint, which follows the source domain",
            "delta_from": "see fit_chunked",
        },
    },
    "spark_timeseries_tpu/forecasting/walk.py::forecast_chunked": {
        "hashed": {
            "model": "reaches forecast_fit's `forecast_model` kwarg "
                     "(hashed wholesale by config_hash)",
            "fitted": "params + statuses become augmented-panel COLUMNS, "
                      "covered by the panel fingerprint",
            "y": "panel fingerprint (content-sampled augmented panel)",
            "horizon": "forecast_fit kwarg (hashed)",
            "model_kwargs": "normalized tuple, forecast_fit kwarg "
                            "(hashed)",
            "status": "augmented-panel status column, covered by the "
                      "panel fingerprint",
            "intervals": "forecast_fit kwarg (hashed)",
            "level": "forecast_fit kwarg (hashed)",
            "n_samples": "forecast_fit kwarg (hashed)",
            "seed": "resolved into base_seed, a forecast_fit kwarg "
                    "(hashed) — a different seed is a different interval "
                    "job",
            "chunk_rows": "forwarded to fit_chunked (hashed there)",
        },
        "excluded": {
            "checkpoint_dir": "see fit_chunked",
            "resume": "see fit_chunked",
            "chunk_budget_s": "see fit_chunked",
            "job_budget_s": "see fit_chunked",
            "pipeline": "see fit_chunked",
            "pipeline_depth": "see fit_chunked",
            "prefetch_depth": "see fit_chunked",
            "shard": "see fit_chunked",
            "mesh": "see fit_chunked",
            "sink": "write-back destination for the packed forecast "
                    "rows (ISSUE 20) — see fit_chunked; the published "
                    "shards are the same bytes split_forecast would "
                    "have unpacked in host RAM",
            "_journal_commit_hook": "fault-injection instrumentation "
                                    "(tests only)",
        },
    },
    "spark_timeseries_tpu/forecasting/backtest.py::run_backtest": {
        "hashed": {
            "model": "campaign_hash extra= key 'model' (and each "
                     "window's walk hashes its own fit config)",
            "y": "campaign panel_fingerprint (stale manifests rejected)",
            "horizon": "campaign_hash extra= key 'horizon'",
            "origins": "campaign_hash extra= key 'origins'",
            "n_windows": "resolved into origins (hashed)",
            "min_train": "resolved into origins (hashed)",
            "model_kwargs": "campaign_hash extra= key 'model_kwargs'",
            "fit_kwargs": "hashed wholesale through the campaign fit_fn "
                          "partial and each window walk's config hash",
            "warm_start": "campaign_hash extra= key 'warm_start' — warm "
                          "and cold windows fit different programs",
            "intervals": "campaign_hash extra= key 'intervals'",
            "level": "campaign_hash extra= key 'level'",
            "n_samples": "campaign_hash extra= key 'n_samples'",
            "seed": "campaign_hash extra= key 'seed'",
            "chunk_rows": "campaign_hash extra= key 'chunk_rows' (low "
                          "order bits follow the chunk grid, so metrics "
                          "identity requires the same grid)",
        },
        "excluded": {
            "checkpoint_dir": "the campaign's LOCATION, not its "
                              "identity (see fit_chunked)",
            "resume": "see fit_chunked",
            "pipeline": "see fit_chunked",
            "pipeline_depth": "see fit_chunked",
            "prefetch_depth": "see fit_chunked",
            "shard": "see fit_chunked",
            "mesh": "see fit_chunked",
            "chunk_budget_s": "see fit_chunked",
            "job_budget_s": "wall-clock bound; timed-out windows are "
                            "per-run status, retried on resume",
            "server": "routes window forecasts through a FitServer's "
                      "batching — placement, not content (batched == "
                      "solo bitwise is the server's contract)",
            "delta": "campaign ADOPTION switch (ISSUE 20): selects "
                     "whether a prior campaign's committed windows may "
                     "be spliced — adoption is gated on the "
                     "origin-independent window_config_hash plus a "
                     "prefix content digest, so an adopted window is "
                     "bitwise the recompute and the campaign_hash "
                     "identity is unchanged; provenance rides the "
                     "manifest's delta block, never the hash",
            "_journal_commit_hook": "fault-injection instrumentation "
                                    "(tests only)",
        },
    },
    "spark_timeseries_tpu/panel.py::TimeSeriesPanel.forecast": {
        "kwargs_param": "model_kwargs",
        "hashed": {
            "model": "forwarded to forecast_chunked (hashed there)",
            "horizon": "forwarded to forecast_chunked (hashed there)",
            "fitted": "forwarded to forecast_chunked (fingerprinted "
                      "there)",
            "status": "forwarded to forecast_chunked (fingerprinted "
                      "there)",
            "intervals": "forwarded to forecast_chunked (hashed there)",
            "level": "forwarded to forecast_chunked (hashed there)",
            "n_samples": "forwarded to forecast_chunked (hashed there)",
            "seed": "forwarded to forecast_chunked (hashed there)",
            "chunk_rows": "forwarded to fit_chunked (hashed there)",
        },
        "excluded": {
            "checkpoint_dir": "see fit_chunked",
            "resume": "see fit_chunked",
            "chunk_budget_s": "see fit_chunked",
            "job_budget_s": "see fit_chunked",
            "pipeline": "see fit_chunked",
            "pipeline_depth": "see fit_chunked",
            "prefetch_depth": "see fit_chunked",
            "shard": "see fit_chunked",
            "mesh": "see fit_chunked",
            "source": "placement spelling; panel identity is carried by "
                      "the augmented-panel fingerprint, which samples "
                      "VALUES in every residency",
            "_journal_commit_hook": "fault-injection instrumentation "
                                    "(tests only)",
        },
    },
    "spark_timeseries_tpu/serving/server.py::FitServer.submit_forecast": {
        "hashed": {
            "values": "augmented-panel fingerprint via the batch walk's "
                      "journal",
            "fitted": "params/status columns of the augmented panel "
                      "(fingerprinted)",
            "model": "rides as forecast_fit's `forecast_model` fit "
                     "kwarg (hashed)",
            "horizon": "forecast_fit kwarg (hashed)",
            "model_kwargs": "forecast_fit kwarg (hashed, JSON "
                            "canonicalized at admission)",
            "status": "augmented-panel status column (fingerprinted)",
            "intervals": "forecast_fit kwarg (hashed)",
            "level": "forecast_fit kwarg (hashed)",
            "n_samples": "forecast_fit kwarg (hashed)",
            "seed": "resolved into base_seed, a forecast_fit kwarg "
                    "(hashed)",
        },
        "excluded": {
            "tenant": "admission/quota identity (see FitServer.submit)",
            "priority": "shedding order under overload; never reaches "
                        "the walk",
            "deadline_s": "per-request wall-clock deadline (watchdog "
                          "contract)",
            "request_id": "idempotency identity for the durable record",
        },
    },
    "spark_timeseries_tpu/serving/server.py::FitServer.submit": {
        "kwargs_param": "fit_kwargs",
        "hashed": {
            "values": "batched panel fingerprint (cell-padded grid), via "
                      "the batch walk's journal",
            "model": "part of the batch key AND the walk's fit_fn "
                     "identity",
        },
        "excluded": {
            "tenant": "admission/quota identity; rides the durable "
                      "request record and the batch_id digest, not the "
                      "walk config",
            "priority": "shedding order under overload; never reaches "
                        "the walk",
            "deadline_s": "per-request wall-clock deadline (watchdog "
                          "contract: TIMEOUT rows, recomputed on "
                          "re-answer)",
            "request_id": "idempotency identity for the durable record",
            "warm_routing": "routing-mode selection for panel_auto "
                            "(ISSUE 19): rides the durable request "
                            "record (injected into fit_kwargs) so "
                            "recovery re-routes identically, and is "
                            "POPPED before the search — each route "
                            "leg's walks hash their own fit configs, "
                            "exact mode (False) is bitwise the plain "
                            "exhaustive search, and the decision is "
                            "recorded in the result meta + trace, "
                            "never silent",
        },
    },
    "spark_timeseries_tpu/models/auto.py::auto_fit": {
        "kwargs_param": "fit_kwargs",  # rides every order walk's
        # fit partial, hashed wholesale by each walk's config_hash
        "hashed": {
            "y": "panel fingerprint of every per-order / fused walk",
            "orders": "the candidate grid: each order resolves into its "
                      "walk's fit_fn identity (order= partial kwarg) and "
                      "grid coordinate",
            "include_intercept": "rides every order walk's fit partial "
                                 "(hashed there); also sets k",
            "stage1_iters": "stage-1 sweeps run max_iters=stage1_iters "
                            "through the walk's fit kwargs (hashed "
                            "there)",
            "chunk_rows": "forwarded to fit_chunked (hashed there)",
            "resilient": "forwarded to fit_chunked (hashed there)",
            "policy": "forwarded to fit_chunked (hashed there)",
            "align_mode": "forwarded to fit_chunked (hashed there)",
        },
        "excluded": {
            "criterion": "selection-time ranking over journaled "
                         "per-order results, recomputed on resume — a "
                         "changed criterion re-selects (and, stepwise, "
                         "re-expands) from the SAME journaled walks; "
                         "per-order walk identity is unchanged",
            "stage2": "selects the walk PLAN (full sweeps vs stage-1 "
                      "sweeps + basin refits); each walk hashes its own "
                      "config and journals under a distinct namespace "
                      "(grid_*_s1), so mixed modes never collide",
            "fuse": "fusion grouping moves orders between dispatches "
                    "without changing per-(row, order) trajectories — "
                    "the fused demux is pinned bitwise against per-order "
                    "walks; groups journal under the leader's grid dir",
            "stepwise": "selects the Hyndman-Khandakar expansion plan "
                        "(ISSUE 19): passes journal under their own "
                        "stepwise_%02d namespaces (never colliding with "
                        "an exhaustive search in the same root), each "
                        "trial order's walk hashes its own config, and "
                        "the searched grid is recorded in the auto "
                        "manifest's stepwise block",
            "stepwise_max_passes": "bounds expansion rounds; journaled "
                                   "passes replay deterministically on "
                                   "resume and a raised cap only "
                                   "appends passes",
            "stepwise_max_order": "bounds the expansion neighborhood; "
                                  "the frontier is a deterministic "
                                  "function of the journaled results "
                                  "under the cap, recorded per pass in "
                                  "the auto manifest",
            "return_criteria": "host-side return shape only",
            "checkpoint_dir": "see fit_chunked",
            "resume": "see fit_chunked",
            "chunk_budget_s": "see fit_chunked",
            "job_budget_s": "see fit_chunked",
            "pipeline": "see fit_chunked",
            "pipeline_depth": "see fit_chunked",
            "prefetch_depth": "see fit_chunked",
            "shard": "see fit_chunked",
            "mesh": "see fit_chunked",
            "_journal_commit_hook": "fault-injection instrumentation "
                                    "(tests only)",
        },
    },
}

# ---------------------------------------------------------------------------
# file-write ownership (rule: journal-writer)
# ---------------------------------------------------------------------------

# The journal's single-writer protocol generalized: every call site in
# the library that writes a file is registered here with the namespace
# it owns.  A helper that splices bytes into someone else's namespace
# (the failure mode this guards: a future utility writing under a
# journal root next to ChunkJournal's manifest) fails the lint until it
# is either routed through the owner or registered as one.
FILE_WRITE_OWNERS = {
    "spark_timeseries_tpu/reliability/journal.py": {
        "durable_replace": "THE durable-file primitive (tmp->fsync->"
                           "replace, hidden-orphan crash semantics) "
                           "every journal-side owner and the npz append "
                           "helpers route through",
        "_atomic_write_bytes": "the shared byte-payload wrapper over "
                               "durable_replace",
        "ChunkJournal": "sole writer of its namespace's shards + manifest "
                        "(one instance per namespace; the pipelined "
                        "committer calls INTO this owner)",
        "merge_job_manifest": "sole writer of the merged root "
                              "manifest.json after sharded lanes join",
        "Lease": "writer of the root's lease.json heartbeat record "
                 "(ISSUE 16): one holder per root by construction — the "
                 "fencing token in the record is what every OTHER "
                 "durable writer on the root checks before splicing",
        "acquire_lease": "sole creator of lease_claims/ claim manifests "
                         "(O_CREAT|O_EXCL: the filesystem arbitrates "
                         "token allocation, so claims are never "
                         "overwritten, only created)",
        "tear_after_replace": "the disk-fault seam's torn-fsync "
                              "primitive (ISSUE 17): DELIBERATELY "
                              "truncates a just-replaced file to "
                              "simulate a lying fsync — invoked only "
                              "when an injected fault schedule says "
                              "'torn', never on an unfaulted root",
    },
    "spark_timeseries_tpu/reliability/source.py": {
        "write_npz_shards": "explicit export utility: creates a brand-new "
                            "shard directory it alone owns — and (ISSUE "
                            "15) extends one in place: append_rows adds "
                            "NEW part_* files, append_time atomically "
                            "rewrites each shard with its new columns "
                            "(the NpzShardSource append helpers route "
                            "through here)",
        "write_parquet_shards": "the parquet sibling (ISSUE 20): sole "
                                "writer of a parquet shard directory — "
                                "fresh writes and the append_rows/"
                                "append_time extensions all land via "
                                "the journal's durable-replace "
                                "primitive, one file per shard "
                                "(ParquetShardSource only READS)",
    },
    "spark_timeseries_tpu/reliability/delta.py": {
        "plan_delta": "READS prior shards only; the delta walk's "
                      "adopted-chunk splice is committed exclusively "
                      "through ChunkJournal.adopt_chunks (the namespace "
                      "owner's batched commit: shards durable first, "
                      "ONE manifest update) — this module performs no "
                      "direct writes, registered so the ownership of "
                      "the manifest splice is written down",
    },
    "spark_timeseries_tpu/reliability/sink.py": {
        "WritableChunkSource": "sole writer of its own output shard "
                               "directory (ISSUE 20): one background "
                               "worker drains the bounded write queue, "
                               "each committed chunk lands as an "
                               "out_<lo>_<hi>.npz via the journal's "
                               "durable-replace primitive, and finalize "
                               "alone writes sink_manifest.json after "
                               "sweeping orphans — the walk's journal "
                               "namespace is never touched",
    },
    "spark_timeseries_tpu/reliability/faultinject.py": {
        "tear_file": "the fault harness DELIBERATELY corrupts a named "
                     "file to simulate a torn write — test-only, "
                     "operator-invoked, never on a live namespace",
    },
    "spark_timeseries_tpu/reliability/chaos.py": {
        "write_chaos_manifest": "sole writer of chaos_manifest.json at "
                                "the fleet root (via the journal's "
                                "atomic byte-payload primitive): the "
                                "scenario's durable record — schedule, "
                                "probe timeline, invariant verdicts — "
                                "for advise_budget and post-mortems",
    },
    "spark_timeseries_tpu/obs/promsink.py": {
        "PromTextfileSink": "sole writer of its textfile path (atomic "
                            "replace; scrapers never see a torn file)",
    },
    "spark_timeseries_tpu/obs/recorder.py": {
        "FlightRecorder": "sole writer of its JSONL stream and "
                          "crash-dump path",
    },
    "spark_timeseries_tpu/serving/session.py": {
        "FitRequest.save": "write-ahead request record under the "
                           "server's requests/ namespace (one file per "
                           "request id)",
    },
    "spark_timeseries_tpu/serving/server.py": {
        "FitServer": "owner of the serving root's results/, knobs.json "
                     "and server.json; batch WALK journals under "
                     "batches/ are written by ChunkJournal, never here",
    },
    "spark_timeseries_tpu/serving/profiles.py": {
        "TenantProfileStore": "sole writer of the serving root's "
                              "profiles/ namespace (ISSUE 19): one npz "
                              "per tenant via journal.durable_replace, "
                              "fenced on fleet roots exactly like the "
                              "result store — standbys and tools only "
                              "READ profiles",
    },
    "spark_timeseries_tpu/serving/tickloop.py": {
        "TickLoop": "sole writer of its loop root (ISSUE 20): "
                    "tickloop.json, each cycle's ticks.npz (tmp+fsync+"
                    "replace) and tick_manifest.json — the data shards "
                    "are extended only through the source module's "
                    "append owners, the fit/forecast journals belong "
                    "to ChunkJournal, and the published forecasts to "
                    "the cycle's WritableChunkSource",
    },
    "spark_timeseries_tpu/serving/batcher.py": {
        "MicroBatch": "durable batch-membership records under the batch "
                      "journal directory it names (batch_id digest)",
    },
    "spark_timeseries_tpu/serving/transport.py": {
        "TransportServer": "the socket front end performs NO durable "
                           "writes of its own (ISSUE 16): request "
                           "records land via FitRequest.save inside the "
                           "backend's submit, results via the fenced "
                           "FitServer._store_result — registered so the "
                           "zero-direct-write contract of the wire "
                           "layer is written down; a future handler "
                           "that opens a file under the root fails the "
                           "lint until routed through an owner",
        "encode_request_blob": "np.savez into an in-memory BytesIO — "
                               "wire encoding of the durable request "
                               "spelling, never a filesystem write",
        "encode_result_blob": "np.savez into an in-memory BytesIO — "
                              "wire encoding of the stored-result "
                              "spelling, never a filesystem write",
    },
    "spark_timeseries_tpu/serving/client.py": {
        "FitClient.submit_forecast": "np.savez into an in-memory BytesIO "
                                     "(the forecast submission blob: "
                                     "values + fitted + status + meta) "
                                     "— wire encoding only, the client "
                                     "never touches the serving root",
        "FitClient._write_clock_journal": "sole writer of the client's "
                                          "<obs stream>.clock.json "
                                          "sidecar (per-endpoint "
                                          "monotonic-offset estimates, "
                                          "ISSUE 18) — next to its own "
                                          "telemetry stream, never under "
                                          "a serving or journal root",
    },
    "spark_timeseries_tpu/serving/fleet.py": {
        "advertise_endpoint": "sole writer of the root's endpoints/ "
                              "namespace (one advert per replica owner, "
                              "atomic via the journal's byte-payload "
                              "primitive so discovery never reads a "
                              "torn advert)",
        "FleetReplica": "performs no direct writes: primaries write "
                        "through the fenced FitServer + Lease owners, "
                        "standbys only READ results/ — registered so "
                        "the single-writer story of a multi-replica "
                        "root is written down",
    },
    "spark_timeseries_tpu/compat/sparkts.py": {
        "_ModelBase.save": "user-facing model save API: writes exactly "
                           "the path the caller names",
    },
    "spark_timeseries_tpu/panel.py": {
        "TimeSeriesPanel.save_csv": "user-facing export API",
        "TimeSeriesPanel.save": "user-facing export API",
    },
    "spark_timeseries_tpu/models/auto.py": {
        "_write_auto_manifest": "sole writer of auto_manifest.json at "
                                "the search root (per-order walk "
                                "manifests belong to ChunkJournal)",
    },
    "spark_timeseries_tpu/forecasting/backtest.py": {
        "_write_backtest_manifest": "sole writer of the campaign-level "
                                    "backtest_manifest.json (per-window "
                                    "fit-walk manifests belong to "
                                    "ChunkJournal)",
        "_write_metrics_npz": "sole writer of the per-window metrics "
                              "npz shards next to the campaign "
                              "manifest (atomic tmp->fsync->replace)",
    },
}

# ---------------------------------------------------------------------------
# lock discipline (rule: lock-map) — runtime instrumentation targets
# ---------------------------------------------------------------------------

# Classes whose ``_protected_by_`` maps the runtime tracker instruments
# on the ci.sh lock-discipline smoke (a real pipelined + sharded +
# serving walk).  The static checker discovers maps by itself from the
# AST; this list only feeds tests/_lockdiscipline_worker.py.
LOCKMAP_RUNTIME_CLASSES = (
    "spark_timeseries_tpu.reliability.committer:ChunkCommitter",
    "spark_timeseries_tpu.reliability.prefetcher:ChunkPrefetcher",
    "spark_timeseries_tpu.reliability.plan:LaneRunner",
    "spark_timeseries_tpu.reliability.plan:WorkQueue",
    "spark_timeseries_tpu.reliability.plan:LaneSupervisor",
    "spark_timeseries_tpu.reliability.journal:ChunkJournal",
    "spark_timeseries_tpu.reliability.source:StagingPool",
    "spark_timeseries_tpu.reliability.source:ChunkSource",
    "spark_timeseries_tpu.reliability.sink:WritableChunkSource",
    "spark_timeseries_tpu.forecasting.augment:ColumnBlockSource",
    "spark_timeseries_tpu.serving.admission:TenantQuota",
    "spark_timeseries_tpu.serving.admission:AdmissionQueue",
    "spark_timeseries_tpu.serving.session:FitTicket",
    "spark_timeseries_tpu.serving.server:FitServer",
    "spark_timeseries_tpu.serving.profiles:TenantProfileStore",
    "spark_timeseries_tpu.serving.transport:TransportServer",
    "spark_timeseries_tpu.serving.client:FitClient",
    "spark_timeseries_tpu.serving.health:EndpointHealthCache",
    "spark_timeseries_tpu.serving.fleet:FleetReplica",
    "spark_timeseries_tpu.reliability.chaos:ChaosRunner",
    "spark_timeseries_tpu.obs.metrics:MetricsRegistry",
    "spark_timeseries_tpu.obs.recorder:FlightRecorder",
    "spark_timeseries_tpu.obs.promsink:PromTextfileSink",
)

# Thread roles that touch the classes above, for documentation and for
# the runtime report: driver (caller of fit_chunked / panel.fit),
# committer worker, prefetcher worker, lane supervisor threads, the
# serve loop, and caller threads submitting to the server.
THREAD_ROLES = ("driver", "committer", "prefetcher", "lane",
                "supervisor", "server", "caller")
