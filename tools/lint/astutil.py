"""Shared AST helpers for the checkers: parent links, qualified names,
attribute-chain dotting, and enclosing-``with`` lookup."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple


def annotate_parents(tree: ast.Module) -> None:
    """Attach ``._lint_parent`` to every node (idempotent)."""
    if getattr(tree, "_lint_parented", False):
        return
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._lint_parent = node  # type: ignore[attr-defined]
    tree._lint_parent = None  # type: ignore[attr-defined]
    tree._lint_parented = True  # type: ignore[attr-defined]


def parents(node: ast.AST) -> Iterator[ast.AST]:
    p = getattr(node, "_lint_parent", None)
    while p is not None:
        yield p
        p = getattr(p, "_lint_parent", None)


def qualname(node: ast.AST) -> str:
    """Dotted enclosing-scope name of ``node`` (``Class.method`` /
    ``function`` / ``<module>``)."""
    names: List[str] = []
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            names.append(p.name)
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        names.append(node.name)
    return ".".join(reversed(names)) or "<module>"


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """Dotted name of the called object, if it is a plain chain."""
    return dotted(node.func)


def enclosing_function(node: ast.AST):
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p
    return None


def enclosing_class(node: ast.AST):
    for p in parents(node):
        if isinstance(p, ast.ClassDef):
            return p
    return None


def with_guards(node: ast.AST, stop: Optional[ast.AST] = None
                ) -> List[ast.expr]:
    """Context expressions of every ``with`` statement enclosing ``node``
    (innermost first), up to (not including) ``stop``."""
    out: List[ast.expr] = []
    for p in parents(node):
        if p is stop:
            break
        if isinstance(p, ast.With):
            out.extend(item.context_expr for item in p.items)
    return out


def local_aliases(func: ast.AST) -> dict:
    """``{local_name: "self.a.b"}`` for simple ``name = self.<chain>``
    assignments anywhere in ``func`` — the codebase's
    ``cond = self.queue.cond`` idiom."""
    aliases: dict = {}
    for sub in ast.walk(func):
        if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)):
            d = dotted(sub.value)
            if d is not None and d.startswith("self."):
                aliases[sub.targets[0].id] = d
    return aliases


def names_in(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id


def literal_str_dict(node: ast.AST) -> Optional[dict]:
    """Evaluate a dict literal whose keys are str constants and whose
    values are str constants or tuples/lists of str constants."""
    if not isinstance(node, ast.Dict):
        return None
    out: dict = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            out[k.value] = (v.value,)
        elif isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in v.elts):
            out[k.value] = tuple(e.value for e in v.elts)
        else:
            return None
    return out


def keyword_arg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def func_params(fn: ast.AST) -> Tuple[List[str], Optional[str]]:
    """(named parameter list incl. kw-only, **kwargs name) of a def."""
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    return names, (a.kwarg.arg if a.kwarg else None)
