"""nondet: wall-clock, ambient RNG, and hash-order nondeterminism banned
in bitwise-critical modules.

The system's headline guarantee is bitwise reproducibility: the same
panel + config produces identical bytes across runs, resumes, shard
layouts, and placements.  Ambient nondeterminism is how that dies one
innocent line at a time.  In every module outside the exempt telemetry
and serving planes, the checker flags

- ``time.time()`` / ``time.time_ns()`` (wall-clock identity;
  ``perf_counter`` / ``monotonic`` are duration measurements and fine),
- ``datetime.now`` / ``utcnow`` / ``date.today``,
- the stdlib ``random`` module (any use; ``jax.random`` with explicit
  keys and seeded ``np.random.default_rng(seed)`` are the sanctioned
  spellings),
- ambient numpy RNG: ``np.random.<draw>`` on the global state,
  ``np.random.seed``, and ``np.random.default_rng()`` with NO seed,
- ``uuid.uuid1`` / ``uuid.uuid4`` (fine as run identity — waive it),
- builtin ``hash()`` (PYTHONHASHSEED-dependent across processes),
- ``json.dumps`` without ``sort_keys=True`` feeding a ``hashlib``
  digest (dict-order-dependent hashing; list/tuple literals are
  order-stable and exempt).

Telemetry timestamps and run ids inside critical modules are legitimate
— they are metadata, never fitted bytes — and carry inline waivers:
``# lint: nondet(manifest wall-clock metadata; never in fitted bytes)``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from .. import astutil
from ..contracts import NONDET_EXEMPT_PREFIXES
from ..engine import Finding, LintModule

RULE = "nondet"

_NP_RANDOM_OK = {"default_rng", "Generator", "SeedSequence",
                 "BitGenerator", "PCG64", "Philox"}


def applies(path: str) -> bool:
    return (path.startswith("spark_timeseries_tpu/")
            and not any(path.startswith(p)
                        for p in NONDET_EXEMPT_PREFIXES))


def _stdlib_random_names(tree: ast.Module) -> Set[str]:
    """Local names bound to the STDLIB random module (so ``from jax
    import random`` does not false-positive)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "random":
                    out.add(alias.asname or "random")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "random" and node.level == 0:
                for alias in node.names:
                    out.add(alias.asname or alias.name)
    return out


def _json_dumps_no_sort(node: ast.Call) -> bool:
    if astutil.call_name(node) not in ("json.dumps",):
        return False
    sk = astutil.keyword_arg(node, "sort_keys")
    if isinstance(sk, ast.Constant) and sk.value is True:
        return False
    # list/tuple displays are order-stable by construction
    if node.args and isinstance(node.args[0], (ast.List, ast.Tuple)):
        return False
    return True


def check(module: LintModule) -> Iterator[Finding]:
    if not applies(module.path):
        return
    astutil.annotate_parents(module.tree)
    rand_names = _stdlib_random_names(module.tree)

    # names assigned from an unsorted json.dumps, for the hash-feed check
    unsorted_json: Set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _json_dumps_no_sort(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    unsorted_json.add(t.id)

    def _feeds_unsorted_json(call: ast.Call) -> Optional[str]:
        for sub in ast.walk(call):
            if sub is call:
                continue
            if isinstance(sub, ast.Call) and _json_dumps_no_sort(sub):
                return "json.dumps(...) without sort_keys=True"
            if isinstance(sub, ast.Name) and sub.id in unsorted_json:
                return f"`{sub.id}` (json.dumps without sort_keys=True)"
        return None

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        if name is None:
            continue
        line, col = node.lineno, node.col_offset

        if name in ("time.time", "time.time_ns"):
            yield Finding(
                rule=RULE, path=module.path, line=line, col=col,
                message=f"`{name}()` is wall-clock nondeterminism in a "
                        "bitwise-critical module — use perf_counter for "
                        "durations, or waive for telemetry metadata")
        elif name.endswith((".now", ".utcnow", ".today")) and \
                name.split(".", 1)[0] in ("datetime", "date", "dt"):
            yield Finding(
                rule=RULE, path=module.path, line=line, col=col,
                message=f"`{name}()` is wall-clock nondeterminism in a "
                        "bitwise-critical module")
        elif name.split(".", 1)[0] in rand_names:
            yield Finding(
                rule=RULE, path=module.path, line=line, col=col,
                message=f"stdlib `random` use (`{name}`) — seed an "
                        "explicit np.random.default_rng or use "
                        "jax.random keys")
        elif name.startswith(("np.random.", "numpy.random.")):
            leaf = name.rsplit(".", 1)[-1]
            if leaf == "seed":
                yield Finding(
                    rule=RULE, path=module.path, line=line, col=col,
                    message="`np.random.seed` mutates ambient global RNG "
                            "state — pass an explicit default_rng")
            elif leaf == "default_rng":
                if not node.args and not node.keywords:
                    yield Finding(
                        rule=RULE, path=module.path, line=line, col=col,
                        message="`np.random.default_rng()` with no seed "
                                "draws OS entropy — pass an explicit "
                                "seed")
            elif leaf not in _NP_RANDOM_OK:
                yield Finding(
                    rule=RULE, path=module.path, line=line, col=col,
                    message=f"ambient numpy RNG draw `{name}` — use an "
                            "explicitly seeded default_rng")
        elif name in ("uuid.uuid1", "uuid.uuid4"):
            yield Finding(
                rule=RULE, path=module.path, line=line, col=col,
                message=f"`{name}()` in a bitwise-critical module — "
                        "fine as run/request identity metadata: waive "
                        "with that reason")
        elif name == "hash":
            yield Finding(
                rule=RULE, path=module.path, line=line, col=col,
                message="builtin `hash()` is PYTHONHASHSEED-dependent "
                        "across processes — use hashlib for anything "
                        "persisted or compared cross-process")
        elif name.startswith("hashlib.") or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "update"
                and astutil.dotted(node.func.value) is not None):
            feed = _feeds_unsorted_json(node)
            if feed is not None and (name.startswith("hashlib.")
                                     or name.endswith(".update")):
                yield Finding(
                    rule=RULE, path=module.path, line=line, col=col,
                    message=f"digest fed by {feed}: dict-order-dependent "
                            "hashing — pass sort_keys=True")
