"""host-sync: flag implicit device->host synchronizations in hot-path
modules.

The pipelined chunk walk's perf contract is ZERO implicit host syncs on
the critical path: stage N+1 / compute N / commit N-1 only overlap while
the driver never blocks on a device value.  A stray ``float(nll)``,
truthiness test on a jax array, or ``np.asarray`` of a device value
stalls the walk for a full dispatch round trip — the exact bug class the
PR 7 host-streamed NaN probe removed.  Deliberate syncs (the commit
fetch, the staging materialization barrier) carry inline waivers:

    jax.block_until_ready(arr)  # lint: host-sync(staging barrier: ...)

Detection is a per-function value-flow approximation tuned for a CI
gate (zero false positives beats exhaustive recall): names assigned
from ``jnp.* / lax.* / jax.*`` calls are DEVICE-TAINTED, taint flows
through operators / subscripts / ternaries / tuple unpacks — but NOT
through the results of unknown function calls (helpers fed device
values usually return host metadata), and host metadata access
(``x.shape``), host casts, identity comparisons, and list-display
names stop it.  The checker flags

- ``float(x) / int(x) / bool(x) / np.asarray(x) / np.array(x) /
  np.ascontiguousarray(x)`` where ``x`` contains a tainted name,
- ``.item()`` / ``.tolist()`` calls (anywhere in a hot module),
- ``jax.block_until_ready`` / ``jax.device_get`` /
  ``<x>.block_until_ready()`` (anywhere in a hot module),
- truthiness on tainted values (``if x:``, ``while x:``, ``assert x``,
  boolean operators, non-``is`` comparisons used as branch tests).

Host-side jax calls that never produce device values are exempt
(``jax.process_index`` etc.).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from .. import astutil
from ..contracts import HOT_PATH_PREFIXES
from ..engine import Finding, LintModule

RULE = "host-sync"

# jax.* calls that return host values / objects, never device arrays
_HOST_SIDE_JAX = {
    "jax.process_index", "jax.process_count", "jax.device_count",
    "jax.local_device_count", "jax.devices", "jax.local_devices",
    "jax.default_backend", "jax.eval_shape", "jax.make_mesh",
    "jax.tree_util", "jax.profiler", "jax.distributed",
    "jax.block_until_ready", "jax.clear_caches",
}

_CAST_SINKS = {"float", "int", "bool", "complex"}
_NP_SINKS = {"np.asarray", "np.array", "np.ascontiguousarray",
             "numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}
_SYNC_CALLS = {"jax.block_until_ready", "jax.device_get"}


def applies(path: str) -> bool:
    return any(path.startswith(p) or path == p.rstrip("/")
               for p in HOT_PATH_PREFIXES)


def _is_device_call(node: ast.Call) -> bool:
    name = astutil.call_name(node)
    if name is None:
        return False
    if name in _HOST_SIDE_JAX or any(
            name.startswith(h + ".") for h in _HOST_SIDE_JAX):
        return False
    root = name.split(".", 1)[0]
    if root in ("jnp", "lax"):
        return True
    if name.startswith(("jax.numpy.", "jax.lax.", "jax.random.")):
        return True
    if name in ("jax.device_put", "jax.jit", "jax.vmap", "jax.pmap",
                "jax.grad", "jax.value_and_grad"):
        return True
    return False


# attributes whose value is HOST metadata even on a device array: reading
# them never touches device bytes, so taint stops there
_METADATA_ATTRS = {
    "shape", "dtype", "ndim", "size", "nbytes", "itemsize", "sharding",
    "device", "devices", "is_fully_addressable", "addressable_shards",
    "kind", "name", "__name__",
}

def _value_tainted(e: ast.AST, tainted: Set[str]) -> bool:
    """Does evaluating ``e`` yield a device value?  Taint flows through
    operators, subscripts, ternaries, and attribute access — but NOT
    through the results of unknown function calls (a helper fed a device
    value usually returns host metadata: fingerprints, plans, meta
    dicts; treating those as tainted floods the walk with false
    positives).  Device producers: direct ``jnp.*``/``lax.*``/seeded
    ``jax.*`` calls, and calls of names themselves bound to jitted
    callables.  Metadata attributes (``x.shape`` ...), host casts
    (``int(x)`` ...), and identity comparisons stop the taint."""
    if isinstance(e, ast.Call):
        if _is_device_call(e):
            return True
        cn = astutil.call_name(e)
        if cn is not None and cn.split(".", 1)[0] in tainted:
            return True  # jitted callable bound earlier
        return False  # opaque call: result assumed host-side
    if isinstance(e, ast.Attribute):
        if e.attr in _METADATA_ATTRS:
            return False
        return _value_tainted(e.value, tainted)
    if isinstance(e, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
               for op in e.ops):
            return False
        return any(_value_tainted(c, tainted)
                   for c in [e.left] + list(e.comparators))
    if isinstance(e, ast.Name):
        return e.id in tainted
    return any(_value_tainted(c, tainted)
               for c in ast.iter_child_nodes(e))


def _tainted_names(fn: ast.AST) -> tuple:
    """(tainted, containers): names bound to device-tainted values, and
    names ever bound to list/tuple/dict/set displays (whose truthiness
    is a host-side length check, not a device sync).

    One forward pass plus propagation to fixpoint over plain assigns:
    ``a = jnp.sum(x)``, ``b = a + 1``, ``lo, hi = a``, ``c = a.params``.
    A call of ANY function on a tainted argument taints the result (a
    fit on device inputs returns device outputs); metadata attributes
    (``x.shape`` ...), host casts (``int(x)`` ...) and identity
    comparisons stop the taint.
    """
    tainted: Set[str] = set()
    containers: Set[str] = set()

    def _is_display(v: ast.AST) -> bool:
        if isinstance(v, (ast.List, ast.ListComp, ast.Tuple, ast.Dict,
                          ast.Set, ast.DictComp, ast.SetComp)):
            return True
        # `x = [a] if flag else []` is still a list-valued name
        if isinstance(v, ast.IfExp):
            return _is_display(v.body) and _is_display(v.orelse)
        return False

    for _ in range(4):  # tiny fixpoint: chains are short in practice
        before = (len(tainted), len(containers))
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign):
                if _is_display(sub.value):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            containers.add(t.id)
                    continue
                if _value_tainted(sub.value, tainted):
                    for t in sub.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
            elif isinstance(sub, ast.AugAssign):
                if isinstance(sub.target, ast.Name) and \
                        sub.target.id not in containers and \
                        _value_tainted(sub.value, tainted):
                    tainted.add(sub.target.id)
        if (len(tainted), len(containers)) == before:
            break
    return tainted - containers, containers


def _contains_tainted(e: ast.AST, tainted: Set[str]) -> bool:
    return _value_tainted(e, tainted)


def _truthy_test_tainted(test: ast.AST, tainted: Set[str]) -> bool:
    """Branch tests that force a device value to a host bool.  ``is`` /
    ``is not`` / ``in`` comparisons, ``isinstance``, ``len`` and
    attribute existence checks never read device bytes and are exempt."""
    if isinstance(test, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
               for op in test.ops):
            return False
        return _contains_tainted(test, tainted)
    if isinstance(test, ast.BoolOp):
        return any(_truthy_test_tainted(v, tainted) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _truthy_test_tainted(test.operand, tainted)
    if isinstance(test, ast.Name):
        return test.id in tainted
    if isinstance(test, ast.Call):
        name = astutil.call_name(test)
        if name is not None and (
                name in ("len", "isinstance", "hasattr", "getattr")
                or name.endswith((".get", ".keys"))):
            return False
        return _is_device_call(test)
    if isinstance(test, ast.Attribute):
        # x.shape / x.dtype / x.ndim are metadata, not bytes
        return False
    return False


def _scope_nodes(scope: ast.AST) -> Iterator[ast.AST]:
    """Nodes belonging directly to ``scope`` (nested defs excluded — each
    function scope reports its own findings against its own taint set)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def check(module: LintModule) -> Iterator[Finding]:
    if not applies(module.path):
        return
    astutil.annotate_parents(module.tree)

    scopes: List[ast.AST] = [module.tree] + [
        n for n in ast.walk(module.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def finding(node: ast.AST, msg: str) -> Finding:
        return Finding(rule=RULE, path=module.path, line=node.lineno,
                       col=node.col_offset,
                       message=f"{msg} in {astutil.qualname(node)}")

    for scope in scopes:
        tainted, _containers = _tainted_names(scope)
        for node in _scope_nodes(scope):
            if isinstance(node, ast.Call):
                name = astutil.call_name(node)
                if name in _SYNC_CALLS:
                    yield finding(
                        node, f"explicit device sync `{name}(...)` — "
                              "waive with the reason if deliberate")
                    continue
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("item", "tolist")
                        and not node.args and not node.keywords
                        and _contains_tainted(node.func.value, tainted)):
                    yield finding(
                        node, f"`.{node.func.attr}()` forces a "
                              "device->host transfer")
                    continue
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "block_until_ready"):
                    yield finding(
                        node, "`.block_until_ready()` is an explicit "
                              "device sync — waive with the reason "
                              "if deliberate")
                    continue
                if name in _CAST_SINKS and node.args and \
                        _contains_tainted(node.args[0], tainted):
                    yield finding(
                        node, f"`{name}()` of a device value blocks "
                              "on dispatch (host sync)")
                    continue
                if name in _NP_SINKS and node.args and \
                        _contains_tainted(node.args[0], tainted):
                    yield finding(
                        node, f"`{name}()` of a device value is an "
                              "implicit device->host copy")
                    continue
            elif isinstance(node, (ast.If, ast.While)):
                if _truthy_test_tainted(node.test, tainted):
                    yield finding(
                        node.test, "truthiness of a device value in a "
                                   "branch test blocks on dispatch")
            elif isinstance(node, ast.Assert):
                if _truthy_test_tainted(node.test, tainted):
                    yield finding(
                        node.test, "assert on a device value blocks "
                                   "on dispatch")
            elif isinstance(node, ast.IfExp):
                if _truthy_test_tainted(node.test, tainted):
                    yield finding(
                        node.test, "conditional expression on a "
                                   "device value blocks on dispatch")
