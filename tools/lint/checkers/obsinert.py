"""obs-inert: library modules reach obs only through the guarded facade.

The telemetry plane's contract is OFF-BY-DEFAULT INERTNESS: with obs
disabled, every facade call returns a shared no-op, adds no events, and
leaves fit results bitwise-identical to the uninstrumented code.  That
holds only while library code goes through the facade
(``from .. import obs`` + ``obs.span`` / ``obs.counter`` / ... — every
name ``obs/__init__`` exports).  Reaching into submodules
(``obs.core``, ``obs.metrics``, ``obs.memory``, ``obs.promsink``,
``obs.recorder``, ``obs.tracing``) bypasses the enabled() guard and
couples the library
to internals; calling ``obs.enable`` / ``obs.disable`` /
``obs.enable_from_env`` from library code mutates global telemetry
state that belongs to the application.  Flagged:

- ``from ..obs.<submodule> import ...`` / ``import ...obs.<submodule>``,
- ``from ..obs import <submodule>`` (importing the submodule by name
  through the facade is the same bypass),
- ``obs.<submodule>.<anything>`` attribute chains in code,
- ``obs.enable(...)`` / ``obs.disable(...)`` / ``obs.enable_from_env``
  calls outside the obs package.

Waiver: ``# lint: obs-inert(<reason>)``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .. import astutil
from ..engine import Finding, LintModule

RULE = "obs-inert"

_SUBMODULES = {"core", "memory", "metrics", "promsink", "recorder",
               "tracing"}
_STATE_CALLS = {"enable", "disable", "enable_from_env"}


def applies(path: str) -> bool:
    return (path.startswith("spark_timeseries_tpu/")
            and not path.startswith("spark_timeseries_tpu/obs/"))


def check(module: LintModule) -> Iterator[Finding]:
    if not applies(module.path):
        return
    astutil.annotate_parents(module.tree)
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            parts = mod.split(".")
            if "obs" in parts:
                after = parts[parts.index("obs") + 1:]
                if after and after[0] in _SUBMODULES:
                    yield Finding(
                        rule=RULE, path=module.path, line=node.lineno,
                        col=node.col_offset,
                        message=f"import from obs submodule `{mod}` "
                                "bypasses the guarded facade — import "
                                "the facade name from `obs` instead")
                elif parts[-1] == "obs":
                    for alias in node.names:
                        if alias.name in _SUBMODULES:
                            yield Finding(
                                rule=RULE, path=module.path,
                                line=node.lineno, col=node.col_offset,
                                message=f"`from ... obs import "
                                        f"{alias.name}` pulls an obs "
                                        "submodule into library code — "
                                        "use the facade functions "
                                        "obs/__init__ exports")
        elif isinstance(node, ast.Import):
            for alias in node.names:
                parts = alias.name.split(".")
                if "obs" in parts and parts[-1] in _SUBMODULES:
                    yield Finding(
                        rule=RULE, path=module.path, line=node.lineno,
                        col=node.col_offset,
                        message=f"`import {alias.name}` reaches an obs "
                                "submodule — use the facade")
        elif isinstance(node, ast.Attribute):
            d = astutil.dotted(node)
            if d is not None:
                parts = d.split(".")
                # exactly obs.<submodule>: a longer chain contains this
                # node as its value child, so each chain flags once
                if len(parts) == 2 and parts[0] == "obs" and \
                        parts[1] in _SUBMODULES:
                    yield Finding(
                        rule=RULE, path=module.path, line=node.lineno,
                        col=node.col_offset,
                        message=f"`{d}` reaches into an obs submodule — "
                                "only facade names are inert when obs "
                                "is disabled")
        elif isinstance(node, ast.Call):
            d = astutil.call_name(node)
            if d is not None:
                parts = d.split(".")
                if len(parts) == 2 and parts[0] == "obs" and \
                        parts[1] in _STATE_CALLS:
                    yield Finding(
                        rule=RULE, path=module.path, line=node.lineno,
                        col=node.col_offset,
                        message=f"`{d}()` mutates global telemetry state "
                                "from library code — enabling/disabling "
                                "obs belongs to the application")
