"""journal-writer: file writes only from registered owner call sites.

The chunk journal's durability proof rests on a single-writer-per-
namespace protocol: ``ChunkJournal`` owns its namespace's shards and
manifest, the pipelined committer is a courier INTO that owner (one
worker, FIFO, shard-before-manifest), and ``merge_job_manifest`` alone
writes the merged root.  A future helper that writes "just one more
file" under a journal root would splice a second writer into the
protocol without tripping any test — until a crash lands between its
write and the manifest's.

This checker generalizes the rule to the whole library: every call site
that writes a file must be registered in
``tools.lint.contracts.FILE_WRITE_OWNERS`` with the namespace it owns.
Write primitives detected: ``open(..., "w"/"a"/"x"/"+")``,
``os.fdopen(..., "w"/"wb")``, ``os.replace`` / ``os.rename``,
``np.savez`` / ``np.savez_compressed`` / ``np.save``,
``shutil.move`` / ``shutil.copy*``, ``Path.write_text`` /
``Path.write_bytes``.  One-off exceptions (there should be none) use
``# lint: journal-writer(<reason>)``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from .. import astutil
from .. import contracts
from ..engine import Finding, LintModule

RULE = "journal-writer"

_WRITE_FUNCS = {
    "os.replace", "os.rename",
    "np.savez", "np.savez_compressed", "np.save",
    "numpy.savez", "numpy.savez_compressed", "numpy.save",
    "shutil.move", "shutil.copy", "shutil.copy2", "shutil.copyfile",
    "shutil.copytree",
}
_WRITE_METHODS = {"write_text", "write_bytes"}
_WRITE_MODES = set("wax+")


def _open_mode(call: ast.Call) -> Optional[str]:
    """The mode literal of an open()/os.fdopen() call, '' if defaulted,
    None if non-literal (conservatively treated as a write)."""
    mode = astutil.keyword_arg(call, "mode")
    if mode is None and len(call.args) >= 2:
        mode = call.args[1]
    if mode is None:
        return ""
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _is_write(node: ast.Call) -> Optional[str]:
    name = astutil.call_name(node)
    if name in _WRITE_FUNCS:
        return name
    if name in ("open", "os.fdopen", "io.open", "gzip.open"):
        mode = _open_mode(node)
        if mode is None:
            return f"{name}(mode=<non-literal>)"
        if _WRITE_MODES & set(mode):
            return f"{name}(mode={mode!r})"
        return None
    if isinstance(node.func, ast.Attribute) and \
            node.func.attr in _WRITE_METHODS:
        return f".{node.func.attr}()"
    return None


def check(module: LintModule,
          owners: Optional[dict] = None) -> Iterator[Finding]:
    if not module.path.startswith("spark_timeseries_tpu/"):
        return
    owners = contracts.FILE_WRITE_OWNERS if owners is None else owners
    allowed = owners.get(module.path, {})
    astutil.annotate_parents(module.tree)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        what = _is_write(node)
        if what is None:
            continue
        qual = astutil.qualname(node)
        ok = any(qual == owner or qual.startswith(owner + ".")
                 for owner in allowed)
        if not ok:
            yield Finding(
                rule=RULE, path=module.path, line=node.lineno,
                col=node.col_offset,
                message=f"file write `{what}` in `{qual}` is not a "
                        "registered owner call site — route it through "
                        "the namespace's owner or register it (with the "
                        "namespace it owns) in FILE_WRITE_OWNERS")
