"""lock-map: declared per-class lock protection maps, honored at every
mutation site.

Six thread roles mutate shared state in this codebase — driver,
committer worker, prefetcher worker, elastic lane/supervisor threads,
the serve loop, and caller threads — and the discipline that keeps them
honest lived only in code review.  This checker makes it declarative:

- a class that creates an instance lock (``self.x = threading.Lock() /
  RLock() / Condition(...)``) MUST declare a class attribute

      _protected_by_ = {"<attr>": "<lock attr>", ...}

  naming, for every shared attribute mutated by more than one thread
  role, the lock that guards it.  Values may be dotted paths rooted at
  self (``"queue.cond"``) and may be a tuple when several spellings
  guard the same state (``("_lock", "_not_empty")`` for a Condition
  built on the lock).  An attribute mutated by a single role (e.g. a
  driver-only accumulator) is deliberately NOT declared.

- every mutation site of a declared attribute — plain/augmented
  assignment, subscript stores/deletes, and mutating method calls
  (``.append`` / ``.pop`` / ``.update`` / ...) — must sit lexically
  inside a ``with self.<lock>:`` block of the declared lock (local
  aliases like ``cond = self.queue.cond`` are resolved), with three
  escapes: ``__init__``/``__new__`` (construction precedes sharing),
  methods named ``*_locked`` (the codebase's called-with-lock-held
  convention), and an inline ``# lint: lock-map(<reason>)`` waiver.

Module-level twins use ``_PROTECTED_BY_ = {"<global>": "<lock global>"}``
(see ``utils/compile_cache.py``).  The static check is an approximation
— cross-function lock holding and aliased containers escape it — which
is why the runtime tracker (:mod:`tools.lint.runtime`) enforces the
same declarations dynamically on the ci.sh lock-discipline smoke.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from .. import astutil
from ..engine import Finding, LintModule

RULE = "lock-map"

CLASS_MAP_NAME = "_protected_by_"
MODULE_MAP_NAME = "_PROTECTED_BY_"

_LOCK_CTORS = ("Lock", "RLock", "Condition")
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "sort", "reverse",
    "appendleft", "popleft", "extendleft", "put", "put_nowait",
}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = astutil.call_name(node)
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1]
    return last in _LOCK_CTORS


def _self_attr(node: ast.AST) -> Optional[str]:
    """``attr`` for a bare ``self.attr`` expression."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _self_path(node: ast.AST) -> Optional[str]:
    """``a.b`` for a ``self.a.b`` chain."""
    d = astutil.dotted(node)
    if d is not None and d.startswith("self."):
        return d[len("self."):]
    return None


def _mutation_target(node: ast.AST) -> Optional[Tuple[str, str]]:
    """(attr, kind) when ``node`` mutates ``self.<attr>`` directly."""
    if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if isinstance(t, ast.Tuple):
                elts: List[ast.AST] = list(t.elts)
            else:
                elts = [t]
            for e in elts:
                attr = _self_attr(e)
                if attr is not None:
                    return attr, "assignment"
                if isinstance(e, ast.Subscript):
                    attr = _self_attr(e.value)
                    if attr is not None:
                        return attr, "subscript store"
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                attr = _self_attr(t.value)
                if attr is not None:
                    return attr, "subscript delete"
            else:
                attr = _self_attr(t)
                if attr is not None:
                    return attr, "attribute delete"
    elif isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            attr = _self_attr(node.func.value)
            if attr is not None:
                return attr, f".{node.func.attr}() call"
    return None


def _guards_held(node: ast.AST, aliases: dict) -> List[str]:
    """Self-rooted dotted paths of every ``with`` guard lexically
    enclosing ``node`` within its own function."""
    out: List[str] = []
    p = getattr(node, "_lint_parent", None)
    while p is not None and not isinstance(
            p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        if isinstance(p, ast.With):
            for item in p.items:
                path = _self_path(item.context_expr)
                if path is None and isinstance(item.context_expr, ast.Name):
                    ali = aliases.get(item.context_expr.id)
                    if ali is not None:
                        path = ali[len("self."):]
                if path is not None:
                    out.append(path)
        p = getattr(p, "_lint_parent", None)
    return out


def _class_map(cls: ast.ClassDef) -> Optional[Tuple[dict, int]]:
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == CLASS_MAP_NAME
                for t in stmt.targets):
            m = astutil.literal_str_dict(stmt.value)
            return (m, stmt.lineno)
    return None


def _check_class(module: LintModule, cls: ast.ClassDef
                 ) -> Iterator[Finding]:
    lock_attrs = set()
    assigned_attrs = set()
    for node in ast.walk(cls):
        mt = _mutation_target(node)
        if mt is not None and mt[1] == "assignment":
            assigned_attrs.add(mt[0])
        if isinstance(node, ast.Assign):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None and _is_lock_ctor(node.value):
                    lock_attrs.add(attr)

    declared = _class_map(cls)
    if declared is None:
        if lock_attrs:
            yield Finding(
                rule=RULE, path=module.path, line=cls.lineno, col=0,
                message=f"class `{cls.name}` creates instance lock(s) "
                        f"{sorted(lock_attrs)} but declares no "
                        f"`{CLASS_MAP_NAME}` protection map — declare "
                        "which shared attributes each lock guards")
        return
    pmap, map_line = declared
    if pmap is None:
        yield Finding(
            rule=RULE, path=module.path, line=map_line, col=0,
            message=f"`{cls.name}.{CLASS_MAP_NAME}` must be a literal "
                    "dict of str -> str (or tuple of str)")
        return

    for attr, guards in pmap.items():
        if attr not in assigned_attrs:
            yield Finding(
                rule=RULE, path=module.path, line=map_line, col=0,
                message=f"`{cls.name}.{CLASS_MAP_NAME}` declares `{attr}` "
                        "but the class never assigns it — stale entry")
        for g in guards:
            head = g.split(".", 1)[0]
            if "." not in g and g not in lock_attrs and \
                    head not in assigned_attrs:
                yield Finding(
                    rule=RULE, path=module.path, line=map_line, col=0,
                    message=f"`{cls.name}.{CLASS_MAP_NAME}` guard `{g}` "
                            "is not a lock attribute this class creates")

    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if method.name in ("__init__", "__new__") or \
                method.name.endswith("_locked"):
            continue
        aliases = astutil.local_aliases(method)
        for node in ast.walk(method):
            mt = _mutation_target(node)
            if mt is None or mt[0] not in pmap:
                continue
            attr, kind = mt
            held = _guards_held(node, aliases)
            if not any(g in held for g in pmap[attr]):
                want = " or ".join(f"self.{g}" for g in pmap[attr])
                yield Finding(
                    rule=RULE, path=module.path, line=node.lineno,
                    col=node.col_offset,
                    message=f"`{cls.name}.{attr}` {kind} in "
                            f"`{method.name}` outside the declared guard "
                            f"`with {want}:`")


def _check_module_level(module: LintModule) -> Iterator[Finding]:
    pmap: Optional[Dict[str, tuple]] = None
    for stmt in module.tree.body:
        if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == MODULE_MAP_NAME
                for t in stmt.targets):
            pmap = astutil.literal_str_dict(stmt.value)
            if pmap is None:
                yield Finding(
                    rule=RULE, path=module.path, line=stmt.lineno, col=0,
                    message=f"`{MODULE_MAP_NAME}` must be a literal dict "
                            "of str -> str (or tuple of str)")
                return
    if pmap is None:
        return
    for fn in ast.walk(module.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name.endswith("_locked"):
            continue
        for node in ast.walk(fn):
            name: Optional[str] = None
            kind = "assignment"
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in pmap:
                        name = t.id
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in pmap:
                name = node.func.value.id
                kind = f".{node.func.attr}() call"
            if name is None:
                continue
            held: List[str] = []
            p = getattr(node, "_lint_parent", None)
            while p is not None and not isinstance(
                    p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                if isinstance(p, ast.With):
                    for item in p.items:
                        if isinstance(item.context_expr, ast.Name):
                            held.append(item.context_expr.id)
                p = getattr(p, "_lint_parent", None)
            if not any(g in held for g in pmap[name]):
                want = " or ".join(pmap[name])
                yield Finding(
                    rule=RULE, path=module.path, line=node.lineno,
                    col=node.col_offset,
                    message=f"module global `{name}` {kind} in "
                            f"`{fn.name}` outside the declared guard "
                            f"`with {want}:`")


def check(module: LintModule) -> Iterator[Finding]:
    if not module.path.startswith("spark_timeseries_tpu/"):
        return
    astutil.annotate_parents(module.tree)
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            yield from _check_class(module, node)
    yield from _check_module_level(module)
