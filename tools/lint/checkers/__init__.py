"""Checker registry: one module per load-bearing contract.

Each checker exposes ``check(module: LintModule) -> Iterable[Finding]``
and a module docstring that doubles as its ``--explain`` text.
"""

from . import (confighash, hostsync, journalwriter, lockmap, nondet,
               obsinert)

ALL_CHECKERS = (
    hostsync.check,
    confighash.check,
    journalwriter.check,
    lockmap.check,
    obsinert.check,
    nondet.check,
)

# rule name -> checker module (the docstring is the --explain text)
RULES = {
    hostsync.RULE: hostsync,
    confighash.RULE: confighash,
    journalwriter.RULE: journalwriter,
    lockmap.RULE: lockmap,
    obsinert.RULE: obsinert,
    nondet.RULE: nondet,
}

# engine-level rules explained inline (no checker module of their own)
ENGINE_RULES = {
    "stale-waiver": (
        "A `# lint: <rule>(<reason>)` waiver no longer covers any "
        "finding: the violation it excused is gone, so the excuse must "
        "go with it.  Delete the comment (or move it back next to the "
        "violation if it drifted during an edit)."),
    "waiver-syntax": (
        "A waiver comment with an empty reason.  The reason is the "
        "point: it is the reviewed record of WHY the violation is "
        "deliberate.  Write one, e.g.\n"
        "    # lint: host-sync(commit fetch: the journal needs host "
        "bytes)"),
    "parse-error": "A target file failed to parse; fix the syntax error.",
}

__all__ = ["ALL_CHECKERS", "RULES", "ENGINE_RULES"]
