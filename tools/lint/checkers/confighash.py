"""config-hash: every knob a fit-entry surface accepts must be reachable
by the journal config hash or registered as a deliberate exclusion.

The journal accepts a resume exactly when ``config_hash`` matches — so a
knob that changes what a chunk's bytes mean MUST reach the hash, and a
knob that only moves work (pipeline depth, shard layout, prefetch
depth) is EXCLUDED so a serial journal resumes under a pipelined run.
Both sets were tribal knowledge; this checker pins them to the registry
in :mod:`tools.lint.contracts` (``CONFIG_HASH_SURFACES``), each
exclusion with a rationale.  Three failure modes are caught:

- a NEW signature keyword with no registry entry (the bug: a knob that
  silently forks journal compatibility, or silently doesn't),
- a STALE registry entry naming a parameter the signature dropped,
- registry drift from the code: a driver knob registered as hashed for
  ``fit_chunked`` must appear as a literal key of the ``extra=`` dict
  actually passed to ``config_hash`` (or be covered by the
  ``**fit_kwargs`` catch-all / panel fingerprint).

Adding a knob therefore forces an explicit decision, reviewed where the
rationale lives.  (There is deliberately NO waiver for this rule — the
registry IS the waiver, with teeth.)
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from .. import astutil
from .. import contracts
from ..engine import Finding, LintModule

RULE = "config-hash"


def _find_def(tree: ast.Module, qual: str) -> Optional[ast.AST]:
    parts = qual.split(".")
    node: ast.AST = tree
    for part in parts:
        nxt = None
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)) and child.name == part:
                nxt = child
                break
        if nxt is None:
            return None
        node = nxt
    return node


def _config_hash_extra_keys(fn: ast.AST) -> Optional[set]:
    """Literal str keys of ``extra={...}`` in the first ``config_hash``
    call inside ``fn`` that carries one (the journal-identity call)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        if name is None or not name.endswith("config_hash"):
            continue
        extra = astutil.keyword_arg(node, "extra")
        if isinstance(extra, ast.Dict):
            keys = set()
            for k in extra.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
                else:
                    return None  # non-literal key: cannot verify
            return keys
    return None


def check(module: LintModule,
          surfaces: Optional[dict] = None) -> Iterator[Finding]:
    surfaces = (contracts.CONFIG_HASH_SURFACES
                if surfaces is None else surfaces)
    for surface, spec in surfaces.items():
        path, qual = surface.split("::", 1)
        if module.path != path:
            continue
        fn = _find_def(module.tree, qual)
        if fn is None or not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield Finding(
                rule=RULE, path=module.path, line=1, col=0,
                message=f"registered surface `{qual}` not found — update "
                        "CONFIG_HASH_SURFACES in tools/lint/contracts.py")
            continue
        params, kwargs_name = astutil.func_params(fn)
        params = [p for p in params if p != "self"]
        hashed = set(spec.get("hashed", {}))
        excluded = set(spec.get("excluded", {}))
        covered = hashed | excluded
        for p in params:
            if p not in covered:
                yield Finding(
                    rule=RULE, path=module.path, line=fn.lineno, col=0,
                    message=f"`{qual}` keyword `{p}` is neither reachable "
                            "by the journal config hash nor registered as "
                            "a deliberate exclusion — decide which and "
                            "record it (with rationale) in "
                            "CONFIG_HASH_SURFACES")
        for p in sorted(covered):
            if p not in params:
                yield Finding(
                    rule=RULE, path=module.path, line=fn.lineno, col=0,
                    message=f"CONFIG_HASH_SURFACES entry `{p}` names a "
                            f"parameter `{qual}` no longer accepts — "
                            "prune the stale registry entry")
        if kwargs_name is not None and spec.get("kwargs_param") \
                is not None and kwargs_name != spec["kwargs_param"]:
            yield Finding(
                rule=RULE, path=module.path, line=fn.lineno, col=0,
                message=f"`{qual}` **{kwargs_name} does not match the "
                        f"registered catch-all **{spec['kwargs_param']}")
        # registry <-> code drift for the anchor surface: hashed driver
        # knobs must be literal extra= keys of the config_hash call
        extra_keys = spec.get("extra_keys")
        if extra_keys is not None:
            live = _config_hash_extra_keys(fn)
            if live is None:
                yield Finding(
                    rule=RULE, path=module.path, line=fn.lineno, col=0,
                    message=f"`{qual}` has no config_hash(extra={{...}}) "
                            "call with literal keys — the checker can no "
                            "longer verify hashed driver knobs")
            else:
                for k in sorted(set(extra_keys) - live):
                    yield Finding(
                        rule=RULE, path=module.path, line=fn.lineno, col=0,
                        message=f"registered hashed knob `{k}` is NOT a "
                                "key of the extra= dict passed to "
                                "config_hash — the registry claims "
                                "coverage the code does not provide")
                for k in sorted(live - set(extra_keys)):
                    yield Finding(
                        rule=RULE, path=module.path, line=fn.lineno, col=0,
                        message=f"config_hash extra= key `{k}` is not "
                                "registered in CONFIG_HASH_SURFACES "
                                "extra_keys — register it so coverage "
                                "stays machine-readable")
