"""Lint engine: file walking, waiver parsing, baseline diffing, reporting.

The engine is checker-agnostic.  A checker is a callable
``check(module: LintModule) -> Iterable[Finding]``; the engine parses
each target file once, hands every checker the shared
:class:`LintModule`, folds inline waivers over the raw findings, and
diffs the surviving set against the committed baseline.

**Waivers** are inline comments ``# lint: <rule>(<reason>)`` on the
flagged line or on the immediately preceding line.  The reason is
mandatory (an empty reason is itself a finding, rule ``waiver-syntax``)
and a waiver that no longer covers any finding is flagged too (rule
``stale-waiver``) — a removed violation must take its excuse with it.

**Baseline** (``LINT_BASELINE.json``): maps finding keys
(``rule|path|message``) to occurrence counts.  A finding beyond its
baselined count is NEW and fails the run; a baselined finding that no
longer fires is reported as prunable.  Keys are line-number-free so
unrelated edits above a pinned finding do not churn the baseline.
"""

from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Callable, Dict, Iterable, List, Optional, Tuple

def _find_repo_root() -> str:
    """The checkout to lint: the tree containing this package when it is
    a source checkout, else (installed copy: site-packages has no
    ``spark_timeseries_tpu`` SOURCE next to ``tools``) the cwd — so the
    ``ststpu-lint`` console script lints the user's checkout, never the
    installed copy."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.path.isdir(os.path.join(here, "spark_timeseries_tpu")) and \
            os.path.isfile(os.path.join(here, "pyproject.toml")):
        return here
    return os.getcwd()


REPO_ROOT = _find_repo_root()
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "LINT_BASELINE.json")
PACKAGE_DIR = "spark_timeseries_tpu"

WAIVER_RE = re.compile(r"#\s*lint:\s*([a-z][a-z0-9-]*)\s*\(([^)]*)\)")


@dataclass
class Finding:
    """One contract violation at one source location."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    waived: bool = False
    waiver_reason: Optional[str] = None

    @property
    def key(self) -> str:
        """Baseline identity: line-free so edits elsewhere don't churn."""
        return f"{self.rule}|{self.path}|{self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "waived": self.waived, "waiver_reason": self.waiver_reason}

    def render(self) -> str:
        tag = f" [waived: {self.waiver_reason}]" if self.waived else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}{tag}"


@dataclass
class Waiver:
    """One parsed ``# lint: rule(reason)`` comment."""

    rule: str
    reason: str
    line: int  # line the comment sits on
    used: bool = False


@dataclass
class LintModule:
    """One parsed target file, shared across checkers."""

    path: str  # repo-relative, forward slashes
    text: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)
    waivers: List[Waiver] = field(default_factory=list)

    @classmethod
    def from_source(cls, text: str, path: str) -> "LintModule":
        tree = ast.parse(text, filename=path)
        return cls(path=path.replace(os.sep, "/"), text=text, tree=tree,
                   lines=text.splitlines(),
                   waivers=collect_waivers(text))


def collect_waivers(text: str) -> List[Waiver]:
    """Parse every waiver comment via the tokenizer (so a ``# lint:``
    inside a string literal is not a waiver)."""
    out: List[Waiver] = []
    try:
        toks = tokenize.generate_tokens(StringIO(text).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            m = WAIVER_RE.search(tok.string)
            if m:
                out.append(Waiver(rule=m.group(1),
                                  reason=m.group(2).strip(),
                                  line=tok.start[0]))
    except tokenize.TokenError:
        pass
    return out


def apply_waivers(module: LintModule,
                  findings: List[Finding]) -> List[Finding]:
    """Mark findings covered by a waiver on their line or the line above;
    a waiver sitting on a ``def`` line (or the line above it) is SCOPED
    — it covers every finding of its rule inside that FUNCTION, for
    deliberate whole-region violations like the resilient ladder's
    host-side assembly (functions only: class bodies are too big for a
    one-line excuse).  Then append waiver-syntax / stale-waiver findings
    for bad or unused waivers."""
    import ast as _ast

    by_line: Dict[Tuple[int, str], Waiver] = {}
    for w in module.waivers:
        by_line[(w.line, w.rule)] = w
    # (start, end, rule) -> waiver for def-line waivers.  FUNCTIONS
    # only: a class-line waiver would blanket hundreds of lines while
    # reading as a one-line excuse, and stale-waiver detection could
    # never catch the overreach.
    scoped: List[Tuple[int, int, str, Waiver]] = []
    for node in _ast.walk(module.tree):
        if isinstance(node, (_ast.FunctionDef, _ast.AsyncFunctionDef)):
            for ln in (node.lineno, node.lineno - 1):
                for w in module.waivers:
                    if w.line == ln and w.reason:
                        scoped.append((node.lineno, node.end_lineno or
                                       node.lineno, w.rule, w))
    for f in findings:
        for ln in (f.line, f.line - 1):
            w = by_line.get((ln, f.rule))
            if w is not None and w.reason:
                f.waived = True
                f.waiver_reason = w.reason
                w.used = True
                break
        if not f.waived:
            for start, end, rule, w in scoped:
                if rule == f.rule and start <= f.line <= end:
                    f.waived = True
                    f.waiver_reason = w.reason
                    w.used = True
                    break
    extra: List[Finding] = []
    for w in module.waivers:
        if not w.reason:
            extra.append(Finding(
                rule="waiver-syntax", path=module.path, line=w.line, col=0,
                message=f"waiver for rule '{w.rule}' has an empty reason — "
                        "say WHY the violation is deliberate"))
        elif not w.used:
            extra.append(Finding(
                rule="stale-waiver", path=module.path, line=w.line, col=0,
                message=f"waiver for rule '{w.rule}' covers no finding — "
                        "the violation is gone, remove its excuse"))
    return findings + extra


# ---------------------------------------------------------------------------
# running
# ---------------------------------------------------------------------------


def _iter_target_files(root: str, paths: Optional[List[str]] = None):
    if paths:
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(root, p)
            if os.path.isdir(ap):
                yield from _iter_target_files(root, [
                    os.path.join(p, f) for f in sorted(os.listdir(ap))])
            elif ap.endswith(".py"):
                yield ap
        return
    pkg = os.path.join(root, PACKAGE_DIR)
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames.sort()
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_source(text: str, path: str,
                checkers: Optional[List[Callable]] = None) -> List[Finding]:
    """Lint one in-memory source blob as if it lived at ``path`` (repo
    relative) — the unit-test / self-test entry point."""
    from . import checkers as checkers_mod

    module = LintModule.from_source(text, path)
    found: List[Finding] = []
    for chk in (checkers if checkers is not None
                else checkers_mod.ALL_CHECKERS):
        found.extend(chk(module))
    found = apply_waivers(module, found)
    found.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return found


def lint_paths(root: str = REPO_ROOT,
               paths: Optional[List[str]] = None,
               checkers: Optional[List[Callable]] = None) -> List[Finding]:
    """Lint the package (or explicit ``paths``) under ``root``."""
    all_findings: List[Finding] = []
    for ap in _iter_target_files(root, paths):
        rel = os.path.relpath(ap, root).replace(os.sep, "/")
        with open(ap, encoding="utf-8") as f:
            text = f.read()
        try:
            all_findings.extend(lint_source(text, rel, checkers))
        except SyntaxError as e:
            all_findings.append(Finding(
                rule="parse-error", path=rel, line=e.lineno or 0, col=0,
                message=f"file does not parse: {e.msg}"))
    return all_findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str = DEFAULT_BASELINE) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def save_baseline(findings: List[Finding],
                  path: str = DEFAULT_BASELINE) -> None:
    counts: Dict[str, int] = {}
    for f in findings:
        if not f.waived:
            counts[f.key] = counts.get(f.key, 0) + 1
    payload = {
        "comment": "ststpu-lint baseline: known findings tracked to zero. "
                   "New findings FAIL; do not add entries to silence a "
                   "checker — fix the violation or waive it inline with "
                   "a reason (see python -m tools.lint --explain).",
        "findings": dict(sorted(counts.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")


def diff_baseline(findings: List[Finding], baseline: Dict[str, int]):
    """Split live findings into (new, known) vs the baseline and report
    baselined keys that no longer fire (prunable)."""
    live: Dict[str, List[Finding]] = {}
    for f in findings:
        if not f.waived and f.rule != "stale-waiver":
            live.setdefault(f.key, []).append(f)
    # stale-waiver findings always count as new: a baseline must not be
    # able to pin an unused excuse in place
    new: List[Finding] = [f for f in findings
                          if not f.waived and f.rule == "stale-waiver"]
    known: List[Finding] = []
    for key, fs in live.items():
        allowed = baseline.get(key, 0)
        known.extend(fs[:allowed])
        new.extend(fs[allowed:])
    prunable = sorted(k for k in baseline if k not in live)
    new.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return new, known, prunable
