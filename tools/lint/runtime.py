"""Runtime lock-discipline tracker: the lock-map contract, enforced live.

The static ``lock-map`` checker is a lexical approximation — it cannot
see cross-function lock holding, aliased containers, or code paths only
a real walk exercises.  This module closes the gap: it instruments the
classes named in ``contracts.LOCKMAP_RUNTIME_CLASSES`` so that, while a
tracker is installed,

- every lock assigned to a declared guard attribute is wrapped in an
  owner-tracking proxy (``Condition`` guards are rebuilt around a
  proxied inner lock, so waits and notify handoffs keep the owner
  accounting exact);
- every assignment to a declared protected attribute checks that the
  declared guard is held by the CURRENT thread (construction inside
  ``__init__`` is exempt — the object is not shared yet);
- dict/list/set values stored into protected attributes are wrapped in
  guarded containers whose mutating methods perform the same check
  (``server.counters["completed"] += 1`` is a subscript store, not an
  attribute store — this is how it stays visible).

Violations are RECORDED (class, attribute, thread, stack), never
raised mid-run — a tracker must not change the system's behavior, only
observe it.  ``tests/_lockdiscipline_worker.py --smoke`` (wired into
ci.sh) runs a real pipelined + sharded + serving walk under a tracker,
first proving the tracker itself catches a seeded violation, then
asserting the real walk produced none.
"""

from __future__ import annotations

import importlib
import threading
import traceback
from typing import Dict, List, Optional, Tuple

from . import contracts

_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))


class LockDisciplineViolation:
    """One observed mutation of a protected attribute without its lock."""

    def __init__(self, cls_name: str, attr: str, guard: str, kind: str):
        self.cls_name = cls_name
        self.attr = attr
        self.guard = guard
        self.kind = kind  # "attribute" | "container"
        self.thread = threading.current_thread().name
        self.stack = "".join(traceback.format_stack(limit=8)[:-2])

    def __repr__(self) -> str:
        return (f"<LockDisciplineViolation {self.cls_name}.{self.attr} "
                f"({self.kind}) guard={self.guard} thread={self.thread}>")

    def render(self) -> str:
        return (f"{self.cls_name}.{self.attr} mutated ({self.kind}) on "
                f"thread {self.thread!r} WITHOUT holding declared guard "
                f"`{self.guard}`\n{self.stack}")


class _OwnedLock:
    """Owner-tracking wrapper around a Lock/RLock (duck-typed: supports
    everything ``threading.Condition`` needs from a lock)."""

    def __init__(self, real):
        self._real = real
        self._owner: Optional[int] = None
        self._depth = 0

    def acquire(self, blocking=True, timeout=-1):
        got = (self._real.acquire(blocking) if timeout in (-1, None)
               else self._real.acquire(blocking, timeout))
        if got:
            self._owner = threading.get_ident()
            self._depth += 1
        return got

    def release(self):
        self._depth -= 1
        if self._depth <= 0:
            self._owner = None
            self._depth = 0
        self._real.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._real.locked() if hasattr(self._real, "locked") \
            else self._owner is not None

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    # Condition's fallback _is_owned probes acquire(False); give it the
    # real answer instead
    def _is_owned(self):
        return self.held_by_me()

    # Condition.wait() binds these at construction when the lock offers
    # them.  Without them, a REENTRANT hold (RLock-backed condition,
    # nested `with cond:`) would only release ONE level before waiting —
    # the waiter would sleep still holding the lock and every peer would
    # deadlock on code that is correct uninstrumented.  Full unwind +
    # restore keeps the tracker strictly observational.
    def _release_save(self):
        depth = self._depth
        self._owner = None
        self._depth = 0
        if hasattr(self._real, "_release_save"):
            return ("rlock", self._real._release_save(), depth)
        self._real.release()
        return ("lock", None, depth)

    def _acquire_restore(self, saved):
        kind, state, depth = saved
        if kind == "rlock":
            self._real._acquire_restore(state)
        else:
            self._real.acquire()
        self._owner = threading.get_ident()
        self._depth = depth


def _guard_held(obj, guard_path: str) -> Optional[bool]:
    """True/False when ownership is decidable for ``obj.<guard_path>``,
    None when the guard object cannot answer (left unchecked)."""
    target = obj
    for part in guard_path.split("."):
        target = getattr(target, part, None)
        if target is None:
            return None
    if isinstance(target, _OwnedLock):
        return target.held_by_me()
    if isinstance(target, threading.Condition):
        lock = getattr(target, "_lock", None)
        if isinstance(lock, _OwnedLock):
            return lock.held_by_me()
        try:
            return target._is_owned()  # RLock-backed: exact
        except Exception:  # noqa: BLE001 - foreign lock type
            return None
    if isinstance(target, _LOCK_TYPES):
        try:
            return target._is_owned()  # RLock: exact; Lock: no attr
        except AttributeError:
            return None  # plain Lock assigned before instrumentation
    return None


class _GuardedDict(dict):
    __slots__ = ("_ld_check",)


class _GuardedList(list):
    __slots__ = ("_ld_check",)


class _GuardedSet(set):
    __slots__ = ("_ld_check",)


def _add_guarded_mutators():
    def make(base, name):
        orig = getattr(base.__bases__[0], name, None)
        if orig is None:
            return

        def mutator(self, *a, **kw):
            check = getattr(self, "_ld_check", None)
            if check is not None:
                check()
            return orig(self, *a, **kw)

        mutator.__name__ = name
        setattr(base, name, mutator)

    for name in ("__setitem__", "__delitem__", "pop", "popitem", "clear",
                 "update", "setdefault"):
        make(_GuardedDict, name)
    for name in ("__setitem__", "__delitem__", "append", "extend",
                 "insert", "pop", "remove", "sort", "reverse", "__iadd__"):
        make(_GuardedList, name)
    for name in ("add", "discard", "remove", "pop", "clear", "update",
                 "__iand__", "__ior__", "__isub__", "__ixor__"):
        make(_GuardedSet, name)


_add_guarded_mutators()


class LockDisciplineTracker:
    """Installs/uninstalls the instrumentation; collects violations."""

    def __init__(self):
        self.violations: List[LockDisciplineViolation] = []
        # decidability accounting: a run whose checks were all
        # undecidable (guards created before install) proves nothing —
        # harnesses assert checks_decided > 0
        self.checks_total = 0
        self.checks_decided = 0
        self._installed: List[Tuple[type, dict]] = []
        self._tls = threading.local()
        self._mu = threading.Lock()

    # -- construction-phase bookkeeping ---------------------------------

    def _ctor_ids(self) -> set:
        ids = getattr(self._tls, "ctor_ids", None)
        if ids is None:
            ids = self._tls.ctor_ids = set()
        return ids

    def _record(self, v: LockDisciplineViolation) -> None:
        with self._mu:
            self.violations.append(v)

    # -- instrumentation -------------------------------------------------

    def install(self, classes=None) -> "LockDisciplineTracker":
        """Instrument ``classes`` (defaults to the contracts registry:
        every class may be a ``"module:Class"`` string or a type)."""
        specs = list(classes if classes is not None
                     else contracts.LOCKMAP_RUNTIME_CLASSES)
        for spec in specs:
            if isinstance(spec, str):
                mod_name, cls_name = spec.split(":")
                cls = getattr(importlib.import_module(mod_name), cls_name)
            else:
                cls = spec
            pmap = self._resolved_map(cls)
            if not pmap:
                raise ValueError(
                    f"{cls.__name__} declares no _protected_by_ map — "
                    "nothing to enforce")
            self._instrument(cls, pmap)
        return self

    @staticmethod
    def _resolved_map(cls) -> Dict[str, tuple]:
        pmap: Dict[str, tuple] = {}
        for base in reversed(cls.__mro__):
            m = base.__dict__.get("_protected_by_")
            if isinstance(m, dict):
                for k, v in m.items():
                    pmap[k] = (v,) if isinstance(v, str) else tuple(v)
        return pmap

    def _instrument(self, cls: type, pmap: Dict[str, tuple]) -> None:
        tracker = self
        guard_attrs = {g.split(".")[0] for gs in pmap.values() for g in gs
                       if "." not in g}
        orig_init = cls.__dict__.get("__init__", None)
        orig_setattr = cls.__dict__.get("__setattr__", None)
        saved = {"__init__": orig_init, "__setattr__": orig_setattr}

        base_init = cls.__init__

        def wrapped_init(self, *a, **kw):
            ids = tracker._ctor_ids()
            ids.add(id(self))
            try:
                return base_init(self, *a, **kw)
            finally:
                ids.discard(id(self))

        base_setattr = cls.__setattr__ if orig_setattr is not None \
            else object.__setattr__

        def wrapped_setattr(self, name, value):
            if name in guard_attrs and isinstance(
                    value, _LOCK_TYPES + (threading.Condition,)):
                value = tracker._wrap_guard(value)
            if name in pmap and id(self) not in tracker._ctor_ids():
                tracker._check(self, cls, name, pmap[name], "attribute")
            if name in pmap:
                value = tracker._wrap_container(self, cls, name,
                                                pmap[name], value)
            return base_setattr(self, name, value)

        cls.__init__ = wrapped_init
        cls.__setattr__ = wrapped_setattr
        self._installed.append((cls, saved))

    def _wrap_guard(self, value):
        if isinstance(value, threading.Condition):
            inner = getattr(value, "_lock", None)
            if inner is not None and not isinstance(inner, _OwnedLock):
                return threading.Condition(_OwnedLock(inner))
            return value
        if isinstance(value, _OwnedLock):
            return value
        return _OwnedLock(value)

    def _wrap_container(self, obj, cls, attr, guards, value):
        wrapped = None
        if type(value) is dict:
            wrapped = _GuardedDict(value)
        elif type(value) is list:
            wrapped = _GuardedList(value)
        elif type(value) is set:
            wrapped = _GuardedSet(value)
        if wrapped is None:
            return value
        tracker = self

        def check():
            if id(obj) not in tracker._ctor_ids():
                tracker._check(obj, cls, attr, guards, "container")

        wrapped._ld_check = check
        return wrapped

    def _check(self, obj, cls, attr, guards, kind) -> None:
        with self._mu:
            self.checks_total += 1
        decidable = False
        for g in guards:
            held = _guard_held(obj, g)
            if held is True:
                with self._mu:
                    self.checks_decided += 1
                return
            if held is not None:
                decidable = True
        if decidable:
            with self._mu:
                self.checks_decided += 1
            self._record(LockDisciplineViolation(
                cls.__name__, attr, " or ".join(guards), kind))

    # -- teardown --------------------------------------------------------

    def uninstall(self) -> None:
        for cls, saved in reversed(self._installed):
            for name, orig in saved.items():
                if orig is None:
                    try:
                        delattr(cls, name)
                    except AttributeError:
                        pass
                else:
                    setattr(cls, name, orig)
        self._installed.clear()

    def __enter__(self) -> "LockDisciplineTracker":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def report(self) -> str:
        if not self.violations:
            return "lock-discipline: no violations"
        out = [f"lock-discipline: {len(self.violations)} violation(s):"]
        out.extend(v.render() for v in self.violations)
        return "\n".join(out)
