"""Seeded-violation self-test: prove each checker still catches a
violation of its contract (and stays quiet on the clean twin).

A linter that silently stops matching is worse than no linter — CI
would go green on a broken guard.  ``python -m tools.lint --self-test``
(run by ci.sh before the real lint) feeds every checker a positive
fixture (must flag) and a negative fixture (must not), plus waiver
parsing and baseline-diff round trips.  Any miss exits nonzero.

The same fixtures back ``tests/test_lint.py``; they live here so the
CI gate and the test suite cannot drift apart.
"""

from __future__ import annotations

import functools
import textwrap
from typing import Callable, Dict, List, Optional, Tuple

from .checkers import confighash, hostsync, journalwriter, lockmap, \
    nondet, obsinert
from .engine import Finding, lint_source

HOT = "spark_timeseries_tpu/reliability/fixture.py"
LIB = "spark_timeseries_tpu/fixture.py"


def _fix(s: str) -> str:
    return textwrap.dedent(s).lstrip("\n")


# each entry: rule (optionally "rule/variant" for extra seeded cases of
# one rule) -> (path, bad source, good source, checkers-or-None)
FIXTURES: Dict[str, Tuple[str, str, str, Optional[List[Callable]]]] = {}


def fixture_rule(key: str) -> str:
    """The rule a fixture key seeds (keys may carry a '/variant')."""
    return key.split("/", 1)[0]

FIXTURES["host-sync"] = (HOT, _fix("""
    import jax.numpy as jnp

    def walk(y):
        nll = jnp.sum(y)
        if nll > 0:            # truthiness on a device value
            return float(nll)  # host-blocking cast
        return nll.item()      # explicit transfer
    """), _fix("""
    import jax.numpy as jnp

    def walk(y, meta):
        nll = jnp.sum(y)
        rows = int(meta["rows"])        # host value: fine
        if meta is None or rows > 0:    # host-side test: fine
            return nll
        return jnp.where(nll > 0, nll, 0.0)   # stays on device
    """), [hostsync.check])

_SURFACES = {
    f"{HOT}::fit_fixture": {
        "kwargs_param": "fit_kwargs",
        "hashed": {"chunk_rows": "extra= key 'chunk_rows'"},
        "extra_keys": ("chunk_rows",),
        "excluded": {"pipeline": "moves I/O between threads only"},
    },
}

FIXTURES["config-hash"] = (HOT, _fix("""
    def fit_fixture(*, chunk_rows=None, pipeline=True, new_knob=0,
                    **fit_kwargs):
        cfg = config_hash(fit_fixture, fit_kwargs,
                          extra={"chunk_rows": chunk_rows})
        return cfg
    """), _fix("""
    def fit_fixture(*, chunk_rows=None, pipeline=True, **fit_kwargs):
        cfg = config_hash(fit_fixture, fit_kwargs,
                          extra={"chunk_rows": chunk_rows})
        return cfg
    """), [functools.partial(confighash.check, surfaces=_SURFACES)])

# ISSUE 14: the forecasting surfaces joined the registries — seed a
# violation of each NEW entry shape so a checker that stopped matching
# them cannot pass vacuously.  (a) config-hash: a forecast-walk-shaped
# surface grows an unregistered knob; (b) journal-writer: a rogue helper
# writes backtest_manifest.json outside the registered owner.
_FC = "spark_timeseries_tpu/forecasting/fixture.py"
_FC_SURFACES = {
    f"{_FC}::forecast_fixture": {
        "hashed": {"horizon": "forecast_fit kwarg (hashed)",
                   "seed": "resolved into base_seed (hashed)"},
        "excluded": {"checkpoint_dir": "journal location, not identity"},
    },
}

FIXTURES["config-hash/forecast"] = (_FC, _fix("""
    def forecast_fixture(*, horizon=1, seed=None, checkpoint_dir=None,
                         band_style=None):
        return horizon, seed, checkpoint_dir, band_style
    """), _fix("""
    def forecast_fixture(*, horizon=1, seed=None, checkpoint_dir=None):
        return horizon, seed, checkpoint_dir
    """), [functools.partial(confighash.check, surfaces=_FC_SURFACES)])

# ISSUE 15: the delta-walk knobs joined the fit_chunked registry entry —
# seed a violation of that shape (a delta-shaped surface growing an
# unregistered delta knob) so a checker that stopped cross-checking the
# driver signature cannot pass vacuously.
_DELTA = "spark_timeseries_tpu/reliability/fixture_delta.py"
_DELTA_SURFACES = {
    f"{_DELTA}::delta_fixture": {
        "kwargs_param": "fit_kwargs",
        "hashed": {"chunk_rows": "extra= key 'chunk_rows'",
                   "delta_warmstart": "resolves into the warm wrapper "
                                      "fit_fn + augmented fingerprint"},
        "extra_keys": ("chunk_rows",),
        "excluded": {"delta_from": "adoption source location; results "
                                   "bitwise the full walk's"},
    },
}

FIXTURES["config-hash/delta"] = (_DELTA, _fix("""
    def delta_fixture(*, chunk_rows=None, delta_from=None,
                      delta_warmstart=True, delta_adopt_torn=False,
                      **fit_kwargs):
        cfg = config_hash(delta_fixture, fit_kwargs,
                          extra={"chunk_rows": chunk_rows})
        return cfg
    """), _fix("""
    def delta_fixture(*, chunk_rows=None, delta_from=None,
                      delta_warmstart=True, **fit_kwargs):
        cfg = config_hash(delta_fixture, fit_kwargs,
                          extra={"chunk_rows": chunk_rows})
        return cfg
    """), [functools.partial(confighash.check, surfaces=_DELTA_SURFACES)])

_FC_OWNERS = {_FC: {"_write_backtest_manifest":
                    "sole writer of the campaign manifest"}}

FIXTURES["journal-writer/backtest"] = (_FC, _fix("""
    import os

    def rogue_campaign_note(root, data):
        path = os.path.join(root, "backtest_manifest.json")
        with open(path, "w") as f:     # unregistered writer
            f.write(data)
    """), _fix("""
    import os

    def _write_backtest_manifest(root, data):
        path = os.path.join(root, "backtest_manifest.json")
        with open(path, "w") as f:
            f.write(data)
        os.replace(path, path)
    """), [functools.partial(journalwriter.check, owners=_FC_OWNERS)])

# ISSUE 16: the fleet's socket plane joined the registries — seed a
# violation of each NEW entry shape so a checker that stopped matching
# them cannot pass vacuously.  (a) journal-writer: a rogue socket
# handler writes an endpoint advert (the fleet discovery namespace)
# directly instead of routing through the registered advertise_endpoint
# owner; (b) lock-map: a transport-server-shaped class mutates its
# connection registry outside the declared lock — the exact shape the
# accept loop / stop() race would take.
_FLEET = "spark_timeseries_tpu/serving/fixture_fleet.py"
_FLEET_OWNERS = {_FLEET: {"advertise_endpoint":
                          "sole writer of the endpoints/ namespace"}}

FIXTURES["journal-writer/fleet"] = (_FLEET, _fix("""
    import json
    import os

    def rogue_handler_advert(root, owner, port):
        path = os.path.join(root, "endpoints", owner + ".json")
        with open(path, "w") as f:     # unregistered writer
            f.write(json.dumps({"port": port}))
    """), _fix("""
    import json
    import os

    def advertise_endpoint(root, owner, port):
        path = os.path.join(root, "endpoints", owner + ".json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps({"port": port}))
        os.replace(tmp, path)
    """), [functools.partial(journalwriter.check, owners=_FLEET_OWNERS)])

FIXTURES["lock-map/transport"] = (_FLEET, _fix("""
    import threading

    class WireServer:
        _protected_by_ = {"_conns": "_conns_lock"}

        def __init__(self):
            self._conns_lock = threading.Lock()
            self._conns = []

        def _accept_loop(self, conn):
            self._conns.append(conn)   # registration outside the lock
    """), _fix("""
    import threading

    class WireServer:
        _protected_by_ = {"_conns": "_conns_lock"}

        def __init__(self):
            self._conns_lock = threading.Lock()
            self._conns = []

        def _accept_loop(self, conn):
            with self._conns_lock:
                self._conns.append(conn)

        def _drain_locked(self):
            out, self._conns = self._conns, []
            return out
    """), [lockmap.check])

# ISSUE 17: the chaos plane and the client's endpoint-health cache
# joined the registries — seed a violation of each NEW entry shape so a
# checker that stopped matching them cannot pass vacuously.  (a)
# journal-writer: a rogue reporter writes chaos_manifest.json (the
# scenario record namespace) directly instead of routing through the
# registered write_chaos_manifest owner; (b) lock-map: a health-cache-
# shaped class mutates its per-endpoint records outside the declared
# lock — the exact shape the reply-site recording / hedge-thread race
# would take.
_CHAOS = "spark_timeseries_tpu/reliability/fixture_chaos.py"
_CHAOS_OWNERS = {_CHAOS: {"write_chaos_manifest":
                          "sole writer of chaos_manifest.json"}}

FIXTURES["journal-writer/chaos"] = (_CHAOS, _fix("""
    import json
    import os

    def rogue_scenario_note(root, manifest):
        path = os.path.join(root, "chaos_manifest.json")
        with open(path, "w") as f:     # unregistered writer
            f.write(json.dumps(manifest, sort_keys=True))
    """), _fix("""
    import json
    import os

    def write_chaos_manifest(root, manifest):
        path = os.path.join(root, "chaos_manifest.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(manifest, sort_keys=True))
        os.replace(tmp, path)
    """), [functools.partial(journalwriter.check, owners=_CHAOS_OWNERS)])

_HEALTH = "spark_timeseries_tpu/serving/fixture_health.py"

FIXTURES["lock-map/health"] = (_HEALTH, _fix("""
    import threading

    class HealthCache:
        _protected_by_ = {"_records": "_lock", "_primary": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._records = {}
            self._primary = None

        def record_failure(self, ep):
            self._records[ep] = "open"   # mutation outside the lock
            self._primary = None
    """), _fix("""
    import threading

    class HealthCache:
        _protected_by_ = {"_records": "_lock", "_primary": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._records = {}
            self._primary = None

        def record_failure(self, ep):
            with self._lock:
                self._records[ep] = "open"
                self._primary = None
    """), [lockmap.check])

# ISSUE 18: the tracing plane joined the registries — seed a violation
# of each NEW entry shape so a checker that stopped matching them
# cannot pass vacuously.  (a) obs-inert: library code reaching the new
# obs.tracing submodule directly (deriving ids / toggling the plane)
# instead of the facade names obs/__init__ exports; (b) journal-writer:
# a rogue helper writes the client's <stream>.clock.json offset sidecar
# outside the registered FitClient._write_clock_journal owner.
FIXTURES["obs-inert/tracing"] = (LIB, _fix("""
    from .obs import tracing

    def stamp(req_id):
        ctx = obs.tracing.trace_for_request(req_id)
        tracing.set_plane(True)
        return ctx
    """), _fix("""
    from . import obs

    def stamp(req_id):
        with obs.trace_scope(obs.trace_for_request(req_id, "client")):
            obs.event("client.submit", req_id=req_id)
        return obs.current_trace()
    """), [obsinert.check])

_CLOCK = "spark_timeseries_tpu/serving/fixture_clock.py"
_CLOCK_OWNERS = {_CLOCK: {"FitClient._write_clock_journal":
                          "sole writer of the clock-offset sidecar"}}

FIXTURES["journal-writer/clock"] = (_CLOCK, _fix("""
    import json

    def rogue_offset_note(stream_path, clock):
        path = stream_path + ".clock.json"
        with open(path, "w") as f:     # unregistered writer
            f.write(json.dumps(clock, sort_keys=True))
    """), _fix("""
    import json

    class FitClient:
        def _write_clock_journal(self, stream_path, clock):
            path = stream_path + ".clock.json"
            with open(path, "w") as f:
                f.write(json.dumps(clock, sort_keys=True))
    """), [functools.partial(journalwriter.check, owners=_CLOCK_OWNERS)])

# ISSUE 19: the warm auto-fit plane joined the registries — seed a
# violation of each NEW entry shape so a checker that stopped matching
# them cannot pass vacuously.  (a) config-hash: an auto-search-shaped
# surface grows an unregistered stepwise knob; (b) journal-writer: a
# rogue helper writes a tenant profile npz (the profiles/ namespace)
# directly instead of routing through the registered TenantProfileStore
# owner; (c) lock-map: a profile-store-shaped class mutates its read
# cache outside the declared lock — the exact shape the serve-loop
# update / caller-thread classify race would take.
_AUTO = "spark_timeseries_tpu/models/fixture_auto.py"
_AUTO_SURFACES = {
    f"{_AUTO}::auto_fixture": {
        "kwargs_param": "fit_kwargs",
        "hashed": {"orders": "each order's walk fit_fn identity"},
        "excluded": {"stepwise": "expansion-plan selection; passes "
                                 "journal under their own namespaces",
                     "stepwise_max_passes": "bounds expansion rounds "
                                            "(deterministic replay)"},
    },
}

FIXTURES["config-hash/stepwise"] = (_AUTO, _fix("""
    def auto_fixture(*, orders=None, stepwise=False,
                     stepwise_max_passes=8, stepwise_seed_jitter=0,
                     **fit_kwargs):
        return orders, stepwise, stepwise_max_passes, stepwise_seed_jitter
    """), _fix("""
    def auto_fixture(*, orders=None, stepwise=False,
                     stepwise_max_passes=8, **fit_kwargs):
        return orders, stepwise, stepwise_max_passes
    """), [functools.partial(confighash.check, surfaces=_AUTO_SURFACES)])

_PROFILES = "spark_timeseries_tpu/serving/fixture_profiles.py"
_PROFILES_OWNERS = {_PROFILES: {"TenantProfileStore":
                                "sole writer of the profiles/ namespace"}}

FIXTURES["journal-writer/profiles"] = (_PROFILES, _fix("""
    import json
    import os

    def rogue_profile_note(root, tenant, arrays):
        path = os.path.join(root, "profiles", tenant + ".npz")
        with open(path, "wb") as f:     # unregistered writer
            f.write(json.dumps(arrays).encode())
    """), _fix("""
    import os

    class TenantProfileStore:
        def update(self, root, tenant, write):
            path = os.path.join(root, "profiles", tenant + ".npz")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                write(f)
            os.replace(tmp, path)
    """), [functools.partial(journalwriter.check,
                             owners=_PROFILES_OWNERS)])

FIXTURES["lock-map/profiles"] = (_PROFILES, _fix("""
    import threading

    class ProfileStore:
        _protected_by_ = {"_cache": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._cache = {}

        def load(self, tenant, key, prof):
            self._cache[tenant] = (key, prof)   # mutation outside lock
    """), _fix("""
    import threading

    class ProfileStore:
        _protected_by_ = {"_cache": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._cache = {}

        def load(self, tenant, key, prof):
            with self._lock:
                self._cache[tenant] = (key, prof)
    """), [lockmap.check])

# ISSUE 20: the streaming plane joined the registries — seed a
# violation of each NEW entry shape so a checker that stopped matching
# them cannot pass vacuously.  (a) config-hash: a write-back-walk-shaped
# surface grows an unregistered sink knob next to the registered one;
# (b) journal-writer: a rogue helper writes an out_*.npz shard into a
# sink directory outside the registered WritableChunkSource owner; (c)
# journal-writer: a rogue helper writes a cycle's tick_manifest.json
# outside the registered TickLoop owner; (d) lock-map: a sink-shaped
# class mutates its queue accounting outside the declared lock — the
# exact shape the driver-enqueue / writer-thread race would take.
_SINK = "spark_timeseries_tpu/reliability/fixture_sink.py"
_SINK_SURFACES = {
    f"{_SINK}::sink_fixture": {
        "kwargs_param": "fit_kwargs",
        "hashed": {"chunk_rows": "extra= key 'chunk_rows'"},
        "extra_keys": ("chunk_rows",),
        "excluded": {"sink": "write-back destination; journal bytes "
                             "identical either way"},
    },
}

FIXTURES["config-hash/sink"] = (_SINK, _fix("""
    def sink_fixture(*, chunk_rows=None, sink=None, sink_compress=False,
                     **fit_kwargs):
        cfg = config_hash(sink_fixture, fit_kwargs,
                          extra={"chunk_rows": chunk_rows})
        return cfg
    """), _fix("""
    def sink_fixture(*, chunk_rows=None, sink=None, **fit_kwargs):
        cfg = config_hash(sink_fixture, fit_kwargs,
                          extra={"chunk_rows": chunk_rows})
        return cfg
    """), [functools.partial(confighash.check, surfaces=_SINK_SURFACES)])

_SINK_OWNERS = {_SINK: {"WritableChunkSource":
                        "sole writer of its output shard directory"}}

FIXTURES["journal-writer/sink"] = (_SINK, _fix("""
    import numpy as np

    def rogue_shard_note(directory, lo, hi, arrays):
        path = "%s/out_%09d_%09d.npz" % (directory, lo, hi)
        np.savez(path, **arrays)       # unregistered writer
    """), _fix("""
    import os

    import numpy as np

    class WritableChunkSource:
        def _write_one(self, directory, lo, hi, arrays):
            path = "%s/out_%09d_%09d.npz" % (directory, lo, hi)
            tmp = path + ".tmp"
            np.savez(tmp, **arrays)
            os.replace(tmp, path)
    """), [functools.partial(journalwriter.check, owners=_SINK_OWNERS)])

_TICK = "spark_timeseries_tpu/serving/fixture_tickloop.py"
_TICK_OWNERS = {_TICK: {"TickLoop": "sole writer of its loop root"}}

FIXTURES["journal-writer/tickloop"] = (_TICK, _fix("""
    import json
    import os

    def rogue_cycle_note(cycle_dir, manifest):
        path = os.path.join(cycle_dir, "tick_manifest.json")
        with open(path, "w") as f:     # unregistered writer
            f.write(json.dumps(manifest, sort_keys=True))
    """), _fix("""
    import json
    import os

    class TickLoop:
        def _write_cycle_manifest(self, cycle_dir, manifest):
            path = os.path.join(cycle_dir, "tick_manifest.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(json.dumps(manifest, sort_keys=True))
            os.replace(tmp, path)
    """), [functools.partial(journalwriter.check, owners=_TICK_OWNERS)])

FIXTURES["lock-map/sink"] = (_SINK, _fix("""
    import threading

    class WriteBackSink:
        _protected_by_ = {"_in_flight": "_lock", "_spans": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._in_flight = 0
            self._spans = []

        def write(self, lo, hi, nbytes):
            self._in_flight += nbytes   # mutation outside the lock
            self._spans.append((lo, hi))
    """), _fix("""
    import threading

    class WriteBackSink:
        _protected_by_ = {"_in_flight": "_lock", "_spans": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._in_flight = 0
            self._spans = []

        def write(self, lo, hi, nbytes):
            with self._lock:
                self._in_flight += nbytes
                self._spans.append((lo, hi))
    """), [lockmap.check])

_OWNERS = {HOT: {"Owner": "fixture namespace owner"}}

FIXTURES["journal-writer"] = (HOT, _fix("""
    import os

    def rogue_helper(path, data):
        with open(path, "w") as f:     # unregistered writer
            f.write(data)
        os.replace(path, path + ".bak")
    """), _fix("""
    import os

    class Owner:
        def write(self, path, data):
            with open(path, "w") as f:
                f.write(data)
            os.replace(path, path + ".bak")

    def reader(path):
        with open(path) as f:
            return f.read()
    """), [functools.partial(journalwriter.check, owners=_OWNERS)])

FIXTURES["lock-map"] = (HOT, _fix("""
    import threading

    class Shared:
        _protected_by_ = {"_pending": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._pending = []

        def submit(self, item):
            self._pending.append(item)   # mutation outside the lock

    class Undeclared:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
    """), _fix("""
    import threading

    class Shared:
        _protected_by_ = {"_pending": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._pending = []

        def submit(self, item):
            with self._lock:
                self._pending.append(item)

        def _drain_locked(self):
            out, self._pending = self._pending, []
            return out
    """), [lockmap.check])

FIXTURES["obs-inert"] = (LIB, _fix("""
    from .obs import core
    from .obs.promsink import PromTextfileSink

    def run():
        obs.enable("run.jsonl")
    """), _fix("""
    from . import obs

    def run(lo, hi):
        with obs.span("chunk", lo=lo, hi=hi):
            obs.counter("chunks").inc()
        return obs.enabled()
    """), [obsinert.check])

FIXTURES["nondet"] = (HOT, _fix("""
    import json, time, hashlib
    import numpy as np

    def stamp(cfg):
        t = time.time()
        noise = np.random.normal(size=3)
        key = hashlib.sha256(json.dumps(cfg).encode())
        return t, noise, key, hash(("a", "b"))
    """), _fix("""
    import json, time, hashlib
    import numpy as np

    def stamp(cfg, seed):
        t = time.perf_counter()
        rng = np.random.default_rng(seed)
        noise = rng.normal(size=3)
        key = hashlib.sha256(
            json.dumps(cfg, sort_keys=True).encode())
        return t, noise, key
    """), [nondet.check])


WAIVER_FIXTURE = (HOT, _fix("""
    import time

    def stamp():
        # lint: nondet(manifest wall-clock metadata; never fitted bytes)
        return time.time()

    def stale():
        return time.perf_counter()  # lint: nondet(covers nothing now)

    def empty():
        return time.time()  # lint: nondet()
    """), [nondet.check])


def _only(rule: str, findings: List[Finding],
          include_waived: bool = False) -> List[Finding]:
    return [f for f in findings if f.rule == rule
            and (include_waived or not f.waived)]


def run_self_test(verbose: bool = True) -> List[str]:
    """Returns a list of failure descriptions (empty = pass)."""
    failures: List[str] = []
    for key, (path, bad, good, checkers) in FIXTURES.items():
        rule = fixture_rule(key)
        got_bad = _only(rule, lint_source(bad, path, checkers))
        got_good = _only(rule, lint_source(good, path, checkers))
        if not got_bad:
            failures.append(
                f"{key}: checker MISSED its seeded violation — the "
                "guard is broken")
        if got_good:
            failures.append(
                f"{key}: checker flagged the clean fixture: "
                + "; ".join(f.message for f in got_good))
        if verbose and not failures:
            pass
    # waiver machinery: waived finding suppressed, stale + empty flagged
    path, src, checkers = WAIVER_FIXTURE
    res = lint_source(src, path, checkers)
    if not any(f.rule == "nondet" and f.waived for f in res):
        failures.append("waivers: a reasoned waiver did not suppress "
                        "its finding")
    if not any(f.rule == "stale-waiver" for f in res):
        failures.append("waivers: an unused waiver was not flagged stale")
    if not any(f.rule == "waiver-syntax" for f in res):
        failures.append("waivers: an empty-reason waiver was not flagged")
    # baseline diff round trip
    from .engine import diff_baseline

    live = _only("nondet", lint_source(
        FIXTURES["nondet"][1], FIXTURES["nondet"][0],
        FIXTURES["nondet"][3]))
    base = {f.key: 1 for f in live}
    new, known, prunable = diff_baseline(live, base)
    if new or len(known) != len(live):
        failures.append("baseline: fully-baselined findings reported "
                        "as new")
    new2, _known2, _ = diff_baseline(live, {})
    if len(new2) != len(live):
        failures.append("baseline: un-baselined findings not reported "
                        "as new")
    _new3, _k3, prunable3 = diff_baseline([], base)
    if len(prunable3) != len(base):
        failures.append("baseline: fixed findings not reported prunable")
    return failures
