"""End-to-end tour: ingest -> clean -> analyze -> model -> persist.

Mirrors the reference's canonical workflow (observations DataFrame ->
TimeSeriesRDD -> fill -> per-series models) on the TPU-native panel.
Runs anywhere (CPU included): ``python examples/quickstart.py``.
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import spark_timeseries_tpu as sts  # noqa: E402
from spark_timeseries_tpu import index as dtix  # noqa: E402
from spark_timeseries_tpu.models import arima, holtwinters  # noqa: E402
from spark_timeseries_tpu.stats import tests as st  # noqa: E402


def main():
    rng = np.random.default_rng(0)

    # --- 1. a shared calendar index (business days, like the reference) ----
    idx = dtix.uniform("2022-01-03", 520, dtix.BusinessDayFrequency(1))
    print(f"index: {idx.size} business days "
          f"{idx.first} .. {idx.last}")

    # --- 2. ingest long-format observations (the groupByKey replacement) ---
    n_series, n_obs = 64, 480
    keys = [f"ticker{i:03d}" for i in range(n_series)]
    obs_keys, obs_ts, obs_vals = [], [], []
    dts = idx.datetimes()
    for k in keys:
        locs = np.sort(rng.choice(idx.size, size=n_obs, replace=False))
        walk = np.cumsum(rng.normal(0.05, 1.0, n_obs)) + 100.0
        obs_keys += [k] * n_obs
        obs_ts.append(dts[locs])
        obs_vals.append(walk)
    panel = sts.from_observations(
        idx, obs_keys, np.concatenate(obs_ts), np.concatenate(obs_vals)
    )
    print(f"panel: {panel.n_series} series x {panel.n_time} instants "
          f"({float(jnp.mean(jnp.isnan(panel.series_values()))):.0%} missing)")

    # --- 3. impute + transform (vmapped kernels, one device dispatch) ------
    filled = panel.fill("linear").fill("previous").fill("next")
    returns = filled.return_rates()
    acf = filled.autocorr(5)
    print("lag-1 autocorrelation, first 3 series:",
          np.round(np.asarray(acf[:3, 0]), 3))

    # --- 4. statistical tests over the whole panel -------------------------
    taus, ps = st.batch_adftest(filled.series_values())
    print(f"ADF: {float((np.asarray(ps) > 0.10).mean()):.0%} of series keep "
          "the unit root at 10% (random walks: expected ~all)")

    # --- 5. fit a model per series in ONE compiled program -----------------
    fit = arima.fit(filled.series_values(), (1, 1, 1))
    print(f"ARIMA(1,1,1): {float(jnp.mean(fit.converged)):.0%} converged, "
          f"median phi = {float(jnp.nanmedian(fit.params[:, 1])):.3f}")
    fc = arima.forecast(fit.params, filled.series_values(), (1, 1, 1), 5)
    print("5-step forecast, series 0:", np.round(np.asarray(fc[0]), 2))

    # --- 6. seasonal workload (Holt-Winters) --------------------------------
    hours = dtix.uniform("2024-01-01", 24 * 28, dtix.HourFrequency(1))
    tt = np.arange(hours.size, dtype=np.float32)
    load = (
        50 + 0.01 * tt[None, :]
        + 8 * np.sin(2 * np.pi * tt[None, :] / 24 + rng.uniform(0, 6, (32, 1)))
        + rng.normal(0, 1, (32, hours.size))
    ).astype(np.float32)
    hw_fit = holtwinters.fit(jnp.asarray(load), period=24)
    print(f"HoltWinters: {float(jnp.mean(hw_fit.converged)):.0%} converged, "
          f"median alpha = {float(jnp.nanmedian(hw_fit.params[:, 0])):.3f}")

    # --- 7. persist + reload ------------------------------------------------
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "panel.parquet")
        try:
            filled.save_parquet(path)
            back = sts.TimeSeriesPanel.load_parquet(path)
            kind = "parquet"
        except ImportError:  # no pyarrow: fall back to npz
            path = os.path.join(td, "panel.npz")
            filled.save(path)
            back = sts.TimeSeriesPanel.load(path)
            kind = "npz"
        assert back.index == filled.index
        print(f"persistence round-trip OK ({kind})")

    print("quickstart complete")


if __name__ == "__main__":
    main()
