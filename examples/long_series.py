"""Long-series (sequence-parallel) tour: one series too big for one chip.

The reference never shards a single series — a series is one JVM vector, so
its length is bounded by executor memory (SURVEY.md Section 5.7).  Here the
time axis of a ``[keys, time]`` panel is split across the ``time`` axis of a
2-D device mesh and within-series work runs as local kernels + ICI
collectives under ``shard_map``: moments/autocorrelation (halo exchange for
lagged cross terms), linear-interpolation fill (carry hand-off of the
nearest-valid summaries), differencing, and EWMA smoothing (log-depth
affine-carry scan).

Runs anywhere: with no accelerator attached, force an 8-device CPU mesh —
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_series.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS"):  # the TPU shim may override the env var
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp  # noqa: E402

from spark_timeseries_tpu.ops import seqparallel as sp  # noqa: E402
from spark_timeseries_tpu.ops import univariate as uv  # noqa: E402
from spark_timeseries_tpu.parallel import mesh as meshlib  # noqa: E402


def main():
    n_dev = len(jax.devices())
    if n_dev < 2:
        print(f"only {n_dev} device visible — sequence parallelism needs a "
              "time-sharded mesh; rerun with\n  JAX_PLATFORMS=cpu "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
              "python examples/long_series.py")
        return
    mesh = meshlib.default_mesh(time_shards=2)
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"on {n_dev} {jax.devices()[0].platform} device(s)")

    # a gappy panel: series axis AND time axis both sharded
    rng = np.random.default_rng(0)
    keys, t = 8, 4096
    vals = rng.normal(size=(keys, t)).cumsum(axis=1).astype(np.float32)
    vals[rng.random((keys, t)) < 0.1] = np.nan
    panel = jax.device_put(jnp.asarray(vals), meshlib.series_sharding(mesh))

    # distributed fill -> difference -> lag feature chain (each shard fills
    # from GLOBAL bracketing observations; the lag crosses shard boundaries
    # through a one-column halo)
    filled, diff, lagged = sp.sp_fill_linear_chain_sharded(mesh, panel)
    print(f"filled NaNs: {int(jnp.isnan(panel).sum())} -> "
          f"{int(jnp.isnan(filled).sum())} (edges only)")

    # distributed moments + autocorrelation (psum + halo over ICI)
    stats = sp.sp_moments_sharded(mesh, filled)
    ac = sp.sp_autocorr_sharded(mesh, jnp.nan_to_num(filled), 5)
    print(f"mean[0]={float(stats['mean'][0]):+.3f}  "
          f"autocorr[0,:3]={np.asarray(ac[0][:3]).round(4)}")

    # cross-check against the single-device kernels
    ref = uv.batch_autocorr(5, backend="scan")(jnp.nan_to_num(
        jax.vmap(uv.fill_linear)(jnp.asarray(vals))))
    np.testing.assert_allclose(np.asarray(ac), np.asarray(ref), atol=1e-4)
    print("sequence-parallel results match the unsharded kernels")

    # time-sharded model FIT: the whole CSS objective (differencing,
    # Yule-Walker init, the error recursion as a log-depth affine scan, the
    # batched L-BFGS) runs with the series split across the time axis — the
    # reference cannot fit a series longer than one executor's memory.
    # (A fresh dense panel: the filled one keeps its EDGE NaNs by design,
    # and zero-stuffing those would corrupt the fit.)
    dense = jax.device_put(
        jnp.asarray(rng.normal(size=(keys, t)).cumsum(axis=1)
                    .astype(np.float32)),
        meshlib.series_sharding(mesh))
    fit = sp.sp_arima_fit(mesh, dense, (1, 1, 1))
    print(f"time-sharded ARIMA(1,1,1): params[0]="
          f"{np.asarray(fit.params[0]).round(4)}  "
          f"converged={float(jnp.mean(fit.converged.astype(jnp.float32))):.2f}")


if __name__ == "__main__":
    main()
