"""Marginal per-panel cost of autocorr when the panel is already resident in
the folded [T, B/128, 128] device layout (fold amortized at ingest)."""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, "/root/repo")
from spark_timeseries_tpu.ops import pallas_kernels as pk


def autocorr_folded(y3, b, t, num_lags):
    tp, cs, nchunk = pk._time_layout(t)
    assert nchunk == 1
    nblk = y3.shape[1] // pk._SUBL
    acc3 = pl.pallas_call(
        functools.partial(pk._autocorr_kernel, num_lags, t, cs, True),
        grid=(nblk, nchunk),
        in_specs=[pk._bs(cs, pk._cur)],
        out_specs=pk._bs(num_lags + 1, pk._fixed),
        out_shape=jax.ShapeDtypeStruct((num_lags + 1, y3.shape[1], pk._LANES),
                                       jnp.float32),
        scratch_shapes=[pk.pltpu.VMEM((num_lags, pk._SUBL, pk._LANES), jnp.float32)],
        compiler_params=pk._VMEM_PARAMS,
    )(y3)
    acc = pk._unfold(acc3, b)
    return acc[:, 1:] / acc[:, :1]


def main():
    b, t, nl = 131_072, 1000, 10
    K = 8
    rng = np.random.default_rng(0)
    y = np.cumsum(rng.normal(size=(b, t)), axis=1).astype(np.float32)
    yd = jnp.asarray(y)
    tp, cs, nchunk = pk._time_layout(t)

    @jax.jit
    def fold(v):
        return pk._fold(jnp.pad(v, ((0, 0), (0, tp - t)), constant_values=jnp.nan))

    # stage K distinct FOLDED panels before any timing
    panels = [fold(yd + 0.1 * i) for i in range(K)]
    for p in panels:
        jax.block_until_ready(p)

    ref = pk.batch_autocorr(yd[:2048], nl)
    got = autocorr_folded(fold(yd[:2048] if False else yd)[:, :16], 2048, t, nl)
    print("parity:", float(jnp.max(jnp.abs(ref - got))))

    def make(kk):
        @jax.jit
        def prog(ps):
            s = 0.0
            for i in range(kk):
                s = s + jnp.sum(autocorr_folded(ps[i], b, t, nl))
            return s
        return prog

    progK, prog1 = make(K), make(1)
    float(progK(panels)); float(prog1(panels))
    tks, t1s = [], []
    for _ in range(10):
        t0 = time.perf_counter(); float(progK(panels)); tks.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); float(prog1(panels)); t1s.append(time.perf_counter() - t0)
    diffs = [a - c for a, c in zip(tks, t1s)]
    per = max(float(np.median(diffs)), min(tks) - min(t1s)) / (K - 1)
    gbps = b * t * 4 / per / 1e9
    print(f"prefolded per-panel {per*1e3:.3f} ms  min-traffic {gbps:.1f} GB/s"
          f"  ({100*gbps/819:.1f}% peak)")

    # one-time fold cost for context
    t0 = time.perf_counter()
    for i in range(3):
        jax.block_until_ready(fold(yd + 0.3 * i))
    print(f"fold cost (amortized once per panel lifetime): "
          f"{(time.perf_counter()-t0)/3*1e3:.3f} ms")


if __name__ == "__main__":
    main()
