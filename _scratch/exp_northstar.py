import sys
import json

import jax.numpy as jnp

sys.path.insert(0, "/root/repo")
from bench import _northstar_1m

print(json.dumps(_northstar_1m(jnp, (1, 1, 1)), indent=1))
