"""Experiment: fold-free autocorr — read the NATURAL [B, T] layout and
transpose inside the kernel, vs the production folded kernel (XLA transpose
pass to [T, B/128, 128] first).  Marginal (dispatch-free) timing.
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")
from spark_timeseries_tpu.ops import pallas_kernels as pk

_LANES = 128


def _ac_nat_kernel(nl, t_limit, tp, sb, y_ref, acc_ref):
    # y_ref: [sb, tp] natural block (sb series on sublanes, tp time on lanes)
    y = y_ref[:]
    yt = y.T  # [tp, sb] in-VMEM transpose: time -> sublane-major axis
    t_id = lax.broadcasted_iota(jnp.int32, (tp, sb), 0)
    valid = (yt == yt) & (t_id < t_limit)
    vf = valid.astype(jnp.float32)
    n = jnp.sum(vf, axis=0)
    mean = jnp.sum(jnp.where(valid, yt, 0.0), axis=0) / jnp.maximum(n, 1.0)
    d = jnp.where(valid, yt - mean, 0.0)
    rows = [jnp.sum(d * d, axis=0)]
    for k in range(1, nl + 1):
        rows.append(jnp.sum(d[k:] * d[: tp - k], axis=0))
    acc_ref[0] = jnp.stack(rows)  # [nl+1, sb]


def batch_autocorr_nat(y, num_lags: int, sb: int = 128):
    b, t = y.shape
    tp = t + (-t) % _LANES
    bp = b + (-b) % sb
    yp = jnp.pad(y, ((0, bp - b), (0, tp - t)), constant_values=jnp.nan)
    acc = pl.pallas_call(
        functools.partial(_ac_nat_kernel, num_lags, t, tp, sb),
        grid=(bp // sb,),
        in_specs=[pl.BlockSpec((sb, tp), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, num_lags + 1, sb), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bp // sb, num_lags + 1, sb), jnp.float32),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024),
    )(yp)
    acc = acc.transpose(0, 2, 1).reshape(bp, num_lags + 1)[:b]  # [B, nl+1]
    return acc[:, 1:] / acc[:, :1]


def _ac_roll_kernel(nl, t_limit, tp, sb, use_roll, y_ref, acc_ref):
    y = y_ref[:]  # [sb, tp] natural: series on sublanes, time on lanes
    t_id = lax.broadcasted_iota(jnp.int32, (sb, tp), 1)
    valid = (y == y) & (t_id < t_limit)
    vf = valid.astype(jnp.float32)
    n = jnp.sum(vf, axis=1, keepdims=True)
    mean = jnp.sum(jnp.where(valid, y, 0.0), axis=1, keepdims=True) / jnp.maximum(n, 1.0)
    d = jnp.where(valid, y - mean, 0.0)
    cols = [jnp.sum(d * d, axis=1, keepdims=True)]
    for k in range(1, nl + 1):
        if use_roll:
            dk = pltpu.roll(d, tp - k, 1)
            dk = jnp.where(t_id < tp - k, dk, 0.0)
            cols.append(jnp.sum(d * dk, axis=1, keepdims=True))
        else:
            cols.append(jnp.sum(d[:, k:] * d[:, : tp - k], axis=1, keepdims=True))
    acc_ref[0] = jnp.concatenate(cols, axis=1)  # [sb, nl+1]


def batch_autocorr_roll(y, num_lags: int, sb: int = 512, use_roll=True):
    b, t = y.shape
    tp = t + (-t) % _LANES
    bp = b + (-b) % sb
    yp = jnp.pad(y, ((0, bp - b), (0, tp - t)), constant_values=jnp.nan)
    acc = pl.pallas_call(
        functools.partial(_ac_roll_kernel, num_lags, t, tp, sb, use_roll),
        grid=(bp // sb,),
        in_specs=[pl.BlockSpec((sb, tp), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, sb, num_lags + 1), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bp // sb, sb, num_lags + 1), jnp.float32),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024),
    )(yp)
    acc = acc.reshape(bp, num_lags + 1)[:b]
    return acc[:, 1:] / acc[:, :1]


def _ac_mxu_kernel(nl, t_limit, tp, sb, y_ref, acc_ref):
    # [sb, tp] natural block; transpose 128-series groups on the MXU
    # (identity matmul — exact in f32, and the MXU is otherwise idle here)
    y = y_ref[:]
    eye = (lax.broadcasted_iota(jnp.int32, (128, 128), 0)
           == lax.broadcasted_iota(jnp.int32, (128, 128), 1)).astype(jnp.float32)
    t_id = lax.broadcasted_iota(jnp.int32, (tp, 128), 0)
    outs = []
    for j in range(sb // 128):
        yj = y[j * 128 : (j + 1) * 128]  # [128, tp]
        yt = lax.dot_general(yj, eye, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [tp, 128]
        valid = (yt == yt) & (t_id < t_limit)
        vf = valid.astype(jnp.float32)
        n = jnp.sum(vf, axis=0)
        mean = jnp.sum(jnp.where(valid, yt, 0.0), axis=0) / jnp.maximum(n, 1.0)
        d = jnp.where(valid, yt - mean, 0.0)
        rows = [jnp.sum(d * d, axis=0)]
        for k in range(1, nl + 1):
            rows.append(jnp.sum(d[k:] * d[: tp - k], axis=0))
        outs.append(jnp.stack(rows))  # [nl+1, 128]
    acc_ref[0] = jnp.stack(outs, axis=0)  # [sb//128, nl+1, 128]


def batch_autocorr_mxu(y, num_lags: int, sb: int = 256):
    b, t = y.shape
    tp = t + (-t) % _LANES
    bp = b + (-b) % sb
    yp = jnp.pad(y, ((0, bp - b), (0, tp - t)), constant_values=jnp.nan)
    nb = bp // sb
    acc = pl.pallas_call(
        functools.partial(_ac_mxu_kernel, num_lags, t, tp, sb),
        grid=(nb,),
        in_specs=[pl.BlockSpec((sb, tp), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, sb // 128, num_lags + 1, 128),
                               lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, sb // 128, num_lags + 1, 128),
                                       jnp.float32),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024),
    )(yp)
    acc = acc.transpose(0, 1, 3, 2).reshape(bp, num_lags + 1)[:b]
    return acc[:, 1:] / acc[:, :1]


def marginal(run_k, run_1, k, reps=10):
    tks, t1s = [], []
    for _ in range(reps):
        t0 = time.perf_counter(); run_k(); tks.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); run_1(); t1s.append(time.perf_counter() - t0)
    diffs = [a - c for a, c in zip(tks, t1s)]
    return max(float(np.median(diffs)), min(tks) - min(t1s)) / (k - 1)


def main():
    b, t, nl = 131_072, 1000, 10
    K = 8
    rng = np.random.default_rng(0)
    y = np.cumsum(rng.normal(size=(b, t)), axis=1).astype(np.float32)
    yd = jnp.asarray(y)
    jax.block_until_ready(yd)

    # parity first
    small = yd[:2048]
    ref = pk.batch_autocorr(small, nl)
    for nm, f in [("mxu", lambda v: batch_autocorr_mxu(v, nl))]:
        got = f(small)
        print(f"parity {nm}: max abs diff {float(jnp.max(jnp.abs(ref - got))):.2e}")

    for name, fn in [("folded(prod)", lambda v: pk.batch_autocorr(v, nl)),
                     ("mxu sb128", lambda v: batch_autocorr_mxu(v, nl, 128)),
                     ("mxu sb256", lambda v: batch_autocorr_mxu(v, nl, 256)),
                     ("mxu sb512", lambda v: batch_autocorr_mxu(v, nl, 512)),
                     ("mxu sb1024", lambda v: batch_autocorr_mxu(v, nl, 1024))]:
        def make(kk):
            @jax.jit
            def prog(v):
                s = 0.0
                for i in range(kk):
                    s = s + jnp.sum(fn(v + 0.1 * i))
                return s
            return prog
        try:
            progK, prog1 = make(K), make(1)
            float(progK(yd)); float(prog1(yd))  # warm
            per = marginal(lambda: float(progK(yd)), lambda: float(prog1(yd)), K)
            gbps = b * t * 4 / per / 1e9
            print(f"{name:18s} per-panel {per*1e3:8.3f} ms  min-traffic {gbps:7.1f} GB/s"
                  f"  ({100*gbps/819:.1f}% peak)")
        except Exception as e:
            print(f"{name:18s} FAILED: {type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
