"""Folded fill-chain marginal perf on TPU (staging mirrors exp_prefold)."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from spark_timeseries_tpu.ops import pallas_kernels as pk
from spark_timeseries_tpu.ops.layout import FoldedPanel


def gen_gappy(b, t, seed=0, gap=0.1):
    rng = np.random.default_rng(seed)
    y = np.cumsum(rng.normal(size=(b, t)), axis=1).astype(np.float32)
    mask = rng.random((b, t)) < gap
    mask[:, 0] = False
    mask[:, -1] = False
    y[mask] = np.nan
    return y


def marginal(run_k, run_1, k, reps=10):
    tks, t1s = [], []
    for _ in range(reps):
        t0 = time.perf_counter(); run_k(); tks.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); run_1(); t1s.append(time.perf_counter() - t0)
    diffs = [a - c for a, c in zip(tks, t1s)]
    return max(float(np.median(diffs)), min(tks) - min(t1s)) / (k - 1)


def main():
    b, t = 98_304, 1000
    K = 8
    tp, cs, nchunk = pk._time_layout(t)
    yd = jnp.asarray(gen_gappy(b, t, seed=2))
    jax.block_until_ready(yd)
    print("transferred", flush=True)

    @jax.jit
    def fold(v):
        return pk._fold(jnp.pad(v, ((0, 0), (0, tp - t)),
                                constant_values=jnp.nan))

    panels = []
    for i in range(K):
        t0 = time.perf_counter()
        p = FoldedPanel(fold(yd + 0.25 * i), b, t)
        jax.block_until_ready(p.data)
        print(f"variant {i}: {time.perf_counter()-t0:.1f}s", flush=True)
        panels.append(p)

    def make(kk, outputs):
        @jax.jit
        def prog(ps):
            s = 0.0
            for i in range(kk):
                outs = pk.fill_linear_chain_folded(ps[i], outputs)
                for o in outs:
                    s = s + jnp.sum(jnp.nan_to_num(o.data))
            return s
        return prog

    for outputs in [("diff", "lag"), ("filled", "diff", "lag")]:
        progK, prog1 = make(K, outputs), make(1, outputs)
        t0 = time.perf_counter()
        float(progK(panels)); float(prog1(panels))
        print(f"compiled {outputs} in {time.perf_counter()-t0:.1f}s", flush=True)
        per = marginal(lambda: float(progK(panels)), lambda: float(prog1(panels)), K)
        npass = 1 + len(outputs)
        gbps = npass * b * t * 4 / per / 1e9
        print(f"chain {outputs}: per-panel {per*1e3:.3f} ms  "
              f"min-traffic({npass} passes) {gbps:.1f} GB/s "
              f"({100*gbps/819:.1f}% peak)", flush=True)


if __name__ == "__main__":
    main()
