"""TPU check: fused fill chain parity (native lowering) + marginal perf of
the folded chain and folded autocorr."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from spark_timeseries_tpu.ops import pallas_kernels as pk
from spark_timeseries_tpu.ops import univariate as uv
from spark_timeseries_tpu.ops.layout import fold_panel, unfold_panel


def gen_gappy(b, t, seed=0, gap=0.1):
    rng = np.random.default_rng(seed)
    y = np.cumsum(rng.normal(size=(b, t)), axis=1).astype(np.float32)
    mask = rng.random((b, t)) < gap
    mask[:, 0] = False
    mask[:, -1] = False
    y[mask] = np.nan
    return y


def marginal(run_k, run_1, k, reps=10):
    tks, t1s = [], []
    for _ in range(reps):
        t0 = time.perf_counter(); run_k(); tks.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); run_1(); t1s.append(time.perf_counter() - t0)
    diffs = [a - c for a, c in zip(tks, t1s)]
    return max(float(np.median(diffs)), min(tks) - min(t1s)) / (k - 1)


def main():
    # native parity, small panel, incl. multi-chunk
    for t in (200, 2 * pk._CHUNK_T + 57):
        y = jnp.asarray(gen_gappy(512, t, seed=1, gap=0.25))
        f_ref = jax.vmap(uv.fill_linear)(y)
        f, d, lg = pk.fill_linear_chain(y)
        err = float(jnp.max(jnp.where(jnp.isnan(f_ref) | jnp.isnan(f),
                                      0.0, jnp.abs(f - f_ref))))
        nanmm = int(jnp.sum(jnp.isnan(f_ref) != jnp.isnan(f)))
        fps = pk.fill_linear_chain_folded(fold_panel(y))
        errf = float(jnp.max(jnp.abs(jnp.nan_to_num(unfold_panel(fps[1]) - d))))
        print(f"t={t}: native chain err {err:.2e} nan-mismatch {nanmm} "
              f"folded-vs-natural diff err {errf:.2e}")

    b, t = 98_304, 1000
    K = 8
    y = gen_gappy(b, t, seed=2)
    yd = jnp.asarray(y)

    # folded chain, diff+lag only: stage K folded variants before timing
    @jax.jit
    def variant_folded(i):
        return fold_panel(yd + 0.25 * i)

    panels = [variant_folded(i) for i in range(K)]
    for p in panels:
        jax.block_until_ready(p.data)

    def make(kk, outputs):
        @jax.jit
        def prog(ps):
            s = 0.0
            for i in range(kk):
                outs = pk.fill_linear_chain_folded(ps[i], outputs)
                for o in outs:
                    s = s + jnp.sum(jnp.nan_to_num(o.data))
            return s
        return prog

    for outputs in [("diff", "lag"), ("filled", "diff", "lag")]:
        progK, prog1 = make(K, outputs), make(1, outputs)
        float(progK(panels)); float(prog1(panels))
        per = marginal(lambda: float(progK(panels)), lambda: float(prog1(panels)), K)
        npass = 1 + len(outputs)
        gbps_min = npass * b * t * 4 / per / 1e9
        print(f"chain {outputs}: per-panel {per*1e3:.3f} ms  "
              f"min-traffic({npass} passes) {gbps_min:.1f} GB/s "
              f"({100*gbps_min/819:.1f}% peak)")


if __name__ == "__main__":
    main()
