"""Where do the headline fit's milliseconds go?  Component timing with all
data passed as jit ARGUMENTS (closures embed the panel as an HLO constant,
which the tunnel's compile endpoint rejects at 413)."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from bench import gen_arima_panel
from spark_timeseries_tpu.models import arima
from spark_timeseries_tpu.models.base import maybe_align
from spark_timeseries_tpu.ops import pallas_kernels as pk
from spark_timeseries_tpu.utils import optim

b, t = 100_352, 1000
order = (1, 1, 1)
y = jnp.asarray(gen_arima_panel(b, t, seed=0))
jax.block_until_ready(y)
print("staged", flush=True)


def _sync(out):
    # the axon tunnel's block_until_ready is a no-op; only a host transfer
    # actually waits for the device
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(jnp.nan_to_num(jnp.ravel(leaf)[:8]).astype(jnp.float32)))


def timeit(name, fn, *args, reps=6):
    out = fn(*args)
    _sync(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _sync(out)
        ts.append(time.perf_counter() - t0)
    print(f"{name:28s} best {min(ts)*1e3:8.1f} ms  p50 {np.median(ts)*1e3:8.1f} ms",
          flush=True)
    return out


@jax.jit
def prep(yb):
    ya, nv0 = maybe_align(yb, "dense")
    yd = jax.vmap(lambda v: arima._difference(v, 1))(ya)
    nvd = nv0 - 1
    y3, zb3 = pk.css_prefold(yd, order, nvd)
    init = pk.hr_init(yd, order, True, nvd, y3=y3)
    return y3, zb3, nvd, init


y3, zb3, nvd, init = timeit("prep+prefold+hr_init", prep, y)
n_eff = jnp.maximum(nvd - 1, 1).astype(jnp.float32)


def obj(P, y3, zb3, nvd, ne):
    return pk.css_neg_loglik_folded(P, y3, zb3, t, order, True, nvd) / ne


@jax.jit
def fwd1(P, y3, zb3, nvd, ne):
    return jnp.sum(obj(P, y3, zb3, nvd, ne))


@jax.jit
def vg1(P, y3, zb3, nvd, ne):
    f, pb = jax.vjp(lambda P_: obj(P_, y3, zb3, nvd, ne), P)
    return pb(jnp.ones_like(f))[0]


timeit("value pass (1 dispatch)", fwd1, init, y3, zb3, nvd, n_eff)
timeit("value+grad (1 dispatch)", vg1, init, y3, zb3, nvd, n_eff)


@jax.jit
def opt(init, y3, zb3, nvd, ne):
    return optim.minimize_lbfgs_batched(
        lambda P: obj(P, y3, zb3, nvd, ne), init, max_iters=60, tol=1e-4)


timeit("optimizer (no compaction)", opt, init, y3, zb3, nvd, n_eff)


@jax.jit
def full(yb):
    return arima.fit(yb, order)


timeit("arima.fit end-to-end", full, y)

# null program: dispatch round-trip floor
@jax.jit
def null(yb):
    return jnp.float32(0.0) + yb[0, 0]


timeit("null dispatch", null, y)
