"""Drill into the headline fit's prep + compaction costs."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from bench import gen_arima_panel
from spark_timeseries_tpu.models import arima
from spark_timeseries_tpu.models.base import maybe_align
from spark_timeseries_tpu.ops import pallas_kernels as pk
from spark_timeseries_tpu.utils import optim

b, t = 100_352, 1000
order = (1, 1, 1)
y = jnp.asarray(gen_arima_panel(b, t, seed=0))
jax.block_until_ready(y)
print("staged", flush=True)


def _sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(jnp.nan_to_num(jnp.ravel(leaf)[:8]).astype(jnp.float32)))


def timeit(name, fn, *args, reps=6):
    out = fn(*args)
    _sync(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _sync(out)
        ts.append(time.perf_counter() - t0)
    print(f"{name:34s} best {min(ts)*1e3:8.1f} ms  p50 {np.median(ts)*1e3:8.1f} ms",
          flush=True)
    return out


@jax.jit
def stage_a(yb):
    ya, nv0 = maybe_align(yb, "dense")
    yd = jax.vmap(lambda v: arima._difference(v, 1))(ya)
    return yd, nv0 - 1


yd, nvd = timeit("align+diff", stage_a, y)


@jax.jit
def stage_b(yd, nvd):
    return pk.css_prefold(yd, order, nvd)


y3, zb3 = timeit("css_prefold", stage_b, yd, nvd)


@jax.jit
def stage_c(yd, nvd, y3):
    return pk.hr_init(yd, order, True, nvd, y3=y3)


init = timeit("hr_init (given y3)", stage_c, yd, nvd, y3)
n_eff = jnp.maximum(nvd - 1, 1).astype(jnp.float32)


def obj(P, y3, zb3, nvd, ne):
    return pk.css_neg_loglik_folded(P, y3, zb3, t, order, True, nvd) / ne


@jax.jit
def opt_plain(init, y3, zb3, nvd, ne):
    return optim.minimize_lbfgs_batched(
        lambda P: obj(P, y3, zb3, nvd, ne), init, max_iters=60, tol=1e-4)


timeit("optimizer no-compact", opt_plain, init, y3, zb3, nvd, n_eff)

cap = -(-max(1024, b // 8) // 1024) * 1024
tp = y3.shape[0]


@jax.jit
def opt_compact(init, y3, zb3, nvd, ne):
    def straggler_fun(idxc):
        y3s = y3.reshape(tp, -1)[:, idxc].reshape(tp, cap // 128, 128)
        zb3s = zb3.reshape(1, -1)[:, idxc].reshape(1, cap // 128, 128)
        nvs = nvd[idxc]
        nes = ne[idxc]
        return lambda P: pk.css_neg_loglik_folded(
            P, y3s, zb3s, t, order, True, nvs) / nes

    return optim.minimize_lbfgs_batched(
        lambda P: obj(P, y3, zb3, nvd, ne), init, max_iters=60, tol=1e-4,
        straggler_fun=straggler_fun, straggler_cap=cap)


timeit("optimizer compact", opt_compact, init, y3, zb3, nvd, n_eff)


@jax.jit
def gather_only(y3, nvd):
    idxc = jnp.arange(cap) * 7 % b
    y3s = y3.reshape(tp, -1)[:, idxc].reshape(tp, cap // 128, 128)
    return y3s


timeit("folded column gather alone", gather_only, y3, nvd)
