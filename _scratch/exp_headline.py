"""Headline ARIMA fit timing with straggler compaction + pass accounting."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from bench import gen_arima_panel
from spark_timeseries_tpu.models import arima

b, t = 100_352, 1000
order = (1, 1, 1)
panels = [gen_arima_panel(b, t, seed=s) for s in range(3)]
dev = [jnp.asarray(p) for p in panels]
for d in dev:
    jax.block_until_ready(d)
print("staged", flush=True)

r = arima.fit(dev[0], order)  # warm/compile
jax.block_until_ready(r.params)
print("compiled", flush=True)

times = []
for v in dev * 2:
    t0 = time.perf_counter()
    r = arima.fit(v, order)
    conv = float(jnp.mean(r.converged))
    float(jnp.sum(jnp.nan_to_num(r.params)))
    times.append(time.perf_counter() - t0)
print(f"fit latencies: {[round(x,3) for x in times]}", flush=True)
best, p50 = min(times), float(np.median(times))
print(f"best {best:.3f}s p50 {p50:.3f}s conv {conv:.4f} "
      f"-> {b*conv/best:.0f} series/s best, {b*conv/p50:.0f} p50", flush=True)

res, info = arima.fit(dev[0], order, count_evals=True)
jax.block_until_ready(res.params)
iters = np.asarray(res.iters)
k_end = int(iters.max())
ca = int(info["compact_at"])
ls = np.asarray(info["ls_evals"])
print(f"compact_at {ca} cap {int(info['cap'])} iters_end {k_end}")
print(f"ls evals stage1 {int(ls[:ca].sum())} stage2 {int(ls[ca:k_end].sum())}")
print(f"per-row iters quantiles:",
      {q: int(np.percentile(iters, q)) for q in (50, 75, 90, 95, 99, 100)})
