"""Statistical hypothesis tests (L4).

Rebuild of the reference's ``sparkts/TimeSeriesStatisticalTests.scala``
(SURVEY.md Section 2.2, upstream path unverified): Augmented Dickey-Fuller,
Durbin-Watson, Breusch-Godfrey, Breusch-Pagan, Ljung-Box, and KPSS.  Each
test is a pure jax function of a ``[time]`` vector (batched variants vmap
over ``[keys, time]``); auxiliary regressions are the shared
normal-equations OLS, and chi-square tail probabilities come from the
regularized incomplete gamma function.

Unit-root p-values follow the reference's approach of embedding published
critical-value tables and interpolating: the asymptotic Dickey-Fuller tau
quantiles (Fuller 1976 / MacKinnon 1994, 2010) and the KPSS table
(Kwiatkowski et al. 1992).  Between tabulated quantiles the p-value is
piecewise-linear in the statistic and SATURATES at the table ends — the
attainable range is [0.01, 0.99] for ADF and [0.01, 0.10] for KPSS (the
published table's span), adequate for accept/reject decisions at
conventional levels.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from ..utils.linalg import ols
from ..ops.lagmat import lag_mat_trim_both

# ---------------------------------------------------------------------------
# Distribution helpers
# ---------------------------------------------------------------------------


def chi2_sf(x, df):
    """Chi-square survival function via the regularized upper gamma."""
    return jax.scipy.special.gammaincc(df / 2.0, x / 2.0)


# ---------------------------------------------------------------------------
# Embedded quantile tables (published asymptotic values)
# ---------------------------------------------------------------------------

# Dickey-Fuller tau quantiles: rows = cumulative probability, columns per
# regression kind.  Asymptotic values from Fuller (1976) / MacKinnon (2010).
_DF_PROBS = jnp.asarray([0.01, 0.025, 0.05, 0.10, 0.90, 0.95, 0.975, 0.99])
_DF_TAU = {
    # no deterministic terms
    "nc": jnp.asarray([-2.56, -2.23, -1.94, -1.62, 0.89, 1.28, 1.62, 2.00]),
    # constant
    "c": jnp.asarray([-3.43, -3.12, -2.86, -2.57, -0.44, -0.07, 0.23, 0.60]),
    # constant + linear trend
    "ct": jnp.asarray([-3.96, -3.66, -3.41, -3.12, -1.25, -0.94, -0.66, -0.33]),
}

# KPSS statistic critical values (Kwiatkowski et al. 1992, Table 1);
# upper-tail probabilities 0.10, 0.05, 0.025, 0.01.
_KPSS_PROBS = jnp.asarray([0.10, 0.05, 0.025, 0.01])
_KPSS_CRIT = {
    "c": jnp.asarray([0.347, 0.463, 0.574, 0.739]),
    "ct": jnp.asarray([0.119, 0.146, 0.176, 0.216]),
}


def _interp_pvalue(stat, quantiles, probs):
    """Piecewise-linear p-value from a (quantile -> cumulative prob) table;
    jnp.interp saturates at the table-end probabilities."""
    return jnp.interp(stat, quantiles, probs)


# ---------------------------------------------------------------------------
# Augmented Dickey-Fuller
# ---------------------------------------------------------------------------


def adftest(y, max_lag: int = 1, regression: str = "c") -> Tuple[jax.Array, jax.Array]:
    """ADF unit-root test -> (tau statistic, p-value).

    Regression: dy_t = [deterministics] + gamma * y_{t-1}
    + sum_{i<=max_lag} delta_i * dy_{t-i} + e_t;  tau = gamma_hat / se.
    ``regression``: "nc" (none), "c" (constant), "ct" (constant+trend).
    """
    if regression not in ("nc", "c", "ct"):
        raise ValueError(f"regression must be nc|c|ct, got {regression!r}")
    y = jnp.asarray(y)
    n = y.shape[0]
    dy = y[1:] - y[:-1]  # [n-1]
    # align rows t = max_lag .. n-2 (of dy): target dy_t, regressors
    target = dy[max_lag:]
    rows = target.shape[0]
    cols = [y[max_lag:-1][:, None]]  # y_{t-1}; gamma is coefficient 0
    for i in range(1, max_lag + 1):
        cols.append(dy[max_lag - i : dy.shape[0] - i][:, None])
    if regression in ("c", "ct"):
        cols.append(jnp.ones((rows, 1), y.dtype))
    if regression == "ct":
        cols.append(jnp.arange(rows, dtype=y.dtype)[:, None])
    X = jnp.concatenate(cols, axis=1)
    # one ridge-stabilized Gram matrix serves both beta and the standard
    # error, so singular designs (e.g. a constant series) stay finite
    XtX = X.T @ X
    k = XtX.shape[0]
    ridge = 1e-8 * jnp.maximum(jnp.trace(XtX) / k, 1.0)
    XtX_inv = jnp.linalg.inv(XtX + ridge * jnp.eye(k, dtype=X.dtype))
    beta = XtX_inv @ (X.T @ target)
    resid = target - X @ beta
    dof = rows - X.shape[1]
    sigma2 = jnp.sum(resid**2) / dof
    se_gamma = jnp.sqrt(sigma2 * XtX_inv[0, 0])
    tau = beta[0] / se_gamma
    pvalue = _interp_pvalue(tau, _DF_TAU[regression], _DF_PROBS)
    return tau, pvalue


# ---------------------------------------------------------------------------
# Durbin-Watson
# ---------------------------------------------------------------------------


def dwtest(residuals) -> jax.Array:
    """Durbin-Watson statistic: sum (e_t - e_{t-1})^2 / sum e_t^2 in (0, 4);
    ~2 means no first-order serial correlation (reference returns the
    statistic only)."""
    e = jnp.asarray(residuals)
    return jnp.sum((e[1:] - e[:-1]) ** 2) / jnp.sum(e * e)


# ---------------------------------------------------------------------------
# Breusch-Godfrey
# ---------------------------------------------------------------------------


def bgtest(residuals, factors, max_lag: int = 1) -> Tuple[jax.Array, jax.Array]:
    """Breusch-Godfrey serial-correlation LM test -> (n*R^2, p-value).

    Auxiliary regression of e_t on [1, factors_t, e_{t-1..t-max_lag}];
    statistic ~ chi2(max_lag) under H0.
    """
    e = jnp.asarray(residuals)
    X = jnp.asarray(factors)
    if X.ndim == 1:
        X = X[:, None]
    n = e.shape[0]
    elags = lag_mat_trim_both(e, max_lag)  # [n - max_lag, max_lag]
    rows = n - max_lag
    Z = jnp.concatenate(
        [jnp.ones((rows, 1), e.dtype), X[max_lag:], elags], axis=1
    )
    target = e[max_lag:]
    beta = ols(Z, target)
    resid = target - Z @ beta
    tss = jnp.sum((target - jnp.mean(target)) ** 2)
    r2 = 1.0 - jnp.sum(resid**2) / jnp.maximum(tss, 1e-30)
    stat = rows * r2
    return stat, chi2_sf(stat, float(max_lag))


# ---------------------------------------------------------------------------
# Breusch-Pagan
# ---------------------------------------------------------------------------


def bptest(residuals, factors) -> Tuple[jax.Array, jax.Array]:
    """Breusch-Pagan heteroskedasticity LM test -> (n*R^2, p-value).

    Auxiliary regression of e_t^2 on [1, factors_t]; ~ chi2(k) under H0.
    """
    e = jnp.asarray(residuals)
    X = jnp.asarray(factors)
    if X.ndim == 1:
        X = X[:, None]
    n = e.shape[0]
    Z = jnp.concatenate([jnp.ones((n, 1), e.dtype), X], axis=1)
    target = e * e
    beta = ols(Z, target)
    resid = target - Z @ beta
    tss = jnp.sum((target - jnp.mean(target)) ** 2)
    r2 = 1.0 - jnp.sum(resid**2) / jnp.maximum(tss, 1e-30)
    stat = n * r2
    return stat, chi2_sf(stat, float(X.shape[1]))


# ---------------------------------------------------------------------------
# Ljung-Box
# ---------------------------------------------------------------------------


def lbtest(residuals, max_lag: int = 10) -> Tuple[jax.Array, jax.Array]:
    """Ljung-Box white-noise test -> (Q, p-value), Q ~ chi2(max_lag)."""
    e = jnp.asarray(residuals)
    n = e.shape[0]
    d = e - jnp.mean(e)
    denom = jnp.sum(d * d)
    terms = []
    for k in range(1, max_lag + 1):
        rho_k = jnp.sum(d[k:] * d[: n - k]) / denom
        terms.append(rho_k**2 / (n - k))
    q = n * (n + 2.0) * jnp.sum(jnp.stack(terms))
    return q, chi2_sf(q, float(max_lag))


# ---------------------------------------------------------------------------
# KPSS
# ---------------------------------------------------------------------------


def kpsstest(y, regression: str = "c", lags: int | None = None) -> Tuple[jax.Array, jax.Array]:
    """KPSS stationarity test -> (eta, p-value).

    H0 is (trend-)stationarity — note the reversed null vs ADF.  Long-run
    variance uses a Bartlett/Newey-West window; default bandwidth
    ``trunc(12 * (n/100)^0.25)`` (the KPSS paper's l12 rule).
    """
    if regression not in ("c", "ct"):
        raise ValueError(f"regression must be c|ct, got {regression!r}")
    y = jnp.asarray(y)
    n = y.shape[0]
    if lags is None:
        lags = int(np_trunc_bandwidth(n))
    if regression == "c":
        e = y - jnp.mean(y)
    else:
        t = jnp.arange(n, dtype=y.dtype)
        X = jnp.stack([jnp.ones((n,), y.dtype), t], axis=1)
        beta = ols(X, y)
        e = y - X @ beta
    s = jnp.cumsum(e)
    # long-run variance: gamma_0 + 2 * sum_k w_k gamma_k, Bartlett weights
    lrv = jnp.sum(e * e) / n
    for k in range(1, lags + 1):
        w = 1.0 - k / (lags + 1.0)
        lrv = lrv + 2.0 * w * jnp.sum(e[k:] * e[: n - k]) / n
    eta = jnp.sum(s * s) / (n * n * jnp.maximum(lrv, 1e-30))
    # upper-tail table: larger eta -> smaller p; saturates in [0.01, 0.10]
    p = jnp.interp(eta, _KPSS_CRIT[regression], _KPSS_PROBS)
    return eta, p


def np_trunc_bandwidth(n: int) -> int:
    return int(12 * (n / 100.0) ** 0.25)


# ---------------------------------------------------------------------------
# Batched variants — one call over a whole panel
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=128)
def _batched(fn, *args):
    """Memoized jit(vmap(fn(., *args))) so repeated panel-level calls reuse
    one compiled kernel (same pattern as panel._cached_batched)."""
    return jax.jit(jax.vmap(lambda v: fn(v, *args)))


def batch_adftest(panel, max_lag: int = 1, regression: str = "c"):
    return _batched(adftest, max_lag, regression)(panel)


def batch_dwtest(panel):
    return _batched(dwtest)(panel)


def batch_lbtest(panel, max_lag: int = 10):
    return _batched(lbtest, max_lag)(panel)


def batch_kpsstest(panel, regression: str = "c", lags: int | None = None):
    n = panel.shape[-1]
    l = lags if lags is not None else np_trunc_bandwidth(n)
    return _batched(kpsstest, regression, l)(panel)
