from . import tests
from .tests import adftest, bgtest, bptest, dwtest, kpsstest, lbtest

__all__ = ["tests", "adftest", "dwtest", "bgtest", "bptest", "lbtest", "kpsstest"]
