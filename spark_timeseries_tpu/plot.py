"""Plotting conveniences — the reference's ``EasyPlot`` (L6).

Replaces upstream ``sparkts/EasyPlot.scala`` (``ezplot``, ``acfPlot``,
``pacfPlot`` — path unverified, see SURVEY.md §1 L6) with matplotlib-backed
equivalents.  The ACF/PACF values themselves come from the batched TPU
kernels (:mod:`spark_timeseries_tpu.ops.univariate`); only the rendering is
host-side.  matplotlib is an optional dependency: importing this module
without it raises a clear error at call time, not import time.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .ops import univariate as uv


def _plt():
    try:
        import matplotlib.pyplot as plt

        return plt
    except ImportError as e:  # pragma: no cover
        raise ImportError("plotting requires matplotlib (not installed)") from e


def _as_2d(values) -> np.ndarray:
    arr = np.asarray(values)
    return arr[None, :] if arr.ndim == 1 else arr


def ezplot(values, index=None, labels: Optional[Sequence] = None, ax=None):
    """Line plot of one series (``[time]``) or several (``[series, time]``).

    Upstream ``EasyPlot.ezplot``.  ``index`` may be a ``DateTimeIndex`` (its
    datetimes become the x axis) or any array of x values.
    """
    plt = _plt()
    arr = _as_2d(values)
    if ax is None:
        _, ax = plt.subplots(figsize=(10, 4))
    x = np.arange(arr.shape[1]) if index is None else (
        index.datetimes() if hasattr(index, "datetimes") else np.asarray(index)
    )
    for i, row in enumerate(arr):
        ax.plot(x, row, label=None if labels is None else str(labels[i]))
    if labels is not None:
        ax.legend(loc="best", fontsize="small")
    ax.set_xlabel("time")
    return ax


def _corr_plot(corr: np.ndarray, n: int, title: str, ax):
    """Stem plot with the +-1.96/sqrt(n) white-noise significance band the
    upstream ACF/PACF plots draw."""
    plt = _plt()
    if ax is None:
        _, ax = plt.subplots(figsize=(8, 3))
    lags = np.arange(1, corr.shape[0] + 1)
    ax.vlines(lags, 0.0, corr)
    ax.plot(lags, corr, "o", markersize=3)
    band = 1.96 / np.sqrt(max(n, 1))
    ax.axhline(0.0, linewidth=0.8)
    ax.axhline(band, linestyle="--", linewidth=0.8)
    ax.axhline(-band, linestyle="--", linewidth=0.8)
    ax.set_xlabel("lag")
    ax.set_title(title)
    return ax


def acf_plot(values, max_lag: int, ax=None):
    """ACF stem plot with significance bands — upstream ``EasyPlot.acfPlot``."""
    x = np.asarray(values, dtype=np.float64)
    corr = np.asarray(uv.autocorr(x, max_lag))
    return _corr_plot(corr, int(np.sum(~np.isnan(x))), "ACF", ax)


def pacf_plot(values, max_lag: int, ax=None):
    """PACF stem plot with significance bands — upstream ``EasyPlot.pacfPlot``."""
    x = np.asarray(values, dtype=np.float64)
    corr = np.asarray(uv.pacf(x, max_lag))
    return _corr_plot(corr, int(np.sum(~np.isnan(x))), "PACF", ax)
