"""spark_timeseries_tpu — a TPU-native time-series framework.

A ground-up JAX/XLA rebuild of the capability surface of
``mjayantkumar/spark-timeseries`` (``com.cloudera.sparkts``): collections of
time series sharing a date-time index, missing-data imputation and
lag/difference/resample transforms, classical models (ARIMA, AR, EWMA,
GARCH/ARGARCH, Holt-Winters, regression with ARIMA errors), and statistical
hypothesis tests — executed as vmapped kernels over a mesh-sharded
``[keys, time]`` device panel instead of per-series JVM loops.
"""

from . import index
from .index import (
    BusinessDayFrequency,
    DateTimeIndex,
    DayFrequency,
    DurationFrequency,
    Frequency,
    HourFrequency,
    HybridDateTimeIndex,
    IrregularDateTimeIndex,
    MinuteFrequency,
    MonthFrequency,
    SecondFrequency,
    UniformDateTimeIndex,
    WeekFrequency,
    YearFrequency,
    from_string,
    hybrid,
    irregular,
    uniform,
    uniform_from_interval,
)
from .ops import univariate
from .panel import (
    TimeSeriesPanel,
    from_dataframe,
    from_observations,
    from_series_dict,
)
from . import parallel
from .parallel import default_mesh
from . import models
from . import obs
from . import reliability
from . import forecasting
from . import serving
from . import stats
from . import compat

try:  # single-sourced from pyproject.toml via package metadata
    from importlib.metadata import version as _pkg_version

    __version__ = _pkg_version("spark-timeseries-tpu")
except Exception:  # not installed (e.g. run from a bare checkout)
    __version__ = "0.0.0+uninstalled"
