"""Lag-matrix construction for regressions (AR / ADF / Breusch-Godfrey).

TPU-native replacement for ``com.cloudera.sparkts.Lag`` (SURVEY.md
Section 2.1, upstream path unverified).  Static-shape slicing only, so the
result is jit/vmap friendly and feeds batched ``lstsq`` on the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def lag_mat_trim_both(x: jax.Array, max_lag: int, include_original: bool = False) -> jax.Array:
    """Trimmed lag matrix: rows are t = max_lag .. n-1.

    Column order: (original x[t] if requested,) x[t-1], x[t-2], ..., x[t-max_lag].
    Shape ``[n - max_lag, max_lag (+1)]``.
    """
    n = x.shape[0]
    if max_lag >= n:
        raise ValueError(f"max_lag {max_lag} must be < series length {n}")
    cols = []
    if include_original:
        cols.append(x[max_lag:])
    for k in range(1, max_lag + 1):
        cols.append(x[max_lag - k : n - k])
    return jnp.stack(cols, axis=1)


def lag_mat_trim_both_2d(x: jax.Array, max_lag: int, include_original: bool = False) -> jax.Array:
    """Lag matrix for multi-column input ``[n, c]`` -> ``[n - max_lag, c * lags]``.

    Lag-major column grouping matches the reference: all columns at lag 1,
    then all columns at lag 2, ...
    """
    n = x.shape[0]
    if max_lag >= n:
        raise ValueError(f"max_lag {max_lag} must be < series length {n}")
    blocks = []
    if include_original:
        blocks.append(x[max_lag:])
    for k in range(1, max_lag + 1):
        blocks.append(x[max_lag - k : n - k])
    return jnp.concatenate(blocks, axis=1)
