"""Sequence (time-axis) parallelism — long-series support.

The reference never shards a single series: a series is one JVM vector, so
its maximum length is bounded by executor memory (SURVEY.md Section 5.7).
This module removes that bound: on a 2-D ``(series, time)`` mesh, one series'
``[time]`` axis is split across chips and within-series reductions and scans
are rebuilt from local work + ICI collectives under ``shard_map``:

- moments / autocovariance:  local partial sums + ``psum`` over the ``time``
  axis; lagged cross terms at shard boundaries come from a halo exchange
  (``ppermute`` of each shard's tail to its right neighbor) — a ring
  transfer over ICI, the time-series analog of ring attention's
  neighbor hand-off.
- prefix scans (cumsum — the integration step of differencing):  local scan
  + exclusive all-shard offset, computed via ``psum`` of masked shard totals
  (carry hand-off without serializing shards).

Every function here takes and returns arrays laid out ``[keys, time]`` and
is meant to be called under ``shard_map`` with spec
``P(SERIES_AXIS, TIME_AXIS)`` — see ``sp_*_sharded`` wrappers which bind the
mesh. On a 1-D mesh the plain kernels in ``ops.univariate`` are the right
tool; these exist for series too long for one chip's HBM.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 top-level export
    from jax import shard_map
except ImportError:  # older builds: the experimental module
    from jax.experimental.shard_map import shard_map

from .. import obs
from ..parallel.mesh import SERIES_AXIS, TIME_AXIS

Order = Tuple[int, int, int]


def _sp_fit_span(model: str, mesh: Mesh, values, **knobs):
    """Telemetry span for one time-sharded fit dispatch (ROADMAP: span
    coverage for the sharded fit paths).  Mirrors the chunk driver's
    first-dispatch tagging: the first dispatch of a (model, mesh, shape,
    dtype, knobs) tuple pays JAX trace+compile (the ``lru_cache``d
    ``_sp_*_fit_program`` builders trace on first use), later dispatches
    execute a cached program.  Free no-op when the plane is disabled."""
    phase = None
    if obs.enabled():
        key = ("sp_fit", model, tuple(mesh.shape.items()),
               tuple(values.shape), str(values.dtype),
               tuple(sorted(knobs.items())))
        phase = "compile+execute" if obs.first_dispatch(key) else "execute"
    return obs.span("sp_fit", model=model, keys=int(values.shape[0]),
                    n_time=int(values.shape[1]), phase=phase)


# ---------------------------------------------------------------------------
# Inside-shard_map kernels (axis_name = TIME_AXIS)
# ---------------------------------------------------------------------------


def _axis_index():
    return lax.axis_index(TIME_AXIS)


def _axis_size():
    # lax.axis_size landed after jax 0.4; psum of the literal 1 is the
    # classic spelling and constant-folds to the same STATIC python int
    # (several callers build ppermute tables with range() over it)
    if hasattr(lax, "axis_size"):
        return lax.axis_size(TIME_AXIS)
    return lax.psum(1, TIME_AXIS)


def sp_moments(block: jax.Array) -> Dict[str, jax.Array]:
    """NaN-aware per-series count/mean/var across a time-sharded axis.

    ``block``: this shard's ``[keys_local, time_local]`` slice.  Returns
    per-series ``[keys_local]`` stats, identical on every time shard.
    """
    valid = ~jnp.isnan(block)
    n = lax.psum(jnp.sum(valid, axis=1), TIME_AXIS)
    s = lax.psum(jnp.sum(jnp.where(valid, block, 0.0), axis=1), TIME_AXIS)
    mean = s / jnp.maximum(n, 1)
    ss = lax.psum(
        jnp.sum(jnp.where(valid, (block - mean[:, None]) ** 2, 0.0), axis=1), TIME_AXIS
    )
    var = ss / jnp.maximum(n - 1, 1)
    return {"count": n, "mean": mean, "var": var}


def _halo_from_left(block: jax.Array, halo: int) -> jax.Array:
    """Each shard receives the previous shard's last ``halo`` columns
    (zeros for the first shard) — the ring hand-off for lagged terms."""
    nshards = _axis_size()
    tail = block[:, -halo:]
    perm = [(i, (i + 1) % nshards) for i in range(nshards)]
    received = lax.ppermute(tail, TIME_AXIS, perm)
    first = _axis_index() == 0
    return jnp.where(first, jnp.zeros_like(received), received)


def sp_autocov(block: jax.Array, max_lag: int) -> jax.Array:
    """Autocovariance at lags 1..max_lag of time-sharded series.

    Cross-shard lagged products use a halo exchange of ``max_lag`` columns
    from the left neighbor.  Assumes no NaNs (fill first).  Returns
    ``[keys_local, max_lag]`` (plus the lag-0 variance as column 0 of the
    companion ``sp_autocorr``).
    """
    stats = sp_moments(block)
    d = block - stats["mean"][:, None]
    halo = _halo_from_left(d, max_lag)  # [k, max_lag] from left neighbor
    ext = jnp.concatenate([halo, d], axis=1)  # [k, max_lag + t_local]
    t_local = d.shape[1]
    covs = []
    for k in range(1, max_lag + 1):
        lagged = lax.dynamic_slice_in_dim(ext, max_lag - k, t_local, axis=1)
        # products whose lagged partner falls before the global start are
        # zero because the first shard's halo is zeroed
        covs.append(lax.psum(jnp.sum(d * lagged, axis=1), TIME_AXIS))
    return jnp.stack(covs, axis=1)


def sp_autocorr(block: jax.Array, max_lag: int) -> jax.Array:
    """Autocorrelation at lags 1..max_lag (matches ``univariate.autocorr``
    on unsharded data)."""
    stats = sp_moments(block)
    d = block - stats["mean"][:, None]
    denom = lax.psum(jnp.sum(d * d, axis=1), TIME_AXIS)
    return sp_autocov(block, max_lag) / denom[:, None]


def sp_cumsum(block: jax.Array) -> jax.Array:
    """Cumulative sum along a time-sharded axis (differencing inversion).

    Local cumsum + exclusive prefix of shard totals.  The prefix is computed
    collective-only: psum of shard totals masked to strictly-lower shard
    indices — no serialization across shards.
    """
    local = jnp.cumsum(block, axis=1)
    total = local[:, -1:]  # [k, 1] this shard's sum
    idx = _axis_index()
    nshards = _axis_size()
    # all_gather shard totals, then sum those before this shard
    gathered = lax.all_gather(total, TIME_AXIS, axis=1, tiled=True)  # [k, nshards]
    mask = jnp.arange(nshards) < idx
    offset = jnp.sum(jnp.where(mask[None, :], gathered, 0.0), axis=1, keepdims=True)
    return local + offset


def sp_differences(block: jax.Array, k_lag: int = 1) -> jax.Array:
    """Lag-k differencing across shard boundaries via halo exchange; the
    first ``k_lag`` global positions are NaN (matches
    ``univariate.differences_at_lag``)."""
    halo = _halo_from_left(block, k_lag)
    ext = jnp.concatenate([halo, block], axis=1)
    lagged = ext[:, : block.shape[1]]
    out = block - lagged
    # global positions < k_lag are NaN
    t0 = _axis_index() * block.shape[1]
    gpos = t0 + jnp.arange(block.shape[1])
    return jnp.where(gpos[None, :] < k_lag, jnp.nan, out)


def _affine_scan_sharded(m_elem: jax.Array, b_elem: jax.Array) -> jax.Array:
    """Inclusive scan of the affine recursion ``s_t = m_t * s_{t-1} + b_t``
    along a time-sharded axis, carry entering the global front = 0.

    Affine maps compose associatively, so BOTH levels parallelize: inside a
    shard a log-depth ``associative_scan`` over the (m, b) pairs, across
    shards one tiny fold of each shard's composed exit pair over the
    all-gathered values (generalizing :func:`sp_cumsum`'s offset trick to
    model recursions).  A global seed or dead prefix is encoded in the
    ELEMENTS (``m = 0`` cuts the incoming carry).
    """
    def comp(l, r):  # apply l then r: r(l(s)) = (rm*lm) s + (rb + rm*lb)
        lm, lb = l
        rm, rb = r
        return lm * rm, rb + rm * lb

    decay, p = lax.associative_scan(comp, (m_elem, b_elem), axis=1)
    # s_t = decay_t * s_in + p_t for the carry s_in entering this shard
    gm = lax.all_gather(decay[:, -1:], TIME_AXIS, axis=1, tiled=True)
    gb = lax.all_gather(p[:, -1:], TIME_AXIS, axis=1, tiled=True)

    def fold(c, mb):
        m, b = mb
        c = m * c + b
        return c, c

    _, carries = lax.scan(fold, jnp.zeros_like(gm[:, 0]), (gm.T, gb.T))
    carries = carries.T  # [k, nshards]: carry EXITING each shard
    idx = _axis_index()
    first = idx == 0
    entering = jnp.where(
        first, jnp.zeros_like(carries[:, 0]), carries[:, jnp.maximum(idx - 1, 0)]
    )
    return decay * entering[:, None] + p


def sp_ewma_smooth(block: jax.Array, alpha: jax.Array) -> jax.Array:
    """EWMA smoothing of time-sharded series (matches ``ewma.smooth`` on
    unsharded data; seeds ``s_0 = x_0``).

    Every step is the affine map ``s -> (1-a) s + a x_t`` (the global seed
    ``s_0 = x_0`` is just ``(0, x_0)``) — see :func:`_affine_scan_sharded`.
    ``alpha``: ``[keys_local]`` smoothing weights (one per series).

    Assumes dense data (fill first) — the seed position is global t = 0.
    """
    k, tl = block.shape
    a = alpha[:, None]
    first = _axis_index() == 0
    pos0 = jnp.arange(tl)[None, :] == 0
    seed = first & pos0  # global t = 0: s = x_0 regardless of the carry
    m_elem = jnp.where(seed, 0.0, jnp.broadcast_to(1.0 - a, (k, tl)))
    b_elem = jnp.where(seed, block, a * block)
    return _affine_scan_sharded(m_elem, b_elem)


def _shift1_from_left(block: jax.Array) -> jax.Array:
    """``x_{t-1}`` along the sharded time axis (global position 0 gets 0)."""
    halo = _halo_from_left(block, 1)
    return jnp.concatenate([halo, block], axis=1)[:, : block.shape[1]]


def _gpos(tl: int):
    """Global time positions of this shard's columns ``[1, tl]``."""
    return (_axis_index() * tl + jnp.arange(tl, dtype=jnp.int32))[None, :]


def sp_ewma_sse(block: jax.Array, alpha: jax.Array) -> jax.Array:
    """One-step-ahead EWMA SSE of time-sharded series ``[keys_local]``
    (matches ``ewma.sse`` on dense unsharded data): the distributed FIT
    objective — smoothing via the affine scan, the ``s_{t-1}`` lag via a
    1-column halo, the sum via ``psum`` over the time axis."""
    s = sp_ewma_smooth(block, alpha)
    sprev = _shift1_from_left(s)
    err = jnp.where(_gpos(block.shape[1]) >= 1, block - sprev, 0.0)
    return lax.psum(jnp.sum(err * err, axis=1), TIME_AXIS)


def sp_garch_neg_loglik(params: jax.Array, r: jax.Array, h0: jax.Array,
                        start: int = 0) -> jax.Array:
    """Gaussian GARCH(1,1) negative log-likelihood on a time-sharded dense
    returns panel -> ``[keys_local]`` (matches ``models.garch.
    neg_log_likelihood``).

    ``params``: ``[keys_local, 3]`` natural rows ``[omega, alpha, beta]``;
    ``h0``: ``[keys_local]`` per-series sample variance (the seed, which
    also stands in for the unobserved ``r_{start-1}^2``).  The variance
    recursion ``h_t = omega + alpha r^2_{t-1} + beta h_{t-1}`` is affine in
    the carry, so it runs as a log-depth :func:`_affine_scan_sharded`; the
    seed is folded into the element at global position ``start`` (a static
    dead prefix — ARGARCH excludes the first residual; positions before
    ``start`` contribute nothing).
    """
    omega = params[:, 0:1]
    alpha = params[:, 1:2]
    beta = params[:, 2:3]
    rsq = r * r
    rsq_prev = _shift1_from_left(rsq)
    gp = _gpos(r.shape[1])
    first = gp == start
    rsq_prev = jnp.where(first, h0[:, None], rsq_prev)
    b_elem = omega + alpha * rsq_prev
    # the seed step absorbs the carry: h_start = omega + (alpha + beta) h0
    b_elem = jnp.where(first, b_elem + beta * h0[:, None], b_elem)
    b_elem = jnp.where(gp < start, 0.0, b_elem)
    m_elem = jnp.where(gp <= start, 0.0, jnp.broadcast_to(beta, b_elem.shape))
    h = jnp.maximum(_affine_scan_sharded(m_elem, b_elem), 1e-12)
    ll_t = jnp.where(gp >= start, jnp.log(2.0 * jnp.pi * h) + rsq / h, 0.0)
    return 0.5 * lax.psum(jnp.sum(ll_t, axis=1), TIME_AXIS)


def _affine_scan_sharded_vec(A_elem: jax.Array, b_elem: jax.Array) -> jax.Array:
    """Vector generalization of :func:`_affine_scan_sharded`: inclusive scan
    of ``s_t = A_t s_{t-1} + b_t`` with ``s`` in R^q along a time-sharded
    axis, carry entering the global front = 0.

    ``A_elem``: ``[k, tl, q, q]``; ``b_elem``: ``[k, tl, q]``.  Affine maps
    on R^q compose associatively (``(A2, b2) o (A1, b1) =
    (A2 A1, b2 + A2 b1)``, O(q^3) per element — cheap for the small-q ARMA
    carries this serves), so both levels parallelize exactly as the scalar
    case: log-depth ``associative_scan`` in shard, one tiny fold of composed
    exit pairs across shards.
    """
    def comp(l, r):  # apply l then r
        lA, lb = l
        rA, rb = r
        return (jnp.einsum("...ij,...jk->...ik", rA, lA),
                rb + jnp.einsum("...ij,...j->...i", rA, lb))

    decay, pfx = lax.associative_scan(comp, (A_elem, b_elem), axis=1)
    gA = lax.all_gather(decay[:, -1:], TIME_AXIS, axis=1, tiled=True)
    gb = lax.all_gather(pfx[:, -1:], TIME_AXIS, axis=1, tiled=True)

    def fold(c, Ab):
        A, b = Ab
        c = jnp.einsum("...ij,...j->...i", A, c) + b
        return c, c

    _, carries = lax.scan(
        fold, jnp.zeros_like(gb[:, 0]),
        (jnp.moveaxis(gA, 1, 0), jnp.moveaxis(gb, 1, 0)),
    )
    carries = jnp.moveaxis(carries, 0, 1)  # [k, nshards, q]: carry EXITING
    idx = _axis_index()
    entering = jnp.where(
        idx == 0,
        jnp.zeros_like(carries[:, 0]),
        carries[:, jnp.maximum(idx - 1, 0)],
    )
    return jnp.einsum("ktij,kj->kti", decay, entering) + pfx


def _lags_from_left(block: jax.Array, nlags: int) -> list:
    """Columns ``x_{t-1} .. x_{t-nlags}`` along the sharded time axis via one
    ``nlags``-column halo exchange (positions reaching below global 0 are
    zero — the first shard's halo is zeroed)."""
    if nlags == 0:
        return []
    tl = block.shape[1]
    ext = jnp.concatenate([_halo_from_left(block, nlags), block], axis=1)
    return [lax.dynamic_slice_in_dim(ext, nlags - i, tl, axis=1)
            for i in range(1, nlags + 1)]


def sp_css_neg_loglik(params: jax.Array, yd: jax.Array, d_dead: int,
                      p: int = 1, q: int = 1) -> jax.Array:
    """Conditional-sum-of-squares negative log-likelihood of ARMA(p, q) with
    intercept on a time-sharded differenced panel -> ``[keys_local]``.

    ``params``: ``[keys_local, 1 + p + q]`` rows ``[c, phi_1..p,
    theta_1..q]``; ``yd``: this shard of the differenced series laid out on
    the ORIGINAL time grid with the first ``d_dead`` global positions zeroed
    (order-d differencing keeps shapes static by leaving a dead prefix).
    Matches ``models.arima.css_neg_loglik`` with order (p, 0, q) on the
    trimmed vector.

    The AR part ``u_t = yd_t - c - sum_i phi_i yd_{t-i}`` is recursion-free
    (a p-column halo).  The MA recursion ``e_t = u_t - sum_j theta_j
    e_{t-j}`` is affine in the carry ``s_t = (e_t .. e_{t-q+1})``: scalar
    for q = 1 (:func:`_affine_scan_sharded`), a companion-matrix carry for
    q > 1 (:func:`_affine_scan_sharded_vec`, O(q^3)-per-element composition
    — the VERDICT r4 general-order path).  Errors in the conditional
    prefix (the first p valid steps) are zeroed.
    """
    tl = yd.shape[1]
    c = params[:, 0:1]
    u = yd - c
    for i, lag in enumerate(_lags_from_left(yd, p), start=1):
        # lags reaching into the dead prefix read the zeros the grid keeps
        # there — exactly the zero-padded lags of the unsharded recursion
        u = u - params[:, i:i + 1] * lag
    live = _gpos(tl) >= d_dead + p  # dead prefix + conditional p-step zero
    if q == 0:
        e = jnp.where(live, u, 0.0)
    elif q == 1:
        theta = params[:, 1 + p:2 + p]
        m_elem = jnp.where(live, jnp.broadcast_to(-theta, u.shape), 0.0)
        b_elem = jnp.where(live, u, 0.0)
        e = _affine_scan_sharded(m_elem, b_elem)
    else:
        k = yd.shape[0]
        theta = params[:, 1 + p:1 + p + q]  # [k, q]
        # companion element: row 0 applies -theta, rows 1..q-1 shift
        row0 = jnp.broadcast_to(-theta[:, None, None, :], (k, tl, 1, q))
        rows = jnp.broadcast_to(
            jnp.eye(q, k=-1, dtype=yd.dtype)[1:][None, None],
            (k, tl, q - 1, q),
        )
        A_elem = jnp.where(live[..., None, None],
                           jnp.concatenate([row0, rows], axis=2), 0.0)
        b_elem = jnp.concatenate(
            [jnp.where(live, u, 0.0)[..., None],
             jnp.zeros((k, tl, q - 1), yd.dtype)], axis=-1,
        )
        e = _affine_scan_sharded_vec(A_elem, b_elem)[..., 0]
    css = lax.psum(jnp.sum(e * e, axis=1), TIME_AXIS)
    n = tl * _axis_size()
    n_eff = (n - d_dead) - p
    sigma2 = css / n_eff
    return 0.5 * n_eff * (jnp.log(2.0 * jnp.pi * sigma2) + 1.0)


def _sp_wols(cols, y2, w, ridge: float = 1e-8):
    """Weighted OLS across a time-sharded axis: the normal equations of
    ``models.arima._wols_cols`` with every Gram entry a ``psum``'d masked
    inner product, then the shared ridge-stabilized solve (replicated per
    time shard — a (k x k) solve per series is noise next to the panel
    reductions)."""
    from ..utils.linalg import ridge_solve

    XtX = jnp.stack(
        [jnp.stack([lax.psum(jnp.sum(w * ci * cj, axis=1), TIME_AXIS)
                    for cj in cols], -1) for ci in cols], -2,
    )  # [keys_local, k, k]
    Xty = jnp.stack(
        [lax.psum(jnp.sum(w * ci * y2, axis=1), TIME_AXIS) for ci in cols],
        -1,
    )
    return ridge_solve(XtX, Xty, ridge)


def sp_hannan_rissanen(ydb: jax.Array, d_dead: int, p: int, q: int,
                       n: int) -> jax.Array:
    """Distributed Hannan-Rissanen startup values ``[keys_local, 1+p+q]``
    (intercept first) on a time-sharded differenced panel.

    The REAL two-stage HR of ``models.arima.hannan_rissanen_batched`` —
    long-AR(m) OLS, residuals stand in for the innovations, one more OLS on
    ``[1, y-lags, e-lags]`` — not a Yule-Walker stand-in (VERDICT r4): every
    normal-equation moment is a psum'd masked product, the lag columns are
    halo exchanges, and the dead grid prefix reproduces the unsharded
    zero-padded lags exactly, so the weighted normal equations are
    identical to the unsharded ones.  ``n`` is the static global length.
    """
    n_trim = n - d_dead
    m = min(p + q + 1, max(n_trim // 4, 1))
    tl = ydb.shape[1]
    gp = _gpos(tl)
    ylag = _lags_from_left(ydb, max(m, p))
    ones = jnp.ones_like(ydb)

    # stage 1: AR(m) of yd on [1, lags 1..m] -> innovation estimates
    w1 = (gp >= d_dead + m).astype(ydb.dtype)
    cols1 = [ones] + ylag[:m]
    beta1 = _sp_wols(cols1, ydb, w1)
    pred = sum(beta1[:, j, None] * cj for j, cj in enumerate(cols1))
    ehat = (ydb - pred) * w1

    # stage 2: OLS of yd on [1, y-lags 1..p, e-lags 1..q]
    cols2 = [ones] + ylag[:p] + _lags_from_left(ehat, q)
    w2 = (gp >= d_dead + m + q).astype(ydb.dtype)
    return _sp_wols(cols2, ydb, w2)


def _carry_fold_across_shards(exit_v, exit_i, exit_f, reverse: bool):
    """Combine per-shard "latest valid (value, index)" summaries into the
    carry ENTERING each shard: a tiny fold over the all-gathered exits
    (``nshards`` elements per series), rightmost-valid-wins — or
    leftmost-valid-wins when walking ``reverse`` for the next-valid side."""
    # exits arrive as [k, 1] columns -> gathered [k, nshards] in shard order
    gv = lax.all_gather(exit_v, TIME_AXIS, axis=1, tiled=True)
    gi = lax.all_gather(exit_i, TIME_AXIS, axis=1, tiled=True)
    gf = lax.all_gather(exit_f, TIME_AXIS, axis=1, tiled=True)
    if reverse:
        gv, gi, gf = gv[:, ::-1], gi[:, ::-1], gf[:, ::-1]

    def fold(c, x):
        cv, ci, cf = c
        xv, xi, xf = x
        nv = jnp.where(xf, xv, cv)
        ni = jnp.where(xf, xi, ci)
        nf = xf | cf
        return (nv, ni, nf), (nv, ni, nf)

    _, (cv, ci, cf) = lax.scan(
        fold,
        (jnp.zeros_like(gv[:, 0]), jnp.zeros_like(gi[:, 0]),
         jnp.zeros_like(gf[:, 0])),
        (gv.T, gi.T, gf.T),
    )
    # carries[j] = combined summary of shards 0..j (walk order); entering
    # shard j is carries[j-1] (none for the walk's first shard)
    cv, ci, cf = cv.T, ci.T, cf.T  # [k, nshards]
    idx = _axis_index()
    nshards = _axis_size()
    pos = (nshards - 1 - idx) if reverse else idx
    first = pos == 0
    prev = jnp.maximum(pos - 1, 0)
    ev = jnp.where(first, jnp.zeros_like(cv[:, 0]), cv[:, prev])
    ei = jnp.where(first, jnp.zeros_like(ci[:, 0]), ci[:, prev])
    ef = jnp.where(first, False, cf[:, prev])
    return ev, ei, ef


def sp_fill_linear(block: jax.Array) -> jax.Array:
    """Linear-interpolation fill of time-sharded series (matches
    ``univariate.fill_linear`` on unsharded data: interior NaN gaps are
    interpolated between the GLOBAL bracketing valid points — which may live
    on other shards — and edge NaNs survive).

    Per shard: the gather-free prev/next-valid associative scans of the
    unsharded kernel run locally with global indices; each shard's exit
    summary (latest/earliest valid value + index) is all-gathered and folded
    into the entering carry — the prefix-combine trick of :func:`sp_cumsum`
    generalized to the "nearest valid observation" semigroup.
    """
    k, tl = block.shape
    idx = _axis_index()
    t0 = idx * tl
    # indices stay int32 end to end: f32 cannot represent positions beyond
    # 2^24, exactly the long-series regime this module exists for — only
    # the SMALL differences (t - prev_idx, span) are cast for the weights
    gpos = (t0 + jnp.arange(tl, dtype=jnp.int32))[None, :]
    valid = ~jnp.isnan(block)
    vals = jnp.where(valid, jnp.nan_to_num(block), 0.0)
    gidx = jnp.where(valid, jnp.broadcast_to(gpos, (k, tl)), 0)

    def comb(a, b):
        av, ai, af = a
        bv, bi, bf = b
        return (jnp.where(bf, bv, av), jnp.where(bf, bi, ai), af | bf)

    pv, pi, pf = lax.associative_scan(comb, (vals, gidx, valid), axis=1)
    nv, ni, nf = lax.associative_scan(comb, (vals, gidx, valid), axis=1, reverse=True)

    epv, epi, epf = _carry_fold_across_shards(
        pv[:, -1:], pi[:, -1:], pf[:, -1:], False
    )
    env, eni, enf = _carry_fold_across_shards(
        nv[:, :1], ni[:, :1], nf[:, :1], True
    )

    pv = jnp.where(pf, pv, epv[:, None])
    pi = jnp.where(pf, pi, epi[:, None])
    pf = pf | epf[:, None]
    nv = jnp.where(nf, nv, env[:, None])
    ni = jnp.where(nf, ni, eni[:, None])
    nf = nf | enf[:, None]

    interior = pf & nf
    span = jnp.maximum(ni - pi, 1).astype(block.dtype)
    w = (gpos - pi).astype(block.dtype) / span
    interp = pv * (1.0 - w) + nv * w
    nan = jnp.asarray(jnp.nan, block.dtype)
    return jnp.where(valid, block, jnp.where(interior, interp, nan))


def sp_fill_linear_chain(block: jax.Array):
    """Time-sharded fillLinear -> (filled, lag-1 difference, lag-1 shift):
    the distributed form of ``univariate.batch_fill_linear_chain`` (the lag
    crosses shard boundaries through a 1-column halo exchange)."""
    f = sp_fill_linear(block)
    halo = _halo_from_left(f, 1)
    lagged = jnp.concatenate([halo, f], axis=1)[:, : block.shape[1]]
    t0 = _axis_index() * block.shape[1]
    gpos = t0 + jnp.arange(block.shape[1])
    lagged = jnp.where(gpos[None, :] < 1, jnp.nan, lagged)
    return f, f - lagged, lagged


# ---------------------------------------------------------------------------
# Mesh-bound wrappers
# ---------------------------------------------------------------------------


def _bind(mesh: Mesh, fn, out_specs):
    spec = P(SERIES_AXIS, TIME_AXIS)
    return shard_map(fn, mesh=mesh, in_specs=(spec,), out_specs=out_specs)


def sp_autocorr_sharded(mesh: Mesh, values: jax.Array, max_lag: int) -> jax.Array:
    """``[keys, time]`` (sharded on a 2-D mesh) -> ``[keys, max_lag]``."""
    fn = _bind(mesh, functools.partial(sp_autocorr, max_lag=max_lag), P(SERIES_AXIS, None))
    return jax.jit(fn)(values)


def sp_moments_sharded(mesh: Mesh, values: jax.Array) -> Dict[str, jax.Array]:
    fn = _bind(mesh, sp_moments, {k: P(SERIES_AXIS) for k in ("count", "mean", "var")})
    return jax.jit(fn)(values)


def sp_cumsum_sharded(mesh: Mesh, values: jax.Array) -> jax.Array:
    fn = _bind(mesh, sp_cumsum, P(SERIES_AXIS, TIME_AXIS))
    return jax.jit(fn)(values)


def sp_differences_sharded(mesh: Mesh, values: jax.Array, k_lag: int = 1) -> jax.Array:
    fn = _bind(mesh, functools.partial(sp_differences, k_lag=k_lag), P(SERIES_AXIS, TIME_AXIS))
    return jax.jit(fn)(values)


def sp_fill_linear_sharded(mesh: Mesh, values: jax.Array) -> jax.Array:
    fn = _bind(mesh, sp_fill_linear, P(SERIES_AXIS, TIME_AXIS))
    return jax.jit(fn)(values)


def sp_fill_linear_chain_sharded(mesh: Mesh, values: jax.Array):
    fn = _bind(mesh, sp_fill_linear_chain, (P(SERIES_AXIS, TIME_AXIS),) * 3)
    return jax.jit(fn)(values)


def sp_ewma_smooth_sharded(mesh: Mesh, values: jax.Array, alpha: jax.Array) -> jax.Array:
    """EWMA smoothing of a ``[keys, time]`` panel time-sharded on a 2-D mesh;
    ``alpha``: ``[keys]``."""
    fn = shard_map(
        sp_ewma_smooth,
        mesh=mesh,
        in_specs=(P(SERIES_AXIS, TIME_AXIS), P(SERIES_AXIS)),
        out_specs=P(SERIES_AXIS, TIME_AXIS),
    )
    return jax.jit(fn)(values, alpha)


# ---------------------------------------------------------------------------
# Time-sharded model FITS (SURVEY.md §5.7 stretch: the reference cannot fit
# a series longer than one executor's memory; here the fit OBJECTIVE itself
# runs on the 2-D mesh, so the optimizer never materializes a whole series)
#
# Family boundary: EWMA, ARMA(1,d,1) CSS, GARCH, and ARGARCH all have
# SCALAR affine carries, so their recursions parallelize as log-depth
# associative scans with O(1) state per element.  Holt-Winters' carry is
# (level, trend, seasonal ring) — dimension m + 2 — and composing affine
# maps on R^(m+2) costs O(m^2) memory per scan element (~676 floats at
# m = 24): time-sharding it would cost far more than it saves, so HW
# long-series fits stay series-sharded by design.
# ---------------------------------------------------------------------------


def _too_short_program(k: int):
    """NaN / not-converged ``FitResult`` with ``params [keys, k]`` for panels
    statically too short to identify a model — the identifiability gates are
    decided at program-build time (panel length is static), so the too-short
    case never pays the distributed L-BFGS (ADVICE r4)."""
    from ..models.base import FitResult

    @jax.jit
    def too_short(vals):
        b = vals.shape[0]
        return FitResult(
            jnp.full((b, k), jnp.nan, vals.dtype),
            jnp.full((b,), jnp.nan, vals.dtype),
            jnp.zeros((b,), bool),
            jnp.zeros((b,), jnp.int32),
        )

    return too_short


@functools.lru_cache(maxsize=64)
def _sp_ewma_fit_program(mesh: Mesh, n: int, max_iters: int, tol: float):
    """One compiled distributed-fit program per (mesh, length, budget) —
    the ``jit_program`` discipline (``models.base``): without this every
    call would re-trace and re-compile the whole distributed L-BFGS."""
    from ..models.base import FitResult
    from ..utils import optim

    sse_sh = shard_map(
        sp_ewma_sse, mesh=mesh,
        in_specs=(P(SERIES_AXIS, TIME_AXIS), P(SERIES_AXIS)),
        out_specs=P(SERIES_AXIS),
    )
    n_eff = float(max(n - 1, 1))

    @jax.jit
    def run(vals):
        def fb(u):
            alpha = optim.sigmoid_to_interval(u[:, 0], 0.0, 1.0)
            return sse_sh(vals, alpha) / n_eff

        u0 = jnp.zeros((vals.shape[0], 1), vals.dtype)
        res = optim.minimize_lbfgs_batched(fb, u0, max_iters=max_iters, tol=tol)
        alpha = optim.sigmoid_to_interval(res.x, 0.0, 1.0)
        return FitResult(alpha, res.f * n_eff, res.converged, res.iters)

    return run


def sp_ewma_fit(mesh: Mesh, values: jax.Array, *, max_iters: int = 40,
                tol: float | None = None):
    """Fit EWMA ``alpha`` per series on a time-sharded dense panel.

    Matches ``models.ewma.fit`` (dense case) to optimizer tolerance: the
    same sigmoid-transformed mean-SSE objective and batched L-BFGS, with
    every objective/gradient evaluation a ``shard_map`` program over the
    2-D mesh (collectives ride ICI).  Returns a ``FitResult`` with
    ``params [keys, 1]``.
    """
    if tol is None:  # same dtype-dependent default as models.ewma.fit
        tol = 1e-8 if values.dtype == jnp.float64 else 1e-4
    with _sp_fit_span("ewma", mesh, values, max_iters=max_iters, tol=tol):
        return _sp_ewma_fit_program(
            mesh, values.shape[1], max_iters, float(tol)
        )(values)


@functools.lru_cache(maxsize=64)
def _sp_garch_fit_program(mesh: Mesh, n: int, max_iters: int, tol: float):
    """One compiled distributed GARCH-fit program per configuration (see
    :func:`_sp_ewma_fit_program`)."""
    from ..models import garch as _garch
    from ..models.base import FitResult
    from ..utils import optim

    if n < 10:
        # same identifiability gate as models.garch.fit (nv >= 10), decided
        # at program-build time (n is static): short panels come back
        # NaN / not-converged WITHOUT paying the distributed L-BFGS
        return _too_short_program(3)

    spec2, spec1 = P(SERIES_AXIS, TIME_AXIS), P(SERIES_AXIS)

    def var_local(rb):
        # population variance (the dense-case seed, models.garch.variances)
        mean = lax.psum(jnp.sum(rb, axis=1), TIME_AXIS) / n
        return lax.psum(jnp.sum((rb - mean[:, None]) ** 2, axis=1),
                        TIME_AXIS) / n

    var_sh = shard_map(var_local, mesh=mesh, in_specs=(spec2,),
                       out_specs=spec1)
    nll_sh = shard_map(
        sp_garch_neg_loglik, mesh=mesh,
        in_specs=(P(SERIES_AXIS, None), spec2, spec1),
        out_specs=spec1,
    )

    @jax.jit
    def run(vals):
        var0 = var_sh(vals)
        nat0 = jnp.stack(
            [0.1 * jnp.maximum(var0, 1e-10), jnp.full_like(var0, 0.1),
             jnp.full_like(var0, 0.8)], axis=1,
        )
        u0 = jax.vmap(_garch._from_natural)(nat0)

        def fb(u):
            nat = jax.vmap(_garch._to_natural)(u)
            return nll_sh(nat, vals, var0) / n

        res = optim.minimize_lbfgs_batched(fb, u0, max_iters=max_iters,
                                           tol=tol)
        nat = jax.vmap(_garch._to_natural)(res.x)
        return FitResult(nat, res.f * n, res.converged, res.iters)

    return run


def sp_garch_fit(mesh: Mesh, values: jax.Array, *, max_iters: int = 80,
                 tol: float | None = None):
    """Fit GARCH(1,1) per series on a time-sharded dense returns panel ->
    ``FitResult`` with natural ``params [keys, 3]`` (omega, alpha, beta).

    Same transform-parameterized mean-NLL objective and batched L-BFGS as
    ``models.garch.fit`` (dense case), with every evaluation a
    ``shard_map`` program on the 2-D mesh via :func:`sp_garch_neg_loglik`.
    """
    if tol is None:  # same dtype-dependent default as models.garch.fit
        tol = 1e-7 if values.dtype == jnp.float64 else 1e-4
    with _sp_fit_span("garch", mesh, values, max_iters=max_iters, tol=tol):
        return _sp_garch_fit_program(
            mesh, values.shape[1], max_iters, float(tol)
        )(values)


@functools.lru_cache(maxsize=64)
def _sp_argarch_fit_program(mesh: Mesh, n: int, max_iters: int, tol: float):
    """One compiled distributed ARGARCH-fit program per configuration (see
    :func:`_sp_ewma_fit_program`)."""
    from ..models import garch as _garch
    from ..models.base import FitResult
    from ..utils import optim

    if n < 12:
        # AR(1) + GARCH needs a few more rows than GARCH alone; decided at
        # program-build time (n is static) so the too-short case never pays
        # the distributed L-BFGS (ADVICE r4)
        return _too_short_program(5)

    spec2, spec1 = P(SERIES_AXIS, TIME_AXIS), P(SERIES_AXIS)

    def init_local(yb):
        # AR(1) moments (matches models.garch._fit_argarch_program, dense)
        mean = lax.psum(jnp.sum(yb, axis=1), TIME_AXIS) / n
        yc = yb - mean[:, None]
        ycprev = _shift1_from_left(yc)
        num = lax.psum(jnp.sum(yc * ycprev, axis=1), TIME_AXIS)
        den = lax.psum(jnp.sum(yc * yc, axis=1), TIME_AXIS)
        phi0 = jnp.clip(num / jnp.maximum(den, 1e-12), -0.95, 0.95)
        c0 = mean * (1.0 - phi0)
        prev = _shift1_from_left(yb)
        gp = _gpos(yb.shape[1])
        r = jnp.where(gp < 1, 0.0, yb - c0[:, None] - phi0[:, None] * prev)
        rvar = lax.psum(jnp.sum(r * r, axis=1), TIME_AXIS) / n
        return jnp.stack(
            [c0, phi0, 0.1 * jnp.maximum(rvar, 1e-8),
             jnp.full_like(c0, 0.1), jnp.full_like(c0, 0.8)], axis=1)

    def nll_local(nat, yb, prev):
        # ``prev`` (the 1-column lag halo, a ppermute) is loop-invariant and
        # hoisted by the caller: XLA does not reliably lift collectives out
        # of the optimizer's while_loop body (same lesson as css_prefold)
        c, phi = nat[:, 0:1], nat[:, 1:2]
        gp = _gpos(yb.shape[1])
        live = (gp >= 1).astype(yb.dtype)
        r = jnp.where(gp < 1, 0.0, yb - c - phi * prev)
        # masked population variance of the residuals over t >= 1 — the
        # GARCH seed is recomputed from the CURRENT (c, phi) every
        # evaluation, exactly as the unsharded objective does
        nv = n - 1
        mean = lax.psum(jnp.sum(r * live, axis=1), TIME_AXIS) / nv
        h0 = lax.psum(jnp.sum(live * (r - mean[:, None]) ** 2, axis=1),
                      TIME_AXIS) / nv
        return sp_garch_neg_loglik(nat[:, 2:], r, h0, start=1)

    init_sh = shard_map(init_local, mesh=mesh, in_specs=(spec2,),
                        out_specs=spec1)
    prev_sh = shard_map(_shift1_from_left, mesh=mesh, in_specs=(spec2,),
                        out_specs=spec2)
    nll_sh = shard_map(nll_local, mesh=mesh,
                       in_specs=(P(SERIES_AXIS, None), spec2, spec2),
                       out_specs=spec1)
    n_eff = float(max(n - 1, 1))

    @jax.jit
    def run(vals):
        nat0 = init_sh(vals)
        u0 = jax.vmap(_garch._argarch_from_natural)(nat0)
        prev = prev_sh(vals)

        def fb(u):
            nat = jax.vmap(_garch._argarch_to_natural)(u)
            return nll_sh(nat, vals, prev) / n_eff

        res = optim.minimize_lbfgs_batched(fb, u0, max_iters=max_iters,
                                           tol=tol)
        nat = jax.vmap(_garch._argarch_to_natural)(res.x)
        return FitResult(nat, res.f * n_eff, res.converged, res.iters)

    return run


def sp_argarch_fit(mesh: Mesh, values: jax.Array, *, max_iters: int = 100,
                   tol: float | None = None):
    """Fit AR(1)+GARCH(1,1) per series on a time-sharded dense panel ->
    ``FitResult`` with natural ``params [keys, 5]``
    ``[c, phi, omega, alpha, beta]``.

    Same transform-parameterized mean-NLL objective and batched L-BFGS as
    ``models.garch.fit_argarch`` (dense case): the AR(1) mean removal is a
    1-column halo, the GARCH seed is a psum'd masked variance of the
    current residuals, and the variance recursion runs as the log-depth
    affine scan of :func:`sp_garch_neg_loglik` with its first residual
    excluded (``start=1``).
    """
    if tol is None:  # same dtype-dependent default as models.garch.fit_argarch
        tol = 1e-7 if values.dtype == jnp.float64 else 1e-4
    with _sp_fit_span("argarch", mesh, values, max_iters=max_iters, tol=tol):
        return _sp_argarch_fit_program(
            mesh, values.shape[1], max_iters, float(tol)
        )(values)


@functools.lru_cache(maxsize=64)
def _sp_arima_fit_program(mesh: Mesh, n: int, order: tuple, max_iters: int,
                          tol: float):
    """One compiled distributed ARIMA-fit program per configuration (see
    :func:`_sp_ewma_fit_program`)."""
    from ..models.base import FitResult
    from ..utils import optim

    p, d, q = order
    k = 1 + p + q
    nvd = n - d
    # same identifiability gate as models.arima.fit (self-initialized
    # branch), decided at program-build time: lags + dof for the CSS fit,
    # plus enough span that HR's long-AR order m equals p+q+1
    if nvd < max(p + q + max(p + q + 1, 1) + k + 2, 4 * (p + q + 1)):
        return _too_short_program(k)

    # a halo exchange delivers at most ONE neighbor's columns, so every lag
    # reach (AR lags, HR's long-AR order m, HR's e-lags) must fit inside a
    # single shard — checkable at program-build time (all static)
    tl = n // mesh.shape[TIME_AXIS]
    m = min(p + q + 1, max(nvd // 4, 1))
    if max(m, p, q) > tl:
        raise ValueError(
            f"time-shard length {tl} is shorter than the longest lag reach "
            f"{max(m, p, q)} for order {order}; use fewer time shards or a "
            "longer panel"
        )

    spec2, spec1 = P(SERIES_AXIS, TIME_AXIS), P(SERIES_AXIS)

    def diff_dead(v):
        # order-d differencing on the original grid: position t holds
        # yd_t = sum_j (-1)^j C(d,j) y_{t-j}; the first d positions are dead
        for _ in range(d):
            prev = _shift1_from_left(v)
            v = v - prev
        return jnp.where(_gpos(v.shape[1]) >= d, v, 0.0)

    diff_sh = shard_map(diff_dead, mesh=mesh, in_specs=(spec2,),
                        out_specs=spec2)
    init_sh = shard_map(
        functools.partial(sp_hannan_rissanen, d_dead=d, p=p, q=q, n=n),
        mesh=mesh, in_specs=(spec2,), out_specs=spec1,
    )
    nll_sh = shard_map(
        functools.partial(sp_css_neg_loglik, d_dead=d, p=p, q=q), mesh=mesh,
        in_specs=(P(SERIES_AXIS, None), spec2),
        out_specs=spec1,
    )
    n_eff = float(max(nvd - p, 1))

    @jax.jit
    def run(vals):
        yd = diff_sh(vals)
        p0 = init_sh(yd)

        def fb(params):
            return nll_sh(params, yd) / n_eff

        res = optim.minimize_lbfgs_batched(fb, p0, max_iters=max_iters, tol=tol)
        return FitResult(res.x, res.f * n_eff, res.converged, res.iters)

    return run


def sp_arima_fit(mesh: Mesh, values: jax.Array, order: Order = (1, 1, 1), *,
                 max_iters: int = 60, tol: float | None = None):
    """Fit ARIMA(p, d, q) with intercept per series on a time-sharded dense
    panel -> ``FitResult`` with ``params [keys, 1+p+q]`` rows
    ``[c, phi_1..p, theta_1..q]``.

    The headline model family, time-sharded end to end for any small order
    (VERDICT r4): order-d differencing (halo exchanges, dead prefix kept on
    the grid), the REAL two-stage Hannan-Rissanen init from psum'd normal
    equations (:func:`sp_hannan_rissanen`), then batched L-BFGS on
    :func:`sp_css_neg_loglik` — every evaluation one ``shard_map`` program
    whose MA recursion is a log-depth (companion-matrix for q > 1) affine
    scan.  Matches ``models.arima.fit`` backends to optimizer tolerance on
    the same panel (both minimize the identical CSS objective).  Panels too
    short for the order come back NaN / not-converged without paying the
    optimizer (same gate as the unsharded fit).
    """
    if tol is None:  # same dtype-dependent default as models.arima.fit
        tol = 1e-6 if values.dtype == jnp.float64 else 1e-4
    with _sp_fit_span("arima", mesh, values, order=tuple(order),
                      max_iters=max_iters, tol=tol):
        return _sp_arima_fit_program(
            mesh, values.shape[1], tuple(order), max_iters, float(tol)
        )(values)
