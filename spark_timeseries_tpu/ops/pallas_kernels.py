"""Pallas TPU kernels for the sequential-recursion hot paths.

The reference runs its model recursions (ARMA one-step-ahead CSS errors,
GARCH conditional variance, EWMA smoothing) as per-series JVM loops
(``sparkts/models/ARIMA.scala`` ``logLikelihoodCSS`` /
``gradientLogLikelihoodCSSARMA``, ``GARCH.scala``, ``EWMA.scala`` —
SURVEY.md §2.2, upstream paths unverified).  The portable rebuild expresses
them as ``jax.vmap(lax.scan)`` (``models/arima.py`` etc.), which is correct
everywhere but pays one XLA loop iteration — several HBM round trips — per
time step.

These kernels fuse the *entire* recursion into one grid step whose series
block lives in VMEM: series are folded to ``[time, 8, 128]`` tiles
(sublane x lane = 1024 series per block), the natural f32 vector-register
shape, so every time step is a handful of full-width VPU ops instead of an
XLA loop iteration.

Like the reference — which hand-derives ``gradientLogLikelihoodCSSARMA``
rather than relying on automatic differentiation — the ARMA kernel ships a
hand-derived adjoint recursion, exposed through ``jax.custom_vjp`` so the
batched L-BFGS driver (``utils/optim``) can differentiate the CSS objective
without XLA's scan transpose.  The adjoint propagates cotangents to the
parameters only; the observations are treated as constants (exactly the
reference's gradient), so these entry points are used inside fit objectives
and not exposed as general autodiff building blocks.

Everything here is optional: callers gate on :func:`supported` and fall back
to the ``lax.scan`` implementations (same semantics, cross-checked by
``tests/test_pallas.py`` in interpret mode).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Order = Tuple[int, int, int]

_SUBL = 8  # f32 sublanes per vector register
_LANES = 128  # TPU lane width
_SBLK = _SUBL * _LANES  # series per grid step (1024)
# VMEM budget: the adjoint kernel holds y, e, and the e-adjoint as
# [T, 8, 128] f32 tiles (4 KiB per time step each) -> ~12 KiB * T; cap T to
# stay well inside ~16 MiB/core.
_MAX_T = 1024
# Scoped-VMEM override shared by every kernel here: at T near _MAX_T the
# double-buffered in/out tiles (plus the adjoint scratch in the backward
# kernel) exceed the default 16 MiB budget.
_VMEM_PARAMS = pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)


def supported(dtype, n_time: int) -> bool:
    """True when the fused kernels can run natively on this platform/shape."""
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover - no/broken backend
        return False
    return (
        platform in ("tpu", "axon")
        and jnp.dtype(dtype) == jnp.dtype(jnp.float32)
        and n_time <= _MAX_T
    )


def _pad_to(n: int, m: int) -> int:
    return (-n) % m


def _fold(x2d):
    """``[B, n] -> [n, B_pad/128-groups]`` series folding.

    Returns ``[n, Bp // 128 sublane-rows, 128]`` where consecutive series map
    to consecutive lanes; the kernel grid walks 8-sublane blocks of axis 1.
    """
    b, n = x2d.shape
    x2d = jnp.pad(x2d, ((0, _pad_to(b, _SBLK)), (0, 0)))
    bp = x2d.shape[0]
    return x2d.T.reshape(n, bp // _LANES, _LANES)


def _unfold(x3d, b: int):
    """Inverse of :func:`_fold`: ``[n, Bp/128, 128] -> [B, n]``."""
    n = x3d.shape[0]
    return x3d.reshape(n, -1).T[:b]


def _blockspec(n0: int):
    """Whole axis 0, one [8, 128] series block of axis 1/2 per grid step."""
    return pl.BlockSpec((n0, _SUBL, _LANES), lambda blk: (0, blk, 0))


# ---------------------------------------------------------------------------
# ARMA CSS one-step-ahead prediction errors (forward + hand-derived adjoint)
# ---------------------------------------------------------------------------
#
# Per series (reference ARIMAModel.logLikelihoodCSSARMA):
#   u_t = y_t - c - sum_i phi_i * y_{t-i} - sum_j theta_j * e_{t-j}
#   e_t = m_t * u_t        with m_t = [zb <= t < t_limit], y_{<0} = e_{<0} = 0
#
# Adjoint (reference gradientLogLikelihoodCSSARMA, generalized to an
# arbitrary upstream cotangent gbar of e):
#   a_t         = m_t * (gbar_t - sum_j theta_j * a_{t+j})      (t descending)
#   dL/dc       = -sum_t a_t
#   dL/dphi_i   = -sum_t y_{t-i} * a_t
#   dL/dtheta_j = -sum_t e_{t-j} * a_t


def _css_fwd_kernel(p, q, t_limit, n_t, y_ref, par_ref, zb_ref, e_ref):
    zb = zb_ref[0]

    def body(t, _):
        pred = par_ref[0]
        for i in range(1, p + 1):
            yi = y_ref[jnp.maximum(t - i, 0)]
            pred += par_ref[i] * jnp.where(t - i >= 0, yi, 0.0)
        for j in range(1, q + 1):
            ej = e_ref[jnp.maximum(t - j, 0)]
            pred += par_ref[p + j] * jnp.where(t - j >= 0, ej, 0.0)
        live = (t.astype(jnp.float32) >= zb) & (t < t_limit)
        e_ref[t] = jnp.where(live, y_ref[t] - pred, 0.0)
        return 0

    lax.fori_loop(0, n_t, body, 0)


def _css_bwd_kernel(p, q, t_limit, n_t,
                    y_ref, e_ref, par_ref, zb_ref, g_ref, gpar_ref, adj_ref):
    adj_ref[:] = g_ref[:]
    zb = zb_ref[0]
    k = 1 + p + q
    zero = jnp.zeros((_SUBL, _LANES), jnp.float32)

    def body(i, accs):
        t = n_t - 1 - i
        live = (t.astype(jnp.float32) >= zb) & (t < t_limit)
        a = jnp.where(live, adj_ref[t], 0.0)
        for j in range(1, q + 1):
            idx = jnp.maximum(t - j, 0)
            contrib = jnp.where(t - j >= 0, par_ref[p + j] * a, 0.0)
            adj_ref[idx] = adj_ref[idx] - contrib
        new = [accs[0] - a]
        for i_ in range(1, p + 1):
            yi = jnp.where(t - i_ >= 0, y_ref[jnp.maximum(t - i_, 0)], 0.0)
            new.append(accs[i_] - yi * a)
        for j in range(1, q + 1):
            ej = jnp.where(t - j >= 0, e_ref[jnp.maximum(t - j, 0)], 0.0)
            new.append(accs[p + j] - ej * a)
        return tuple(new)

    accs = lax.fori_loop(0, n_t, body, tuple(zero for _ in range(k)))
    for r in range(k):
        gpar_ref[r] = accs[r]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def css_errors(p: int, q: int, interpret: bool, params, yd, zb):
    """Batched ARMA(p, q) CSS errors ``[B, T]`` on a fused TPU kernel.

    ``params``: ``[B, 1 + p + q]`` rows ``[c, phi_1..p, theta_1..q]`` (models
    without an intercept pass ``c = 0``); ``yd``: ``[B, T]`` differenced
    series with any invalid prefix already zeroed; ``zb``: ``[B]`` float —
    errors before this position are forced to zero (``start + p`` for the
    conditional likelihood).  Gradients flow to ``params`` only.
    """
    e, _ = _css_errors_fwd(p, q, interpret, params, yd, zb)
    return e


def _css_errors_fwd(p, q, interpret, params, yd, zb):
    b, t = yd.shape
    k = 1 + p + q
    assert params.shape == (b, k), (params.shape, (b, k))
    tp = t + _pad_to(t, _SUBL)
    y3 = _fold(jnp.pad(yd, ((0, 0), (0, tp - t))))
    par3 = _fold(params)
    zb3 = _fold(zb.astype(yd.dtype)[:, None])
    nblk = y3.shape[1] // _SUBL
    e3 = pl.pallas_call(
        functools.partial(_css_fwd_kernel, p, q, t, tp),
        grid=(nblk,),
        in_specs=[_blockspec(tp), _blockspec(k), _blockspec(1)],
        out_specs=_blockspec(tp),
        out_shape=jax.ShapeDtypeStruct(y3.shape, yd.dtype),
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(y3, par3, zb3)
    return _unfold(e3, b)[:, :t], (y3, par3, zb3, e3)


def _css_errors_bwd(p, q, interpret, res, g):
    y3, par3, zb3, e3 = res
    tp = y3.shape[0]
    b, t = g.shape
    k = 1 + p + q
    g3 = _fold(jnp.pad(g, ((0, 0), (0, tp - t))))
    nblk = y3.shape[1] // _SUBL
    gpar3 = pl.pallas_call(
        functools.partial(_css_bwd_kernel, p, q, t, tp),
        grid=(nblk,),
        in_specs=[_blockspec(tp)] * 2 + [_blockspec(k), _blockspec(1), _blockspec(tp)],
        out_specs=_blockspec(k),
        out_shape=jax.ShapeDtypeStruct(par3.shape, g.dtype),
        scratch_shapes=[pltpu.VMEM((tp, _SUBL, _LANES), jnp.float32)],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(y3, e3, par3, zb3, g3)
    gparams = _unfold(gpar3, b)
    # observations and the mask boundary are constants of the fit objective
    return gparams, jnp.zeros((b, t), g.dtype), jnp.zeros((b,), g.dtype)


css_errors.defvjp(_css_errors_fwd, _css_errors_bwd)


def css_neg_loglik(params, yd, order: Order, include_intercept: bool,
                   n_valid=None, *, interpret: bool = False):
    """Batched CSS negative log-likelihood ``[B]`` on the fused kernel.

    Matches ``models.arima.css_neg_loglik`` (vmapped) to float tolerance;
    differentiable in ``params`` via the hand-derived adjoint.
    """
    p, _, q = order
    b, n = yd.shape
    nv = jnp.full((b,), n, yd.dtype) if n_valid is None else n_valid.astype(yd.dtype)
    start = n - nv
    t_idx = jnp.arange(n, dtype=yd.dtype)
    ydz = jnp.where(t_idx[None, :] >= start[:, None], yd, 0.0)
    if include_intercept:
        params_k = params
    else:  # kernel layout always carries an intercept slot
        params_k = jnp.concatenate(
            [jnp.zeros((b, 1), params.dtype), params], axis=1
        )
    e = css_errors(p, q, interpret, params_k, ydz, start + p)
    n_eff = nv - p
    css = jnp.sum(e * e, axis=1)
    sigma2 = css / n_eff
    return 0.5 * n_eff * (jnp.log(2.0 * jnp.pi * sigma2) + 1.0)


# ---------------------------------------------------------------------------
# GARCH(1, 1) conditional-variance recursion
# ---------------------------------------------------------------------------
#
# h_t = omega + alpha * r_{t-1}^2 + beta * h_{t-1}, h_start = h0
# (reference GARCH.scala log-likelihood loop).  The prefix [0, zb) holds
# h_t = h0 so padded series contribute nothing.


def _garch_fwd_kernel(t_limit, n_t, r2_ref, par_ref, h0_ref, zb_ref, h_ref):
    zb = zb_ref[0]
    h0 = h0_ref[0]

    def body(t, _):
        tf = t.astype(jnp.float32)
        hp = h_ref[jnp.maximum(t - 1, 0)]
        hp = jnp.where(t - 1 >= 0, hp, h0)
        r2p = jnp.where(t - 1 >= 0, r2_ref[jnp.maximum(t - 1, 0)], 0.0)
        # the first live step seeds with h0 standing in for r_{start-1}^2
        # (matching models.garch.variances)
        r2p = jnp.where(tf == zb, h0, r2p)
        h = par_ref[0] + par_ref[1] * r2p + par_ref[2] * hp
        live = (tf >= zb) & (t < t_limit)
        h_ref[t] = jnp.where(live, h, h0)
        return 0

    lax.fori_loop(0, n_t, body, 0)


def garch_variances(params, r, h0, zb, *, interpret: bool = False):
    """Batched GARCH(1,1) conditional variances ``[B, T]`` (no grad path —
    used for the forward/diagnostic entry points).

    ``params``: ``[B, 3]`` rows ``[omega, alpha, beta]``; ``r``: ``[B, T]``
    returns with the invalid prefix zeroed; ``h0``: ``[B]`` start variance;
    ``zb``: ``[B]`` first live position.
    """
    b, t = r.shape
    tp = t + _pad_to(t, _SUBL)
    r2 = _fold(jnp.pad(r * r, ((0, 0), (0, tp - t))))
    par3 = _fold(params)
    h03 = _fold(h0[:, None].astype(r.dtype))
    zb3 = _fold(zb.astype(r.dtype)[:, None])
    nblk = r2.shape[1] // _SUBL
    h3 = pl.pallas_call(
        functools.partial(_garch_fwd_kernel, t, tp),
        grid=(nblk,),
        in_specs=[_blockspec(tp), _blockspec(3), _blockspec(1), _blockspec(1)],
        out_specs=_blockspec(tp),
        out_shape=jax.ShapeDtypeStruct(r2.shape, r.dtype),
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(r2, par3, h03, zb3)
    return _unfold(h3, b)[:, :t]
