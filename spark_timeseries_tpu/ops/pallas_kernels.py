"""Pallas TPU kernels for the sequential-recursion hot paths.

The reference runs its model recursions (ARMA one-step-ahead CSS errors,
GARCH conditional variance, EWMA smoothing, Holt-Winters state) as
per-series JVM loops (``sparkts/models/ARIMA.scala`` ``logLikelihoodCSS`` /
``gradientLogLikelihoodCSSARMA``, ``GARCH.scala``, ``EWMA.scala``,
``HoltWinters.scala`` — SURVEY.md §2.2, upstream paths unverified).  The
portable rebuild expresses them as ``jax.vmap(lax.scan)`` (``models/*``),
which is correct everywhere but pays one XLA loop iteration — several HBM
round trips — per time step.

These kernels fuse the recursion into grid steps whose series block lives in
VMEM: series are folded to ``[time, 8, 128]`` tiles (sublane x lane = 1024
series per block), the natural f32 vector-register shape, so every time step
is a handful of full-width VPU ops instead of an XLA loop iteration.

SERIES LENGTH IS UNBOUNDED: the grid is ``(series_block, time_chunk)`` with
the chunk axis innermost (TPU iterates it sequentially), each chunk holding
``_CHUNK_T`` steps in VMEM.  Lag reads that cross a chunk boundary come from
a NEIGHBOR INPUT BLOCK (the previous time chunk mapped as a second input);
recursion state that flows forward/backward across chunks (trailing errors,
the variance/smoothing carry, adjoint carries, gradient accumulators) lives
in VMEM scratch, which persists across the sequential chunk dimension.
Parameter-gradient outputs use the revisited-output-block reduction pattern
(initialize at the first chunk, accumulate, final value flushed once).

Like the reference — which hand-derives ``gradientLogLikelihoodCSSARMA``
rather than relying on automatic differentiation — every kernel pair ships a
hand-derived adjoint recursion, exposed through ``jax.custom_vjp`` so the
batched L-BFGS driver (``utils/optim``) can differentiate the objectives
without XLA's scan transpose.  Cotangents flow to the parameters (and for
GARCH also to the squared returns and the variance seed, so ARGARCH's mean
parameters differentiate exactly); everything else is a constant of the fit
objective, so these entry points are used inside fit objectives and not
exposed as general autodiff building blocks.

Everything here is optional: callers gate on :func:`supported` and fall back
to the ``lax.scan`` implementations (same semantics, cross-checked by
``tests/test_pallas.py`` in interpret mode and by the on-device parity gate
in ``bench.py``).

PROFILED HEADROOM (next round): the per-step recursion loops are bounded by
loop machinery, not arithmetic — the vectorized (full-tile, static-slice)
rewrite of the non-recursive kernels here (autocorr, HR moments) measured
~6x over their per-step forms.  The CSS/GARCH/EWMA recursions with q <= 1
are LINEAR with per-series constant coefficients, i.e. affine maps of the
carry, so they admit an in-VMEM log-depth doubling scan over composed
(m, b) pairs exactly like ``ops.seqparallel.sp_ewma_smooth`` does across
shards — ~10 full-tile steps instead of ~1000 serial ones, for both the
forward and the (also affine) adjoint recursion.  One invariant to keep:
the value-only and residual-saving variants of an objective must emit
BITWISE-identical values (same accumulation association), so the scan
rewrite must cover every mode of a kernel at once, not just the hot one.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.custom_derivatives import SymbolicZero
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Order = Tuple[int, int, int]

_SUBL = 8  # f32 sublanes per vector register
_LANES = 128  # TPU lane width
_SBLK = _SUBL * _LANES  # series per grid step (1024)
_CHUNK_T = 1024  # time steps resident in VMEM per grid step
# Scoped-VMEM override shared by every kernel here: a handful of
# [_CHUNK_T, 8, 128] blocks plus double buffering exceeds the default budget.
# (``CompilerParams`` was named ``TPUCompilerParams`` before jax 0.6 — take
# whichever this build provides so CPU-only environments can still import
# the module and reach the interpret/scan paths.)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")
_VMEM_PARAMS = _CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)

_ZERO = lambda: jnp.zeros((_SUBL, _LANES), jnp.float32)  # noqa: E731


def _fori(n, body, init, unroll: int = 1):
    """Sequential time loop with the index coerced to int32: under
    ``jax_enable_x64`` the loop variable would otherwise trace as int64,
    which pallas ref indexing cannot lower.  (Unrolling was measured to buy
    nothing for the RECURSION kernels — their true data dependencies, not
    loop overhead, bound each step — but the fill sweeps' dependency chains
    are one select deep, and there loop machinery dominates: pass
    ``unroll`` > 1 for those.)"""

    def body32(i, carry):
        return body(jnp.asarray(i, jnp.int32), carry)

    if unroll == 1:
        return lax.fori_loop(0, n, body32, init)
    if n % unroll:  # chunk lengths are 8-aligned, so 2/4/8 always divide
        raise ValueError(f"unroll={unroll} must divide n={n}")

    def outer(j, carry):
        i0 = j * jnp.int32(unroll)
        for k in range(unroll):  # Mosaic only full-unrolls, so do it by hand
            carry = body32(i0 + k, carry)
        return carry

    return lax.fori_loop(0, n // unroll, outer, init)


def supported(dtype, n_time: int) -> bool:
    """True when the fused kernels can run natively on this platform/dtype.

    ``n_time`` is unrestricted (time-chunked grids); it remains a parameter
    so callers keep passing their shape and future constraints stay cheap.
    """
    del n_time
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover - no/broken backend
        return False
    return platform in ("tpu", "axon") and jnp.dtype(dtype) == jnp.dtype(jnp.float32)


def css_structural_ok(p: int, q: int) -> bool:
    """The CSS kernels' chunked layout: lag reads reach back at most one
    chunk (the neighbor input block), and the cross-chunk adjoint/error
    stashes interleave their reads (positions ``>= cs - order``) with their
    writes (positions ``< order``) inside one chunk, which is race-free only
    while ``order <= chunk/2`` — so both orders must stay under
    ``_CHUNK_T // 2``."""
    return 0 <= p <= _CHUNK_T // 2 and 0 <= q <= _CHUNK_T // 2


def hw_structural_ok(period: int) -> bool:
    """The Holt-Winters kernels keep two whole ``[period, 8, 128]`` seasonal
    rings in VMEM scratch beside the chunk blocks; periods past one chunk
    blow the scoped-VMEM budget with an opaque Mosaic error, so they are
    rejected up front (use the scan backend)."""
    return 0 < period <= _CHUNK_T


def _pad_to(n: int, m: int) -> int:
    return (-n) % m


def _scoped(name):
    """Profiler annotation (SURVEY.md §5.1 rebuild analog): each fused
    objective shows up as one named block in jax.profiler / Perfetto traces."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*a, **k):
            with jax.named_scope(name):
                return fn(*a, **k)
        return wrapped
    return deco


def _time_layout(t: int) -> Tuple[int, int, int]:
    """-> (padded_t, chunk_len, n_chunks) for a series of length ``t``."""
    tp8 = t + _pad_to(t, _SUBL)
    if tp8 <= _CHUNK_T:
        return tp8, tp8, 1
    tp = t + _pad_to(t, _CHUNK_T)
    return tp, _CHUNK_T, tp // _CHUNK_T


def _fold(x2d):
    """``[B, n] -> [n, Bp/128, 128]`` series folding: consecutive series map
    to consecutive lanes; the kernel grid walks 8-sublane blocks of axis 1."""
    b, n = x2d.shape
    x2d = jnp.pad(x2d, ((0, _pad_to(b, _SBLK)), (0, 0)))
    bp = x2d.shape[0]
    return x2d.T.reshape(n, bp // _LANES, _LANES)


def _unfold(x3d, b: int):
    """Inverse of :func:`_fold`: ``[n, Bp/128, 128] -> [B, n]``."""
    n = x3d.shape[0]
    return x3d.reshape(n, -1).T[:b]


def _bs(n0: int, imap):
    return pl.BlockSpec((n0, _SUBL, _LANES), imap)


def _cur(blk, c):  # current time chunk
    return (c, blk, 0)


def _prev(blk, c):  # previous time chunk (clamped; guarded by global-t checks)
    return (jnp.maximum(c - 1, 0), blk, 0)


def _fixed(blk, c):  # chunk-invariant block (params, seeds, reductions)
    return (0, blk, 0)


def _rev(nchunk):  # walk time chunks last-to-first
    return lambda blk, c: (nchunk - 1 - c, blk, 0)


def _rev_prev(nchunk):  # previous TIME chunk while walking backward
    return lambda blk, c: (jnp.maximum(nchunk - 2 - c, 0), blk, 0)


# ---------------------------------------------------------------------------
# ARMA CSS one-step-ahead prediction errors (forward + hand-derived adjoint)
# ---------------------------------------------------------------------------
#
# Per series (reference ARIMAModel.logLikelihoodCSSARMA):
#   u_t = y_t - c - sum_i phi_i * y_{t-i} - sum_j theta_j * e_{t-j}
#   e_t = m_t * u_t        with m_t = [zb <= t < t_limit], y_{<0} = e_{<0} = 0
#
# Adjoint (reference gradientLogLikelihoodCSSARMA, generalized to an
# arbitrary upstream cotangent gbar of e):
#   a_t         = m_t * (gbar_t - sum_j theta_j * a_{t+j})      (t descending)
#   dL/dc       = -sum_t a_t
#   dL/dphi_i   = -sum_t y_{t-i} * a_t
#   dL/dtheta_j = -sum_t e_{t-j} * a_t
#
# Cross-chunk state: the forward carries the last q errors (scratch); the
# backward carries the adjoints of the first q positions of the next-later
# chunk (scratch) and accumulates the k parameter gradients in the revisited
# output block.


def _css_fwd_kernel(p, q, t_limit, cs, hp, mode, *refs):
    # mode "e":    errors out (the css_errors vjp building block)
    # mode "sum":  ONLY the per-series sum of squares leaves the kernel
    #              (linesearch evaluations: the [B, T] error write + re-read
    #              is the pass's HBM bill); errors live in a VMEM scratch
    # mode "both": errors out AND the sum, accumulated in the SAME order as
    #              "sum" (the optimizer compares f across both paths; mixed
    #              accumulation orders stall rows at the noise floor)
    # mode "tail": ONLY the last q errors leave the kernel (the forecast
    #              carry rebuild: a read-only pass over y instead of a full
    #              [B, T] error write the caller immediately discards)
    refs = list(refs)
    y_ref = refs.pop(0)
    yp_ref = refs.pop(0) if hp else None
    par_ref = refs.pop(0)
    zb_ref = refs.pop(0)
    e_ref = refs.pop(0) if mode in ("e", "both") else None
    css_ref = refs.pop(0) if mode in ("sum", "both") else None
    tail_ref = refs.pop(0) if mode == "tail" else None
    if mode in ("sum", "tail") and q > 0:
        e_ref = refs.pop(0)  # scratch: lag reads still need recent errors
    ce_ref = refs.pop(0)
    c = pl.program_id(1)
    base = c * cs
    zb = zb_ref[0]

    @pl.when(c == 0)
    def _():
        for j in range(max(q, 1)):
            ce_ref[j] = _ZERO()
        if css_ref is not None:
            css_ref[0] = _ZERO()

    def body(tl, acc):
        t = base + tl
        pred = par_ref[0]
        for i in range(1, p + 1):
            far = yp_ref[jnp.clip(cs + tl - i, 0, cs - 1)] if hp else 0.0
            yv = jnp.where(tl - i >= 0, y_ref[jnp.maximum(tl - i, 0)], far)
            pred += par_ref[i] * jnp.where(t - i >= 0, yv, 0.0)
        for j in range(1, q + 1):
            ev = jnp.where(
                tl - j >= 0,
                e_ref[jnp.maximum(tl - j, 0)],
                ce_ref[jnp.clip(q + tl - j, 0, max(q - 1, 0))],
            )
            pred += par_ref[p + j] * jnp.where(t - j >= 0, ev, 0.0)
        live = (t.astype(jnp.float32) >= zb) & (t < t_limit)
        e = jnp.where(live, y_ref[tl] - pred, 0.0)
        if e_ref is not None:  # sum mode with q == 0 never reads errors back
            e_ref[tl] = e
        return (acc + e * e) if css_ref is not None else acc

    # (a guarded-prologue / unguarded-steady-state split was measured to buy
    # nothing: the recursion's serial data dependency, not the boundary
    # selects, bounds each step)
    acc = _fori(cs, body, _ZERO() if css_ref is not None else 0)
    if css_ref is not None:
        css_ref[0] = css_ref[0] + acc
    if tail_ref is not None:
        # the last q TRUE errors sit at static global positions
        # t_limit - q + j; each lands in a statically known chunk/slot
        for j in range(q):
            g = t_limit - q + j
            ci, loc = g // cs, g % cs

            @pl.when(c == ci)
            def _(j=j, loc=loc):
                tail_ref[j] = e_ref[loc]
    # slot s holds e at global (base + cs) - q + s for the next chunk
    for j in range(q):
        ce_ref[j] = e_ref[cs - q + j]


def _css_bwd_kernel(p, q, t_limit, cs, nchunk, hp, want_gy, *refs):
    refs = list(refs)
    y_ref = refs.pop(0)
    yp_ref = refs.pop(0) if hp else None
    e_ref = refs.pop(0)
    ep_ref = refs.pop(0) if hp else None
    par_ref = refs.pop(0)
    zb_ref = refs.pop(0)
    g_ref = refs.pop(0)
    gpar_ref = refs.pop(0)
    gy_ref = refs.pop(0) if want_gy else None
    adj_ref = refs.pop(0)
    ca_ref = refs.pop(0)
    cap_ref = refs.pop(0) if want_gy else None
    c = pl.program_id(1)
    base = (nchunk - 1 - c) * cs
    zb = zb_ref[0]
    k = 1 + p + q

    @pl.when(c == 0)
    def _():
        for j in range(max(q, 1)):
            ca_ref[j] = _ZERO()
        for r in range(k):
            gpar_ref[r] = _ZERO()
        if want_gy:
            for i_ in range(max(p, 1)):
                cap_ref[i_] = _ZERO()

    adj_ref[:] = g_ref[:]

    def body(i, accs):
        tl = cs - 1 - i
        t = base + tl
        live = (t.astype(jnp.float32) >= zb) & (t < t_limit)
        aval = adj_ref[tl]
        # contributions from a_{t+j} that live in the next-later chunk
        for j in range(1, q + 1):
            aval = aval - jnp.where(
                tl + j >= cs,
                par_ref[p + j] * ca_ref[jnp.clip(tl + j - cs, 0, max(q - 1, 0))],
                0.0,
            )
        a = jnp.where(live, aval, 0.0)
        if want_gy:
            # adj_ref[s] for s > tl has already been read (descending walk)
            # and every theta adjustment targeting it landed before its own
            # iteration, so the slot is dead — overwrite it with the FINAL
            # adjoint a_s and read it back for the data cotangent
            #   dL/dy_t = a_t - sum_i phi_i a_{t+i}
            # (a_{t+i} in the next-later chunk comes from the cap carry)
            adj_ref[tl] = a
            gy = a
            for i_ in range(1, p + 1):
                far = (cap_ref[jnp.clip(tl + i_ - cs, 0, max(p - 1, 0))]
                       if hp else 0.0)
                av = jnp.where(
                    tl + i_ < cs, adj_ref[jnp.clip(tl + i_, 0, cs - 1)], far
                )
                gy = gy - par_ref[i_] * av
            gy_ref[tl] = gy
            if hp and p > 0:
                # stash a for the chunk below: writes hit tl < p, reads need
                # tl >= cs - p; disjoint because cs >= 2p (css_structural_ok)
                curc = cap_ref[jnp.clip(tl, 0, max(p - 1, 0))]
                cap_ref[jnp.clip(tl, 0, max(p - 1, 0))] = jnp.where(
                    tl < p, a, curc
                )
        for j in range(1, q + 1):
            idx = jnp.maximum(tl - j, 0)
            contrib = jnp.where(tl - j >= 0, par_ref[p + j] * a, 0.0)
            adj_ref[idx] = adj_ref[idx] - contrib
        new = [accs[0] - a]
        for i_ in range(1, p + 1):
            far = yp_ref[jnp.clip(cs + tl - i_, 0, cs - 1)] if hp else 0.0
            yv = jnp.where(tl - i_ >= 0, y_ref[jnp.maximum(tl - i_, 0)], far)
            yv = jnp.where(t - i_ >= 0, yv, 0.0)
            new.append(accs[i_] - yv * a)
        for j in range(1, q + 1):
            far = ep_ref[jnp.clip(cs + tl - j, 0, cs - 1)] if hp else 0.0
            ev = jnp.where(tl - j >= 0, e_ref[jnp.maximum(tl - j, 0)], far)
            ev = jnp.where(t - j >= 0, ev, 0.0)
            new.append(accs[p + j] - ev * a)
        # stash a for the chunk below: writes hit tl < q, reads need
        # tl >= cs - q; disjoint because cs >= 2q
        cur = ca_ref[jnp.clip(tl, 0, max(q - 1, 0))]
        ca_ref[jnp.clip(tl, 0, max(q - 1, 0))] = jnp.where(tl < q, a, cur)
        return tuple(new)

    accs = _fori(cs, body, tuple(_ZERO() for _ in range(k)))
    for r in range(k):
        gpar_ref[r] = gpar_ref[r] + accs[r]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def css_errors(p: int, q: int, interpret: bool, params, yd, zb):
    """Batched ARMA(p, q) CSS errors ``[B, T]`` on a fused TPU kernel.

    ``params``: ``[B, 1 + p + q]`` rows ``[c, phi_1..p, theta_1..q]`` (models
    without an intercept pass ``c = 0``); ``yd``: ``[B, T]`` differenced
    series with any invalid prefix already zeroed; ``zb``: ``[B]`` float —
    errors before this position are forced to zero (``start + p`` for the
    conditional likelihood).  Differentiable in ``params`` AND ``yd`` (the
    data cotangent ``dL/dy_t = a_t - sum_i phi_i a_{t+i}`` is an extra
    backward-kernel output computed only when ``yd`` is perturbed, so the
    params-only fit path pays nothing for it — ADVICE r4).
    """
    if not css_structural_ok(p, q):
        raise ValueError(
            f"fused CSS kernel supports p, q <= {_CHUNK_T // 2} (got p={p}, q={q}); "
            "use backend='scan'"
        )
    e, _ = _css_errors_primal(p, q, interpret, params, yd, zb)
    return e


def _css_fwd_call(p, q, interpret, mode, params, yd, zb):
    b, t = yd.shape
    k = 1 + p + q
    assert params.shape == (b, k), (params.shape, (b, k))
    tp, cs, nchunk = _time_layout(t)
    y3 = _fold(jnp.pad(yd, ((0, 0), (0, tp - t))))
    zb3 = _fold(zb.astype(yd.dtype)[:, None])
    return _css_fwd_call_f(p, q, interpret, mode, params, y3, zb3, t)


def _css_fwd_call_f(p, q, interpret, mode, params, y3, zb3, t):
    # pre-FOLDED entry: y3/zb3 already in kernel layout.  The fit objective
    # is evaluated hundreds of times inside one lax.while_loop, and XLA does
    # not reliably hoist the [B, T] zero-mask + fold transpose out of the
    # loop body — callers that fold once (css_prefold) skip that cost on
    # every evaluation.
    k = 1 + p + q
    par3 = _fold(params)  # [B, k]: trivially small
    tp, cs, nchunk = _time_layout(t)
    nblk = y3.shape[1] // _SUBL
    hp = nchunk > 1
    out_specs, out_shape = [], []
    if mode in ("e", "both"):
        out_specs.append(_bs(cs, _cur))
        out_shape.append(jax.ShapeDtypeStruct(y3.shape, y3.dtype))
    if mode in ("sum", "both"):
        out_specs.append(_bs(1, _fixed))
        out_shape.append(
            jax.ShapeDtypeStruct((1, y3.shape[1], _LANES), y3.dtype)
        )
    if mode == "tail":
        out_specs.append(_bs(max(q, 1), _fixed))
        out_shape.append(
            jax.ShapeDtypeStruct((max(q, 1), y3.shape[1], _LANES), y3.dtype)
        )
    scratch = []
    if mode in ("sum", "tail") and q > 0:  # errors live in VMEM only
        scratch.append(pltpu.VMEM((cs, _SUBL, _LANES), jnp.float32))
    scratch.append(pltpu.VMEM((max(q, 1), _SUBL, _LANES), jnp.float32))
    outs = pl.pallas_call(
        functools.partial(_css_fwd_kernel, p, q, t, cs, hp, mode),
        grid=(nblk, nchunk),
        in_specs=([_bs(cs, _cur)] + ([_bs(cs, _prev)] if hp else [])
                  + [_bs(k, _fixed), _bs(1, _fixed)]),
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(*((y3, y3) if hp else (y3,)), par3, zb3)
    return outs, (y3, par3, zb3)


def _css_errors_primal(p, q, interpret, params, yd, zb):
    b, t = yd.shape
    (e3,), (y3, par3, zb3) = _css_fwd_call(p, q, interpret, "e", params, yd, zb)
    return _unfold(e3, b)[:, :t], (y3, par3, zb3, e3)


def _css_errors_fwd(p, q, interpret, params, yd, zb):
    # symbolic_zeros: args are CustomVJPPrimal; .perturbed says whether the
    # caller differentiates w.r.t. each input (see _ewma_s_fwd).  The data
    # cotangent is an extra backward-kernel output computed only when yd is
    # perturbed; the marker is structural (None vs ()) so the bwd branch is
    # resolved at trace time.
    b, t = yd.value.shape
    e, res = _css_errors_primal(p, q, interpret, params.value, yd.value,
                                zb.value)
    marker = () if yd.perturbed else None
    return e, res + (b, t, marker)


@_scoped("pallas.css_last_errors")
def css_last_errors(p: int, q: int, interpret: bool, params, yd, zb):
    """The last ``q`` one-step CSS errors ``[B, q]`` (oldest first).

    The forecast carry rebuild (``models.arima.forecast``) needs only the
    trailing ``q`` errors; this runs the same recursion as
    :func:`css_errors` but keeps the error panel in VMEM scratch, so the
    pass reads ``y`` once and writes O(B * q) — not a ``[B, T]`` panel.
    Not differentiable (forecasting is a post-fit read-only path; use the
    scan backend for gradients through forecasts).
    """
    if not css_structural_ok(p, q):
        raise ValueError(
            f"fused CSS kernel supports p, q <= {_CHUNK_T // 2} (got p={p}, q={q}); "
            "use backend='scan'"
        )
    if q == 0:
        return jnp.zeros((yd.shape[0], 0), yd.dtype)
    if yd.shape[1] < q:
        raise ValueError(f"series length {yd.shape[1]} < q={q}")
    b, t = yd.shape
    (tail3,), _ = _css_fwd_call(p, q, interpret, "tail", params, yd, zb)
    return _unfold(tail3, b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _css_ss_f(p: int, q: int, interpret: bool, t: int, b: int,
              params, y3, zb3):
    """Per-series CSS sum of squared errors ``[B]`` from the FOLDED layout
    (differentiable in ``params`` and ``y3`` — the data cotangent is computed
    only when the data is perturbed; ``t``/``b`` are the true unpadded
    lengths).

    Primal path uses the sum-only kernel (errors never leave VMEM — a
    linesearch objective evaluation pays one panel READ, not a read plus a
    full error write and re-read); the vjp path saves the errors and reuses
    the hand-derived adjoint, with the VALUE accumulated in the identical
    in-kernel order (mixed accumulation orders stall noise-floor rows).
    The unfolded API (:func:`css_neg_loglik`) is a thin fold-then-delegate
    wrapper, so there is exactly ONE adjoint implementation."""
    (css3,), _ = _css_fwd_call_f(p, q, interpret, "sum", params, y3, zb3, t)
    return _unfold(css3, b)[:, 0]


def _css_ss_f_fwd(p, q, interpret, t, b, params, y3, zb3):
    (e3, css3), (y3_, par3, zb3_) = _css_fwd_call_f(
        p, q, interpret, "both", params.value, y3.value, zb3.value, t
    )
    marker = () if y3.perturbed else None  # see _css_errors_fwd
    return _unfold(css3, b)[:, 0], (y3_, par3, zb3_, e3, marker)


def _css_ss_f_bwd(p, q, interpret, t, b, resid, gbar):
    y3, par3, zb3, e3, marker = resid
    k = 1 + p + q
    if isinstance(gbar, SymbolicZero):  # output provably unused
        return (jnp.zeros((b, k), e3.dtype), jnp.zeros(y3.shape, y3.dtype),
                jnp.zeros(zb3.shape, zb3.dtype))
    # the error cotangent stays IN the folded layout: gbar [B] folds to a
    # [1, Bp/128, 128] plane that broadcasts over the time axis, so the
    # gradient evaluation pays no unfold/refold panel passes (this runs
    # once per optimizer iteration on the fit hot path)
    gb3 = _fold(gbar[:, None].astype(e3.dtype))
    g_e3 = 2.0 * e3 * gb3
    if marker is not None:
        # data perturbed: the backward kernel additionally emits the folded
        # data cotangent (an output the params-only fit path never pays for)
        gparams, gy3 = _css_errors_bwd_f(p, q, interpret, (y3, par3, zb3, e3),
                                         g_e3, b, t, want_gy=True)
    else:
        gparams = _css_errors_bwd_f(p, q, interpret, (y3, par3, zb3, e3),
                                    g_e3, b, t)
        gy3 = jnp.zeros(y3.shape, y3.dtype)
    return gparams, gy3, jnp.zeros(zb3.shape, zb3.dtype)


_css_ss_f.defvjp(_css_ss_f_fwd, _css_ss_f_bwd, symbolic_zeros=True)


def css_prefold(yd, order: Order, n_valid=None):
    """Fold a differenced panel into the CSS kernel layout ONCE ->
    ``(y3, zb3)`` for :func:`css_neg_loglik_folded`.

    The fit objective runs hundreds of evaluations inside one
    ``lax.while_loop``; folding outside the loop keeps the [B, T]
    zero-mask + layout transpose off every evaluation (XLA does not
    reliably hoist them out of the loop body).
    """
    p, _, q = order
    b, n = yd.shape
    nv = jnp.full((b,), n, yd.dtype) if n_valid is None else n_valid.astype(yd.dtype)
    start = n - nv
    t_idx = jnp.arange(n, dtype=yd.dtype)
    ydz = jnp.where(t_idx[None, :] >= start[:, None], yd, 0.0)
    tp, _, _ = _time_layout(n)
    y3 = _fold(jnp.pad(ydz, ((0, 0), (0, tp - n))))
    zb3 = _fold((start + p).astype(yd.dtype)[:, None])
    return y3, zb3


@_scoped("pallas.css_neg_loglik")
def css_neg_loglik_folded(params, y3, zb3, n: int, order: Order,
                          include_intercept: bool, n_valid=None, *,
                          interpret: bool = False):
    """Batched CSS negative log-likelihood from a pre-folded panel
    (:func:`css_prefold`).  Matches :func:`css_neg_loglik` exactly."""
    p, _, q = order
    b = params.shape[0]
    nv = (jnp.full((b,), n, params.dtype) if n_valid is None
          else n_valid.astype(params.dtype))
    if include_intercept:
        params_k = params
    else:  # kernel layout always carries an intercept slot
        params_k = jnp.concatenate(
            [jnp.zeros((b, 1), params.dtype), params], axis=1
        )
    css = _css_ss_f(p, q, interpret, n, b, params_k, y3, zb3)
    n_eff = nv - p
    sigma2 = css / n_eff
    return 0.5 * n_eff * (jnp.log(2.0 * jnp.pi * sigma2) + 1.0)


def _css_errors_bwd(p, q, interpret, res, g):
    y3, par3, zb3, e3, b, t, marker = res
    k = 1 + p + q
    if isinstance(g, SymbolicZero):  # output provably unused: all-zero grads
        return (jnp.zeros((b, k), e3.dtype), jnp.zeros((b, t), e3.dtype),
                jnp.zeros((b,), e3.dtype))
    tp = y3.shape[0]
    g3 = _fold(jnp.pad(g, ((0, 0), (0, tp - t))))
    core_res = (y3, par3, zb3, e3)
    if marker is not None:
        gparams, gy3 = _css_errors_bwd_f(p, q, interpret, core_res, g3, b, t,
                                         want_gy=True)
        gy = _unfold(gy3, b)[:, :t]
    else:
        gparams = _css_errors_bwd_f(p, q, interpret, core_res, g3, b, t)
        gy = jnp.zeros((b, t), g.dtype)
    # the mask boundary zb is discrete: its cotangent stays zero
    return gparams, gy, jnp.zeros((b,), g.dtype)


def _css_errors_bwd_f(p, q, interpret, res, g3, b, t, want_gy=False):
    """Adjoint core on FOLDED cotangents -> ``gparams [B, k]`` or, with
    ``want_gy``, ``(gparams, gy3)`` where ``gy3`` is the data cotangent in
    the folded layout (an extra kernel output only callers that perturb the
    data pay for — see ``_css_ss_f_fwd``)."""
    y3, par3, zb3, e3 = res
    k = 1 + p + q
    _, cs, nchunk = _time_layout(t)
    nblk = y3.shape[1] // _SUBL
    hp = nchunk > 1
    if hp:
        ins = [_bs(cs, _rev(nchunk)), _bs(cs, _rev_prev(nchunk)),
               _bs(cs, _rev(nchunk)), _bs(cs, _rev_prev(nchunk)),
               _bs(k, _fixed), _bs(1, _fixed), _bs(cs, _rev(nchunk))]
        args = (y3, y3, e3, e3, par3, zb3, g3)
    else:
        ins = [_bs(cs, _rev(nchunk)), _bs(cs, _rev(nchunk)),
               _bs(k, _fixed), _bs(1, _fixed), _bs(cs, _rev(nchunk))]
        args = (y3, e3, par3, zb3, g3)
    out_specs = [_bs(k, _fixed)]
    out_shape = [jax.ShapeDtypeStruct(par3.shape, g3.dtype)]
    if want_gy:
        out_specs.append(_bs(cs, _rev(nchunk)))
        out_shape.append(jax.ShapeDtypeStruct(y3.shape, g3.dtype))
    scratch = [
        pltpu.VMEM((cs, _SUBL, _LANES), jnp.float32),
        pltpu.VMEM((max(q, 1), _SUBL, _LANES), jnp.float32),
    ]
    if want_gy:
        scratch.append(pltpu.VMEM((max(p, 1), _SUBL, _LANES), jnp.float32))
    outs = pl.pallas_call(
        functools.partial(_css_bwd_kernel, p, q, t, cs, nchunk, hp, want_gy),
        grid=(nblk, nchunk),
        in_specs=ins,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(*args)
    gparams = _unfold(outs[0], b)
    if want_gy:
        return gparams, outs[1]
    return gparams


css_errors.defvjp(_css_errors_fwd, _css_errors_bwd, symbolic_zeros=True)


@_scoped("pallas.css_neg_loglik")
def css_neg_loglik(params, yd, order: Order, include_intercept: bool,
                   n_valid=None, *, interpret: bool = False):
    """Batched CSS negative log-likelihood ``[B]`` on the fused kernel.

    Matches ``models.arima.css_neg_loglik`` (vmapped) to float tolerance;
    differentiable in ``params`` via the hand-derived adjoint.
    """
    y3, zb3 = css_prefold(yd, order, n_valid)
    return css_neg_loglik_folded(params, y3, zb3, yd.shape[1], order,
                                 include_intercept, n_valid,
                                 interpret=interpret)


# ---------------------------------------------------------------------------
# GARCH(1, 1) conditional-variance recursion (forward + hand-derived adjoint)
# ---------------------------------------------------------------------------
#
# h_t = omega + alpha * r_{t-1}^2 + beta * h_{t-1}, h_start = h0
# (reference GARCH.scala log-likelihood loop).  The prefix [0, zb) holds
# h_t = h0 so padded series contribute nothing.
#
# Adjoint, for an upstream cotangent gbar of h (t descending over live steps):
#   lam_t      = gbar_t + beta * lam_{t+1}
#   dL/domega  = sum_t lam_t
#   dL/dalpha  = sum_t lam_t * r2p_t          (r2p_zb = h0 at the seed)
#   dL/dbeta   = sum_t lam_t * h_{t-1}        (h_{zb-1} = h0 at the seed)
#   dL/dr2_t   = alpha * lam_{t+1}            (t+1 live and not the seed)
#   dL/dh0     = lam_zb * (alpha + beta) + sum_{dead t} gbar_t
# Cotangents flow to r^2 and h0 as well as the parameters so callers that
# build the returns from model parameters (ARGARCH's AR(1) mean) get exact
# gradients; ``zb`` is a constant of the objective.


def _garch_fwd_kernel(t_limit, cs, hp, mode, *refs):
    # mode "e": conditional variances out; "sum": only the per-series
    # Gaussian log-likelihood sum leaves the kernel (linesearch evals);
    # "both": variances AND the sum, accumulated in the identical order
    refs = list(refs)
    r2_ref = refs.pop(0)
    r2p_ref = refs.pop(0) if hp else None
    par_ref = refs.pop(0)
    h0_ref = refs.pop(0)
    zb_ref = refs.pop(0)
    h_ref = refs.pop(0) if mode != "sum" else None
    ll_ref = refs.pop(0) if mode != "e" else None
    ch_ref = refs.pop(0)
    c = pl.program_id(1)
    base = c * cs
    zb = zb_ref[0]
    h0 = h0_ref[0]

    @pl.when(c == 0)
    def _():
        ch_ref[0] = h0
        if mode != "e":
            ll_ref[0] = _ZERO()

    def body(tl, carry):
        hprev_c, acc = carry
        t = base + tl
        tf = t.astype(jnp.float32)
        hprev = jnp.where(tl - 1 >= 0, hprev_c, ch_ref[0])
        far = r2p_ref[cs - 1] if hp else 0.0
        r2p = jnp.where(tl - 1 >= 0, r2_ref[jnp.maximum(tl - 1, 0)], far)
        r2p = jnp.where(t - 1 >= 0, r2p, 0.0)
        # the first live step seeds with h0 standing in for r_{start-1}^2
        # (matching models.garch.variances)
        r2p = jnp.where(tf == zb, h0, r2p)
        h = par_ref[0] + par_ref[1] * r2p + par_ref[2] * hprev
        live = (tf >= zb) & (t < t_limit)
        hval = jnp.where(live, h, h0)
        if mode != "sum":
            h_ref[tl] = hval
        if mode != "e":
            hc = jnp.maximum(hval, 1e-12)
            acc = acc + jnp.where(
                live, jnp.log(2.0 * jnp.pi * hc) + r2_ref[tl] / hc, 0.0
            )
        return hval, acc

    hlast, acc = _fori(cs, body, (ch_ref[0], _ZERO()))
    ch_ref[0] = hlast
    if mode != "e":
        ll_ref[0] = ll_ref[0] + acc


def _garch_bwd_kernel(t_limit, cs, nchunk, hpv, *refs):
    if hpv:
        (r2_ref, r2p_ref, par_ref, h0_ref, zb_ref, h_ref, hp_ref,
         g_ref, gpar_ref, gr2_ref, gh0_ref, cl_ref) = refs
    else:
        (r2_ref, par_ref, h0_ref, zb_ref, h_ref,
         g_ref, gpar_ref, gr2_ref, gh0_ref, cl_ref) = refs
        r2p_ref = hp_ref = None
    c = pl.program_id(1)
    base = (nchunk - 1 - c) * cs
    zb = zb_ref[0]
    h0 = h0_ref[0]
    alpha = par_ref[1]
    beta = par_ref[2]

    @pl.when(c == 0)
    def _():
        cl_ref[0] = _ZERO()
        for r in range(3):
            gpar_ref[r] = _ZERO()
        gh0_ref[0] = _ZERO()

    def body(i, carry):
        lam_next, dw, da, db, dh0 = carry
        tl = cs - 1 - i
        t = base + tl
        tf = t.astype(jnp.float32)
        live = (tf >= zb) & (t < t_limit)
        # r2_t feeds h_{t+1} unless t+1 is the seed (which uses h0 instead)
        next_live = (tf + 1.0 > zb) & (t + 1 < t_limit)
        gr2_ref[tl] = jnp.where(next_live, alpha * lam_next, 0.0)
        lam = g_ref[tl] + beta * lam_next
        lam = jnp.where(live, lam, 0.0)
        # dead positions emit h0 directly
        dh0 = dh0 + jnp.where(live, 0.0, g_ref[tl])
        seed = tf == zb
        hfar = hp_ref[cs - 1] if hpv else 0.0
        hprev = jnp.where(tl - 1 >= 0, h_ref[jnp.maximum(tl - 1, 0)], hfar)
        hprev = jnp.where(t - 1 >= 0, hprev, h0)
        rfar = r2p_ref[cs - 1] if hpv else 0.0
        r2p = jnp.where(tl - 1 >= 0, r2_ref[jnp.maximum(tl - 1, 0)], rfar)
        r2p = jnp.where(t - 1 >= 0, r2p, 0.0)
        r2p_eff = jnp.where(seed, h0, r2p)
        dw = dw + lam
        da = da + lam * r2p_eff
        db = db + lam * hprev
        # h0 enters the seed step through BOTH recursion inputs
        hp_is_h0 = tf - 1.0 < zb
        dh0 = dh0 + jnp.where(live & seed, alpha * lam, 0.0)
        dh0 = dh0 + jnp.where(live & hp_is_h0, beta * lam, 0.0)
        return lam, dw, da, db, dh0

    lam, dw, da, db, dh0 = lax.fori_loop(
        0, cs, body, (cl_ref[0], _ZERO(), _ZERO(), _ZERO(), _ZERO())
    )
    cl_ref[0] = lam
    gpar_ref[0] = gpar_ref[0] + dw
    gpar_ref[1] = gpar_ref[1] + da
    gpar_ref[2] = gpar_ref[2] + db
    gh0_ref[0] = gh0_ref[0] + dh0


def _garch_fwd_call(interpret, mode, params, r2, h0, zb):
    b, t = r2.shape
    tp, cs, nchunk = _time_layout(t)
    r23 = _fold(jnp.pad(r2, ((0, 0), (0, tp - t))))
    par3 = _fold(params)
    h03 = _fold(h0[:, None].astype(r2.dtype))
    zb3 = _fold(zb.astype(r2.dtype)[:, None])
    nblk = r23.shape[1] // _SUBL
    hp = nchunk > 1
    out_specs, out_shape = [], []
    if mode != "sum":
        out_specs.append(_bs(cs, _cur))
        out_shape.append(jax.ShapeDtypeStruct(r23.shape, r2.dtype))
    if mode != "e":
        out_specs.append(_bs(1, _fixed))
        out_shape.append(
            jax.ShapeDtypeStruct((1, r23.shape[1], _LANES), r2.dtype)
        )
    outs = pl.pallas_call(
        functools.partial(_garch_fwd_kernel, t, cs, hp, mode),
        grid=(nblk, nchunk),
        in_specs=([_bs(cs, _cur)] + ([_bs(cs, _prev)] if hp else [])
                  + [_bs(3, _fixed), _bs(1, _fixed), _bs(1, _fixed)]),
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((1, _SUBL, _LANES), jnp.float32)],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(*((r23, r23) if hp else (r23,)), par3, h03, zb3)
    return outs, (r23, par3, h03, zb3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _garch_h(interpret: bool, params, r2, h0, zb):
    h, _ = _garch_h_fwd(interpret, params, r2, h0, zb)
    return h


def _garch_h_fwd(interpret, params, r2, h0, zb):
    b, t = r2.shape
    (h3,), (r23, par3, h03, zb3) = _garch_fwd_call(
        interpret, "e", params, r2, h0, zb
    )
    return _unfold(h3, b)[:, :t], (r23, par3, h03, zb3, h3, b, t)


def _garch_h_bwd(interpret, res, g):
    r23, par3, h03, zb3, h3, b, t = res
    tp = r23.shape[0]
    _, cs, nchunk = _time_layout(t)
    g3 = _fold(jnp.pad(g, ((0, 0), (0, tp - t))))
    nblk = r23.shape[1] // _SUBL
    hp = nchunk > 1
    if hp:
        ins = [_bs(cs, _rev(nchunk)), _bs(cs, _rev_prev(nchunk)),
               _bs(3, _fixed), _bs(1, _fixed), _bs(1, _fixed),
               _bs(cs, _rev(nchunk)), _bs(cs, _rev_prev(nchunk)),
               _bs(cs, _rev(nchunk))]
        args = (r23, r23, par3, h03, zb3, h3, h3, g3)
    else:
        ins = [_bs(cs, _rev(nchunk)), _bs(3, _fixed), _bs(1, _fixed),
               _bs(1, _fixed), _bs(cs, _rev(nchunk)), _bs(cs, _rev(nchunk))]
        args = (r23, par3, h03, zb3, h3, g3)
    gpar3, gr23, gh03 = pl.pallas_call(
        functools.partial(_garch_bwd_kernel, t, cs, nchunk, hp),
        grid=(nblk, nchunk),
        in_specs=ins,
        out_specs=[_bs(3, _fixed), _bs(cs, _rev(nchunk)), _bs(1, _fixed)],
        out_shape=[
            jax.ShapeDtypeStruct(par3.shape, g.dtype),
            jax.ShapeDtypeStruct(r23.shape, g.dtype),
            jax.ShapeDtypeStruct(h03.shape, g.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((1, _SUBL, _LANES), jnp.float32)],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(*args)
    return (
        _unfold(gpar3, b),
        _unfold(gr23, b)[:, :t],
        _unfold(gh03, b)[:, 0],
        jnp.zeros((b,), g.dtype),
    )


_garch_h.defvjp(_garch_h_fwd, _garch_h_bwd)


def garch_variances(params, r, h0, zb, *, interpret: bool = False):
    """Batched GARCH(1,1) conditional variances ``[B, T]`` on a fused kernel.

    ``params``: ``[B, 3]`` rows ``[omega, alpha, beta]``; ``r``: ``[B, T]``
    returns with the invalid prefix zeroed; ``h0``: ``[B]`` start variance;
    ``zb``: ``[B]`` first live position.  Differentiable in ``params``, ``r``,
    and ``h0`` via the hand-derived adjoint (``zb`` is constant).
    """
    return _garch_h(interpret, params, r * r, h0, zb)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _garch_ll(interpret: bool, params, rz, h0, zb):
    """Unscaled Gaussian log-likelihood sum ``[B]`` of the GARCH recursion:
    ``sum_t mask (log 2 pi h_t + r_t^2 / h_t)``.

    Primal path: sum-only kernel (the variance path never reaches HBM);
    vjp path saves the variances and chains the likelihood partials into
    the hand-derived recursion adjoint, with the VALUE accumulated in the
    identical in-kernel order (see ``_css_ss_f``).
    """
    b, t = rz.shape
    (ll3,), _ = _garch_fwd_call(interpret, "sum", params, rz * rz, h0, zb)
    return _unfold(ll3, b)[:, 0]


def _garch_ll_fwd(interpret, params, rz, h0, zb):
    b, t = rz.shape
    (h3, ll3), (r23, par3, h03, zb3) = _garch_fwd_call(
        interpret, "both", params, rz * rz, h0, zb
    )
    return _unfold(ll3, b)[:, 0], (r23, par3, h03, zb3, h3, rz, zb, b, t)


def _garch_ll_bwd(interpret, resid, gbar):
    r23, par3, h03, zb3, h3, rz, zb, b, t = resid
    h = _unfold(h3, b)[:, :t]
    t_idx = jnp.arange(t, dtype=rz.dtype)
    mask = t_idx[None, :] >= zb[:, None]
    hc = jnp.maximum(h, 1e-12)
    # d ll_t / d h_t = 1/h - r^2/h^2 (zero through the eps clamp)
    g_h = jnp.where(mask & (h >= 1e-12),
                    gbar[:, None] * (1.0 / hc - (rz * rz) / (hc * hc)), 0.0)
    gpar, g_r2, g_h0, _ = _garch_h_bwd(
        interpret, (r23, par3, h03, zb3, h3, b, t), g_h
    )
    # r feeds the likelihood through the recursion (r^2 chain) AND directly
    g_rz = g_r2 * 2.0 * rz + jnp.where(
        mask, gbar[:, None] * 2.0 * rz / hc, 0.0
    )
    return gpar, g_rz, g_h0, jnp.zeros_like(zb)


_garch_ll.defvjp(_garch_ll_fwd, _garch_ll_bwd)


@_scoped("pallas.garch_neg_loglik")
def garch_neg_loglik(params, r, n_valid=None, *, interpret: bool = False):
    """Batched GARCH(1,1) Gaussian negative log-likelihood ``[B]``.

    Matches ``models.garch.neg_log_likelihood`` (vmapped) to float tolerance:
    h0 is the masked sample variance of the valid span, the prefix is dead,
    and the likelihood sums over valid steps.  Differentiable in ``params``
    and (through the returns/variance seed) in ``r``.
    """
    b, n = r.shape
    nv = (
        jnp.full((b,), n, jnp.int32)
        if n_valid is None
        else n_valid.astype(jnp.int32)
    )
    start = (n - nv).astype(r.dtype)
    t_idx = jnp.arange(n, dtype=r.dtype)
    mask = t_idx[None, :] >= start[:, None]
    rz = jnp.where(mask, r, 0.0)
    nvf = jnp.maximum(nv, 1).astype(r.dtype)
    mean = jnp.sum(rz, axis=1) / nvf
    h0 = jnp.sum(jnp.where(mask, (rz - mean[:, None]) ** 2, 0.0), axis=1) / nvf
    return 0.5 * _garch_ll(interpret, params, rz, h0, start)


# ---------------------------------------------------------------------------
# EWMA smoothing recursion (forward + hand-derived adjoint)
# ---------------------------------------------------------------------------
#
# s_t = alpha * x_t + (1 - alpha) * s_{t-1}, seeded s_zb = x_zb, prefix 0
# (reference EWMA.scala; matches models.ewma.smooth with a right-aligned
# span).  Adjoint for an upstream cotangent gbar of s:
#   lam_t     = gbar_t + (1 - alpha) * lam_{t+1}   (no flow into the seed's
#                                                   predecessor)
#   dL/dalpha = sum_{t > zb} lam_t * (x_t - s_{t-1})
#   dL/dx_t   = alpha * lam_t  (t > zb);  lam_zb at the seed (s_zb = x_zb)
# The data cotangent costs an extra [B, T] write, so it is emitted only
# when the caller actually differentiates w.r.t. x (symbolic_zeros on the
# custom_vjp) — the fit hot path (alpha-only) never pays it (ADVICE r3).


def _ewma_fwd_kernel(t_limit, cs, mode, *refs):
    # mode "e": smoothed series out; "sum": only the one-step-ahead SSE
    # leaves the kernel (linesearch evals); "both": series AND the SSE,
    # accumulated in the identical order
    refs = list(refs)
    x_ref = refs.pop(0)
    a_ref = refs.pop(0)
    zb_ref = refs.pop(0)
    s_ref = refs.pop(0) if mode != "sum" else None
    ss_ref = refs.pop(0) if mode != "e" else None
    cs_ref = refs.pop(0)
    c = pl.program_id(1)
    base = c * cs
    zb = zb_ref[0]
    a = a_ref[0]

    @pl.when(c == 0)
    def _():
        cs_ref[0] = _ZERO()
        if mode != "e":
            ss_ref[0] = _ZERO()

    def body(tl, carry):
        sprev_c, acc = carry
        t = base + tl
        tf = t.astype(jnp.float32)
        xt = x_ref[tl]
        sp = jnp.where(tl - 1 >= 0, sprev_c, cs_ref[0])
        s = a * xt + (1.0 - a) * sp
        s = jnp.where(tf == zb, xt, s)
        live = (tf >= zb) & (t < t_limit)
        sval = jnp.where(live, s, 0.0)
        if mode != "sum":
            s_ref[tl] = sval
        if mode != "e":
            # one-step-ahead error x_t - s_{t-1}, live strictly after seed
            e = jnp.where((tf > zb) & (t < t_limit), xt - sp, 0.0)
            acc = acc + e * e
        return sval, acc

    sval, acc = _fori(cs, body, (cs_ref[0], _ZERO()))
    cs_ref[0] = sval
    if mode != "e":
        ss_ref[0] = ss_ref[0] + acc


def _ewma_bwd_kernel(t_limit, cs, nchunk, hp, want_gx, *refs):
    refs = list(refs)
    x_ref = refs.pop(0)
    a_ref = refs.pop(0)
    zb_ref = refs.pop(0)
    s_ref = refs.pop(0)
    sp_ref = refs.pop(0) if hp else None
    g_ref = refs.pop(0)
    ga_ref = refs.pop(0)
    gx_ref = refs.pop(0) if want_gx else None
    cl_ref = refs.pop(0)
    c = pl.program_id(1)
    base = (nchunk - 1 - c) * cs
    zb = zb_ref[0]
    a = a_ref[0]

    @pl.when(c == 0)
    def _():
        cl_ref[0] = _ZERO()
        ga_ref[0] = _ZERO()

    def body(i, carry):
        lam_next, da = carry
        tl = cs - 1 - i
        t = base + tl
        tf = t.astype(jnp.float32)
        live = (tf >= zb) & (t < t_limit)
        lam = g_ref[tl] + (1.0 - a) * lam_next
        lam = jnp.where(live, lam, 0.0)
        far = sp_ref[cs - 1] if hp else 0.0
        sp = jnp.where(tl - 1 >= 0, s_ref[jnp.maximum(tl - 1, 0)], far)
        sp = jnp.where(t - 1 >= 0, sp, 0.0)
        da = da + jnp.where(live & (tf > zb), lam * (x_ref[tl] - sp), 0.0)
        if gx_ref is not None:
            # d s_t / d x_t = alpha past the seed, 1 at it (s_zb = x_zb)
            gx_ref[tl] = jnp.where(live, jnp.where(tf > zb, a * lam, lam), 0.0)
        # the seed step s_zb = x_zb does not read s_{zb-1}
        lam_out = jnp.where(tf > zb, lam, 0.0)
        return lam_out, da

    lam, da = _fori(cs, body, (cl_ref[0], _ZERO()))
    cl_ref[0] = lam
    ga_ref[0] = ga_ref[0] + da


def _ewma_fwd_call(interpret, mode, alpha, x, zb):
    b, t = x.shape
    tp, cs, nchunk = _time_layout(t)
    x3 = _fold(jnp.pad(x, ((0, 0), (0, tp - t))))
    a3 = _fold(alpha[:, None].astype(x.dtype))
    zb3 = _fold(zb.astype(x.dtype)[:, None])
    nblk = x3.shape[1] // _SUBL
    out_specs, out_shape = [], []
    if mode != "sum":
        out_specs.append(_bs(cs, _cur))
        out_shape.append(jax.ShapeDtypeStruct(x3.shape, x.dtype))
    if mode != "e":
        out_specs.append(_bs(1, _fixed))
        out_shape.append(jax.ShapeDtypeStruct((1, x3.shape[1], _LANES), x.dtype))
    outs = pl.pallas_call(
        functools.partial(_ewma_fwd_kernel, t, cs, mode),
        grid=(nblk, nchunk),
        in_specs=[_bs(cs, _cur), _bs(1, _fixed), _bs(1, _fixed)],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((1, _SUBL, _LANES), jnp.float32)],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(x3, a3, zb3)
    return outs, (x3, a3, zb3)


def _ewma_bwd_call(interpret, res, g, want_gx):
    """Shared EWMA adjoint dispatch -> ``(g_alpha [B], g_x [B, T] | None)``."""
    x3, a3, zb3, s3, b, t = res
    tp = x3.shape[0]
    _, cs, nchunk = _time_layout(t)
    g3 = _fold(jnp.pad(g, ((0, 0), (0, tp - t))))
    nblk = x3.shape[1] // _SUBL
    hp = nchunk > 1
    if hp:
        ins = [_bs(cs, _rev(nchunk)), _bs(1, _fixed), _bs(1, _fixed),
               _bs(cs, _rev(nchunk)), _bs(cs, _rev_prev(nchunk)),
               _bs(cs, _rev(nchunk))]
        args = (x3, a3, zb3, s3, s3, g3)
    else:
        ins = [_bs(cs, _rev(nchunk)), _bs(1, _fixed), _bs(1, _fixed),
               _bs(cs, _rev(nchunk)), _bs(cs, _rev(nchunk))]
        args = (x3, a3, zb3, s3, g3)
    out_specs = [_bs(1, _fixed)]
    out_shape = [jax.ShapeDtypeStruct(a3.shape, g.dtype)]
    if want_gx:
        out_specs.append(_bs(cs, _rev(nchunk)))
        out_shape.append(jax.ShapeDtypeStruct(x3.shape, g.dtype))
    outs = pl.pallas_call(
        functools.partial(_ewma_bwd_kernel, t, cs, nchunk, hp, want_gx),
        grid=(nblk, nchunk),
        in_specs=ins,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((1, _SUBL, _LANES), jnp.float32)],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(*args)
    ga = _unfold(outs[0], b)[:, 0]
    gx = _unfold(outs[1], b)[:, :t] if want_gx else None
    return ga, gx


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ewma_s(interpret: bool, alpha, x, zb):
    b, t = x.shape
    (s3,), _ = _ewma_fwd_call(interpret, "e", alpha, x, zb)
    return _unfold(s3, b)[:, :t]


def _ewma_s_fwd(interpret, alpha, x, zb):
    # symbolic_zeros: args are CustomVJPPrimal; .perturbed says whether the
    # caller differentiates w.r.t. each input.  The x cotangent is computed
    # only when x is perturbed (an extra [B, T] kernel output otherwise
    # wasted on the alpha-only fit path).  The marker is structural
    # (None vs ()) so the bwd branch is resolved at trace time.
    alpha_p, x_p, zb_p = alpha.value, x.value, zb.value
    b, t = x_p.shape
    (s3,), (x3, a3, zb3) = _ewma_fwd_call(interpret, "e", alpha_p, x_p, zb_p)
    marker = () if x.perturbed else None
    return _unfold(s3, b)[:, :t], (x3, a3, zb3, s3, b, t, marker)


def _ewma_s_bwd(interpret, res, g):
    x3, a3, zb3, s3, b, t, marker = res
    if isinstance(g, SymbolicZero):  # output provably unused: all-zero grads
        return (jnp.zeros((b,), g.dtype), jnp.zeros((b, t), g.dtype),
                jnp.zeros((b,), g.dtype))
    want_gx = marker is not None
    ga, gx = _ewma_bwd_call(interpret, (x3, a3, zb3, s3, b, t), g, want_gx)
    if gx is None:
        gx = jnp.zeros((b, t), g.dtype)
    return ga, gx, jnp.zeros((b,), g.dtype)


_ewma_s.defvjp(_ewma_s_fwd, _ewma_s_bwd, symbolic_zeros=True)


def ewma_smooth(alpha, x, zb, *, interpret: bool = False):
    """Batched EWMA smoothing ``[B, T]`` on a fused kernel.

    ``alpha``: ``[B]``; ``x``: ``[B, T]`` with the invalid prefix zeroed;
    ``zb``: ``[B]`` first live position.  Differentiable in ``alpha`` AND
    ``x`` (the data cotangent is computed only when x is perturbed).
    """
    return _ewma_s(interpret, alpha, x, zb)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ewma_ssq(interpret: bool, alpha, xz, zb):
    """One-step-ahead SSE ``[B]`` of the EWMA recursion.

    Primal path: sum-only kernel (the smoothed series never reaches HBM);
    vjp path saves it and chains the error partials into the hand-derived
    smoothing adjoint, with the VALUE accumulated in the identical
    in-kernel order (see ``_css_ss_f``).
    """
    b, t = xz.shape
    (ss3,), _ = _ewma_fwd_call(interpret, "sum", alpha, xz, zb)
    return _unfold(ss3, b)[:, 0]


def _ewma_ssq_fwd(interpret, alpha, xz, zb):
    alpha_p, x_p, zb_p = alpha.value, xz.value, zb.value
    b, t = x_p.shape
    (s3, ss3), (x3, a3, zb3) = _ewma_fwd_call(interpret, "both", alpha_p,
                                              x_p, zb_p)
    marker = () if xz.perturbed else None  # see _ewma_s_fwd
    return _unfold(ss3, b)[:, 0], (x3, a3, zb3, s3, x_p, zb_p, b, t, marker)


def _ewma_ssq_bwd(interpret, resid, gbar):
    x3, a3, zb3, s3, xz, zb, b, t, marker = resid
    if isinstance(gbar, SymbolicZero):  # output provably unused
        return (jnp.zeros((b,), xz.dtype), jnp.zeros_like(xz),
                jnp.zeros_like(zb))
    want_gx = marker is not None
    s = _unfold(s3, b)[:, :t]
    t_idx = jnp.arange(t, dtype=xz.dtype)
    live_e = t_idx[None, 1:] > zb[:, None]  # err_t = x_t - s_{t-1}, t > seed
    err = jnp.where(live_e, xz[:, 1:] - s[:, :-1], 0.0)
    # d sse / d s_{t-1} = -2 err_t; the last position feeds no error
    g_s = jnp.concatenate(
        [-2.0 * err * gbar[:, None], jnp.zeros((b, 1), xz.dtype)], axis=1
    )
    g_alpha, gx_chain = _ewma_bwd_call(
        interpret, (x3, a3, zb3, s3, b, t), g_s, want_gx
    )
    if want_gx:
        # direct term: d err_t^2 / d x_t = 2 err_t (the smoothing-path term
        # -2 err_t * d s_{t-1}/dx came through the adjoint kernel above)
        gx = gx_chain + jnp.concatenate(
            [jnp.zeros((b, 1), xz.dtype), 2.0 * err * gbar[:, None]], axis=1
        )
    else:
        gx = jnp.zeros_like(xz)
    return g_alpha, gx, jnp.zeros_like(zb)


_ewma_ssq.defvjp(_ewma_ssq_fwd, _ewma_ssq_bwd, symbolic_zeros=True)


@_scoped("pallas.ewma_sse")
def ewma_sse(alpha, x, n_valid=None, *, interpret: bool = False):
    """Batched one-step-ahead EWMA SSE ``[B]`` (matches ``models.ewma.sse``).
    Differentiable in ``alpha`` AND ``x`` (the data cotangent is computed
    only when x is perturbed, so the alpha-only fit path pays nothing)."""
    b, n = x.shape
    nv = (
        jnp.full((b,), n, jnp.int32)
        if n_valid is None
        else n_valid.astype(jnp.int32)
    )
    start = (n - nv).astype(x.dtype)
    t_idx = jnp.arange(n, dtype=x.dtype)
    xz = jnp.where(t_idx[None, :] >= start[:, None], x, 0.0)
    return _ewma_ssq(interpret, alpha, xz, start)


# ---------------------------------------------------------------------------
# Holt-Winters smoothing, additive & multiplicative, ragged-aware
# (forward + hand-derived adjoint)
# ---------------------------------------------------------------------------
#
# Per series (reference HoltWinters.scala; matches models.holtwinters._run
# with a right-aligned valid span starting at zb).  Additive:
#   pred_t = L_{t-1} + T_{t-1} + S_t          with S_t = ring[t mod m]
#   L_t    = a (y_t - S_t) + (1-a)(L_{t-1} + T_{t-1})
#   T_t    = b (L_t - L_{t-1}) + (1-b) T_{t-1}
#   ring[t mod m] = g (y_t - L_t) + (1-g) S_t
# Multiplicative:
#   pred_t = (L_{t-1} + T_{t-1}) * S_t
#   L_t    = a y_t / S_t + (1-a)(L_{t-1} + T_{t-1})
#   ring[t mod m] = g y_t / L_t + (1-g) S_t        (denominators eps-clamped)
#   e_t    = [zb + m <= t < t_limit] * (y_t - pred_t)
# State is frozen outside [zb, t_limit): the recursion effectively starts at
# the first valid observation.  The ring is indexed by t mod m with PER-ROW
# zb, so the caller pre-rotates the seed ring (seed element j lands at slot
# (zb + j) mod m) — scratch indices must be scalar per block.
#
# The seasonal ring lives in a [m, 8, 128] VMEM scratch and persists across
# time chunks.  Seeds (L_0, T_0, ring init) are computed OUTSIDE the kernel
# from the first two valid seasons — they depend on the data only, so the
# adjoint propagates to the three smoothing parameters alone.  Reverse pass
# replays saved (L, T, S_old) trajectories with a ring of seasonal adjoints.
# Additive (gp = -[live-err] gbar_t):
#   vL        = uL + b uT - g uS
#   da       += (y_t - S_t - L_{t-1} - T_{t-1}) vL
#   db       += (L_t - L_{t-1} - T_{t-1}) uT
#   dg       += (y_t - L_t - S_t) uS
#   uL'       = -b uT + (1-a) vL + gp
#   uT'       = (1-b) uT + (1-a) vL + gp
#   rho[slot] = (1-g) uS - a vL + gp
# Multiplicative replaces the pred/level/seasonal partials with the product
# and quotient rules (S_t gp into the level/trend adjoints, (L+T) gp into
# the ring, -a y/S^2 and -g y/L^2 quotient terms, eps-clamp subgradients).
# Level/trend carries cross chunks through 1-slot scratches; both rings
# (seasonal state forward, seasonal adjoint backward) persist untouched.


def _hw_fwd_kernel(m, mult, save_resid, t_limit, cs, y_ref, par_ref, l0_ref,
                   t0_ref, s0_ref, zb_ref, *refs):
    if save_resid:  # vjp path: trajectories for the adjoint + the SSE,
        # accumulated in the same in-kernel order as the primal variant
        e_ref, lv_ref, tr_ref, so_ref, ss_ref, seas_ref, clt_ref = refs
    else:  # primal path (linesearch evals): ONLY the per-series SSE leaves
        ss_ref, seas_ref, clt_ref = refs  # the kernel — the error/trajectory
        e_ref = lv_ref = tr_ref = so_ref = None  # stores are the HBM bill
    c = pl.program_id(1)
    base = c * cs
    a = par_ref[0]
    b = par_ref[1]
    g = par_ref[2]
    zb = zb_ref[0]

    @pl.when(c == 0)
    def _():
        for j in range(m):
            seas_ref[j] = s0_ref[j]
        clt_ref[0] = l0_ref[0]
        clt_ref[1] = t0_ref[0]
        ss_ref[0] = _ZERO()

    def body(tl, carry):
        level, trend, acc = carry
        t = base + tl
        tf = t.astype(jnp.float32)
        slot = lax.rem(t, jnp.asarray(m, t.dtype))
        s = seas_ref[slot]
        yt = y_ref[tl]
        live = (tf >= zb) & (t < t_limit)
        live_err = (tf >= zb + m) & (t < t_limit)
        lt_sum = level + trend
        if mult:
            pred = lt_sum * s
            nl = a * yt / jnp.maximum(s, 1e-12) + (1.0 - a) * lt_sum
            snew = g * yt / jnp.maximum(nl, 1e-12) + (1.0 - g) * s
        else:
            pred = lt_sum + s
            nl = a * (yt - s) + (1.0 - a) * lt_sum
            snew = g * (yt - nl) + (1.0 - g) * s
        nt = b * (nl - level) + (1.0 - b) * trend
        e = jnp.where(live_err, yt - pred, 0.0)
        nl_o = jnp.where(live, nl, level)
        nt_o = jnp.where(live, nt, trend)
        seas_ref[slot] = jnp.where(live, snew, s)
        if save_resid:
            e_ref[tl] = e
            so_ref[tl] = s
            lv_ref[tl] = nl_o
            tr_ref[tl] = nt_o
        return nl_o, nt_o, acc + e * e

    level, trend, acc = _fori(cs, body, (clt_ref[0], clt_ref[1], _ZERO()))
    clt_ref[0] = level
    clt_ref[1] = trend
    ss_ref[0] = ss_ref[0] + acc


def _hw_bwd_kernel(m, mult, t_limit, cs, nchunk, hp, *refs):
    if hp:
        (y_ref, par_ref, l0_ref, t0_ref, zb_ref, lv_ref, lvp_ref, tr_ref,
         trp_ref, so_ref, g_ref, gpar_ref, rho_ref, clam_ref) = refs
    else:
        (y_ref, par_ref, l0_ref, t0_ref, zb_ref, lv_ref, tr_ref,
         so_ref, g_ref, gpar_ref, rho_ref, clam_ref) = refs
        lvp_ref = trp_ref = None
    c = pl.program_id(1)
    base = (nchunk - 1 - c) * cs
    a = par_ref[0]
    b = par_ref[1]
    g = par_ref[2]
    zb = zb_ref[0]

    @pl.when(c == 0)
    def _():
        for j in range(m):
            rho_ref[j] = _ZERO()
        clam_ref[0] = _ZERO()
        clam_ref[1] = _ZERO()
        for r in range(3):
            gpar_ref[r] = _ZERO()

    def body(i, carry):
        lamL, lamT, da, db, dg = carry
        tl = cs - 1 - i
        t = base + tl
        tf = t.astype(jnp.float32)
        slot = lax.rem(t, jnp.asarray(m, t.dtype))
        live = (tf >= zb) & (t < t_limit)
        live_err = (tf >= zb + m) & (t < t_limit)
        uS = rho_ref[slot]
        uL = lamL
        uT = lamT
        gp = jnp.where(live_err, -g_ref[tl], 0.0)
        lfar = lvp_ref[cs - 1] if hp else 0.0
        lp = jnp.where(tl - 1 >= 0, lv_ref[jnp.maximum(tl - 1, 0)], lfar)
        lp = jnp.where(t - 1 >= 0, lp, l0_ref[0])
        tfar = trp_ref[cs - 1] if hp else 0.0
        tp_ = jnp.where(tl - 1 >= 0, tr_ref[jnp.maximum(tl - 1, 0)], tfar)
        tp_ = jnp.where(t - 1 >= 0, tp_, t0_ref[0])
        so = so_ref[tl]
        lt = lv_ref[tl]
        yt = y_ref[tl]
        if mult:
            sc = jnp.maximum(so, 1e-12)
            ltc = jnp.maximum(lt, 1e-12)
            # eps-clamp subgradients: no flow through a clamped denominator
            s_pass = (so >= 1e-12).astype(jnp.float32)
            l_pass = (lt >= 1e-12).astype(jnp.float32)
            vL = uL + b * uT - g * (yt / (ltc * ltc)) * uS * l_pass
            da_t = (yt / sc - lp - tp_) * vL
            dg_t = (yt / ltc - so) * uS
            new_lamL = -b * uT + (1.0 - a) * vL + so * gp
            new_lamT = (1.0 - b) * uT + (1.0 - a) * vL + so * gp
            rho_new = (
                (1.0 - g) * uS
                - a * (yt / (sc * sc)) * vL * s_pass
                + (lp + tp_) * gp
            )
        else:
            vL = uL + b * uT - g * uS
            da_t = (yt - so - lp - tp_) * vL
            dg_t = (yt - lt - so) * uS
            new_lamL = -b * uT + (1.0 - a) * vL + gp
            new_lamT = (1.0 - b) * uT + (1.0 - a) * vL + gp
            rho_new = (1.0 - g) * uS - a * vL + gp
        db_t = (lt - lp - tp_) * uT
        da = da + jnp.where(live, da_t, 0.0)
        db = db + jnp.where(live, db_t, 0.0)
        dg = dg + jnp.where(live, dg_t, 0.0)
        lamL_o = jnp.where(live, new_lamL, uL)
        lamT_o = jnp.where(live, new_lamT, uT)
        rho_ref[slot] = jnp.where(live, rho_new, uS)
        return lamL_o, lamT_o, da, db, dg

    lamL, lamT, da, db, dg = lax.fori_loop(
        0, cs, body, (clam_ref[0], clam_ref[1], _ZERO(), _ZERO(), _ZERO())
    )
    clam_ref[0] = lamL
    clam_ref[1] = lamT
    gpar_ref[0] = gpar_ref[0] + da
    gpar_ref[1] = gpar_ref[1] + db
    gpar_ref[2] = gpar_ref[2] + dg


def _hw_fwd_call(interpret, m, mult, save_resid, params, y, l0, t0, s0, zb):
    b, t = y.shape
    tp, cs, nchunk = _time_layout(t)
    y3 = _fold(jnp.pad(y, ((0, 0), (0, tp - t))))
    par3 = _fold(params)
    l03 = _fold(l0[:, None].astype(y.dtype))
    t03 = _fold(t0[:, None].astype(y.dtype))
    s03 = _fold(s0)
    zb3 = _fold(zb.astype(y.dtype)[:, None])
    nblk = y3.shape[1] // _SUBL
    ss_spec = _bs(1, _fixed)
    ss_shape = jax.ShapeDtypeStruct((1, y3.shape[1], _LANES), y.dtype)
    if save_resid:  # e + replay trajectories for the adjoint + the SSE
        out_specs = [_bs(cs, _cur)] * 4 + [ss_spec]
        out_shape = [jax.ShapeDtypeStruct(y3.shape, y.dtype)] * 4 + [ss_shape]
    else:  # per-series SSE only
        out_specs = [ss_spec]
        out_shape = [ss_shape]
    outs = pl.pallas_call(
        functools.partial(_hw_fwd_kernel, m, mult, save_resid, t, cs),
        grid=(nblk, nchunk),
        in_specs=[_bs(cs, _cur), _bs(3, _fixed), _bs(1, _fixed),
                  _bs(1, _fixed), _bs(m, _fixed), _bs(1, _fixed)],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((m, _SUBL, _LANES), jnp.float32),
            pltpu.VMEM((2, _SUBL, _LANES), jnp.float32),
        ],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(y3, par3, l03, t03, s03, zb3)
    return outs, (y3, par3, l03, t03, zb3, b, t)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _hw_ss(interpret: bool, m: int, mult: bool, params, y, l0, t0, s0, zb):
    """Per-series one-step-ahead SSE ``[B]``.

    Primal (no-gradient) path: sum-only kernel — a linesearch objective
    evaluation pays one panel read and no error/trajectory stores.  The vjp
    path saves the replay trajectories and reuses the hand-derived adjoint.
    """
    (ss3,), (_, _, _, _, _, b, t) = _hw_fwd_call(
        interpret, m, mult, False, params, y, l0, t0, s0, zb
    )
    return _unfold(ss3, b)[:, 0]


def _hw_ss_fwd(interpret, m, mult, params, y, l0, t0, s0, zb):
    (e3, lv3, tr3, so3, ss3), (y3, par3, l03, t03, zb3, b, t) = _hw_fwd_call(
        interpret, m, mult, True, params, y, l0, t0, s0, zb
    )
    e = _unfold(e3, b)[:, :t]
    res = (y3, par3, l03, t03, zb3, lv3, tr3, so3, b, t)
    # the value is accumulated in the same in-kernel order as the primal
    # variant — see _css_ss_fwd: mixed accumulation orders stall rows
    return _unfold(ss3, b)[:, 0], (res, e)


def _hw_ss_bwd(interpret, m, mult, resid, gbar):
    res, e = resid
    g_e = 2.0 * e * gbar[:, None]
    return _hw_e_bwd(interpret, m, mult, res, g_e)


def _hw_e_bwd(interpret, m, mult, res, g):
    y3, par3, l03, t03, zb3, lv3, tr3, so3, b, t = res
    tp = y3.shape[0]
    _, cs, nchunk = _time_layout(t)
    g3 = _fold(jnp.pad(g, ((0, 0), (0, tp - t))))
    nblk = y3.shape[1] // _SUBL
    hp = nchunk > 1
    if hp:
        ins = [_bs(cs, _rev(nchunk)), _bs(3, _fixed), _bs(1, _fixed),
               _bs(1, _fixed), _bs(1, _fixed),
               _bs(cs, _rev(nchunk)), _bs(cs, _rev_prev(nchunk)),
               _bs(cs, _rev(nchunk)), _bs(cs, _rev_prev(nchunk)),
               _bs(cs, _rev(nchunk)), _bs(cs, _rev(nchunk))]
        args = (y3, par3, l03, t03, zb3, lv3, lv3, tr3, tr3, so3, g3)
    else:
        ins = [_bs(cs, _rev(nchunk)), _bs(3, _fixed), _bs(1, _fixed),
               _bs(1, _fixed), _bs(1, _fixed), _bs(cs, _rev(nchunk)),
               _bs(cs, _rev(nchunk)), _bs(cs, _rev(nchunk)),
               _bs(cs, _rev(nchunk))]
        args = (y3, par3, l03, t03, zb3, lv3, tr3, so3, g3)
    gpar3 = pl.pallas_call(
        functools.partial(_hw_bwd_kernel, m, mult, t, cs, nchunk, hp),
        grid=(nblk, nchunk),
        in_specs=ins,
        out_specs=_bs(3, _fixed),
        out_shape=jax.ShapeDtypeStruct(par3.shape, g.dtype),
        scratch_shapes=[
            pltpu.VMEM((m, _SUBL, _LANES), jnp.float32),
            pltpu.VMEM((2, _SUBL, _LANES), jnp.float32),
        ],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(*args)
    return (
        _unfold(gpar3, b),
        jnp.zeros((b, t), g.dtype),
        jnp.zeros((b,), g.dtype),
        jnp.zeros((b,), g.dtype),
        jnp.zeros((b, m), g.dtype),
        jnp.zeros((b,), g.dtype),
    )


_hw_ss.defvjp(_hw_ss_fwd, _hw_ss_bwd)


# ---------------------------------------------------------------------------
# Fused fill-linear feature chain (forward-only transform, no adjoint)
# ---------------------------------------------------------------------------
#
# The portable fills (ops.univariate.fill_linear) are built from FOUR
# log2(T)-step associative scans — ~40 full-panel HBM round trips for the
# fillLinear -> difference -> lag feature chain that the reference runs as
# one per-series pass (UnivariateTimeSeries.fillLinear, SURVEY.md §2.1).
# ONE kernel, two phases over the time-chunk grid (VERDICT r4 weak item 1:
# the old two-kernel version streamed its (next-valid value, index)
# intermediates through HBM — 2 full panel writes + 2 reads that never
# belonged to the interface):
#   phase 0 (chunks last->first) records only the per-chunk backward carry
#     in VMEM scratch — a vectorized first-valid reduction, no HBM writes;
#   phase 1 (chunks first->last) rebuilds the chunk-local next-valid arrays
#     in VMEM from the recorded carry (sequential backward minisweep), then
#     runs the forward fill sweep emitting ONLY the requested outputs.
# Total HBM traffic: 2 panel reads + one write per requested output (1 read
# when the series fits a single chunk — phase 0 is skipped entirely).


def _fillchain_fused_kernel(t_limit, cs, nchunk, which, *refs):
    n_out = sum(which)
    y_ref = refs[0]
    out_refs = list(refs[1 : 1 + n_out])
    carry_ref, nv_ref, ni_ref, fwd_ref = refs[1 + n_out :]
    single = nchunk == 1
    s = pl.program_id(1)
    nan = jnp.float32(jnp.nan)
    f_ref = out_refs.pop(0) if which[0] else None
    d_ref = out_refs.pop(0) if which[1] else None
    l_ref = out_refs.pop(0) if which[2] else None

    if not single:
        # live backward carry rides the last two scratch slots
        @pl.when(s == 0)
        def _():
            carry_ref[2 * nchunk] = _ZERO()
            carry_ref[2 * nchunk + 1] = jnp.full(
                (_SUBL, _LANES), 1e30, jnp.float32
            )

        @pl.when(s < nchunk)
        def _():  # phase 0, chunk c = nchunk-1-s: record + merge, no stores
            c = nchunk - 1 - s
            y = y_ref[:]
            tf = (c * cs + lax.broadcasted_iota(jnp.int32, (cs, 1, 1), 0)
                  ).astype(jnp.float32)
            valid = (y == y) & (tf < t_limit)
            # first valid element of the chunk, vectorized (tf is unique
            # along the time axis, so the masked sum selects exactly one)
            tmin = jnp.min(jnp.where(valid, tf, 1e30), axis=0)
            vsel = jnp.sum(jnp.where(valid & (tf == tmin[None]), y, 0.0), axis=0)
            carry_ref[2 * c] = carry_ref[2 * nchunk]
            carry_ref[2 * c + 1] = carry_ref[2 * nchunk + 1]
            has = tmin < 1e30
            carry_ref[2 * nchunk] = jnp.where(has, vsel, carry_ref[2 * nchunk])
            carry_ref[2 * nchunk + 1] = jnp.where(
                has, tmin, carry_ref[2 * nchunk + 1]
            )

    first_fwd = 0 if single else nchunk

    @pl.when(s >= first_fwd)
    def _():  # phase 1, chunk c = s - first_fwd
        c = s - first_fwd
        base = c * cs

        @pl.when(s == first_fwd)
        def _():
            fwd_ref[0] = _ZERO()  # prev-valid value
            fwd_ref[1] = jnp.full((_SUBL, _LANES), -1e30, jnp.float32)
            fwd_ref[2] = jnp.full((_SUBL, _LANES), nan, jnp.float32)  # fill[t-1]

        def bwd(i, carry):
            cnv, cni = carry
            tl = cs - 1 - i
            yt = y_ref[tl]
            tf = (base + tl).astype(jnp.float32)
            valid = (yt == yt) & (base + tl < t_limit)  # NaN != NaN
            cnv = jnp.where(valid, yt, cnv)
            cni = jnp.where(valid, tf, cni)
            nv_ref[tl] = cnv
            ni_ref[tl] = cni
            return cnv, cni

        if single:
            seed = (_ZERO(), jnp.full((_SUBL, _LANES), 1e30, jnp.float32))
        else:
            seed = (carry_ref[2 * c], carry_ref[2 * c + 1])
        _fori(cs, bwd, seed, unroll=8)

        def fwd(tl, carry):
            pv, pi, fprev = carry
            t = base + tl
            tf = t.astype(jnp.float32)
            yt = y_ref[tl]
            valid = (yt == yt) & (t < t_limit)
            interior = (pi >= 0.0) & (ni_ref[tl] < t_limit)
            span = jnp.maximum(ni_ref[tl] - pi, 1.0)
            w = (tf - pi) / span
            interp = pv * (1.0 - w) + nv_ref[tl] * w
            fill = jnp.where(valid, yt, jnp.where(interior, interp, nan))
            if f_ref is not None:
                f_ref[tl] = fill
            if d_ref is not None:
                d_ref[tl] = fill - fprev  # NaN fprev poisons t=0 as required
            if l_ref is not None:
                l_ref[tl] = fprev
            pv = jnp.where(valid, yt, pv)
            pi = jnp.where(valid, tf, pi)
            return pv, pi, fill

        pv, pi, fprev = _fori(cs, fwd, (fwd_ref[0], fwd_ref[1], fwd_ref[2]),
                              unroll=8)
        fwd_ref[0] = pv
        fwd_ref[1] = pi
        fwd_ref[2] = fprev


def _fill_linear_call_folded(y3, t: int, which, interpret: bool):
    """Core fused chain on a FOLDED panel -> folded outputs (no layout
    conversion: the resident-layout entry point)."""
    tp, cs, nchunk = _time_layout(t)
    if y3.shape[0] != tp:
        raise ValueError(
            f"folded panel has time dim {y3.shape[0]}, layout wants {tp}"
        )
    nblk = y3.shape[1] // _SUBL
    n_out = sum(which)
    single = nchunk == 1
    steps = nchunk if single else 2 * nchunk

    if single:
        ymap = _cur
        omap = _cur
    else:
        def ymap(blk, s):
            return (jnp.where(s < nchunk, nchunk - 1 - s, s - nchunk), blk, 0)

        def omap(blk, s):
            # park output windows on chunk 0 through phase 0 (no stores);
            # every window is fully written during its phase-1 visit
            return (jnp.where(s < nchunk, 0, s - nchunk), blk, 0)

    outs = pl.pallas_call(
        functools.partial(_fillchain_fused_kernel, t, cs, nchunk, which),
        grid=(nblk, steps),
        in_specs=[_bs(cs, ymap)],
        out_specs=[_bs(cs, omap)] * n_out,
        out_shape=[jax.ShapeDtypeStruct(y3.shape, jnp.float32)] * n_out,
        scratch_shapes=[
            pltpu.VMEM((2 * nchunk + 2, _SUBL, _LANES), jnp.float32),
            pltpu.VMEM((cs, _SUBL, _LANES), jnp.float32),
            pltpu.VMEM((cs, _SUBL, _LANES), jnp.float32),
            pltpu.VMEM((3, _SUBL, _LANES), jnp.float32),
        ],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(y3)
    return outs  # list even when singleton


_CHAIN_OUTPUTS = ("filled", "diff", "lag")


def fill_linear_chain_folded(fp, outputs=_CHAIN_OUTPUTS, *,
                             interpret: bool = False):
    """Fused fill chain on a resident :class:`~.layout.FoldedPanel`,
    emitting ONLY the requested outputs as folded panels (VERDICT r4: the
    old chain wrote all three whether or not the caller wanted them).

    ``outputs`` is an ordered subset of ``("filled", "diff", "lag")``; the
    result tuple matches its order.
    """
    from .layout import FoldedPanel

    bad = [o for o in outputs if o not in _CHAIN_OUTPUTS]
    if bad or not outputs:
        raise ValueError(f"outputs must be a non-empty subset of "
                         f"{_CHAIN_OUTPUTS}, got {outputs!r}")
    which = tuple(o in outputs for o in _CHAIN_OUTPUTS)
    outs = _fill_linear_call_folded(fp.data, fp.t, which, interpret)
    by_name = dict(zip([o for o, w in zip(_CHAIN_OUTPUTS, which) if w], outs))
    return tuple(FoldedPanel(by_name[o], fp.b, fp.t) for o in outputs)


def _fill_linear_call(y, chain: bool, interpret: bool):
    b, t = y.shape
    tp, _, _ = _time_layout(t)
    # pad with NaN so padded tail positions read as invalid
    y3 = _fold(jnp.pad(y, ((0, 0), (0, tp - t)), constant_values=jnp.nan))
    which = (True, chain, chain)
    outs = _fill_linear_call_folded(y3, t, which, interpret)
    return tuple(_unfold(o, b)[:, :t] for o in outs)


@_scoped("pallas.fill_linear_chain")
def fill_linear_chain(y, *, interpret: bool = False):
    """Fused fillLinear -> (filled, lag-1 difference, lag-1 shift) on ``[B, T]``.

    Matches ``vmap(fill_linear)``, ``vmap(differences_at_lag(., 1))`` and
    ``vmap(lag(., 1))`` composed (same NaN semantics: edge NaNs survive the
    fill; position 0 of the difference and the shift is NaN).
    """
    return _fill_linear_call(y, True, interpret)


@_scoped("pallas.fill_linear")
def fill_linear(y, *, interpret: bool = False):
    """Batched linear-interpolation fill ``[B, T]`` on the fused kernel
    (fill output only — no difference/lag stores)."""
    return _fill_linear_call(y, False, interpret)[0]


# ---------------------------------------------------------------------------
# Fused Hannan-Rissanen moment kernels (forward-only, no adjoint)
# ---------------------------------------------------------------------------
#
# The ARIMA fit's startup values come from two weighted OLS stages
# (models.arima.hannan_rissanen).  Their normal equations need only masked
# lagged inner products of the series (and of the stage-1 residuals) — a
# handful of [B] moments.  The XLA construction (hannan_rissanen_batched)
# assembles them from ~30 shifted-elementwise-reduce passes over the panel;
# here each stage is ONE sweep with lag rings in VMEM and the moment
# accumulators in a revisited output block, after which XLA solves the tiny
# [k, k] systems.  Stage 2 recomputes the stage-1 residuals on the fly from
# beta1 (no [B, T] residual array ever lands in HBM).


def _hr_kernel(lag_y, lag_e, intercept, woff, beta_m, t_limit, cs, *refs):
    """Shared moment-sweep body.  Column streams at step t:
    ``[1 (if intercept), y_{t-1}..y_{t-lag_y}, e_{t-1}..e_{t-lag_e}]``
    where ``e`` is the AR(beta_m) residual (stage 2 only, ``lag_e > 0``).
    Accumulates sum(w * c_a * c_b) for a <= b and sum(w * c_a * y_t) with
    ``w = [zb + woff <= t < t_limit]``.

    Nothing here is recursive, so the whole chunk runs as full-tile VPU ops
    with STATIC time-axis slices (a per-step loop is bounded by loop
    machinery, not arithmetic); lag reads crossing the chunk boundary come
    from halo scratches holding the previous chunk's trailing tiles."""
    if lag_e:
        y_ref, zb_ref, beta_ref, acc_ref, yhalo_ref, ehalo_ref = refs
    else:
        y_ref, zb_ref, acc_ref, yhalo_ref = refs
        beta_ref = ehalo_ref = None
    c = pl.program_id(1)
    base = c * cs
    zb = zb_ref[0]
    ncols = int(intercept) + lag_y + lag_e
    nacc = ncols * (ncols + 1) // 2 + ncols
    ydepth = max(lag_y, beta_m, 1)
    edepth = max(lag_e, 1)

    @pl.when(c == 0)
    def _():
        for r_ in range(nacc):
            acc_ref[r_] = _ZERO()
        for j in range(ydepth):
            yhalo_ref[j] = _ZERO()  # values before the global start are 0
        if lag_e:
            for j in range(edepth):
                ehalo_ref[j] = _ZERO()

    y = y_ref[:]  # [cs, 8, 128]
    t_id = base + lax.broadcasted_iota(jnp.int32, (cs, 1, 1), 0)
    tf = t_id.astype(jnp.float32)
    w = ((tf >= zb + woff) & (t_id < t_limit)).astype(jnp.float32)

    def shifted(tile, halo_ref_, depth, k):
        """tile value at t - k (zero-filled before the global start)."""
        if k == 0:
            return tile
        top = jnp.stack([halo_ref_[depth - k + i] for i in range(k)])
        return jnp.concatenate([top, tile[: cs - k]], axis=0)

    cols = []
    if intercept:
        cols.append(None)  # the constant-1 stream, handled symbolically
    for i in range(1, lag_y + 1):
        cols.append(shifted(y, yhalo_ref, ydepth, i))
    if lag_e:
        # stage-1 residual stream (zero outside its own live window)
        w1 = ((tf >= zb + beta_m) & (t_id < t_limit)).astype(jnp.float32)
        pred = beta_ref[0][None]
        for i in range(1, beta_m + 1):
            pred = pred + beta_ref[i][None] * shifted(y, yhalo_ref, ydepth, i)
        ehat = w1 * (y - pred)
        for j in range(1, lag_e + 1):
            cols.append(shifted(ehat, ehalo_ref, edepth, j))

    def cval(a):
        return 1.0 if cols[a] is None else cols[a]

    r_ = 0
    for a in range(ncols):
        for b_ in range(a, ncols):
            prod = w if (cols[a] is None and cols[b_] is None) else (
                w * cval(b_) if cols[a] is None else
                (w * cval(a) if cols[b_] is None else w * cval(a) * cval(b_))
            )
            acc_ref[r_] = acc_ref[r_] + jnp.sum(prod, axis=0)
            r_ += 1
    for a in range(ncols):
        prod = w * y if cols[a] is None else w * cval(a) * y
        acc_ref[r_] = acc_ref[r_] + jnp.sum(prod, axis=0)
        r_ += 1

    # write halos AFTER all shifted() reads of the previous chunk's tiles
    for j in range(ydepth):
        yhalo_ref[j] = y[cs - ydepth + j]
    if lag_e:
        for j in range(edepth):
            ehalo_ref[j] = ehat[cs - edepth + j]


def _hr_moments(y3, zb3, t, cs, nchunk, nblk, lag_y, lag_e, intercept,
                woff, beta_m, beta3, interpret):
    ncols = int(intercept) + lag_y + lag_e
    nacc = ncols * (ncols + 1) // 2 + ncols
    ydepth = max(lag_y, beta_m, 1)
    ins = [_bs(cs, _cur), _bs(1, _fixed)]
    args = [y3, zb3]
    scratch = [pltpu.VMEM((ydepth, _SUBL, _LANES), jnp.float32)]
    if lag_e:
        ins.append(_bs(beta_m + 1, _fixed))
        args.append(beta3)
        scratch.append(pltpu.VMEM((max(lag_e, 1), _SUBL, _LANES), jnp.float32))
    return pl.pallas_call(
        functools.partial(_hr_kernel, lag_y, lag_e, intercept, woff, beta_m,
                          t, cs),
        grid=(nblk, nchunk),
        in_specs=ins,
        out_specs=_bs(nacc, _fixed),
        out_shape=jax.ShapeDtypeStruct((nacc, y3.shape[1], _LANES), jnp.float32),
        scratch_shapes=scratch,
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(*args)


def _solve_moments(acc, ncols, dtype, ridge=1e-8):
    """[B, nacc] moment rows -> ridge-stabilized OLS solutions [B, ncols]
    (the ONE stabilization rule: utils.linalg.ridge_solve)."""
    from ..utils.linalg import ridge_solve

    b = acc.shape[0]
    XtX = jnp.zeros((b, ncols, ncols), dtype)
    r_ = 0
    for a in range(ncols):
        for b_ in range(a, ncols):
            XtX = XtX.at[:, a, b_].set(acc[:, r_])
            if a != b_:
                XtX = XtX.at[:, b_, a].set(acc[:, r_])
            r_ += 1
    Xty = acc[:, r_ : r_ + ncols]
    return ridge_solve(XtX, Xty, ridge)


def hr_structural_ok(p: int, q: int) -> bool:
    """Ring depths must stay tiny (VMEM planes grow O((p+q)^2))."""
    return 0 <= p <= 8 and 0 <= q <= 8


@_scoped("pallas.hr_init")
def hr_init(yd, order: Order, include_intercept: bool, n_valid=None, *,
            interpret: bool = False, y3=None):
    """Batched Hannan-Rissanen startup values ``[B, k]`` on fused kernels.

    Matches ``models.arima.hannan_rissanen_batched`` (identical weighted
    normal equations and ridge stabilization) in two panel sweeps: stage-1
    AR(m) moments -> solve -> stage-2 moments with on-the-fly residuals ->
    solve.  ``yd``: differenced panel with the invalid prefix zeroed.

    ``y3``: optionally the already-folded panel (:func:`css_prefold`'s
    first output — its extra zero at ``start - 1`` is never read by a
    weighted row), so one fit folds the panel exactly once.
    """
    p, _, q = order
    if not hr_structural_ok(p, q):
        raise ValueError(f"fused HR kernel supports p, q <= 8 (got {p}, {q})")
    b, t = yd.shape
    n = t
    m = min(p + q + 1, max(n // 4, 1))
    nv = jnp.full((b,), n, jnp.int32) if n_valid is None else n_valid
    zb = (n - nv).astype(yd.dtype)
    tp, cs, nchunk = _time_layout(t)
    if y3 is None:
        y3 = _fold(jnp.pad(yd, ((0, 0), (0, tp - t))))
    zb3 = _fold(zb[:, None])
    nblk = y3.shape[1] // _SUBL

    acc1 = _hr_moments(y3, zb3, t, cs, nchunk, nblk, m, 0, True, m, 0, None,
                       interpret)
    beta1 = _solve_moments(_unfold(acc1, b), m + 1, yd.dtype)  # [B, m+1]

    ncols2 = int(include_intercept) + p + q
    if ncols2 == 0:
        return jnp.zeros((b, 0), yd.dtype)
    beta3 = _fold(beta1)
    acc2 = _hr_moments(y3, zb3, t, cs, nchunk, nblk, p, q, include_intercept,
                       m + q, m, beta3, interpret)
    return _solve_moments(_unfold(acc2, b), ncols2, yd.dtype)


# ---------------------------------------------------------------------------
# Fused multi-lag autocorrelation (forward-only transform, no adjoint)
# ---------------------------------------------------------------------------
#
# autocorr(num_lags) reads the panel once: d_t = valid ? x_t - mean : 0 is
# computed on the fly, the last ``num_lags`` d values stay in a VMEM ring,
# and num_lags+1 accumulators (lag products + denominator) land in a
# revisited output block — versus ~num_lags full-panel passes for the XLA
# lowering of the vmapped kernel (ops.univariate.autocorr).  The mean is a
# single cheap XLA reduction beforehand (it must complete before any
# product term, so fusing it would force a second sequential sweep anyway).


def _autocorr_kernel(nl, t_limit, cs, mean_inside, *refs):
    # autocorrelation has NO serial recursion, so the whole chunk runs as
    # full-tile VPU ops with STATIC time-axis slices — a per-step loop (even
    # with carried registers) is bounded by loop machinery, not arithmetic.
    # Cross-chunk lag pairs read the previous chunk's last nl centered
    # values from a halo scratch (static indices, touched once per chunk).
    # (A fold-free lane-layout variant — series on sublanes, time on lanes,
    # no transpose — was measured 2-3x SLOWER on a v5e: the misaligned lane
    # slices for the lag products relayout on every term, while this
    # layout's time-axis shifts are free register re-indexing.)
    if mean_inside:  # single-chunk: the tile IS the series; fuse the mean
        y_ref, acc_ref, halo_ref = refs
        mean = None
    else:
        y_ref, mean_ref, acc_ref, halo_ref = refs
        mean = mean_ref[0]
    c = pl.program_id(1)
    base = c * cs

    @pl.when(c == 0)
    def _():
        for r in range(nl + 1):
            acc_ref[r] = _ZERO()
        for j in range(nl):
            halo_ref[j] = _ZERO()  # d before the global start is zero

    y = y_ref[:]  # [cs, 8, 128]
    t_id = base + lax.broadcasted_iota(jnp.int32, (cs, 1, 1), 0)
    valid = (y == y) & (t_id < t_limit)
    if mean_inside:
        vf = valid.astype(jnp.float32)
        n = jnp.sum(vf, axis=0)
        mean = jnp.sum(jnp.where(valid, y, 0.0), axis=0) / jnp.maximum(n, 1.0)
    d = jnp.where(valid, y - mean, 0.0)
    acc_ref[0] = acc_ref[0] + jnp.sum(d * d, axis=0)
    for k_ in range(1, nl + 1):
        main = jnp.sum(d[k_:] * d[: cs - k_], axis=0)
        # boundary pairs: local t < k_ partners with halo[nl - k_ + t]
        bsum = _ZERO()
        for t_ in range(k_):
            bsum = bsum + d[t_] * halo_ref[nl - k_ + t_]
        acc_ref[k_] = acc_ref[k_] + main + bsum
    for j in range(nl):
        halo_ref[j] = d[cs - nl + j]


def _batch_autocorr_call(y3, b: int, t: int, num_lags: int, interpret: bool):
    if not 0 < num_lags < min(t, _CHUNK_T):
        raise ValueError(
            f"num_lags must be in (0, min(T, {_CHUNK_T})) = "
            f"(0, {min(t, _CHUNK_T)}), got {num_lags}"
        )
    tp, cs, nchunk = _time_layout(t)
    if y3.shape[0] != tp:
        raise ValueError(
            f"folded panel has time dim {y3.shape[0]}, layout wants {tp}"
        )
    mean_inside = nchunk == 1  # the tile holds the whole series: fuse the
    # mean into the kernel (saves one full XLA panel pass)
    args = [y3]
    ins = [_bs(cs, _cur)]
    if not mean_inside:
        t_ok = jnp.arange(tp)[:, None, None] < t
        valid = (y3 == y3) & t_ok
        n = jnp.sum(valid, axis=0)
        mean = jnp.sum(jnp.where(valid, y3, 0.0), axis=0) / jnp.maximum(n, 1)
        args.append(mean[None].astype(jnp.float32))
        ins.append(_bs(1, _fixed))
    nblk = y3.shape[1] // _SUBL
    acc3 = pl.pallas_call(
        functools.partial(_autocorr_kernel, num_lags, t, cs, mean_inside),
        grid=(nblk, nchunk),
        in_specs=ins,
        out_specs=_bs(num_lags + 1, _fixed),
        out_shape=jax.ShapeDtypeStruct(
            (num_lags + 1, y3.shape[1], _LANES), jnp.float32
        ),
        scratch_shapes=[pltpu.VMEM((num_lags, _SUBL, _LANES), jnp.float32)],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(*args)
    acc = _unfold(acc3, b)  # [B, num_lags + 1]
    return acc[:, 1:] / acc[:, :1]


@_scoped("pallas.batch_autocorr")
def batch_autocorr(y, num_lags: int, *, interpret: bool = False):
    """Batched sample autocorrelation ``[B, num_lags]`` on a fused kernel.

    Matches ``vmap(ops.univariate.autocorr)`` (valid-sample mean/denominator
    convention) to float tolerance.
    """
    b, t = y.shape
    tp, _, _ = _time_layout(t)
    y3 = _fold(jnp.pad(y, ((0, 0), (0, tp - t)), constant_values=jnp.nan))
    return _batch_autocorr_call(y3, b, t, num_lags, interpret)


@_scoped("pallas.batch_autocorr")
def batch_autocorr_folded(fp, num_lags: int, *, interpret: bool = False):
    """:func:`batch_autocorr` on a resident :class:`~.layout.FoldedPanel` —
    no per-dispatch layout conversion: the kernel streams the panel once
    (measured 79% of HBM peak vs 19% with the fold in the dispatch)."""
    return _batch_autocorr_call(fp.data, fp.b, fp.t, num_lags, interpret)


def hw_seeds(y, period: int, multiplicative: bool = False, n_valid=None):
    """Level/trend/seasonal-ring seeds for :func:`hw_sse_seeded`.

    Returns ``(l0, t0, s0r, zb)``: the first-two-valid-seasons seed scheme
    shared with the scan path (``models.holtwinters._init_state`` — pallas/
    scan fit parity depends on these being identical), with the seasonal
    ring PRE-ROTATED for the kernel's ``t mod m`` indexing (scratch indices
    are scalar per block, ``zb`` is per row): seed element ``j`` sits at
    slot ``(start + j) mod m``, i.e. ``ring[p] = s0[(p - start) mod m]``.

    Seeds depend on the data only — they are constants of the fit objective.
    Compute them ONCE per fit and close over them: the vmapped dynamic
    slices lower to batched gathers, expensive enough at panel scale to
    dominate an objective evaluation if recomputed inside the optimizer.

    ``n_valid=None`` asserts a DENSE panel (every row starts at t=0): the
    per-row slices are then static and the whole computation vectorizes
    with no gathers — measured ~0.5 s of device time saved per 131k x 960
    fit versus the general path with a zero start vector.
    """
    m = period
    b, t = y.shape
    from ..models.holtwinters import _init_state

    if n_valid is None:  # dense: _init_state's static-slice path (no
        # gathers), identity ring rotation — one seeding scheme, one place
        l0, t0, s0 = jax.vmap(
            lambda yv: _init_state(yv, m, multiplicative, None)
        )(y)
        return l0, t0, s0, jnp.zeros((b,), y.dtype)
    start = (t - n_valid).astype(jnp.int32)

    l0, t0, s0 = jax.vmap(
        lambda yv, st: _init_state(yv, m, multiplicative, st)
    )(y, start)
    pos = (jnp.arange(m)[None, :] - start[:, None]) % m
    s0r = jnp.take_along_axis(s0, pos, axis=1)
    return l0, t0, s0r, start.astype(y.dtype)


@_scoped("pallas.hw_sse")
def hw_sse_seeded(params, y, seeds, period: int,
                  multiplicative: bool = False, *, interpret: bool = False):
    """Batched Holt-Winters one-step-ahead SSE ``[B]`` on a fused kernel,
    with precomputed :func:`hw_seeds` — the fit-loop entry point.

    Matches ``models.holtwinters.sse`` (vmapped) for additive AND
    multiplicative seasonality with a right-aligned valid span (the invalid
    prefix of ``y`` must already be zeroed — ``base.align_right``).
    Differentiable in ``params``; the seeds are constants of the objective.
    """
    m = period
    if not hw_structural_ok(m):
        raise ValueError(
            f"fused Holt-Winters kernel supports period <= {_CHUNK_T} "
            f"(got {m}); use backend='scan'"
        )
    l0, t0, s0r, zb = seeds
    return _hw_ss(interpret, m, multiplicative, params, y, l0, t0, s0r, zb)


def hw_sse(params, y, period: int, multiplicative: bool = False,
           n_valid=None, *, interpret: bool = False):
    """One-shot entry: compute seeds then the SSE (tests / single calls).
    Inside an optimizer loop use :func:`hw_seeds` + :func:`hw_sse_seeded`."""
    if not hw_structural_ok(period):  # before seeds: a clear error, not a
        raise ValueError(             # dynamic_slice TypeError from the seed
            f"fused Holt-Winters kernel supports period <= {_CHUNK_T} "
            f"(got {period}); use backend='scan'"
        )
    seeds = hw_seeds(y, period, multiplicative, n_valid)
    return hw_sse_seeded(params, y, seeds, period, multiplicative,
                         interpret=interpret)


def hw_additive_sse(params, y, period: int, *, interpret: bool = False):
    """Additive dense-panel entry (kept for compatibility): see :func:`hw_sse`."""
    return hw_sse(params, y, period, False, None, interpret=interpret)
