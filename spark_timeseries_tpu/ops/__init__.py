from . import univariate
from .lagmat import lag_mat_trim_both, lag_mat_trim_both_2d
from .layout import FoldedPanel, fold_panel, unfold_panel

__all__ = [
    "univariate",
    "lag_mat_trim_both",
    "lag_mat_trim_both_2d",
    "FoldedPanel",
    "fold_panel",
    "unfold_panel",
]
