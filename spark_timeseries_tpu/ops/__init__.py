from . import univariate
from .lagmat import lag_mat_trim_both, lag_mat_trim_both_2d

__all__ = ["univariate", "lag_mat_trim_both", "lag_mat_trim_both_2d"]
