"""Per-series kernels: pure ``[time] -> [time]`` functions, NaN/mask-aware.

This is the L2 layer — the TPU-native replacement for the reference's
``com.cloudera.sparkts.UnivariateTimeSeries`` object (SURVEY.md Section 2.1,
upstream path unverified): autocorr, lag(s), differences (order-d / at-lag),
quotients, price2ret, the fill family (nearest / previous / next / linear /
spline / value), NaN trims, and down/upsampling.

Design: every function is written for a single ``f32/f64[time]`` vector with
NaN marking missing data, is jit-compatible (static shapes, no data-dependent
Python control flow), and is exposed batched over the series axis via
``jax.vmap`` — replacing the reference's sequential per-series Breeze loops
inside Spark executor tasks (SURVEY.md Section 3.2 hot loop #2).  Batched
variants are exported with a ``batch_`` prefix and operate on ``[keys, time]``
panels, which is what ``TimeSeriesPanel.map_series`` dispatches to.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "first_not_nan_loc",
    "last_not_nan_loc",
    "autocorr",
    "cross_corr",
    "lag",
    "lags",
    "differences_at_lag",
    "differences_of_order",
    "quotients",
    "price2ret",
    "fill_value",
    "fill_with_default",
    "fill_previous",
    "fill_next",
    "fill_nearest",
    "fill_linear",
    "fill_spline",
    "fillts",
    "trim_leading",
    "trim_trailing",
    "downsample",
    "upsample",
    "resample",
    "batched",
    "batch_autocorr",
    "batch_fill",
    "batch_fill_linear_chain",
]


def _isvalid(x):
    return ~jnp.isnan(x)


def _nan(dtype):
    return jnp.asarray(jnp.nan, dtype=dtype)


# ---------------------------------------------------------------------------
# Locations of valid data
# ---------------------------------------------------------------------------


def first_not_nan_loc(x: jax.Array) -> jax.Array:
    """Index of the first non-NaN element, or ``size`` if all NaN."""
    valid = _isvalid(x)
    return jnp.where(jnp.any(valid), jnp.argmax(valid), x.shape[0])


def last_not_nan_loc(x: jax.Array) -> jax.Array:
    """Index of the last non-NaN element, or -1 if all NaN."""
    valid = _isvalid(x)
    rev = jnp.argmax(valid[::-1])
    return jnp.where(jnp.any(valid), x.shape[0] - 1 - rev, -1)


def trim_leading(x) -> jax.Array:
    """Drop the leading NaN run.  Host-side (dynamic shape — not jittable)."""
    import numpy as np

    x = np.asarray(x)
    loc = int(first_not_nan_loc(jnp.asarray(x)))
    return x[loc:]


def trim_trailing(x) -> jax.Array:
    """Drop the trailing NaN run.  Host-side (dynamic shape — not jittable)."""
    import numpy as np

    x = np.asarray(x)
    loc = int(last_not_nan_loc(jnp.asarray(x)))
    return x[: loc + 1]


# ---------------------------------------------------------------------------
# Correlation
# ---------------------------------------------------------------------------


def autocorr(x: jax.Array, num_lags: int) -> jax.Array:
    """Sample autocorrelation at lags ``1..num_lags`` -> ``[num_lags]``.

    r_k = sum_{t=k}^{n-1} (x_t - m)(x_{t-k} - m) / sum_t (x_t - m)^2, computed
    over valid (non-NaN) entries; denominators use the full valid sample.
    Replaces ``UnivariateTimeSeries.autocorr`` (reference used Breeze loops).
    """
    if not 0 < num_lags < x.shape[0]:
        raise ValueError(
            f"num_lags must be in (0, series length {x.shape[0]}), got {num_lags}"
        )
    valid = _isvalid(x)
    n = jnp.sum(valid)
    xz = jnp.where(valid, x, 0.0)
    mean = jnp.sum(xz) / jnp.maximum(n, 1)
    d = jnp.where(valid, x - mean, 0.0)
    denom = jnp.sum(d * d)

    def corr_at(k):
        prod = d[k:] * d[: x.shape[0] - k]
        return jnp.sum(prod) / denom

    return jnp.stack([corr_at(k) for k in range(1, num_lags + 1)])


def pacf(x: jax.Array, num_lags: int) -> jax.Array:
    """Sample partial autocorrelation at lags ``1..num_lags`` -> ``[num_lags]``.

    Durbin-Levinson recursion on the sample autocorrelations (Yule-Walker
    solution), the standard estimator behind the reference's PACF plot
    (upstream ``EasyPlot.pacfPlot`` — path unverified).  NaNs are handled by
    the same valid-sample convention as :func:`autocorr`.
    """
    rho = jnp.concatenate([jnp.ones((1,), x.dtype), autocorr(x, num_lags)])

    def step(carry, k):
        phi = carry  # [num_lags] coefficients of the order-(k-1) model
        idx = jnp.arange(num_lags)
        prev = idx < k - 1
        # numerator: rho[k] - sum_{j=1}^{k-1} phi_j * rho[k-j]
        num = rho[k] - jnp.sum(jnp.where(prev, phi * rho[jnp.abs(k - 1 - idx)], 0.0))
        den = 1.0 - jnp.sum(jnp.where(prev, phi * rho[idx + 1], 0.0))
        pk = num / den
        # phi_j^{(k)} = phi_j^{(k-1)} - pk * phi_{k-j}^{(k-1)}
        rev = jnp.where(prev, phi[jnp.abs(k - 2 - idx)], 0.0)
        phi = jnp.where(prev, phi - pk * rev, phi)
        phi = jnp.where(idx == k - 1, pk, phi)
        return phi, pk

    _, pks = jax.lax.scan(step, jnp.zeros((num_lags,), rho.dtype), jnp.arange(1, num_lags + 1))
    return pks


def cross_corr(x: jax.Array, y: jax.Array, num_lags: int) -> jax.Array:
    """Cross-correlation of ``x`` with ``y`` at lags ``-num_lags..num_lags``."""
    xd = x - jnp.nanmean(x)
    yd = y - jnp.nanmean(y)
    sx = jnp.sqrt(jnp.nansum(xd * xd))
    sy = jnp.sqrt(jnp.nansum(yd * yd))
    xz = jnp.where(_isvalid(xd), xd, 0.0)
    yz = jnp.where(_isvalid(yd), yd, 0.0)
    out = []
    for k in range(-num_lags, num_lags + 1):
        if k >= 0:
            prod = jnp.sum(xz[k:] * yz[: x.shape[0] - k]) if k < x.shape[0] else 0.0
        else:
            prod = jnp.sum(yz[-k:] * xz[: x.shape[0] + k])
        out.append(prod / (sx * sy))
    return jnp.stack(out)


# ---------------------------------------------------------------------------
# Lags and differences
# ---------------------------------------------------------------------------


def lag(x: jax.Array, k: int) -> jax.Array:
    """Shift right by ``k``; the first ``k`` entries become NaN."""
    if not 0 <= k < x.shape[0]:
        raise ValueError(f"lag {k} must be in [0, {x.shape[0]}) for series length {x.shape[0]}")
    if k == 0:
        return x
    return jnp.concatenate([jnp.full((k,), jnp.nan, dtype=x.dtype), x[:-k]])


def lags(x: jax.Array, max_lag: int, include_original: bool = True) -> jax.Array:
    """Lagged copies as columns -> ``[time, max_lag (+1)]``.

    Column order matches the reference's ``TimeSeries.lags`` / ``Lag``:
    original first (if included), then lag 1, lag 2, ...
    """
    cols = ([x] if include_original else []) + [lag(x, k) for k in range(1, max_lag + 1)]
    return jnp.stack(cols, axis=1)


def differences_at_lag(x: jax.Array, k: int) -> jax.Array:
    """``out[t] = x[t] - x[t-k]``; the first ``k`` entries are NaN."""
    return x - lag(x, k)


def differences_of_order(x: jax.Array, d: int) -> jax.Array:
    """Order-``d`` differencing (d applications of lag-1 differencing).

    The first ``d`` entries are NaN.  ARIMA's ``d`` step.
    """
    for _ in range(d):
        x = differences_at_lag(x, 1)
    return x


def quotients(x: jax.Array, k: int = 1) -> jax.Array:
    """``out[t] = x[t] / x[t-k]``; the first ``k`` entries are NaN."""
    return x / lag(x, k)


def price2ret(x: jax.Array, k: int = 1) -> jax.Array:
    """Simple returns: ``x[t] / x[t-k] - 1``; first ``k`` entries NaN."""
    return quotients(x, k) - 1.0


# ---------------------------------------------------------------------------
# Fill family
# ---------------------------------------------------------------------------


def fill_value(x: jax.Array, value) -> jax.Array:
    """Replace every NaN with ``value``."""
    return jnp.where(_isvalid(x), x, jnp.asarray(value, dtype=x.dtype))


def fill_with_default(x: jax.Array, default=0.0) -> jax.Array:
    return fill_value(x, default)


def _prev_valid_idx(valid: jax.Array) -> jax.Array:
    """For each t, the index of the latest valid position <= t, or -1."""
    t = jnp.arange(valid.shape[0])
    cand = jnp.where(valid, t, -1)
    return lax.associative_scan(jnp.maximum, cand)


def _next_valid_idx(valid: jax.Array) -> jax.Array:
    """For each t, the index of the earliest valid position >= t, or size."""
    n = valid.shape[0]
    t = jnp.arange(n)
    cand = jnp.where(valid, t, n)
    return lax.associative_scan(jnp.minimum, cand, reverse=True)


def _carry_valid_vals(valid: jax.Array, x: jax.Array, reverse: bool = False):
    """-> (value, seen): value of the nearest valid position at-or-before t
    (``reverse=False``) or at-or-after t (``reverse=True``) with 0.0 where
    none exists, and the boolean "some valid position exists on that side".

    Expressed as an associative "rightmost-valid-wins" scan over
    (value, seen-valid) pairs instead of ``x[prev_idx]`` gathers: batched
    gathers are the single most expensive construct for the TPU compiler at
    panel scale, while this lowers to log2(n) elementwise select steps.
    """
    vals = jnp.where(valid, jnp.nan_to_num(x), 0.0)

    def comb(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av), af | bf

    return lax.associative_scan(comb, (vals, valid), reverse=reverse)


def fill_previous(x: jax.Array) -> jax.Array:
    """Forward fill (last observation carried forward); leading NaNs remain."""
    valid = _isvalid(x)
    prev_val, seen = _carry_valid_vals(valid, x)
    return jnp.where(seen, prev_val, _nan(x.dtype))


def fill_next(x: jax.Array) -> jax.Array:
    """Backward fill (next observation carried backward); trailing NaNs remain."""
    valid = _isvalid(x)
    next_val, seen = _carry_valid_vals(valid, x, reverse=True)
    return jnp.where(seen, next_val, _nan(x.dtype))


def fill_nearest(x: jax.Array) -> jax.Array:
    """Fill each NaN with the nearest valid value (ties -> previous)."""
    valid = _isvalid(x)
    n = x.shape[0]
    t = jnp.arange(n)
    ip = _prev_valid_idx(valid)
    inx = _next_valid_idx(valid)
    dp = jnp.where(ip >= 0, t - ip, n + 1)
    dn = jnp.where(inx < n, inx - t, n + 1)
    pick_prev = dp <= dn
    prev_val, _ = _carry_valid_vals(valid, x)
    next_val, _ = _carry_valid_vals(valid, x, reverse=True)
    filled = jnp.where(pick_prev, prev_val, next_val)
    any_side = (ip >= 0) | (inx < n)
    return jnp.where(valid, x, jnp.where(any_side, filled, _nan(x.dtype)))


def fill_linear(x: jax.Array) -> jax.Array:
    """Linear interpolation across interior NaN gaps; edge NaNs remain."""
    valid = _isvalid(x)
    n = x.shape[0]
    t = jnp.arange(n)
    ip = _prev_valid_idx(valid)
    inx = _next_valid_idx(valid)
    interior = (ip >= 0) & (inx < n)
    ip_c = jnp.maximum(ip, 0)
    in_c = jnp.minimum(inx, n - 1)
    span = jnp.maximum(in_c - ip_c, 1).astype(x.dtype)
    w = (t - ip_c).astype(x.dtype) / span
    prev_val, _ = _carry_valid_vals(valid, x)
    next_val, _ = _carry_valid_vals(valid, x, reverse=True)
    interp = prev_val * (1.0 - w) + next_val * w
    return jnp.where(valid, x, jnp.where(interior, interp, _nan(x.dtype)))


def fill_spline(x: jax.Array) -> jax.Array:
    """Natural cubic spline through the valid points; edge NaNs remain.

    Mask-aware, fixed-shape: valid knots are compacted to the front with a
    stable argsort, the natural-spline tridiagonal system is solved with a
    Thomas-algorithm ``lax.scan`` (time-serial per series, vmapped over
    series), and interior NaNs are evaluated on their bracketing knot
    interval.  Matches ``scipy.interpolate.CubicSpline(bc_type='natural')``
    on the valid points (oracle-tested).  Reference used Commons-Math
    ``SplineInterpolator`` (SURVEY.md Section 2.1).
    """
    n = x.shape[0]
    dtype = x.dtype
    valid = _isvalid(x)
    m = jnp.sum(valid)  # number of knots

    # Compact valid knots to the front (stable: preserves time order).
    order = jnp.argsort(~valid, stable=True)
    kx = jnp.where(jnp.arange(n) < m, order, n)  # knot time-positions, pad n
    ky = jnp.where(jnp.arange(n) < m, x[jnp.minimum(order, n - 1)], 0.0)

    kxf = kx.astype(dtype)
    h = jnp.maximum(kxf[1:] - kxf[:-1], 1e-30)  # knot spacings [n-1]
    dy = (ky[1:] - ky[:-1]) / h

    # Natural spline: solve for second derivatives M[0..m-1], M[0]=M[m-1]=0.
    # Interior rows i=1..m-2:  h[i-1]*M[i-1] + 2(h[i-1]+h[i])*M[i] + h[i]*M[i+1]
    #                          = 6*(dy[i] - dy[i-1])
    i = jnp.arange(n)
    is_interior = (i >= 1) & (i < jnp.maximum(m - 1, 1))
    a = jnp.where(is_interior, jnp.concatenate([jnp.zeros((1,), dtype), h]), 0.0)[:n]
    b = jnp.where(
        is_interior,
        2.0 * (jnp.concatenate([jnp.zeros((1,), dtype), h])[:n] + jnp.concatenate([h, jnp.zeros((1,), dtype)])[:n]),
        1.0,
    )
    c = jnp.where(is_interior, jnp.concatenate([h, jnp.zeros((1,), dtype)]), 0.0)[:n]
    rhs_full = jnp.concatenate([jnp.zeros((1,), dtype), 6.0 * (dy[1:] - dy[:-1]), jnp.zeros((1,), dtype)])[:n]
    rhs = jnp.where(is_interior, rhs_full, 0.0)

    # Thomas algorithm: forward elimination then back substitution via scans.
    def fwd(carry, abcr):
        cp_prev, dp_prev = carry
        ai, bi, ci, ri = abcr
        denom = bi - ai * cp_prev
        cp = ci / denom
        dp = (ri - ai * dp_prev) / denom
        return (cp, dp), (cp, dp)

    (_, _), (cps, dps) = lax.scan(fwd, (jnp.zeros((), dtype), jnp.zeros((), dtype)), (a, b, c, rhs))

    def bwd(carry, cd):
        cp, dp = cd
        mi = dp - cp * carry
        return mi, mi

    _, Ms_rev = lax.scan(bwd, jnp.zeros((), dtype), (cps[::-1], dps[::-1]))
    M = Ms_rev[::-1]  # second derivatives at knots

    # Evaluate at every position; knots map back exactly via the pieces.
    # Find bracketing knot interval j: kx[j] <= t < kx[j+1].
    t = jnp.arange(n)
    srch_keys = jnp.where(jnp.arange(n) < m, kx, jnp.iinfo(jnp.int32).max)
    j = jnp.clip(jnp.searchsorted(srch_keys, t, side="right") - 1, 0, n - 2)
    x0, x1 = kxf[j], kxf[j + 1]
    y0, y1 = ky[j], ky[j + 1]
    M0, M1 = M[j], M[j + 1]
    hj = jnp.maximum(x1 - x0, 1e-30)
    tt = t.astype(dtype)
    A = (x1 - tt) / hj
    B = (tt - x0) / hj
    s = (
        A * y0
        + B * y1
        + ((A**3 - A) * M0 + (B**3 - B) * M1) * (hj**2) / 6.0
    )

    ip = _prev_valid_idx(valid)
    inx = _next_valid_idx(valid)
    interior = (ip >= 0) & (inx < n)
    return jnp.where(valid, x, jnp.where(interior, s, _nan(dtype)))


_FILLS: dict = {
    "value": None,  # needs an argument; handled in fillts
    "previous": fill_previous,
    "next": fill_next,
    "nearest": fill_nearest,
    "linear": fill_linear,
    "spline": fill_spline,
    "zero": lambda x: fill_value(x, 0.0),
}


def fillts(x: jax.Array, method: str, value=None) -> jax.Array:
    """Dispatch on fill-method name — mirrors ``UnivariateTimeSeries.fillts``."""
    if method == "value":
        if value is None:
            raise ValueError("fill method 'value' requires a value")
        return fill_value(x, value)
    if method not in _FILLS:
        raise ValueError(f"unknown fill method {method!r}; options: {sorted(_FILLS)}")
    return _FILLS[method](x)


# ---------------------------------------------------------------------------
# Resampling
# ---------------------------------------------------------------------------


def downsample(x: jax.Array, n: int, offset: int = 0) -> jax.Array:
    """Every ``n``-th element starting at ``offset`` (static output shape)."""
    return x[offset::n]


def upsample(x: jax.Array, n: int, offset: int = 0, use_nan: bool = True) -> jax.Array:
    """Spread elements ``n`` apart, padding with NaN (or 0) between."""
    out_len = x.shape[0] * n
    pad = jnp.nan if use_nan else 0.0
    out = jnp.full((out_len,), pad, dtype=x.dtype)
    return out.at[offset::n].set(x)


def resample(
    x: jax.Array,
    ratio: int,
    aggr: Callable[[jax.Array], jax.Array] = jnp.nanmean,
) -> jax.Array:
    """Aggregate consecutive windows of length ``ratio`` (e.g. hourly->daily)."""
    n_out = x.shape[0] // ratio
    return aggr(x[: n_out * ratio].reshape(n_out, ratio), axis=1)


# ---------------------------------------------------------------------------
# Batched (panel) variants — the TPU hot path
# ---------------------------------------------------------------------------


def batched(fn: Callable, *static_args, **static_kwargs) -> Callable:
    """Lift a ``[time] -> ...`` kernel to ``[keys, time] -> ...`` via vmap+jit."""
    lifted = jax.vmap(lambda v: fn(v, *static_args, **static_kwargs))
    return jax.jit(lifted)


def batch_autocorr(num_lags: int, backend: str = "auto") -> Callable:
    """``[keys, time] -> [keys, num_lags]`` autocorrelation.

    ``backend="auto"`` uses the fused single-pass Pallas kernel on TPU/f32
    panels (``ops.pallas_kernels.batch_autocorr``; ~num_lags fewer HBM
    passes than the vmapped lowering) and falls back to ``vmap(autocorr)``
    everywhere else.  Both paths agree to float tolerance.

    A resident :class:`~.layout.FoldedPanel` is accepted directly: the
    kernel then streams the panel once, with no per-dispatch layout
    conversion (``ops.layout`` — the TPU residency decision).
    """
    vmapped = batched(autocorr, num_lags)

    def run(panel):
        from . import pallas_kernels as pk
        from .layout import FoldedPanel, unfold_panel

        if isinstance(panel, FoldedPanel):
            if (
                backend != "scan"
                and 0 < num_lags < min(panel.t, pk._CHUNK_T)
                and pk.supported(panel.dtype, panel.t)
            ):
                return pk.batch_autocorr_folded(panel, num_lags)
            return vmapped(unfold_panel(panel))
        if (
            backend != "scan"
            and getattr(panel, "ndim", 0) == 2
            and 0 < num_lags < min(panel.shape[1], pk._CHUNK_T)
            and pk.supported(panel.dtype, panel.shape[1])
        ):
            return pk.batch_autocorr(panel, num_lags)
        return vmapped(panel)

    if backend == "scan":
        return lambda panel: run(panel) if _is_folded(panel) else vmapped(panel)
    # the branch reads only static shape/dtype/platform, so it resolves at
    # trace time: callers get one compiled program either way
    return jax.jit(run)


def _is_folded(panel) -> bool:
    from .layout import FoldedPanel

    return isinstance(panel, FoldedPanel)


def batch_fill(method: str, backend: str = "auto") -> Callable:
    """``[keys, time] -> [keys, time]`` fill; pallas fast path for linear."""
    vmapped = batched(fillts, method)
    if method != "linear" or backend == "scan":
        return vmapped

    def run(panel):
        from . import pallas_kernels as pk

        if getattr(panel, "ndim", 0) == 2 and pk.supported(panel.dtype, panel.shape[1]):
            return pk.fill_linear(panel)
        return vmapped(panel)

    return jax.jit(run)


def batch_fill_linear_chain(panel, backend: str = "auto", outputs=None):
    """Fused fillLinear -> (filled, lag-1 difference, lag-1 shift) on a panel.

    The feature-prep chain of SURVEY.md Section 6 config 2 as ONE device
    program: the Pallas path (TPU/f32) runs a two-phase fused kernel whose
    intermediates never leave VMEM; elsewhere the same chain runs as the
    composed portable kernels.

    ``outputs`` (default all three) selects which results to compute AND
    return, in order — e.g. ``("diff", "lag")`` skips the filled-panel
    store entirely on the Pallas path.  A resident
    :class:`~.layout.FoldedPanel` input yields folded outputs with no
    layout conversion anywhere in the chain.
    """
    from . import pallas_kernels as pk
    from .layout import FoldedPanel, fold_panel, unfold_panel

    sel = pk._CHAIN_OUTPUTS if outputs is None else tuple(outputs)
    if not sel or any(o not in pk._CHAIN_OUTPUTS for o in sel):
        raise ValueError(f"outputs must be a non-empty subset of "
                         f"{pk._CHAIN_OUTPUTS}, got {outputs!r}")

    if isinstance(panel, FoldedPanel):
        if backend != "scan" and pk.supported(panel.dtype, panel.t):
            return pk.fill_linear_chain_folded(panel, sel)
        nat = batch_fill_linear_chain(unfold_panel(panel), backend, sel)
        return tuple(fold_panel(o) for o in nat)

    if (
        backend != "scan"
        and getattr(panel, "ndim", 0) == 2
        and pk.supported(panel.dtype, panel.shape[1])
    ):
        if outputs is None:
            return pk.fill_linear_chain(panel)
        fps = pk.fill_linear_chain_folded(fold_panel(panel), sel)
        return tuple(unfold_panel(o) for o in fps)
    f = jax.vmap(fill_linear)(panel)
    by_name = {
        "filled": lambda: f,
        "diff": lambda: jax.vmap(lambda v: differences_at_lag(v, 1))(f),
        "lag": lambda: jax.vmap(lambda v: lag(v, 1))(f),
    }
    return tuple(by_name[o]() for o in sel)
