"""Resident TPU device layout for panels: lane-major series folding.

The fused Pallas kernels all compute in the folded ``[Tp, ceil(B/128), 128]``
layout — time on the major axis (so lag shifts are free register
re-indexing), 128 consecutive series on the lanes.  Converting from the
natural ``[B, T]`` layout is a full HBM transpose (read + write), which is
2-3x the traffic of the kernels themselves: paying it once per *dispatch*
caps every transform at ~20-25% of the HBM roofline no matter how well the
kernel streams (measured: the autocorr kernel runs at 79% of peak on a
prefolded panel vs 19% when the per-dispatch fold is included; in-kernel
transposes — VPU relayout, ``pltpu.roll`` lane rotations, MXU identity
matmuls — all measured slower than the XLA fold they replace).

So the fold is a *residency* decision, the TPU analogue of picking NCHW vs
NHWC once at ingest: :func:`fold_panel` converts a panel ONCE, the
:class:`FoldedPanel` stays on device in kernel layout, and every subsequent
transform/fit reads it at streaming rate.  The reference has no equivalent
decision to make — JVM rows are object arrays — so this layer is purely a
TPU-rebuild concern.

``FoldedPanel`` is a registered pytree: it passes through ``jit`` /
``vmap``-free program boundaries with ``b``/``t`` as static aux data, so
shape-dependent kernel grids specialize correctly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["FoldedPanel", "fold_panel", "unfold_panel"]


@jax.tree_util.register_pytree_node_class
class FoldedPanel:
    """A ``[B, T]`` panel resident in kernel layout ``[Tp, Bp/128, 128]``.

    ``data`` is NaN-padded on the time axis to the kernel chunk layout and
    zero-padded on the series axis to a multiple of 128 (padded series are
    dead lanes, discarded on unfold).  ``b`` and ``t`` are the true sizes.
    """

    __slots__ = ("data", "b", "t")

    def __init__(self, data: jax.Array, b: int, t: int):
        self.data = data
        self.b = int(b)
        self.t = int(t)

    @property
    def shape(self):  # natural-layout shape, for duck-typed shape checks
        return (self.b, self.t)

    @property
    def dtype(self):
        return self.data.dtype

    def tree_flatten(self):
        return (self.data,), (self.b, self.t)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)

    def __repr__(self):
        return (f"FoldedPanel(b={self.b}, t={self.t}, "
                f"data={self.data.shape}{self.data.dtype})")


def fold_panel(y) -> FoldedPanel:
    """``[B, T] -> FoldedPanel`` — one HBM transpose, amortized over every
    subsequent kernel dispatch on the panel.  Time padding is NaN (reads as
    missing under the kernels' validity masks, which also clamp at ``t``)."""
    from . import pallas_kernels as pk

    b, t = y.shape
    tp, _, _ = pk._time_layout(t)
    y3 = pk._fold(jnp.pad(y, ((0, 0), (0, tp - t)), constant_values=jnp.nan))
    return FoldedPanel(y3, b, t)


def unfold_panel(fp: FoldedPanel) -> jax.Array:
    """``FoldedPanel -> [B, T]`` natural layout (one HBM transpose)."""
    from . import pallas_kernels as pk

    return pk._unfold(fp.data, fp.b)[:, : fp.t]
