"""Write-ahead chunk journal: whole-job durability for panel fits.

Upstream spark-timeseries inherited *job-level* durability from Spark
itself: RDD lineage meant a lost executor or a preempted node only
recomputed its partitions, and a restarted driver replayed the DAG from the
last materialized stage.  The TPU rebuild runs a multi-chunk panel fit in
one Python process, so a SIGKILL, TPU preemption, or hung compile at chunk
7 of 8 would lose every finished chunk.  This module is the replacement
lineage: a directory holding

- one **npz result shard per committed chunk** (params / nll / converged /
  iters / status for its row range), written tmp-then-``os.replace`` so a
  shard either exists whole or not at all; and
- an atomically updated **JSON manifest** recording the run id, git commit,
  panel fingerprint, fit-config hash, and — per chunk — the row range,
  status (``committed`` / ``TIMEOUT``), ``FitStatus`` counts, wall time,
  peak device memory, and (journal version 2, ISSUE 15) a per-chunk
  **content fingerprint** of the chunk's own rows — the identity the
  delta planner (:mod:`.delta`) diffs against a new panel to refit only
  what changed.

Write-ahead ordering: the shard is durable *before* the manifest names it,
so a crash between the two leaves an orphan shard that is simply
recomputed — the manifest never references bytes that might not exist.

**Resume contract** (``reliability.fit_chunked(..., checkpoint_dir=...)``):
on restart with the same panel and fit config, committed chunks load from
their shards and only pending/TIMEOUT chunks recompute, producing results
bitwise-identical to an uninterrupted run (same chunk boundaries -> same
compiled programs over the same rows; a chunk's committed bytes ARE the
bytes the uninterrupted run produced).  A manifest whose config hash or
panel fingerprint does not match is STALE — resuming under it would splice
rows fitted under a different model/config into the result — and is
rejected loudly (:class:`StaleJournalError`); an unparseable manifest is a
torn write from a mid-commit crash of a non-atomic filesystem and is also
rejected (:class:`TornManifestError`) rather than silently started over.

**Multi-host ownership**: every process journals into its own namespace
(``proc_00001/...``) with a process-local manifest, but only process 0
commits ``manifest.json`` — the job-level manifest tooling and post-mortems
read (``tools/inspect_journal.py``) — mirroring the Spark driver being the
single writer of job state while executors own their shuffle files.

**Sharded lanes** (ISSUE 6) extend the same rule one level down: a sharded
chunk walk gives every mesh shard its own namespace (``shard_00000/...``)
with a shard-local manifest — lanes are concurrent writers, and the
single-writer protocol is per namespace — and after the lanes join,
shard/process 0 calls :func:`merge_job_manifest` to fold the shard
manifests into the ONE job-level ``manifest.json``: merged chunk entries
(shard-relative npz paths, tagged ``shard_id``), a ``shards`` block with
per-shard accounting, and the merged telemetry timeline.  Because the
shard spans sit on the single-device chunk grid and plan knobs are
excluded from the config hash, the merged manifest is itself resumable —
even by a later single-device walk of the same job.
"""

from __future__ import annotations

import errno
import functools
import hashlib
import json
import os
import subprocess
import tempfile
import threading
import time
import uuid
import zipfile
from typing import Callable, Optional

import numpy as np

from .. import obs

__all__ = [
    "ChunkJournal",
    "FencedError",
    "JournalError",
    "Lease",
    "LeaseError",
    "LoadedChunk",
    "MergeWarmer",
    "ShardJournalView",
    "StaleJournalError",
    "TornManifestError",
    "acquire_lease",
    "chunk_fingerprint",
    "chunk_sample_steps",
    "config_hash",
    "consult_disk_fault",
    "merge_job_manifest",
    "panel_fingerprint",
    "read_lease",
    "set_disk_fault_hook",
    "tear_after_replace",
]

# version 2 (ISSUE 15): manifest chunk entries gain a per-chunk content
# fingerprint (``chunk_fingerprint``) next to the panel-wide
# ``panel_fingerprint`` — the identity a delta walk (reliability.delta)
# diffs to adopt unchanged chunks.  Version-1 manifests stay RESUMABLE
# (resume never checks the version; entries without the field simply
# recompute nothing new) but are not delta-eligible — the planner
# rejects them with an explanatory error.
JOURNAL_VERSION = 2
MANIFEST = "manifest.json"
RESUME_MODES = ("auto", "require", "never")


class JournalError(RuntimeError):
    """Base class for journal failures."""


class TornManifestError(JournalError):
    """The manifest exists but does not parse — a torn/partial write."""


class StaleJournalError(JournalError):
    """The manifest belongs to a different panel or fit configuration."""


class LeaseError(JournalError):
    """Base class for lease-protocol failures (ISSUE 16)."""


class FencedError(LeaseError):
    """A stale-token holder tried to act on a root it no longer owns.

    The fencing contract (ISSUE 16): every durable write a lease holder
    performs is preceded by a token check, and a holder whose token is no
    longer the highest claim LOSES LOUDLY — it must stop writing, never
    fall back to best-effort.  Raised by :meth:`Lease.check` (and so by
    every fenced write path in ``serving.fleet``)."""


def _array_digest(v) -> str:
    """Shape + dtype + content digest of an array-valued fit kwarg.

    Contents MUST count: two ``init_params`` arrays of equal shape are
    different fit configurations, and accepting a journal across them
    would splice rows fitted under the other init.  Large arrays hash a
    deterministic strided subsample (same trust argument as
    :func:`panel_fingerprint`)."""
    a = np.asarray(v)
    if a.size > 1 << 20:
        step = -(-a.size // (1 << 20))
        a = np.ascontiguousarray(a.reshape(-1)[::step])
    digest = hashlib.sha256(np.ascontiguousarray(a).tobytes()).hexdigest()[:12]
    return f"array{tuple(np.shape(v))}:{np.asarray(v).dtype}:{digest}"


def config_hash(fit_fn: Callable, fit_kwargs: dict,
                extra: Optional[dict] = None) -> str:
    """Stable hash of everything that decides what a chunk's bytes mean.

    Covers the fit function's identity (``functools.partial`` layers are
    unwrapped and their bound arguments included), every fit kwarg (arrays
    by shape, dtype, AND a content digest — a different ``init_params`` is
    a different config), and driver-level knobs passed via ``extra``
    (chunk size, resilient mode, ...).  Two runs with equal hashes over
    the same panel produce interchangeable shards; a mismatch on resume
    means the journal is stale and must not be spliced into the new run.
    """
    layers = []
    f = fit_fn
    while isinstance(f, functools.partial):
        layers.append([
            repr(tuple(_enc(a) for a in f.args)),
            repr(sorted((k, _enc(v)) for k, v in (f.keywords or {}).items())),
        ])
        f = f.func
    name = (getattr(f, "__module__", "?") + "."
            + getattr(f, "__qualname__", repr(f)))
    kv = sorted((k, _enc(v)) for k, v in fit_kwargs.items())
    ex = sorted((k, _enc(v)) for k, v in (extra or {}).items())
    blob = json.dumps([name, layers, kv, ex], default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _enc(v):
    """Hashable text encoding of one fit-kwarg value (see config_hash)."""
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        return _array_digest(v)
    return repr(v)


def panel_fingerprint(y, max_side: int = 256) -> str:
    """Cheap content fingerprint of a ``[B, T]`` panel.

    Hashes the shape, dtype, and a deterministic strided subsample of at
    most ``max_side**2`` raw values (bit patterns, so NaN placement
    counts).  The subsample keeps the device->host transfer a few hundred
    KB even for the million-series panel; a journal is rejected as stale
    when the fingerprint differs, so collisions only risk *accepting* a
    journal for a panel that agrees on every sampled byte — the same
    trust level a size+mtime check gives, at content strength.
    """
    b, t = int(y.shape[0]), int(y.shape[1])
    sr, sc = max(1, -(-b // max_side)), max(1, -(-t // max_side))
    sample = np.ascontiguousarray(np.asarray(y[::sr, ::sc]))
    h = hashlib.sha256()
    h.update(f"{b}x{t}:{sample.dtype}".encode())
    h.update(sample.tobytes())
    return h.hexdigest()[:16]


# side cap for the per-chunk fingerprint's strided subsample: chunks are
# already row-bounded, so a smaller cap than panel_fingerprint's keeps
# the per-commit hashing cost (and, for device panels, the D2H sample
# transfer on the committer thread) negligible next to the result fetch
CHUNK_FP_MAX_SIDE = 128


def chunk_sample_steps(n_rows: int, n_cols: int,
                       max_side: int = CHUNK_FP_MAX_SIDE):
    """(row_step, col_step) of the deterministic strided subsample a
    chunk fingerprint hashes.  Shared by every residency's sampler
    (device slice, host array, streamed source rows) so npz/host/device
    walks fingerprint a chunk's rows identically."""
    return (max(1, -(-int(n_rows) // max_side)),
            max(1, -(-int(n_cols) // max_side)))


def chunk_fingerprint(sample: np.ndarray, n_rows: int, n_cols: int) -> str:
    """Content fingerprint of one chunk's rows (ISSUE 15).

    ``sample`` is the chunk's strided subsample (``chunk_sample_steps``
    over rows ``[lo, hi)`` and the chunk's DATA columns) — raw bit
    patterns, so NaN placement counts, exactly like
    :func:`panel_fingerprint` but per chunk.  The delta planner
    (:mod:`.delta`) compares these across two panels to classify a chunk
    clean (identical rows — adopt the committed result), warm (history
    grew, prefix identical), or dirty (revised).  Same trust argument as
    the panel fingerprint: a mismatch always recomputes; a collision
    only risks adopting a chunk that agrees on every sampled byte.
    """
    sample = np.ascontiguousarray(sample)
    h = hashlib.sha256()
    h.update(f"chunk{int(n_rows)}x{int(n_cols)}:{sample.dtype}".encode())
    h.update(sample.tobytes())
    return h.hexdigest()[:16]


def _git_commit(root: Optional[str] = None) -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "-C", root or os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
             "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except (OSError, subprocess.SubprocessError):
        return None


# -- disk-fault seam (ISSUE 17) ---------------------------------------------
# reliability.faultinject installs a hook here so tier-1 CPU tests can
# drive EIO / ENOSPC / torn-at-fsync faults through the real durable
# write paths (journal shards, serving write-ahead records, stored
# results) without a faulty device.  Production never sets a hook; the
# consult is a single None check.

_disk_fault_hook: Optional[Callable] = None


def set_disk_fault_hook(hook: Optional[Callable]) -> Optional[Callable]:
    """Install (or clear, with None) the process-wide disk-fault hook;
    returns the previous hook so tests can restore it.  The hook is
    called as ``hook(path, kind)`` before each guarded durable write and
    answers ``None``/``"pass"`` (write normally), ``"eio"``/``"enospc"``
    (raise the matching ``OSError`` before any bytes land), or
    ``"torn"`` (write, then truncate the final file to a prefix — a
    lying fsync)."""
    global _disk_fault_hook
    prev = _disk_fault_hook
    _disk_fault_hook = hook
    return prev


def consult_disk_fault(path: str, kind: str) -> Optional[str]:
    """Ask the installed hook about one durable write (see
    :func:`set_disk_fault_hook`).  Raises the injected ``OSError`` for
    ``eio``/``enospc``; returns ``"torn"`` when the caller must tear the
    file AFTER its replace lands, else None."""
    hook = _disk_fault_hook
    if hook is None:
        return None
    verdict = hook(path, kind)
    if verdict in (None, "pass"):
        return None
    if verdict == "eio":
        raise OSError(errno.EIO,
                      f"injected I/O error on {kind} write", path)
    if verdict == "enospc":
        raise OSError(errno.ENOSPC,
                      f"injected no-space error on {kind} write", path)
    if verdict == "torn":
        return "torn"
    raise ValueError(f"unknown disk-fault verdict {verdict!r}")


def tear_after_replace(path: str) -> None:
    """Truncate a just-replaced durable file to a half prefix — the
    "fsync lied" fault: the rename landed but the device persisted only
    part of the data.  Readers must treat the file as torn (CRC/npz
    parse failure), never as silently shorter data."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, size // 2))


def durable_replace(path: str, write: Callable, *,
                    suffix: Optional[str] = None,
                    fault_kind: str = "durable") -> None:
    """The ONE durable-file primitive: ``write(f)`` into a hidden tmp in
    the target's directory, fsync, ``os.replace`` — the final path holds
    a whole file (or its previous content), never a torn write, and a
    crash leaves only a hidden ``.tmp-*`` orphan every reader ignores.
    Shared by the journal's shard/manifest writes, adoption's byte
    splices, and the npz append helpers, so the crash-safety sequence
    lives in one place (which is also why the disk-fault seam guards
    exactly here — ``fault_kind`` names the write class for the hook)."""
    verdict = consult_disk_fault(path, fault_kind)
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(
        dir=d, prefix=".tmp-",
        suffix=os.path.basename(path) if suffix is None else suffix)
    try:
        with os.fdopen(fd, "wb") as f:
            write(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if verdict == "torn":
        tear_after_replace(path)


def _atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp -> fsync -> ``os.replace``: the file is whole or absent."""
    durable_replace(path, lambda f: f.write(data))


class LoadedChunk:
    """A committed chunk rehydrated from its shard (duck-types the result
    pieces ``fit_chunked`` assembles: ``params`` / ``neg_log_likelihood`` /
    ``converged`` / ``iters`` / ``status`` / ``meta``)."""

    __slots__ = ("params", "neg_log_likelihood", "converged", "iters",
                 "status", "meta")

    def __init__(self, z, entry: dict):
        self.params = z["params"]
        self.neg_log_likelihood = z["nll"]
        self.converged = z["converged"]
        self.iters = z["iters"]
        self.status = z["status"]
        self.meta = {"resumed_from_journal": True, "lo": entry["lo"],
                     "hi": entry["hi"]}


class ChunkJournal:
    """Directory-backed chunk journal (see module docstring).

    ``resume``: ``"auto"`` adopts a compatible existing manifest (and
    starts fresh when none exists), ``"require"`` demands one,
    ``"never"`` ignores any prior state and starts a fresh run (existing
    entries are dropped from the new manifest; shard files are
    overwritten as their chunks recommit).  Stale and torn manifests
    raise under every mode — deleting a journal is the operator's
    explicit act, never a side effect.

    ``process_index`` selects the namespace: process 0 owns the job-level
    ``manifest.json`` at the directory root; every other process works
    under ``proc_{i:05d}/`` with a manifest named for it, so concurrent
    multi-host writers never race on one file.  ``shard_index`` (sharded
    chunk walks) namespaces one lane of ONE job the same way — the journal
    lives under ``shard_{i:05d}/`` with a manifest named for the shard,
    regardless of process (a shard id is globally unique across the
    mesh's processes), and the job-level root ``manifest.json`` is written
    only by :func:`merge_job_manifest` after the lanes join.  A shard
    journal whose recorded span (``extra`` keys ``shard_lo``/``shard_hi``/
    ``n_shards``) does not match the new run's lane layout is STALE: the
    mesh changed, and resuming would replay another lane's boundaries.

    ``commit_hook(event, lo)`` is a test/fault-injection surface called
    with ``"shard_written"`` (shard durable, manifest not yet updated) and
    ``"committed"`` (manifest updated) — ``reliability.faultinject`` uses
    it to kill the process at either point.
    """

    # lock-discipline contract (tools/lint lock-map): the pipelined
    # committer commits from its worker thread while the driver reads
    # resume state and elastic lanes adopt entries cross-namespace —
    # the manifest map and its index mutate only under the reentrant
    # _mu (single-WRITER protocol unchanged: one committer between
    # submit and drain).
    _protected_by_ = {
        "_manifest": "_mu",
        "_by_lo": "_mu",
        "resumed_entries": "_mu",
    }

    def __init__(
        self,
        directory: str,
        *,
        config_hash: str,
        panel_fingerprint: str,
        n_rows: int,
        chunk_rows: int,
        resume: str = "auto",
        process_index: int = 0,
        shard_index: Optional[int] = None,
        extra: Optional[dict] = None,
        commit_hook: Optional[Callable[[str, int], None]] = None,
        chunk_fp: Optional[Callable[[int, int], str]] = None,
    ):
        if resume not in RESUME_MODES:
            raise ValueError(f"resume must be one of {RESUME_MODES}, got {resume!r}")
        self.process_index = int(process_index)
        self.shard_index = None if shard_index is None else int(shard_index)
        root = os.path.abspath(directory)
        if self.shard_index is not None:
            # one lane of a sharded walk: shard ids are globally unique
            # across the mesh's processes, so the shard namespace alone
            # keeps concurrent writers apart (no proc_ nesting needed)
            self.dir = os.path.join(root, f"shard_{self.shard_index:05d}")
        else:
            self.dir = root if self.process_index == 0 else os.path.join(
                root, f"proc_{self.process_index:05d}")
        os.makedirs(self.dir, exist_ok=True)
        if self.shard_index is not None:
            manifest_name = f"manifest.shard_{self.shard_index:05d}.json"
        elif self.process_index == 0:
            manifest_name = MANIFEST
        else:
            manifest_name = f"manifest.proc_{self.process_index:05d}.json"
        self.manifest_path = os.path.join(self.dir, manifest_name)
        self.config_hash = config_hash
        self.panel_fingerprint = panel_fingerprint
        self.n_rows = int(n_rows)
        self.run_id = uuid.uuid4().hex[:12]  # lint: nondet(run identity metadata, never hashed into results)
        self._commit_hook = commit_hook
        # per-chunk content fingerprint callback (ISSUE 15): the driver
        # supplies a sampler over ITS panel residency; every committed
        # entry then records `chunk_fingerprint`, the identity a later
        # delta walk diffs to adopt unchanged chunks.  None (multi-process
        # global arrays, external callers) simply leaves the field off —
        # resumable as ever, not delta-eligible.
        self._chunk_fp = chunk_fp
        self.resumed_entries = 0
        # the pipelined chunk driver commits from a background committer
        # thread while the driver thread reads resume state
        # (committed / next_committed_lo); one reentrant lock keeps the
        # manifest map coherent without changing the single-WRITER protocol
        # (the committer is the only writer between submit and drain)
        self._mu = threading.RLock()

        prior = self._load_manifest() if resume != "never" else None
        if resume == "never":
            # a torn/stale manifest still must not be silently destroyed:
            # surface it even though we will not resume from it
            self._load_manifest()
        if resume == "require" and prior is None:
            raise JournalError(
                f"resume='require' but no manifest at {self.manifest_path}")
        if prior is not None and self.shard_index is not None:
            # a shard journal belongs to ONE lane layout: if the mesh (and
            # with it this shard's span) changed, replaying these chunks
            # would splice another lane's boundaries into the new walk
            pex = prior.get("extra") or {}
            nex = dict(extra or {})
            bad = [k for k in ("shard_lo", "shard_hi", "n_shards")
                   if k in nex and pex.get(k) != nex[k]]
            if bad:
                raise StaleJournalError(
                    f"{self.manifest_path} was written under a different "
                    f"shard layout ({'; '.join(f'{k} {pex.get(k)} != {nex[k]}' for k in bad)}). "
                    "Resume a sharded job with the same mesh/shard count, "
                    "or point checkpoint_dir at a fresh directory.")
        if prior is not None:
            self._manifest = prior
            head = _git_commit()
            if head and prior.get("git_commit") and head != prior["git_commit"]:
                # same config hash across a code upgrade can still mean
                # different numerics (a changed model default); surface it —
                # the operator decides whether mixed-code chunks are fine
                import warnings

                warnings.warn(
                    f"resuming journal {self.manifest_path} written at git "
                    f"commit {prior['git_commit'][:12]} from {head[:12]}: "
                    "committed chunks were fitted by the older code",
                    stacklevel=3,
                )
            self._manifest.setdefault("resumes", []).append(
                {"run_id": self.run_id, "at": time.time(),  # lint: nondet(resume-history wall-clock metadata)
                 "git_commit": head})
        else:
            self._manifest = {
                "journal_version": JOURNAL_VERSION,
                "run_id": self.run_id,
                "created_at": time.time(),  # lint: nondet(manifest wall-clock metadata; never in fitted bytes)
                "git_commit": _git_commit(),
                "config_hash": config_hash,
                "panel_fingerprint": panel_fingerprint,
                "n_rows": self.n_rows,
                "chunk_rows": int(chunk_rows),
                "process_index": self.process_index,
                **({"shard_index": self.shard_index}
                   if self.shard_index is not None else {}),
                "extra": dict(extra or {}),
                "resumes": [],
                "chunks": [],
            }
            self._write_manifest()
        self._by_lo = {e["lo"]: e for e in self._manifest["chunks"]}

    # -- manifest I/O -------------------------------------------------------

    def _load_manifest(self) -> Optional[dict]:
        if not os.path.exists(self.manifest_path):
            return None
        try:
            with open(self.manifest_path, "rb") as f:
                m = json.loads(f.read().decode())
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise TornManifestError(
                f"{self.manifest_path} does not parse ({e}); a mid-commit "
                "crash tore the write. Inspect/remove the journal directory "
                "explicitly — it will not be silently overwritten."
            ) from e
        mismatches = []
        if m.get("config_hash") != self.config_hash:
            mismatches.append(
                f"config_hash {m.get('config_hash')} != {self.config_hash}")
        if m.get("panel_fingerprint") != self.panel_fingerprint:
            mismatches.append(
                f"panel_fingerprint {m.get('panel_fingerprint')} != "
                f"{self.panel_fingerprint}")
        if int(m.get("n_rows", -1)) != self.n_rows:
            mismatches.append(f"n_rows {m.get('n_rows')} != {self.n_rows}")
        if mismatches:
            raise StaleJournalError(
                f"{self.manifest_path} was written by a different run "
                f"({'; '.join(mismatches)}). Resuming would splice rows "
                "fitted under a different panel/config into this result; "
                "point checkpoint_dir at a fresh directory or remove the "
                "stale journal explicitly."
            )
        return m

    def _write_manifest(self) -> None:
        # _mu is reentrant: callers already hold it, and taking it here
        # keeps the declared lock-map discipline lexically visible
        with self._mu:
            # lint: nondet(manifest wall-clock metadata; never in fitted bytes)
            self._manifest["updated_at"] = time.time()
            _atomic_write_bytes(
                self.manifest_path,
                (json.dumps(self._manifest, indent=1,
                            sort_keys=True) + "\n").encode())

    # -- chunk lifecycle ----------------------------------------------------

    def _shard_name(self, lo: int, hi: int) -> str:
        return f"chunk_{lo:09d}_{hi:09d}.npz"

    def committed(self, lo: int) -> Optional[dict]:
        """The committed manifest entry starting at row ``lo``, if any."""
        with self._mu:
            e = self._by_lo.get(int(lo))
            return e if e is not None and e["status"] == "committed" else None

    def next_committed_lo(self, lo: int) -> Optional[int]:
        """Smallest committed-chunk start strictly beyond ``lo`` — the
        boundary a recomputing walk must not run past."""
        with self._mu:
            starts = [e["lo"] for e in self._manifest["chunks"]
                      if e["status"] == "committed" and e["lo"] > int(lo)]
        return min(starts) if starts else None

    def committed_crossing(self, pos: int) -> Optional[int]:
        """``hi`` of the once-committed chunk that strictly contains row
        ``pos`` (``lo < pos < hi``), or None.  The elastic steal path
        (ISSUE 11) must never split a span inside such a chunk — a
        previous run's OOM backoff can leave off-grid boundaries — or
        thief and victim would both compute its rows.  ``shard-lost``
        entries (a committed chunk whose npz tore) count too: the walk
        recomputes them as FORCED boundaries pinned to the recorded
        ``[lo, hi)``, dispatching past any narrower steal split."""
        pos = int(pos)
        with self._mu:
            for e in self._manifest["chunks"]:
                if e["status"] in ("committed", "shard-lost") \
                        and e["lo"] < pos < e["hi"]:
                    return int(e["hi"])
        return None

    def load_chunk(self, entry: dict) -> Optional[LoadedChunk]:
        """Rehydrate a committed chunk; ``None`` (recompute) when the shard
        is missing or unreadable — a shard torn by a crash downgrades to a
        recompute, never to corrupt rows."""
        path = os.path.join(self.dir, entry["shard"])
        try:
            with np.load(path, allow_pickle=False) as z:
                piece = LoadedChunk({k: z[k] for k in
                                     ("params", "nll", "converged", "iters",
                                      "status")}, entry)
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            with self._mu:
                entry["status"] = "shard-lost"
                self._write_manifest()
                self._by_lo.pop(entry["lo"], None)
            return None
        if piece.params.shape[0] != entry["hi"] - entry["lo"]:
            with self._mu:
                entry["status"] = "shard-lost"
                self._write_manifest()
                self._by_lo.pop(entry["lo"], None)
            return None
        with self._mu:  # elastic lanes may ADOPT from a peer namespace
            self.resumed_entries += 1  # concurrently (ISSUE 11); resumed =
        obs.counter("journal.chunks_resumed").inc()  # actually rehydrated
        return piece

    def _record(self, entry: dict) -> None:
        with self._mu:
            self._manifest["chunks"] = [
                e for e in self._manifest["chunks"] if e["lo"] != entry["lo"]]
            self._manifest["chunks"].append(entry)
            self._manifest["chunks"].sort(key=lambda e: e["lo"])
            self._by_lo[entry["lo"]] = entry
            self._write_manifest()
        if self._commit_hook is not None:
            # "committed" fires only for durable result chunks: a TIMEOUT
            # mark is bookkeeping, and kill_after_commits counting it would
            # shift the crash window the harness means to exercise
            event = ("committed" if entry["status"] == "committed"
                     else "timeout_recorded")
            self._commit_hook(event, entry["lo"])

    def commit_chunk(self, lo: int, hi: int, arrays: dict, **info) -> dict:
        """Write the shard durably, THEN name it in the manifest."""
        t0 = time.perf_counter()
        lo, hi = int(lo), int(hi)
        shard = self._shard_name(lo, hi)
        path = os.path.join(self.dir, shard)
        durable_replace(path, lambda f: np.savez(f, **arrays),
                        suffix=".npz")
        if self._commit_hook is not None:
            self._commit_hook("shard_written", lo)
        if self._chunk_fp is not None and "chunk_fingerprint" not in info:
            # computed on the committer thread, next to the result fetch
            # (a device panel's sampler pays a small D2H there, never on
            # the driver's dispatch path)
            info["chunk_fingerprint"] = self._chunk_fp(lo, hi)
        entry = {"lo": lo, "hi": hi, "status": "committed", "shard": shard,
                 "run_id": self.run_id, "committed_at": time.time(), **info}  # lint: nondet(commit wall-clock metadata; never in fitted bytes)
        self._record(entry)
        commit_s = time.perf_counter() - t0
        obs.histogram("journal.commit_s").observe(commit_s)
        obs.event("journal.commit", lo=lo, hi=hi,
                  commit_s=round(commit_s, 6))
        return entry

    def adopt_chunks(self, items) -> list:
        """Batch-commit ADOPTED chunks (ISSUE 15): every shard is written
        durably first (tmp -> fsync -> replace, like any commit), then
        ONE manifest update names them all.  Write-ahead ordering is
        preserved — a crash mid-batch leaves orphan shards the next
        delta walk simply re-adopts — while the delta walk's fixed cost
        drops from N manifest rewrites to one (the adoption path is the
        90%-of-chunks path; per-chunk manifest churn there would eat the
        speedup adoption exists to provide).

        ``items`` is ``[(lo, hi, payload, info), ...]`` where ``payload``
        is either a dict of result arrays (serialized like any commit) or
        a PATH to an existing shard npz whose bytes are copied verbatim —
        the adoption fast path: "byte-for-byte" is then literal, and the
        delta walk never round-trips the prior results through
        numpy.  Returns the recorded entries.  The commit hook sees every
        ``shard_written`` as shards land and every ``committed`` after
        the single manifest write, in item order.
        """
        def _splice(payload):
            def write(f):
                if isinstance(payload, (str, os.PathLike)):
                    with open(payload, "rb") as srcf:
                        while True:
                            block = srcf.read(1 << 20)
                            if not block:
                                break
                            f.write(block)
                else:
                    np.savez(f, **payload)
            return write

        entries = []
        for lo, hi, payload, info in items:
            t0 = time.perf_counter()
            lo, hi = int(lo), int(hi)
            shard = self._shard_name(lo, hi)
            path = os.path.join(self.dir, shard)
            durable_replace(path, _splice(payload), suffix=".npz")
            if self._commit_hook is not None:
                self._commit_hook("shard_written", lo)
            info = dict(info)
            if self._chunk_fp is not None and \
                    "chunk_fingerprint" not in info:
                info["chunk_fingerprint"] = self._chunk_fp(lo, hi)
            entries.append({"lo": lo, "hi": hi, "status": "committed",
                            "shard": shard, "run_id": self.run_id,
                            "committed_at": time.time(), **info})  # lint: nondet(commit wall-clock metadata; never in fitted bytes)
            obs.histogram("journal.commit_s").observe(
                time.perf_counter() - t0)
        with self._mu:
            keep = {e["lo"] for e in entries}
            self._manifest["chunks"] = [
                e for e in self._manifest["chunks"] if e["lo"] not in keep]
            self._manifest["chunks"].extend(entries)
            self._manifest["chunks"].sort(key=lambda e: e["lo"])
            for e in entries:
                self._by_lo[e["lo"]] = e
            self._write_manifest()
        for e in entries:
            if self._commit_hook is not None:
                self._commit_hook("committed", e["lo"])
            obs.event("journal.commit", lo=e["lo"], hi=e["hi"],
                      adopted=True)
        return entries

    def mark_timeout(self, lo: int, hi: int, **info) -> dict:
        """Record a chunk that overran its budget (no shard: a resume
        retries it — ``committed()`` skips non-committed entries)."""
        entry = {"lo": int(lo), "hi": int(hi), "status": "TIMEOUT",
                 "run_id": self.run_id, "committed_at": time.time(), **info}  # lint: nondet(commit wall-clock metadata; never in fitted bytes)
        self._record(entry)
        obs.event("journal.timeout", lo=int(lo), hi=int(hi))
        return entry

    def record_telemetry(self, telemetry: dict) -> None:
        """Embed the run's telemetry summary in the manifest (atomically
        rewritten), so post-mortems read compile/execute span times,
        counters, and peak memory from the journal alone
        (``tools/inspect_journal.py`` prints it, ``tools/obs_report.py
        --manifest`` validates it)."""
        with self._mu:
            self._manifest["telemetry"] = telemetry
            self._write_manifest()

    # -- summary ------------------------------------------------------------

    def accounting(self) -> dict:
        """Job-level journal metadata for result ``meta`` / bench artifacts."""
        with self._mu:
            chunks = list(self._manifest["chunks"])
        return {
            "dir": self.dir,
            "manifest": os.path.basename(self.manifest_path),
            "run_id": self.run_id,
            "config_hash": self.config_hash,
            "process_index": self.process_index,
            "chunks_committed": sum(1 for e in chunks
                                    if e["status"] == "committed"),
            "chunks_timeout": sum(1 for e in chunks
                                  if e["status"] == "TIMEOUT"),
            "chunks_resumed": self.resumed_entries,
            "resumes": len(self._manifest.get("resumes", [])),
        }


def check_root_manifest(directory: str, *, config_hash: str,
                        panel_fingerprint: str, n_rows: int) -> None:
    """Raise if the job-level ``manifest.json`` at ``directory`` belongs to
    a DIFFERENT job (config hash / panel fingerprint / row count mismatch)
    or is torn; no-op when absent or matching.

    A sharded walk's lanes only ever open shard namespaces, so without
    this check a foreign root manifest would survive untouched until the
    merge destroyed it — the single-device path rejects the same
    situation at ``ChunkJournal`` construction.
    """
    root_mp = os.path.join(os.path.abspath(directory), MANIFEST)
    if not os.path.exists(root_mp):
        return
    try:
        with open(root_mp, "rb") as f:
            prior = json.loads(f.read().decode())
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise TornManifestError(
            f"{root_mp} does not parse ({e}); inspect/remove the journal "
            "directory explicitly — it will not be silently overwritten "
            "by a shard merge.") from e
    mismatches = []
    if prior.get("config_hash") != config_hash:
        mismatches.append("config_hash")
    if prior.get("panel_fingerprint") != panel_fingerprint:
        mismatches.append("panel_fingerprint")
    if int(prior.get("n_rows", -1)) != int(n_rows):
        mismatches.append("n_rows")
    if mismatches:
        raise StaleJournalError(
            f"root manifest {root_mp} belongs to a different job "
            f"({', '.join(mismatches)} mismatch); merging this sharded "
            "walk would destroy that job's durable state — use a fresh "
            "checkpoint_dir or remove the stale journal explicitly.")


class ShardJournalView:
    """One elastic lane's journal handle: WRITE to its own shard namespace,
    READ committed state across EVERY namespace of the job (ISSUE 11).

    Under elastic reassignment a chunk's durable shard can live in any
    lane's namespace — the lane that COMPUTED it (tagged ``owner`` in its
    manifest entry), which after a quarantine, a steal, or a resumed
    rebalanced job need not be the lane whose nominal span contains it.
    The walk's resume/skip logic (``committed`` / ``load_chunk`` /
    ``next_committed_lo`` / ``committed_crossing``) therefore consults the
    lane's own journal first, then every peer namespace, ADOPTING foreign
    commits instead of recomputing them — "resume replays only
    truly-uncommitted work".  Writes (``commit_chunk`` / ``mark_timeout``)
    go exclusively to the lane's own namespace, so the journal's
    single-writer-per-namespace protocol is untouched; a loaded entry is
    always rehydrated (and, on a torn shard, downgraded) by the journal
    that OWNS it, so its manifest bookkeeping stays correct.
    """

    def __init__(self, own: ChunkJournal, peers):
        self.own = own
        self.peers = [p for p in peers if p is not own]
        # lo -> journal holding the committed entry last returned for it;
        # load_chunk must dispatch to that journal (paths are
        # namespace-relative, and a torn-shard downgrade must hit the
        # owning manifest).  One view per lane; the rare concurrent writer
        # is a watchdog-abandoned worker re-probing the same lo, which
        # writes the same value.
        self._found_in: dict = {}

    def committed(self, lo: int):
        e = self.own.committed(lo)
        if e is not None:
            self._found_in[int(lo)] = self.own
            return e
        for j in self.peers:
            e = j.committed(lo)
            if e is not None:
                self._found_in[int(lo)] = j
                return e
        return None

    def load_chunk(self, entry: dict):
        j = self._found_in.get(int(entry["lo"]), self.own)
        return j.load_chunk(entry)

    def next_committed_lo(self, lo: int):
        cands = [j.next_committed_lo(lo) for j in (self.own, *self.peers)]
        cands = [c for c in cands if c is not None]
        return min(cands) if cands else None

    def committed_crossing(self, pos: int):
        for j in (self.own, *self.peers):
            x = j.committed_crossing(pos)
            if x is not None:
                return x
        return None

    def commit_chunk(self, *args, **kwargs):
        return self.own.commit_chunk(*args, **kwargs)

    def mark_timeout(self, *args, **kwargs):
        return self.own.mark_timeout(*args, **kwargs)


class MergeWarmer:
    """Overlap the sharded root-manifest merge with the last lanes' tails.

    A sharded walk's fast lanes finish (and atomically commit their shard
    manifests) while stragglers are still computing; the merge used to
    start only after EVERY lane joined, re-reading and re-parsing all the
    shard manifests on the critical path.  The warmer is a read-only
    background poller shard/process 0 runs while its lanes are still out:
    it watches each ``shard_?????/manifest.shard_?????.json``, parses any
    version it has not seen (keyed by ``(mtime_ns, size)`` — shard
    manifests are written by atomic replace, so a stat change IS a new
    complete version), and hands the cache to
    :func:`merge_job_manifest(cache=...)`, which re-reads only manifests
    that changed after their last warm parse.

    The single-writer rule is untouched: the warmer never writes anything
    — the root manifest is still written once, by the merge, after the
    barrier.  A parse failure is simply not cached (the merge re-reads
    and raises its own, properly attributed, error).
    """

    def __init__(self, directory: str, n_shards: int,
                 interval_s: float = 0.05):
        self.root = os.path.abspath(directory)
        self.paths = [
            os.path.join(self.root, f"shard_{sid:05d}",
                         f"manifest.shard_{sid:05d}.json")
            for sid in range(int(n_shards))]
        self.interval_s = float(interval_s)
        self._cache: dict = {}  # path -> ((mtime_ns, size), manifest)
        self._stop = threading.Event()
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="merge-warmer")
        self._worker.start()

    def _poll_once(self) -> None:
        for path in self.paths:
            try:
                st = os.stat(path)
            except OSError:
                continue  # lane has not committed its manifest yet
            sig = (st.st_mtime_ns, st.st_size)
            hit = self._cache.get(path)
            if hit is not None and hit[0] == sig:
                continue
            try:
                with open(path, "rb") as f:
                    m = json.loads(f.read().decode())
            except (OSError, json.JSONDecodeError, UnicodeDecodeError):
                continue  # merge will re-read and attribute the error
            self._cache[path] = (sig, m)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._poll_once()

    def stop(self) -> dict:
        """Stop polling and return the warm cache (one final sweep first,
        so lanes that committed in the last interval are still warm)."""
        self._stop.set()
        self._worker.join(timeout=30.0)
        self._poll_once()
        return self._cache


def merge_job_manifest(
    directory: str,
    *,
    config_hash: str,
    panel_fingerprint: str,
    n_rows: int,
    chunk_rows: int,
    spans,
    telemetry: Optional[dict] = None,
    extra: Optional[dict] = None,
    cache: Optional[dict] = None,
    rebalance: Optional[dict] = None,
) -> dict:
    """Fold the shard-namespace manifests of a sharded walk into the ONE
    job-level ``manifest.json`` at the journal root, and return the merged
    accounting.

    Called by shard/process 0 AFTER the lanes join — it is the only writer
    of the root manifest, mirroring the per-process single-writer rule.
    ``spans`` is the run's lane layout (``plan.shard_spans``); a shard
    manifest recorded under a different job (config hash, fingerprint,
    row count) or a different lane layout is STALE and raises rather than
    splicing foreign chunks into the job record.  Missing shard manifests
    are tolerated (a lane that crashed before its first commit, or another
    process's lane on a non-shared filesystem): their chunks simply stay
    pending, and a resume recomputes them.

    Merged chunk entries keep their npz shards where the lanes wrote them
    — the ``shard`` path is re-rooted relative to the journal root and
    each entry gains its ``shard_id`` — so the merged manifest itself
    satisfies the resume contract: the same sharded job resumes lane by
    lane from the shard namespaces, and a later SINGLE-device walk of the
    same (panel, config) can adopt the merged root manifest directly
    (plan knobs are excluded from the config hash; the chunk grid is
    shared by construction).

    ``cache`` (a :meth:`MergeWarmer.stop` result) short-circuits the read
    and parse of shard manifests whose ``(mtime_ns, size)`` signature is
    unchanged since the warmer saw them — the merge I/O then overlapped
    the last lanes' tails instead of following them.  Validation runs on
    the cached parse exactly as on a fresh read.

    **Elastic reconciliation** (ISSUE 11): a quarantined or stolen-from
    lane's chunks are committed by SURVIVORS into the survivors'
    namespaces, each entry tagged with its computing ``owner`` lane.  The
    merge reconciles by row range: per chunk ``lo`` a ``committed`` entry
    wins over a stale ``TIMEOUT``/pending duplicate from another
    namespace, every entry keeps its namespace-rooted npz path plus its
    ``owner`` tag, each ``shards[*]`` entry records its ``owner`` identity
    and how many of its committed chunks were reassigned in from other
    lanes' nominal spans, and the driver's quarantine/steal record lands
    as a top-level ``rebalance`` block (``tools/obs_report.py --check``
    validates all three; ``tools/advise_budget.py`` turns them into
    ``lane_retries``/``rebalance_threshold`` advice).
    """
    root = os.path.abspath(directory)
    # the root manifest is another job's write-ahead record until proven
    # otherwise: a sharded walk's lanes only ever open shard namespaces,
    # so the merge is the last line of defense — mirror ChunkJournal's
    # never-silently-overwrite contract (the driver also calls
    # check_root_manifest up front to fail BEFORE any compute)
    check_root_manifest(root, config_hash=config_hash,
                        panel_fingerprint=panel_fingerprint, n_rows=n_rows)
    spans = [(int(lo), int(hi)) for lo, hi in spans]
    shards, chunks = [], []
    run_id = None
    for sid, (slo, shi) in enumerate(spans):
        d = f"shard_{sid:05d}"
        mp = os.path.join(root, d, f"manifest.{d}.json")
        if not os.path.exists(mp):
            shards.append({"shard_id": sid, "lo": slo, "hi": shi,
                           "dir": d, "manifest": None, "run_id": None,
                           "chunks_committed": 0, "chunks_timeout": 0,
                           "resumes": 0})
            continue
        m = None
        if cache is not None:
            hit = cache.get(mp)
            if hit is not None:
                try:
                    st = os.stat(mp)
                    if (st.st_mtime_ns, st.st_size) == hit[0]:
                        m = hit[1]  # warm parse still current
                except OSError:
                    pass
        if m is None:
            try:
                with open(mp, "rb") as f:
                    m = json.loads(f.read().decode())
            except (json.JSONDecodeError, UnicodeDecodeError) as e:
                raise TornManifestError(
                    f"shard manifest {mp} does not parse ({e}); "
                    "inspect/remove the journal directory explicitly."
                ) from e
        mismatches = []
        if m.get("config_hash") != config_hash:
            mismatches.append("config_hash")
        if m.get("panel_fingerprint") != panel_fingerprint:
            mismatches.append("panel_fingerprint")
        if int(m.get("n_rows", -1)) != int(n_rows):
            mismatches.append("n_rows")
        mex = m.get("extra") or {}
        if (mex.get("shard_lo"), mex.get("shard_hi")) != (slo, shi) or \
                mex.get("n_shards") != len(spans):
            mismatches.append("shard layout")
        if mismatches:
            raise StaleJournalError(
                f"shard manifest {mp} belongs to a different job/layout "
                f"({', '.join(mismatches)} mismatch); remove the stale "
                "journal explicitly or use a fresh checkpoint_dir.")
        if run_id is None:
            run_id = m.get("run_id")
        entries = []
        for e in m.get("chunks", []):
            e2 = dict(e)
            e2["shard_id"] = sid
            if "shard" in e2:
                e2["shard"] = f"{d}/{e2['shard']}"
            entries.append(e2)
        chunks.extend(entries)
        shards.append({
            "shard_id": sid, "lo": slo, "hi": shi, "dir": d,
            "manifest": os.path.basename(mp), "run_id": m.get("run_id"),
            "chunks_committed": sum(1 for e in entries
                                    if e["status"] == "committed"),
            "chunks_timeout": sum(1 for e in entries
                                  if e["status"] == "TIMEOUT"),
            "resumes": len(m.get("resumes") or []),
        })
    # elastic reconciliation: one entry per chunk lo.  A chunk marked
    # TIMEOUT (or left pending) by one lane and later COMMITTED by another
    # must merge as committed — the committed shard is the durable truth,
    # and a duplicate entry would double-count its rows
    by_lo: dict = {}
    for e in chunks:
        cur = by_lo.get(e["lo"])
        if cur is None or (e["status"] == "committed"
                           and cur["status"] != "committed"):
            by_lo[e["lo"]] = e
    chunks = sorted(by_lo.values(), key=lambda e: e["lo"])
    # per-shard accounting is recomputed from the RECONCILED entries: a
    # TIMEOUT mark another lane later resolved as committed must not
    # linger in its namespace's totals (post-mortems and advise_budget
    # would report a timeout no chunk in the final result has).  Plus the
    # owner accounting: entries in this namespace whose rows fall OUTSIDE
    # its nominal span were reassigned in (a quarantine hand-off or a
    # steal) — a journaled fact read from the manifest alone
    for s in shards:
        sid, (slo, shi) = s["shard_id"], (s["lo"], s["hi"])
        mine = [e for e in chunks if e.get("shard_id") == sid]
        s["chunks_committed"] = sum(1 for e in mine
                                    if e["status"] == "committed")
        s["chunks_timeout"] = sum(1 for e in mine
                                  if e["status"] == "TIMEOUT")
        s["owner"] = sid
        s["chunks_reassigned_in"] = sum(
            1 for e in mine if e["status"] == "committed"
            and not (slo <= e["lo"] and e["hi"] <= shi))
    manifest = {
        "journal_version": JOURNAL_VERSION,
        "run_id": run_id or uuid.uuid4().hex[:12],  # lint: nondet(merge run identity metadata, never hashed)
        "created_at": time.time(),  # lint: nondet(manifest wall-clock metadata; never in fitted bytes)
        "updated_at": time.time(),  # lint: nondet(manifest wall-clock metadata; never in fitted bytes)
        "git_commit": _git_commit(),
        "config_hash": config_hash,
        "panel_fingerprint": panel_fingerprint,
        "n_rows": int(n_rows),
        "chunk_rows": int(chunk_rows),
        "process_index": 0,
        "merged_from_shards": len(spans),
        "extra": dict(extra or {}),
        "resumes": [],
        "chunks": chunks,
        "shards": shards,
    }
    if rebalance is not None:
        manifest["rebalance"] = {
            **rebalance,
            "reassigned_chunks": sum(s["chunks_reassigned_in"]
                                     for s in shards),
        }
    if telemetry is not None:
        manifest["telemetry"] = telemetry
    _atomic_write_bytes(
        os.path.join(root, MANIFEST),
        (json.dumps(manifest, indent=1, sort_keys=True) + "\n").encode())
    obs.event("journal.merged", shards=len(spans),
              chunks=len(chunks))
    return {
        "dir": root,
        "manifest": MANIFEST,
        "run_id": manifest["run_id"],
        "config_hash": config_hash,
        "process_index": 0,
        "merged_shards": len(spans),
        "chunks_committed": sum(s["chunks_committed"] for s in shards),
        "chunks_timeout": sum(s["chunks_timeout"] for s in shards),
        "shards": shards,
        **({"rebalance": manifest["rebalance"]}
           if rebalance is not None else {}),
    }


# ---------------------------------------------------------------------------
# lease records (ISSUE 16: fleet serving's single-writer election)
# ---------------------------------------------------------------------------
# A fleet of FitServer replicas shares ONE checkpoint root, but the root's
# durability story (write-ahead requests, batch journals, results) is a
# single-writer protocol — so exactly one replica may run a server at a
# time.  The lease is built from the primitives this module already
# guarantees:
#
# - **fencing tokens** are allocated by atomic claim manifests:
#   ``<root>/lease_claims/claim_<token>.json`` created with
#   ``O_CREAT | O_EXCL`` — the filesystem arbitrates, exactly one process
#   ever owns a token, and tokens are strictly monotonic (next = highest
#   existing + 1).  The HIGHEST claim is the lease holder.
# - **the lease record** ``<root>/lease.json`` is the holder's heartbeat,
#   written via :func:`durable_replace` (whole or absent, never torn).
#
# Liveness: a lease is LIVE while its highest claim is fresh — either the
# lease record's ``heartbeat_at`` or the claim file's mtime is within
# ``ttl_s``.  A SIGKILLed holder simply stops heartbeating; after ttl a
# standby claims token+1 and takes over.  A restarted zombie holding the
# OLD token fails :meth:`Lease.check` on its next write — stale-token
# writers lose loudly (:class:`FencedError`), they never splice bytes
# into the new holder's root.

LEASE_FILE = "lease.json"
LEASE_CLAIMS_DIR = "lease_claims"


def _lease_path(root: str) -> str:
    return os.path.join(root, LEASE_FILE)


def _claims_dir(root: str) -> str:
    return os.path.join(root, LEASE_CLAIMS_DIR)


def _claim_path(root: str, token: int) -> str:
    return os.path.join(_claims_dir(root), f"claim_{int(token):08d}.json")


def highest_claim(root: str) -> int:
    """The highest fencing token ever claimed under ``root`` (0 = none)."""
    top = 0
    try:
        for fn in os.listdir(_claims_dir(root)):
            if fn.startswith("claim_") and fn.endswith(".json"):
                try:
                    top = max(top, int(fn[len("claim_"):-len(".json")]))
                except ValueError:
                    pass
    except OSError:
        pass
    return top


def read_lease(root: str) -> Optional[dict]:
    """The current lease record, or None when absent/unreadable.

    ``lease.json`` is written via :func:`durable_replace`, so an
    unreadable record only happens under manual corruption — token
    monotonicity (and therefore fencing safety) rests on the claim
    manifests, never on this record, so unreadable degrades to None."""
    try:
        with open(_lease_path(root)) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return rec if isinstance(rec, dict) else None


def lease_is_live(root: str, *, now: Optional[float] = None) -> bool:
    """Whether SOME holder currently owns the root (highest claim fresh).

    The freshness source is the lease record's heartbeat when it carries
    the highest token, else the highest claim file's mtime (the window
    between a claim landing and its first heartbeat write)."""
    top = highest_claim(root)
    if top == 0:
        return False
    now = time.time() if now is None else now  # lint: nondet(lease liveness is wall-clock by design; never fitted bytes)
    rec = read_lease(root)
    if rec is not None and int(rec.get("token", 0)) == top:
        if rec.get("released"):
            return False
        ttl = float(rec.get("ttl_s", 5.0))
        return (now - float(rec.get("heartbeat_at", 0.0))) < ttl
    # highest claimant has not heartbeated yet: fresh claim == live
    try:
        claim_path = _claim_path(root, top)
        with open(claim_path) as f:
            claim = json.load(f)
        ttl = float(claim.get("ttl_s", 5.0))
        return (now - os.stat(claim_path).st_mtime) < ttl
    except (OSError, json.JSONDecodeError, ValueError):
        return False


class Lease:
    """A held fleet lease: fencing token + heartbeat record (ISSUE 16).

    Instances come from :func:`acquire_lease`; holders call
    :meth:`heartbeat` at most every ``ttl_s / 3`` and :meth:`check`
    before every durable write they gate.  Both raise
    :class:`FencedError` the moment a higher claim exists — the holder
    must stop writing and step down.
    """

    def __init__(self, root: str, owner: str, token: int, ttl_s: float):
        self.root = os.path.abspath(root)
        self.owner = str(owner)
        self.token = int(token)
        self.ttl_s = float(ttl_s)

    def __repr__(self) -> str:
        return (f"Lease(root={self.root!r}, owner={self.owner!r}, "
                f"token={self.token}, ttl_s={self.ttl_s})")

    def check(self) -> None:
        """Raise :class:`FencedError` unless this token is still the
        highest claim — the gate every fenced write runs behind."""
        top = highest_claim(self.root)
        if top != self.token:
            raise FencedError(
                f"lease token {self.token} (owner {self.owner!r}) is "
                f"fenced: highest claim on {self.root} is {top} — "
                "stale-token writers must stop, not retry")

    def heartbeat(self) -> None:
        """Refresh the lease record's liveness (check first: a fenced
        holder must not resurrect its record over the new holder's)."""
        self.check()
        self._write_record()

    def release(self) -> None:
        """Mark the lease released so a successor acquires immediately
        instead of waiting out the ttl.  No-op once fenced."""
        try:
            self.check()
        except FencedError:
            return
        self._write_record(released=True)

    def _write_record(self, released: bool = False) -> None:
        rec = {
            "token": self.token,
            "owner": self.owner,
            "ttl_s": self.ttl_s,
            "heartbeat_at": time.time(),  # lint: nondet(lease liveness metadata; never fitted bytes)
            "released": bool(released),
        }
        _atomic_write_bytes(
            _lease_path(self.root),
            (json.dumps(rec, indent=1, sort_keys=True) + "\n").encode())


def acquire_lease(root: str, owner: str, *,
                  ttl_s: float = 5.0) -> Optional[Lease]:
    """Try to acquire the root's lease; None while another holder is live.

    The claim write is the election: an atomic hard link onto the next
    token's claim manifest means the filesystem picks exactly one winner
    per token, and a fresh claim counts as live (``lease_is_live``), so
    a racer that lost the claim sees the winner as the holder and backs
    off.  Callers poll — a standby loops ``acquire_lease`` until the
    incumbent's heartbeat goes stale."""
    root = os.path.abspath(root)
    os.makedirs(_claims_dir(root), exist_ok=True)
    for _ in range(64):
        if lease_is_live(root):
            return None
        token = highest_claim(root) + 1
        claim = {
            "token": token,
            "owner": str(owner),
            "ttl_s": float(ttl_s),
            "claimed_at": time.time(),  # lint: nondet(lease liveness metadata; never fitted bytes)
        }
        # the claim must be atomic AS WELL AS exclusive: a racer that
        # lost this token re-checks liveness immediately, and a claim
        # file it can see but not yet parse (created, bytes not landed)
        # would read as dead — letting it claim token+1 and seat TWO
        # winners.  So the bytes land in a hidden tmp first and a hard
        # link performs the election: the link either publishes a whole
        # claim or fails because someone else's whole claim is there.
        fd, tmp = tempfile.mkstemp(dir=_claims_dir(root),
                                   prefix=".tmp-claim-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write((json.dumps(claim, indent=1, sort_keys=True)
                         + "\n").encode())
                f.flush()
                os.fsync(f.fileno())
            try:
                os.link(tmp, _claim_path(root, token))
            except FileExistsError:
                continue  # lost the election for this token; re-evaluate
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass
        lease = Lease(root, owner, token, ttl_s)
        lease._write_record()
        obs.event("lease.acquired", root=root, owner=str(owner),
                  token=token)
        return lease
    return None


