"""Resilient fit execution: sanitize -> fit -> retry ladder -> fallback.

The batch analog of Spark task retry (PAPER.md: per-series numerics ran
inside executor tasks, and a failed task was simply re-run elsewhere).
Here a "task" is a ROW of a monolithic vmapped fit, so recovery is a
gather/re-fit/scatter ladder:

1. **Sanitize** the input panel (``reliability.sanitize``): repair or
   exclude rows no fit can survive (inf, interior NaN, constant, all-NaN).
2. **Primary fit** via the model's public ``fit`` — one compiled program
   over the whole batch, exactly as before.
3. **Retry rung**: rows that came back non-converged or non-finite are
   gathered into a small padded batch (the host-side analog of the
   straggler compaction in ``utils.optim`` — ``optim.retry_cap`` bounds
   the distinct compiled shapes) and re-fit with a larger iteration budget
   and, where the model supports ``init_params``, a deterministically
   perturbed init.
4. **Fallback rung**: rows still failing are re-fit on the conservative
   path — portable ``scan`` backend (no Pallas), no straggler compaction,
   largest budget.  ``utils.linalg.ridge_solve`` independently falls back
   from the unpivoted Cholesky to ``jnp.linalg.solve`` for non-SPD rows.
5. Rows that survive nothing are marked ``DIVERGED`` (NaN params, flagged)
   instead of silently propagating NaNs into downstream aggregates.

Per-row outcomes are reported as :class:`~.status.FitStatus` codes;
``meta`` records what every rung attempted and recovered.
"""

from __future__ import annotations

import inspect
from typing import Callable, NamedTuple, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from .. import obs
from ..utils import optim
from .sanitize import sanitize as _sanitize
from .status import STATUS_DTYPE, FitStatus, status_counts

__all__ = ["RetryRung", "ResilientFitResult", "default_ladder", "resilient_fit"]


class RetryRung(NamedTuple):
    """One rung of the retry ladder."""

    name: str  # label recorded in meta
    status: int  # FitStatus granted to rows this rung rescues
    kwargs: dict  # fit-kwarg overrides (filtered to the fit's signature)
    perturb: float = 0.0  # init perturbation scale (models with init_params)


class ResilientFitResult(NamedTuple):
    """Batched fit output with per-row status and run metadata.

    Field layout extends ``models.base.FitResult``; arrays are host-side
    (the ladder assembles rows across several device programs).
    """

    params: np.ndarray  # [batch, k]
    neg_log_likelihood: np.ndarray  # [batch]
    converged: np.ndarray  # [batch] bool
    iters: np.ndarray  # [batch]
    status: np.ndarray  # [batch] int8 FitStatus codes
    meta: dict


def default_ladder(fit_fn: Callable, base_iters: Optional[int] = None) -> tuple:
    """The standard two-rung ladder, filtered to what ``fit_fn`` accepts.

    Rung 1 (``RETRIED``) re-fits with a LARGER iteration budget (at least
    double the primary fit's ``base_iters`` when known) and a small
    perturbed init; rung 2 (``FALLBACK``) escalates to the portable scan
    backend with compaction disabled and a larger budget still.  Models
    without a ``backend``/``max_iters`` knob simply get whichever
    overrides their signature supports.
    """
    base = int(base_iters) if base_iters else 60
    return (
        RetryRung("retry", int(FitStatus.RETRIED),
                  {"max_iters": max(120, 2 * base)}, perturb=0.05),
        RetryRung("fallback", int(FitStatus.FALLBACK),
                  {"max_iters": max(240, 4 * base), "backend": "scan",
                   "compact": False},
                  perturb=0.2),
    )


def _accepted_kwargs(fit_fn: Callable, kwargs: dict) -> dict:
    """Drop overrides the fit's signature does not accept."""
    try:
        params = inspect.signature(fit_fn).parameters
    except (TypeError, ValueError):  # builtins / C callables: pass through
        return dict(kwargs)
    if any(p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()):
        return dict(kwargs)
    return {k: v for k, v in kwargs.items() if k in params}


def _failed_mask(res) -> np.ndarray:
    """Rows whose fit cannot be trusted: non-converged or non-finite."""
    params = np.asarray(res.params)
    nll = np.asarray(res.neg_log_likelihood)
    conv = np.asarray(res.converged)
    finite = np.isfinite(params).all(axis=-1) & np.isfinite(nll)
    return ~(conv & finite)


def _structurally_excluded(res) -> np.ndarray:
    """Rows the model itself refused (too short / empty): retry cannot help."""
    if res.status is None:
        return np.zeros(np.asarray(res.converged).shape, bool)
    return np.asarray(res.status) == FitStatus.EXCLUDED


def _recoverable_oom(e: BaseException) -> bool:
    """RESOURCE_EXHAUSTED is recoverable one layer up (``fit_chunked``
    backoff) — no crash dump for it here; the lazy import avoids the
    runner<->chunked cycle (chunked imports this module)."""
    from .chunked import is_resource_exhausted

    return is_resource_exhausted(e)


@obs.dump_on_failure("resilient_fit", unless=_recoverable_oom)
def resilient_fit(
    fit_fn: Callable,
    y,
    *,
    policy: str = "impute",
    ladder: Optional[Sequence[RetryRung]] = None,
    sanitize: bool = True,
    max_retry_rows: Optional[int] = None,
    seed: int = 0,
    **fit_kwargs,
) -> ResilientFitResult:
    """Run ``fit_fn(y, **fit_kwargs)`` with sanitization and the retry ladder.

    ``fit_fn`` is any public model fit (``models.arima.fit`` partials
    included) returning a ``FitResult``.  ``policy`` is the sanitizer's
    non-finite policy (``"impute"`` / ``"exclude"`` / ``"raise"``);
    ``sanitize=False`` skips the pass entirely (rows the models reject
    still come back ``EXCLUDED`` via their own status output).  ``ladder``
    overrides :func:`default_ladder`; an empty ladder means failed rows go
    straight to ``DIVERGED``.  ``seed`` drives the deterministic init
    perturbation of retry rungs.

    COST NOTE: every non-converged row enters the ladder, and the default
    fallback rung re-fits on the portable ``scan`` backend — much slower
    per row than the fused path.  A panel where a sizable fraction of rows
    legitimately fails to converge within budget can therefore spend far
    longer in the ladder than in the primary fit.  For latency-critical
    serving, bound the ladder with ``max_retry_rows`` (rows beyond the cap
    skip the ladder and are flagged ``DIVERGED`` directly, ladder rungs
    recorded in ``meta`` either way), pass a custom ``ladder`` without the
    scan rung, or ``ladder=()`` to disable retries entirely.

    An ``align_mode=`` entry in ``fit_kwargs`` (the chunk driver's static
    alignment plan) is forwarded to ``fit_fn`` only when its signature
    accepts it, and is downgraded to ``"general"`` whenever the sanitizer
    actually repaired or excluded rows — the repairs change the panel's
    NaN pattern, so a stronger panel-level claim may no longer hold on
    the cleaned values.

    Healthy rows are fitted bit-identically to a direct ``fit_fn`` call on
    the SANITIZED panel: the ladder only ever re-fits the failed subset,
    scattering recovered rows back without touching their neighbors.  (A
    direct call on the raw panel can differ at f32 fusion level when
    sanitization changes the panel's NaN pattern — the alignment mode, and
    with it the compiled program, is chosen per panel.)
    """
    yb = jnp.asarray(y)
    single = yb.ndim == 1
    if single:
        yb = yb[None, :]
    b = yb.shape[0]

    # static align-mode hint (the chunk driver's per-walk plan): held back
    # from the fit until the sanitizer has run — repairs and exclusions
    # CHANGE the panel's NaN pattern (imputed gaps, inf->NaN edges, rows
    # NaN-ed out), so a panel-level "dense"/"no-trailing" claim may no
    # longer hold on the cleaned values.  Untouched chunks keep the fast
    # plan; touched chunks downgrade to the always-correct "general" path
    # (deterministic per chunk content, so journaled resumes reproduce it)
    align_hint = fit_kwargs.pop("align_mode", None)

    if sanitize:
        rep = _sanitize(yb, policy=policy)
        y_clean, status, san_meta = rep.values, rep.status.copy(), rep.meta
    else:
        y_clean = yb
        status = np.zeros(b, STATUS_DTYPE)
        san_meta = {"policy": "off"}
    if align_hint is not None:
        if san_meta.get("rows_sanitized") or san_meta.get("rows_excluded"):
            align_hint = "general"
        if "align_mode" in _accepted_kwargs(fit_fn, {"align_mode": None}):
            fit_kwargs = {**fit_kwargs, "align_mode": align_hint}

    with obs.span("fit.primary", rows=b):
        res = fit_fn(y_clean, **fit_kwargs)
    params = np.array(res.params)
    nll = np.array(res.neg_log_likelihood)
    conv = np.array(res.converged)
    iters = np.array(res.iters)
    excluded = (status == FitStatus.EXCLUDED) | _structurally_excluded(res)
    status = np.maximum(
        status, np.where(excluded, FitStatus.EXCLUDED, 0)
    ).astype(STATUS_DTYPE)

    failed = _failed_mask(res) & ~excluded
    # ladder size cap: rows past the cap skip the ladder entirely (they
    # stay in ``failed`` and are flagged DIVERGED below), bounding the
    # worst-case ladder cost on mass-non-convergence panels
    retryable = failed.copy()
    over_cap = 0
    if max_retry_rows is not None and int(retryable.sum()) > max_retry_rows:
        skipped = np.nonzero(retryable)[0][max_retry_rows:]
        retryable[skipped] = False
        over_cap = skipped.size
    rungs = (default_ladder(fit_fn, fit_kwargs.get("max_iters"))
             if ladder is None else tuple(ladder))
    # register every rung's counters up front (zero-valued when no row ever
    # enters the ladder) so the run summary always reports the full
    # ladder-rung vocabulary, not just the rungs that happened to fire
    for rung in rungs:
        obs.counter(f"ladder.{rung.name}.attempted")
        obs.counter(f"ladder.{rung.name}.rescued")
    rung_meta = []
    rng = np.random.default_rng(seed)
    supports_init = "init_params" in _accepted_kwargs(
        fit_fn, {"init_params": None}
    )

    for depth, rung in enumerate(rungs):
        idx = np.nonzero(retryable)[0]
        if idx.size == 0:
            break
        # gather the failed subset into an aligned bucket (same contract as
        # the optimizer's straggler compaction: out-of-range pad rows are
        # copies of a real row whose results are dropped on the scatter)
        cap = optim.retry_cap(idx.size)
        pad_idx = optim.gather_pad_indices(idx, cap)
        y_sub = y_clean[jnp.asarray(pad_idx)]
        kw = {**fit_kwargs, **rung.kwargs}
        if supports_init and rung.perturb:
            # deterministic perturbed init: best-seen params of the failed
            # rows, jittered relative to their own magnitude
            base = np.nan_to_num(params[pad_idx], nan=0.0,
                                 posinf=0.0, neginf=0.0)
            jitter = rung.perturb * (1.0 + np.abs(base)) * rng.standard_normal(
                base.shape
            )
            kw["init_params"] = jnp.asarray(
                (base + jitter).astype(y_clean.dtype)  # no host round-trip for dtype
            )
        kw = _accepted_kwargs(fit_fn, kw)
        with obs.span(f"fit.rung.{rung.name}", rows=int(idx.size), cap=cap):
            sub = fit_fn(y_sub, **kw)
        sub_failed = _failed_mask(sub)[: idx.size]
        rescued = idx[~sub_failed]
        if rescued.size:
            keep = np.nonzero(~sub_failed)[0]
            params[rescued] = np.asarray(sub.params)[keep]
            nll[rescued] = np.asarray(sub.neg_log_likelihood)[keep]
            conv[rescued] = np.asarray(sub.converged)[keep]
            iters[rescued] = np.asarray(sub.iters)[keep]
            status[rescued] = np.maximum(status[rescued], rung.status)
            failed[rescued] = False
            retryable[rescued] = False
        rung_meta.append({
            "rung": rung.name, "depth": depth,
            "attempted": int(idx.size), "rescued": int(rescued.size),
            "kwargs": {k: v for k, v in rung.kwargs.items()},
        })
        obs.counter(f"ladder.{rung.name}.attempted").add(int(idx.size))
        obs.counter(f"ladder.{rung.name}.rescued").add(int(rescued.size))

    # survivors of every rung: flag DIVERGED and refuse to hand back
    # non-finite params as if they were estimates
    if failed.any():
        params[failed] = np.nan
        nll[failed] = np.nan
        conv[failed] = False
        status[failed] = np.maximum(status[failed], FitStatus.DIVERGED)

    meta = {
        "sanitize": san_meta,
        "ladder": rung_meta,
        "retry_rows_over_cap": over_cap,
        "status_counts": status_counts(status),
    }
    if single:
        return ResilientFitResult(
            params[0], nll[0], conv[0], iters[0], status[0], meta
        )
    return ResilientFitResult(params, nll, conv, iters, status, meta)
