"""Chunk sources: where a chunk walk's rows live — HBM, host RAM, or disk.

Through PR 6 the chunk driver assumed the WHOLE panel was resident on
device before the walk started (``fit_chunked`` called ``jnp.asarray`` on
its input), capping a single-chip job at whatever fits in HBM next to the
fit program's workspace.  The reference system never had that cap: a
TimeSeriesRDD lived in executor memory (or spilled to disk) and streamed
through tasks partition by partition.  This module is the TPU rebuild of
that promise — **the panel becomes a** :class:`ChunkSource`, an object the
driver asks for one chunk's rows at a time:

- :class:`DeviceChunkSource` — the panel is already a device array;
  today's path, unwrapped by the driver so it stays byte-identical.
- :class:`HostChunkSource` — the panel is a host ``np.ndarray`` (RAM the
  device cannot address); each chunk is copied H2D through the staging
  pool when the walk reaches (or prefetches) it.
- :class:`NpzShardSource` — the panel is a directory of row-partitioned
  ``.npz`` shards on disk; chunks are decompressed into the staging pool
  and copied H2D, so the panel never fully materializes even in host RAM.

**The staging pool** (:class:`StagingPool`): H2D copies go through a small
set of REUSABLE host staging buffers instead of a fresh allocation per
chunk — the host-side twin of the classic pinned-buffer pool (actual page
pinning is the runtime's business; what this pool guarantees is that the
steady state allocates nothing and the transfer source is a stable,
contiguous buffer).  The pool records hits (buffer reused), misses (fresh
allocation), and its peak host footprint, and registers itself with
``obs.memory`` so the peak-memory probe reports staging bytes alongside
device/RSS peaks.

**Donated device buffers**: a staged slice is returned to the driver with
NO reference retained anywhere in this module or the prefetcher, so the
moment the chunk's fit has consumed it and the driver's reference dies,
the runtime can recycle its HBM for the chunk after next — steady-state
device footprint is O(prefetch_depth + 1 chunks), not O(panel).  The
source tracks that contract: every staged buffer carries a finalizer, and
``stats()['peak_live_device_bytes']`` is the high-water mark of staged
bytes whose Python references were still alive — the number the
oversubscribed bench asserts is O(chunk).

**Identity contract**: ``source.stage(lo, hi)`` must return exactly the
bytes ``panel[lo:hi]`` would hold on device.  Everything downstream —
journal fingerprints, bitwise identity with the in-HBM walk, resume — is
built on that; a source whose shards disagree on dtype or time length is
rejected at construction (:class:`SourceError`), BEFORE any compute, and
a shard that tears after construction fails the read loudly (input data
is not recomputable — unlike a torn JOURNAL shard, which downgrades to a
recompute through this same source).

Sources plug into the walk as ``fit_chunked(fit_fn, source)`` /
``panel.fit(model, source=...)`` / compat ``fit_model(source, ...)`` —
one argument, everything else (journal, watchdog, pipeline, mesh lanes)
composes unchanged.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
import zipfile
from typing import Optional, Sequence, Tuple

import numpy as np

from .. import obs
from .journal import durable_replace as _durable_replace

__all__ = [
    "ChunkSource",
    "DeviceChunkSource",
    "HostChunkSource",
    "NpzShardSource",
    "SourceError",
    "SourceLane",
    "StagingPool",
    "as_source",
    "write_npz_shards",
]


class SourceError(RuntimeError):
    """A chunk source is malformed (mixed dtype/shape across shards, torn
    or missing input shard, non-2-D data).  Raised BEFORE compute where
    detectable at construction; at read time for damage that appears
    later.  Input data is not recomputable, so this never downgrades
    silently."""


def _on_cpu(arr) -> bool:
    """True when ``arr`` lives on a CPU device (where ``device_put`` of a
    host buffer may be zero-copy — see :meth:`ChunkSource.stage`)."""
    try:
        return next(iter(arr.devices())).platform == "cpu"
    except Exception:  # noqa: BLE001 - older jax Array surfaces
        try:
            return arr.device().platform == "cpu"
        except Exception:  # noqa: BLE001
            return True  # unknown: assume aliasing is possible (safe)


_copy_fn = None


def _alias_break_copy(arr):
    global _copy_fn
    if _copy_fn is None:
        import jax
        import jax.numpy as jnp

        _copy_fn = jax.jit(lambda x: jnp.copy(x))
    return _copy_fn(arr)


class StagingPool:
    """Reusable host staging buffers for chunk-sized H2D copies.

    ``acquire(rows)`` leases a ``[rows, t]`` view of a pooled buffer
    (reusing any free buffer with enough capacity — a *hit* — else
    allocating one, a *miss*); ``lease.release()`` returns it.  The pool
    never copies or zeroes: the caller overwrites the leased view before
    the transfer.  Peak leased bytes and peak total footprint are tracked,
    and the pool registers with ``obs.memory`` so oversubscribed runs
    report their staging RAM instead of undercounting host peaks.
    """

    # lock-discipline contract (tools/lint lock-map): the pool is shared
    # across prefetcher workers, lane threads, and (ISSUE 12) the whole
    # serving process — free list and accounting mutate only under _lock.
    _protected_by_ = {
        "_free": "_lock",
        "_n_buffers": "_lock",
        "hits": "_lock",
        "misses": "_lock",
        "in_use_bytes": "_lock",
        "peak_in_use_bytes": "_lock",
        "total_bytes": "_lock",
        "peak_host_bytes": "_lock",
    }

    def __init__(self, n_cols: int, dtype):
        self.n_cols = int(n_cols)
        self.dtype = np.dtype(dtype)
        self._free: list = []  # np buffers, any capacity
        self._lock = threading.Lock()
        self._n_buffers = 0
        self.hits = 0
        self.misses = 0
        self.in_use_bytes = 0
        self.peak_in_use_bytes = 0
        self.total_bytes = 0
        self.peak_host_bytes = 0
        obs.register_staging_pool(self)

    class _Lease:
        __slots__ = ("pool", "buf", "view", "_released")

        def __init__(self, pool, buf, rows):
            self.pool = pool
            self.buf = buf
            self.view = buf[:rows]
            self._released = False

        def release(self):
            if not self._released:
                self._released = True
                self.pool._release(self.buf)

    def acquire(self, rows: int) -> "StagingPool._Lease":
        rows = int(rows)
        with self._lock:
            # smallest free buffer that fits: keeps big buffers available
            # for big requests after OOM backoff has mixed chunk sizes
            fits = [b for b in self._free if b.shape[0] >= rows]
            if fits:
                buf = min(fits, key=lambda b: b.shape[0])
                self._free.remove(buf)
                self.hits += 1
            else:
                buf = np.empty((rows, self.n_cols), self.dtype)
                self.misses += 1
                self._n_buffers += 1
                self.total_bytes += buf.nbytes
                self.peak_host_bytes = max(self.peak_host_bytes,
                                           self.total_bytes)
            self.in_use_bytes += buf.nbytes
            self.peak_in_use_bytes = max(self.peak_in_use_bytes,
                                         self.in_use_bytes)
        return StagingPool._Lease(self, buf, rows)

    def _release(self, buf) -> None:
        with self._lock:
            self.in_use_bytes -= buf.nbytes
            self._free.append(buf)

    def stats(self) -> dict:
        with self._lock:
            return {
                "pool_hits": self.hits,
                "pool_misses": self.misses,
                "pool_buffers": self._n_buffers,
                "pool_bytes": self.total_bytes,
                "peak_host_bytes": self.peak_host_bytes,
            }


class ChunkSource:
    """Base class: a ``[n_rows, n_cols]`` panel the driver reads in row
    chunks.  Subclasses implement :meth:`read_rows` (fill a host buffer)
    and :meth:`_nan_probe` (streamed align probe); staging, pooling, and
    the donated-buffer accounting live here.
    """

    kind = "abstract"

    # lock-discipline contract (tools/lint lock-map): staging runs on
    # prefetcher workers while the driver probes align mode /
    # fingerprint and weakref finalizers retire buffers from arbitrary
    # threads — every mutation holds _mu.
    _protected_by_ = {
        "_align_mode": "_mu",
        "_fingerprint": "_mu",
        "_live_device_bytes": "_mu",
        "_peak_live_device_bytes": "_mu",
        "h2d_copies": "_mu",
        "h2d_bytes": "_mu",
        "h2d_wall_s": "_mu",
    }

    def __init__(self, shape: Tuple[int, int], dtype,
                 pool: Optional[StagingPool] = None):
        b, t = int(shape[0]), int(shape[1])
        if b <= 0 or t <= 0:
            raise SourceError(f"chunk source must be non-empty 2-D, "
                              f"got shape {shape}")
        self.shape = (b, t)
        self.ndim = 2
        self.dtype = np.dtype(dtype)
        self.nbytes = b * t * self.dtype.itemsize
        self.default_chunk_rows: Optional[int] = None
        if pool is not None:
            # a caller-owned pool shared across sources (ISSUE 12: the
            # resident fit server keeps ONE process-level pool warm across
            # requests, so buffer reuse spans panels, not just chunks) —
            # geometry must match or the leased views would be wrong-shaped
            if pool.n_cols != t or pool.dtype != self.dtype:
                raise SourceError(
                    f"shared staging pool is [*, {pool.n_cols}] "
                    f"{pool.dtype}, panel needs [*, {t}] {self.dtype}")
            self._pool = pool
        else:
            self._pool = StagingPool(t, self.dtype)
        self._mu = threading.Lock()
        self._align_mode: Optional[str] = None
        self._fingerprint: Optional[str] = None
        # donated-buffer accounting: bytes of staged device slices whose
        # Python references are still alive.  The walk's reference hygiene
        # (prefetcher slots cleared at take, chunk locals dying with the
        # fit) is what bounds steady-state HBM at O(chunk); this counter
        # PROVES it per run instead of asserting it in a docstring.
        self._live_device_bytes = 0
        self._peak_live_device_bytes = 0
        self.h2d_copies = 0
        self.h2d_bytes = 0
        self.h2d_wall_s = 0.0

    # -- subclass surface ----------------------------------------------------

    def read_rows(self, lo: int, hi: int, out: np.ndarray) -> None:
        raise NotImplementedError

    def _nan_probe(self) -> Tuple[bool, bool]:
        """(any NaN anywhere, any NaN in the last column) — streamed."""
        raise NotImplementedError

    def fingerprint(self) -> str:
        raise NotImplementedError

    # -- staging -------------------------------------------------------------

    def stage(self, lo: int, hi: int, device=None):
        """The device slice ``panel[lo:hi]`` — host read into a pooled
        staging buffer, one H2D copy, buffer back to the pool.  The
        returned array is DONATED: no reference survives here, and a
        finalizer keeps the live-bytes accounting honest."""
        import jax

        lo, hi = int(lo), int(hi)
        if not (0 <= lo < hi <= self.shape[0]):
            raise IndexError(f"stage span [{lo}, {hi}) outside "
                             f"[0, {self.shape[0]})")
        n = hi - lo
        nbytes = n * self.shape[1] * self.dtype.itemsize
        lease = self._pool.acquire(n)
        t0 = time.perf_counter()
        try:
            with obs.span("stage.h2d", lo=lo, hi=hi, bytes=nbytes):
                self.read_rows(lo, hi, lease.view)
                arr = jax.device_put(lease.view, device)
                if _on_cpu(arr):
                    # the CPU backend's device_put ALIASES a compatible
                    # host buffer instead of copying it — reusing the
                    # pool buffer would then rewrite this chunk's bytes
                    # under its (async-dispatched) fit.  One jitted copy
                    # breaks the alias (its output buffer is distinct by
                    # construction: no donation), costing exactly the
                    # memcpy a real H2D transfer performs.  TPU/GPU H2D
                    # is always a genuine copy and skips this.
                    arr = _alias_break_copy(arr)
                # the pool buffer is reused for the NEXT chunk the moment
                # the lease releases: the transfer (and the alias-breaking
                # copy, which reads the buffer) must be complete first
                # pool-buffer reuse requires the H2D copy, and the
                # alias-breaking read, to be complete first:
                # lint: host-sync(deliberate pool-reuse barrier)
                jax.block_until_ready(arr)
        finally:
            lease.release()
        wall = time.perf_counter() - t0
        with self._mu:
            self.h2d_copies += 1
            self.h2d_bytes += nbytes
            self.h2d_wall_s += wall
            self._live_device_bytes += nbytes
            self._peak_live_device_bytes = max(
                self._peak_live_device_bytes, self._live_device_bytes)
        try:
            weakref.finalize(arr, self._retire, nbytes)
        except TypeError:  # not weak-referenceable on this backend
            with self._mu:
                self._live_device_bytes -= nbytes
        obs.counter("source.h2d_copies").inc()
        return arr

    def _retire(self, nbytes: int) -> None:
        with self._mu:
            self._live_device_bytes -= nbytes

    def __getitem__(self, s: slice):
        if not isinstance(s, slice) or s.step not in (None, 1):
            raise TypeError("chunk sources support contiguous row slices")
        return self.stage(0 if s.start is None else s.start,
                          self.shape[0] if s.stop is None else s.stop)

    # -- walk support --------------------------------------------------------

    def align_mode(self) -> str:
        """Static align-mode plan for the whole panel, probed on the HOST
        (streamed through the source — the panel never touches the device
        for the probe) and cached: same vocabulary and same answer as
        ``models.base.align_mode_on_host`` on the materialized array."""
        with self._mu:
            if self._align_mode is not None:
                return self._align_mode
        nan_any, nan_last = self._nan_probe()
        mode = ("dense" if not nan_any
                else ("no-trailing" if not nan_last else "general"))
        with self._mu:
            self._align_mode = mode
        return mode

    def stats(self) -> dict:
        """Staging accounting: pool reuse, H2D wall/bytes, and the
        donated-buffer high-water mark (see class docstring)."""
        with self._mu:
            out = {
                "h2d_copies": self.h2d_copies,
                "h2d_bytes": self.h2d_bytes,
                "h2d_wall_s": round(self.h2d_wall_s, 6),
                "peak_live_device_bytes": self._peak_live_device_bytes,
            }
        out.update(self._pool.stats())
        return out

    def reset_peak_live(self) -> None:
        """Rebase the donated-buffer high-water mark to what is live NOW.

        The chunk driver calls this at walk start so
        ``peak_live_device_bytes`` in a walk's meta/manifest is THAT
        walk's footprint, not an earlier (bigger-chunked) walk's —
        consumers assert O(chunk) bounds against it.  Accounting only:
        concurrent walks sharing one source see a merged peak.
        """
        with self._mu:
            self._peak_live_device_bytes = self._live_device_bytes

    def stats_delta(self, before: Optional[dict]) -> dict:
        """``stats()`` with the monotonic counters rebased to ``before``
        (one source can feed several walks; each walk's meta must report
        its own staging activity, like the obs counter deltas).  The
        peak fields are NOT subtracted — peaks have no meaningful delta;
        ``peak_live_device_bytes`` is instead rebased per walk via
        :meth:`reset_peak_live`, while the pool's ``peak_host_bytes`` /
        ``pool_bytes`` are deliberately lifetime values (buffer REUSE
        across walks is the pool's point)."""
        now = self.stats()
        if not before:
            return now
        for k in ("h2d_copies", "h2d_bytes", "pool_hits", "pool_misses"):
            now[k] = now[k] - before.get(k, 0)
        now["h2d_wall_s"] = round(now["h2d_wall_s"]
                                  - before.get("h2d_wall_s", 0.0), 6)
        return now


class SourceLane:
    """One lane's view of a source: LOCAL row coordinates (row 0 is global
    row ``base``) staged to the lane's device — the source-backed twin of
    the device-array lane placement, so :class:`~.plan.LaneRunner` and the
    prefetcher slice it with the same expressions either way."""

    __slots__ = ("source", "base", "device")

    def __init__(self, source: ChunkSource, base: int = 0, device=None):
        self.source = source
        self.base = int(base)
        self.device = device

    def __getitem__(self, s: slice):
        return self.source.stage(s.start + self.base, s.stop + self.base,
                                 device=self.device)


class DeviceChunkSource(ChunkSource):
    """A panel already resident on device — today's path.  The driver
    unwraps it (``.array``) and walks exactly as before; this class exists
    so every input kind has a source spelling."""

    kind = "device"

    def __init__(self, array):
        import jax.numpy as jnp

        self.array = jnp.asarray(array)
        if self.array.ndim != 2:
            raise SourceError(
                f"expected [batch, time], got {self.array.shape}")
        super().__init__(self.array.shape, str(self.array.dtype))

    def read_rows(self, lo, hi, out):
        np.copyto(out, np.asarray(self.array[lo:hi]))

    def stage(self, lo, hi, device=None):
        # already on device: a slice IS the staged buffer (no pool trip)
        return self.array[int(lo):int(hi)]

    def _nan_probe(self):
        from ..models import base as model_base

        mode = model_base.align_mode_on_host(self.array)
        return mode != "dense", mode == "general"

    def fingerprint(self) -> str:
        from . import journal as journal_mod

        return journal_mod.panel_fingerprint(self.array)


# default cap on one staged slice when the caller gives no chunk_rows: a
# whole-panel "chunk" would stage the oversubscribed panel in one H2D
# copy (and allocate a panel-sized pool buffer) — exactly the failure
# this module exists to remove
_DEFAULT_SLICE_BYTES = 256 << 20


class HostChunkSource(ChunkSource):
    """A panel in host RAM (``np.ndarray``) the device cannot address —
    the larger-than-HBM workhorse.  Chunks are copied H2D through the
    staging pool as the walk (or its prefetcher) reaches them; nothing
    else ever moves to the device, so a 64 GB panel walks through a 16 GB
    chip at O(chunk) device footprint.

    Without an explicit ``chunk_rows`` the walk defaults to slices of at
    most ``_DEFAULT_SLICE_BYTES`` (256 MiB) — small panels stay one
    chunk, big panels never stage whole."""

    kind = "host"

    def __init__(self, values, pool: Optional[StagingPool] = None):
        arr = np.asarray(values)
        if arr.ndim != 2:
            raise SourceError(f"expected [batch, time], got {arr.shape}")
        self._arr = arr
        super().__init__(arr.shape, arr.dtype, pool=pool)
        row_bytes = max(1, self.shape[1] * self.dtype.itemsize)
        self.default_chunk_rows = max(
            1, min(self.shape[0], _DEFAULT_SLICE_BYTES // row_bytes))

    def read_rows(self, lo, hi, out):
        np.copyto(out, self._arr[lo:hi])

    def _nan_probe(self):
        # streamed in row blocks: a whole-panel isnan mask would allocate
        # panel_bytes/4 of host RAM — real money on the 64 GB panels this
        # source exists for
        nan_any = False
        block = max(1, (1 << 24) // max(1, self.shape[1]))
        for lo in range(0, self.shape[0], block):
            if np.isnan(self._arr[lo:lo + block]).any():
                nan_any = True
                break
        nan_last = bool(np.isnan(self._arr[:, -1]).any())
        return nan_any, nan_last

    def fingerprint(self) -> str:
        # the SAME strided-sample fingerprint the in-HBM walk computes on
        # the device array: a journal written by either residency resumes
        # under the other (the bytes are the panel's, not the placement's)
        with self._mu:
            if self._fingerprint is None:
                from . import journal as journal_mod

                self._fingerprint = journal_mod.panel_fingerprint(self._arr)
            return self._fingerprint


def _npz_member_header(zf: zipfile.ZipFile, name: str):
    """(shape, dtype) of one ``.npy`` member without decompressing it."""
    with zf.open(name) as f:
        version = np.lib.format.read_magic(f)
        if version == (1, 0):
            shape, _forder, dtype = np.lib.format.read_array_header_1_0(f)
        elif version == (2, 0):
            shape, _forder, dtype = np.lib.format.read_array_header_2_0(f)
        else:
            raise SourceError(f"unsupported npy format {version} in {name}")
        return shape, dtype


class NpzShardSource(ChunkSource):
    """A panel stored as a directory of row-partitioned ``.npz`` shards.

    Files matching ``*.npz`` are taken in sorted name order; each holds
    one 2-D array under ``key`` (default: the file's only array).  Shard
    HEADERS are read at construction — shape/dtype metadata only, no
    decompression — and a shard whose dtype or time length disagrees with
    the first is rejected there, before any compute.  Zero-row shards
    (an empty trailing shard from a generator that rounded up) are
    tolerated and skipped.  A shard that is unreadable/torn raises
    :class:`SourceError` naming the file — at construction when the zip
    structure is damaged, at read time when the payload is.

    Reads keep a 2-shard decompression cache (sequential walks re-read
    each shard at most once per pass; the prefetch worker and an inline
    miss may straddle the same shard).  ``default_chunk_rows`` is the
    first shard's row count, so an un-hinted walk lands its chunk
    boundaries on shard boundaries.
    """

    kind = "npz_dir"

    def __init__(self, directory, key: Optional[str] = None,
                 cache_shards: int = 2):
        self.directory = os.path.abspath(os.fspath(directory))
        self.key = key
        # hidden files excluded: a crashed append (ISSUE 15) can leave a
        # fully-valid ".tmp-*.npz" orphan behind, and ".tmp-" sorts
        # before "part_" — counting it as shard 0 would silently shift
        # every row offset in the panel
        names = sorted(n for n in os.listdir(self.directory)
                       if n.endswith(".npz") and not n.startswith("."))
        if not names:
            raise SourceError(f"no .npz shards in {self.directory}")
        self._shards: list = []  # (path, member, row_lo, row_hi, crc)
        n_cols = dtype = None
        row = 0
        for fname in names:
            path = os.path.join(self.directory, fname)
            try:
                with zipfile.ZipFile(path) as zf:
                    members = [n for n in zf.namelist()
                               if n.endswith(".npy")]
                    if key is not None:
                        member = f"{key}.npy"
                        if member not in members:
                            raise SourceError(
                                f"shard {path} has no array {key!r} "
                                f"(members: {members})")
                    elif len(members) == 1:
                        member = members[0]
                    else:
                        raise SourceError(
                            f"shard {path} holds {len(members)} arrays "
                            f"({members}); pass key= to pick one")
                    shape, dt = _npz_member_header(zf, member)
                    crc = zf.getinfo(member).CRC
            except SourceError:
                raise
            except (OSError, ValueError, KeyError,
                    zipfile.BadZipFile) as e:
                raise SourceError(
                    f"input shard {path} is unreadable/torn ({e}); input "
                    "data cannot be recomputed — restore the shard or "
                    "rebuild the source directory") from e
            if len(shape) != 2:
                raise SourceError(
                    f"shard {path} array is {len(shape)}-D "
                    f"(shape {shape}); expected [rows, time]")
            if shape[0] == 0:
                continue  # empty trailing shard: legal, no rows to serve
            if n_cols is None:
                n_cols, dtype = shape[1], np.dtype(dt)
            elif shape[1] != n_cols or np.dtype(dt) != dtype:
                raise SourceError(
                    f"shard {path} is [{shape[0]}, {shape[1]}] {dt}, but "
                    f"the panel is [*, {n_cols}] {dtype}; mixed shard "
                    "layouts are rejected before compute")
            self._shards.append((path, member, row, row + shape[0], crc))
            row += shape[0]
        if n_cols is None:
            raise SourceError(
                f"{self.directory} holds only zero-row shards")
        super().__init__((row, n_cols), dtype)
        self.default_chunk_rows = self._shards[0][3] - self._shards[0][2]
        self._cache_n = max(1, int(cache_shards))
        self._cache: dict = {}  # path -> (tick, array)
        self._tick = 0

    def _load(self, path: str, member: str, rows: int) -> np.ndarray:
        with self._mu:
            hit = self._cache.get(path)
            if hit is not None:
                self._tick += 1
                self._cache[path] = (self._tick, hit[1])
                return hit[1]
        k = member[:-len(".npy")]
        try:
            with np.load(path, allow_pickle=False) as z:
                arr = z[k]
        except (OSError, ValueError, KeyError, zipfile.BadZipFile) as e:
            raise SourceError(
                f"input shard {path} is unreadable/torn ({e}); input data "
                "cannot be recomputed — restore the shard or rebuild the "
                "source directory") from e
        if arr.ndim != 2 or arr.shape != (rows, self.shape[1]) or \
                arr.dtype != self.dtype:
            raise SourceError(
                f"input shard {path} payload is {arr.shape} {arr.dtype}, "
                f"but its header promised ({rows}, {self.shape[1]}) "
                f"{self.dtype} — the shard changed after the source "
                "was opened")
        with self._mu:
            self._tick += 1
            self._cache[path] = (self._tick, arr)
            while len(self._cache) > self._cache_n:
                oldest = min(self._cache, key=lambda p: self._cache[p][0])
                del self._cache[oldest]
        return arr

    def read_rows(self, lo, hi, out):
        for path, member, slo, shi, _crc in self._shards:
            if shi <= lo or slo >= hi:
                continue
            a, b = max(lo, slo), min(hi, shi)
            arr = self._load(path, member, shi - slo)
            np.copyto(out[a - lo:b - lo], arr[a - slo:b - slo])

    def _nan_probe(self):
        nan_any = nan_last = False
        for path, member, slo, shi, _crc in self._shards:
            arr = self._load(path, member, shi - slo)
            nan = np.isnan(arr)
            nan_any = nan_any or bool(nan.any())
            nan_last = nan_last or bool(nan[:, -1].any())
            if nan_last:
                break
        return nan_any, nan_last

    def append_rows(self, values, rows_per_shard: Optional[int] = None
                    ) -> "NpzShardSource":
        """Append NEW series to the shard directory (new ``part_*``
        files; existing shards untouched) and return a fresh source over
        the extended directory — this instance's cached headers describe
        the OLD layout and stay valid for it."""
        write_npz_shards(self.directory, values,
                         rows_per_shard=rows_per_shard,
                         key=self.key or self._member_key(),
                         append_rows=True)
        return NpzShardSource(self.directory, key=self.key,
                              cache_shards=self._cache_n)

    def append_time(self, values) -> "NpzShardSource":
        """Append new time steps (``values [B, dt]``) to EVERY row —
        each shard atomically rewritten with its slice of the new
        columns — and return a fresh source over the grown panel."""
        write_npz_shards(self.directory, values,
                         key=self.key or self._member_key(),
                         append_time=True)
        return NpzShardSource(self.directory, key=self.key,
                              cache_shards=self._cache_n)

    def _member_key(self) -> str:
        member = self._shards[0][1]
        return member[:-len(".npy")]

    def fingerprint(self) -> str:
        """Content-derived without decompression: shape/dtype plus every
        shard's (name, rows, zip CRC-32) — the CRC is computed from the
        payload bytes by whatever wrote the shard, so edits to any shard
        change the fingerprint like a content hash would, at zero read
        cost.  Shard-dir jobs therefore fingerprint differently from the
        same panel as an in-RAM/in-HBM array (those sample values); a
        journal follows its source spelling."""
        with self._mu:
            if self._fingerprint is None:
                import hashlib

                h = hashlib.sha256(
                    f"npzdir:{self.shape}:{self.dtype}".encode())
                for path, _m, slo, shi, crc in self._shards:
                    h.update(f"{os.path.basename(path)}:"
                             f"{shi - slo}:{crc:08x}".encode())
                self._fingerprint = h.hexdigest()[:16]
            return self._fingerprint


def _pyarrow():
    """Import pyarrow lazily; parquet support is optional and the error
    must say so instead of an ImportError from the middle of a walk."""
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq
    except Exception as e:  # pragma: no cover - environment-dependent
        raise SourceError(
            "parquet shard support requires pyarrow, which is not "
            f"available here ({e}); write npz shards instead or install "
            "pyarrow") from e
    return pa, pq


_PARQUET_DIGEST_KEY = b"spark_ts_sha256"


def _parquet_shard_header(path: str):
    """(rows, n_cols, dtype, digest) of one parquet shard from its footer
    METADATA only — no row groups are decoded.  ``digest`` is the content
    sha256 our writer stamps into the file's key-value metadata; ``None``
    for foreign files (fingerprinting then hashes the file bytes)."""
    _pa, pq = _pyarrow()
    pf = pq.ParquetFile(path)
    meta = pf.metadata
    schema = pf.schema_arrow
    if len(schema) != 1:
        raise SourceError(
            f"parquet shard {path} has {len(schema)} columns "
            f"({schema.names}); expected one fixed_size_list column")
    field = schema.field(0)
    import pyarrow as pa
    if not pa.types.is_fixed_size_list(field.type):
        raise SourceError(
            f"parquet shard {path} column {field.name!r} is {field.type}; "
            "expected fixed_size_list<value_type>[n_time]")
    n_cols = int(field.type.list_size)
    dtype = np.dtype(field.type.value_type.to_pandas_dtype())
    digest = None
    kv = meta.metadata or {}
    raw = kv.get(_PARQUET_DIGEST_KEY)
    if raw is not None:
        digest = raw.decode("ascii", errors="replace")
    return int(meta.num_rows), n_cols, dtype, digest, field.name


class ParquetShardSource(ChunkSource):
    """A panel stored as a directory of row-partitioned ``.parquet``
    shards — the arrow sibling of :class:`NpzShardSource`.

    Each shard holds one ``fixed_size_list<dtype>[n_time]`` column (one
    list per series row).  Files matching ``*.parquet`` are taken in
    sorted name order; footer METADATA is read at construction — row
    counts, list width, value dtype, no row-group decode — and a shard
    whose layout disagrees with the first is rejected there, before any
    compute.  Zero-row shards are tolerated and skipped; hidden
    ``.tmp-*`` orphans from a crashed append are excluded, so a torn
    writer can never shift row offsets.  A shard whose footer is
    damaged/torn raises :class:`SourceError` naming the file.

    Reads go through the same staging-pool machinery as every other
    residency, with a 2-shard decompression cache; the float bytes a
    walk stages are identical to the npz spelling of the same panel, so
    journals, delta plans, and forecasts are bitwise-interchangeable
    across the two on-disk layouts.
    """

    kind = "parquet_dir"

    def __init__(self, directory, key: Optional[str] = None,
                 cache_shards: int = 2):
        self.directory = os.path.abspath(os.fspath(directory))
        self.key = key
        names = sorted(n for n in os.listdir(self.directory)
                       if n.endswith(".parquet") and not n.startswith("."))
        if not names:
            raise SourceError(f"no .parquet shards in {self.directory}")
        self._shards: list = []  # (path, column, row_lo, row_hi, digest)
        n_cols = dtype = column = None
        row = 0
        for fname in names:
            path = os.path.join(self.directory, fname)
            try:
                rows, cols, dt, digest, col = _parquet_shard_header(path)
            except SourceError:
                raise
            except Exception as e:
                raise SourceError(
                    f"input shard {path} is unreadable/torn ({e}); input "
                    "data cannot be recomputed — restore the shard or "
                    "rebuild the source directory") from e
            if key is not None and col != key:
                raise SourceError(
                    f"shard {path} holds column {col!r}, not {key!r}")
            if rows == 0:
                continue  # empty trailing shard: legal, no rows to serve
            if n_cols is None:
                n_cols, dtype, column = cols, dt, col
            elif cols != n_cols or dt != dtype or col != column:
                raise SourceError(
                    f"shard {path} is [{rows}, {cols}] {dt} column "
                    f"{col!r}, but the panel is [*, {n_cols}] {dtype} "
                    f"column {column!r}; mixed shard layouts are rejected "
                    "before compute")
            self._shards.append((path, col, row, row + rows, digest))
            row += rows
        if n_cols is None:
            raise SourceError(
                f"{self.directory} holds only zero-row shards")
        super().__init__((row, n_cols), dtype)
        self.default_chunk_rows = self._shards[0][3] - self._shards[0][2]
        self._cache_n = max(1, int(cache_shards))
        self._cache: dict = {}  # path -> (tick, array)
        self._tick = 0

    def _load(self, path: str, column: str, rows: int) -> np.ndarray:
        with self._mu:
            hit = self._cache.get(path)
            if hit is not None:
                self._tick += 1
                self._cache[path] = (self._tick, hit[1])
                return hit[1]
        _pa, pq = _pyarrow()
        try:
            table = pq.read_table(path, columns=[column])
            col = table.column(column).combine_chunks()
            arr = np.asarray(col.values).reshape(len(col), self.shape[1])
        except Exception as e:
            raise SourceError(
                f"input shard {path} is unreadable/torn ({e}); input data "
                "cannot be recomputed — restore the shard or rebuild the "
                "source directory") from e
        if arr.shape != (rows, self.shape[1]) or arr.dtype != self.dtype:
            raise SourceError(
                f"input shard {path} payload is {arr.shape} {arr.dtype}, "
                f"but its footer promised ({rows}, {self.shape[1]}) "
                f"{self.dtype} — the shard changed after the source "
                "was opened")
        with self._mu:
            self._tick += 1
            self._cache[path] = (self._tick, arr)
            while len(self._cache) > self._cache_n:
                oldest = min(self._cache, key=lambda p: self._cache[p][0])
                del self._cache[oldest]
        return arr

    def read_rows(self, lo, hi, out):
        for path, column, slo, shi, _d in self._shards:
            if shi <= lo or slo >= hi:
                continue
            a, b = max(lo, slo), min(hi, shi)
            arr = self._load(path, column, shi - slo)
            np.copyto(out[a - lo:b - lo], arr[a - slo:b - slo])

    def _nan_probe(self):
        nan_any = nan_last = False
        for path, column, slo, shi, _d in self._shards:
            arr = self._load(path, column, shi - slo)
            nan = np.isnan(arr)
            nan_any = nan_any or bool(nan.any())
            nan_last = nan_last or bool(nan[:, -1].any())
            if nan_last:
                break
        return nan_any, nan_last

    def append_rows(self, values, rows_per_shard: Optional[int] = None
                    ) -> "ParquetShardSource":
        """Append NEW series as additional ``part_*.parquet`` files
        (existing shards untouched) and return a fresh source."""
        write_parquet_shards(self.directory, values,
                             rows_per_shard=rows_per_shard,
                             key=self.key or self._shards[0][1],
                             append_rows=True)
        return ParquetShardSource(self.directory, key=self.key,
                                  cache_shards=self._cache_n)

    def append_time(self, values) -> "ParquetShardSource":
        """Append new time steps to EVERY row — each shard atomically
        rewritten — and return a fresh source over the grown panel."""
        write_parquet_shards(self.directory, values,
                             key=self.key or self._shards[0][1],
                             append_time=True)
        return ParquetShardSource(self.directory, key=self.key,
                                  cache_shards=self._cache_n)

    def _shard_digest(self, path: str, digest: Optional[str]) -> str:
        if digest is not None:
            return digest
        # foreign file without our stamped content digest: hash the file
        # bytes once — same identity guarantee, paid at fingerprint time
        import hashlib

        h = hashlib.sha256()
        with open(path, "rb") as f:
            for block in iter(lambda: f.read(1 << 20), b""):
                h.update(block)
        return h.hexdigest()

    def fingerprint(self) -> str:
        """Content-derived without decoding row groups: shape/dtype plus
        every shard's (name, rows, payload sha256).  The digest is
        stamped into the parquet key-value metadata by
        :func:`write_parquet_shards`; foreign files fall back to hashing
        the file bytes.  Like the npz spelling, a shard-dir fingerprint
        differs from the same panel's in-RAM fingerprint — a journal
        follows its source spelling."""
        with self._mu:
            if self._fingerprint is None:
                import hashlib

                h = hashlib.sha256(
                    f"parquetdir:{self.shape}:{self.dtype}".encode())
                for path, _c, slo, shi, digest in self._shards:
                    h.update(f"{os.path.basename(path)}:{shi - slo}:"
                             f"{self._shard_digest(path, digest)}".encode())
                self._fingerprint = h.hexdigest()[:16]
            return self._fingerprint


def _write_parquet_file(f, values: np.ndarray, column: str) -> None:
    """Write ``values [rows, T]`` to an open file object as one
    fixed_size_list column, content digest stamped in the metadata."""
    import hashlib

    pa, pq = _pyarrow()
    rows, n_cols = values.shape
    flat = pa.array(np.ascontiguousarray(values).reshape(-1))
    col = pa.FixedSizeListArray.from_arrays(flat, n_cols)
    digest = hashlib.sha256(np.ascontiguousarray(values).tobytes())
    table = pa.table({column: col})
    table = table.replace_schema_metadata(
        {_PARQUET_DIGEST_KEY: digest.hexdigest().encode()})
    pq.write_table(table, f)


def write_parquet_shards(directory, values,
                         rows_per_shard: Optional[int] = None,
                         key: str = "values", *, append_rows: bool = False,
                         append_time: bool = False,
                         expect_time: Optional[int] = None) -> Sequence[str]:
    """Write ``values [B, T]`` as a row-partitioned ``.parquet`` shard
    directory that :class:`ParquetShardSource` reads back — same naming,
    same durability, and same append semantics as
    :func:`write_npz_shards` (``expect_time`` included), with the
    content digest stamped into each shard's key-value metadata."""
    values = np.asarray(values)
    if values.ndim != 2:
        raise SourceError(f"expected [batch, time], got {values.shape}")
    if append_rows and append_time:
        raise SourceError("append_rows and append_time are exclusive: "
                          "appended series and appended time steps are "
                          "different shard edits")
    if append_rows or append_time:
        existing = sorted(n for n in os.listdir(directory)
                          if n.endswith(".parquet")
                          and not n.startswith("."))
        if not existing:
            raise SourceError(f"nothing to append to: no .parquet shards "
                              f"in {directory}")
    if append_time:
        # validated UP FRONT from footers, and per-shard width-gated so a
        # killed append re-runs to completion (see write_npz_shards)
        dt = values.shape[1]
        headers = []
        total_rows = 0
        widths = set()
        for fname in existing:
            path = os.path.join(directory, fname)
            rows, cols, _dt, _dig, col = _parquet_shard_header(path)
            headers.append((path, rows, cols, col))
            total_rows += rows
            widths.add(cols)
        if total_rows != values.shape[0]:
            raise SourceError(
                f"append_time values have {values.shape[0]} rows but the "
                f"directory holds {total_rows}")
        if expect_time is not None:
            allowed = {int(expect_time), int(expect_time) + dt}
            if not widths <= allowed:
                raise SourceError(
                    f"append_time(expect_time={expect_time}) found shard "
                    f"widths {sorted(widths)}; expected only "
                    f"{sorted(allowed)}")
        elif len(widths) > 1:
            raise SourceError(
                f"append_time found mixed shard widths {sorted(widths)}; "
                "pass expect_time= to resume a torn append")
        paths = []
        row = 0
        for path, rows, cols, col in headers:
            lo, hi = row, row + rows
            row = hi
            if expect_time is not None and cols == int(expect_time) + dt:
                paths.append(path)  # already appended: idempotent skip
                continue
            _pa, pq = _pyarrow()
            table = pq.read_table(path, columns=[col])
            carr = table.column(col).combine_chunks()
            old = np.asarray(carr.values).reshape(rows, cols)
            merged = np.concatenate(
                [old, values[lo:hi].astype(old.dtype)], axis=1)
            _durable_replace(path, lambda f, c=col, m=merged:
                             _write_parquet_file(f, m, c),
                             suffix=".parquet")
            paths.append(path)
        return paths
    start = 0
    if append_rows:
        start = len(existing)
        rows0, cols0, dt0, _dig, _col = _parquet_shard_header(
            os.path.join(directory, existing[0]))
        if values.shape[1] != cols0 or values.dtype != dt0:
            raise SourceError(
                f"append_rows values are [*, {values.shape[1]}] "
                f"{values.dtype}, but the directory holds [*, {cols0}] "
                f"{dt0} shards")
        if rows_per_shard is None:
            rows_per_shard = max(1, rows0)
    if rows_per_shard is None:
        raise SourceError("rows_per_shard is required when writing a "
                          "fresh shard directory")
    rows_per_shard = max(1, int(rows_per_shard))
    os.makedirs(directory, exist_ok=True)
    paths = []
    n = -(-values.shape[0] // rows_per_shard)
    for i in range(n):
        lo = i * rows_per_shard
        hi = min(lo + rows_per_shard, values.shape[0])
        path = os.path.join(directory, f"part_{start + i:05d}.parquet")
        _durable_replace(path, lambda f, lo=lo, hi=hi:
                         _write_parquet_file(f, values[lo:hi], key),
                         suffix=".parquet")
        paths.append(path)
    return paths


def as_source(obj, **kwargs) -> ChunkSource:
    """Coerce a panel spelling into a :class:`ChunkSource`.

    - a ``ChunkSource`` passes through;
    - a directory path (str / ``os.PathLike``) opens an
      :class:`NpzShardSource`, or a :class:`ParquetShardSource` when the
      directory holds ``.parquet`` shards and no ``.npz`` ones
      (``key=`` rides along either way);
    - a host ``np.ndarray`` becomes a :class:`HostChunkSource`
      (host-resident walk — the opt-in this function exists for);
    - anything else (device arrays) becomes a :class:`DeviceChunkSource`.
    """
    if isinstance(obj, ChunkSource):
        return obj
    if isinstance(obj, (str, os.PathLike)):
        path = os.fspath(obj)
        if os.path.isdir(path):
            names = [n for n in os.listdir(path) if not n.startswith(".")]
            if any(n.endswith(".parquet") for n in names) and \
                    not any(n.endswith(".npz") for n in names):
                return ParquetShardSource(path, **kwargs)
        return NpzShardSource(obj, **kwargs)
    if isinstance(obj, np.ndarray):
        return HostChunkSource(obj)
    return DeviceChunkSource(obj)


def write_npz_shards(directory, values, rows_per_shard: Optional[int] = None,
                     key: str = "values", *, append_rows: bool = False,
                     append_time: bool = False,
                     expect_time: Optional[int] = None) -> Sequence[str]:
    """Write ``values [B, T]`` as a row-partitioned shard directory that
    :class:`NpzShardSource` reads back — the test/bench/docs helper for
    producing larger-than-HBM inputs (real pipelines write shards from
    their own ingest).

    **Appending** (ISSUE 15, the tick-feed scenario):

    - ``append_rows=True``: ``values`` are NEW series appended to an
      existing shard directory as additional ``part_*.npz`` files after
      the existing ones — clean shards are never rewritten, so a delta
      walk over the extended directory adopts every old chunk
      byte-for-byte.  ``rows_per_shard`` defaults to the directory's
      existing shard size.
    - ``append_time=True``: ``values [B_existing, dt]`` are new time
      steps for EVERY existing row; each shard is rewritten atomically
      (tmp → ``os.replace``) with its row-slice of the new columns —
      rewriting is unavoidable (every row grows), but a reader never
      sees a torn shard.  A kill BETWEEN shard rewrites still leaves the
      directory mixed-width; pass ``expect_time=`` (the pre-append
      width) to make the call idempotent — shards already at
      ``expect_time + dt`` are skipped, shards still at ``expect_time``
      are appended, any other width is rejected.  Re-running the same
      append with the same values therefore always converges to the
      fully-appended directory, which is what the tick loop's
      kill-anywhere resume leans on.

    Both flags assume the ``part_%05d`` naming this function writes.
    Returns the paths written.
    """
    values = np.asarray(values)
    if values.ndim != 2:
        raise SourceError(f"expected [batch, time], got {values.shape}")
    if append_rows and append_time:
        raise SourceError("append_rows and append_time are exclusive: "
                          "appended series and appended time steps are "
                          "different shard edits")
    if append_rows or append_time:
        # hidden files excluded (crashed-append .tmp-* orphans, see
        # NpzShardSource) — they are neither shards to extend nor a
        # numbering anchor
        existing = sorted(n for n in os.listdir(directory)
                          if n.endswith(".npz") and not n.startswith("."))
        if not existing:
            raise SourceError(f"nothing to append to: no .npz shards in "
                              f"{directory}")
    if append_time:
        # row-count validated UP FRONT from the zip headers: failing
        # mid-loop would leave the directory torn across shards (some
        # rewritten at T+dt, the rest still at T).  With expect_time=
        # the loop is additionally width-gated per shard, so re-running
        # the same append finishes a torn one instead of failing.
        dt_cols = values.shape[1]
        headers = []
        total_rows = 0
        widths = set()
        for fname in existing:
            with zipfile.ZipFile(os.path.join(directory, fname)) as zf:
                member = next(n for n in zf.namelist()
                              if n.endswith(".npy"))
                shape, _dt = _npz_member_header(zf, member)
            headers.append((fname, int(shape[0]), int(shape[1])))
            total_rows += int(shape[0])
            widths.add(int(shape[1]))
        if total_rows != values.shape[0]:
            raise SourceError(
                f"append_time values have {values.shape[0]} rows but the "
                f"directory holds {total_rows}")
        if expect_time is not None:
            allowed = {int(expect_time), int(expect_time) + dt_cols}
            if not widths <= allowed:
                raise SourceError(
                    f"append_time(expect_time={expect_time}) found shard "
                    f"widths {sorted(widths)}; expected only "
                    f"{sorted(allowed)}")
        elif len(widths) > 1:
            raise SourceError(
                f"append_time found mixed shard widths {sorted(widths)}; "
                "pass expect_time= to resume a torn append")
        paths = []
        row = 0
        for fname, rows, cols in headers:
            path = os.path.join(directory, fname)
            lo, hi = row, row + rows
            row = hi
            if expect_time is not None and \
                    cols == int(expect_time) + dt_cols:
                paths.append(path)  # already appended: idempotent skip
                continue
            with np.load(path, allow_pickle=False) as z:
                names = list(z.files)
                k = key if key in names else names[0]
                old = z[k]
            merged = np.concatenate(
                [old, values[lo:hi].astype(old.dtype)], axis=1)
            _durable_replace(path, lambda f, k=k, m=merged:
                             np.savez(f, **{k: m}), suffix=".npz")
            paths.append(path)
        return paths
    start = 0
    if append_rows:
        # the new series must match the LIVE directory's layout BEFORE
        # anything is written: a mismatched width/dtype shard under its
        # final part_* name would make every future source open fail
        start = len(existing)
        with zipfile.ZipFile(os.path.join(directory, existing[0])) as zf:
            member = next(n for n in zf.namelist() if n.endswith(".npy"))
            shape, dt = _npz_member_header(zf, member)
        if values.shape[1] != int(shape[1]) or \
                values.dtype != np.dtype(dt):
            raise SourceError(
                f"append_rows values are [*, {values.shape[1]}] "
                f"{values.dtype}, but the directory holds [*, {shape[1]}] "
                f"{np.dtype(dt)} shards")
        if rows_per_shard is None:
            rows_per_shard = max(1, int(shape[0]))
    if rows_per_shard is None:
        raise SourceError("rows_per_shard is required when writing a "
                          "fresh shard directory")
    rows_per_shard = max(1, int(rows_per_shard))
    os.makedirs(directory, exist_ok=True)
    paths = []
    n = -(-values.shape[0] // rows_per_shard)
    for i in range(n):
        lo = i * rows_per_shard
        hi = min(lo + rows_per_shard, values.shape[0])
        path = os.path.join(directory, f"part_{start + i:05d}.npz")
        # durable like every journal write: a crash mid-append must never
        # leave a torn shard under its final name in a LIVE directory
        # (fresh directories get the same treatment for free)
        _durable_replace(path, lambda f, lo=lo, hi=hi:
                         np.savez(f, **{key: values[lo:hi]}),
                         suffix=".npz")
        paths.append(path)
    return paths
