"""Resilient fit execution (L4.5): the batch analog of Spark task retry.

The reference inherited robustness from its substrate — a NaN-poisoned or
OOM-killed executor task was re-run elsewhere by Spark.  The TPU rebuild's
substrate is one monolithic vmapped program, so this package rebuilds the
same guarantees at row granularity:

- :mod:`.status` — the per-row :class:`FitStatus` vocabulary every public
  ``fit`` now reports.
- :mod:`.sanitize` — input repair/rejection (NaN/Inf/constant/all-NaN)
  with an impute / exclude / raise policy.
- :mod:`.runner` — :func:`resilient_fit`: sanitize, fit, then a retry ->
  fallback ladder over the failed subset (perturbed inits, portable
  backend) before any row is marked ``DIVERGED``.
- :mod:`.chunked` — :func:`fit_chunked`: chunked execution with bounded
  ``RESOURCE_EXHAUSTED`` backoff and degradation recorded in metadata.
- :mod:`.faultinject` — deterministic data and behavioral faults so every
  ladder rung runs in tier-1 CPU tests.
"""

from . import chunked, faultinject, runner, sanitize, status
from .chunked import OOMBackoffExceeded, fit_chunked, is_resource_exhausted
from .runner import (ResilientFitResult, RetryRung, default_ladder,
                     resilient_fit)
from .sanitize import SanitizeReport, sanitize
from .status import FitStatus, merge_status, status_counts

__all__ = [
    "FitStatus",
    "OOMBackoffExceeded",
    "ResilientFitResult",
    "RetryRung",
    "SanitizeReport",
    "chunked",
    "default_ladder",
    "faultinject",
    "fit_chunked",
    "is_resource_exhausted",
    "merge_status",
    "resilient_fit",
    "runner",
    "sanitize",
    "status",
    "status_counts",
]
