"""Resilient fit execution (L4.5): the batch analog of Spark task retry.

The reference inherited robustness from its substrate — a NaN-poisoned or
OOM-killed executor task was re-run elsewhere by Spark.  The TPU rebuild's
substrate is one monolithic vmapped program, so this package rebuilds the
same guarantees at row granularity:

- :mod:`.status` — the per-row :class:`FitStatus` vocabulary every public
  ``fit`` now reports.
- :mod:`.sanitize` — input repair/rejection (NaN/Inf/constant/all-NaN)
  with an impute / exclude / raise policy.
- :mod:`.runner` — :func:`resilient_fit`: sanitize, fit, then a retry ->
  fallback ladder over the failed subset (perturbed inits, portable
  backend) before any row is marked ``DIVERGED``.
- :mod:`.chunked` — :func:`fit_chunked`: chunked execution with bounded
  ``RESOURCE_EXHAUSTED`` backoff and degradation recorded in metadata.
- :mod:`.plan` — :class:`ExecutionPlan` / :class:`LaneRunner`: the walk's
  configuration as data (spans, lanes, budgets) and the per-lane
  scheduler that owns one prefetch → compute → commit pipeline; the
  serial, pipelined, and mesh-sharded walks are all the same plan with
  one-vs-many lanes (``fit_chunked(shard=True)`` runs one lane per mesh
  device, bitwise-identical to the single-device walk).  Sharded walks
  are ELASTIC (:class:`~.plan.LaneSupervisor` + :class:`~.plan.WorkQueue`):
  a failing lane is retried then quarantined — survivors adopt its
  committed chunks and recompute the rest — and idle lanes steal
  grid-aligned spans from stragglers, still bitwise-identical to the
  uninterrupted single-device walk.
- :mod:`.committer` — :class:`ChunkCommitter`: the pipelined driver's
  bounded background commit thread — journal commits and host I/O overlap
  the next chunk's device compute while preserving the journal's
  single-writer, in-order commit protocol.
- :mod:`.prefetcher` — :class:`ChunkPrefetcher`: the input half of the
  pipeline — a bounded background stager that materializes chunk N+1's
  device slice while chunk N computes (stage ∥ compute ∥ commit), with
  driver-controlled invalidation on OOM backoff and rollback.
- :mod:`.source` — :class:`ChunkSource`: where the panel's rows live —
  device array (today's path), host ``np.ndarray``, or an npz shard
  directory — so ``fit_chunked(fit_fn, as_source(...))`` walks panels
  that NEVER fully reside on device: chunks are staged H2D through a
  pool of reusable host buffers and donated back to the allocator as the
  walk passes, bounding steady-state device footprint at O(chunk).
- :mod:`.journal` — :class:`ChunkJournal`: write-ahead per-chunk npz
  shards + an atomic JSON manifest, so a journaled multi-chunk fit
  (``fit_chunked(..., checkpoint_dir=...)``) survives process death and
  resumes bitwise-identical, skipping committed chunks.
- :mod:`.watchdog` — wall-clock deadlines for fit dispatch: overrunning
  chunks are flagged ``TIMEOUT`` and the job degrades gracefully instead
  of hanging past its SLO.
- :mod:`.faultinject` — deterministic data, behavioral, and process
  faults (forced non-convergence, simulated OOM, SIGKILL-after-commit,
  torn manifests, disk EIO/ENOSPC/torn-write schedules) so every
  recovery path runs in tier-1 CPU tests.
- :mod:`.chaos` — seeded chaos scenarios (ISSUE 17): timed schedules
  composing the fault primitives against a live fleet, the invariant
  checker (conservation, bitwise re-answers, monotonic fencing, bounded
  unavailability), and the durable ``chaos_manifest.json`` record.
"""

from . import (chaos, chunked, committer, delta, faultinject, journal, plan,
               prefetcher, runner, sanitize, source, status, watchdog)
from .chaos import (ChaosEvent, ChaosRunner, InvariantViolation,
                    chaos_schedule, check_invariants, load_chaos_manifest,
                    unavailability_windows, write_chaos_manifest)
from .chunked import OOMBackoffExceeded, fit_chunked, is_resource_exhausted
from .delta import (DeltaError, DeltaPlan, StalePriorError, WarmstartFit,
                    plan_delta)
from .committer import ChunkCommitter, CommitterStats
from .plan import (ExecutionPlan, LaneRunner, LaneSpec, LaneSupervisor,
                   RestagedPanel, WorkQueue, shard_spans)
from .prefetcher import ChunkPrefetcher, PrefetchStats
from .journal import (ChunkJournal, FencedError, JournalError, Lease,
                      LeaseError, MergeWarmer, ShardJournalView,
                      StaleJournalError, TornManifestError, acquire_lease,
                      config_hash, merge_job_manifest, panel_fingerprint,
                      read_lease)
from .source import (ChunkSource, DeviceChunkSource, HostChunkSource,
                     NpzShardSource, SourceError, StagingPool, as_source,
                     write_npz_shards)
from .runner import (ResilientFitResult, RetryRung, default_ladder,
                     resilient_fit)
from .sanitize import SanitizeReport, sanitize
from .status import FitStatus, merge_status, status_counts
from .watchdog import Deadline, DeadlineExceeded, call_with_deadline

__all__ = [
    "ChaosEvent",
    "ChaosRunner",
    "ChunkCommitter",
    "ChunkJournal",
    "ChunkPrefetcher",
    "ChunkSource",
    "CommitterStats",
    "DeviceChunkSource",
    "HostChunkSource",
    "MergeWarmer",
    "NpzShardSource",
    "PrefetchStats",
    "SourceError",
    "StagingPool",
    "as_source",
    "write_npz_shards",
    "Deadline",
    "DeadlineExceeded",
    "ExecutionPlan",
    "FencedError",
    "FitStatus",
    "JournalError",
    "Lease",
    "LeaseError",
    "acquire_lease",
    "read_lease",
    "LaneRunner",
    "LaneSpec",
    "LaneSupervisor",
    "OOMBackoffExceeded",
    "RestagedPanel",
    "ShardJournalView",
    "WorkQueue",
    "ResilientFitResult",
    "RetryRung",
    "SanitizeReport",
    "StaleJournalError",
    "TornManifestError",
    "DeltaError",
    "DeltaPlan",
    "InvariantViolation",
    "StalePriorError",
    "WarmstartFit",
    "call_with_deadline",
    "chaos",
    "chaos_schedule",
    "check_invariants",
    "chunked",
    "committer",
    "config_hash",
    "default_ladder",
    "delta",
    "plan_delta",
    "faultinject",
    "fit_chunked",
    "is_resource_exhausted",
    "journal",
    "load_chaos_manifest",
    "merge_job_manifest",
    "merge_status",
    "panel_fingerprint",
    "plan",
    "prefetcher",
    "shard_spans",
    "resilient_fit",
    "runner",
    "sanitize",
    "source",
    "status",
    "status_counts",
    "unavailability_windows",
    "watchdog",
    "write_chaos_manifest",
]
