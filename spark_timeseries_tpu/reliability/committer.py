"""Background chunk committer: overlap journal I/O with device compute.

The serial chunk walk paid for durability twice per chunk: the driver
thread blocked on the device->host fetch of the finished chunk, then on
the npz shard write + fsync + manifest rewrite — and the TPU idled for all
of it before the next chunk could even dispatch.  Spark never billed that
tax: per-partition compute pipelined with shuffle/persist I/O under lazy
RDD execution (PAPER.md §3).  This module is the single-process rebuild of
that overlap: ONE daemon worker thread that drains a bounded FIFO of
finished chunks, performing for each — strictly in submit order —

1. the host fetch of the chunk's result arrays (``fetch(piece)``),
2. the durable shard write + atomic manifest update
   (:meth:`~.journal.ChunkJournal.commit_chunk`),

while the driver thread is already slicing and dispatching the next chunk.

**The journal's commit protocol is preserved exactly**: a single writer
(this worker is the only thread that touches the journal between
``submit`` and ``drain``), shard-before-manifest ordering per chunk, and
manifest updates in chunk order (FIFO queue, one worker — commit N+1 can
never land before commit N).  A crash with commits in flight therefore
leaves the same journal states a serial crash can: committed chunks are
durable, everything after the first in-flight commit is simply
recomputed on resume — no torn state beyond what the journal already
tolerates.

**Backpressure**: at most ``depth`` submitted-but-uncommitted chunks
(``pipeline_depth``); ``submit`` blocks when the window is full, bounding
both host memory (fetched-but-unwritten arrays) and the work a crash can
lose.  Time the driver spends blocked here (and in ``drain``) is the
commit cost the pipeline FAILED to hide; :meth:`stats` reports it next to
the total commit wall so the driver can publish overlap efficiency
(``hidden_commit_s / commit_wall_s``).

**Errors** never vanish into the worker: the first failure (I/O error,
fault-injection crash, an XLA ``RESOURCE_EXHAUSTED`` surfacing at fetch
time for an async-dispatched chunk) is captured with its chunk range,
subsequent queued commits are discarded uncommitted, and the error is
re-raised in the driver thread at the next ``submit``/``drain``/``check``
— or handed over via ``take_error`` so the chunk driver can roll the walk
back and re-enter OOM backoff.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, NamedTuple, Optional

from .. import obs

__all__ = ["ChunkCommitter", "CommitterStats"]

_STOP = object()


class CommitterStats(NamedTuple):
    """Driver-facing accounting of one committer's lifetime."""

    commits: int  # chunks committed by the worker
    commit_wall_s: float  # total fetch+write wall inside the worker
    blocked_s: float  # driver wall spent waiting (backpressure + drain)
    max_queue_depth: int  # high-water mark of in-flight commits

    @property
    def hidden_s(self) -> float:
        """Commit wall the driver never waited for — hidden under compute."""
        return max(0.0, self.commit_wall_s - self.blocked_s)


class _Item(NamedTuple):
    lo: int
    hi: int
    piece: object
    wall_s: float
    info: dict  # extra manifest-entry fields captured at submit time


class ChunkCommitter:
    """Bounded in-order background committer for one journaled chunk walk.

    ``fetch(piece) -> dict`` converts a finished chunk into the journal's
    host-side shard schema (``chunked._commit_arrays``) — it runs on the
    worker thread, so for non-resilient fits the device->host copy itself
    overlaps the next chunk's compute.  ``probe()`` (optional) samples
    peak memory per commit, matching the serial driver's per-chunk
    ``peak_hbm_*`` manifest fields.
    """

    # lock-discipline contract (tools/lint lock-map): attributes shared
    # between the driver thread and the committer worker, each mutated
    # only under its declared lock.  Driver-only state (_blocked_s,
    # _closed, the queue handle) is deliberately not declared.
    _protected_by_ = {
        "_error": "_lock",  # worker sets, driver clears via take_error
        "_commits": "_lock",
        "_commit_wall_s": "_lock",
        "_max_depth": "_lock",
    }

    def __init__(self, journal, fetch: Callable[[object], dict], *,
                 depth: int = 2, probe: Optional[Callable] = None,
                 status_counts: Optional[Callable] = None,
                 on_commit: Optional[Callable] = None):
        self._journal = journal
        self._fetch = fetch
        self._probe = probe
        self._status_counts = status_counts
        # write-back sink hook (ISSUE 20): called AFTER the journal commit
        # is durable, with the fetched host arrays — the sink's own write
        # failure surfaces through the same worker-error machinery
        self._on_commit = on_commit
        self.depth = max(1, int(depth))
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._lock = threading.Lock()
        self._error: Optional[tuple] = None  # (exc, lo, hi)
        self._commits = 0
        self._commit_wall_s = 0.0
        self._blocked_s = 0.0
        self._max_depth = 0
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="chunk-committer")
        self._worker.start()

    # -- worker side --------------------------------------------------------

    def _run(self):
        while True:
            item = self._q.get()
            if item is _STOP:
                self._q.task_done()
                return
            try:
                if self._error is None:
                    self._commit_one(item)
            except BaseException as e:  # noqa: BLE001 - re-raised in driver
                with self._lock:
                    if self._error is None:
                        self._error = (e, item.lo, item.hi)
            finally:
                self._q.task_done()

    def _commit_one(self, item: _Item):
        t0 = time.perf_counter()
        with obs.span("commit.overlap", lo=item.lo, hi=item.hi):
            arrays = self._fetch(item.piece)
            info = dict(item.info)
            if self._probe is not None:
                pm = self._probe()
                info.setdefault("peak_hbm_bytes", pm.bytes)
                info.setdefault("peak_hbm_source", pm.source)
                sp = getattr(pm, "staging_pool_bytes", None)
                if sp is not None:  # host-resident walk staged through a pool
                    info.setdefault("peak_staging_pool_bytes", sp)
            if self._status_counts is not None:
                info.setdefault("status_counts",
                                self._status_counts(arrays["status"]))
            self._journal.commit_chunk(item.lo, item.hi, arrays,
                                       wall_s=item.wall_s, **info)
            if self._on_commit is not None:
                self._on_commit(item.lo, item.hi, arrays)
        with self._lock:
            self._commits += 1
            self._commit_wall_s += time.perf_counter() - t0

    # -- driver side --------------------------------------------------------

    def check(self) -> None:
        """Re-raise the worker's pending error (if any) in the driver."""
        with self._lock:
            err = self._error
        if err is not None:
            raise err[0]

    def take_error(self) -> Optional[tuple]:
        """Pop the pending ``(exception, lo, hi)`` so the driver can handle
        it (OOM rollback) instead of dying.

        Everything still queued BEHIND the failed commit is discarded
        first (the worker drops items while the error is set; the join
        here waits for that): those chunks sit at/after the failure in
        walk order, the driver is about to roll the walk back across
        them, and committing them would splice soon-to-be-recomputed
        boundaries into the manifest.  Only then is the error cleared so
        commits submitted by the rolled-back walk proceed normally."""
        with self._lock:
            err = self._error
        if err is None:
            return None
        self._q.join()
        with self._lock:
            self._error = None
        return err

    def submit(self, lo: int, hi: int, piece, *, wall_s: float,
               **info) -> None:
        """Queue one finished chunk for background commit.

        Blocks while ``depth`` commits are already in flight (backpressure
        — the blocked time is accounted as commit cost the pipeline could
        not hide).  Raises the worker's pending error, if any, BEFORE
        enqueueing: the driver must not sail past a failed commit.
        """
        self.check()
        if self._closed:
            raise RuntimeError("submit() on a closed ChunkCommitter")
        item = _Item(int(lo), int(hi), piece, float(wall_s), info)
        t0 = time.perf_counter()
        while True:
            try:
                self._q.put(item, timeout=0.05)
                break
            except queue.Full:
                self.check()  # a failed worker will never free the slot
        self._blocked_s += time.perf_counter() - t0
        with self._lock:
            d = self._q.qsize()
            if d > self._max_depth:
                self._max_depth = d
        obs.gauge("committer.queue_depth").set(self._q.qsize())

    def drain(self, *, raise_pending: bool = True) -> Optional[tuple]:
        """Block until every queued commit is durable, then surface any
        worker error.  This is the determinism point the OOM-backoff and
        watchdog-timeout paths synchronize on: after ``drain`` the journal
        reflects exactly the chunks submitted so far, in order, and the
        driver is again the only journal writer.

        ``raise_pending=False`` returns the pending ``(exc, lo, hi)``
        tuple (cleared) instead of raising, so the chunk driver can roll
        the walk back on a fetch-time OOM."""
        t0 = time.perf_counter()
        self._q.join()
        self._blocked_s += time.perf_counter() - t0
        obs.gauge("committer.queue_depth").set(0)
        if raise_pending:
            self.check()
            return None
        return self.take_error()

    def close(self, *, raise_pending: bool = True) -> CommitterStats:
        """Drain, stop the worker, and return lifetime stats.

        ``raise_pending=False`` is for exception unwinding: the walk is
        already failing, so a second (pending) commit error must not mask
        the original — it stays readable via ``take_error``.
        """
        if not self._closed:
            self._closed = True
            t0 = time.perf_counter()
            self._q.join()
            self._blocked_s += time.perf_counter() - t0
            self._q.put(_STOP)
            self._worker.join(timeout=30.0)
        if raise_pending:
            self.check()
        return self.stats()

    def stats(self) -> CommitterStats:
        with self._lock:
            return CommitterStats(self._commits, self._commit_wall_s,
                                  self._blocked_s, self._max_depth)
