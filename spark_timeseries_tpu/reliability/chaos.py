"""Seeded chaos orchestration + invariant checking (ISSUE 17).

:mod:`.faultinject` provides the PRIMITIVES — SIGKILL-after-commits,
lossy wires, disk faults — each deterministic in isolation.  This module
composes them into timed SCENARIOS against a live fleet and states what
must survive them:

- :func:`chaos_schedule` — a seeded list of :class:`ChaosEvent`\\ s (kill
  the primary at t=1.2s, arm disk faults on a standby at t=2.0s, …):
  the same seed replays the same scenario in every process, so a chaos
  run that finds a bug IS its reproducer.
- :class:`ChaosRunner` — walks a schedule against caller-supplied
  handlers on a background thread while the caller storms the fleet.
  Execution is wall-clock (sleeping to each event's offset); the
  *decisions* — what fires, in what order, with what parameters — are
  all in the seeded schedule.
- :func:`check_invariants` — the contract a degraded fleet must still
  honor, as data: **conservation** (every admitted request answered
  exactly once — zero lost, zero duplicated), **bitwise re-answers**
  (a re-polled result is byte-identical to its first answer),
  **monotonic fencing** (lease tokens only ever increase; no two
  holders overlap), and **bounded unavailability** (the longest window
  with zero successful probes stays under the bound).  Returns the
  violations; an empty list is the pass.
- :func:`write_chaos_manifest` — the scenario's durable record
  (schedule, probe timeline, invariant verdicts, counters) written
  atomically at the fleet root; ``tools/advise_budget.py`` turns it
  into circuit-breaker and hedge advice for the next run.
- :func:`join_injections` — the manifest's injections joined to their
  observed consequences in the merged fleet event timeline (injection
  -> victim's last heartbeat -> survivor's election -> takeover
  latency); ``tools/obs_report.py --fleet`` renders the result.

The orchestration of real subprocess replicas lives in
``tests/_chaos_worker.py`` (the ci smoke); this module is the library
both it and the ``chaos_northstar`` bench drive.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, \
    Tuple

import numpy as np

from .journal import _atomic_write_bytes

__all__ = [
    "CHAOS_KINDS",
    "ChaosEvent",
    "ChaosRunner",
    "InvariantViolation",
    "chaos_schedule",
    "check_invariants",
    "join_injections",
    "load_chaos_manifest",
    "unavailability_windows",
    "write_chaos_manifest",
]

CHAOS_MANIFEST = "chaos_manifest.json"

# the composable fault kinds a schedule draws from; handlers interpret
# the target/params (the library does not know what "kill" means for a
# given deployment — subprocess SIGKILL, in-process crash hook, …)
CHAOS_KINDS = ("kill", "disk", "frames", "pause")

RESULT_FIELDS = ("params", "neg_log_likelihood", "converged", "iters",
                 "status")


class ChaosEvent(NamedTuple):
    """One timed fault: ``t_s`` after scenario start, a ``kind`` from
    :data:`CHAOS_KINDS`, a ``target`` role/owner string, and kind-
    specific ``params`` (all JSON-serializable — the event list IS the
    manifest's scenario record)."""

    t_s: float
    kind: str
    target: str
    params: dict


def chaos_schedule(seed: int, duration_s: float, *,
                   n_events: int = 4,
                   kinds: Sequence[str] = ("kill", "disk", "frames"),
                   targets: Sequence[str] = ("primary", "standby"),
                   ) -> List[ChaosEvent]:
    """A seeded scenario: ``n_events`` faults at sorted offsets inside
    ``(0.1, duration_s)``.  Kind-specific parameters derive from the
    same generator, so the whole scenario — timing, victims, fault
    intensities — replays from one integer."""
    for k in kinds:
        if k not in CHAOS_KINDS:
            raise ValueError(f"unknown chaos kind {k!r} "
                             f"(have {CHAOS_KINDS})")
    if not targets:
        raise ValueError("chaos_schedule needs >= 1 target")
    rng = np.random.default_rng(int(seed))
    n = int(n_events)
    times = np.sort(rng.uniform(0.1, max(0.2, float(duration_s)), size=n))
    out: List[ChaosEvent] = []
    for i in range(n):
        kind = str(kinds[int(rng.integers(0, len(kinds)))])
        target = str(targets[int(rng.integers(0, len(targets)))])
        params: dict = {}
        if kind == "kill":
            # victims die after 1..3 further durable commits, so the
            # kill lands mid-protocol, not between requests
            params = {"after_commits": int(rng.integers(1, 4))}
        elif kind == "disk":
            params = {
                "fault_seed": int(rng.integers(0, 2 ** 31 - 1)),
                "n": 32,
                "eio_frac": round(float(rng.uniform(0.05, 0.2)), 3),
                "torn_frac": round(float(rng.uniform(0.05, 0.2)), 3),
            }
        elif kind == "frames":
            params = {
                "fault_seed": int(rng.integers(0, 2 ** 31 - 1)),
                "drop_frac": round(float(rng.uniform(0.02, 0.1)), 3),
                "reset_frac": round(float(rng.uniform(0.02, 0.1)), 3),
            }
        elif kind == "pause":
            params = {"pause_s": round(float(rng.uniform(0.1, 0.5)), 3)}
        out.append(ChaosEvent(round(float(times[i]), 3), kind, target,
                              params))
    return out


class ChaosRunner:
    """Executes a schedule against caller handlers on a daemon thread.

    ``handlers`` maps each kind appearing in the schedule to a callable
    taking the :class:`ChaosEvent`; a handler that raises marks the
    event errored (recorded, never re-raised — chaos must not kill the
    orchestrator) and the run continues.

    .. attribute:: _protected_by_

        Lock-discipline contract (tools/lint lock-map): the runner
        thread appends fired/errored records while the orchestrator
        thread reads them mid-storm and joins at the end.
    """

    _protected_by_ = {
        "_fired": "_lock",
        "_errors": "_lock",
    }

    def __init__(self, schedule: Sequence[ChaosEvent],
                 handlers: Dict[str, Callable[[ChaosEvent], None]]):
        self.schedule = sorted(schedule, key=lambda e: e.t_s)
        missing = {e.kind for e in self.schedule} - set(handlers)
        if missing:
            raise ValueError(
                f"schedule uses kinds with no handler: {sorted(missing)}")
        self.handlers = dict(handlers)
        self._lock = threading.Lock()
        self._fired: List[dict] = []
        self._errors: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ChaosRunner":
        if self._thread is not None:
            raise RuntimeError("ChaosRunner.start() called twice")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="chaos-runner")
        self._thread.start()
        return self

    def _run(self) -> None:
        t0 = time.monotonic()
        for ev in self.schedule:
            delay = ev.t_s - (time.monotonic() - t0)
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            rec = {"t_s": ev.t_s, "kind": ev.kind, "target": ev.target,
                   "params": ev.params,
                   "fired_at_s": round(time.monotonic() - t0, 3)}
            try:
                self.handlers[ev.kind](ev)
            except Exception as e:  # noqa: BLE001 - chaos never kills
                # the orchestrator; the record is the diagnosis
                with self._lock:
                    self._errors.append({**rec, "error": repr(e)[:300]})
            else:
                with self._lock:
                    self._fired.append(rec)

    def join(self, timeout_s: float = 60.0) -> Tuple[List[dict],
                                                     List[dict]]:
        """Wait for the schedule to finish; returns (fired, errors)."""
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
        with self._lock:
            return list(self._fired), list(self._errors)

    def stop(self) -> None:
        self._stop.set()


# ---------------------------------------------------------------------------
# invariants
# ---------------------------------------------------------------------------


class InvariantViolation(NamedTuple):
    invariant: str  # conservation | bitwise | fencing | availability
    detail: str


def _result_fields(res) -> dict:
    return {f: np.asarray(getattr(res, f)) for f in RESULT_FIELDS
            if hasattr(res, f)}


def unavailability_windows(probes: Sequence[Tuple[float, bool]]
                           ) -> List[Tuple[float, float]]:
    """Contiguous ``(start, end)`` windows with zero successful probes,
    from a ``(t, ok)`` timeline (t monotonic-relative seconds).  A
    window opens at the first failed probe after a success and closes
    at the next success; a trailing failure run closes at the last
    probe's time."""
    out: List[Tuple[float, float]] = []
    start: Optional[float] = None
    last_t = None
    for t, ok in sorted(probes):
        last_t = t
        if ok:
            if start is not None:
                out.append((start, t))
                start = None
        elif start is None:
            start = t
    if start is not None and last_t is not None and last_t > start:
        out.append((start, last_t))
    elif start is not None:
        out.append((start, start))
    return out


def check_invariants(*, expected_ids: Optional[Sequence[str]] = None,
                     answers: Optional[dict] = None,
                     reanswers: Optional[dict] = None,
                     lease_history: Optional[Sequence[dict]] = None,
                     probes: Optional[Sequence[Tuple[float, bool]]] = None,
                     max_unavailable_s: Optional[float] = None,
                     ) -> List[InvariantViolation]:
    """The degraded-fleet contract, checked over collected evidence
    (every argument optional — pass what the scenario gathered):

    - ``expected_ids`` + ``answers``: conservation — every admitted id
      has exactly one answer (``answers`` values may be result objects
      or None for a lost answer).
    - ``answers`` + ``reanswers``: bitwise — a re-polled id's fields
      equal its first answer's byte for byte.
    - ``lease_history``: fencing — token sequence strictly increases
      (each dict needs ``token``; equal-token repeats of the SAME owner
      are heartbeats and fine).
    - ``probes`` + ``max_unavailable_s``: bounded unavailability.
    """
    out: List[InvariantViolation] = []
    if expected_ids is not None and answers is not None:
        for rid in expected_ids:
            if answers.get(rid) is None:
                out.append(InvariantViolation(
                    "conservation", f"request {rid!r} was admitted but "
                    "never answered (lost)"))
        extra = set(answers) - set(expected_ids)
        if extra:
            out.append(InvariantViolation(
                "conservation", f"answers for ids never admitted: "
                f"{sorted(extra)[:5]}"))
    if answers is not None and reanswers is not None:
        for rid, re_res in reanswers.items():
            first = answers.get(rid)
            if first is None or re_res is None:
                continue  # conservation covers the missing side
            a, b = _result_fields(first), _result_fields(re_res)
            for f in a:
                if not np.array_equal(a[f], b.get(f), equal_nan=True):
                    out.append(InvariantViolation(
                        "bitwise", f"request {rid!r} field {f} differs "
                        "on re-answer — the durable result is not the "
                        "answer of record"))
                    break
    if lease_history:
        prev_tok, prev_owner = None, None
        for rec in lease_history:
            tok, owner = rec.get("token"), rec.get("owner")
            if tok is None:
                continue
            if prev_tok is not None and tok < prev_tok:
                out.append(InvariantViolation(
                    "fencing", f"lease token regressed {prev_tok} -> "
                    f"{tok} (owner {owner!r})"))
            elif (prev_tok is not None and tok == prev_tok
                    and owner != prev_owner):
                out.append(InvariantViolation(
                    "fencing", f"two owners ({prev_owner!r}, {owner!r}) "
                    f"share token {tok}"))
            prev_tok, prev_owner = tok, owner
    if probes is not None and max_unavailable_s is not None:
        for start, end in unavailability_windows(probes):
            if end - start > float(max_unavailable_s):
                out.append(InvariantViolation(
                    "availability", f"fleet unavailable for "
                    f"{end - start:.2f}s (bound "
                    f"{float(max_unavailable_s):.2f}s) from t={start:.2f}"))
    return out


def join_injections(fired: Sequence[dict],
                    events: Sequence[dict]) -> List[dict]:
    """Join the manifest's ``kill`` injections to their observed fleet
    consequences, from recorder evidence alone (ISSUE 18).

    ``fired`` is the chaos manifest's fired-injection list (each record
    carries at least ``kind``; kills are the ones joined).  ``events``
    is the merged fleet event timeline: recorder event lines as dicts,
    each carrying its recorder ``ts`` and tagged by the caller with the
    ``stream`` it came from (the replica owner, or ``"client"``).

    Injection offsets (monotonic, scenario-relative) and recorder
    timestamps (wall clock) share no common zero, so the join is
    ORDINAL: the N-th kill pairs with the N-th ownership CHANGE — a
    ``fleet.elected`` naming a different owner than the previous
    holder (the fleet's initial election is not a consequence).  Each
    consequence record names the victim and survivor, the victim
    stream's last event before the takeover, and the takeover latency
    (survivor's election ts minus the victim's last ts — a wall-clock
    delta across same-host replica processes, see the clock-offset
    caveats in ``tools/obs_report.py``).  A kill with no matching
    election reports ``observed=False`` (e.g. the handler declined to
    fire because the fleet was already down to one replica).

    Pure function: no clocks, no I/O — callers feed it loaded streams.
    """
    def _attr(e: dict, key: str):
        # recorder event lines nest attributes under "attrs"; accept
        # pre-flattened dicts too so callers need not reshape
        return e[key] if key in e else (e.get("attrs") or {}).get(key)

    kills = [r for r in fired if r.get("kind") == "kill"]
    elected = sorted(
        (e for e in events
         if e.get("name") == "fleet.elected" and e.get("ts") is not None),
        key=lambda e: float(e["ts"]))
    changes: List[Tuple[str, dict]] = []
    holder: Optional[str] = None
    for e in elected:
        who = _attr(e, "owner")
        if holder is not None and who != holder:
            changes.append((holder, e))
        holder = who
    out: List[dict] = []
    for i, kill in enumerate(kills):
        rec: dict = {"injection": dict(kill), "observed": i < len(changes)}
        if i < len(changes):
            victim, e = changes[i]
            t_elect = float(e["ts"])
            last = max((float(v["ts"]) for v in events
                        if v.get("stream") == victim
                        and v.get("ts") is not None
                        and float(v["ts"]) <= t_elect), default=None)
            rec.update({
                "victim": victim,
                "survivor": _attr(e, "owner"),
                "elected_token": _attr(e, "token"),
                "victim_last_ts": last,
                "takeover_latency_s": (None if last is None
                                       else round(t_elect - last, 3)),
            })
        out.append(rec)
    return out


# ---------------------------------------------------------------------------
# the durable scenario record
# ---------------------------------------------------------------------------


def write_chaos_manifest(root: str, manifest: dict) -> str:
    """Atomically write the scenario record (``chaos_manifest.json``)
    at the fleet root — schedule, probe timeline, invariant verdicts,
    counters — for ``tools/advise_budget.py`` and post-mortems."""
    path = os.path.join(os.path.abspath(root), CHAOS_MANIFEST)
    payload = (json.dumps(manifest, sort_keys=True, indent=1,
                          default=repr) + "\n").encode()
    _atomic_write_bytes(path, payload)
    return path


def load_chaos_manifest(root: str) -> dict:
    path = os.path.join(os.path.abspath(root), CHAOS_MANIFEST)
    with open(path, encoding="utf-8") as f:
        return json.load(f)
