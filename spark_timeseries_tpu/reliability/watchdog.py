"""Deadline watchdog: wall-clock budgets for compiled fit dispatch.

Spark bounded a runaway job twice over — ``spark.task.maxFailures`` killed a
task that would not finish, and the driver's scheduler could abandon a stage
that blew its allotment.  The TPU rebuild dispatches one compiled program
per chunk, and a hung compile or a pathological optimizer tail has nothing
above it to pull the plug: the job simply never returns.  This module
rebuilds the bound at the two granularities the chunk driver works in:

- **per-chunk budget** (:func:`call_with_deadline`): the chunk's fit runs in
  a worker thread; if it has not produced a result within ``budget_s`` the
  driver gets :class:`DeadlineExceeded` and moves on, marking the chunk's
  rows ``FitStatus.TIMEOUT`` (and the chunk ``TIMEOUT`` in the journal when
  one is attached).  The overrunning computation is ABANDONED, not
  cancelled — XLA dispatch is not interruptible from Python — so its thread
  may finish in the background; its results are discarded either way.
- **per-job budget** (:class:`Deadline`): a monotonic wall-clock allotment
  for the whole chunk walk.  Once spent, remaining chunks are marked
  ``TIMEOUT`` *without dispatch*, so a journaled job always terminates with
  an accurate per-chunk account instead of hanging past its SLO.

Both degrade gracefully by design: a timed-out chunk never aborts the job;
finished chunks keep their results and the partial output reports exact
per-row status counts.  A later resume (``checkpoint_dir=``) retries only
the TIMEOUT/pending chunks.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Optional

from .. import obs

__all__ = ["Deadline", "DeadlineExceeded", "call_with_deadline",
           "current_lane", "current_request", "lane_context",
           "request_context"]

# -- lane identity (ISSUE 11) -----------------------------------------------
# The elastic sharded walk needs to know, from INSIDE a fit call, which lane
# dispatched it: the deterministic lane-targeted faults
# (reliability.faultinject.lane_kill / slow_lane / lane_oom_storm) key on it,
# and it keeps working across the thread hop call_with_deadline performs for
# budgeted chunks.  Thread-local by design — concurrent lanes each see their
# own id; code outside any lane sees None.
_lane_ctx = threading.local()


def current_lane() -> Optional[int]:
    """Shard id of the lane whose walk is executing on THIS thread (set by
    ``plan.LaneRunner`` around every chunk dispatch, and propagated into
    the watchdog worker thread for budgeted chunks); None outside a lane."""
    return getattr(_lane_ctx, "shard_id", None)


@contextlib.contextmanager
def lane_context(shard_id: Optional[int]):
    """Tag the current thread as running lane ``shard_id`` (None: untag)."""
    prev = getattr(_lane_ctx, "shard_id", None)
    _lane_ctx.shard_id = shard_id
    try:
        yield
    finally:
        _lane_ctx.shard_id = prev


# -- request identity (ISSUE 12) ---------------------------------------------
# The serving layer's twin of the lane tag: a FitServer batch walk serves
# several tenants' requests in ONE fit program, and the request-level fault
# injectors (reliability.faultinject.slow_tenant / server_kill targeting)
# need to know, from inside a fit call, WHOSE work is on this thread.  The
# tag is the tuple of tenant ids riding the active micro-batch (or a single
# request's tenant for a solo run), propagated across the watchdog's worker
# thread hop exactly like the lane tag.


def current_request() -> Optional[tuple]:
    """Tenant tags of the serving request/batch executing on THIS thread
    (set by ``serving.FitServer`` around each batch walk); None outside."""
    return getattr(_lane_ctx, "request_tags", None)


@contextlib.contextmanager
def request_context(tags):
    """Tag the current thread as serving ``tags`` (a tuple of tenant ids;
    None: untag)."""
    prev = getattr(_lane_ctx, "request_tags", None)
    _lane_ctx.request_tags = tuple(tags) if tags is not None else None
    try:
        yield
    finally:
        _lane_ctx.request_tags = prev


class DeadlineExceeded(RuntimeError):
    """A fit dispatch (or the whole job) overran its wall-clock budget."""

    def __init__(self, label: str, budget_s: float):
        super().__init__(
            f"{label or 'fit dispatch'} exceeded its {budget_s:g}s wall-clock "
            "budget (reliability.watchdog)"
        )
        self.label = label
        self.budget_s = budget_s


class Deadline:
    """A monotonic wall-clock allotment for a whole job.

    ``budget_s=None`` means unbounded (every query answers "plenty left").
    The clock starts at construction — build it when the job starts.
    """

    def __init__(self, budget_s: Optional[float] = None):
        self.budget_s = None if budget_s is None else float(budget_s)
        self._t0 = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    def remaining(self) -> Optional[float]:
        """Seconds left, or None when unbounded.  Can be negative."""
        if self.budget_s is None:
            return None
        return self.budget_s - self.elapsed()

    def exceeded(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0.0


def call_with_deadline(fn: Callable, budget_s: Optional[float] = None,
                       *, label: str = "", lane: Optional[int] = None):
    """Run ``fn()`` with at most ``budget_s`` seconds of wall clock.

    ``budget_s=None`` calls ``fn`` inline (zero overhead).  Otherwise ``fn``
    runs in a daemon worker thread and this call blocks up to ``budget_s``:
    a result (or the exception ``fn`` raised — re-raised here unchanged, so
    OOM backoff still sees RESOURCE_EXHAUSTED through the watchdog) within
    the budget is returned normally; overrunning raises
    :class:`DeadlineExceeded` and ABANDONS the worker — the computation is
    not cancelled (XLA dispatch cannot be interrupted from Python), its
    eventual result is discarded, and the thread dies with the process.

    ``lane=`` propagates the calling lane's identity into the worker
    thread (:func:`current_lane`), so lane-targeted fault injection and
    per-lane accounting survive the thread hop; ``None`` inherits the
    caller's lane tag.
    """
    if lane is None:
        lane = current_lane()
    req = current_request()  # serving request tag survives the hop too
    tctx = obs.current_trace()  # and so does the trace context (ISSUE 18)
    if budget_s is None:
        with lane_context(lane):
            return fn()
    box: dict = {}
    done = threading.Event()

    def worker():
        try:
            with lane_context(lane), request_context(req), \
                    obs.trace_scope(tctx):
                box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 - re-raised in the caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=worker, daemon=True,
                         name=f"watchdog:{label or 'fit'}")
    t.start()
    if not done.wait(timeout=float(budget_s)):
        obs.counter("watchdog.deadline_exceeded").inc()
        obs.event("watchdog.timeout", label=label, budget_s=float(budget_s))
        raise DeadlineExceeded(label, float(budget_s))
    if "error" in box:
        raise box["error"]
    return box["result"]
