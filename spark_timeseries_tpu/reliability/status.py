"""Per-row fit status vocabulary shared by every fit path.

The reference's robustness story is Spark task retry: a failed executor task
re-runs elsewhere and the driver log says what happened to each partition.
The TPU rebuild fits the whole panel in one vmapped program, so "what
happened" must be a per-ROW record instead: every public ``fit`` returns a
``status`` array of :class:`FitStatus` codes alongside the parameters, and
the resilient runner (``reliability.runner``) refines those codes as rows
move through the sanitize -> fit -> retry -> fallback ladder.

Codes are ordered by severity so ladder stages can be merged with an
elementwise ``maximum`` — a row keeps the most severe thing that happened
to it:

====  ==========  ====================================================
code  name        meaning
====  ==========  ====================================================
0     OK          fit converged on the primary path, params finite
1     SANITIZED   input was repaired (NaN/Inf imputed) before fitting
2     RETRIED     primary fit failed; a retry rung (perturbed init /
                  larger budget) succeeded
3     FALLBACK    retries failed; the conservative fallback rung
                  (portable backend, no compaction) succeeded
4     DIVERGED    every rung failed; params are NaN, row is flagged
                  instead of poisoning the batch
5     EXCLUDED    input rejected before/without fitting (all-NaN,
                  constant, too short, or policy="exclude" hit)
6     TIMEOUT     the chunk holding the row overran its wall-clock
                  budget (reliability.watchdog); the fit never
                  finished, params are NaN
====  ==========  ====================================================
"""

from __future__ import annotations

import enum

import numpy as np


class FitStatus(enum.IntEnum):
    """Severity-ordered per-row fit outcome (see module docstring)."""

    OK = 0
    SANITIZED = 1
    RETRIED = 2
    FALLBACK = 3
    DIVERGED = 4
    EXCLUDED = 5
    TIMEOUT = 6


# dtype every status array uses (device and host side)
STATUS_DTYPE = np.int8


def status_counts(status) -> dict:
    """``{status_name: row_count}`` for a status array (host-side)."""
    s = np.asarray(status)
    return {m.name: int((s == m.value).sum()) for m in FitStatus}


def merge_status(a, b):
    """Elementwise most-severe-wins merge of two status arrays."""
    return np.maximum(np.asarray(a), np.asarray(b)).astype(STATUS_DTYPE)
