"""Delta walks: incremental refit for appended and revised panels (ISSUE 15).

Every walk through PR 13 refit the whole panel from scratch even when only
a sliver of data changed — the ROADMAP's tick-to-fit scenario (a market
feed appends ticks every minute) paid full-refit cost for a 1% change.
The journal already made chunks durable and the warm-start machinery
(PR 9's basin refits, PR 13's augmented init-param columns) made refits
cheap; what was missing was a per-chunk content identity and a planner
that uses it.  This module is that planner:

- **Identity** — journal version 2 manifests record a
  ``chunk_fingerprint`` in every committed chunk entry: a strided content
  hash of the chunk's OWN rows (``journal.chunk_fingerprint``), computed
  host-streamed through ``ChunkSource.read_rows`` (or a device-slice
  sample — same bytes by the staging identity contract), so npz, host,
  and device residencies fingerprint a chunk identically.  The manifest's
  ``extra.chunk_fp_cols`` records how many leading DATA columns the
  fingerprints cover (a warm delta walk's panel carries init columns the
  fingerprints deliberately exclude).

- **Planning** — :func:`plan_delta` diffs a new panel against a committed
  journal and classifies each prior chunk:

  * **clean** — identical rows (fingerprint match, same time length):
    adopt the committed result byte-for-byte, ZERO compute.  Sound
    because the walk is deterministic: refitting identical rows under an
    identical config reproduces identical bytes, so adoption IS the
    from-scratch result.  Requires the prior config hash to match the
    new walk's (enforced by the driver before any compute).
  * **warm** — the chunk's history GREW (new time steps appended) but
    the old prefix is byte-identical: refit, warm-started from the
    journaled params via augmented init-param columns
    (:class:`WarmstartFit` — exactly PR 9's basin-refit trick).  Warm
    results are pinned bitwise against a warm-started full walk of the
    same augmented panel (iteration counts differ from a cold fit, so
    the cold walk is not the reference here).
  * **dirty / new** — revised rows, rows never committed, or rows beyond
    the prior panel: full refit.

- **Execution** — ``fit_chunked(delta_from=root)`` (and
  ``panel.fit(delta_from=...)``) journals the delta walk into a NEW
  namespace: clean chunks are spliced in up front as ordinary commits
  (entry ``delta.class == "adopted"``, naming the source manifest), so
  the ordinary resume machinery skips them and the walk runs ONLY
  warm+dirty chunks — pipelining, prefetch, sources, sharding, elastic
  lanes, and the FitServer compose with no new driver code, and a
  SIGKILLed delta walk resumes without ever recomputing an adopted
  chunk.  ``delta_warmstart=False`` (exact mode) refits warm chunks
  cold, keeping the whole result bitwise-identical to a from-scratch
  cold walk of the new panel on the same chunk grid.

A prior journal that cannot support the contract is rejected LOUDLY
(:class:`StalePriorError`): version-1 manifests without chunk
fingerprints (still resumable, not delta-eligible), shrunk panels,
shrunk time axes, or a same-shape prior fitted under a different config.
"""

from __future__ import annotations

import inspect
import os
import zipfile
from typing import List, NamedTuple, Optional

import numpy as np

from . import source as source_mod
from ..utils import optim
from .journal import (JournalError, TornManifestError, chunk_fingerprint,
                      chunk_sample_steps)

__all__ = [
    "ChunkClass",
    "DeltaError",
    "DeltaPlan",
    "StalePriorError",
    "WarmstartFit",
    "chunk_fp_fn",
    "plan_delta",
    "warm_panel",
]


class DeltaError(JournalError):
    """A delta walk cannot be planned against this prior journal."""


class StalePriorError(DeltaError):
    """The prior journal is structurally incompatible with the new panel
    (or was fitted under a different configuration) — refit from scratch
    or point ``delta_from`` at the right journal."""


class ChunkClass(NamedTuple):
    """One span of the delta plan's grid."""

    lo: int
    hi: int
    cls: str  # "adopted" | "warm" | "dirty" | "new"


class DeltaPlan(NamedTuple):
    """The classified chunk grid of a delta walk (see module docstring).

    ``chunks`` covers ``[0, n_rows_new)`` exactly, ascending and
    disjoint; ``counts`` tallies the classes; ``adopted`` carries each
    clean chunk's prior manifest entry and its shard PATH (structurally
    checked at plan time so a torn prior shard downgrades to dirty, not
    into spliced bytes — adoption then copies the file's bytes
    verbatim); ``init`` is the ``[n_rows_new, k]``
    warm-start matrix (prior params on warm rows, NaN elsewhere — the
    :class:`WarmstartFit` wrapper zeroes non-finite inits), None when no
    warm chunk exists or ``warmstart=False``.
    """

    prior_dir: str
    manifest: dict
    grown: bool
    data_cols: int
    chunk_rows: int
    chunks: List[ChunkClass]
    counts: dict
    adopted: list  # [(prior_entry, shard_path), ...]
    k: Optional[int]
    init: Optional[np.ndarray]
    prior_config_hash: Optional[str]


# probe-and-compact engagement gates (module-level so tests can
# monkeypatch them): a warm chunk below _PROBE_MIN_ROWS is too small for
# the two-dispatch overhead to pay off, and a probe below _PROBE_MIN_ITERS
# would flag healthy warm rows as stragglers
_PROBE_MIN_ROWS = 64
_PROBE_MIN_ITERS = 4


def _probe_plan(fit_fn, rows: int, kw: dict):
    """``(full_iters, probe_iters)`` when the probe-and-compact economy
    can engage for this dispatch, else ``None`` (plain single-dispatch
    path).  Requires the inner fit to expose ``max_iters`` and
    ``init_params``, and enough rows/budget for the split to pay.  The
    full budget comes from the caller's pinned ``max_iters=`` kwarg when
    present (ISSUE 20 — the delta walks ``fit_chunked`` drives always
    pin it, and they are exactly the warm dispatches compaction exists
    for), else from the fit signature's concrete default
    (``functools.partial`` bindings surface there)."""
    if rows < _PROBE_MIN_ROWS:
        return None
    try:
        sig = inspect.signature(fit_fn)
    except (TypeError, ValueError):
        return None
    if "max_iters" not in sig.parameters or \
            "init_params" not in sig.parameters:
        return None
    full = kw.get("max_iters", sig.parameters["max_iters"].default)
    if isinstance(full, bool) or not isinstance(full, int) or \
            full < 2 * _PROBE_MIN_ITERS:
        return None
    # probe budget: the lockstep dispatch pays for every iteration the
    # probe rides, so the budget is the economy's whole margin.  Warm
    # rows converge in a handful of steps (measured locally: mean ~2
    # iters per row at tick-loop sizes) while full // 8 still rides 12
    # of a 96-iter budget; full // 16 halves the probe's lockstep cost
    # and only moves rows converging inside [full//16, full//8) into
    # the straggler refit — same composite result, cheaper stage 1
    return int(full), max(_PROBE_MIN_ITERS, int(full) // 16)


class WarmstartFit:
    """Chunk fit function for a warm-started delta refit.

    The walk's panel is augmented ``[y (n_time) | init params (k)]``;
    each chunk fit slices its own init columns and hands them to the
    underlying model fit as ``init_params`` — per chunk, so the warm
    start rides any chunking/sharding/streaming, exactly like PR 13's
    backtest windows.  Non-finite inits (dirty/new rows, or a failed
    prior row) are zeroed — the model's cold-ish default, mirroring the
    winners refit.  Run with ``resilient=False``: the sanitizer must
    never "repair" init-param columns.

    **Probe-and-compact** (ISSUE 19): a warm start converges most rows
    in a handful of iterations, but a lockstep batched optimizer still
    streams the WHOLE panel until its slowest row terminates.  Large
    dispatches therefore run in two stages: a full-width probe at
    ``max_iters // 16``, then the straggler rows (still running when the
    probe budget lapsed) gathered into a ``optim.retry_cap``-aligned
    sub-batch and refit at the full budget FROM THE ORIGINAL INIT (pad
    tail drops on scatter).  The composite is *equivalent* to the
    single full-budget dispatch — identical convergence/status maps,
    params to optimizer tolerance — but NOT bitwise: the compacted
    refit is a different compiled program (the ``retry_cap`` shape
    bucket), and cross-program trajectories are out of scope exactly as
    on the pallas backends.  What resume leans on instead is
    DETERMINISM: the same dispatch replays the same bytes.  Pinned by
    the warm-routing tests; ``compact=False`` forces the exact
    single-dispatch path.

    The instance carries a stable ``__qualname__`` naming the inner fit
    and the column split, so ``journal.config_hash`` hashes the warm
    configuration deterministically across runs (a bare callable's repr
    would embed a memory address and break resume).  Because compaction
    changes the bytes a chunk commits, ``compact=False`` is part of the
    qualname: journals written in one mode must not silently adopt the
    other's chunks on resume.
    """

    def __init__(self, fit_fn, n_time: int, k: int, *, compact: bool = True):
        self.fit_fn = fit_fn
        self.n_time = int(n_time)
        self.k = int(k)
        self.compact = bool(compact)
        inner = (getattr(fit_fn, "__module__", "?") + "."
                 + getattr(fit_fn, "__qualname__", repr(fit_fn)))
        self.__qualname__ = (f"WarmstartFit({inner}, "
                             f"n_time={self.n_time}, k={self.k}"
                             + ("" if self.compact else ", compact=False")
                             + ")")

    def __call__(self, aug, *, align_mode=None, **kw):
        import jax.numpy as jnp

        aug = jnp.asarray(aug)
        y = aug[:, :self.n_time]
        init = aug[:, self.n_time:self.n_time + self.k]
        init = jnp.where(jnp.isfinite(init), init, 0.0)
        if align_mode is not None:
            kw["align_mode"] = align_mode
        plan = (_probe_plan(self.fit_fn, int(y.shape[0]), kw)
                if self.compact else None)
        if plan is None:
            return self.fit_fn(y, init_params=init, **kw)
        _, probe_iters = plan
        # the probe's max_iters OVERRIDES a caller-pinned budget; the
        # straggler sub-dispatch (and the too-many-stragglers bail) keep
        # the caller's kw untouched, i.e. the full budget
        probe_kw = {k2: v for k2, v in kw.items() if k2 != "max_iters"}
        probe = self.fit_fn(y, init_params=init, max_iters=probe_iters,
                            **probe_kw)
        # the straggler set gates the second dispatch — a host decision
        # by design, exactly like the resilient ladder's retry gather
        iters = np.asarray(probe.iters)
        conv = np.asarray(probe.converged)
        stragglers = np.nonzero((iters >= probe_iters) & ~conv)[0]
        if stragglers.size == 0:
            return probe
        cap = optim.retry_cap(int(stragglers.size))
        if 2 * cap > int(y.shape[0]):
            # too many stragglers for the compacted shape to pay: eat the
            # probe and run the plain full-budget dispatch
            return self.fit_fn(y, init_params=init, **kw)
        gi = jnp.asarray(optim.gather_pad_indices(stragglers, cap))
        sub = self.fit_fn(y[gi], init_params=init[gi], **kw)
        rows = jnp.asarray(stragglers)
        n = int(stragglers.size)
        out = []
        for field in probe._fields:
            pv, sv = getattr(probe, field), getattr(sub, field)
            if pv is None or sv is None:
                out.append(pv)
                continue
            out.append(jnp.asarray(pv).at[rows].set(
                jnp.asarray(sv)[:n]))
        return type(probe)(*out)

    def __repr__(self):
        return self.__qualname__


def chunk_fp_fn(src, yb, data_cols: int):
    """``fp(lo, hi) -> str`` sampler over ONE panel residency.

    ``src`` (a :class:`~.source.ChunkSource`) streams sampled rows on the
    host through ``read_rows``; ``yb`` (device/host array) slices the
    strided sample directly.  Both hash the identical bytes (the staging
    identity contract: a staged chunk IS ``panel[lo:hi]``), so journals
    written from any residency agree on every chunk fingerprint.
    ``data_cols`` bounds the hash to the panel's leading DATA columns —
    a warm delta walk's init columns never reach the fingerprint, which
    is what lets tick-feed chains delta from a warm journal.
    """
    cols = int(data_cols)
    if src is not None:
        t_full = int(src.shape[1])
        dtype = src.dtype

        def fp(lo: int, hi: int) -> str:
            lo, hi = int(lo), int(hi)
            n = hi - lo
            sr, sc = chunk_sample_steps(n, cols)
            rows = range(lo, hi, sr)
            buf = np.empty((1, t_full), dtype)
            sample = np.empty((len(rows), len(range(0, cols, sc))), dtype)
            for i, r in enumerate(rows):
                src.read_rows(r, r + 1, buf)
                sample[i] = buf[0, :cols:sc]
            return chunk_fingerprint(sample, n, cols)
    else:

        def fp(lo: int, hi: int) -> str:
            lo, hi = int(lo), int(hi)
            n = hi - lo
            sr, sc = chunk_sample_steps(n, cols)
            # commit-path content fingerprint: the D2H sample runs on
            # the committer thread next to the result fetch, never on
            # the driver's dispatch path
            sample = np.asarray(yb[lo:hi:sr, :cols:sc])
            return chunk_fingerprint(sample, n, cols)

    return fp


def load_prior(prior_root: str) -> dict:
    """The prior job's root manifest, with torn/missing writes loud."""
    import json

    root = os.path.abspath(os.fspath(prior_root))
    path = os.path.join(root, "manifest.json")
    if not os.path.exists(path):
        raise DeltaError(
            f"delta_from={root} holds no manifest.json — a delta walk "
            "needs a COMMITTED prior journal (for a sharded prior, the "
            "merged root manifest)")
    try:
        with open(path, "rb") as f:
            return json.loads(f.read().decode())
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise TornManifestError(
            f"prior manifest {path} does not parse ({e}); inspect/remove "
            "the journal explicitly before planning a delta against it."
        ) from e


def _load_shard(root: str, entry: dict) -> Optional[dict]:
    """A committed chunk's result arrays, None when the shard is
    unreadable (the planner downgrades it to dirty — adoption must never
    splice torn bytes)."""
    path = os.path.join(root, entry["shard"])
    try:
        with np.load(path, allow_pickle=False) as z:
            arrays = {k: z[k] for k in
                      ("params", "nll", "converged", "iters", "status")}
    except (OSError, ValueError, KeyError, zipfile.BadZipFile):
        return None
    if arrays["params"].shape[0] != entry["hi"] - entry["lo"]:
        return None
    return arrays


def _check_shard(root: str, entry: dict) -> Optional[str]:
    """Light structural check of a prior shard (zip directory + member
    headers, no decompression): the adoption fast path COPIES the file's
    bytes, so the planner only needs to know the shard is whole and
    holds the expected arrays at the expected row count.  Returns the
    path, or None (downgrade to dirty) when damaged."""
    path = os.path.join(root, entry["shard"])
    try:
        with zipfile.ZipFile(path) as zf:
            names = set(zf.namelist())
            if {"params.npy", "nll.npy", "converged.npy", "iters.npy",
                    "status.npy"} - names:
                return None
            from .source import _npz_member_header

            shape, _dt = _npz_member_header(zf, "params.npy")
    except (OSError, ValueError, KeyError, zipfile.BadZipFile):
        return None
    if not shape or int(shape[0]) != int(entry["hi"]) - int(entry["lo"]):
        return None
    return path


def assemble_params(manifest: dict, root: str):
    """``[n_rows, k]`` params assembled from the committed shards (NaN on
    uncovered rows), or ``(None, None)`` when nothing committed."""
    params = None
    for e in manifest.get("chunks", []):
        if e.get("status") != "committed":
            continue
        arrays = _load_shard(root, e)
        if arrays is None:
            continue
        p = np.asarray(arrays["params"])
        if params is None:
            params = np.full((int(manifest["n_rows"]), p.shape[1]),
                             np.nan, p.dtype)
        if p.shape[1] == params.shape[1]:
            params[int(e["lo"]):int(e["hi"])] = p
    if params is None:
        return None, None
    return params, int(params.shape[1])


def plan_delta(prior_root, panel, *, chunk_rows: Optional[int] = None,
               warmstart: bool = True) -> DeltaPlan:
    """Classify every chunk of ``panel`` against the committed journal at
    ``prior_root`` (see module docstring for the classes and their
    contracts).  ``panel`` is a device/host array or any
    :class:`~.source.ChunkSource`; ``chunk_rows`` defaults to the prior
    walk's, keeping the grids aligned.  Raises :class:`StalePriorError`
    for priors that cannot support a delta (no chunk fingerprints,
    shrunk rows/time)."""
    root = os.path.abspath(os.fspath(prior_root))
    m = load_prior(root)

    if isinstance(panel, source_mod.ChunkSource):
        if isinstance(panel, source_mod.DeviceChunkSource):
            src, yb = None, panel.array
            b, t_new = int(yb.shape[0]), int(yb.shape[1])
        else:
            src, yb = panel, None
            b, t_new = int(panel.shape[0]), int(panel.shape[1])
    else:
        src, yb = None, panel
        if yb.ndim != 2:
            raise ValueError(f"expected [batch, time], got {yb.shape}")
        b, t_new = int(yb.shape[0]), int(yb.shape[1])

    committed = [e for e in m.get("chunks", [])
                 if e.get("status") == "committed"]
    if committed and any("chunk_fingerprint" not in e for e in committed):
        raise StalePriorError(
            f"prior journal {root} has committed chunks without "
            "chunk_fingerprint entries (journal version "
            f"{m.get('journal_version')}, written before delta support). "
            "It remains fully RESUMABLE, but a delta walk cannot prove "
            "which chunks are unchanged — run one full refit with this "
            "code (writing a version-2 manifest), then delta from that.")
    prior_cols = int((m.get("extra") or {}).get("chunk_fp_cols")
                     or ((m.get("extra") or {}).get("panel") or {})
                     .get("time") or 0)
    if prior_cols <= 0:
        raise StalePriorError(
            f"prior journal {root} records no chunk_fp_cols/panel "
            "geometry; cannot align its chunk fingerprints with the new "
            "panel — run one full refit to refresh the manifest.")
    b_prior = int(m.get("n_rows", 0))
    if b < b_prior:
        raise StalePriorError(
            f"new panel has {b} rows but the prior journal fitted "
            f"{b_prior}; rows disappeared — a delta cannot reconcile a "
            "shrunk panel (refit from scratch).")
    if t_new < prior_cols:
        raise StalePriorError(
            f"new panel has {t_new} time steps but the prior journal's "
            f"chunks fingerprint {prior_cols}; the time axis shrank — a "
            "delta cannot reconcile truncated history (refit from "
            "scratch).")
    grown = t_new > prior_cols

    step = int(chunk_rows or m.get("chunk_rows") or b_prior or b)
    step = max(1, min(step, b))
    if not grown and int(m.get("chunk_rows") or 0) != step:
        # adoption splices prior-grid chunks into this walk's grid; a
        # mismatch would mix chunk shapes (and, sharded, overlap lanes).
        # The config hash covers chunk_rows too, but this names the
        # actual problem instead of a bare hash mismatch.
        raise StalePriorError(
            f"prior journal {root} walked a {m.get('chunk_rows')}-row "
            f"chunk grid but this walk uses {step}; adoption requires "
            "the SAME grid — pass chunk_rows to match (or omit it: the "
            "delta defaults to the prior grid).")

    fp = chunk_fp_fn(src, yb, prior_cols)
    chunks: List[ChunkClass] = []
    adopted: list = []
    warm_spans: list = []
    counts = {"adopted": 0, "warm": 0, "dirty": 0, "new": 0}

    def _note(lo, hi, cls):
        chunks.append(ChunkClass(int(lo), int(hi), cls))
        counts[cls] += 1

    def _fill(lo, hi, cls):
        # an uncovered region starts at a committed boundary, exactly
        # where the walk will dispatch from — split it on the grid step
        # the walk will use
        pos = int(lo)
        while pos < hi:
            _note(pos, min(pos + step, hi), cls)
            pos = min(pos + step, hi)

    pos = 0
    for e in sorted(committed, key=lambda e: e["lo"]):
        lo, hi = int(e["lo"]), int(e["hi"])
        if lo > pos:
            _fill(pos, lo, "dirty")  # never committed in the prior walk
        same = fp(lo, hi) == e.get("chunk_fingerprint")
        # adoption must land on the grid the cold walk would chunk: an
        # off-grid prior boundary (OOM backoff, or a trailing partial
        # chunk with rows appended after it) would shift every
        # downstream computed chunk's shape — and chunk SHAPE ties the
        # lockstep optimizer's low-order result bits, silently breaking
        # the bitwise-vs-cold-walk contract.  hi == b is the one legal
        # off-grid end: the panel truly ends there in BOTH walks.
        aligned = lo % step == 0 and (hi % step == 0 or hi == b)
        if same and not grown and aligned:
            shard_path = _check_shard(root, e)
            if shard_path is None:
                _note(lo, hi, "dirty")  # prior shard torn: recompute
            else:
                _note(lo, hi, "adopted")
                adopted.append((e, shard_path))
        elif same and grown and warmstart:
            _note(lo, hi, "warm")
            warm_spans.append((lo, hi))
        else:
            _note(lo, hi, "dirty")
        pos = hi
    if pos < b_prior:
        _fill(pos, b_prior, "dirty")
    if b > b_prior:
        _fill(b_prior, b, "new")

    k = init = None
    if warm_spans:
        params, k = assemble_params(m, root)
        if params is None:
            # nothing committed durably enough to warm from: recompute
            chunks = [ChunkClass(lo, hi, "dirty" if cls == "warm" else cls)
                      for lo, hi, cls in chunks]
            counts["dirty"] += counts.pop("warm")
            counts["warm"] = 0
            warm_spans = []
        else:
            dtype = (src.dtype if src is not None
                     else np.dtype(str(yb.dtype)))
            init = np.full((b, k), np.nan, dtype)
            for lo, hi in warm_spans:
                init[lo:hi] = params[lo:hi].astype(dtype)

    return DeltaPlan(
        prior_dir=root, manifest=m, grown=grown, data_cols=prior_cols,
        chunk_rows=step, chunks=chunks, counts=counts, adopted=adopted,
        k=k, init=init, prior_config_hash=m.get("config_hash"))


def warm_panel(panel, init: np.ndarray):
    """The augmented ``[y | init params]`` panel in the input's own
    residency: device arrays concatenate on device; a
    :class:`~.source.ChunkSource` composes into a streaming
    ``ColumnBlockSource`` serving the init columns from host RAM (byte
    positions identical either way)."""
    init = np.asarray(init)
    if isinstance(panel, source_mod.ChunkSource) and not isinstance(
            panel, source_mod.DeviceChunkSource):
        # lazy: forecasting composes on reliability, not the reverse —
        # ColumnBlockSource is pure source machinery and safe to borrow
        from ..forecasting.augment import ColumnBlockSource

        return ColumnBlockSource(
            [(panel, 0, int(panel.shape[1])),
             np.ascontiguousarray(init.astype(panel.dtype))])
    import jax.numpy as jnp

    yb = (panel.array if isinstance(panel, source_mod.DeviceChunkSource)
          else jnp.asarray(panel))
    return jnp.concatenate(
        [yb, jnp.asarray(init.astype(np.dtype(str(yb.dtype))))], axis=1)


def delta_extra(plan: DeltaPlan, *, warmstart: bool, data_cols: int) -> dict:
    """The manifest ``extra.delta`` provenance block: where the adopted
    chunks came from, what the plan decided, and how many data columns
    the new walk's chunk fingerprints cover.  ``tools/obs_report.py
    --check`` validates the block (counts sum to the grid, adopted
    entries name their source manifest); ``tools/advise_budget.py``
    turns the dirty fraction into advice."""
    return {
        "from": plan.prior_dir,
        "source_manifest": os.path.join(plan.prior_dir, "manifest.json"),
        "prior_run_id": plan.manifest.get("run_id"),
        "prior_config_hash": plan.prior_config_hash,
        "warmstart": bool(warmstart),
        "data_cols": int(data_cols),
        "counts": dict(plan.counts),
        "chunks": [[c.lo, c.hi, c.cls] for c in plan.chunks],
    }
