"""Input sanitization pass: repair or reject rows a batched fit cannot survive.

The reference could lean on per-series JVM exceptions — one NaN-laced series
threw inside its own executor task and Spark retried or dropped that task.
A monolithic vmapped fit has no such isolation: every row shares one
program, so bad input must be found and neutralized BEFORE the fit.  Models
already tolerate leading/trailing NaNs (the ragged-panel contract,
``models.base.align_right``); what they cannot tolerate is

- ``inf``/``-inf`` anywhere (squares overflow, gradients go non-finite),
- NaN *inside* the valid span (``align_right`` zero-fills them, silently
  biasing the fit),
- constant rows (zero innovation variance -> ``log(0)`` objectives), and
- all-NaN rows (nothing to fit).

:func:`sanitize` detects all four with one fused device pass and applies a
configurable policy, emitting a per-row :class:`~.status.FitStatus` code.
Rows it does not touch are returned BIT-IDENTICAL, so healthy rows fit
exactly as they would have without the pass.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..ops import univariate as uv
from .status import STATUS_DTYPE, FitStatus

POLICIES = ("impute", "exclude", "raise")


class SanitizeReport(NamedTuple):
    """Output of :func:`sanitize`."""

    values: jax.Array  # [B, T] cleaned panel (untouched rows bit-identical)
    status: np.ndarray  # [B] int8: OK / SANITIZED / EXCLUDED
    flags: dict  # per-row bool masks: had_inf / interior_nan / constant / all_nan
    meta: dict  # summary counts for result metadata


@jax.jit  # module-level: one compile per panel shape
def _probe(yb):
    """One fused pass: per-row fault masks (no repair work — the fill runs
    in :func:`_impute` only when a repairable row actually exists, so the
    clean-panel hot path pays masks-only)."""
    t = jnp.arange(yb.shape[1])[None, :]
    had_inf = jnp.any(jnp.isinf(yb), axis=1)
    y1 = jnp.where(jnp.isinf(yb), jnp.nan, yb)  # non-inf entries bit-identical
    valid = ~jnp.isnan(y1)
    any_valid = jnp.any(valid, axis=1)
    first = jnp.argmax(valid, axis=1)
    last = yb.shape[1] - 1 - jnp.argmax(valid[:, ::-1], axis=1)
    inside = (t >= first[:, None]) & (t <= last[:, None])
    interior_nan = jnp.any(inside & ~valid, axis=1)
    hi = jnp.max(jnp.where(valid, y1, -jnp.inf), axis=1)
    lo = jnp.min(jnp.where(valid, y1, jnp.inf), axis=1)
    constant = any_valid & (hi == lo)
    return y1, had_inf, interior_nan, constant, ~any_valid


@jax.jit
def _impute(y1, repair_mask):
    """Linear-fill interior gaps of the flagged rows (others bit-identical)."""
    filled = jax.vmap(uv.fill_linear)(y1)  # interior gaps only; edges stay NaN
    return jnp.where(repair_mask[:, None], filled, y1)


def sanitize(y, policy: str = "impute") -> SanitizeReport:
    """Detect and handle non-finite / degenerate rows of a ``[B, T]`` panel.

    ``policy`` governs rows with repairable faults (inf entries or NaNs
    inside the valid span):

    - ``"impute"``: inf -> NaN, interior NaNs linearly interpolated
      (``ops.univariate.fill_linear``); the row is flagged ``SANITIZED``.
    - ``"exclude"``: the row is replaced by all-NaN (models return NaN
      params for it without touching its neighbors) and flagged
      ``EXCLUDED``.
    - ``"raise"``: a ``ValueError`` naming the offending rows.

    Constant and all-NaN rows are unrepairable (no innovation variance /
    nothing to fit): they are excluded under both non-raising policies.
    Leading/trailing NaNs alone are NOT faults — ragged panels pass
    through untouched (the ``align_right`` contract).
    """
    if policy not in POLICIES:
        raise ValueError(f"unknown sanitize policy {policy!r} (one of {POLICIES})")
    yb = jnp.asarray(y)
    if yb.ndim != 2:
        raise ValueError(f"sanitize expects [batch, time], got {yb.shape}")
    with obs.span("sanitize", rows=int(yb.shape[0]), policy=policy):
        return _sanitize_timed(yb, policy)


def _sanitize_timed(yb, policy: str) -> SanitizeReport:
    y1, had_inf, interior_nan, constant, all_nan = _probe(yb)
    had_inf = np.asarray(had_inf)
    interior_nan = np.asarray(interior_nan)
    constant = np.asarray(constant)
    all_nan = np.asarray(all_nan)

    repairable = had_inf | interior_nan
    unusable = constant | all_nan
    if policy == "raise" and (repairable | unusable).any():
        bad = np.nonzero(repairable | unusable)[0]
        raise ValueError(
            f"{bad.size} rows failed sanitization (policy='raise'), e.g. rows "
            f"{bad[:5].tolist()}: inf={int(had_inf.sum())}, "
            f"interior NaN={int(interior_nan.sum())}, "
            f"constant={int(constant.sum())}, all-NaN={int(all_nan.sum())}"
        )

    status = np.zeros(yb.shape[0], STATUS_DTYPE)
    if policy == "impute":
        excluded = unusable
        status[repairable & ~excluded] = FitStatus.SANITIZED
    else:  # exclude
        excluded = unusable | repairable
    status[excluded] = FitStatus.EXCLUDED

    out = y1
    if policy == "impute" and repairable.any():
        out = _impute(out, jnp.asarray(repairable))
    if excluded.any():
        out = jnp.where(jnp.asarray(excluded)[:, None], jnp.nan, out)

    flags = {
        "had_inf": had_inf,
        "interior_nan": interior_nan,
        "constant": constant,
        "all_nan": all_nan,
    }
    meta = {
        "policy": policy,
        "rows_sanitized": int((status == FitStatus.SANITIZED).sum()),
        "rows_excluded": int((status == FitStatus.EXCLUDED).sum()),
        **{f"rows_{k}": int(v.sum()) for k, v in flags.items()},
    }
    # telemetry: sanitizer actions as monotonic counters (no-ops when off)
    obs.counter("sanitize.rows_checked").add(int(yb.shape[0]))
    obs.counter("sanitize.rows_sanitized").add(meta["rows_sanitized"])
    obs.counter("sanitize.rows_excluded").add(meta["rows_excluded"])
    for k, v in flags.items():
        n = int(v.sum())
        if n:
            obs.counter(f"sanitize.rows_{k}").add(n)
    return SanitizeReport(out, status, flags, meta)
