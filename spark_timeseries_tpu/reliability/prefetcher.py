"""Background chunk prefetcher: stage the NEXT chunk's device slice while
the current chunk computes.

The pipelined chunk driver (PR 4) hid the journal's *output* side — host
fetch + shard + manifest I/O run on the :class:`~.committer.ChunkCommitter`
while the device computes the next chunk.  The *input* side still stalled
the driver: each walk slice ``yb[lo:hi]`` is a fresh device buffer staged
when the driver reaches the chunk, and for resilient fits (which block on
host-side assembly per chunk) the slice of chunk N+1 could not even
dispatch until chunk N's host work finished.  This module is the input
half of that pipeline — the producer of a training-style input pipeline,
mirroring the committer's design: ONE daemon worker thread that drains a
bounded FIFO of staging requests, and for each

1. dispatches the slice ``panel[lo:hi]`` (the SAME expression the serial
   driver uses, so the compiled slice program and the resulting bytes are
   identical), and
2. blocks until the buffer is materialized on device
   (``jax.block_until_ready``), so a taken slice never re-pays the copy.

``panel`` can also be a lane view over a :class:`~.source.ChunkSource`
(ISSUE 7): the "slice" is then a genuine host→device staging — host read
into a pooled pinned-style buffer plus an H2D copy — and this worker is
what makes the copy ASYNC: chunk N+1's transfer rides here while chunk N
computes, which for host-resident panels is the difference between
walking at device speed and walking at PCIe speed.  The staged buffer is
handed to the driver with no reference retained (slot cleared at take),
so the device allocator recycles chunk N's HBM for chunk N+2 — the
donated-buffer half of the O(chunk)-footprint contract.

With the committer draining finished chunks behind the walk and the
prefetcher staging slices ahead of it, the steady state is the full
three-stage overlap: **stage N+1 ∥ compute N ∥ commit N−1**.

**Prediction, not speculation**: the driver schedules exactly the spans
the walk will visit next (up to ``depth`` consecutive ones, with
committed-grid clamping, torn-shard forced boundaries, and the current
chunk size all applied by the driver before scheduling).  When the walk
deviates anyway — an OOM backoff halves the chunk size, a committer
rollback rewinds the walk, or an idle elastic lane STEALS the tail of
this lane's span (``plan.LaneRunner.try_steal``, ISSUE 11 — every staged
prediction past the split now belongs to the thief) — the driver (or the
thief) **invalidates** the staged slices; a ``take`` that finds no
matching span simply slices inline (a recorded miss), so a stale
prediction can cost at most the work it saved, never correctness: the
staged buffer either IS ``panel[lo:hi]`` for the requested span or it is
not used.

**Bounded depth** (``prefetch_depth``, default 1): at most ``depth``
staged-but-untaken slices exist at any time, bounding the extra device
memory to ``depth`` chunk buffers.  Depth 1 is the classic double buffer
(chunk N computing, chunk N+1 staged).

**Errors** never vanish into the worker: a staging failure (typically an
XLA ``RESOURCE_EXHAUSTED`` — the slice is a fresh HBM allocation) is
delivered at ``take`` for that span, where the chunk driver's normal
fit-time OOM handling rolls it into the backoff ladder.

**Accounting**: the worker records the staging wall per slice; ``take``
records the driver wall spent waiting on an in-flight staging.  Their
difference is the input-staging cost the overlap hid —
``stats().hidden_s`` — published next to the committer's numbers as
``meta["pipeline"]`` input-side fields and the
``input_overlap_efficiency``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import NamedTuple

import jax

from .. import obs

__all__ = ["ChunkPrefetcher", "PrefetchStats"]

_STOP = object()


class PrefetchStats(NamedTuple):
    """Driver-facing accounting of one prefetcher's lifetime."""

    staged: int  # slices the worker finished staging
    hits: int  # takes served from a staged/in-flight slice
    misses: int  # takes that had to slice inline
    staging_wall_s: float  # total dispatch+materialize wall in the worker
    blocked_s: float  # driver wall spent waiting in take()
    invalidated: int  # staged/pending slices dropped by the driver

    @property
    def hidden_s(self) -> float:
        """Staging wall the driver never waited for — hidden under the
        previous chunk's compute (and host work)."""
        return max(0.0, self.staging_wall_s - self.blocked_s)


class _Slot:
    """One staged (or in-flight) slice."""

    __slots__ = ("event", "value", "error", "cancelled")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error = None
        self.cancelled = False


class ChunkPrefetcher:
    """Bounded background slice stager for one chunk walk over ``panel``.

    ``schedule(lo, hi)`` requests staging of ``panel[lo:hi]`` (ignored
    when ``depth`` slices are already staged/in flight, or the span is
    already scheduled); ``take(lo, hi)`` returns the staged buffer when
    the prediction matched (waiting out an in-flight staging) and slices
    inline otherwise; ``invalidate()`` drops every staged/pending slice
    (OOM backoff / rollback re-chunked the walk).  ``close()`` stops the
    worker and returns :class:`PrefetchStats`.
    """

    # lock-discipline contract (tools/lint lock-map): slot map + stats
    # are mutated from both the driver (schedule/take/invalidate) and
    # the staging worker; every site holds _lock.  _closed and the
    # queue handle are driver-only.
    _protected_by_ = {
        "_slots": "_lock",
        "_staged": "_lock",
        "_hits": "_lock",
        "_misses": "_lock",
        "_staging_wall_s": "_lock",
        "_blocked_s": "_lock",
        "_invalidated": "_lock",
    }

    def __init__(self, panel, *, depth: int = 1):
        self._panel = panel
        self.depth = max(1, int(depth))
        self._q: queue.Queue = queue.Queue()
        self._slots: dict = {}  # (lo, hi) -> _Slot
        self._lock = threading.Lock()
        self._staged = 0
        self._hits = 0
        self._misses = 0
        self._staging_wall_s = 0.0
        self._blocked_s = 0.0
        self._invalidated = 0
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="chunk-prefetcher")
        self._worker.start()

    # -- worker side --------------------------------------------------------

    def _run(self):
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            lo, hi, slot = item
            # drop the tuple's slice reference immediately: the worker
            # blocks in q.get() between requests, and a lingering local
            # would pin the previous staged buffer (= one chunk of HBM)
            # for that whole idle stretch
            item = None
            if slot.cancelled:
                slot.event.set()
                slot = None
                continue
            t0 = time.perf_counter()
            try:
                with obs.span("stage.overlap", lo=lo, hi=hi):
                    # the SAME slice expression the serial driver uses:
                    # identical compiled program, identical bytes
                    vals = self._panel[lo:hi]
                    # a taken slice must never re-pay the copy:
                    # lint: host-sync(deliberate staging barrier)
                    jax.block_until_ready(vals)
                slot.value = vals
                vals = None
            except BaseException as e:  # noqa: BLE001 - re-raised at take()
                slot.error = e
            wall = time.perf_counter() - t0
            with self._lock:
                self._staging_wall_s += wall
                if slot.error is None and not slot.cancelled:
                    self._staged += 1
                cancelled = slot.cancelled
            if cancelled:
                # invalidated mid-staging: free the buffer BEFORE signaling
                # — invalidate() waits on this event precisely so the HBM is
                # back when its caller (the OOM-backoff retry) dispatches
                slot.value = None
            obs.counter("prefetch.staged").inc()
            slot.event.set()
            slot = None

    # -- driver side --------------------------------------------------------

    def schedule(self, lo: int, hi: int) -> None:
        """Request staging of ``panel[lo:hi]`` (bounded, idempotent)."""
        if self._closed:
            return
        lo, hi = int(lo), int(hi)
        with self._lock:
            if (lo, hi) in self._slots or len(self._slots) >= self.depth:
                return
            slot = _Slot()
            self._slots[(lo, hi)] = slot
        self._q.put((lo, hi, slot))
        obs.gauge("prefetch.queue_depth").set(len(self._slots))

    def take(self, lo: int, hi: int):
        """The slice for ``[lo, hi)`` — staged when predicted, inline
        otherwise.  Also drops staged slices the walk has passed (their
        ``lo`` is behind the requested one), so a resume-skipped span
        cannot pin a depth slot forever.  Re-raises a staging-time error
        (e.g. RESOURCE_EXHAUSTED) in the driver."""
        lo, hi = int(lo), int(hi)
        with self._lock:
            slot = self._slots.pop((lo, hi), None)
            stale = [k for k in self._slots if k[0] < hi]
            for k in stale:
                self._slots.pop(k).cancelled = True
            self._invalidated += len(stale)
        if slot is None:
            with self._lock:
                self._misses += 1
            obs.counter("prefetch.misses").inc()
            return self._panel[lo:hi]
        t0 = time.perf_counter()
        slot.event.wait()
        blocked = time.perf_counter() - t0
        with self._lock:
            self._blocked_s += blocked
            if slot.error is None:
                self._hits += 1
        if slot.error is not None:
            raise slot.error
        obs.counter("prefetch.hits").inc()
        return slot.value

    def invalidate(self) -> None:
        """Drop every staged/pending slice — the walk re-chunked (OOM
        backoff halved the boundary, or a committer rollback rewound it),
        so every prediction is now wrong.  Blocks until any IN-FLIGHT
        staging has finished and its buffer is released: the caller is
        typically the OOM-backoff path, and a freed staged slice is
        exactly the HBM the halved retry needs — returning while the
        worker still holds the doomed buffer would make the retry re-OOM
        and burn a backoff level for nothing.  The wait is bounded: the
        worker sets every slot's event, including on a staging-time error
        and for cancelled-before-start requests."""
        with self._lock:
            dropped = list(self._slots.values())
            for slot in dropped:
                slot.cancelled = True
            self._invalidated += len(dropped)
            self._slots.clear()
        for slot in dropped:
            slot.event.wait()
            slot.value = None
        obs.gauge("prefetch.queue_depth").set(0)

    def close(self) -> PrefetchStats:
        """Stop the worker, drop staged slices, and return lifetime stats."""
        if not self._closed:
            self._closed = True
            self.invalidate()
            self._q.put(_STOP)
            self._worker.join(timeout=30.0)
        return self.stats()

    def stats(self) -> PrefetchStats:
        with self._lock:
            return PrefetchStats(self._staged, self._hits, self._misses,
                                 self._staging_wall_s, self._blocked_s,
                                 self._invalidated)
