"""Execution plan + lane scheduler: the chunk walk as data, then as code.

Through PR 5 the durable pipelined walk lived as one hand-wired loop inside
``reliability.chunked.fit_chunked``: prefetcher, committer, watchdog, and
journal were constructed inline and driven by closures, and the whole
arrangement assumed ONE device and ONE lane.  This module is the refactor
ROADMAP called the right first move for scale-out: the walk's
configuration becomes an explicit :class:`ExecutionPlan` (spans, lanes,
budgets as *data*), and the walk itself becomes :class:`LaneRunner` — the
per-lane scheduler that owns exactly one prefetch → compute → commit
pipeline over one contiguous row span.

**One plan, one to N lanes.**  The serial walk, the pipelined walk, and
the sharded walk are the SAME ``ExecutionPlan`` with different knob values
and one-vs-many :class:`LaneSpec` entries.  A single-lane plan reproduces
the PR 1–5 driver bit for bit; a sharded plan (``fit_chunked(shard=True)``
or ``mesh=``) partitions the CHUNK GRID into contiguous per-shard spans —
each mesh device owns a contiguous block of whole chunks, the sharded
twin of the reference's "every partition owns whole series" invariant —
and runs one ``LaneRunner`` per shard concurrently, each dispatching to
its own device.  Because shard boundaries always land on the single-device
walk's chunk boundaries, every chunk is the same rows through the same
compiled program either way, so the sharded result is bitwise-identical
to the single-device walk on the same panel.

**Durability composes unchanged.**  Each lane journals into its own shard
namespace (``shard_00000/…`` — the per-process namespace rule of
:mod:`.journal`, extended down to lanes), and the driver's shard 0 merges
the shard manifests into ONE job manifest after the lanes join.  A
crash/preemption resume rebuilds the same plan, and each lane replays only
its own uncommitted chunks.

Plan knobs (lanes, mesh, pipeline depths) are deliberately EXCLUDED from
the journal's config hash: they move work between threads and devices
without changing a byte of any chunk, so a journal written by the
pre-plan single-device driver resumes under a SINGLE-lane plan, and a
merged sharded job manifest can even be adopted by a later single-device
walk (the merged entries keep their shard-relative paths).  The reverse
is not adoption: a sharded plan's lanes journal into fresh shard
namespaces, so chunks a root/serial manifest already committed are
recomputed (identical bytes, just repeated work), never spliced.
"""

from __future__ import annotations

import time
from typing import Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np

from .. import obs
from ..obs import memory as memory_probe
from . import committer as committer_mod
from . import prefetcher as prefetcher_mod
from . import source as source_mod
from . import watchdog as watchdog_mod
from .runner import resilient_fit
from .status import FitStatus, STATUS_DTYPE, status_counts

__all__ = [
    "ExecutionPlan",
    "LaneRunner",
    "LaneSpec",
    "OOMBackoffExceeded",
    "is_resource_exhausted",
    "shard_spans",
]

# substrings the XLA runtime uses for allocation failure; the simulated OOM
# of reliability.faultinject raises with the same marker so tier-1 CPU tests
# drive this path without a real HBM exhaustion
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


class OOMBackoffExceeded(RuntimeError):
    """Raised when the minimum chunk size still exhausts device memory."""


def is_resource_exhausted(e: BaseException) -> bool:
    """True for XLA RESOURCE_EXHAUSTED-style allocation failures.

    ``jaxlib``'s ``XlaRuntimeError`` subclasses ``RuntimeError``, so the
    check is message-based on RuntimeError/MemoryError rather than pinned
    to a jaxlib exception type that moves between releases.
    """
    if isinstance(e, MemoryError):
        return True
    if not isinstance(e, RuntimeError):
        return False
    msg = str(e)
    return any(m in msg for m in _OOM_MARKERS)


class LaneSpec(NamedTuple):
    """One lane of the walk: a contiguous row span and (optionally) the
    device that owns it.  ``device=None`` means "wherever the caller's
    panel lives" — the single-device walk."""

    shard_id: int
    lo: int  # global row offset (inclusive)
    hi: int  # global row offset (exclusive)
    device: Optional[object] = None  # jax.Device for sharded lanes


class ExecutionPlan(NamedTuple):
    """The whole walk as data: spans, lanes, budgets, pipeline knobs.

    Built once per ``fit_chunked`` call (and rebuilt identically on a
    journaled resume — everything that decides a chunk's BYTES is covered
    by the journal config hash; everything here that is not hashed only
    decides WHERE/WHEN work happens).
    """

    n_rows: int
    chunk_rows: int  # initial chunk size (chunk0)
    min_chunk_rows: int
    max_backoffs: int  # per-lane OOM backoff budget
    resilient: bool
    policy: str
    ladder: Optional[tuple]
    checkpoint_dir: Optional[str]
    resume: str
    chunk_budget_s: Optional[float]
    job_budget_s: Optional[float]
    pipeline: bool
    pipeline_depth: int
    prefetch_depth: int
    align_mode: Optional[str]  # resolved static plan mode (None: no hint)
    lanes: Tuple[LaneSpec, ...]  # the lanes THIS process runs
    process_index: int
    # GLOBAL shard count: under jax.distributed a process may run a single
    # lane (or none) of a genuinely sharded walk, and its telemetry/events
    # must still carry shard tags so the merged timeline stays per-lane
    n_shards: int = 1
    # GRID coordinate (ISSUE 9): an auto-fit order search runs one ordinary
    # walk per candidate order; ``(grid_index, grid_total)`` places this
    # walk's plan on that grid so its chunk spans/events/telemetry carry a
    # ``grid`` tag (tools/obs_report.py renders one timeline lane per
    # order).  Like the shard/pipeline knobs it is deliberately EXCLUDED
    # from the journal config hash — the order itself rides in fit_kwargs,
    # which IS hashed; the coordinate only labels where work happened.
    grid: Optional[Tuple[int, int]] = None

    @property
    def sharded(self) -> bool:
        return self.n_shards > 1


def shard_spans(n_rows: int, chunk_rows: int,
                n_shards: int) -> Sequence[Tuple[int, int]]:
    """Partition the chunk grid into at most ``n_shards`` contiguous spans.

    The unit of distribution is the CHUNK, not the row: every span is a
    whole number of ``chunk_rows`` chunks (the last span absorbs the
    ragged tail), so a sharded walk visits exactly the chunk boundaries
    the single-device walk would — the invariant the bitwise-identity
    contract rests on.  Shards are balanced to within one chunk; when
    there are fewer chunks than shards, the extra shards get no lane.
    """
    n_rows = int(n_rows)
    chunk_rows = max(1, int(chunk_rows))
    n_chunks = -(-n_rows // chunk_rows)
    n_lanes = max(1, min(int(n_shards), n_chunks))
    q, r = divmod(n_chunks, n_lanes)
    spans, start = [], 0
    for i in range(n_lanes):
        take = q + (1 if i < r else 0)
        lo = start * chunk_rows
        start += take
        hi = min(start * chunk_rows, n_rows)
        spans.append((lo, hi))
    return spans


def _span_times(sp) -> dict:
    """Wall/process times of a closed chunk span, or ``{}`` when the plane
    was disabled mid-run (the span degraded to the shared no-op whose
    times are None — telemetry may lose a row's timings but must never
    crash the fit it observes)."""
    if sp.wall_s is None:
        return {}
    out = {"wall_s": round(sp.wall_s, 6)}
    if sp.process_s is not None:
        out["process_s"] = round(sp.process_s, 6)
    return out


class _TimeoutChunk:
    """Placeholder for a chunk whose fit never finished; materialized into
    NaN-param / ``TIMEOUT``-status rows once the parameter width is known
    (from any finished chunk) at assembly time."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo: int, hi: int):
        self.lo, self.hi = lo, hi


def _piece_status(p) -> np.ndarray:
    """Status of one chunk result; synthesized when the fit has none."""
    status = getattr(p, "status", None)
    conv = np.asarray(p.converged)
    if status is None:
        finite = np.isfinite(np.asarray(p.params)).all(axis=-1)
        return np.where(conv & finite, FitStatus.OK,
                        FitStatus.DIVERGED).astype(STATUS_DTYPE)
    return np.asarray(status).astype(STATUS_DTYPE)


def _commit_arrays(piece) -> dict:
    """Host-side arrays of one finished chunk, in the journal shard schema.

    Under the pipelined driver this runs on the committer thread, so for
    non-resilient fits the device->host fetch itself overlaps the next
    chunk's device compute."""
    return {
        "params": np.asarray(piece.params),
        "nll": np.asarray(piece.neg_log_likelihood),
        "converged": np.asarray(piece.converged),
        "iters": np.asarray(piece.iters),
        "status": _piece_status(piece),
    }


class _LaneView:
    """Offset view over a lane's device-local panel: translates the walk's
    GLOBAL row spans into the lane array's local rows, so the prefetcher
    and the inline slice path share one expression (and the staged bytes
    are exactly the bytes the inline slice would produce)."""

    __slots__ = ("arr", "base")

    def __init__(self, arr, base: int):
        self.arr = arr
        self.base = int(base)

    def __getitem__(self, s: slice):
        return self.arr[s.start - self.base:s.stop - self.base]


class LaneResult(NamedTuple):
    """Everything one lane hands back to the driver for merging."""

    spec: LaneSpec
    pieces: list  # (lo, hi, piece) in walk order; piece may be _TimeoutChunk
    oom_events: list
    timeout_events: list
    tele_chunks: Optional[list]
    pipe_stats: Optional[committer_mod.CommitterStats]
    pf_stats: Optional[prefetcher_mod.PrefetchStats]
    chunk_final: int
    committer_depth: Optional[int]
    prefetch_depth: Optional[int]


class LaneRunner:
    """One prefetch → compute → commit lane over one contiguous row span.

    This IS the former ``fit_chunked`` loop, verbatim in behavior: the
    single-lane plan reproduces the PR 1–5 driver (same chunk boundaries,
    same journal protocol, same backoff/timeout/rollback semantics, same
    bytes).  A sharded plan runs several of these concurrently, one per
    mesh device, each against its own journal namespace and its own
    committer/prefetcher pair; the shared pieces of state are the job
    :class:`~.watchdog.Deadline` (wall clock is global) and the obs
    metrics registry (counters are merged accounting by design).

    ``values`` is the lane's device-local panel whose row 0 is global row
    ``spec.lo``; the walk itself runs in GLOBAL row coordinates so journal
    entries, telemetry rows, and result assembly agree across lanes.
    """

    def __init__(self, plan: ExecutionPlan, spec: LaneSpec, fit_fn: Callable,
                 fit_kwargs: dict, values, *, journal=None, deadline=None,
                 tele: bool = False, fit_key=None):
        self.plan = plan
        self.spec = spec
        self.fit_fn = fit_fn
        self.fit_kwargs = fit_kwargs
        self.values = values
        self.journal = journal
        self.deadline = deadline or watchdog_mod.Deadline(plan.job_budget_s)
        self.tele = tele
        self.fit_key = fit_key
        # obs attrs tagged with the shard id ONLY for sharded plans: the
        # single-lane walk's spans/events/meta stay byte-identical to the
        # pre-plan driver.  A grid-placed plan (auto-fit order search)
        # additionally tags every span/event with its order's grid index
        self.tag = {"shard": spec.shard_id} if plan.sharded else {}
        if plan.grid is not None:
            self.tag = {**self.tag, "grid": int(plan.grid[0])}
        # source-backed lanes (ISSUE 7): `values` is a SourceLane over a
        # host-resident ChunkSource — every chunk, including a whole-span
        # one, must be STAGED (there is no resident device array to hand
        # through), and the staged buffer is donated back to the allocator
        # the moment the chunk's fit drops it
        self._from_source = isinstance(values, source_mod.SourceLane)

        span_rows = spec.hi - spec.lo
        self.chunk = max(1, min(plan.chunk_rows, span_rows))
        self.committer = None
        if journal is not None and plan.pipeline:
            self.committer = committer_mod.ChunkCommitter(
                journal, _commit_arrays, depth=plan.pipeline_depth,
                probe=memory_probe.peak_memory, status_counts=status_counts)
        # input-side pipeline: stage chunk N+1's slice while chunk N
        # computes.  Only sliced walks stage (a whole-span chunk has no
        # next slice), and pipeline=False stays the fully serial escape
        # hatch for BOTH halves
        self.prefetcher = None
        if plan.pipeline and plan.prefetch_depth and self.chunk < span_rows:
            panel = values if spec.lo == 0 else _LaneView(values, spec.lo)
            self.prefetcher = prefetcher_mod.ChunkPrefetcher(
                panel, depth=plan.prefetch_depth)

        self.pieces: list = []
        self.oom_events: list = []
        self.timeout_events: list = []
        self.tele_chunks: Optional[list] = [] if tele else None
        # boundaries of committed-but-unloadable (torn-shard) chunks: the
        # recompute must cover the EXACT recorded [lo, hi) — deriving hi
        # from the current chunk size could overlap a later committed chunk
        # and break the bitwise-identical-boundaries contract
        self.lost_boundaries: dict = {}

    # -- slicing -------------------------------------------------------------

    def _slice(self, lo: int, hi: int):
        base = self.spec.lo
        return self.values[lo - base:hi - base]

    # -- backoff / rollback --------------------------------------------------

    def _record_oom(self, at_row: int, rows: int, e: BaseException) -> int:
        """Shared backoff bookkeeping for fit-time, staging-time, and
        commit-time OOMs; returns the halved chunk size (or raises when
        the budget/floor is spent).  Every staged slice is invalidated
        first: the halved boundary makes every prefetch prediction wrong,
        and a freed staged buffer is exactly the HBM the retry needs."""
        plan = self.plan
        if self.prefetcher is not None:
            self.prefetcher.invalidate()
        self.oom_events.append({
            "at_row": at_row, "chunk_rows": rows,
            "error": f"{type(e).__name__}: {e}"[:200],
        })
        obs.counter("chunked.oom_backoffs").inc()
        obs.event("chunk.oom_backoff", at_row=at_row, chunk_rows=rows,
                  **self.tag)
        if rows <= plan.min_chunk_rows or len(self.oom_events) > plan.max_backoffs:
            raise OOMBackoffExceeded(
                f"chunk of {rows} rows still RESOURCE_EXHAUSTED after "
                f"{len(self.oom_events)} backoffs (floor {plan.min_chunk_rows})"
            ) from e
        return max(plan.min_chunk_rows, rows // 2)

    def _rollback(self, err):
        """Handle a committer-detected failure (the fetch/commit of an
        async-dispatched chunk raised on the worker thread).

        Non-OOM errors re-raise unchanged.  An OOM rolls the walk back to
        the failed chunk: everything at/after it is uncommitted (in-order
        queue), so its pieces are dropped, the chunk size halves, and the
        walk re-enters at the failed row — the pipelined twin of the
        fit-time backoff.  Returns the (lo, chunk) to continue from."""
        e, flo, fhi = err
        if not is_resource_exhausted(e):
            raise e
        new_chunk = self._record_oom(flo, fhi - flo, e)
        self.pieces[:] = [p for p in self.pieces if p[0] < flo]
        if self.tele:
            self.tele_chunks[:] = [r for r in self.tele_chunks
                                   if r["lo"] < flo]
        return flo, new_chunk

    def _next_span(self, nlo: int, cur_chunk: int):
        """The span the walk will visit after the current chunk — the
        prefetcher's prediction.  Mirrors the walk's own boundary logic
        exactly: torn-shard forced boundaries, then the committed-grid
        clamp (a staged slice must never sail past a committed chunk's
        ``lo``).  Returns None at the lane end or when the next span is
        already committed (the resume path loads it from its shard — no
        device slice needed)."""
        if nlo >= self.spec.hi:
            return None
        journal = self.journal
        if journal is not None and journal.committed(nlo) is not None:
            return None
        forced = self.lost_boundaries.get(nlo)
        if forced:
            return nlo, forced[0]
        nhi = min(nlo + cur_chunk, self.spec.hi)
        if journal is not None:
            nxt = journal.next_committed_lo(nlo)
            if nxt is not None and nxt < nhi:
                nhi = nxt
        return nlo, nhi

    def _drain_for_journal_write(self):
        """Synchronize with the committer before the driver itself writes
        the journal (TIMEOUT marks, forced torn-shard recommits): after
        this, every earlier commit is durable and the driver is the only
        writer.  Returns a pending error tuple instead of raising so the
        caller can roll back."""
        if self.committer is None:
            return None
        return self.committer.drain(raise_pending=False)

    # -- the walk ------------------------------------------------------------

    def run(self) -> LaneResult:
        try:
            self._walk()
        except BaseException:
            if self.committer is not None:
                # the walk is failing: stop the worker without letting a
                # second (pending) commit error mask the original exception
                self.committer.close(raise_pending=False)
            if self.prefetcher is not None:
                self.prefetcher.close()
            raise
        pipe_stats = (self.committer.close()
                      if self.committer is not None else None)
        pf_stats = (self.prefetcher.close()
                    if self.prefetcher is not None else None)
        return LaneResult(
            self.spec, self.pieces, self.oom_events, self.timeout_events,
            self.tele_chunks, pipe_stats, pf_stats, self.chunk,
            self.committer.depth if self.committer is not None else None,
            self.prefetcher.depth if self.prefetcher is not None else None)

    def _walk(self) -> None:
        plan, spec = self.plan, self.spec
        journal, deadline = self.journal, self.deadline
        tele = self.tele
        fit_fn, fit_kwargs = self.fit_fn, self.fit_kwargs
        lo = spec.lo
        while True:
            if self.committer is not None:
                err = self.committer.take_error()
                if err is not None:
                    lo, self.chunk = self._rollback(err)
                    continue
            if lo >= spec.hi:
                # final drain: a commit of one of the last chunks may still
                # fail (or OOM at fetch) — that must surface (or roll the
                # walk back) BEFORE assembly reads the pieces
                err = self._drain_for_journal_write()
                if err is not None:
                    lo, self.chunk = self._rollback(err)
                    continue
                break
            if journal is not None:
                entry = journal.committed(lo)
                if entry is not None:
                    piece = journal.load_chunk(entry)
                    if piece is not None:
                        self.pieces.append((lo, int(entry["hi"]), piece))
                        if tele:
                            self.tele_chunks.append(
                                {"lo": lo, "hi": int(entry["hi"]),
                                 "phase": "resumed", **self.tag})
                        lo = entry["hi"]
                        # replay the backoff state in effect when the chunk
                        # committed, so the resumed walk visits the SAME
                        # boundaries the uninterrupted run would have
                        self.chunk = int(entry.get("chunk_rows_after",
                                                   self.chunk))
                        continue
                    self.lost_boundaries[lo] = (
                        int(entry["hi"]),
                        int(entry.get("chunk_rows_after", self.chunk)))
            forced = self.lost_boundaries.get(lo)
            hi = forced[0] if forced else min(lo + self.chunk, spec.hi)
            if journal is not None and not forced:
                # keep the walk on the committed grid: after an OOM backoff
                # whose halving does not divide the original chunk size, a
                # free-running hi would sail past the next committed chunk's
                # lo, orphaning it (never matched again) and double-counting
                # its rows in the manifest — clamp to the boundary instead
                nxt = journal.next_committed_lo(lo)
                if nxt is not None and nxt < hi:
                    hi = nxt
            if deadline.exceeded():
                err = self._drain_for_journal_write()
                if err is not None:
                    lo, self.chunk = self._rollback(err)
                    continue
                if forced:
                    self.chunk = forced[1]
                    self.lost_boundaries.pop(lo, None)
                self.timeout_events.append({
                    "at_row": lo, "chunk_rows": hi - lo, "dispatched": False,
                    "budget_s": deadline.budget_s, "scope": "job"})
                obs.counter("chunked.timeouts.job").inc()
                obs.event("chunk.timeout", lo=lo, hi=hi, scope="job",
                          dispatched=False, **self.tag)
                if tele:
                    self.tele_chunks.append({"lo": lo, "hi": hi,
                                             "phase": "timeout",
                                             "scope": "job", **self.tag})
                self.pieces.append((lo, hi, _TimeoutChunk(lo, hi)))
                if journal is not None:
                    journal.mark_timeout(lo, hi, scope="job",
                                         budget_s=deadline.budget_s,
                                         chunk_rows_after=self.chunk)
                lo = hi
                continue

            def run_chunk(lo=lo, hi=hi, chunk=self.chunk):
                # lo/hi/chunk are DEFAULT-ARG SNAPSHOTS, not closure reads:
                # a watchdog-abandoned thread keeps running after the driver
                # has mutated the loop variables, and it must keep operating
                # on ITS chunk's span — never take() the live chunk's staged
                # slice or slice a torn lo/hi pair mid-update.
                # acquire this chunk's values INSIDE the watchdog window:
                # the whole-span chunk hands the lane's array through
                # untouched (a slice would be a fresh device buffer — an
                # extra HBM copy, and a miss in the per-array-identity
                # align-mode cache callers pre-warm); sliced chunks come
                # from the prefetcher when the staged prediction matched.
                # A staged slice can be queued behind an ABANDONED
                # (timed-out) computation, so the wait on it must be
                # bounded by the same budget as the compute it feeds — and
                # a staging-time RESOURCE_EXHAUSTED surfaces here, through
                # the watchdog, into the same backoff ladder as a fit-time
                # one.  A source-backed lane never hands `values` through:
                # a whole-span chunk still stages H2D (the panel lives in
                # host RAM/disk, not on device).
                if lo == spec.lo and hi == spec.hi and not self._from_source:
                    vals = self.values
                elif self.prefetcher is not None:
                    vals = self.prefetcher.take(lo, hi)
                else:
                    vals = self._slice(lo, hi)
                if self.prefetcher is not None:
                    # stage the next spans now (up to depth ahead — take()
                    # just freed this chunk's slot), so they materialize
                    # while this chunk computes (and, for resilient fits,
                    # while the ladder blocks on host work)
                    nlo = hi
                    for _ in range(self.prefetcher.depth):
                        nxt = self._next_span(nlo, chunk)
                        if nxt is None:
                            break
                        self.prefetcher.schedule(*nxt)
                        nlo = nxt[1]
                if plan.resilient:
                    return resilient_fit(
                        fit_fn, vals, policy=plan.policy, ladder=plan.ladder,
                        **fit_kwargs)
                out = fit_fn(vals, **fit_kwargs)
                if plan.chunk_budget_s is not None:
                    # with a deadline armed the budget must cover the device
                    # computation, not just its async dispatch — block here,
                    # INSIDE the watchdog window
                    jax.block_until_ready(out)
                return out

            phase = None
            if tele:
                # first dispatch of this (fit config, chunk rows) pays JAX
                # trace+compile; later dispatches of the same shape execute
                # a cached program — the split BENCH scraped ad hoc, now
                # recorded per chunk (a backoff-halved chunk is a NEW shape
                # = new compile).  Keyed per SHARD: executables are cached
                # per device placement, so every lane's first chunk pays
                # its own compile, not just the first lane to dispatch
                phase = ("compile+execute"
                         if obs.first_dispatch(
                             (self.fit_key, self.spec.shard_id, hi - lo))
                         else "execute")
            sp = obs.span("chunk", lo=lo, hi=hi, phase=phase, **self.tag)
            t0 = time.perf_counter()
            try:
                with sp:
                    piece = watchdog_mod.call_with_deadline(
                        run_chunk, plan.chunk_budget_s,
                        label=f"chunk rows [{lo}, {hi})")
            except watchdog_mod.DeadlineExceeded:
                err = self._drain_for_journal_write()
                if err is not None:
                    lo, self.chunk = self._rollback(err)
                    continue
                if forced:
                    self.chunk = forced[1]
                    self.lost_boundaries.pop(lo, None)
                self.timeout_events.append({
                    "at_row": lo, "chunk_rows": hi - lo, "dispatched": True,
                    "budget_s": plan.chunk_budget_s, "scope": "chunk"})
                obs.counter("chunked.timeouts.chunk").inc()
                obs.event("chunk.timeout", lo=lo, hi=hi, scope="chunk",
                          dispatched=True, budget_s=plan.chunk_budget_s,
                          **self.tag)
                if tele:
                    self.tele_chunks.append(
                        {"lo": lo, "hi": hi, "phase": "timeout",
                         "scope": "chunk", **self.tag, **_span_times(sp)})
                self.pieces.append((lo, hi, _TimeoutChunk(lo, hi)))
                if journal is not None:
                    journal.mark_timeout(lo, hi, scope="chunk",
                                         budget_s=plan.chunk_budget_s,
                                         chunk_rows_after=self.chunk)
                lo = hi
                continue
            except Exception as e:  # noqa: BLE001 - filtered just below
                if not is_resource_exhausted(e):
                    raise
                # drain before re-entering backoff: the journal state is
                # then deterministic at every backoff decision, and a
                # failed commit of an EARLIER chunk takes precedence over
                # this chunk's fit-time OOM (it is earlier in the walk)
                err = self._drain_for_journal_write()
                if err is not None:
                    lo, self.chunk = self._rollback(err)
                    continue
                if forced:
                    # a torn-shard recompute is pinned to the committed
                    # [lo, hi): halving `chunk` would not shrink the
                    # dispatch (hi stays forced), so retrying is futile —
                    # fail with the actionable cause instead of burning the
                    # backoff budget
                    raise OOMBackoffExceeded(
                        f"recompute of torn-shard chunk [{lo}, {hi}) hit "
                        "RESOURCE_EXHAUSTED; its boundaries are fixed by the "
                        "journal, so backoff cannot help. Free device "
                        "memory, or restart the job under a fresh "
                        "checkpoint_dir (or remove this journal explicitly) "
                        "to let the walk re-chunk."
                    ) from e
                self.chunk = self._record_oom(lo, self.chunk, e)
                continue
            if forced:  # torn-shard recompute done: restore the recorded walk
                self.chunk = forced[1]
                self.lost_boundaries.pop(lo, None)
            if tele:
                self.tele_chunks.append({"lo": lo, "hi": hi, "phase": phase,
                                         **self.tag, **_span_times(sp)})
            if journal is not None:
                wall_s = round(time.perf_counter() - t0, 4)
                if self.committer is not None and not forced:
                    # background commit: the fetch + shard + manifest update
                    # overlap the next chunk's dispatch/compute.  chunk_rows
                    # _after is captured NOW (not at commit time) so the
                    # recorded backoff state matches the serial walk exactly
                    try:
                        self.committer.submit(lo, hi, piece, wall_s=wall_s,
                                              chunk_rows_after=self.chunk)
                    except BaseException as se:
                        err = self.committer.take_error()
                        # only the worker's OWN re-raised error enters the
                        # rollback path: an unrelated exception (e.g. a
                        # Ctrl-C landing while submit blocked) must abort,
                        # not be converted into an OOM retry
                        if err is None or err[0] is not se:
                            raise
                        lo, self.chunk = self._rollback(err)
                        continue
                else:
                    # forced torn-shard recommits stay synchronous: they are
                    # rare, their boundaries are pinned by the journal, and
                    # the serial path keeps their edge semantics exact
                    err = self._drain_for_journal_write()
                    if err is not None:
                        lo, self.chunk = self._rollback(err)
                        continue
                    arrays = _commit_arrays(piece)
                    pm = memory_probe.peak_memory()
                    journal.commit_chunk(
                        lo, hi, arrays,
                        wall_s=wall_s,
                        peak_hbm_bytes=pm.bytes,
                        peak_hbm_source=pm.source,
                        chunk_rows_after=self.chunk,
                        status_counts=status_counts(arrays["status"]),
                        # host-resident walks: the staging RAM behind the
                        # device peak, so oversubscribed post-mortems see
                        # the job's whole footprint (obs.memory)
                        **({"peak_staging_pool_bytes": pm.staging_pool_bytes}
                           if pm.staging_pool_bytes is not None else {}),
                    )
            self.pieces.append((lo, hi, piece))
            lo = hi
