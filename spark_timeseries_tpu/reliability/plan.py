"""Execution plan + lane scheduler: the chunk walk as data, then as code.

Through PR 5 the durable pipelined walk lived as one hand-wired loop inside
``reliability.chunked.fit_chunked``: prefetcher, committer, watchdog, and
journal were constructed inline and driven by closures, and the whole
arrangement assumed ONE device and ONE lane.  This module is the refactor
ROADMAP called the right first move for scale-out: the walk's
configuration becomes an explicit :class:`ExecutionPlan` (spans, lanes,
budgets as *data*), and the walk itself becomes :class:`LaneRunner` — the
per-lane scheduler that owns exactly one prefetch → compute → commit
pipeline over one contiguous row span.

**One plan, one to N lanes.**  The serial walk, the pipelined walk, and
the sharded walk are the SAME ``ExecutionPlan`` with different knob values
and one-vs-many :class:`LaneSpec` entries.  A single-lane plan reproduces
the PR 1–5 driver bit for bit; a sharded plan (``fit_chunked(shard=True)``
or ``mesh=``) partitions the CHUNK GRID into contiguous per-shard spans —
each mesh device owns a contiguous block of whole chunks, the sharded
twin of the reference's "every partition owns whole series" invariant —
and runs one ``LaneRunner`` per shard concurrently, each dispatching to
its own device.  Because shard boundaries always land on the single-device
walk's chunk boundaries, every chunk is the same rows through the same
compiled program either way, so the sharded result is bitwise-identical
to the single-device walk on the same panel.

**Durability composes unchanged.**  Each lane journals into its own shard
namespace (``shard_00000/…`` — the per-process namespace rule of
:mod:`.journal`, extended down to lanes), and the driver's shard 0 merges
the shard manifests into ONE job manifest after the lanes join.  A
crash/preemption resume rebuilds the same plan, and each lane replays only
its own uncommitted chunks.

Plan knobs (lanes, mesh, pipeline depths) are deliberately EXCLUDED from
the journal's config hash: they move work between threads and devices
without changing a byte of any chunk, so a journal written by the
pre-plan single-device driver resumes under a SINGLE-lane plan, and a
merged sharded job manifest can even be adopted by a later single-device
walk (the merged entries keep their shard-relative paths).  The reverse
is not adoption: a sharded plan's lanes journal into fresh shard
namespaces, so chunks a root/serial manifest already committed are
recomputed (identical bytes, just repeated work), never spliced.

**Elastic lanes** (ISSUE 11).  Through PR 9 a sharded walk inverted the
reference's resilience promise: one lane hitting an unrecoverable fit
exception, an exhausted OOM-backoff ladder, or a dead device failed the
ENTIRE job, and a straggler lane paced every healthy device.  The
:class:`LaneSupervisor` restores the Spark contract at lane granularity:
lanes PULL grid-aligned spans from a shared lock-protected
:class:`WorkQueue` instead of owning a static partition; a lane whose
walk raises is retried up to ``lane_retries`` times with backoff, then
**quarantined** — its device leaves the active set, its *uncommitted*
chunks are re-enqueued and recomputed by survivors (committed shards are
ADOPTED from the dead lane's journal namespace via the cross-namespace
:class:`~.journal.ShardJournalView`, so only truly-uncommitted work
replays), and each idle survivor re-stages reassigned chunks to its own
device (:class:`RestagedPanel` / ``SourceLane``, O(chunk) either way).
Stragglers rebalance the same way: an idle lane STEALS the grid-aligned
tail of the slowest lane's remaining span when that lane's projected
finish exceeds ``rebalance_threshold`` mean chunk walls.  Every steal
boundary stays on the single-device chunk grid (and never splits a
committed chunk), so the walk's results remain bitwise-identical to the
uninterrupted single-device walk regardless of which lane computed which
chunk; a job that loses ALL lanes still fails with the original error.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np

from .. import obs
from . import committer as committer_mod
from . import prefetcher as prefetcher_mod
from . import source as source_mod
from . import watchdog as watchdog_mod
from .runner import resilient_fit
from .status import FitStatus, STATUS_DTYPE, status_counts

__all__ = [
    "ExecutionPlan",
    "LaneRunner",
    "LaneSpec",
    "LaneSupervisor",
    "OOMBackoffExceeded",
    "RestagedPanel",
    "WorkQueue",
    "is_resource_exhausted",
    "shard_spans",
]

# substrings the XLA runtime uses for allocation failure; the simulated OOM
# of reliability.faultinject raises with the same marker so tier-1 CPU tests
# drive this path without a real HBM exhaustion
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


class OOMBackoffExceeded(RuntimeError):
    """Raised when the minimum chunk size still exhausts device memory."""


def is_resource_exhausted(e: BaseException) -> bool:
    """True for XLA RESOURCE_EXHAUSTED-style allocation failures.

    ``jaxlib``'s ``XlaRuntimeError`` subclasses ``RuntimeError``, so the
    check is message-based on RuntimeError/MemoryError rather than pinned
    to a jaxlib exception type that moves between releases.
    """
    if isinstance(e, MemoryError):
        return True
    if not isinstance(e, RuntimeError):
        return False
    msg = str(e)
    return any(m in msg for m in _OOM_MARKERS)


class LaneSpec(NamedTuple):
    """One lane of the walk: a contiguous row span and (optionally) the
    device that owns it.  ``device=None`` means "wherever the caller's
    panel lives" — the single-device walk."""

    shard_id: int
    lo: int  # global row offset (inclusive)
    hi: int  # global row offset (exclusive)
    device: Optional[object] = None  # jax.Device for sharded lanes


class ExecutionPlan(NamedTuple):
    """The whole walk as data: spans, lanes, budgets, pipeline knobs.

    Built once per ``fit_chunked`` call (and rebuilt identically on a
    journaled resume — everything that decides a chunk's BYTES is covered
    by the journal config hash; everything here that is not hashed only
    decides WHERE/WHEN work happens).
    """

    n_rows: int
    chunk_rows: int  # initial chunk size (chunk0)
    min_chunk_rows: int
    max_backoffs: int  # per-lane OOM backoff budget
    resilient: bool
    policy: str
    ladder: Optional[tuple]
    checkpoint_dir: Optional[str]
    resume: str
    chunk_budget_s: Optional[float]
    job_budget_s: Optional[float]
    pipeline: bool
    pipeline_depth: int
    prefetch_depth: int
    align_mode: Optional[str]  # resolved static plan mode (None: no hint)
    lanes: Tuple[LaneSpec, ...]  # the lanes THIS process runs
    process_index: int
    # GLOBAL shard count: under jax.distributed a process may run a single
    # lane (or none) of a genuinely sharded walk, and its telemetry/events
    # must still carry shard tags so the merged timeline stays per-lane
    n_shards: int = 1
    # GRID coordinate (ISSUE 9): an auto-fit order search runs one ordinary
    # walk per candidate order; ``(grid_index, grid_total)`` places this
    # walk's plan on that grid so its chunk spans/events/telemetry carry a
    # ``grid`` tag (tools/obs_report.py renders one timeline lane per
    # order).  Like the shard/pipeline knobs it is deliberately EXCLUDED
    # from the journal config hash — the order itself rides in fit_kwargs,
    # which IS hashed; the coordinate only labels where work happened.
    grid: Optional[Tuple[int, int]] = None
    # ELASTIC knobs (ISSUE 11) — like every other plan knob they move work
    # between lanes without changing a byte, so none are config-hashed.
    # ``elastic`` is resolved by the driver: True for single-process
    # multi-lane walks (under jax.distributed a process cannot re-stage
    # another process's rows, so those keep the fail-fast static layout).
    elastic: bool = False
    lane_retries: int = 1  # failed-lane retries before quarantine
    lane_retry_backoff_s: float = 0.1  # first retry's backoff (doubles)
    rebalance_threshold: float = 4.0  # steal when a lane's projected
    # remaining wall exceeds this many mean chunk walls

    @property
    def sharded(self) -> bool:
        return self.n_shards > 1


def shard_spans(n_rows: int, chunk_rows: int,
                n_shards: int) -> Sequence[Tuple[int, int]]:
    """Partition the chunk grid into at most ``n_shards`` contiguous spans.

    The unit of distribution is the CHUNK, not the row: every span is a
    whole number of ``chunk_rows`` chunks (the last span absorbs the
    ragged tail), so a sharded walk visits exactly the chunk boundaries
    the single-device walk would — the invariant the bitwise-identity
    contract rests on.  Shards are balanced to within one chunk; when
    there are fewer chunks than shards, the extra shards get no lane.
    """
    n_rows = int(n_rows)
    chunk_rows = max(1, int(chunk_rows))
    n_chunks = -(-n_rows // chunk_rows)
    n_lanes = max(1, min(int(n_shards), n_chunks))
    q, r = divmod(n_chunks, n_lanes)
    spans, start = [], 0
    for i in range(n_lanes):
        take = q + (1 if i < r else 0)
        lo = start * chunk_rows
        start += take
        hi = min(start * chunk_rows, n_rows)
        spans.append((lo, hi))
    return spans


def _span_times(sp) -> dict:
    """Wall/process times of a closed chunk span, or ``{}`` when the plane
    was disabled mid-run (the span degraded to the shared no-op whose
    times are None — telemetry may lose a row's timings but must never
    crash the fit it observes)."""
    if sp.wall_s is None:
        return {}
    out = {"wall_s": round(sp.wall_s, 6)}
    if sp.process_s is not None:
        out["process_s"] = round(sp.process_s, 6)
    return out


class _TimeoutChunk:
    """Placeholder for a chunk whose fit never finished; materialized into
    NaN-param / ``TIMEOUT``-status rows once the parameter width is known
    (from any finished chunk) at assembly time."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo: int, hi: int):
        self.lo, self.hi = lo, hi


class _SunkChunk:
    """Placeholder for a chunk whose result already streamed out through
    the write-back sink (ISSUE 20): the walk keeps only its boundaries,
    so a sink-mode walk's host footprint stays O(chunk) instead of
    accumulating every chunk's arrays for the final concatenate."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo: int, hi: int):
        self.lo, self.hi = lo, hi


def _piece_status(p) -> np.ndarray:
    """Status of one chunk result; synthesized when the fit has none."""
    status = getattr(p, "status", None)
    conv = np.asarray(p.converged)
    if status is None:
        finite = np.isfinite(np.asarray(p.params)).all(axis=-1)
        return np.where(conv & finite, FitStatus.OK,
                        FitStatus.DIVERGED).astype(STATUS_DTYPE)
    return np.asarray(status).astype(STATUS_DTYPE)


def _commit_arrays(piece) -> dict:
    """Host-side arrays of one finished chunk, in the journal shard schema.

    Under the pipelined driver this runs on the committer thread, so for
    non-resilient fits the device->host fetch itself overlaps the next
    chunk's device compute."""
    return {
        "params": np.asarray(piece.params),
        "nll": np.asarray(piece.neg_log_likelihood),
        "converged": np.asarray(piece.converged),
        "iters": np.asarray(piece.iters),
        "status": _piece_status(piece),
    }


class _LaneView:
    """Offset view over a lane's device-local panel: translates the walk's
    GLOBAL row spans into the lane array's local rows, so the prefetcher
    and the inline slice path share one expression (and the staged bytes
    are exactly the bytes the inline slice would produce)."""

    __slots__ = ("arr", "base")

    def __init__(self, arr, base: int):
        self.arr = arr
        self.base = int(base)

    def __getitem__(self, s: slice):
        return self.arr[s.start - self.base:s.stop - self.base]


class RestagedPanel:
    """Device-staging view over the driver's resident panel, for a lane
    walking a REASSIGNED span (quarantine hand-off or a straggler steal —
    ISSUE 11): the lane's device never held those rows, so each chunk's
    slice is staged to it on demand — ``device_put(panel[lo:hi], device)``,
    the same bytes the original lane's resident slice held, at O(chunk)
    device footprint (the SourceLane pattern, for in-HBM panels).

    Local coordinates: row 0 is global row ``base`` (the reassigned span's
    lo), matching the lane-array convention ``LaneRunner`` slices with.
    """

    __slots__ = ("arr", "device", "base")

    def __init__(self, arr, device=None, base: int = 0):
        self.arr = arr
        self.device = device
        self.base = int(base)

    def __getitem__(self, s: slice):
        vals = self.arr[s.start + self.base:s.stop + self.base]
        return (jax.device_put(vals, self.device)
                if self.device is not None else jax.numpy.asarray(vals))


class LaneResult(NamedTuple):
    """Everything one lane hands back to the driver for merging."""

    spec: LaneSpec
    pieces: list  # (lo, hi, piece) in walk order; piece may be _TimeoutChunk
    oom_events: list
    timeout_events: list
    tele_chunks: Optional[list]
    pipe_stats: Optional[committer_mod.CommitterStats]
    pf_stats: Optional[prefetcher_mod.PrefetchStats]
    chunk_final: int
    committer_depth: Optional[int]
    prefetch_depth: Optional[int]


class LaneRunner:
    """One prefetch → compute → commit lane over one contiguous row span.

    This IS the former ``fit_chunked`` loop, verbatim in behavior: the
    single-lane plan reproduces the PR 1–5 driver (same chunk boundaries,
    same journal protocol, same backoff/timeout/rollback semantics, same
    bytes).  A sharded plan runs several of these concurrently, one per
    mesh device, each against its own journal namespace and its own
    committer/prefetcher pair; the shared pieces of state are the job
    :class:`~.watchdog.Deadline` (wall clock is global) and the obs
    metrics registry (counters are merged accounting by design).

    ``values`` is the lane's device-local panel whose row 0 is global row
    ``spec.lo``; the walk itself runs in GLOBAL row coordinates so journal
    entries, telemetry rows, and result assembly agree across lanes.
    """

    # lock-discipline contract (tools/lint lock-map): the elastic span
    # state is mutated by this lane's thread AND by thieves calling
    # try_steal from supervisor threads — every site holds the span
    # lock.  _t0 is written once by the lane thread at run() entry
    # (single writer; readers take the lock) and stays undeclared.
    _protected_by_ = {
        "_hi": "_mu",
        "_busy_hi": "_mu",
        "_steal_closed": "_mu",
        "_rows_done": "_mu",
    }

    def __init__(self, plan: ExecutionPlan, spec: LaneSpec, fit_fn: Callable,
                 fit_kwargs: dict, values, *, journal=None, deadline=None,
                 tele: bool = False, fit_key=None, sink=None):
        self.plan = plan
        self.spec = spec
        self.fit_fn = fit_fn
        self.fit_kwargs = fit_kwargs
        self.values = values
        self.journal = journal
        # write-back sink (ISSUE 20): every committed chunk's host arrays
        # stream out through it, and the pieces list keeps boundary-only
        # placeholders — the walk never accumulates result arrays
        self.sink = sink
        self.deadline = deadline or watchdog_mod.Deadline(plan.job_budget_s)
        self.tele = tele
        self.fit_key = fit_key
        # obs attrs tagged with the shard id ONLY for sharded plans: the
        # single-lane walk's spans/events/meta stay byte-identical to the
        # pre-plan driver.  A grid-placed plan (auto-fit order search)
        # additionally tags every span/event with its order's grid index
        self.tag = {"shard": spec.shard_id} if plan.sharded else {}
        if plan.grid is not None:
            self.tag = {**self.tag, "grid": int(plan.grid[0])}
        # sharded journal entries — commits AND timeout marks — record the
        # lane that produced them (ISSUE 11): under elastic reassignment
        # either kind can land in a namespace whose nominal span does not
        # contain it, and the merge/validators reconcile by this tag.
        # Single-device manifests stay byte-identical (no tag).
        self._owner = {"owner": spec.shard_id} if plan.sharded else {}
        # source-backed lanes (ISSUE 7): `values` is a SourceLane over a
        # host-resident ChunkSource — every chunk, including a whole-span
        # one, must be STAGED (there is no resident device array to hand
        # through), and the staged buffer is donated back to the allocator
        # the moment the chunk's fit drops it.  RestagedPanel (ISSUE 11)
        # is the in-HBM twin for reassigned spans: same rule.
        self._from_source = isinstance(
            values, (source_mod.SourceLane, RestagedPanel))
        # elastic-steal state (ISSUE 11): the span's END is mutable — an
        # idle lane may steal the grid-aligned tail of the remaining span
        # (try_steal, called from ANOTHER thread) — so every read of the
        # span end and every dispatch-boundary decision happens under one
        # lock, and nothing at/before _busy_hi can ever be stolen
        self._mu = threading.Lock()
        self._hi = spec.hi
        self._busy_hi = spec.lo
        self._steal_closed = False
        self._rows_done = 0  # rows COMPUTED by this runner (not resumed)
        self._t0: Optional[float] = None

        span_rows = spec.hi - spec.lo
        self.chunk = max(1, min(plan.chunk_rows, span_rows))
        self.committer = None
        if journal is not None and plan.pipeline:
            self.committer = committer_mod.ChunkCommitter(
                journal, _commit_arrays, depth=plan.pipeline_depth,
                probe=obs.peak_memory, status_counts=status_counts,
                on_commit=(sink.write if sink is not None else None))
        # input-side pipeline: stage chunk N+1's slice while chunk N
        # computes.  Only sliced walks stage (a whole-span chunk has no
        # next slice), and pipeline=False stays the fully serial escape
        # hatch for BOTH halves
        self.prefetcher = None
        if plan.pipeline and plan.prefetch_depth and self.chunk < span_rows:
            panel = values if spec.lo == 0 else _LaneView(values, spec.lo)
            self.prefetcher = prefetcher_mod.ChunkPrefetcher(
                panel, depth=plan.prefetch_depth)

        self.pieces: list = []
        self.oom_events: list = []
        self.timeout_events: list = []
        self.tele_chunks: Optional[list] = [] if tele else None
        # boundaries of committed-but-unloadable (torn-shard) chunks: the
        # recompute must cover the EXACT recorded [lo, hi) — deriving hi
        # from the current chunk size could overlap a later committed chunk
        # and break the bitwise-identical-boundaries contract
        self.lost_boundaries: dict = {}

    # -- slicing -------------------------------------------------------------

    def _slice(self, lo: int, hi: int):
        base = self.spec.lo
        return self.values[lo - base:hi - base]

    # -- elastic span (ISSUE 11) ---------------------------------------------

    @property
    def hi(self) -> int:
        """The span's CURRENT end — shrinks when an idle lane steals the
        tail (``try_steal``)."""
        with self._mu:
            return self._hi

    def progress(self) -> dict:
        """Live walk telemetry for the supervisor's rebalance decision."""
        with self._mu:
            return {
                "rows_done": self._rows_done,
                "rows_remaining": max(0, self._hi - self._busy_hi),
                "elapsed_s": (time.perf_counter() - self._t0
                              if self._t0 is not None else 0.0),
            }

    def try_steal(self) -> Optional[Tuple[int, int]]:
        """Give up the grid-aligned tail of this lane's remaining span to
        an idle lane; returns the stolen ``(lo, hi)`` or None.

        The split lands on the single-device chunk grid (multiples of the
        plan's ``chunk_rows`` — the invariant the bitwise contract rests
        on), strictly beyond everything this lane has dispatched or
        resumed (``_busy_hi``), keeps the victim at least half the
        remaining whole chunks, and never lands strictly inside a chunk
        some namespace already committed (a previous run's OOM backoff
        can leave off-grid committed boundaries; splitting one would make
        thief and victim double-compute its rows).  Staged slices are
        invalidated — every prediction past the split is now wrong.
        """
        chunk0 = max(1, int(self.plan.chunk_rows))
        with self._mu:
            if self._steal_closed:
                return None
            hi = self._hi
            base = max(self._busy_hi, self.spec.lo)
            g0 = -(-base // chunk0) * chunk0
            if g0 >= hi:
                return None
            n_rem = -(-(hi - g0) // chunk0)  # whole grid chunks left
            if n_rem < 2:
                return None
            split = g0 + ((n_rem + 1) // 2) * chunk0  # victim keeps ceil
            if self.journal is not None:
                for _ in range(n_rem):
                    x = self.journal.committed_crossing(split)
                    if x is None:
                        break
                    split = int(x)
            if split <= base or split >= hi:
                return None
            self._hi = split
        if self.prefetcher is not None:
            # staged predictions past the split belong to the thief now;
            # dropping ALL staged slices is conservative but safe (a kept
            # span degrades to an inline slice — a miss, never a wrong one)
            self.prefetcher.invalidate()
        return split, hi

    def close_steals(self) -> int:
        """Atomically close the span to further steals and return its
        FINAL end.  The supervisor calls this the moment a runner's walk
        fails: the retry/quarantine hand-off re-walks ``[lo, hi)``, and a
        steal landing between the failure and that hand-off would make
        the stolen tail both the thief's work and the retry's — duplicate
        rows in the assembled result.  Steals that completed before the
        close already shrank ``_hi``, so the returned end excludes them.
        """
        with self._mu:
            self._steal_closed = True
            return self._hi

    def _note_busy(self, row: int) -> None:
        with self._mu:
            if row > self._busy_hi:
                self._busy_hi = row

    # -- backoff / rollback --------------------------------------------------

    def _record_oom(self, at_row: int, rows: int, e: BaseException) -> int:
        """Shared backoff bookkeeping for fit-time, staging-time, and
        commit-time OOMs; returns the halved chunk size (or raises when
        the budget/floor is spent).  Every staged slice is invalidated
        first: the halved boundary makes every prefetch prediction wrong,
        and a freed staged buffer is exactly the HBM the retry needs."""
        plan = self.plan
        if self.prefetcher is not None:
            self.prefetcher.invalidate()
        self.oom_events.append({
            "at_row": at_row, "chunk_rows": rows,
            "error": f"{type(e).__name__}: {e}"[:200],
        })
        obs.counter("chunked.oom_backoffs").inc()
        obs.event("chunk.oom_backoff", at_row=at_row, chunk_rows=rows,
                  **self.tag)
        if rows <= plan.min_chunk_rows or len(self.oom_events) > plan.max_backoffs:
            raise OOMBackoffExceeded(
                f"chunk of {rows} rows still RESOURCE_EXHAUSTED after "
                f"{len(self.oom_events)} backoffs (floor {plan.min_chunk_rows})"
            ) from e
        return max(plan.min_chunk_rows, rows // 2)

    def _rollback(self, err):
        """Handle a committer-detected failure (the fetch/commit of an
        async-dispatched chunk raised on the worker thread).

        Non-OOM errors re-raise unchanged.  An OOM rolls the walk back to
        the failed chunk: everything at/after it is uncommitted (in-order
        queue), so its pieces are dropped, the chunk size halves, and the
        walk re-enters at the failed row — the pipelined twin of the
        fit-time backoff.  Returns the (lo, chunk) to continue from."""
        e, flo, fhi = err
        if not is_resource_exhausted(e):
            raise e
        new_chunk = self._record_oom(flo, fhi - flo, e)
        self.pieces[:] = [p for p in self.pieces if p[0] < flo]
        if self.sink is not None:
            # defensive: in-order commits mean spans >= flo never reached
            # the sink, but the rolled-back grid must not leave any behind
            self.sink.discard_from(flo)
        if self.tele:
            self.tele_chunks[:] = [r for r in self.tele_chunks
                                   if r["lo"] < flo]
        return flo, new_chunk

    def _next_span(self, nlo: int, cur_chunk: int):
        """The span the walk will visit after the current chunk — the
        prefetcher's prediction.  Mirrors the walk's own boundary logic
        exactly: torn-shard forced boundaries, then the committed-grid
        clamp (a staged slice must never sail past a committed chunk's
        ``lo``).  Returns None at the lane end or when the next span is
        already committed (the resume path loads it from its shard — no
        device slice needed)."""
        span_hi = self.hi
        if nlo >= span_hi:
            return None
        journal = self.journal
        if journal is not None and journal.committed(nlo) is not None:
            return None
        forced = self.lost_boundaries.get(nlo)
        if forced:
            return nlo, forced[0]
        nhi = min(nlo + cur_chunk, span_hi)
        if journal is not None:
            nxt = journal.next_committed_lo(nlo)
            if nxt is not None and nxt < nhi:
                nhi = nxt
        return nlo, nhi

    def _drain_for_journal_write(self):
        """Synchronize with the committer before the driver itself writes
        the journal (TIMEOUT marks, forced torn-shard recommits): after
        this, every earlier commit is durable and the driver is the only
        writer.  Returns a pending error tuple instead of raising so the
        caller can roll back."""
        if self.committer is None:
            return None
        return self.committer.drain(raise_pending=False)

    # -- the walk ------------------------------------------------------------

    def run(self) -> LaneResult:
        self._t0 = time.perf_counter()
        try:
            # sharded lanes tag their thread (and, via the watchdog, their
            # budgeted workers) with the shard id: lane-targeted fault
            # injection and per-lane accounting key on it.  Single-lane
            # walks stay untagged — byte-identical to the pre-plan driver.
            with watchdog_mod.lane_context(
                    self.spec.shard_id if self.plan.sharded else None):
                self._walk()
        except BaseException:
            if self.committer is not None:
                # the walk is failing: stop the worker without letting a
                # second (pending) commit error mask the original exception
                self.committer.close(raise_pending=False)
            if self.prefetcher is not None:
                self.prefetcher.close()
            raise
        pipe_stats = (self.committer.close()
                      if self.committer is not None else None)
        pf_stats = (self.prefetcher.close()
                    if self.prefetcher is not None else None)
        return LaneResult(
            self.spec, self.pieces, self.oom_events, self.timeout_events,
            self.tele_chunks, pipe_stats, pf_stats, self.chunk,
            self.committer.depth if self.committer is not None else None,
            self.prefetcher.depth if self.prefetcher is not None else None)

    def _walk(self) -> None:
        plan, spec = self.plan, self.spec
        journal, deadline = self.journal, self.deadline
        tele = self.tele
        fit_fn, fit_kwargs = self.fit_fn, self.fit_kwargs
        lo = spec.lo
        while True:
            if self.committer is not None:
                err = self.committer.take_error()
                if err is not None:
                    lo, self.chunk = self._rollback(err)
                    continue
            if lo >= self.hi:
                # final drain: a commit of one of the last chunks may still
                # fail (or OOM at fetch) — that must surface (or roll the
                # walk back) BEFORE assembly reads the pieces
                err = self._drain_for_journal_write()
                if err is not None:
                    lo, self.chunk = self._rollback(err)
                    continue
                break
            if journal is not None:
                entry = journal.committed(lo)
                if entry is not None:
                    piece = journal.load_chunk(entry)
                    if piece is not None:
                        self._note_busy(int(entry["hi"]))  # not stealable
                        if self.sink is not None:
                            # resume re-emits the chunk through the sink:
                            # the durable re-write replaces any torn or
                            # missing output shard with the same bytes,
                            # which is what makes a killed-and-resumed
                            # sink directory finalize bitwise-identical
                            self.sink.write(lo, int(entry["hi"]),
                                            _commit_arrays(piece))
                            piece = _SunkChunk(lo, int(entry["hi"]))
                        self.pieces.append((lo, int(entry["hi"]), piece))
                        if tele:
                            self.tele_chunks.append(
                                {"lo": lo, "hi": int(entry["hi"]),
                                 "phase": "resumed", **self.tag})
                        lo = entry["hi"]
                        # replay the backoff state in effect when the chunk
                        # committed, so the resumed walk visits the SAME
                        # boundaries the uninterrupted run would have
                        self.chunk = int(entry.get("chunk_rows_after",
                                                   self.chunk))
                        continue
                    self.lost_boundaries[lo] = (
                        int(entry["hi"]),
                        int(entry.get("chunk_rows_after", self.chunk)))
            forced = self.lost_boundaries.get(lo)
            # the chunk boundary is decided and PUBLISHED (as _busy_hi)
            # under the span lock, so a concurrent try_steal can never
            # split inside a chunk this iteration is about to dispatch
            with self._mu:
                hi = forced[0] if forced else min(lo + self.chunk, self._hi)
                if journal is not None and not forced:
                    # keep the walk on the committed grid: after an OOM
                    # backoff whose halving does not divide the original
                    # chunk size, a free-running hi would sail past the next
                    # committed chunk's lo, orphaning it (never matched
                    # again) and double-counting its rows in the manifest —
                    # clamp to the boundary instead
                    nxt = journal.next_committed_lo(lo)
                    if nxt is not None and nxt < hi:
                        hi = nxt
                if hi > self._busy_hi:
                    self._busy_hi = hi
            if deadline.exceeded():
                err = self._drain_for_journal_write()
                if err is not None:
                    lo, self.chunk = self._rollback(err)
                    continue
                if forced:
                    self.chunk = forced[1]
                    self.lost_boundaries.pop(lo, None)
                self.timeout_events.append({
                    "at_row": lo, "chunk_rows": hi - lo, "dispatched": False,
                    "budget_s": deadline.budget_s, "scope": "job"})
                obs.counter("chunked.timeouts.job").inc()
                obs.event("chunk.timeout", lo=lo, hi=hi, scope="job",
                          dispatched=False, **self.tag)
                if tele:
                    self.tele_chunks.append({"lo": lo, "hi": hi,
                                             "phase": "timeout",
                                             "scope": "job", **self.tag})
                self.pieces.append((lo, hi, _TimeoutChunk(lo, hi)))
                if journal is not None:
                    journal.mark_timeout(lo, hi, scope="job",
                                         budget_s=deadline.budget_s,
                                         chunk_rows_after=self.chunk,
                                         **self._owner)
                lo = hi
                continue

            def run_chunk(lo=lo, hi=hi, chunk=self.chunk):
                # lo/hi/chunk are DEFAULT-ARG SNAPSHOTS, not closure reads:
                # a watchdog-abandoned thread keeps running after the driver
                # has mutated the loop variables, and it must keep operating
                # on ITS chunk's span — never take() the live chunk's staged
                # slice or slice a torn lo/hi pair mid-update.
                # acquire this chunk's values INSIDE the watchdog window:
                # the whole-span chunk hands the lane's array through
                # untouched (a slice would be a fresh device buffer — an
                # extra HBM copy, and a miss in the per-array-identity
                # align-mode cache callers pre-warm); sliced chunks come
                # from the prefetcher when the staged prediction matched.
                # A staged slice can be queued behind an ABANDONED
                # (timed-out) computation, so the wait on it must be
                # bounded by the same budget as the compute it feeds — and
                # a staging-time RESOURCE_EXHAUSTED surfaces here, through
                # the watchdog, into the same backoff ladder as a fit-time
                # one.  A source-backed lane never hands `values` through:
                # a whole-span chunk still stages H2D (the panel lives in
                # host RAM/disk, not on device).
                if lo == spec.lo and hi == spec.hi and not self._from_source:
                    vals = self.values
                elif self.prefetcher is not None:
                    vals = self.prefetcher.take(lo, hi)
                else:
                    vals = self._slice(lo, hi)
                if self.prefetcher is not None:
                    # stage the next spans now (up to depth ahead — take()
                    # just freed this chunk's slot), so they materialize
                    # while this chunk computes (and, for resilient fits,
                    # while the ladder blocks on host work)
                    nlo = hi
                    for _ in range(self.prefetcher.depth):
                        nxt = self._next_span(nlo, chunk)
                        if nxt is None:
                            break
                        self.prefetcher.schedule(*nxt)
                        nlo = nxt[1]
                if plan.resilient:
                    return resilient_fit(
                        fit_fn, vals, policy=plan.policy, ladder=plan.ladder,
                        **fit_kwargs)
                out = fit_fn(vals, **fit_kwargs)
                if plan.chunk_budget_s is not None:
                    # with a deadline armed the budget must cover the device
                    # computation, not just its async dispatch — block here,
                    # INSIDE the watchdog window
                    # the watchdog must bound the computation itself,
                    # not just its async dispatch:
                    # lint: host-sync(deliberate watchdog barrier)
                    jax.block_until_ready(out)
                return out

            phase = None
            if tele:
                # first dispatch of this (fit config, chunk rows) pays JAX
                # trace+compile; later dispatches of the same shape execute
                # a cached program — the split BENCH scraped ad hoc, now
                # recorded per chunk (a backoff-halved chunk is a NEW shape
                # = new compile).  Keyed per SHARD: executables are cached
                # per device placement, so every lane's first chunk pays
                # its own compile, not just the first lane to dispatch
                phase = ("compile+execute"
                         if obs.first_dispatch(
                             (self.fit_key, self.spec.shard_id, hi - lo))
                         else "execute")
            sp = obs.span("chunk", lo=lo, hi=hi, phase=phase, **self.tag)
            t0 = time.perf_counter()
            try:
                with sp:
                    piece = watchdog_mod.call_with_deadline(
                        run_chunk, plan.chunk_budget_s,
                        label=f"chunk rows [{lo}, {hi})")
            except watchdog_mod.DeadlineExceeded:
                err = self._drain_for_journal_write()
                if err is not None:
                    lo, self.chunk = self._rollback(err)
                    continue
                if forced:
                    self.chunk = forced[1]
                    self.lost_boundaries.pop(lo, None)
                self.timeout_events.append({
                    "at_row": lo, "chunk_rows": hi - lo, "dispatched": True,
                    "budget_s": plan.chunk_budget_s, "scope": "chunk"})
                obs.counter("chunked.timeouts.chunk").inc()
                obs.event("chunk.timeout", lo=lo, hi=hi, scope="chunk",
                          dispatched=True, budget_s=plan.chunk_budget_s,
                          **self.tag)
                if tele:
                    self.tele_chunks.append(
                        {"lo": lo, "hi": hi, "phase": "timeout",
                         "scope": "chunk", **self.tag, **_span_times(sp)})
                self.pieces.append((lo, hi, _TimeoutChunk(lo, hi)))
                if journal is not None:
                    journal.mark_timeout(lo, hi, scope="chunk",
                                         budget_s=plan.chunk_budget_s,
                                         chunk_rows_after=self.chunk,
                                         **self._owner)
                lo = hi
                continue
            except Exception as e:  # noqa: BLE001 - filtered just below
                if not is_resource_exhausted(e):
                    raise
                # drain before re-entering backoff: the journal state is
                # then deterministic at every backoff decision, and a
                # failed commit of an EARLIER chunk takes precedence over
                # this chunk's fit-time OOM (it is earlier in the walk)
                err = self._drain_for_journal_write()
                if err is not None:
                    lo, self.chunk = self._rollback(err)
                    continue
                if forced:
                    # a torn-shard recompute is pinned to the committed
                    # [lo, hi): halving `chunk` would not shrink the
                    # dispatch (hi stays forced), so retrying is futile —
                    # fail with the actionable cause instead of burning the
                    # backoff budget
                    raise OOMBackoffExceeded(
                        f"recompute of torn-shard chunk [{lo}, {hi}) hit "
                        "RESOURCE_EXHAUSTED; its boundaries are fixed by the "
                        "journal, so backoff cannot help. Free device "
                        "memory, or restart the job under a fresh "
                        "checkpoint_dir (or remove this journal explicitly) "
                        "to let the walk re-chunk."
                    ) from e
                self.chunk = self._record_oom(lo, self.chunk, e)
                continue
            if forced:  # torn-shard recompute done: restore the recorded walk
                self.chunk = forced[1]
                self.lost_boundaries.pop(lo, None)
            if tele:
                self.tele_chunks.append({"lo": lo, "hi": hi, "phase": phase,
                                         **self.tag, **_span_times(sp)})
            if journal is not None:
                wall_s = round(time.perf_counter() - t0, 4)
                owner = self._owner
                if self.committer is not None and not forced:
                    # background commit: the fetch + shard + manifest update
                    # overlap the next chunk's dispatch/compute.  chunk_rows
                    # _after is captured NOW (not at commit time) so the
                    # recorded backoff state matches the serial walk exactly
                    try:
                        self.committer.submit(lo, hi, piece, wall_s=wall_s,
                                              chunk_rows_after=self.chunk,
                                              **owner)
                    except BaseException as se:
                        err = self.committer.take_error()
                        # only the worker's OWN re-raised error enters the
                        # rollback path: an unrelated exception (e.g. a
                        # Ctrl-C landing while submit blocked) must abort,
                        # not be converted into an OOM retry
                        if err is None or err[0] is not se:
                            raise
                        lo, self.chunk = self._rollback(err)
                        continue
                else:
                    # forced torn-shard recommits stay synchronous: they are
                    # rare, their boundaries are pinned by the journal, and
                    # the serial path keeps their edge semantics exact
                    err = self._drain_for_journal_write()
                    if err is not None:
                        lo, self.chunk = self._rollback(err)
                        continue
                    arrays = _commit_arrays(piece)
                    pm = obs.peak_memory()
                    journal.commit_chunk(
                        lo, hi, arrays,
                        wall_s=wall_s,
                        peak_hbm_bytes=pm.bytes,
                        peak_hbm_source=pm.source,
                        chunk_rows_after=self.chunk,
                        status_counts=status_counts(arrays["status"]),
                        # host-resident walks: the staging RAM behind the
                        # device peak, so oversubscribed post-mortems see
                        # the job's whole footprint (obs.memory)
                        **({"peak_staging_pool_bytes": pm.staging_pool_bytes}
                           if pm.staging_pool_bytes is not None else {}),
                        **owner,
                    )
                    if self.sink is not None:
                        self.sink.write(lo, hi, arrays)
            if self.sink is not None:
                # the committer (or the serial path above) owns the real
                # piece until its arrays are durable in the sink; the walk
                # keeps only the boundaries
                self.pieces.append((lo, hi, _SunkChunk(lo, hi)))
            else:
                self.pieces.append((lo, hi, piece))
            with self._mu:
                self._rows_done += hi - lo
            lo = hi


# ---------------------------------------------------------------------------
# elastic lane scheduling (ISSUE 11): work queue, supervision, rebalance
# ---------------------------------------------------------------------------


class WorkQueue:
    """Lock-protected queue of chunk-grid spans the elastic lanes pull.

    Seeded with the static shard partition, each span PREFERRED by its
    nominal lane — so a healthy walk pulls exactly the spans the static
    layout would have assigned and stays namespace- and byte-identical to
    it.  A quarantined lane's span re-enters unpreferred and is picked up
    by whichever survivor goes idle first.  ``cond`` is the one condition
    variable the whole supervisor synchronizes on (push, pull, lane
    completion, fatal errors): lanes never take it while holding a
    runner/journal lock, so the lock order cond → runner → journal is
    acyclic.
    """

    # lock-discipline contract (tools/lint lock-map): every lane thread
    # pushes/pulls spans; the ``*_locked`` helpers are called with the
    # condition held (the codebase convention the linter honors).
    _protected_by_ = {"_spans": "cond"}

    def __init__(self):
        self.cond = threading.Condition()
        self._spans: list = []  # (lo, hi, preferred_sid-or-None)

    def push(self, lo: int, hi: int, preferred: Optional[int] = None) -> None:
        with self.cond:
            self._push_locked(lo, hi, preferred)
            self.cond.notify_all()

    def _push_locked(self, lo: int, hi: int,
                     preferred: Optional[int] = None) -> None:
        self._spans.append((int(lo), int(hi), preferred))
        self._spans.sort(key=lambda s: s[0])

    def _pull_locked(self, sid: int) -> Optional[Tuple[int, int]]:
        """Lowest-lo span preferred by ``sid``, else lowest-lo UNPREFERRED
        span.  A span preferred by ANOTHER lane is never poached: its lane
        is alive and will pull it (at thread-startup a fast lane could
        otherwise grab a peer's span before that peer's thread is even
        scheduled — work the peer's device should do, and the surface
        lane-targeted fault injection and per-lane accounting key on);
        quarantine strips the dead lane's preference first
        (:meth:`release_preference`), so nothing is ever stranded."""
        pick = None
        for i, (_lo, _hi, pref) in enumerate(self._spans):
            if pref == sid:
                pick = i
                break
            if pref is None and pick is None:
                pick = i
        if pick is None:
            return None
        lo, hi, _ = self._spans.pop(pick)
        return lo, hi

    def _release_preference_locked(self, sid: int) -> None:
        self._spans = [(lo, hi, None if pref == sid else pref)
                       for lo, hi, pref in self._spans]

    def pending(self) -> list:
        with self.cond:
            return [(lo, hi) for lo, hi, _ in self._spans]


class LaneSupervisor:
    """Elastic scheduler for a multi-lane sharded walk (ISSUE 11).

    One supervisor thread per lane device, each looping pull → walk →
    pull over the shared :class:`WorkQueue`.  Failure containment per the
    module docstring: an ``Exception`` escaping a lane's walk (fit bug,
    exhausted OOM ladder, dead device) is retried up to
    ``plan.lane_retries`` times with exponential backoff, then the lane is
    QUARANTINED — its span re-enqueued for survivors, who re-stage the
    rows to their own devices (``restage``) and adopt whatever chunks the
    dead lane already committed (the per-lane journal handle is a
    cross-namespace :class:`~.journal.ShardJournalView`).  A
    ``BaseException`` (KeyboardInterrupt, the fault harness's
    ``SimulatedCrash`` standing in for SIGKILL) is FATAL: no quarantine,
    no reassignment — it re-raises from :meth:`run` exactly as the static
    layout would, so crash-resume semantics are unchanged.  Idle lanes
    STEAL from stragglers via ``LaneRunner.try_steal`` once the victim's
    projected remaining wall exceeds ``plan.rebalance_threshold`` mean
    chunk walls.  If every lane is quarantined with work remaining, the
    FIRST lane's original error re-raises — a job that loses all lanes
    still fails loudly.
    """

    # lock-discipline contract (tools/lint lock-map): supervisor state
    # is mutated from every lane thread; the ONE condition variable the
    # whole supervisor synchronizes on (queue.cond) guards it all —
    # keeping the lock order cond -> runner -> journal acyclic.
    _protected_by_ = {
        "results": "queue.cond",
        "_active": "queue.cond",
        "_busy": "queue.cond",
        "_fatal": "queue.cond",
        "_quarantined": "queue.cond",
        "_errors": "queue.cond",
        "_steals": "queue.cond",
        "_retries": "queue.cond",
        "_global_walls": "queue.cond",
        "_lane_mean_wall": "queue.cond",
    }

    def __init__(self, plan: ExecutionPlan, fit_fn: Callable,
                 fit_kwargs: dict, lanes: Sequence[tuple], *,
                 journals: Optional[Sequence] = None, deadline=None,
                 tele: bool = False, fit_key=None,
                 restage: Optional[Callable] = None):
        self.plan = plan
        self.fit_fn = fit_fn
        self.fit_kwargs = fit_kwargs
        self.lanes = list(lanes)  # [(LaneSpec, values), ...]
        self.journals = list(journals) if journals is not None else None
        self.deadline = deadline or watchdog_mod.Deadline(plan.job_budget_s)
        self.tele = tele
        self.fit_key = fit_key
        self.restage = restage

        self.queue = WorkQueue()
        self.results: list = []
        self._active: dict = {}  # sid -> live LaneRunner (steal victims)
        self._busy: set = set()  # sids mid-span (walking or retry backoff)
        self._journal_by_sid = {}
        if self.journals is not None:
            for (spec, _v), j in zip(self.lanes, self.journals):
                self._journal_by_sid[spec.shard_id] = j
        self._lane_mean_wall: dict = {}  # sid -> mean computed-chunk wall
        self._global_walls: list = []  # (n_chunks, wall_s) per finished span
        self._quarantined: list = []
        self._errors: list = []
        self._fatal: Optional[BaseException] = None
        self._steals = 0
        self._retries = 0

    # -- scheduling ---------------------------------------------------------

    def _state(self, sid: int, state: str) -> None:
        obs.gauge(f"lane.state.{sid}").set(state)

    def _mean_chunk_wall(self, sid: int) -> Optional[float]:
        ref = self._lane_mean_wall.get(sid)
        if ref:
            return ref
        n = sum(c for c, _w in self._global_walls)
        w = sum(w for _c, w in self._global_walls)
        return (w / n) if n else None

    def _pick_victim_locked(self, thief_sid: int):
        """The active lane worth stealing from, or None.  Called under the
        queue cond; reads each runner's live progress (runner lock)."""
        ref = self._mean_chunk_wall(thief_sid)
        if ref is None:
            return None  # no completed chunk anywhere yet: too early
        best, best_proj = None, 0.0
        for vsid, runner in self._active.items():
            if vsid == thief_sid:
                continue
            p = runner.progress()
            if p["rows_remaining"] <= 0:
                continue
            if p["rows_done"] > 0:
                proj = p["rows_remaining"] * p["elapsed_s"] / p["rows_done"]
            elif p["elapsed_s"] > 2.0 * ref:
                proj = math.inf  # no chunk done yet and already overdue
            else:
                continue
            if proj > best_proj:
                best, best_proj = runner, proj
        if best is None:
            return None
        if best_proj <= self.plan.rebalance_threshold * ref:
            return None
        return best

    def _next_work(self, sid: int):
        """Block until there is a span for this lane: from the queue, or
        stolen from a straggler.  None = no work will ever come (all spans
        done, or a fatal error is propagating)."""
        cond = self.queue.cond
        while True:
            with cond:
                if self._fatal is not None:
                    return None
                span = self.queue._pull_locked(sid)
                if span is not None:
                    self._busy.add(sid)
                    return span
                if not self._busy and not self.queue._spans:
                    cond.notify_all()  # release peers blocked in wait()
                    return None
                victim = self._pick_victim_locked(sid)
            if victim is not None:
                stolen = victim.try_steal()
                if stolen is not None:
                    with cond:
                        self._busy.add(sid)
                        self._steals += 1
                    obs.counter("lane.steal").inc()
                    obs.counter("lane.rebalance").inc()
                    obs.event("lane.steal", shard=sid,
                              victim=victim.spec.shard_id,
                              lo=stolen[0], hi=stolen[1])
                    return stolen
            with cond:
                # spans preferred by not-yet-started peers also park us
                # here: their own lanes will pull them (or a quarantine
                # will release them to everyone)
                if self._fatal is None and (self._busy
                                            or self.queue._spans):
                    cond.wait(timeout=0.05)

    def _values_for(self, spec0: LaneSpec, values0, lo: int, hi: int):
        """The values a lane walks for span ``[lo, hi)``: its own resident
        array when that IS its nominal span, else a re-staged O(chunk)
        view onto the driver's panel/source."""
        if (lo, hi) == (spec0.lo, spec0.hi):
            return values0
        if self.restage is None:
            raise RuntimeError(
                "elastic reassignment needs a restage callback")
        return self.restage(lo, hi, spec0.device)

    def _quarantine(self, sid: int, e: Exception, attempts: int,
                    lo: int, hi: int) -> None:
        cause = f"{type(e).__name__}: {e}"[:200]
        rec = {"shard_id": int(sid), "cause": cause,
               "retries": int(attempts - 1), "span": [int(lo), int(hi)]}
        with self.queue.cond:
            self._quarantined.append(rec)
            self._errors.append(e)
            self._busy.discard(sid)
            self._push_remainder_locked(sid, lo, hi)
            # any span still reserved for this lane is up for grabs now
            self.queue._release_preference_locked(sid)
            self.queue.cond.notify_all()
        obs.counter("lane.quarantine").inc()
        obs.counter("lane.rebalance").inc()
        obs.event("lane.quarantine", shard=sid, cause=cause,
                  retries=attempts - 1, lo=lo, hi=hi)
        self._state(sid, "quarantined")

    def _push_remainder_locked(self, sid: int, lo: int, hi: int) -> None:
        self.queue._push_locked(lo, hi, preferred=None)

    # -- the lane loop ------------------------------------------------------

    def _drive(self, idx: int) -> None:
        plan = self.plan
        spec0, values0 = self.lanes[idx]
        sid = spec0.shard_id
        cond = self.queue.cond
        jour = self._journal_by_sid.get(sid)
        self._state(sid, "active")
        while True:
            work = self._next_work(sid)
            if work is None:
                self._state(sid,
                            "done" if self._fatal is None else "stopped")
                return
            lo, hi = work
            try:
                vals = self._values_for(spec0, values0, lo, hi)
            except Exception as e:  # noqa: BLE001 - a restage failure is a
                # lane failure (the device may be gone): quarantine, do not
                # kill the job
                self._quarantine(sid, e, 1, lo, hi)
                return
            failures = 0
            span_hi = hi
            while True:  # attempt loop over this span
                spec = LaneSpec(sid, lo, span_hi, spec0.device)
                if span_hi != hi and not isinstance(
                        vals, (source_mod.SourceLane, RestagedPanel)):
                    # a steal landed during a failed attempt: shrink the
                    # resident values to the kept span so the whole-span
                    # hand-through can never pass extra rows to the fit
                    vals = vals[:span_hi - lo]
                    hi = span_hi
                runner = LaneRunner(plan, spec, self.fit_fn,
                                    self.fit_kwargs, vals, journal=jour,
                                    deadline=self.deadline, tele=self.tele,
                                    fit_key=self.fit_key)
                with cond:
                    self._active[sid] = runner
                self._state(sid, "active")
                t0 = time.perf_counter()
                try:
                    result = runner.run()
                except Exception as e:  # noqa: BLE001 - lane containment
                    with cond:
                        self._active.pop(sid, None)
                    # close the failed span to steals BEFORE reading its
                    # end: a thief holding this runner could otherwise
                    # still shrink it after we decide what to retry/
                    # re-enqueue, and the stolen tail would be walked by
                    # both sides (duplicate rows in assembly)
                    span_hi = runner.close_steals()
                    failures += 1
                    if failures <= plan.lane_retries:
                        # concurrent peers retry too: the counter is
                        # cond-guarded like the rest of the supervisor
                        # state (a bare += here dropped increments)
                        with cond:
                            self._retries += 1
                        self._state(sid, "retrying")
                        obs.counter("lane.retry").inc()
                        obs.event("lane.retry", shard=sid, attempt=failures,
                                  lo=lo, hi=span_hi,
                                  error=f"{type(e).__name__}: {e}"[:160])
                        time.sleep(plan.lane_retry_backoff_s
                                   * (2 ** (failures - 1)))
                        continue
                    self._quarantine(sid, e, failures, lo, span_hi)
                    return
                except BaseException as e:  # crash/interrupt: fatal, no
                    # containment — resume semantics must match the static
                    # layout (the journal, not a survivor, is the recovery)
                    with cond:
                        self._active.pop(sid, None)
                        self._busy.discard(sid)
                        if self._fatal is None:
                            self._fatal = e
                        cond.notify_all()
                    raise
                break
            span_wall = time.perf_counter() - t0
            n_comp = 0
            for _plo, _phi, p in result.pieces:
                if isinstance(p, _TimeoutChunk):
                    continue
                pm = getattr(p, "meta", None)
                if isinstance(pm, dict) and pm.get("resumed_from_journal"):
                    continue
                n_comp += 1
            with cond:
                self._active.pop(sid, None)
                self._busy.discard(sid)
                self.results.append(result)
                if n_comp:
                    self._global_walls.append((n_comp, span_wall))
                    prev = self._lane_mean_wall.get(sid)
                    mean = span_wall / n_comp
                    self._lane_mean_wall[sid] = (
                        mean if prev is None else 0.5 * (prev + mean))
                cond.notify_all()
            self._state(sid, "idle")

    # -- entry point --------------------------------------------------------

    def run(self) -> Tuple[list, dict]:
        """Run the elastic walk; returns ``(results, elastic_meta)``.

        Raises the fatal error (crash/interrupt) unchanged, or — when
        every lane was quarantined with spans still unprocessed — the
        FIRST quarantined lane's original error.
        """
        for spec, _vals in self.lanes:
            self.queue.push(spec.lo, spec.hi, preferred=spec.shard_id)
        threads = [
            threading.Thread(target=self._drive_safe, args=(i,), daemon=True,
                             name=f"chunk-lane-{spec.shard_id}")
            for i, (spec, _v) in enumerate(self.lanes)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        if self._fatal is not None:
            raise self._fatal
        undone = self.queue.pending()
        if undone:
            # every lane is gone and work remains: the job is lost — fail
            # with the FIRST lane's original error (invariant 3 of the
            # tentpole), the quarantine record riding its __notes__-free
            # message via the exception chain below
            first = self._errors[0] if self._errors else RuntimeError(
                f"elastic walk stalled with spans pending: {undone}")
            raise first
        with self.queue.cond:
            # every lane joined, but the declared discipline (results is
            # cond-guarded) holds uniformly — uncontended here
            self.results.sort(key=lambda r: r.spec.lo)
        return self.results, self.elastic_meta()

    def _drive_safe(self, idx: int) -> None:
        sid = self.lanes[idx][0].shard_id
        try:
            self._drive(idx)
        except BaseException as e:  # noqa: BLE001 - re-raised after join
            # ANY error escaping the lane loop — including supervisor-level
            # failures outside the runner.run() handlers (LaneRunner
            # construction, the retry-path values slice) — is recorded as
            # fatal and the lane's busy state released, so peers stop
            # polling and the job FAILS LOUDLY instead of hanging with a
            # silently dead lane still marked busy
            with self.queue.cond:
                self._active.pop(sid, None)
                self._busy.discard(sid)
                if self._fatal is None:
                    self._fatal = e
                self.queue.cond.notify_all()

    def elastic_meta(self) -> dict:
        return {
            "quarantined": list(self._quarantined),
            "steals": int(self._steals),
            "lane_retries_used": int(self._retries),
            "reassigned_spans": len(self._quarantined) + int(self._steals),
        }
