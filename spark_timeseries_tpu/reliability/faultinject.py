"""Deterministic fault injection for the reliability layer.

Every rung of the resilience ladder must be exercisable in tier-1 CPU
tests — a recovery path that only runs when a real TPU OOMs is a recovery
path that has never run.  This module provides two kinds of fault:

**Data faults** (pure ``numpy -> numpy`` panel corruptions): NaN holes
inside the valid span, inf spikes, constant rows, all-NaN rows, and
explosive near-collinear rows whose f32 normal equations go indefinite
(the non-SPD Hannan-Rissanen case of ADVICE round 5).  All are driven by
an explicit seed.

**Behavioral faults** (fit-function wrappers): :func:`failing_fit` forces
designated rows to report non-convergence for a fixed number of fit calls
— rows are recognized by a value fingerprint, so the same row keeps
failing as the ladder gathers it into retry sub-batches — and
:func:`oom_fit` raises a ``RESOURCE_EXHAUSTED``-marked error whenever the
batch exceeds a row threshold, driving the chunk driver's backoff without
a real allocation failure.

**Process/durability faults** (ISSUE 2 — the chunk journal and watchdog
must be exercisable in tier-1 CPU tests): :func:`hanging_fit` stalls
designated fit calls past any watchdog budget; :func:`kill_after_commits`
and :func:`crash_after_commits` are journal commit hooks that SIGKILL the
process / raise mid-run after N durable chunk commits (between or mid
commit, selectable), simulating preemption exactly where it hurts; and
:func:`tear_file` truncates a manifest or shard to a prefix, simulating a
torn write on a non-atomic filesystem.

**Lane faults** (ISSUE 11 — the elastic sharded walk's quarantine and
rebalance paths must be exercisable without real hardware failures):
:func:`lane_kill` raises a :class:`SimulatedLaneFailure` on every fit
call a designated lane dispatches (after an optional warm-up chunk
count), simulating a dead device; :func:`slow_lane` delays one lane's
every fit call, simulating a straggler chip the rebalancer must steal
from; and :func:`lane_oom_storm` makes one lane's every allocation fail
``RESOURCE_EXHAUSTED`` so its backoff ladder exhausts and the lane is
quarantined.  All three key on :func:`~.watchdog.current_lane` — the
thread-local lane tag the :class:`~.plan.LaneRunner` sets around each
chunk dispatch — so the SAME wrapped fit behaves normally on every
other lane, deterministically.

**Request faults** (ISSUE 12 — the resident fit server's admission,
deadline, shedding, and crash-recovery paths): :func:`request_storm`
burst-admits a list of submissions from a thread pool (driving the
bounded queue into shedding); :func:`server_kill` SIGKILLs the serving
process after N durable chunk commits across its batch walks;
:func:`slow_tenant` makes any micro-batch carrying one tenant's rows
straggle, keyed on the thread-local request tag
(:func:`~.watchdog.current_request`) exactly like the lane faults key on
the lane tag.

**Transport faults** (ISSUE 16 — the fleet's socket plane): a
:func:`frame_fault_schedule` maps a seed to a deterministic per-frame
fault sequence (drop / duplicate / tear / pass), and :class:`FaultyWire`
wraps a client socket so each ``sendall`` — exactly one wire frame by
the transport contract — suffers its scheduled fault: dropped frames
exercise the client's reconnect-and-resubmit path, duplicated frames the
server's idempotent-resubmit ack and the client's msg-id reply pairing,
torn frames (a prefix followed by an abrupt reset) the CRC frame
validation, and ``reset_after`` connection resets the mid-batch failover
path.  Replica death mid-storm reuses :func:`server_kill` — the fleet
primary is just a FitServer.

**Disk faults** (ISSUE 17 — storage-fault tolerance):
:func:`disk_fault_schedule` maps a seed to a deterministic per-write
fault sequence (EIO / ENOSPC / torn-at-fsync / pass) and
:class:`disk_faults` installs it as the journal's process-wide
disk-fault hook (:func:`~.journal.set_disk_fault_hook`), so the REAL
durable write paths — journal shards, serving write-ahead records,
stored results — fail on cue: refusals must surface as typed
``storage_degraded`` backpressure (never a crash), torn files must be
rejected loudly by readers and recomputed by recovery.
"""

from __future__ import annotations

import functools
import os
import signal
import time
from typing import Callable, Optional

import numpy as np

from .status import STATUS_DTYPE, FitStatus
from .watchdog import current_lane, current_request

__all__ = [
    "FaultyWire",
    "SimulatedCrash",
    "SimulatedLaneFailure",
    "SimulatedResourceExhausted",
    "disk_fault_schedule",
    "disk_faults",
    "frame_fault_schedule",
    "inject_nan_rows",
    "inject_inf_rows",
    "make_constant_rows",
    "make_all_nan_rows",
    "make_explosive_rows",
    "nonspd_gram",
    "failing_fit",
    "oom_fit",
    "hanging_fit",
    "kill_after_commits",
    "crash_after_commits",
    "lane_kill",
    "lane_oom_storm",
    "request_storm",
    "server_kill",
    "slow_lane",
    "slow_tenant",
    "tear_file",
]


class SimulatedResourceExhausted(RuntimeError):
    """Stands in for jaxlib's XlaRuntimeError on allocation failure.

    Carries the same ``RESOURCE_EXHAUSTED`` marker the real error message
    does, so ``reliability.chunked.is_resource_exhausted`` treats both
    identically.
    """

    def __init__(self, nbytes: int):
        super().__init__(
            f"RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            f"{nbytes} bytes. (simulated by reliability.faultinject)"
        )


def _as_host(y) -> np.ndarray:
    return np.array(y, dtype=np.asarray(y).dtype, copy=True)


def inject_nan_rows(y, rows, frac: float = 0.2, seed: int = 0) -> np.ndarray:
    """Punch NaN holes INSIDE the valid span of the given rows.

    Edge positions are kept so the holes are interior — the fault the
    sanitizer must repair, not legitimate raggedness.
    """
    out = _as_host(y)
    rng = np.random.default_rng(seed)
    t = out.shape[1]
    n_holes = max(1, int(frac * (t - 2)))
    for r in np.atleast_1d(rows):
        holes = rng.choice(np.arange(1, t - 1), size=n_holes, replace=False)
        out[r, holes] = np.nan
    return out


def inject_inf_rows(y, rows, n: int = 3, seed: int = 0) -> np.ndarray:
    """Replace ``n`` interior positions of each given row with +/-inf."""
    out = _as_host(y)
    rng = np.random.default_rng(seed)
    t = out.shape[1]
    for r in np.atleast_1d(rows):
        pos = rng.choice(np.arange(1, t - 1), size=n, replace=False)
        out[r, pos] = np.where(rng.random(n) < 0.5, np.inf, -np.inf)
    return out


def make_constant_rows(y, rows, value: float = 1.0) -> np.ndarray:
    """Overwrite the given rows with a constant (zero innovation variance)."""
    out = _as_host(y)
    out[np.atleast_1d(rows)] = value
    return out


def make_all_nan_rows(y, rows) -> np.ndarray:
    """Overwrite the given rows with NaN everywhere (nothing to fit)."""
    out = _as_host(y)
    out[np.atleast_1d(rows)] = np.nan
    return out


def make_explosive_rows(y, rows, growth: float = 1.35, seed: int = 0) -> np.ndarray:
    """Overwrite rows with an explosive near-collinear AR process.

    ``y_t ~= growth * y_{t-1}`` spans ~130 orders of magnitude over a 1k
    panel: at f32 the Hannan-Rissanen lag Gram matrix accumulates to an
    (effectively) indefinite / overflowed system — the non-SPD
    normal-equations fault of ADVICE round 5 — and CSS optimization on the
    row is hopeless within any budget, exercising the DIVERGED terminal.
    """
    out = _as_host(y)
    rng = np.random.default_rng(seed)
    t = out.shape[1]
    for r in np.atleast_1d(rows):
        noise = 1.0 + 0.01 * rng.standard_normal(t)
        out[r] = (growth ** np.arange(t)) * noise
    return out


def nonspd_gram(k: int = 4, dtype=np.float32) -> np.ndarray:
    """A deterministic symmetric matrix with one (slightly) negative
    eigenvalue — what f32 accumulation can make of a rank-deficient
    ``X^T X``.  For unit tests of ``utils.linalg.ridge_solve``'s
    non-positive-pivot fallback."""
    rng = np.random.default_rng(7)
    q, _ = np.linalg.qr(rng.standard_normal((k, k)))
    eig = np.ones(k)
    eig[-1] = -1e-3
    return (q @ np.diag(eig) @ q.T).astype(dtype)


def _fingerprints(y, rows) -> np.ndarray:
    """Identify rows by their last value (float64-exact).

    The resilient runner re-fits failed rows on the SAME (sanitized) data,
    so a row's tail value is stable across ladder rungs and sub-batch
    gathers; designated rows should be NaN-free so the sanitizer passes
    them through bit-identically.
    """
    tails = np.asarray(y)[np.atleast_1d(rows), -1].astype(np.float64)
    if np.unique(tails).size != tails.size or np.isnan(tails).any():
        raise ValueError(
            "failing_fit fingerprints must be unique, finite tail values; "
            "pick clean rows (or perturb their last sample)"
        )
    return tails


def failing_fit(fit_fn: Callable, y, rows, n_failures: int = 1) -> Callable:
    """Wrap ``fit_fn`` so the given rows of ``y`` report non-convergence.

    Each designated row fails (``converged=False``, NaN params/nll,
    ``DIVERGED`` model status) for its first ``n_failures`` fit calls that
    include it, then behaves normally — so ``n_failures=1`` drives the
    ``RETRIED`` transition, ``n_failures=2`` drives ``FALLBACK`` (with the
    default two-rung ladder), and a large value drives ``DIVERGED``.
    Budgets decrement once per CALL per row (pad rows duplicating a failed
    row do not burn extra budget).
    """
    budgets = {fp: n_failures for fp in _fingerprints(y, rows)}

    # functools.wraps: signature introspection (the runner's per-rung
    # kwarg filtering) must see the REAL fit's signature, not (yb, **kw)
    @functools.wraps(fit_fn)
    def wrapped(yb, **kwargs):
        res = fit_fn(yb, **kwargs)
        tails = np.asarray(yb)[:, -1].astype(np.float64)
        mask = np.zeros(tails.shape[0], bool)
        for fp in list(budgets):
            if budgets[fp] <= 0:
                continue
            hit = tails == fp
            if hit.any():
                mask |= hit
                budgets[fp] -= 1
        if not mask.any():
            return res
        import jax.numpy as jnp

        m = jnp.asarray(mask)
        params = jnp.where(m[:, None], jnp.nan, res.params)
        nll = jnp.where(m, jnp.nan, res.neg_log_likelihood)
        conv = res.converged & ~m
        status = res.status
        if status is not None:
            status = jnp.where(
                m, np.int8(FitStatus.DIVERGED), status
            ).astype(STATUS_DTYPE)
        return res._replace(
            params=params, neg_log_likelihood=nll, converged=conv,
            status=status,
        )

    return wrapped


def oom_fit(fit_fn: Callable, max_rows: int) -> Callable:
    """Wrap ``fit_fn`` to raise a simulated RESOURCE_EXHAUSTED whenever the
    batch has more than ``max_rows`` rows — the chunk driver must back off
    to at most ``max_rows`` before the fit is allowed to run."""

    @functools.wraps(fit_fn)
    def wrapped(yb, **kwargs):
        shape = np.asarray(yb.shape)
        if int(shape[0]) > max_rows:
            raise SimulatedResourceExhausted(int(shape.prod()) * 4)
        return fit_fn(yb, **kwargs)

    return wrapped


# ---------------------------------------------------------------------------
# process / durability faults (chunk journal + deadline watchdog)
# ---------------------------------------------------------------------------


class SimulatedCrash(BaseException):
    """In-process stand-in for a SIGKILL: derives from ``BaseException`` so
    no ``except Exception`` recovery path can accidentally swallow it — the
    journaled driver must survive by durability, not by catching it."""


def hanging_fit(fit_fn: Callable, hang_calls, sleep_s: float = 30.0) -> Callable:
    """Wrap ``fit_fn`` so the given (0-based) call indices stall ``sleep_s``
    before fitting — a stand-in for a hung compile or pathological optimizer
    tail.  With a ``chunk_budget_s`` below ``sleep_s`` the watchdog abandons
    the call and marks the chunk TIMEOUT; the abandoned worker thread wakes
    later, runs the real fit, and its result is discarded.  One fit call per
    chunk (``resilient=False``) makes the call index the chunk index."""
    hang = set(int(i) for i in np.atleast_1d(hang_calls))
    state = {"calls": 0}

    @functools.wraps(fit_fn)
    def wrapped(yb, **kwargs):
        i = state["calls"]
        state["calls"] += 1
        if i in hang:
            time.sleep(sleep_s)
        return fit_fn(yb, **kwargs)

    return wrapped


def kill_after_commits(n: int, *, mid_commit: bool = False) -> Callable:
    """Journal commit hook that SIGKILLs THIS process after ``n`` chunks
    have been made durable — no atexit, no cleanup, exactly like a
    preemption.  ``mid_commit=True`` kills after the nth shard is written
    but BEFORE the manifest names it (the orphan-shard window the
    write-ahead ordering must make recoverable); otherwise the kill lands
    after the manifest update (between chunks).  Pass as
    ``fit_chunked(..., _journal_commit_hook=...)`` in a subprocess.
    """
    event = "shard_written" if mid_commit else "committed"
    seen = {"n": 0}

    def hook(ev: str, lo: int) -> None:
        if ev != event:
            return
        seen["n"] += 1
        if seen["n"] >= n:
            os.kill(os.getpid(), signal.SIGKILL)

    return hook


def crash_after_commits(n: int, *, mid_commit: bool = False) -> Callable:
    """Like :func:`kill_after_commits` but raises :class:`SimulatedCrash`
    instead of dying — the in-process variant for tests that want to crash
    and resume inside one interpreter (same journal state on disk, no
    subprocess round trip)."""
    event = "shard_written" if mid_commit else "committed"
    seen = {"n": 0}

    def hook(ev: str, lo: int) -> None:
        if ev != event:
            return
        seen["n"] += 1
        if seen["n"] >= n:
            raise SimulatedCrash(
                f"simulated process death after {n} {event} events")

    return hook


# ---------------------------------------------------------------------------
# lane faults (ISSUE 11: elastic sharded walk — quarantine and rebalance)
# ---------------------------------------------------------------------------


class SimulatedLaneFailure(RuntimeError):
    """Stands in for a dead lane device: an exception no backoff ladder can
    absorb (not RESOURCE_EXHAUSTED, not a watchdog timeout), so the elastic
    supervisor's retry → quarantine path is the only recovery."""

    def __init__(self, shard_id: int):
        super().__init__(
            f"lane shard={shard_id} failed "
            "(simulated by reliability.faultinject.lane_kill)")
        self.shard_id = int(shard_id)


def lane_kill(fit_fn: Callable, shard_id: int, after_chunks: int = 0,
              n_failures: Optional[int] = None) -> Callable:
    """Wrap ``fit_fn`` so lane ``shard_id``'s fit calls raise
    :class:`SimulatedLaneFailure` after ``after_chunks`` successful calls.

    ``n_failures=None`` (default) is a PERMANENT death — every later call
    on that lane fails too, so the supervisor's retries burn out and the
    lane is quarantined, its span reassigned to survivors.  An integer
    makes the fault TRANSIENT (the lane recovers after that many
    failures), exercising the retry-without-quarantine path.  Calls from
    other lanes (or outside any lane) pass through untouched.
    """
    state = {"ok": 0, "failed": 0}

    @functools.wraps(fit_fn)
    def wrapped(yb, **kwargs):
        if current_lane() == int(shard_id):
            if state["ok"] >= int(after_chunks) and (
                    n_failures is None or state["failed"] < int(n_failures)):
                state["failed"] += 1
                raise SimulatedLaneFailure(int(shard_id))
            state["ok"] += 1
        return fit_fn(yb, **kwargs)

    return wrapped


def slow_lane(fit_fn: Callable, shard_id: int, delay_s: float) -> Callable:
    """Wrap ``fit_fn`` so lane ``shard_id`` stalls ``delay_s`` before every
    fit call — a deterministic straggler chip.  The elastic walk's idle
    survivors should STEAL the straggler's unstarted chunks once its
    projected finish blows the rebalance threshold; the fault follows the
    LANE, so stolen chunks run at full speed on their new lane."""

    @functools.wraps(fit_fn)
    def wrapped(yb, **kwargs):
        if current_lane() == int(shard_id):
            time.sleep(float(delay_s))
        return fit_fn(yb, **kwargs)

    return wrapped


def lane_oom_storm(fit_fn: Callable, shard_id: int) -> Callable:
    """Wrap ``fit_fn`` so every fit call on lane ``shard_id`` raises a
    simulated ``RESOURCE_EXHAUSTED`` — an allocator storm no chunk halving
    survives.  The lane's OOM backoff ladder exhausts
    (``OOMBackoffExceeded``), its retries re-exhaust, and the elastic
    supervisor quarantines it; survivors recompute its chunks at their own
    (healthy) chunk size."""

    @functools.wraps(fit_fn)
    def wrapped(yb, **kwargs):
        if current_lane() == int(shard_id):
            raise SimulatedResourceExhausted(
                int(np.prod(np.asarray(yb.shape))) * 4)
        return fit_fn(yb, **kwargs)

    return wrapped


# ---------------------------------------------------------------------------
# request faults (ISSUE 12: the resident fit server's admission, deadline,
# shedding, and crash-recovery paths must be exercisable in tier-1 CPU tests)
# ---------------------------------------------------------------------------


def request_storm(submit: Callable, calls, threads: int = 8,
                  timeout_s: float = 120.0) -> tuple:
    """Burst-admit ``calls`` concurrently — the admission-control load
    test.  ``submit`` is typically ``server.submit``; each element of
    ``calls`` is ``(args_tuple, kwargs_dict)`` and is fired from a pool
    of ``threads`` worker threads as fast as they can go.

    Returns ``(results, errors)``, both lists aligned with ``calls``:
    ``results[i]`` is the submit's return value (a ticket) or None,
    ``errors[i]`` the exception it raised (``RejectedError`` under
    overload — the storm is exactly how shedding is driven) or None.
    Deterministic in coverage, deliberately NOT in interleaving: the
    invariant under test is conservation (every call is answered or
    explicitly rejected; none hang, none OOM), not ordering.
    """
    import queue as queue_mod
    import threading

    calls = list(calls)
    results: list = [None] * len(calls)
    errors: list = [None] * len(calls)
    work: "queue_mod.Queue" = queue_mod.Queue()
    for i, c in enumerate(calls):
        work.put((i, c))

    def _worker():
        while True:
            try:
                i, (args, kwargs) = work.get_nowait()
            except queue_mod.Empty:
                return
            try:
                results[i] = submit(*args, **(kwargs or {}))
            except BaseException as e:  # noqa: BLE001 - reported per call
                errors[i] = e

    ts = [threading.Thread(target=_worker, daemon=True,
                           name=f"request-storm-{k}")
          for k in range(max(1, int(threads)))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=timeout_s)
    return results, errors


def server_kill(n_commits: int, *, mid_commit: bool = False) -> Callable:
    """SIGKILL stand-in for a dying fit SERVER: a journal commit hook that
    kills the process after ``n_commits`` durable chunk commits COUNTED
    ACROSS every batch walk the server runs (pass as
    ``FitServer(_commit_hook=...)`` in a subprocess).  With
    ``mid_commit=True`` the kill lands inside a commit (shard written,
    manifest not yet updated) — the torn-batch window restart recovery
    must replay.  Same contract as :func:`kill_after_commits`; the
    serving spelling exists so the serving tests read as what they
    simulate."""
    return kill_after_commits(n_commits, mid_commit=mid_commit)


def slow_tenant(fit_fn: Callable, tenant: str, delay_s: float) -> Callable:
    """Wrap ``fit_fn`` so any serving batch carrying ``tenant``'s rows
    straggles ``delay_s`` per fit call — one tenant's pathological panel
    slowing the micro-batch it rides in.  Keys on the thread-local
    request tag (:func:`~.watchdog.current_request`, the serving twin of
    the PR 10 lane tags), so the SAME registered fit behaves normally for
    every other batch, deterministically; with a chunk/job budget armed
    the watchdog TIMEOUTs the straggling batch instead of hanging the
    server."""

    @functools.wraps(fit_fn)
    def wrapped(yb, **kwargs):
        tags = current_request() or ()
        if tenant in tags:
            time.sleep(float(delay_s))
        return fit_fn(yb, **kwargs)

    return wrapped


# ---------------------------------------------------------------------------
# transport faults (ISSUE 16: the fleet's socket plane — dropped/duplicated/
# half-written frames and connection resets, deterministically seeded)
# ---------------------------------------------------------------------------


def frame_fault_schedule(seed: int, n: int, *, drop_frac: float = 0.1,
                         dup_frac: float = 0.1,
                         tear_frac: float = 0.05) -> list:
    """A deterministic per-frame fault plan: ``n`` entries drawn from
    ``{"pass", "drop", "dup", "tear"}`` with the given rates.  Same seed
    → same schedule, bit-for-bit, so a transport test's fault pattern is
    reproducible from its seed alone (the client's backoff jitter is
    seeded the same way — :func:`serving.client.backoff_schedule`)."""
    if drop_frac + dup_frac + tear_frac > 1.0:
        raise ValueError("fault fractions must sum to at most 1.0")
    rng = np.random.default_rng(int(seed))
    u = rng.random(int(n))
    out = []
    for x in u:
        if x < drop_frac:
            out.append("drop")
        elif x < drop_frac + dup_frac:
            out.append("dup")
        elif x < drop_frac + dup_frac + tear_frac:
            out.append("tear")
        else:
            out.append("pass")
    return out


class FaultyWire:
    """A lossy socket: each ``sendall`` (one wire frame, by the transport
    layer's one-``sendall``-per-message contract) consumes the next entry
    of a :func:`frame_fault_schedule` — ``pass`` forwards the frame,
    ``drop`` swallows it (the peer never sees it; the client's deadline +
    resubmit machinery must recover), ``dup`` forwards it twice (the
    server must ack idempotently and the client must pair replies by
    msg id), ``tear`` forwards a half-frame prefix then resets the
    connection (the peer's CRC/EOF validation must reject the torn frame
    loudly).  ``reset_after=k`` additionally drops the connection after
    ``k`` successful frames — the mid-batch reset fault.  Past the end of
    the schedule every frame passes (faults are a finite storm, not a
    dead wire).  Duck-types the socket surface the transport layer uses
    (``sendall/recv/settimeout/close``); wrap client connections via
    ``FitClient(_wire_wrap=...)``."""

    def __init__(self, sock, schedule, *, reset_after: Optional[int] = None):
        self._sock = sock
        self._schedule = list(schedule)
        self._sent = 0
        self._ok = 0
        self._reset_after = None if reset_after is None else int(reset_after)
        self.log: list = []

    def _next_fault(self) -> str:
        i = self._sent
        self._sent += 1
        if self._reset_after is not None and self._ok >= self._reset_after:
            return "reset"
        return self._schedule[i] if i < len(self._schedule) else "pass"

    def sendall(self, data: bytes) -> None:
        fault = self._next_fault()
        self.log.append(fault)
        if fault == "drop":
            return
        if fault == "dup":
            self._sock.sendall(data)
            self._sock.sendall(data)
            self._ok += 1
            return
        if fault == "tear":
            self._sock.sendall(data[: max(1, len(data) // 2)])
            self._reset()
            raise ConnectionResetError(
                "simulated torn frame (reliability.faultinject.FaultyWire)")
        if fault == "reset":
            self._reset()
            raise ConnectionResetError(
                "simulated connection reset "
                "(reliability.faultinject.FaultyWire)")
        self._sock.sendall(data)
        self._ok += 1

    def _reset(self) -> None:
        try:
            import socket as socket_mod
            import struct

            # SO_LINGER 0: RST on close, not FIN — an abrupt peer death
            self._sock.setsockopt(socket_mod.SOL_SOCKET,
                                  socket_mod.SO_LINGER,
                                  struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def recv(self, n: int) -> bytes:
        return self._sock.recv(n)

    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def tear_file(path: str, keep_frac: float = 0.5) -> None:
    """Truncate ``path`` to a prefix, simulating a torn write (a crash on a
    filesystem without atomic replace, or a partially flushed page).  Torn
    manifests must be REJECTED on resume (``TornManifestError``), torn
    shards silently downgraded to a recompute."""
    size = os.path.getsize(path)
    keep = max(1, int(size * keep_frac))
    with open(path, "r+b") as f:
        f.truncate(keep)


# ---------------------------------------------------------------------------
# disk faults (ISSUE 17: storage-fault tolerance — the durable write paths
# themselves fail, and the server must degrade, not crash)
# ---------------------------------------------------------------------------


def disk_fault_schedule(seed: int, n: int, *, eio_frac: float = 0.05,
                        enospc_frac: float = 0.05,
                        torn_frac: float = 0.05) -> list:
    """A deterministic per-write disk-fault plan: ``n`` entries drawn
    from ``{"pass", "eio", "enospc", "torn"}`` with the given rates —
    the durable-write twin of :func:`frame_fault_schedule`.  ``eio`` and
    ``enospc`` refuse the write before any bytes land (the server must
    answer ``storage_degraded``, never crash); ``torn`` lets the replace
    land then truncates the file (a lying fsync — readers must reject
    the bytes loudly, recovery must recompute)."""
    if eio_frac + enospc_frac + torn_frac > 1.0:
        raise ValueError("fault fractions must sum to at most 1.0")
    rng = np.random.default_rng(int(seed))
    u = rng.random(int(n))
    out = []
    for x in u:
        if x < eio_frac:
            out.append("eio")
        elif x < eio_frac + enospc_frac:
            out.append("enospc")
        elif x < eio_frac + enospc_frac + torn_frac:
            out.append("torn")
        else:
            out.append("pass")
    return out


class disk_faults:
    """Context manager installing a :func:`disk_fault_schedule` as the
    process-wide journal disk-fault hook
    (:func:`~.journal.set_disk_fault_hook`).

    Each GUARDED durable write — journal shards/manifests
    (``kind="durable"``), serving write-ahead records
    (``kind="write_ahead"``), stored results (``kind="result"``) —
    consumes the next schedule entry; past the end every write passes
    (faults are a finite storm, not a dead disk).  ``kinds`` restricts
    the fault to a write class and ``path_substr`` to matching paths;
    filtered-out writes pass WITHOUT consuming schedule entries, so a
    schedule's shape is independent of unrelated background writes.
    ``log`` records ``(kind, path, verdict)`` per faulted consult for
    the chaos invariant checker.

    .. attribute:: _protected_by_

        Lock-discipline contract (tools/lint lock-map): concurrent
        durable writers (serve loop, committer thread, standby scratch)
        all consult the one installed hook; the schedule cursor and the
        fault log advance under the lock so each entry is consumed
        exactly once.
    """

    _protected_by_ = {
        "_i": "_lock",
        "log": "_lock",
    }

    def __init__(self, schedule, *, kinds: Optional[tuple] = None,
                 path_substr: Optional[str] = None):
        self._schedule = list(schedule)
        self._kinds = None if kinds is None else tuple(kinds)
        self._path_substr = path_substr
        self._i = 0
        self._lock = None  # created on enter (threading import kept local)
        self._prev = None
        self.log: list = []

    def _hook(self, path: str, kind: str) -> str:
        if self._kinds is not None and kind not in self._kinds:
            return "pass"
        if self._path_substr is not None and self._path_substr not in path:
            return "pass"
        with self._lock:
            i = self._i
            self._i += 1
            verdict = (self._schedule[i] if i < len(self._schedule)
                       else "pass")
            if verdict != "pass":
                self.log.append((kind, path, verdict))
        return verdict

    def __enter__(self) -> "disk_faults":
        import threading

        from . import journal

        self._lock = threading.Lock()
        self._prev = journal.set_disk_fault_hook(self._hook)
        return self

    def __exit__(self, *exc) -> None:
        from . import journal

        journal.set_disk_fault_hook(self._prev)

