"""Chunked fit execution: pipelined commits, OOM backoff, journal, watchdog.

The north-star workload (ROADMAP: 1M series x 1k obs) cannot always fit one
monolithic batch in HBM — and the right chunk size depends on the model,
the dtype, and what else is resident on the chip.  Rather than making the
caller guess, :func:`fit_chunked` walks the panel in row chunks and treats
``RESOURCE_EXHAUSTED`` as a recoverable signal: the chunk size is halved
(bounded retries) and the degradation is recorded in the result metadata,
the batch analog of Spark re-running a too-big task after an executor OOM.

Only allocation failures trigger backoff; every other error propagates
unchanged (halving a chunk cannot fix a shape bug, and silently retrying
would bury it).

Above the backoff sit the two *job-level* durability layers Spark provided
for free and a single Python process does not:

- ``checkpoint_dir=`` attaches a write-ahead **chunk journal**
  (:mod:`.journal`): every finished chunk is committed as an npz shard
  plus an atomically updated manifest, and a restarted run SKIPS committed
  chunks, producing results bitwise-identical to an uninterrupted run.
- ``chunk_budget_s=`` / ``job_budget_s=`` arm the **deadline watchdog**
  (:mod:`.watchdog`): a chunk that overruns its wall-clock budget is
  marked ``FitStatus.TIMEOUT`` (rows NaN, journal entry ``TIMEOUT``) and
  the walk continues; once the job budget is spent, remaining chunks are
  marked TIMEOUT without dispatch.  The job always terminates with exact
  per-row status counts instead of hanging past its SLO, and a later
  resume retries only the TIMEOUT/pending chunks.

**Pipelined execution** (``pipeline=True``, the default): finished chunks
are handed to a bounded background committer
(:class:`~.committer.ChunkCommitter`) that preserves the journal's
single-writer, shard-before-manifest, in-order protocol while the driver
thread is already slicing and dispatching the next chunk; a background
:class:`~.prefetcher.ChunkPrefetcher` stages chunk N+1's device slice
while chunk N computes, under a static align-mode plan computed once per
walk.  The steady state is stage N+1 ∥ compute N ∥ commit N−1, results
are bitwise-identical to ``pipeline=False``, and ``meta["pipeline"]``
reports how much commit and staging wall the overlap hid.

**Host-resident panels** (ISSUE 7): everything above assumed the panel
resident in device memory before the walk began.  Passing a
:class:`~.source.ChunkSource` instead of an array (host ``np.ndarray``
via ``HostChunkSource``, an npz shard directory via ``NpzShardSource``,
or anything ``as_source`` coerces) walks a panel that NEVER fully
resides on device: each chunk is staged H2D through the source's pool of
reusable host buffers — prefetched ahead of the walk by the same
:class:`~.prefetcher.ChunkPrefetcher` — and the staged device buffer is
donated back to the allocator the moment its chunk's fit has consumed
it, so steady-state device footprint is O(chunk), not O(panel).  The
staged bytes are exactly ``panel[lo:hi]``, so the host-resident walk is
bitwise-identical to the in-HBM walk and journals cross-resume between
residencies.

**Sharded execution** (ISSUE 6): everything above ran on ONE device.  With
``shard=True`` (or an explicit ``mesh=``) the walk's configuration is
compiled into an :class:`~.plan.ExecutionPlan` whose lanes partition the
CHUNK GRID contiguously across the mesh's series-axis devices, and one
:class:`~.plan.LaneRunner` per shard — each with its own journal
namespace, committer, and prefetcher — walks its span concurrently while
the job deadline and the obs registry stay shared.  Shard boundaries
always land on the single-device walk's chunk boundaries, so the sharded
result is bitwise-identical to the single-device walk on the same panel;
shard/process 0 merges the per-shard manifests into ONE job manifest
(``journal.merge_job_manifest``) and ONE shard-tagged telemetry timeline,
and a crash/preemption resume replays only the shards/chunks that did not
commit.  Under ``jax.distributed`` each process runs the lanes of its own
addressable shards (build the global panel with
``parallel.mesh.distribute_panel``) and returns its local rows.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from . import delta as delta_mod
from . import journal as journal_mod
from . import plan as plan_mod
from . import sink as sink_mod
from . import source as source_mod
from . import watchdog as watchdog_mod
from .plan import (ExecutionPlan, LaneRunner, LaneSpec, OOMBackoffExceeded,
                   _TimeoutChunk, _piece_status, is_resource_exhausted)
from .runner import ResilientFitResult, _accepted_kwargs
from .status import STATUS_DTYPE, FitStatus, status_counts

__all__ = ["OOMBackoffExceeded", "is_resource_exhausted", "fit_chunked"]


@obs.dump_on_failure("fit_chunked")
def fit_chunked(
    fit_fn: Callable,
    y,
    *,
    chunk_rows: Optional[int] = None,
    min_chunk_rows: int = 256,
    max_backoffs: int = 8,
    resilient: bool = True,
    policy: str = "impute",
    ladder=None,
    checkpoint_dir: Optional[str] = None,
    resume: str = "auto",
    chunk_budget_s: Optional[float] = None,
    job_budget_s: Optional[float] = None,
    pipeline: bool = True,
    pipeline_depth: int = 2,
    prefetch_depth: int = 1,
    align_mode: Optional[str] = None,
    mesh=None,
    shard: bool = False,
    lane_retries: int = 1,
    lane_retry_backoff_s: float = 0.1,
    rebalance_threshold: float = 4.0,
    process_index: Optional[int] = None,
    grid: Optional[tuple] = None,
    delta_from: Optional[str] = None,
    delta_warmstart: bool = True,
    sink=None,
    journal_extra: Optional[dict] = None,
    _journal_commit_hook=None,
    **fit_kwargs,
) -> ResilientFitResult:
    """Fit ``y [B, T]`` in row chunks of at most ``chunk_rows``.

    ``y`` is a device-placeable array — or a :class:`~.source.ChunkSource`
    for panels that must NOT fully reside on device (host RAM, npz shard
    directories): the walk then stages each chunk H2D through the
    source's staging pool as it arrives, at the same chunk boundaries,
    producing bitwise-identical results (see the module docstring's
    host-resident section; ``meta["source"]`` and
    ``meta["pipeline"]["staging_pool"]`` carry the staging accounting,
    and sources without an explicit ``chunk_rows`` default to the
    source's natural chunking, e.g. npz shard size).

    Each chunk runs through :func:`~.runner.resilient_fit` (sanitize +
    retry ladder) unless ``resilient=False``, in which case ``fit_fn`` is
    called directly and per-row status comes from the model's own status
    output.  On a ``RESOURCE_EXHAUSTED`` failure the chunk size halves
    (never below ``min_chunk_rows``) and the chunk is retried, at most
    ``max_backoffs`` times per lane; exhausting the budget (or OOMing at
    the floor) raises :class:`OOMBackoffExceeded`.

    **Durability** (``checkpoint_dir=``): finished chunks are committed to
    a write-ahead journal (:class:`~.journal.ChunkJournal`) — npz shard
    first, then an atomic manifest update recording the row range, per-row
    ``FitStatus`` counts, wall time, peak device memory, and the run's
    config hash / panel fingerprint.  A restarted call with the same panel
    and config (``resume="auto"``, the default) loads committed chunks
    from their shards and recomputes only what is missing, so the final
    result is bitwise-identical to an uninterrupted run; a journal written
    under a different panel or config is rejected
    (:class:`~.journal.StaleJournalError`), as is a torn manifest
    (:class:`~.journal.TornManifestError`) — under EVERY resume mode: a
    journal directory belongs to one (panel, config) job for its lifetime,
    and a different job must claim a fresh directory (or the operator
    removes the old one explicitly).  ``resume="never"`` reruns the same
    job from scratch, ignoring its committed chunks; ``"require"`` demands
    a resumable manifest.  Under ``jax.distributed`` every process
    journals into its own namespace and only process 0 commits the
    job-level ``manifest.json`` (``process_index`` defaults to
    ``jax.process_index()``).

    **Pipelining** (``pipeline=True``, default): with a journal attached,
    the host fetch + shard write + manifest update of a finished chunk run
    on a background committer thread (at most ``pipeline_depth`` commits
    in flight, in order) while the driver dispatches the next chunk, so
    the device no longer idles for the commit latency.  The pipeline
    changes WHERE the commit I/O happens, never what is computed: results
    are bitwise-identical to ``pipeline=False``, the journal's
    single-writer / shard-before-manifest / in-order protocol is
    preserved, and a crash with commits in flight resumes exactly as a
    serial crash would (uncommitted chunks recompute).  The pipeline
    knobs are deliberately EXCLUDED from the journal's config hash — a
    serial journal resumes under a pipelined run and vice versa.
    ``pipeline=False`` restores the fully serial walk.
    ``meta["pipeline"]`` reports the commit wall time, how much of it the
    driver never waited for (``hidden_commit_s``), and the resulting
    ``overlap_efficiency``.

    **Input staging** (the other half of the pipeline): while chunk N
    computes, a background :class:`~.prefetcher.ChunkPrefetcher` stages
    chunk N+1's device slice (at most ``prefetch_depth`` slices ahead,
    default 1 — the classic double buffer), so in steady state the walk
    runs stage N+1 ∥ compute N ∥ commit N−1.  The staged buffer is the
    SAME ``yb[lo:hi]`` the serial driver slices (identical bytes); the
    driver predicts the next span on the committed grid (resume clamping
    and torn-shard boundaries included) and invalidates staged slices
    whenever OOM backoff or a committer rollback re-chunks the walk, so a
    stale prediction degrades to an inline slice, never a wrong one.
    ``prefetch_depth=0`` (or ``pipeline=False``) disables staging.
    ``meta["pipeline"]`` gains the input-side accounting
    (``staging_wall_s`` / ``hidden_staging_s`` /
    ``input_overlap_efficiency``) and the combined
    ``end_to_end_overlap_efficiency``.

    **Static align-mode plan**: when ``fit_fn`` accepts the ``align_mode``
    hint (every bundled model fit does — ``models.base.resolve_align_mode``),
    a sliced walk computes the panel's alignment mode ONCE and threads it
    into every chunk fit as a static argument, eliminating the per-chunk
    NaN-probe host sync and the per-array-identity align-cache misses on
    fresh slice buffers.  The panel-level mode is a row-wise property, so
    it is exact for every row slice.  Pass ``align_mode=`` to skip even
    the one probe (the journal's config hash covers the resolved mode, so
    a resumed run must use the same plan); a hint too strong for the data
    flags the violating rows instead of silently misfitting them (see
    ``resolve_align_mode``).  Resilient walks downgrade the hint to
    ``"general"`` for chunks the sanitizer actually modified
    (``runner.resilient_fit``), keeping the hint sound when repairs
    change a chunk's NaN pattern.  ``meta["align_mode"]`` records the
    plan.

    **Sharded execution** (``shard=True`` or ``mesh=``): the chunk grid is
    partitioned contiguously across the mesh's series-axis devices
    (:func:`~.plan.shard_spans` — every shard owns whole chunks, so shard
    boundaries ARE single-device chunk boundaries) and one
    :class:`~.plan.LaneRunner` per shard walks its span concurrently,
    each with its own prefetch → compute → commit pipeline over its
    device-resident slice (``parallel.mesh.lane_values`` places the
    panel, using one ``NamedSharding(mesh, P("series", None))`` placement
    when the spans are the even split).  The sharded result is
    bitwise-identical to the single-device walk on the same panel.  With
    ``shard=True`` and no ``chunk_rows``, each shard gets one chunk.
    Journaled sharded walks commit into per-shard namespaces
    (``shard_00000/…``) and shard/process 0 merges them into ONE
    ``manifest.json`` (with a ``shards`` block and shard-tagged telemetry
    timeline) after the lanes join; a resume rebuilds the same lanes
    (same mesh/shard count — a changed shard layout is rejected as stale)
    and replays only uncommitted chunks, and the merged manifest can even
    be adopted by a later SINGLE-device walk of the same job (plan knobs
    are excluded from the config hash).  ``meta["shards"]`` records the
    lane layout; ``meta["pipeline"]`` aggregates the lanes and reports
    per-shard overlap in ``meta["pipeline"]["shards"]``.  Under
    ``jax.distributed`` each process runs the lanes of its addressable
    shards and returns its LOCAL rows (build the global panel with
    ``parallel.mesh.distribute_panel``).

    **Deadlines**: ``chunk_budget_s`` bounds each chunk's fit (overrun ->
    rows flagged ``TIMEOUT``, walk continues — the compiled computation is
    abandoned, not cancelled; with the budget armed, non-resilient fits
    block on device completion inside the watchdog window so the budget
    covers compute, not just async dispatch); ``job_budget_s`` bounds the
    whole walk (once spent, remaining chunks are marked TIMEOUT without
    dispatch — the deadline is shared by every lane).  Both paths drain
    the commit queue before touching the journal, so the TIMEOUT mark
    always lands after every earlier commit.  Partial results always
    carry exact status counts, and TIMEOUT chunks are retried on a
    journaled resume.

    ``meta`` records ``chunk_rows_initial`` / ``chunk_rows_final``, every
    backoff and timeout event, ``degraded=True`` whenever a backoff or
    timeout happened, and — when journaled — the journal accounting
    (``meta["journal"]``: run id, chunks committed/resumed/timeout).

    **Elastic lanes** (ISSUE 11, single-process sharded walks): lane
    failures no longer fail the job.  Lanes pull grid-aligned spans from
    a shared work queue (seeded with the static partition, so a healthy
    walk is layout-identical to PR 6); a lane whose walk raises is
    retried up to ``lane_retries`` times with exponential backoff
    (``lane_retry_backoff_s``), then QUARANTINED — its device leaves the
    active set, its uncommitted chunks are re-staged to survivors'
    devices and recomputed, and chunks it already committed are ADOPTED
    from its journal namespace (chunk entries carry an ``owner`` lane
    tag; the merged manifest reconciles reassigned chunks and records a
    ``rebalance`` block).  Idle lanes STEAL the grid-aligned tail of a
    straggler's remaining span once its projected finish exceeds
    ``rebalance_threshold`` mean chunk walls.  Results stay
    bitwise-identical to the uninterrupted single-device walk regardless
    of which lane computed which chunk; SIGKILL-resume composes (a
    resumed job re-admits previously quarantined devices and replays
    only truly-uncommitted work); a job that loses ALL lanes still fails
    with the original error.  ``meta["shards"]["elastic"]`` records
    quarantines/steals/retries.  Under ``jax.distributed`` (host RAM is
    process-local, so a process cannot re-stage another process's rows)
    the static fail-fast layout is kept.

    **Delta walks** (``delta_from=PRIOR_ROOT``, ISSUE 15): refit only
    what changed.  The planner (:mod:`.delta`) diffs this panel against
    the committed journal at ``PRIOR_ROOT`` using the per-chunk content
    fingerprints every version-2 manifest records: unchanged chunks
    (**clean**) are spliced into this walk's NEW journal namespace as
    ordinary commits up front — zero compute, provenance recorded in the
    manifest's ``extra.delta`` block and the entries' ``delta.class`` —
    so the resume machinery skips them; chunks whose history GREW with a
    byte-identical prefix (**warm**) refit warm-started from the
    journaled params via augmented init-param columns
    (:class:`~.delta.WarmstartFit`; requires ``resilient=False`` and a
    fit with ``init_params=``, e.g. the arima family); revised/new
    chunks refit in full.  The delta result is bitwise-identical to a
    from-scratch refit of the new panel on the same chunk grid — a
    same-length delta (clean + dirty) against the COLD walk
    (determinism: identical rows + identical config + aligned grid
    reproduce identical bytes; off-grid prior boundaries are refused
    adoption and recomputed), a grown (warm) delta against a
    warm-started full walk of the same augmented panel (EVERY computed
    chunk rides the warm wrapper there — dirty/new rows start from
    zeroed inits rather than the model's own cold init);
    ``delta_warmstart=False`` (exact mode) refits everything cold,
    pinning the WHOLE result bitwise against the cold walk — prefer it
    when the delta is mostly new/revised rows rather than appended
    ticks.  A prior journal without chunk fingerprints (journal
    version 1 — still resumable), with shrunk rows/time, or fitted
    under a different config is rejected loudly
    (:class:`~.delta.StalePriorError`).  Requires ``checkpoint_dir=``;
    a SIGKILLed delta walk resumes bitwise and never recomputes an
    adopted chunk.  ``meta["delta"]`` reports the class counts.

    **Grid coordinate** (``grid=(index, total)`` or
    ``(index, total, members)``): an auto-fit order search
    (``models.auto``) runs one ordinary walk per candidate order — or,
    fused, one walk per same-``d`` fusion group, whose member grid
    indices ride in ``members`` (leading with the walk's own index); the
    coordinate places this walk's plan on that grid — chunk
    spans/events/telemetry rows carry a ``grid`` tag (one
    ``tools/obs_report.py`` timeline lane per walk), the manifest
    records ``extra.grid`` (with ``fused`` for a group walk), and
    ``meta["grid"]`` echoes it.  Like the pipeline/shard knobs it is NOT
    part of the journal config hash: the orders themselves ride in the
    hashed fit kwargs; the coordinate only labels where in the search
    the work happened.

    **Telemetry** (``obs.enable()``): each chunk dispatch runs under an
    ``obs.span("chunk")`` whose first dispatch per (fit, shape, dtype) is
    tagged ``compile+execute`` (JAX pays trace+compile there) and the rest
    ``execute``; backoffs, timeouts, and per-row status totals feed the
    metrics registry; the committer reports a ``committer.queue_depth``
    gauge, per-commit ``commit.overlap`` spans, and a
    ``committer.hidden_commit_ms`` counter; and the per-run summary —
    per-chunk span times (shard-tagged under a sharded plan), counters,
    peak memory (never null: host-RSS fallback) — lands in
    ``meta["telemetry"]`` and, when journaled, the manifest's
    ``telemetry`` block.  Disabled (the default), none of this runs and
    the result is bitwise-identical to the uninstrumented driver.
    """
    # -- chunk source (ISSUE 7) ----------------------------------------------
    # `y` may be a ChunkSource instead of an array: the panel then lives
    # wherever the source says (host RAM, an npz shard directory) and every
    # chunk is staged H2D through the source's pinned-style staging pool as
    # the walk reaches it — the panel NEVER fully resides on device.  A
    # DeviceChunkSource unwraps to the resident-array walk, byte-identical
    # to passing the array itself.
    src = None
    chunk_rows_from_source = False
    if isinstance(y, source_mod.ChunkSource):
        if isinstance(y, source_mod.DeviceChunkSource):
            yb = y.array
        else:
            src = y
            yb = None
            if chunk_rows is None and src.default_chunk_rows:
                chunk_rows_from_source = True
                # sources know their natural chunking — shard size for
                # npz dirs, a bounded slice for host arrays — and the
                # grid lands there unless the caller says otherwise (a
                # whole-panel default chunk would stage the oversubscribed
                # panel in one slice and defeat the point)
                chunk_rows = src.default_chunk_rows
    else:
        yb = jnp.asarray(y)
    if src is not None:
        b, t_len = src.shape
        panel_dtype = src.dtype
        src_stats0 = src.stats()
        # peak_live_device_bytes must be THIS walk's high-water mark (the
        # O(chunk) footprint consumers assert), not a previous walk's
        src.reset_peak_live()
    else:
        if yb.ndim != 2:
            raise ValueError(
                f"fit_chunked expects [batch, time], got {yb.shape}")
        b = yb.shape[0]
        t_len = int(yb.shape[1])
        panel_dtype = np.dtype(str(yb.dtype))

    # -- delta walk (ISSUE 15) -----------------------------------------------
    # delta_from= diffs THIS panel against a committed prior journal
    # (reliability.delta): unchanged chunks are spliced into the new
    # journal as ordinary commits up front (zero compute — the resume
    # machinery then skips them), grown-history chunks refit warm-started
    # from the journaled params via augmented init columns, and only the
    # revised/new remainder refits cold.  Everything after this branch is
    # the ordinary walk: pipelining, prefetch, sources, sharding, elastic
    # lanes, and serving compose with no delta-specific driver code.
    delta_plan = None
    delta_wrapped = False
    data_cols = None
    # grid- and placement-independent identity of the INNER fit (the
    # model + its kwargs, align/driver knobs excluded), recorded in every
    # journaled manifest (`extra.fit`) and checked before a warm delta
    # splices another job's params in as inits: warm-starting
    # arima(1,0,1) from an arima(2,0,1) journal must fail loudly, not as
    # an opaque shape error (or worse, a silent wrong-basin init)
    fit_base = journal_mod.config_hash(
        fit_fn, {k: v for k, v in fit_kwargs.items() if k != "align_mode"})
    _inner = fit_fn
    while isinstance(_inner, functools.partial):
        _inner = _inner.func
    fit_name = (getattr(_inner, "__module__", "?") + "."
                + getattr(_inner, "__qualname__", repr(_inner)))
    if delta_from is not None:
        if checkpoint_dir is None:
            raise ValueError(
                "delta_from= requires checkpoint_dir=: the delta walk "
                "journals adopted + recomputed chunks into a NEW namespace")
        try:
            _n_procs0 = jax.process_count()
        except Exception:  # noqa: BLE001 - no backend yet: single process
            _n_procs0 = 1
        if _n_procs0 > 1:
            raise ValueError(
                "delta walks are single-process (the planner streams the "
                "panel's rows on the host to fingerprint each chunk)")
        # only a CALLER-chosen chunk_rows constrains the delta grid: a
        # source's natural chunking (npz shard size) must not preempt
        # the prior walk's grid, or the documented "omit chunk_rows and
        # the delta defaults to the prior grid" workflow would reject
        # itself whenever the shard size differs from the prior grid
        delta_plan = delta_mod.plan_delta(
            delta_from, src if src is not None else yb,
            chunk_rows=None if chunk_rows_from_source else chunk_rows,
            warmstart=delta_warmstart)
        # the prior walk's grid: delta identity is per-chunk, so the
        # grids must align for adoption to mean anything
        chunk_rows = delta_plan.chunk_rows
        data_cols = t_len  # the new walk's fingerprints cover the raw data
        if delta_plan.counts["warm"] and delta_warmstart:
            pfit = ((delta_plan.manifest.get("extra") or {})
                    .get("fit") or {})
            if pfit.get("base_config") and \
                    pfit["base_config"] != fit_base:
                raise delta_mod.StalePriorError(
                    f"prior journal {delta_plan.prior_dir} fitted "
                    f"{pfit.get('name')} under a different model "
                    "configuration; its params cannot warm-start this "
                    "fit — refit from scratch or point delta_from at a "
                    "journal of the SAME fit/kwargs")
            if resilient:
                raise ValueError(
                    "a warm-started delta walk must run resilient=False "
                    "(the sanitizer would 'repair' the init-param "
                    "columns); pass resilient=False, or "
                    "delta_warmstart=False for an exact cold delta")
            import inspect as _dinspect

            try:
                _fit_params = _dinspect.signature(fit_fn).parameters
            except (TypeError, ValueError):
                _fit_params = {}
            for need in ("init_params", "align_mode"):
                if need not in _fit_params:
                    raise TypeError(
                        "delta_warmstart=True needs a fit_fn with an "
                        f"explicit {need}= parameter (the arima family "
                        "has one); pass delta_warmstart=False for an "
                        "exact cold delta")
            if align_mode is None:
                # resolved on the RAW panel before augmentation: the init
                # columns carry NaN on dirty/new rows, which would
                # otherwise downgrade the plan to "general" for data the
                # fit never sees unaligned
                from ..models import base as _model_base

                align_mode = (src.align_mode() if src is not None
                              else _model_base.align_mode_on_host(yb))
            fit_fn = delta_mod.WarmstartFit(fit_fn, t_len, delta_plan.k)
            aug = delta_mod.warm_panel(src if src is not None else yb,
                                       delta_plan.init)
            delta_wrapped = True
            if isinstance(aug, source_mod.ChunkSource):
                src = aug
                b, t_len = src.shape
                panel_dtype = src.dtype
                src_stats0 = src.stats()
                src.reset_peak_live()
            else:
                yb = aug
                b = yb.shape[0]
                t_len = int(yb.shape[1])

    # -- lane layout (the sharded half of the ExecutionPlan) -----------------
    # resolved BEFORE the align plan and the journal: the shard count can
    # pick the default chunk size, and lane placement is the mesh plane's
    # data distribution step
    use_mesh = mesh
    if use_mesh is not None or shard:
        # lazy: parallel must stay importable without the driver and
        # vice versa, and unsharded walks never pay the import
        from ..parallel import mesh as meshlib
    if use_mesh is None and shard:
        use_mesh = meshlib.default_mesh()
    n_shards = 1
    if use_mesh is not None:
        n_shards = len(meshlib.series_devices(use_mesh))
        if chunk_rows is None and n_shards > 1:
            # shard=True without a chunk size: one chunk per shard — the
            # coarsest layout that still gives every device a lane
            chunk_rows = -(-b // n_shards)
    chunk = int(chunk_rows) if chunk_rows else b
    chunk = max(1, min(chunk, b))
    chunk0 = chunk

    spans = [(0, b)]
    lanes = None  # [(shard_id, lo, hi, device, lane_values), ...]
    if use_mesh is not None and n_shards > 1:
        spans = list(plan_mod.shard_spans(b, chunk0, n_shards))
        if len(spans) > 1:
            if src is not None:
                # source-backed lanes need no device placement up front:
                # each lane stages ONLY its own spans, H2D to its device,
                # as its walk reaches them.  Host RAM is process-local,
                # so a source-backed sharded walk is SINGLE-process —
                # enforced here, before any journal namespace is opened:
                # under jax.distributed every process would otherwise
                # build lanes for ALL spans (duplicate work, concurrent
                # writers on the same shard namespaces) and die at
                # device_put to a non-addressable device.  The multi-host
                # path distributes device arrays (distribute_panel).
                try:
                    n_procs = jax.process_count()
                except Exception:  # noqa: BLE001 - no backend: 1 process
                    n_procs = 1
                if n_procs > 1:
                    raise ValueError(
                        "sharded walks over a ChunkSource are "
                        "single-process (host RAM/disk is process-local); "
                        "under jax.distributed build a global device "
                        "panel with parallel.mesh.distribute_panel "
                        "instead of a source")
                devs = meshlib.series_devices(use_mesh)
                lanes = [(sid, slo, shi, devs[sid],
                          source_mod.SourceLane(src, base=slo,
                                                device=devs[sid]))
                         for sid, (slo, shi) in enumerate(spans)]
            else:
                try:
                    lanes = meshlib.lane_values(yb, use_mesh, spans)
                except BaseException:
                    # lane placement fails per-process (local shard
                    # layout): on a journaled job the OTHER processes will
                    # block in the timeout-less pre-merge barrier — join
                    # it so the error surfaces instead of hanging the
                    # survivors (unjournaled jobs have no barrier: joining
                    # one would hang US)
                    if checkpoint_dir is not None:
                        _distributed_barrier()
                    raise
    sharded = lanes is not None
    if not sharded:
        spans = [(0, b)]
        lanes = [(0, 0, b, None,
                  source_mod.SourceLane(src) if src is not None else yb)]

    # static align-mode plan: resolve the panel's alignment mode ONCE (or
    # take the caller's hint) and thread it into every chunk fit as a
    # static argument — the per-chunk NaN probe (one host sync per sliced
    # chunk) disappears.  The mode is a row-wise property of the panel, so
    # the panel-level answer is exact for every row slice (and for every
    # shard's slice).  Injected BEFORE the journal's config hash is
    # computed: the plan changes which compiled program fits the chunks,
    # so a resume must run the same one.
    from ..models import base as model_base

    import inspect as _inspect

    def _explicit_align_param(fn) -> bool:
        try:
            return "align_mode" in _inspect.signature(fn).parameters
        except (TypeError, ValueError):
            return False

    fit_takes_align = "align_mode" in _accepted_kwargs(
        fit_fn, {"align_mode": None})
    if align_mode is not None:
        # a caller-provided hint is an explicit opt-in: a **kwargs fit_fn
        # is trusted to forward it (the caller asserted it can)
        if not fit_takes_align:
            raise TypeError(
                "align_mode= was given but fit_fn does not accept an "
                "align_mode keyword (the hint would be silently dropped)")
        fit_kwargs = {**fit_kwargs,
                      "align_mode": model_base.resolve_align_mode(
                          yb if src is None else src, align_mode)}
    elif (_explicit_align_param(fit_fn)
          and (src is not None or chunk < b or sharded)
          and "align_mode" not in fit_kwargs):
        # AUTO-injection requires align_mode as an explicitly NAMED
        # parameter — a bare **kwargs does not count (a third-party
        # `def my_fit(y, **opts)` forwarding to a strict solver would
        # blow up on, or silently absorb, a keyword it never asked for).
        # Only sliced walks benefit: a whole-panel chunk hands the
        # caller's array through and the model's own per-array probe
        # cache holds.  A sharded walk always slices (every lane array is
        # a fresh buffer), so it always plans — and a SOURCE walk always
        # stages fresh buffers, so it plans too, probing on the HOST
        # (streamed through the source: the panel never touches the
        # device for the probe).
        fit_kwargs = {**fit_kwargs,
                      "align_mode": (src.align_mode() if src is not None
                                     else model_base.align_mode_on_host(yb))}
    plan_mode = fit_kwargs.get("align_mode") if fit_takes_align else None

    # -- grid coordinate (ISSUE 9 / 10) --------------------------------------
    # an auto-fit order search (models.auto) runs one ordinary walk per
    # candidate order — or, fused (ISSUE 10), one walk per fusion GROUP of
    # same-d orders; grid=(index, total) or (index, total, members) places
    # this walk on that grid so its telemetry rows/events are per-walk
    # lanes and the journal records where in the search the chunks belong
    # (a fused walk's chunks carry the whole group in extra.grid.fused).
    # NOT config-hashed (the orders themselves ride in fit_kwargs, which
    # is) — purely a label.
    if grid is not None:
        gi, gn = (int(grid[0]), int(grid[1]))
        if not (0 <= gi < gn):
            raise ValueError(f"grid index {gi} out of range for total {gn}")
        members = None
        if len(grid) > 2 and grid[2] is not None:
            members = [int(m) for m in grid[2]]
            if any(not (0 <= m < gn) for m in members) or members[0] != gi:
                raise ValueError(
                    f"grid members {members} must sit in [0, {gn}) and "
                    f"lead with the walk's own index {gi}")
        grid = (gi, gn)
        grid_members = members
        gx = {"index": gi, "total": gn}
        if members is not None:
            gx["fused"] = members
        journal_extra = {**(journal_extra or {}), "grid": gx}
    else:
        grid_members = None

    # -- journal(s) ----------------------------------------------------------
    if src is not None:
        # the source spelling rides in the manifest `extra` (NOT the config
        # hash: the bytes are the panel's, not the placement's — an in-HBM
        # journal resumes under a host-RAM walk and vice versa, both
        # fingerprinting sampled VALUES; npz shard dirs fingerprint by
        # shard identity and so journal in their own domain) so
        # post-mortems and the budget advisor can see what the walk read
        # and how big the panel really was
        journal_extra = {**(journal_extra or {}),
                         "source": {"kind": src.kind,
                                    "panel_bytes": int(src.nbytes)}}
    # -- write-back sink (ISSUE 20) ------------------------------------------
    # results stream OUT as durable output shards instead of concatenating
    # in host RAM: every committed chunk's arrays are handed to the sink's
    # background writer (the committer's on_commit hook), the walk keeps
    # boundary-only placeholders, and assembly finalizes the sink instead
    # of materializing the panel-sized result.  The sink moves I/O only —
    # like the pipeline knobs it is NOT part of the journal's config hash,
    # so a sink walk resumes an in-RAM journal and vice versa.
    if sink is not None:
        if checkpoint_dir is None:
            raise ValueError(
                "sink= streams committed chunks out, so it requires a "
                "journaled walk: pass checkpoint_dir= as well")
        if sharded:
            raise ValueError(
                "sink= is not supported with shard=True/mesh=: output "
                "shards are named by global row span and a merged "
                "multi-lane sink is not implemented")
        if isinstance(sink, (str, os.PathLike)):
            sink = sink_mod.WritableChunkSource(sink)
        journal_extra = {**(journal_extra or {}),
                         "sink": {"directory": sink.directory,
                                  "depth": sink.depth}}
    journals = None
    cfg = fp = None
    if checkpoint_dir is not None:
        # EVERY journaled walk records the panel's geometry (extra, not
        # hashed): the budget advisor needs panel bytes from an IN-HBM
        # manifest to say "the next run of this panel should go
        # host-resident" — advice that is moot once a source already ran
        if data_cols is None:
            data_cols = t_len
        journal_extra = {
            **(journal_extra or {}),
            "panel": {"bytes": int(b) * int(t_len) * panel_dtype.itemsize,
                      "time": int(t_len), "dtype": str(panel_dtype)},
            # how many leading DATA columns the per-chunk fingerprints
            # cover (ISSUE 15) — a warm delta walk's init columns are
            # deliberately excluded so tick-feed chains stay delta-eligible
            "chunk_fp_cols": int(data_cols),
            # the INNER fit's identity (warm-wrapped walks record the
            # wrapped model, not the wrapper) — what a later warm delta
            # checks before adopting these params as inits
            "fit": {"name": fit_name, "base_config": fit_base}}
        if delta_plan is not None:
            journal_extra["delta"] = delta_mod.delta_extra(
                delta_plan, warmstart=delta_wrapped, data_cols=data_cols)
        if process_index is None:
            try:
                process_index = jax.process_index()
            except Exception:  # noqa: BLE001 - no backend yet: single process
                process_index = 0
        # pipeline/shard knobs deliberately NOT hashed: they move I/O and
        # compute between threads and devices without changing a byte of
        # the result, so a serial journal resumes under a pipelined run
        # (and vice versa), and a merged sharded manifest is adopted by a
        # later single-device walk.  The reverse direction is NOT adoption:
        # a sharded walk starts fresh shard namespaces and recomputes
        # chunks a root/serial manifest already holds (identical bytes,
        # just repeated work)
        cfg = journal_mod.config_hash(
            fit_fn, fit_kwargs,
            extra={"chunk_rows": chunk0, "min_chunk_rows": min_chunk_rows,
                   "resilient": resilient, "policy": policy,
                   "ladder": "default" if ladder is None else repr(ladder)})
        fp = src.fingerprint() if src is not None else _fingerprint(yb)
        if delta_plan is not None and not delta_plan.grown \
                and delta_plan.prior_config_hash != cfg:
            # clean adoption rests on determinism: identical rows under an
            # IDENTICAL config reproduce identical bytes.  A same-shape
            # prior fitted under a different config cannot donate a single
            # chunk — pointing delta_from at it is operator error, not a
            # silent full refit
            raise delta_mod.StalePriorError(
                f"prior journal {delta_plan.prior_dir} was fitted under a "
                f"different configuration (config_hash "
                f"{delta_plan.prior_config_hash} != {cfg}); its chunks "
                "cannot be adopted into this walk — refit from scratch or "
                "point delta_from at the matching journal")
        # per-chunk content fingerprint sampler (ISSUE 15): every commit
        # records the chunk's own row identity so a LATER delta walk can
        # adopt unchanged chunks.  Multi-process global arrays are not
        # host-sampleable here; their entries simply omit the field.
        chunk_fp = None
        try:
            _addressable = (True if src is not None
                            else getattr(yb, "is_fully_addressable", True))
        except Exception:  # noqa: BLE001 - duck typing over jax versions
            _addressable = False
        if _addressable:
            chunk_fp = delta_mod.chunk_fp_fn(src, yb, data_cols)
        if not sharded:
            journals = [journal_mod.ChunkJournal(
                checkpoint_dir,
                config_hash=cfg,
                panel_fingerprint=fp,
                n_rows=b,
                chunk_rows=chunk0,
                resume=resume,
                process_index=process_index,
                extra=journal_extra,
                commit_hook=_journal_commit_hook,
                chunk_fp=chunk_fp,
            )]
        else:
            # one journal namespace per shard (shard_00000/…): lanes are
            # concurrent writers, and the journal's single-writer rule is
            # per namespace.  The shard layout rides in `extra` so a
            # resume under a DIFFERENT mesh is rejected as stale instead
            # of splicing mismatched spans.
            journals = []
            try:
                # lanes never open the root manifest, so a foreign job's
                # durable state in this dir would survive unnoticed until
                # the merge destroyed it — reject it BEFORE any compute,
                # like the single-device journal does
                journal_mod.check_root_manifest(
                    checkpoint_dir, config_hash=cfg,
                    panel_fingerprint=fp, n_rows=b)
                for (sid, slo, shi, _dev, _vals) in lanes:
                    extra = dict(journal_extra or {})
                    extra.update({"shard_id": sid, "shard_lo": slo,
                                  "shard_hi": shi, "n_shards": len(spans)})
                    journals.append(journal_mod.ChunkJournal(
                        checkpoint_dir,
                        config_hash=cfg,
                        panel_fingerprint=fp,
                        n_rows=b,
                        chunk_rows=chunk0,
                        resume=resume,
                        process_index=process_index,
                        shard_index=sid,
                        extra=extra,
                        commit_hook=_journal_commit_hook,
                        chunk_fp=chunk_fp,
                    ))
            except BaseException:
                # stale/torn LOCAL journal state is asymmetric across
                # processes: peers with clean disks will finish their
                # lanes and block in the timeout-less pre-merge barrier —
                # join it so the error surfaces cluster-wide
                _distributed_barrier()
                raise
        if delta_plan is not None and delta_plan.adopted:
            # splice the clean chunks' committed results into the NEW
            # namespace as ordinary commits BEFORE the walk starts: the
            # resume machinery then skips them like any committed chunk,
            # and a resumed delta walk (committed() already true) never
            # re-adopts — nor recomputes — them
            _delta_adopt(delta_plan, journals,
                         spans if sharded else None, sharded)
    deadline = watchdog_mod.Deadline(job_budget_s)

    # per-chunk telemetry rows for meta["telemetry"] / the manifest block;
    # None (not empty) when disabled so the disabled path allocates nothing
    # and meta stays byte-identical to the uninstrumented driver
    tele = obs.enabled()
    # counter baseline at fit start: the registry is run-wide (one
    # obs.enable() can span many fits), but THIS fit's summary must report
    # its own activity — counters are emitted as deltas from here, so fit
    # B's manifest does not inherit fit A's DIVERGED rows or OOM backoffs.
    # Known limit: a watchdog-ABANDONED worker (timed-out chunk) may still
    # be incrementing counters after its fit returns; those late increments
    # land in whichever delta window is open (XLA dispatch cannot be
    # cancelled, so this is inherent to abandonment, and data-quality only)
    counters0 = (obs.snapshot() or {}).get("counters") if tele else None
    # compile-affecting identity of this fit config, computed ONCE: the
    # first dispatch per (config, chunk-rows) pays JAX trace+compile, and a
    # later job with the same shape but different static config (order,
    # max_iters, backend, ladder) compiles anew — reuse the journal's
    # config_hash (fit identity + every kwarg + driver knobs) so the
    # compile-identity ingredients live in ONE place
    fit_key = journal_mod.config_hash(
        fit_fn, fit_kwargs,
        extra={"resilient": resilient, "policy": policy,
               "ladder": "default" if ladder is None else repr(ladder),
               "time": t_len, "dtype": str(panel_dtype)},
    ) if tele else None

    # -- the plan, then its lanes -------------------------------------------
    lane_specs = tuple(LaneSpec(sid, slo, shi, dev)
                       for (sid, slo, shi, dev, _vals) in lanes)
    # elastic supervision (ISSUE 11) applies to SINGLE-PROCESS multi-lane
    # walks: under jax.distributed a process cannot re-stage another
    # process's rows (they are not addressable here), so multi-host jobs
    # keep the static fail-fast layout
    try:
        _n_procs = jax.process_count()
    except Exception:  # noqa: BLE001 - no backend yet: single process
        _n_procs = 1
    elastic = sharded and len(lane_specs) > 1 and _n_procs <= 1
    plan = ExecutionPlan(
        n_rows=b,
        chunk_rows=chunk0,
        min_chunk_rows=min_chunk_rows,
        max_backoffs=max_backoffs,
        resilient=resilient,
        policy=policy,
        ladder=ladder,
        checkpoint_dir=checkpoint_dir,
        resume=resume,
        chunk_budget_s=chunk_budget_s,
        job_budget_s=job_budget_s,
        pipeline=pipeline,
        pipeline_depth=pipeline_depth,
        prefetch_depth=prefetch_depth,
        align_mode=plan_mode,
        lanes=lane_specs,
        process_index=int(process_index or 0),
        n_shards=len(spans) if sharded else 1,
        grid=grid,
        elastic=elastic,
        lane_retries=int(lane_retries),
        lane_retry_backoff_s=float(lane_retry_backoff_s),
        rebalance_threshold=float(rebalance_threshold),
    )
    # journal handles: an elastic lane READS committed state across every
    # shard namespace (adopting a quarantined/stolen-from lane's durable
    # chunks) and WRITES only its own; static walks keep the direct handle
    lane_journals = None
    if journals is not None:
        lane_journals = (
            [journal_mod.ShardJournalView(j, journals) for j in journals]
            if elastic else list(journals))
    runners = [
        LaneRunner(plan, spec, fit_fn, fit_kwargs, vals,
                   journal=(lane_journals[i] if lane_journals is not None
                            else None),
                   deadline=deadline, tele=tele, fit_key=fit_key,
                   sink=sink)
        for i, (spec, (_sid, _lo, _hi, _dev, vals))
        in enumerate(zip(lane_specs, lanes))
    ] if not elastic else None
    # overlap the root-manifest merge with the last lanes' tails (ISSUE 7
    # satellite, PR-6 follow-on): while slower lanes finish, shard/process 0
    # already READS and parses the shard manifests the committed lanes have
    # written — the merge after the barrier then only re-reads manifests
    # that changed since.  Read-only by construction: the root manifest's
    # single writer is still merge_job_manifest, after the lanes join.
    warmer = None
    if (journals is not None and sharded and len(lane_specs) > 1
            and int(process_index or 0) == 0):
        warmer = journal_mod.MergeWarmer(checkpoint_dir, len(spans))
    elastic_meta = None
    try:
        if elastic:
            # elastic supervision (ISSUE 11): lanes pull spans from the
            # shared work queue, failures quarantine instead of failing
            # the job, idle lanes steal from stragglers, and reassigned
            # spans are re-staged to the computing lane's device
            def _restage(rlo, rhi, device):
                if src is not None:
                    return source_mod.SourceLane(src, base=rlo,
                                                 device=device)
                return plan_mod.RestagedPanel(yb, device=device, base=rlo)

            supervisor = plan_mod.LaneSupervisor(
                plan, fit_fn, fit_kwargs,
                [(spec, vals) for spec, (_s, _l, _h, _d, vals)
                 in zip(lane_specs, lanes)],
                journals=lane_journals, deadline=deadline, tele=tele,
                fit_key=fit_key, restage=_restage)
            results, elastic_meta = supervisor.run()
        elif len(runners) == 1:
            results = [runners[0].run()]
        else:
            results = [None] * len(runners)
            errors = [None] * len(runners)

            def _drive(i):
                try:
                    results[i] = runners[i].run()
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    errors[i] = e

            threads = [threading.Thread(target=_drive, args=(i,), daemon=True,
                                        name=f"chunk-lane-{r.spec.shard_id}")
                       for i, r in enumerate(runners)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            first = next((e for e in errors if e is not None), None)
            if first is not None:
                # the failing lane already closed its own committer/
                # prefetcher; the OTHER lanes ran to completion (their
                # journals keep their commits — a resume replays only
                # what is missing)
                raise first
            results = [r for r in results if r is not None]
            results.sort(key=lambda r: r.spec.lo)
    except BaseException:
        if warmer is not None:
            warmer.stop()
        # peer processes of a journaled sharded job are (or will be)
        # blocked in the pre-merge barrier, which has no timeout: a
        # process whose lane failed must still JOIN it so the error
        # surfaces cluster-wide instead of hanging the survivors (the
        # barrier is best-effort and a no-op single-process)
        if journals is not None and sharded:
            _distributed_barrier()
        raise

    # -- merge lanes ---------------------------------------------------------
    # results arrive one per WALKED SPAN (an elastic lane can walk several);
    # spans are disjoint and each result's pieces ascend, so the sort by
    # span lo yields globally ascending pieces either way
    pieces = [p for r in results for p in r.pieces]
    pieces.sort(key=lambda p: p[0])
    oom_events, timeout_events = [], []
    for r in results:
        tag = {"shard": r.spec.shard_id} if sharded else {}
        oom_events.extend({**ev, **tag} for ev in r.oom_events)
        timeout_events.extend({**ev, **tag} for ev in r.timeout_events)
    chunk_final = min((r.chunk_final for r in results), default=chunk0)
    tele_chunks = None
    if tele:
        tele_chunks = [row for r in results for row in (r.tele_chunks or [])]
        tele_chunks.sort(key=lambda c: c["lo"])

    dtype = panel_dtype
    sink_acct = None
    if sink is not None:
        # write-back assembly (ISSUE 20): every computed/resumed chunk
        # already streamed out through the sink — only TIMEOUT spans are
        # materialized here (as the NaN/TIMEOUT rows the in-RAM assembly
        # would synthesize), then the sink verifies its spans tile
        # [0, n_rows) and writes the durable sink manifest.  The result
        # arrays stay None: the caller reads the output shards back at
        # O(chunk) footprint (NpzShardSource over the sink directory).
        sink.barrier()  # every queued write durable; param width known
        k = sink.param_width or 1
        for plo, phi, p in pieces:
            if isinstance(p, _TimeoutChunk):
                n = phi - plo
                sink.write(plo, phi, {
                    "params": np.full((n, k), np.nan, dtype),
                    "nll": np.full(n, np.nan, dtype),
                    "converged": np.zeros(n, bool),
                    "iters": np.zeros(n, np.int32),
                    "status": np.full(n, FitStatus.TIMEOUT, STATUS_DTYPE),
                })
        sink_acct = sink.finalize(b)
        params = nll = conv = iters = status = None
        counts = {m.name: int(sink_acct["status_counts"].get(
            str(m.value), 0)) for m in FitStatus}
    else:
        # parameter width for synthesized TIMEOUT rows comes from any
        # finished chunk; an all-TIMEOUT job degenerates to one NaN column
        k = next((int(np.asarray(p.params).shape[-1]) for _, _, p in pieces
                  if not isinstance(p, _TimeoutChunk)), 1)

        def _mat(p):
            if isinstance(p, _TimeoutChunk):
                n = p.hi - p.lo
                return (np.full((n, k), np.nan, dtype),
                        np.full(n, np.nan, dtype),
                        np.zeros(n, bool),
                        np.zeros(n, np.int32),
                        np.full(n, FitStatus.TIMEOUT, STATUS_DTYPE))
            return (np.asarray(p.params), np.asarray(p.neg_log_likelihood),
                    np.asarray(p.converged), np.asarray(p.iters),
                    _piece_status(p))

        mats = [_mat(p) for _, _, p in pieces]
        if mats:
            params = np.concatenate([m[0] for m in mats])
            nll = np.concatenate([m[1] for m in mats])
            conv = np.concatenate([m[2] for m in mats])
            iters = np.concatenate([m[3] for m in mats])
            status = np.concatenate([m[4] for m in mats])
        else:
            # a jax.distributed process whose addressable devices own no
            # lane (fewer local spans than mesh devices): its LOCAL result
            # is legitimately empty — it still joins the barrier below
            params = np.zeros((0, k), dtype)
            nll = np.zeros(0, dtype)
            conv = np.zeros(0, bool)
            iters = np.zeros(0, np.int32)
            status = np.zeros(0, STATUS_DTYPE)
        counts = status_counts(status)

    meta = {
        "chunk_rows_initial": chunk0,
        "chunk_rows_final": chunk_final,
        "chunks_run": len(pieces),
        "oom_backoffs": len(oom_events),
        "oom_events": oom_events,
        "timeouts": len(timeout_events),
        "timeout_events": timeout_events,
        "degraded": bool(oom_events or timeout_events),
        "status_counts": counts,
    }
    if sink_acct is not None:
        meta["sink"] = sink_acct
    if sharded:
        meta["shards"] = {
            "n_shards": len(spans),
            "spans": [[int(slo), int(shi)] for slo, shi in spans],
            "lanes_run": len({r.spec.shard_id for r in results}),
            "devices": [str(spec.device) for spec in lane_specs],
        }
        if elastic_meta is not None:
            meta["shards"]["elastic"] = elastic_meta
    if grid is not None:
        meta["grid"] = {"index": grid[0], "total": grid[1]}
        if grid_members is not None:
            meta["grid"]["fused"] = grid_members
    if delta_plan is not None:
        meta["delta"] = {"from": delta_plan.prior_dir,
                         "counts": dict(delta_plan.counts),
                         "warmstart": delta_wrapped}
    if journals is not None and not sharded:
        meta["journal"] = journals[0].accounting()
    if plan_mode is not None:
        meta["align_mode"] = plan_mode
    pipe_meta = _pipeline_meta(results, sharded)
    if src is not None:
        # host-resident accounting (ISSUE 7): the staging pool's
        # hit/reuse counts, the H2D copy wall/bytes, and the
        # donated-buffer high-water mark (peak_live_device_bytes — the
        # O(chunk) steady-state device footprint the oversubscribed bench
        # asserts).  Deltas against the walk's start, so a source shared
        # across walks reports per-walk numbers.
        src_staging = src.stats_delta(src_stats0)
        meta["source"] = {"kind": src.kind,
                          "panel_bytes": int(src.nbytes),
                          "shape": [int(b), int(t_len)],
                          "staging_pool": src_staging}
        if pipe_meta is None:
            pipe_meta = {}  # serial source walks still report staging
        pipe_meta["staging_pool"] = src_staging
    if pipe_meta is not None:
        meta["pipeline"] = pipe_meta
    # ladder/sanitize accounting aggregated across chunks (resilient mode)
    rung_totals: dict = {}
    for _, _, p in pieces:
        for r in (getattr(p, "meta", None) or {}).get("ladder", ()):
            agg = rung_totals.setdefault(
                r["rung"], {"attempted": 0, "rescued": 0})
            agg["attempted"] += r["attempted"]
            agg["rescued"] += r["rescued"]
    if rung_totals:
        meta["ladder_totals"] = rung_totals

    telemetry = None
    if tele:
        for name, v in meta["status_counts"].items():
            if v:
                obs.counter(f"fit_status.{name}").add(v)
        # summary() is None if the plane was disabled mid-run: drop the
        # block entirely rather than crash or journal a null
        extra_tele = {}
        if plan_mode is not None:
            extra_tele["align_mode"] = plan_mode
        if pipe_meta is not None and ("staging_wall_s" in pipe_meta
                                      or "staging_pool" in pipe_meta):
            # the input-staging overlap numbers ride into the manifest so
            # tools/advise_budget.py can suggest prefetch_depth (and the
            # align hint) for the next run of this config; host-resident
            # walks add the staging-pool block (pool reuse, H2D wall,
            # donated-buffer peak) even when the walk ran serially
            extra_tele["input_staging"] = {
                k2: pipe_meta[k2] for k2 in (
                    "prefetch_depth", "chunks_staged", "staged_hits",
                    "staged_misses", "staging_wall_s", "hidden_staging_s",
                    "input_overlap_efficiency", "staging_pool")
                if k2 in pipe_meta}
        if pipe_meta is not None and "shards" in pipe_meta:
            # per-lane commit/staging overlap rides into the merged job
            # manifest so a straggler lane is a journaled fact, not a
            # vanished meta dict (bench gates on it; advise_budget reads it)
            extra_tele["shards_pipeline"] = pipe_meta["shards"]
        telemetry = obs.summary(counters_since=counters0, chunks=tele_chunks,
                                **extra_tele)
        if telemetry is not None:
            meta["telemetry"] = telemetry
            if journals is not None and not sharded:
                journals[0].record_telemetry(telemetry)
            obs.emit_metrics()

    if journals is not None and sharded:
        # shard/process 0 is the single writer of the job-level manifest:
        # merge every shard namespace (chunks re-pathed shard-relative and
        # tagged with their shard id, a `shards` block, the merged
        # telemetry timeline) into ONE manifest.json after the lanes join
        acct = None
        if int(process_index or 0) == 0:
            _distributed_barrier()
            acct = journal_mod.merge_job_manifest(
                checkpoint_dir,
                config_hash=cfg,
                panel_fingerprint=fp,
                n_rows=b,
                chunk_rows=chunk0,
                spans=spans,
                telemetry=telemetry,
                extra=journal_extra,
                cache=warmer.stop() if warmer is not None else None,
                rebalance=elastic_meta,
            )
        else:
            _distributed_barrier()
            # a process may own ZERO local lanes (fewer spans than its
            # addressable devices): journals is then empty, but the job
            # root is just the checkpoint dir
            root = (journals[0].dir.rsplit("/shard_", 1)[0] if journals
                    else os.path.abspath(checkpoint_dir))
            acct = {"dir": root,
                    "manifest": None, "merged_shards": None,
                    "config_hash": cfg,
                    "process_index": int(process_index or 0)}
        acct["chunks_resumed"] = sum(j.resumed_entries for j in journals)
        meta["journal"] = acct
    return ResilientFitResult(params, nll, conv, iters, status, meta)


def _pipeline_meta(results, sharded: bool) -> Optional[dict]:
    """``meta["pipeline"]`` merged across lanes.

    The single-lane block is byte-identical to the pre-plan driver's; a
    sharded plan sums the lanes (total commit/staging wall vs total driver
    blocked wall) and adds a per-shard breakdown so a slow lane is visible
    behind the aggregate.
    """
    pipes = [(r.spec.shard_id, r.pipe_stats, r.committer_depth)
             for r in results if r.pipe_stats is not None]
    pfs = [(r.spec.shard_id, r.pf_stats, r.prefetch_depth)
           for r in results if r.pf_stats is not None]
    if not pipes and not pfs:
        return None
    pipe_meta = {}
    commit_wall = hidden_commit = 0.0
    if pipes:
        commit_wall = sum(s.commit_wall_s for _, s, _ in pipes)
        hidden_commit = sum(s.hidden_s for _, s, _ in pipes)
        pipe_meta.update({
            "depth": pipes[0][2],
            "commits_background": sum(s.commits for _, s, _ in pipes),
            "commit_wall_s": round(commit_wall, 6),
            "driver_blocked_s": round(
                sum(s.blocked_s for _, s, _ in pipes), 6),
            "hidden_commit_s": round(hidden_commit, 6),
            "max_queue_depth": max(s.max_queue_depth for _, s, _ in pipes),
            # fraction of commit wall the driver never waited for — the
            # number the bench's journaled-vs-unjournaled pair publishes
            "overlap_efficiency": (
                round(hidden_commit / commit_wall, 4)
                if commit_wall > 0 else None),
        })
        obs.gauge("committer.hidden_commit_s").set(round(hidden_commit, 6))
        obs.counter("committer.hidden_commit_ms").add(
            int(hidden_commit * 1000))
    staging_wall = hidden_staging = 0.0
    if pfs:
        staging_wall = sum(s.staging_wall_s for _, s, _ in pfs)
        hidden_staging = sum(s.hidden_s for _, s, _ in pfs)
        pipe_meta.update({
            "prefetch_depth": pfs[0][2],
            "chunks_staged": sum(s.staged for _, s, _ in pfs),
            "staged_hits": sum(s.hits for _, s, _ in pfs),
            "staged_misses": sum(s.misses for _, s, _ in pfs),
            "staged_invalidated": sum(s.invalidated for _, s, _ in pfs),
            "staging_wall_s": round(staging_wall, 6),
            "staging_blocked_s": round(
                sum(s.blocked_s for _, s, _ in pfs), 6),
            "hidden_staging_s": round(hidden_staging, 6),
            # fraction of input-staging wall hidden under compute
            "input_overlap_efficiency": (
                round(hidden_staging / staging_wall, 4)
                if staging_wall > 0 else None),
        })
        obs.counter("prefetch.hidden_staging_ms").add(
            int(hidden_staging * 1000))
    # end-to-end: of ALL the overlap-eligible wall (journal commits +
    # input staging), the fraction the driver never waited for — the
    # single number that says "the walk is dispatch-ahead end to end"
    total_wall = commit_wall + staging_wall
    total_hidden = hidden_commit + hidden_staging
    pipe_meta["end_to_end_overlap_efficiency"] = (
        round(total_hidden / total_wall, 4) if total_wall > 0 else None)
    if sharded:
        # per-shard accumulation: an ELASTIC lane (ISSUE 11) walks several
        # spans — one LaneResult each — and its commit/staging accounting
        # must sum into ONE row per shard, not overwrite
        by_shard: dict = {}
        for sid, s, _d in pipes:
            e = by_shard.setdefault(sid, {"shard": sid})
            cw = e.get("commit_wall_s", 0.0) + s.commit_wall_s
            hc = e.get("hidden_commit_s", 0.0) + s.hidden_s
            e.update({
                "commits_background": e.get("commits_background", 0)
                + s.commits,
                "commit_wall_s": round(cw, 6),
                "hidden_commit_s": round(hc, 6),
                "overlap_efficiency": (round(hc / cw, 4) if cw > 0
                                       else None),
            })
        for sid, s, _d in pfs:
            e = by_shard.setdefault(sid, {"shard": sid})
            sw = e.get("staging_wall_s", 0.0) + s.staging_wall_s
            hs = e.get("hidden_staging_s", 0.0) + s.hidden_s
            e.update({
                "chunks_staged": e.get("chunks_staged", 0) + s.staged,
                "staging_wall_s": round(sw, 6),
                "hidden_staging_s": round(hs, 6),
                "input_overlap_efficiency": (round(hs / sw, 4) if sw > 0
                                             else None),
            })
        pipe_meta["shards"] = [by_shard[sid] for sid in sorted(by_shard)]
    return pipe_meta


def _delta_adopt(plan, journals, spans, sharded: bool) -> None:
    """Commit a delta plan's clean chunks into the new walk's journal(s).

    Adoption is an ordinary ``commit_chunk`` of the prior result arrays
    (zero compute, entry tagged ``delta.class == "adopted"`` with the
    source manifest), routed into the shard namespace whose span holds
    the chunk under a sharded plan — single-writer protocol untouched,
    and the elastic ``ShardJournalView`` sees cross-namespace adoption
    like any reassigned commit.  Already-committed chunks (a resumed
    delta walk) are left exactly as they are: adopted chunks are never
    recomputed OR re-spliced on resume.
    """
    src_manifest = os.path.join(plan.prior_dir, "manifest.json")
    batches: dict = {}  # journal -> [(lo, hi, shard_path, info), ...]
    for entry, shard_path in plan.adopted:
        lo, hi = int(entry["lo"]), int(entry["hi"])
        if sharded:
            sid = next((i for i, (slo, shi) in enumerate(spans)
                        if slo <= lo < shi), 0)
            j = journals[sid]
        else:
            j = journals[0]
        if j.committed(lo) is not None:
            continue
        counts = entry.get("status_counts")
        if counts is None:
            with np.load(shard_path, allow_pickle=False) as z:
                counts = status_counts(np.asarray(z["status"]))
        info = {"wall_s": 0.0, "status_counts": counts,
                "delta": {"class": "adopted",
                          "source_manifest": src_manifest}}
        if entry.get("chunk_fingerprint"):
            # the planner just PROVED the new panel's rows hash to this —
            # recording the prior value verbatim skips a redundant sample
            info["chunk_fingerprint"] = entry["chunk_fingerprint"]
        batches.setdefault(id(j), (j, []))[1].append(
            (lo, hi, shard_path, info))
    for j, items in batches.values():
        adopted = j.adopt_chunks(items)
        obs.counter("delta.chunks_adopted").add(len(adopted))


def _fingerprint(yb) -> str:
    """Panel fingerprint, tolerant of multi-process global arrays (whose
    rows are not all addressable here — sampling them would need a
    collective): those fall back to a shape/dtype/sharding fingerprint,
    which is weaker but consistent across the processes of one job."""
    try:
        addressable = getattr(yb, "is_fully_addressable", True)
    except Exception:  # noqa: BLE001 - duck typing over jax versions
        addressable = True
    if addressable:
        return journal_mod.panel_fingerprint(yb)
    import hashlib

    h = hashlib.sha256(
        f"global:{yb.shape}:{yb.dtype}:{yb.sharding}".encode())
    return h.hexdigest()[:16]


def _distributed_barrier() -> None:
    """Best-effort cross-process barrier before the job-manifest merge:
    process 0 must not merge shard manifests other processes are still
    writing.  No-op (and never fatal) single-process or on backends
    without collectives."""
    try:
        if jax.process_count() <= 1:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ststpu-sharded-merge")
    except Exception:  # noqa: BLE001 - barrier is best-effort by design
        import warnings

        warnings.warn(
            "fit_chunked: cross-process barrier before the job-manifest "
            "merge failed; the merged manifest may briefly lag the last "
            "shard commits", stacklevel=2)
